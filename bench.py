"""Benchmark: GPT-2 350M training throughput on the available TPU chip(s).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.

Baseline anchor (BASELINE.md): the reference's published BERT-class single-V100
kernel numbers don't map 1:1 to a v5e chip, so the baseline here is the
BASELINE.json north-star framing — model FLOPs utilization (MFU). vs_baseline is
measured MFU / 0.45 (the 45% MFU target the reference stack achieves at scale);
1.0 means on-target.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np  # noqa: E402


def peak_flops_per_chip() -> float:
    """bf16 peak for the local chip generation."""
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "v5e")
    table = {"v4": 275e12, "v5e": 197e12, "v5p": 459e12, "v6e": 918e12}
    for k, v in table.items():
        if gen.startswith(k):
            return v
    return 197e12


def main():
    import jax

    import deepspeed_tpu
    from deepspeed_tpu.models import build_gpt

    model_name = os.environ.get("BENCH_MODEL", "gpt2-350m")
    micro_bs = int(os.environ.get("BENCH_BS", "16"))
    seq = int(os.environ.get("BENCH_SEQ", "1024"))
    stage = int(os.environ.get("BENCH_ZERO_STAGE", "2"))
    steps = int(os.environ.get("BENCH_STEPS", "20"))

    import dataclasses

    from deepspeed_tpu.models import gpt as gpt_mod

    cfg = gpt_mod.PRESETS[model_name]
    if os.environ.get("BENCH_REMAT", "1") == "1":
        cfg = dataclasses.replace(cfg, remat=True)
    model, cfg = build_gpt(cfg)
    n_chips = len(jax.devices())
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model,
        config={
            "train_micro_batch_size_per_gpu": micro_bs,
            "optimizer": {"type": "AdamW",
                          "params": {"lr": 3e-4, "weight_decay": 0.1}},
            "bf16": {"enabled": True},
            "zero_optimization": {"stage": stage},
            "gradient_clipping": 1.0,
            "steps_per_print": 0,
        })

    rng = np.random.default_rng(0)

    def make_batch(i):
        return {"input_ids": rng.integers(
            0, cfg.vocab_size, size=(micro_bs * n_chips, seq), dtype=np.int32)}

    # warmup (compile)
    m = engine.train_batch(make_batch(0))
    float(m["loss"])

    t0 = time.perf_counter()
    for i in range(steps):
        m = engine.train_batch(make_batch(i + 1))
    # force a host transfer of an end-of-step output: device_get cannot return
    # until every step in the dependency chain has executed (block_until_ready is
    # not trustworthy through remote-dispatch tunnels)
    float(m["loss"])
    _ = np.asarray(jax.device_get(m["grad_norm"]))
    dt = time.perf_counter() - t0

    tokens = steps * micro_bs * n_chips * (seq - 1)
    tok_per_sec_chip = tokens / dt / n_chips
    # 6*N FLOPs/token (fwd+bwd) + attention term 12*L*d*T per token
    n_params = cfg.num_params()
    flops_per_token = 6 * n_params + 12 * cfg.n_layer * cfg.d_model * seq
    mfu = tok_per_sec_chip * flops_per_token / peak_flops_per_chip()
    result = {
        "metric": f"{model_name} ZeRO-{stage} bf16 training tokens/sec/chip",
        "value": round(tok_per_sec_chip, 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(mfu / 0.45, 3),
        "mfu": round(mfu, 4),
        "chips": n_chips,
        "micro_bs": micro_bs,
        "seq": seq,
        "loss": round(float(m["loss"]), 4),
        "step_ms": round(dt / steps * 1e3, 1),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
