#!/usr/bin/env python
"""Benchmark harness: prints ONE JSON line {"metric","value","unit","vs_baseline",...}.

Round-1 post-mortem (VERDICT.md weak #1): a single axon/TPU backend-init hiccup
must not cost the round's perf evidence. This file is therefore an ORCHESTRATOR
that never imports jax itself:

1. probe the TPU backend in a small subprocess with a hard timeout, retrying
   with backoff (axon init can hang rather than raise);
2. run each benchmark config in its own worker subprocess (``--worker``) with a
   timeout, retrying once — a crash/timeout in one config degrades the sweep,
   not the artifact;
3. if the TPU never comes up, fall back to a forced-CPU mesh so a real measured
   number (clearly marked ``"platform": "cpu"``) is still emitted alongside the
   TPU error record.

Sweep (VERDICT "next" #2, BASELINE.json matrix): ZeRO-1/2/3 training MFU on the
flagship GPT, plus an inference decode p50/p90 latency config (parity:
``/root/reference/benchmarks/inference/gpt-bench.py``). The headline metric is
the best training config's tokens/sec/chip; ``vs_baseline`` is its MFU / 0.45
(the reference stack's at-scale MFU bar — BASELINE.md north star).
"""

import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)
# persistent XLA compile cache, inherited by worker subprocesses: chunk-loss
# train programs compile in the ~20min range on the v5e — without the cache,
# repeat configs (and the driver's end-of-round sweep) pay it every time
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR", os.path.join(REPO, ".jax_cache"))

PROBE_ATTEMPTS = int(os.environ.get("BENCH_PROBE_ATTEMPTS", "3"))
PROBE_TIMEOUT = int(os.environ.get("BENCH_PROBE_TIMEOUT", "240"))
WORKER_TIMEOUT = int(os.environ.get("BENCH_WORKER_TIMEOUT", "1200"))
# mid-sweep tunnel-recovery probing (VERDICT r4 'next' #6): while the sweep is
# running on the CPU fallback, re-probe the real backend between rows so a
# tunnel that comes back MID-run is caught by the driver itself — no builder
# orchestrator needed. Each probe is a watchdogged subprocess; a down tunnel
# costs RECOVERY_PROBE_TIMEOUT once per RECOVERY_PROBE_EVERY seconds, capped.
RECOVERY_PROBE_EVERY = int(os.environ.get("BENCH_RECOVERY_EVERY", "300"))
RECOVERY_PROBE_TIMEOUT = int(os.environ.get("BENCH_RECOVERY_TIMEOUT", "90"))
MAX_RECOVERY_PROBES = int(os.environ.get("BENCH_MAX_RECOVERY_PROBES", "8"))
# partial-sweep ledger: every completed config row is appended here the moment
# it finishes, so a mid-sweep tunnel drop can never zero a round's evidence
# (round-3 post-mortem: the whole r3 sweep died with the tunnel and left no
# recorded TPU numbers — VERDICT r3 "next" #9)
PARTIAL_PATH = os.environ.get("BENCH_PARTIAL_PATH",
                              os.path.join(REPO, "bench_partial.jsonl"))
# global sweep budget (r05 post-mortem: the sweep exceeded the round's wall
# clock and died rc=124 with its evidence stranded in the partial ledger).
# When set, each row's worker timeout is clamped to the remaining budget and
# rows that no longer fit are SKIPPED with a recorded reason instead of
# letting an external `timeout` kill the whole artifact; a SIGTERM mid-row
# still flushes a final summary of everything measured so far.
TOTAL_BUDGET = int(os.environ.get("BENCH_TOTAL_BUDGET", "0"))  # seconds, 0=off
ROW_RESERVE = int(os.environ.get("BENCH_ROW_RESERVE", "45"))


def _persist_row(row: dict) -> None:
    try:
        with open(PARTIAL_PATH, "a") as f:
            f.write(json.dumps({"ts": time.time(), **row}) + "\n")
    except OSError as e:
        print(f"[bench] partial persist failed: {e}", file=sys.stderr)


# ZeRO-Infinity rows (single source of truth; scripts/chip_session.py imports
# these so the tunnel-watch path always benches the same shapes): host masters
# streamed unit-by-unit through HBM — multi-billion-param training on the
# single chip (VERDICT r3 next #3; the reference trains 13B on one V100 the
# same way, docs/_pages/training.md:301)
INFINITY_CONFIGS = [
    # micro_bs 16: the streaming schedule's HBM estimate is 10.6 GB at 6.7B
    # (infinity_aot row) — doubling the batch doubles the tokens amortizing
    # the fixed host-Adam + transfer cost per step
    {"kind": "train", "name": "gpt2-1.3b-infinity", "model": "gpt2-1.3b",
     "micro_bs": 16, "seq": 1024, "steps": 3, "offload": "param_stream",
     "keep_layers": 2, "timeout": 3600},
    {"kind": "train", "name": "gpt-neox-6.7b-infinity",
     "model": "gpt-neox-6.7b", "micro_bs": 16, "seq": 1024, "steps": 2,
     "offload": "param_stream", "keep_layers": 2, "timeout": 5400},
    # the ROADMAP item 3 deliverable: a real measured train step for a >=7B
    # model on ONE v5e host, host masters streamed through the depth-2
    # prefetch pipeline with quantized (block-int8) host fetches — the
    # infinity_aot fit rows say bloom-7b1 fits; this row is the chip-session
    # flagship that turns the AOT verdict into a measured step (reports the
    # host-DMA column: exposed_wait_s, overlapped_frac, qpush ratio)
    {"kind": "train", "name": "bloom-7b1-infinity-streamed",
     "model": "bloom-7b1", "micro_bs": 4, "seq": 1024, "steps": 2,
     "offload": "param_stream", "keep_layers": 2,
     "offload_prefetch_depth": 2, "offload_quantized_fetch": True,
     "timeout": 7200},
    # ZeRO-Offload (optimizer-only) at billion scale: bf16 params resident
    # (2.6 GB), fp32 grads (5.2 GB) + chunked loss ≈ 10 GB device; fp32
    # master+moments (15.6 GB) live in host RAM, stepped by the C++ SIMD Adam
    {"kind": "train", "name": "gpt2-1.3b-offload-opt", "model": "gpt2-1.3b",
     "micro_bs": 8, "seq": 1024, "steps": 3, "offload": "optimizer",
     "stage": 1, "loss_chunk": 128, "timeout": 3600},
]

# Quantized ZeRO collectives (ZeRO++-style, comm/quantized.py): two
# apples-to-apples pairs at identical geometry — stage-3 fp vs quantized
# param gathers (the weight-wire lever), and stage-2 fp vs quantized grad
# reduction (the gradient-wire lever; stage 2 because the quantized grad
# program replicates params per device, which would negate the stage-3 row's
# memory story). fp32 compute on purpose — the wire ratio is measured against
# the logical dtype, and bf16 would halve the 4x-class reduction the knob is
# sold on. Rows report the wire_ledger per-op dict next to step time.
QUANTIZED_ZERO_CONFIGS = [
    {"kind": "train", "name": "gpt2-125m-zero3-fp", "model": "gpt2-125m",
     "micro_bs": 4, "seq": 512, "stage": 3, "steps": 3, "precision": "fp32",
     "timeout": 1800},
    {"kind": "train", "name": "gpt2-125m-zero3-qw8", "model": "gpt2-125m",
     "micro_bs": 4, "seq": 512, "stage": 3, "steps": 3, "precision": "fp32",
     "quantized_weights": True, "timeout": 1800},
    {"kind": "train", "name": "gpt2-125m-zero2-fp", "model": "gpt2-125m",
     "micro_bs": 4, "seq": 512, "stage": 2, "steps": 3, "precision": "fp32",
     "timeout": 1800},
    {"kind": "train", "name": "gpt2-125m-zero2-qg8", "model": "gpt2-125m",
     "micro_bs": 4, "seq": 512, "stage": 2, "steps": 3, "precision": "fp32",
     "quantized_gradients": True, "timeout": 1800},
    # overlap A/B at identical geometry: pipelined (default) vs inline
    # quantized gathers, each with a profiled step reporting the
    # exposed-vs-overlapped collective-time column (wire_overlap)
    {"kind": "train", "name": "gpt2-125m-zero3-qw8-overlap",
     "model": "gpt2-125m", "micro_bs": 4, "seq": 512, "stage": 3, "steps": 3,
     "precision": "fp32", "quantized_weights": True, "measure_overlap": True,
     "timeout": 1800},
    {"kind": "train", "name": "gpt2-125m-zero3-qw8-inline",
     "model": "gpt2-125m", "micro_bs": 4, "seq": 512, "stage": 3, "steps": 3,
     "precision": "fp32", "quantized_weights": True, "overlap_comm": False,
     "measure_overlap": True, "timeout": 1800},
]

# Compile-only evidence rows: the XLA TPU compiler runs on the host, so these
# produce real-v5e HBM/FLOPs numbers for the flagship train configs even when
# the tunnel is dead (round-3 post-mortem: a down tunnel left the round with
# no TPU-grounded numbers at all).
AOT_TRAIN_CONFIGS = [
    {"kind": "sd_aot", "name": "aot-sd-ddim20", "latent": 32,
     "ddim_steps": 20, "force_cpu": True},
    {"kind": "infer_aot", "name": "aot-350m-decode-b1", "model": "gpt2-350m",
     "batch": 1, "prompt": 128, "gen": 64, "force_cpu": True},
    {"kind": "infer_aot", "name": "aot-350m-decode-b8", "model": "gpt2-350m",
     "batch": 8, "prompt": 128, "gen": 64, "force_cpu": True},
    {"kind": "infer_aot", "name": "aot-350m-decode-b8-int8",
     "model": "gpt2-350m", "batch": 8, "prompt": 128, "gen": 64,
     "quantize_bits": 8, "force_cpu": True},
    # 13B weights chip-RESIDENT via the int8 Pallas matmul (the reference
    # needs host offload at this size — ZeRO-Inference regime)
    {"kind": "infer_aot", "name": "aot-opt13b-decode-b1-int8",
     "model": "opt-13b", "batch": 1, "prompt": 128, "gen": 64,
     "quantize_bits": 8, "force_cpu": True},
    # 20B chip-RESIDENT via the packed int4 Pallas matmul (13.8 GB peak,
    # 1.9 GB headroom — outside the fragmentation margin)
    {"kind": "infer_aot", "name": "aot-neox20b-decode-b1-int4",
     "model": "gpt-neox-20b", "batch": 1, "prompt": 128, "gen": 64,
     "quantize_bits": 4, "force_cpu": True, "timeout": 2700},
    {"kind": "kernels_aot", "name": "pallas-kernels-v5e-aot",
     "force_cpu": True, "timeout": 1500},
    {"kind": "train_aot", "name": "gpt2-760m-selrm16-chunk-aot",
     "model": "gpt2-760m", "micro_bs": 16, "seq": 1024,
     "remat_policy": "save_attn_mlp_out", "loss_chunk": 128,
     "force_cpu": True, "timeout": 1500},
    {"kind": "train_aot", "name": "gpt2-760m-bs24-chunk-aot",
     "model": "gpt2-760m", "micro_bs": 24, "seq": 1024, "loss_chunk": 128,
     "force_cpu": True, "timeout": 1500},
    {"kind": "infinity_aot", "name": "bloom-7b1-infinity-aot",
     "model": "bloom-7b1", "micro_bs": 4, "seq": 1024, "keep_layers": 2,
     "force_cpu": True},
    {"kind": "infinity_aot", "name": "gpt-neox-20b-infinity-aot",
     "model": "gpt-neox-20b", "micro_bs": 8, "seq": 1024, "keep_layers": 2,
     "force_cpu": True},
    {"kind": "infinity_aot", "name": "gpt-neox-6.7b-infinity-aot",
     "model": "gpt-neox-6.7b", "micro_bs": 8, "seq": 1024, "keep_layers": 2,
     "force_cpu": True, "timeout": 1500},
    # long context: ring-attention sequence parallelism over 4 chips at
    # seq 8192, and SINGLE-chip 8k via the streamed flash kernels (the k/v
    # stream rides the grid, so there is no whole-sequence VMEM residency)
    {"kind": "train_aot", "name": "gpt2-350m-seq8k-ring-sp4",
     "model": "gpt2-350m", "micro_bs": 2, "seq": 8192, "sp": 4,
     "seq_parallel_impl": "ring", "loss_chunk": 512,
     "force_cpu": True, "timeout": 1500},
    {"kind": "train_aot", "name": "gpt2-350m-seq8k-1chip",
     "model": "gpt2-350m", "micro_bs": 2, "seq": 8192, "loss_chunk": 512,
     "force_cpu": True, "timeout": 1500},
    {"kind": "train_aot", "name": "gpt2-350m-seq8k-ulysses-sp4",
     "model": "gpt2-350m", "micro_bs": 2, "seq": 8192, "sp": 4,
     "seq_parallel_impl": "ulysses", "loss_chunk": 512,
     "force_cpu": True, "timeout": 1500},
    # tensor parallelism: Megatron specs + the shard_mapped flash kernel
    # over tp=2 x dp=2 (the multi-chip config the GSPMD/Mosaic bug would
    # have crashed before this round's fix)
    {"kind": "train_aot", "name": "gpt2-350m-tp2-dp2",
     "model": "gpt2-350m", "micro_bs": 8, "dp": 2, "tp": 2, "seq": 1024,
     "loss_chunk": 128, "force_cpu": True, "timeout": 1500},
    # expert parallelism (BASELINE config #4 shape): expert bank over ep=4,
    # gating all-to-alls over ICI, ZeRO-1 over the (dp, ep) world
    {"kind": "moe_aot", "name": "moe-125m-8e-ep4-aot",
     "model": "moe-125m-8e", "ep": 4, "micro_bs": 4, "seq": 1024,
     "force_cpu": True, "timeout": 1500},
]

# Pipeline rows (VERDICT r3 next #4). The AOT row needs no chips at all — the
# XLA TPU compiler runs on the host against a v5e:2x2 topology — so it
# produces real-TPU memory/FLOPs evidence even through a dead tunnel.
PIPELINE_CONFIGS = [
    {"kind": "pipeline_aot", "name": "gpt2-350m-pp2-aot",
     "model": "gpt2-350m", "pp": 2, "dp": 2, "micro_bs": 4, "seq": 1024,
     "num_micro": 4, "force_cpu": True, "timeout": 1500},
    {"kind": "pipeline_mpmd", "name": "mpmd-dispatch-overhead",
     "d_model": 1024, "n_blocks": 24, "stages": 2, "num_micro": 4,
     "micro_bs": 4, "seq": 1024, "steps": 5, "timeout": 1500},
    # static schedule-prover comparison (ISSUE 18): 1F1B vs interleaved vs
    # zero-bubble bubble % at equal microbatches on the 8-device mesh shape
    # (MULTICHIP_r05.json dry-run world) — pure host math, proofs included
    {"kind": "pipeline_schedule", "name": "schedule-bubble-pp8",
     "stages": 8, "num_micro": 16, "vstages": 2, "micro_bs": 4, "seq": 1024,
     "d_model": 1024, "force_cpu": True, "n_devices": 8, "timeout": 600},
]


def peak_flops_per_chip(platform: str) -> float:
    """bf16 peak for the local chip generation (meaningless on cpu fallback)."""
    if platform == "cpu":
        return 1e12  # nominal; MFU not reported for cpu
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "v5e")
    table = {"v4": 275e12, "v5e": 197e12, "v5p": 459e12, "v6e": 918e12}
    for k, v in table.items():
        if gen.startswith(k):
            return v
    return 197e12


def _cpu_env(env: dict, n_devices: int = 1) -> dict:
    """Force a virtual n-device CPU mesh — single source of truth lives in
    __graft_entry__ (the round-1 axon-hang post-mortem recipe)."""
    from __graft_entry__ import _force_cpu_env

    return _force_cpu_env(n_devices, env)


# the one probe program: a real matmul, so 'initialized' means 'usable'
# (shared by startup probing and mid-sweep recovery probing — keep in sync)
_PROBE_CODE = (
    "import jax, jax.numpy as jnp; d = jax.devices(); "
    "x = jnp.ones((256,256), jnp.bfloat16); (x@x).block_until_ready(); "
    "print('PLATFORM=%s NCHIPS=%d' % (d[0].platform, len(d)))")


def probe_backend() -> tuple:
    """Return ("tpu", n_chips) if a real accelerator initializes, else ("cpu", 1).

    Never blocks the parent: the probe runs in a subprocess under a timeout and
    does one real matmul so 'initialized' means 'usable', not just 'registered'.
    A backend whose devices are CPU counts as the fallback, not the target.
    """
    code = _PROBE_CODE
    errors = []
    for attempt in range(PROBE_ATTEMPTS):
        try:
            p = subprocess.run([sys.executable, "-c", code], timeout=PROBE_TIMEOUT,
                               capture_output=True, text=True, cwd=REPO)
            if p.returncode == 0 and "NCHIPS=" in p.stdout:
                platform = p.stdout.split("PLATFORM=")[1].split()[0]
                n = int(p.stdout.split("NCHIPS=")[1].split()[0])
                if platform == "cpu":
                    errors.append("probe found only CPU devices")
                    return "cpu", 1, errors
                return "tpu", n, errors
            errors.append(f"probe rc={p.returncode}: {p.stderr.strip()[-400:]}")
        except subprocess.TimeoutExpired:
            errors.append(f"probe attempt {attempt + 1} hung >{PROBE_TIMEOUT}s (killed)")
        if attempt < PROBE_ATTEMPTS - 1:
            time.sleep(10 * (attempt + 1))
    return "cpu", 1, errors


def quick_probe(timeout: int = RECOVERY_PROBE_TIMEOUT) -> bool:
    """One fast watchdogged matmul probe; True only if a non-CPU device
    answered. Used between fallback rows to catch a mid-sweep tunnel
    recovery (a down tunnel hangs rather than erroring, hence the timeout)."""
    try:
        p = subprocess.run([sys.executable, "-c", _PROBE_CODE],
                           timeout=timeout, capture_output=True, text=True,
                           cwd=REPO)
        return (p.returncode == 0 and "PLATFORM=" in p.stdout
                and p.stdout.split("PLATFORM=")[1].split()[0] != "cpu")
    except subprocess.TimeoutExpired:
        return False


def run_worker(cfg: dict, platform: str, retries: int = 1):
    """Run one benchmark config in a subprocess; returns parsed JSON or error dict."""
    if cfg.get("force_cpu"):
        # e.g. the AOT pipeline row: the XLA TPU compiler runs on the host —
        # touching the axon backend would only add a hang risk. Rows that
        # model a multi-chip world (the schedule-prover row's 8-stage mesh)
        # set n_devices for a virtual CPU mesh of that size.
        env = _cpu_env(os.environ, n_devices=int(cfg.get("n_devices", 1)))
    else:
        env = dict(os.environ) if platform == "tpu" else _cpu_env(os.environ)
    timeout = int(cfg.get("timeout", WORKER_TIMEOUT))
    last_err = None
    for attempt in range(retries + 1):
        try:
            p = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--worker", json.dumps(cfg)],
                timeout=timeout, capture_output=True, text=True, env=env, cwd=REPO)
            for line in reversed(p.stdout.strip().splitlines()):
                line = line.strip()
                if line.startswith("{"):
                    return json.loads(line)
            last_err = f"rc={p.returncode}: {p.stderr.strip()[-500:]}"
        except subprocess.TimeoutExpired:
            last_err = f"worker hung >{timeout}s (killed)"
        if attempt < retries:
            time.sleep(5)
    return {"config": cfg.get("name"), "error": last_err}


# ---------------------------------------------------------------- worker side

def _worker(cfg: dict) -> None:
    import jax

    # explicit (not env): sitecustomize imports jax before env edits apply
    # when a worker is exec'd without the var already in its environment
    jax.config.update("jax_compilation_cache_dir",
                      os.environ["JAX_COMPILATION_CACHE_DIR"])
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    fn = {"train": _worker_train, "inference": _worker_infer,
          "serving": _worker_serving,
          "serving_overload": _worker_serving_overload,
          "serving_tiered": _worker_serving_tiered,
          "serving_lever": _worker_serving_lever,
          "serving_fleet": _worker_serving_fleet,
          "serving_disagg": _worker_serving_disagg,
          "moe_train": _worker_moe_train,
          "kernels": _worker_kernels, "diffusion": _worker_diffusion,
          "pipeline_aot": _worker_pipeline_aot,
          "pipeline_mpmd": _worker_pipeline_mpmd,
          "pipeline_schedule": _worker_pipeline_schedule,
          "train_aot": _worker_train_aot,
          "infer_aot": _worker_infer_aot,
          "sd_aot": _worker_sd_aot,
          "kernels_aot": _worker_kernels_aot,
          "infinity_aot": _worker_infinity_aot,
          "chaos_mttr": _worker_chaos_mttr,
          "chaos_sdc": _worker_chaos_sdc,
          "moe_aot": _worker_moe_aot}[cfg["kind"]]
    print(json.dumps(fn(cfg)))


def _worker_kernels(cfg: dict) -> dict:
    """Mosaic-compile every Pallas kernel on the chip at bench-realistic shapes
    BEFORE the sweep, so a BlockSpec regression costs one config, not the
    round's inference evidence (VERDICT r2 'next' #1)."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    platform = jax.devices()[0].platform
    rng = np.random.default_rng(0)
    results, failed = {}, []

    def check(name, fn):
        try:
            t0 = time.perf_counter()
            jax.block_until_ready(fn())
            results[name] = {"ok": True,
                             "compile_s": round(time.perf_counter() - t0, 1)}
        except Exception as e:  # record, keep probing the others
            results[name] = {"ok": False, "error": str(e)[-300:]}
            failed.append(name)

    B, H, S, Dh = 4, 16, 1024, 64
    q4 = jnp.asarray(rng.standard_normal((B, S, H, Dh)), jnp.bfloat16)

    def flash():
        from deepspeed_tpu.ops.pallas.flash_attention import flash_attention

        f = jax.jit(lambda q, k, v: flash_attention(q, k, v, causal=True))
        return f(q4, q4, q4)

    def flash_bwd():
        from deepspeed_tpu.ops.pallas.flash_attention import flash_attention

        f = jax.jit(jax.grad(
            lambda q, k, v: flash_attention(q, k, v, causal=True)
            .astype(jnp.float32).sum()))
        return f(q4, q4, q4)

    def decode():
        from deepspeed_tpu.ops.pallas.decode_attention import decode_attention

        qd = jnp.asarray(rng.standard_normal((B, 1, H, Dh)), jnp.bfloat16)
        kc = jnp.asarray(rng.standard_normal((B, H, S, Dh)), jnp.bfloat16)
        f = jax.jit(lambda q, k, v, n: decode_attention(q, k, v, n))
        return f(qd, kc, kc, jnp.int32(S // 2))

    def decode_b16():
        # the BENCH_r02 regression shape: wide batch grid + per-row lengths
        from deepspeed_tpu.ops.pallas.decode_attention import decode_attention

        qd = jnp.asarray(rng.standard_normal((16, 1, H, Dh)), jnp.bfloat16)
        kc = jnp.asarray(rng.standard_normal((16, H, S, Dh)), jnp.bfloat16)
        lens = jnp.asarray(rng.integers(1, S + 1, (16,)), jnp.int32)
        f = jax.jit(lambda q, k, v, n: decode_attention(q, k, v, n))
        return f(qd, kc, kc, lens)

    def paged_decode():
        # block-table gather through the scalar-prefetched index_map
        from deepspeed_tpu.ops.pallas.decode_attention import \
            paged_decode_attention

        ps, MP, P = 128, 8, 256
        qd = jnp.asarray(rng.standard_normal((16, 1, H, Dh)), jnp.bfloat16)
        kp = jnp.asarray(rng.standard_normal((H, P, ps, Dh)), jnp.bfloat16)
        tbl = jnp.asarray(rng.integers(1, P, (16, MP)), jnp.int32)
        lens = jnp.asarray(rng.integers(1, MP * ps + 1, (16,)), jnp.int32)
        f = jax.jit(lambda q, k, v, n, t: paged_decode_attention(
            q, k, v, n, t, impl="kernel"))
        return f(qd, kp, kp, lens, tbl)

    def blocksparse():
        from deepspeed_tpu.ops.pallas.blocksparse_attention import (
            blocksparse_attention)
        from deepspeed_tpu.ops.sparse_attention import FixedSparsityConfig

        sc = FixedSparsityConfig(num_heads=H, block=128)
        layout = np.asarray(sc.make_layout(S))
        f = jax.jit(lambda q, k, v: blocksparse_attention(
            q, k, v, layout=layout, block=128))
        return f(q4, q4, q4)

    def blocksparse_bwd():
        from deepspeed_tpu.ops.pallas.blocksparse_attention import (
            blocksparse_attention)
        from deepspeed_tpu.ops.sparse_attention import FixedSparsityConfig

        sc = FixedSparsityConfig(num_heads=H, block=128)
        layout = np.asarray(sc.make_layout(S))
        f = jax.jit(jax.grad(lambda q, k, v: blocksparse_attention(
            q, k, v, layout=layout, block=128).astype(jnp.float32).sum()))
        return f(q4, q4, q4)

    def int8mm():
        from deepspeed_tpu.ops.pallas.int8_matmul import int8_matmul

        x8 = jnp.asarray(rng.standard_normal((8, 512)), jnp.bfloat16)
        q8 = jnp.asarray(rng.integers(-127, 128, (512, 1536)), jnp.int8)
        s8 = jnp.asarray(rng.uniform(0.01, 0.1, (512 * 1536 // 128,)),
                         jnp.float32)
        f = jax.jit(lambda x, q, s: int8_matmul(x, q, s, group_size=128))
        return f(x8, q8, s8)

    def int4mm():
        from deepspeed_tpu.ops.pallas.int8_matmul import int4_matmul

        x4 = jnp.asarray(rng.standard_normal((8, 512)), jnp.bfloat16)
        q4 = jnp.asarray(rng.integers(-128, 128, (512, 1536)), jnp.int8)
        s4 = jnp.asarray(rng.uniform(0.01, 0.1, (512 * 3072 // 128,)),
                         jnp.float32)
        f = jax.jit(lambda x, q, s: int4_matmul(x, q, s, group_size=128))
        return f(x4, q4, s4)

    check("flash_attention", flash)
    check("flash_attention_bwd", flash_bwd)
    check("decode_attention", decode)
    check("decode_attention_b16", decode_b16)
    check("paged_decode_attention", paged_decode)
    check("blocksparse_attention", blocksparse)
    check("blocksparse_attention_bwd", blocksparse_bwd)
    check("int8_matmul", int8mm)
    check("int4_matmul", int4mm)
    out = {"config": cfg["name"], "kind": "kernels", "platform": platform,
           "kernels": results}
    if failed:
        out["error"] = "Mosaic compile failed: " + ", ".join(
            f"{k} ({results[k]['error'][-120:]})" for k in failed)
    return out


def _worker_train(cfg: dict) -> dict:
    import dataclasses

    import numpy as np

    import jax

    import deepspeed_tpu
    from deepspeed_tpu.models import build_gpt
    from deepspeed_tpu.models import gpt as gpt_mod

    platform = jax.devices()[0].platform
    mcfg = gpt_mod.PRESETS[cfg["model"]]
    if cfg.get("remat", True):
        mcfg = dataclasses.replace(
            mcfg, remat=True,
            remat_policy=cfg.get("remat_policy", "nothing_saveable"))
    if cfg.get("loss_chunk"):
        mcfg = dataclasses.replace(mcfg, loss_chunk=int(cfg["loss_chunk"]))
    model, mcfg = build_gpt(mcfg)
    n_chips = len(jax.devices())
    micro_bs, seq, steps = cfg["micro_bs"], cfg["seq"], cfg["steps"]
    zero_cfg = {"stage": cfg.get("stage", 0)}
    # quantized collectives (QUANTIZED_ZERO_CONFIGS): block-int8 wire for the
    # ZeRO-3 param gathers and/or the dp gradient reduction
    if cfg.get("quantized_weights"):
        zero_cfg["zero_quantized_weights"] = True
    if cfg.get("quantized_gradients"):
        zero_cfg["zero_quantized_gradients"] = True
    if cfg.get("quantize_bits"):
        zero_cfg["zero_quantize_bits"] = int(cfg["quantize_bits"])
    # overlap knobs (docs/COMM_COMPRESSION.md "Overlap & fusion"): default is
    # the pipelined/bucketed schedules; overlap_comm=False benches the inline
    # baseline the overlap rows are compared against
    if cfg.get("overlap_comm") is not None:
        zero_cfg["overlap_comm"] = bool(cfg["overlap_comm"])
    if cfg.get("prefetch_depth"):
        zero_cfg["overlap_prefetch_depth"] = int(cfg["prefetch_depth"])
    if cfg.get("offload") == "param_stream":
        # ZeRO-Infinity: host masters streamed unit-by-unit through HBM —
        # the bigger-than-HBM single-chip regime (reference: 13B on one V100,
        # docs/_pages/training.md:301). Streaming knobs (docs/OFFLOAD.md):
        # offload_stream=False benches the fetch-on-demand baseline the
        # streamed rows are A/B'd against; offload_quantized_fetch pushes
        # units over the block-int8 host wire
        op_cfg = {"device": "cpu", "buffer_count": cfg.get("keep_layers", 2)}
        if cfg.get("offload_stream") is not None:
            op_cfg["stream"] = bool(cfg["offload_stream"])
        if cfg.get("offload_prefetch_depth") is not None:
            op_cfg["prefetch_depth"] = int(cfg["offload_prefetch_depth"])
        if cfg.get("offload_quantized_fetch"):
            op_cfg["quantized_fetch"] = True
        zero_cfg["offload_param"] = op_cfg
    elif cfg.get("offload") == "optimizer":
        zero_cfg["offload_optimizer"] = {"device": "cpu"}
    # gas>1 folds all micro-steps into one compiled program (engine's fused
    # accumulation scan): amortizes per-dispatch tunnel RTT (~350ms constant,
    # measured r4) exactly the way real accumulated training does
    gas = int(cfg.get("gas", 1))
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model,
        config={
            "train_micro_batch_size_per_gpu": micro_bs,
            "gradient_accumulation_steps": gas,
            "optimizer": {"type": "AdamW",
                          "params": {"lr": 3e-4, "weight_decay": 0.1}},
            # precision=fp32 (the quantized-zero rows): logical wire dtype is
            # fp32 so the ledger ratio reflects the full int8 reduction
            "bf16": {"enabled": cfg.get("precision", "bf16") != "fp32"},
            "zero_optimization": zero_cfg,
            "gradient_clipping": 1.0,
            "steps_per_print": 0,
        })

    rng = np.random.default_rng(0)
    # k_steps>1: K complete optimizer steps per dispatch (engine.train_batches
    # scan — no cross-step accumulator, peak HBM equals the k=1 program; the
    # gas=8 variants AOT-OOM at the lead geometries)
    k_steps = int(cfg.get("k_steps", 1))
    shape = ((gas, micro_bs * n_chips, seq) if gas > 1
             else (micro_bs * n_chips, seq))
    if k_steps > 1:
        shape = (k_steps,) + shape

    def make_batch():
        return {"input_ids": rng.integers(
            0, mcfg.vocab_size, size=shape, dtype=np.int32)}

    step_fn = engine.train_batches if k_steps > 1 else engine.train_batch
    m = step_fn(make_batch())  # warmup/compile
    float(m["loss"])

    t0 = time.perf_counter()
    for _ in range(steps):
        m = step_fn(make_batch())
    # host transfer: device_get can't return until the whole chain executed
    # (block_until_ready is not trustworthy through remote-dispatch tunnels)
    float(m["loss"])
    _ = np.asarray(jax.device_get(m["grad_norm"]))
    dt = time.perf_counter() - t0

    tokens = steps * k_steps * gas * micro_bs * n_chips * (seq - 1)
    tok_per_sec_chip = tokens / dt / n_chips
    n_params = mcfg.num_params()
    # 6*N FLOPs/token (fwd+bwd) + attention term 12*L*d*T per token
    flops_per_token = 6 * n_params + 12 * mcfg.n_layer * mcfg.d_model * seq
    mfu = tok_per_sec_chip * flops_per_token / peak_flops_per_chip(platform)
    out = {
        "config": cfg["name"], "kind": "train", "platform": platform,
        "tokens_per_sec_chip": round(tok_per_sec_chip, 1),
        "mfu": round(mfu, 4), "chips": n_chips, "micro_bs": micro_bs,
        "gas": gas, "k_steps": k_steps, "seq": seq,
        "stage": cfg.get("stage", 0),
        "loss": round(float(m["loss"]), 4),
        "step_ms": round(dt / (steps * k_steps) * 1e3, 1),
    }
    if cfg.get("measure_overlap"):
        # one extra profiled step: the exposed-vs-overlapped collective-time
        # column — where the step time actually went (docs/COMM_COMPRESSION.md
        # "Overlap & fusion"). A profiling failure must not cost the row's
        # measured numbers.
        try:
            single = {"input_ids": rng.integers(
                0, mcfg.vocab_size,
                size=((gas, micro_bs * n_chips, seq) if gas > 1
                      else (micro_bs * n_chips, seq)), dtype=np.int32)}
            out["wire_overlap"] = engine.measure_overlap(single).to_dict()
        except Exception as e:
            out["wire_overlap"] = {"error": str(e)[-200:]}
    if cfg.get("quantized_weights") or cfg.get("quantized_gradients"):
        # logical-vs-wire bytes per quantized op (trace-time ledger): the
        # compression evidence the QUANTIZED_ZERO_CONFIGS rows exist for
        from deepspeed_tpu.comm.runtime_accounting import wire_ledger

        out["wire"] = wire_ledger.summary_dict()
        out["wire_ratio"] = round(wire_ledger.ratio(), 3)
    if cfg.get("offload"):
        out["offload"] = cfg["offload"]
        runner = getattr(engine, "_param_stream", None)
        if runner is not None and runner.last_stats:
            # HBM/host breakdown: the whole point of the >HBM-sized row
            out["memory"] = {k: runner.last_stats[k]
                             for k in ("hbm_peak_bytes", "host_rss_bytes",
                                       "n_params", "wire_bytes_per_step",
                                       "prefetch_depth",
                                       "stream_buffer_bytes")
                             if k in runner.last_stats}
            # the streamed-vs-inline A/B observable (docs/OFFLOAD.md): how
            # much of the host<->HBM DMA sat exposed at a consume point,
            # and the fraction of waits the prefetch schedule hid entirely
            if "host_dma" in runner.last_stats:
                out["host_dma"] = runner.last_stats["host_dma"]
    return out


def _worker_chaos_mttr(cfg: dict) -> dict:
    """MTTR row (docs/RESILIENCE.md "In-run health"): inject a NaN at a known
    data cursor and measure the self-heal — detection + rollback latency,
    steps to rejoin a pre-divergence loss level, and the poisoned cursors
    provably excluded. Runs the REAL engine health loop (sentinel config +
    chaos injector), not a simulation."""
    import math
    import tempfile
    import time as _time

    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models import build_gpt
    from deepspeed_tpu.models import gpt as gpt_mod
    from deepspeed_tpu.resilience import FaultPlan, install_plan

    mcfg = gpt_mod.PRESETS[cfg["model"]]
    model, mcfg = build_gpt(mcfg)
    micro_bs, seq = cfg["micro_bs"], cfg["seq"]
    steps, nan_at = int(cfg["steps"]), int(cfg["nan_at"])
    with tempfile.TemporaryDirectory() as td:
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model,
            config={
                "train_micro_batch_size_per_gpu": micro_bs,
                "optimizer": {"type": "AdamW", "params": {"lr": 3e-4}},
                "bf16": {"enabled": False},
                "steps_per_print": 0,
                "resilience": {
                    "enabled": True, "save_dir": td,
                    "install_signal_handlers": False,
                    "sentinel": {"enabled": True, "warmup_steps": 1,
                                 "checkpoint_interval": 1,
                                 "cursor_checkpointable": True}},
            })
        install_plan(FaultPlan.from_dict({"nan_at_step": nan_at}))

        def make_batch(cursor):
            r = np.random.default_rng(cursor)
            return {"input_ids": r.integers(
                0, mcfg.vocab_size, size=(micro_bs, seq), dtype=np.int32)}

        losses, rollback = [], None
        detect_wall = heal_wall = None
        t0 = _time.monotonic()
        while engine.global_steps < steps:
            m = engine.train_batch(make_batch(engine.data_cursor))
            if m.get("skipped_batch"):
                continue
            h = m.get("health", {}).get("rolled_back")
            if h:
                rollback = h
                detect_wall = _time.monotonic() - t0
            elif math.isfinite(float(m["loss"])):
                losses.append(float(m["loss"]))
                if rollback is not None and heal_wall is None:
                    heal_wall = _time.monotonic() - t0
        install_plan(None)
        health = engine._health
        return {
            "config": cfg["name"],
            "healed": rollback is not None and math.isfinite(losses[-1]),
            "rollbacks": health.rollbacks,
            "rollback_latency_s": (round(rollback["latency_s"], 4)
                                   if rollback else None),
            # wall-clock from divergence detection to the first healthy
            # post-heal step — the row's MTTR
            "mttr_s": (round(heal_wall - detect_wall, 3)
                       if heal_wall is not None else None),
            "skipped_cursors": health.skipped_cursors,
            "final_loss": round(losses[-1], 4) if losses else None,
            "steps": int(engine.global_steps),
            "data_cursor": int(engine.data_cursor),
        }


def _worker_chaos_sdc(cfg: dict) -> dict:
    """SDC row (docs/RESILIENCE.md "Data integrity"): one REAL bit flip in
    each of two state domains — a cpu-offloaded optimizer shard mid-training
    and a prefix-shared KV page mid-serving — measuring detection latency,
    heal (rollback replay must be step-exact; serving re-prefill must be
    generate-identical), and the integrity scan's overhead at the DEFAULT
    budget (scan_interval=16 x 4 blocks), which the row asserts ≤5%."""
    import math
    import tempfile
    import time as _time

    import numpy as np

    import jax

    import deepspeed_tpu
    from deepspeed_tpu.inference.serving import ServingConfig, ServingEngine
    from deepspeed_tpu.inference.serving.scheduler import Request
    from deepspeed_tpu.models import build_gpt
    from deepspeed_tpu.models import gpt as gpt_mod
    from deepspeed_tpu.resilience import FaultPlan, install_plan

    mcfg = gpt_mod.PRESETS[cfg["model"]]
    micro_bs, seq = cfg["micro_bs"], cfg["seq"]
    steps, flip_at = int(cfg["steps"]), int(cfg["flip_at"])

    # ---- training domain: host-offloaded optimizer shard -----------------
    def train_run(td: str, flip: bool) -> dict:
        install_plan(FaultPlan.from_dict(
            {"flip_bit_at": flip_at, "flip_bit_domain": "host_shards"})
            if flip else None)
        model, _ = build_gpt(mcfg)
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model,
            config={
                "train_micro_batch_size_per_gpu": micro_bs,
                "optimizer": {"type": "AdamW", "params": {"lr": 3e-4}},
                "steps_per_print": 0,
                "zero_optimization": {
                    "stage": 2, "offload_optimizer": {"device": "cpu"}},
                "resilience": {
                    "enabled": True, "save_dir": td,
                    "install_signal_handlers": False,
                    "sentinel": {"enabled": True, "warmup_steps": 1,
                                 "checkpoint_interval": 4,
                                 "cursor_checkpointable": True},
                    # DEFAULT scan budget — the overhead number the row
                    # reports is the one production would pay
                    "integrity": {"enabled": True}},
            })

        def make_batch(cursor):
            r = np.random.default_rng(cursor)
            return {"input_ids": r.integers(
                0, mcfg.vocab_size, size=(micro_bs, seq), dtype=np.int32)}

        rollback = None
        detect_step = detect_wall = heal_wall = None
        t0 = _time.monotonic()
        loss = float("nan")
        while engine.global_steps < steps:
            m = engine.train_batch(make_batch(engine.data_cursor))
            h = m.get("health", {}).get("rolled_back")
            if h is not None and "sdc" in m:
                rollback = h
                # the cursor already rewound with the rollback — the
                # detection boundary is where the rollback started from
                detect_step = int(h.get("from_step", engine.data_cursor))
                detect_wall = _time.monotonic() - t0
                continue
            loss = float(m["loss"])
            if rollback is not None and heal_wall is None \
                    and math.isfinite(loss):
                heal_wall = _time.monotonic() - t0
        report = engine._integrity.report()
        counters = dict(engine._recovery_log.counters)
        install_plan(None)
        return {"loss": loss, "rollback": rollback,
                "detect_step": detect_step,
                "mttr_s": (round(heal_wall - detect_wall, 3)
                           if heal_wall is not None else None),
                "report": report, "counters": counters}

    with tempfile.TemporaryDirectory() as td:
        ref = train_run(os.path.join(td, "ref"), flip=False)
        hit = train_run(os.path.join(td, "flip"), flip=True)
    training = {
        "detected": hit["rollback"] is not None,
        # boundaries from injection to detection: the flip lands at the
        # pre-step verify of the SAME boundary, so this is scan latency
        "detect_latency_steps": (hit["detect_step"] - flip_at
                                 if hit["detect_step"] is not None else None),
        "rollback_latency_s": (round(hit["rollback"]["latency_s"], 4)
                               if hit["rollback"] else None),
        "mttr_s": hit["mttr_s"],
        # the heal contract: replayed batches land on the SAME final loss
        "step_exact": hit["loss"] == ref["loss"],
        "final_loss": round(hit["loss"], 4),
        "clean_run_sdc_events": ref["counters"].get("sdc_detected", 0),
        "scan_overhead_frac": round(ref["report"]["overhead_frac"], 5),
        "blocks_verified": ref["report"]["blocks_verified"],
    }

    # ---- serving domain: prefix-shared KV page ---------------------------
    params = gpt_mod.init_params(mcfg, jax.random.PRNGKey(0))
    eng = ServingEngine(mcfg, params, ServingConfig(
        num_slots=4, page_size=16, max_model_len=128, prefill_chunk=32,
        dtype="float32", decode_block=1, max_queue=64,
        enable_prefix_cache=True, page_fingerprints=True))
    prompt = (np.arange(40, dtype=np.int32) % (mcfg.vocab_size - 1)) + 1

    def serve_run(flip: bool) -> dict:
        install_plan(FaultPlan.from_dict(
            {"flip_bit_at": 2, "flip_bit_domain": "kv_page"})
            if flip else None)
        sched = eng.make_scheduler()
        reqs = [Request(prompt=prompt.copy(), max_new_tokens=8)
                for _ in range(2)]
        sched.submit(reqs[0])
        for _ in range(3):
            sched.step()
        sched.submit(reqs[1])
        detect_step = flip_step = None
        audit_mid = None
        for _ in range(120):
            sched.step()
            if flip_step is None and sched.counters.get("chaos_injected"):
                flip_step = sched.steps
            if detect_step is None and sched.counters.get("sdc_detected"):
                detect_step = sched.steps
            if audit_mid is None and sched.page_stats["shared"]:
                audit_mid = sched.audit()
            if all(r.state.value == "finished" for r in reqs):
                break
        out = {"tokens": [list(r.tokens) for r in reqs],
               "counters": dict(sched.counters),
               "flip_step": flip_step, "detect_step": detect_step,
               "audit_mid": audit_mid, "audit": sched.audit()}
        sched.close()
        install_plan(None)
        return out

    sref = serve_run(flip=False)
    sflip = serve_run(flip=True)
    serving = {
        "detected": bool(sflip["counters"].get("sdc_detected")),
        "healed": bool(sflip["counters"].get("sdc_healed")),
        "detect_latency_steps": (sflip["detect_step"] - sflip["flip_step"]
                                 if sflip["detect_step"] is not None
                                 and sflip["flip_step"] is not None else None),
        "borrower_preemptions": sflip["counters"].get("preemption", 0),
        "greedy_identical": sflip["tokens"] == sref["tokens"],
        "audit_ok": bool(sflip["audit"]["ok"]),
        "pages_fingerprint_swept": (sref["audit_mid"] or {}).get(
            "fingerprinted", 0),
        "clean_run_sdc_events": sref["counters"].get("sdc_detected", 0),
    }

    domains = int(training["detected"]) + int(serving["detected"])
    return {
        "config": cfg["name"],
        "training": training,
        "serving": serving,
        "domains_detected": domains,
        "healed": (domains == 2 and training["step_exact"]
                   and serving["greedy_identical"] and serving["audit_ok"]),
        "overhead_ok": training["scan_overhead_frac"] <= 0.05,
    }


def _worker_moe_train(cfg: dict) -> dict:
    """Measured MoE training step (VERDICT r4 'next' #5): GShard top-k gating +
    expert bank through the full engine step on the real device. Single-chip
    ep=1 keeps the whole expert bank resident; the gating/dispatch einsums are
    identical to the ep>1 program (moe/sharded_moe.py), so step time here is
    the per-chip compute term of BASELINE config #4 (the reference measures
    this path in ``DeepSpeed-MoE``, deepspeed/moe/sharded_moe.py)."""
    import numpy as np

    import jax

    import deepspeed_tpu
    from deepspeed_tpu.models import build_gpt_moe

    platform = jax.devices()[0].platform
    model, mcfg = build_gpt_moe(cfg.get("model", "moe-125m-8e"))
    micro_bs, seq = int(cfg["micro_bs"]), int(cfg["seq"])
    steps = int(cfg.get("steps", 5))
    n_chips = len(jax.devices())
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model,
        config={
            "train_micro_batch_size_per_gpu": micro_bs,
            "optimizer": {"type": "AdamW",
                          "params": {"lr": 3e-4, "weight_decay": 0.1}},
            "bf16": {"enabled": True},
            "zero_optimization": {"stage": cfg.get("stage", 1)},
            "gradient_clipping": 1.0,
            "steps_per_print": 0,
        })
    b = mcfg.base
    rng = np.random.default_rng(0)

    def make_batch():
        # global batch rides the dp mesh axis, micro_bs per chip (as
        # _worker_train does) so tokens/sec/chip stays per-chip truth
        return {"input_ids": rng.integers(
            0, b.vocab_size, size=(micro_bs * n_chips, seq), dtype=np.int32)}

    m = engine.train_batch(make_batch())  # warmup/compile
    float(m["loss"])
    t0 = time.perf_counter()
    for _ in range(steps):
        m = engine.train_batch(make_batch())
    float(m["loss"])
    dt = time.perf_counter() - t0

    # MFU over ACTIVE FLOPs/token: attention + dense MLPs + gate + the k
    # routed expert FFNs (a dropped-token step does fewer — this is the upper
    # bound the capacity factor allows, the standard MoE-MFU convention)
    d, L, ff = b.d_model, b.n_layer, b.ffn_dim
    n_super = mcfg.n_super
    active = (L * 4 * d * d + (L - n_super) * 2 * d * ff
              + n_super * (mcfg.k * 2 * d * ff + d * mcfg.num_experts)
              + d * b.vocab_size)
    flops_per_token = 6 * active + 12 * L * d * seq
    tok = steps * micro_bs * n_chips * (seq - 1) / dt / n_chips
    mfu = tok * flops_per_token / peak_flops_per_chip(platform)
    return {
        "config": cfg["name"], "kind": "moe_train", "platform": platform,
        "model": cfg.get("model", "moe-125m-8e"),
        "num_experts": mcfg.num_experts, "k": mcfg.k,
        "micro_bs": micro_bs, "seq": seq, "chips": n_chips,
        "tokens_per_sec_chip": round(tok, 1), "mfu": round(mfu, 4),
        "step_ms": round(dt / steps * 1e3, 1),
        "loss": round(float(m["loss"]), 4),
    }


def _worker_infer(cfg: dict) -> dict:
    import numpy as np

    import jax

    from deepspeed_tpu.inference import DeepSpeedInferenceConfig, InferenceEngine
    from deepspeed_tpu.inference.engine import for_gpt
    from deepspeed_tpu.models import gpt as gpt_mod

    platform = jax.devices()[0].platform
    mcfg = gpt_mod.PRESETS[cfg["model"]]
    # quantize_bits: weight-only int8/int4 decode (Pallas dequant-per-tile
    # matmuls) — measures the weight-bandwidth lever on the real chip
    qbits = int(cfg.get("quantize_bits", 0))
    if cfg.get("stream_init"):
        # big models (13B/20B): host-streamed quantized init — the fp32 tree
        # never exists anywhere, the device gets only the narrow stacks
        params = gpt_mod.init_quantized_decode_params(
            mcfg, bits=qbits or 4, group_size=128)
        quant = {"enabled": False}  # params arrive pre-quantized
    else:
        params = gpt_mod.init_params(mcfg, jax.random.PRNGKey(0))
        quant = {"enabled": bool(qbits), "bits": qbits or 8,
                 "group_size": 128}
    engine = InferenceEngine(
        for_gpt(mcfg, params),
        DeepSpeedInferenceConfig(
            dtype="bfloat16",
            max_out_tokens=cfg["prompt"] + cfg["gen"] + 8,
            quant=quant))
    ids = np.asarray(np.random.default_rng(0).integers(
        0, mcfg.vocab_size, (cfg["batch"], cfg["prompt"])), np.int32)

    short, long_ = max(cfg["gen"] // 4, 1), cfg["gen"]
    # warmup/compile both shapes
    np.asarray(engine.generate(ids, max_new_tokens=short))
    np.asarray(engine.generate(ids, max_new_tokens=long_))
    lat = []
    for _ in range(cfg.get("reps", 5)):
        t0 = time.perf_counter()
        np.asarray(engine.generate(ids, max_new_tokens=short))
        t1 = time.perf_counter()
        np.asarray(engine.generate(ids, max_new_tokens=long_))
        t2 = time.perf_counter()
        # subtract prefill+dispatch overhead: marginal per-token decode latency
        lat.append(((t2 - t1) - (t1 - t0)) / (long_ - short) * 1e3)
    lat.sort()
    p50 = lat[len(lat) // 2]
    p90 = lat[min(len(lat) - 1, int(len(lat) * 0.9))]
    out = {
        "config": cfg["name"], "kind": "inference", "platform": platform,
        "decode_p50_ms": round(p50, 3), "decode_p90_ms": round(p90, 3),
        "tokens_per_sec": round(1e3 / max(p50, 1e-9) * cfg["batch"], 1),
        "batch": cfg["batch"], "prompt": cfg["prompt"],
    }
    if qbits:
        out["quantize_bits"] = qbits
    return out


def _worker_serving(cfg: dict) -> dict:
    """Request-level serving bench: open-loop arrivals through the
    continuous-batching paged stack vs the static-batch ``generate``
    baseline on the SAME seeded workload (equal useful-token accounting,
    comparable HBM budget). Reports p50/p99 TTFT, per-token latency, and
    aggregate tokens/s for both, plus the speedup the serving row's
    acceptance bar is judged on."""
    import numpy as np

    import jax

    from deepspeed_tpu.inference import (DeepSpeedInferenceConfig,
                                         InferenceEngine)
    from deepspeed_tpu.inference.engine import for_gpt
    from deepspeed_tpu.inference.serving import (ServingConfig, ServingEngine,
                                                 make_open_loop_workload,
                                                 run_continuous,
                                                 run_static_baseline)
    from deepspeed_tpu.models import gpt as gpt_mod

    platform = jax.devices()[0].platform
    mcfg = gpt_mod.PRESETS[cfg["model"]]
    params = gpt_mod.init_params(mcfg, jax.random.PRNGKey(0))
    dtype = cfg.get("dtype", "bfloat16")
    slots = int(cfg.get("slots", 8))
    max_len = int(cfg.get("max_model_len", 512))
    page_size = int(cfg.get("page_size", 64))
    prompt_rng = tuple(cfg.get("prompt_range", (32, 128)))
    gen_rng = tuple(cfg.get("gen_range", (16, 96)))
    n_req = int(cfg.get("requests", 24))
    rate = float(cfg.get("rate_rps", 8.0))

    def workload(seed=0):
        return make_open_loop_workload(
            n_req, rate, prompt_rng, gen_rng, mcfg.vocab_size, seed=seed)

    # equal-HBM framing: both sides get the same KV token budget. Static
    # batching must reserve the workload's padded worst case per row; the
    # paged pool shares the same tokens across MORE slots (mixed lengths
    # mean average residency << worst case; preemption covers the tail).
    wl_probe = workload()
    warm_t = max(len(r.prompt) for r in wl_probe)
    warm_g = max(r.max_new_tokens for r in wl_probe)
    static_row_tokens = -(-(warm_t + warm_g) // 128) * 128  # generate's pad
    hbm_tokens = int(cfg.get("hbm_tokens", slots * static_row_tokens // 2))
    static_batch = max(1, hbm_tokens // static_row_tokens)

    eng = ServingEngine(mcfg, params, ServingConfig(
        num_slots=slots, page_size=page_size, max_model_len=max_len,
        num_pages=hbm_tokens // page_size + 1,
        prefill_chunk=int(cfg.get("prefill_chunk", 128)), dtype=dtype,
        tp=int(cfg.get("tp", 1))))

    # compile every serving program shape outside the timed window
    eng.warmup()
    cont = run_continuous(eng, workload())

    ie = InferenceEngine(for_gpt(mcfg, params), DeepSpeedInferenceConfig(
        dtype=dtype, max_out_tokens=max_len))
    # warm the exact batch shape the measured baseline will run (the
    # baseline pads globally to the workload's max prompt/gen)
    from deepspeed_tpu.inference.serving import Request
    warm = [Request(prompt=np.zeros(warm_t, np.int32), max_new_tokens=warm_g)
            for _ in range(static_batch)]
    run_static_baseline(ie, warm, batch_size=static_batch)
    static = run_static_baseline(ie, workload(), batch_size=static_batch)

    speedup = (cont["tokens_per_sec"] / static["tokens_per_sec"]
               if static["tokens_per_sec"] else float("nan"))
    out = {
        "config": cfg["name"], "kind": "serving", "platform": platform,
        "model": cfg["model"], "num_slots": slots,
        "hbm_tokens": hbm_tokens, "static_batch": static_batch,
        "static_row_tokens": static_row_tokens,
        "requests": n_req, "rate_rps": rate,
        "tokens_per_sec": cont["tokens_per_sec"],
        "ttft_p50_ms": cont["ttft_p50_ms"], "ttft_p99_ms": cont["ttft_p99_ms"],
        "per_token_p50_ms": cont["per_token_p50_ms"],
        "per_token_p99_ms": cont["per_token_p99_ms"],
        "preemptions": cont["preemptions"],
        "compiled_programs": cont["compiled_programs"],
        "hbm_token_slots": cont["hbm_token_slots"],
        "static_tokens_per_sec": static["tokens_per_sec"],
        "static_ttft_p50_ms": static["ttft_p50_ms"],
        "static_ttft_p99_ms": static["ttft_p99_ms"],
        "speedup_vs_static": round(speedup, 3),
        "continuous": cont, "static": static,
    }
    return out


def _worker_serving_overload(cfg: dict) -> dict:
    """Overload A/B at 2x saturation (docs/SERVING.md "Overload & failure"):
    calibrate the server's closed-loop saturation rate, then drive the SAME
    2x-rate Poisson workload through (a) an overload-CONTROLLED scheduler
    (bounded queue, token backpressure, deadlines = the SLO) and (b) an
    uncontrolled one (the unsafe default). Both score against the same
    evaluation SLO, so the row shows what admission control buys: bounded
    p99 TTFT of *accepted* requests and higher goodput, versus a baseline
    whose queue — and tail — grows for as long as the load lasts."""
    import jax

    from deepspeed_tpu.inference.serving import (ContinuousBatchingScheduler,
                                                 ServingConfig, ServingEngine,
                                                 estimate_saturation_rps,
                                                 make_open_loop_workload,
                                                 run_continuous)
    from deepspeed_tpu.models import gpt as gpt_mod

    platform = jax.devices()[0].platform
    mcfg = gpt_mod.PRESETS[cfg["model"]]
    params = gpt_mod.init_params(mcfg, jax.random.PRNGKey(0))
    slots = int(cfg.get("slots", 8))
    page_size = int(cfg.get("page_size", 16))
    max_len = int(cfg.get("max_model_len", 128))
    prompt_rng = tuple(cfg.get("prompt_range", (8, 32)))
    gen_rng = tuple(cfg.get("gen_range", (8, 32)))
    n_req = int(cfg.get("requests", 24))
    slo_s = float(cfg.get("slo_s", 2.0))

    eng = ServingEngine(mcfg, params, ServingConfig(
        num_slots=slots, page_size=page_size, max_model_len=max_len,
        prefill_chunk=int(cfg.get("prefill_chunk", 32)),
        dtype=cfg.get("dtype", "float32")))
    eng.warmup()
    sat_rps = estimate_saturation_rps(eng, prompt_rng, gen_rng,
                                      mcfg.vocab_size)
    rate = float(cfg.get("overload_factor", 2.0)) * sat_rps

    def workload():
        return make_open_loop_workload(n_req, rate, prompt_rng, gen_rng,
                                       mcfg.vocab_size,
                                       seed=int(cfg.get("seed", 5)))

    def sched(controlled: bool) -> ContinuousBatchingScheduler:
        kw = {}
        if controlled:
            kw = dict(max_queue=slots,
                      max_queued_tokens=eng.hbm_token_slots(),
                      ttft_deadline_s=slo_s / 2, deadline_s=slo_s)
        return ContinuousBatchingScheduler(
            executor=eng, num_slots=eng.num_slots, num_pages=eng.num_pages,
            page_size=page_size, pages_per_seq=eng.serving.pages_per_seq,
            decode_block=eng.serving.decode_block, max_context=max_len, **kw)

    wall = float(cfg.get("max_wall_s", 120.0))
    on = run_continuous(eng, workload(), max_wall_s=wall, slo_s=slo_s,
                        scheduler=sched(True))
    off = run_continuous(eng, workload(), max_wall_s=wall, slo_s=slo_s,
                         scheduler=sched(False))
    return {
        "config": cfg["name"], "kind": "serving_overload",
        "platform": platform, "model": cfg["model"], "num_slots": slots,
        "saturation_rps": round(sat_rps, 3), "rate_rps": round(rate, 3),
        "slo_s": slo_s, "requests": n_req,
        "goodput_tokens_per_sec": on["goodput_tokens_per_sec"],
        "shed_rate": on["shed_rate"],
        "deadline_miss_rate": on["deadline_miss_rate"],
        "accepted_ttft_p99_ms": on["ttft_p99_ms"],
        "pool_audit_ok": on["pool_audit_ok"] and off["pool_audit_ok"],
        "uncontrolled_goodput_tokens_per_sec":
            off["goodput_tokens_per_sec"],
        "uncontrolled_ttft_p99_ms": off["ttft_p99_ms"],
        "uncontrolled_deadline_miss_rate": off["deadline_miss_rate"],
        "controlled": on, "uncontrolled": off,
    }


def _worker_serving_tiered(cfg: dict) -> dict:
    """Multi-tenant SLO-tier A/B at 2x saturation (docs/SERVING.md
    "Multi-tenancy & SLO tiers"): a 3-tier mixed-tenant Poisson stream
    (one tenant per tier) driven through (a) a TIERED scheduler — WFQ
    virtual-time ordering, per-tier admission partitions, the brownout
    degradation ladder, tier-aware preemption — and (b) the same
    scheduler untiered (FIFO, tier-blind shed). The overload stream is
    batch-heavy (default shares 15/25/60) — the noisy-neighbor shape:
    a tenant whose OWN demand saturates the box is not a neighbor
    problem, so the protected tier must be light relative to capacity
    for "protect interactive" to be a scheduling claim rather than a
    physics violation. A light-load (0.5x saturation, even shares)
    tiered run calibrates the unloaded interactive TTFT floor the
    overloaded run is judged against. The row shows what the tier
    table buys: interactive p99 TTFT pinned near its light-load value
    (WFQ ordering + latency preemption of batch slots) while the batch
    tier absorbs the shed, versus an untiered baseline that sheds and
    queues tier-blind. Greedy agreement between
    the tiered and untiered runs is compared over the COMMON generated
    prefix (the ladder's clamp_batch stage may shorten a batch
    request's budget; prioritization must never change the tokens
    themselves). Batch bounded-wait is asserted structurally: every
    batch request reaches a terminal state — finished, typed shed, or
    typed expiry — never a silent starve."""
    import jax

    from deepspeed_tpu.inference.serving import (BrownoutConfig,
                                                 ContinuousBatchingScheduler,
                                                 RequestState, ServingConfig,
                                                 ServingEngine,
                                                 estimate_saturation_rps,
                                                 make_tiered_workload,
                                                 resolve_tiers,
                                                 run_continuous)
    from deepspeed_tpu.models import gpt as gpt_mod

    platform = jax.devices()[0].platform
    mcfg = gpt_mod.PRESETS[cfg["model"]]
    params = gpt_mod.init_params(mcfg, jax.random.PRNGKey(0))
    slots = int(cfg.get("slots", 4))
    page_size = int(cfg.get("page_size", 16))
    max_len = int(cfg.get("max_model_len", 96))
    prompt_rng = tuple(cfg.get("prompt_range", (8, 24)))
    gen_rng = tuple(cfg.get("gen_range", (8, 24)))
    n_per_tier = int(cfg.get("requests_per_tier", 8))
    slo_s = float(cfg.get("slo_s", 3.0))
    seed = int(cfg.get("seed", 5))
    wall = float(cfg.get("max_wall_s", 120.0))

    eng = ServingEngine(mcfg, params, ServingConfig(
        num_slots=slots, page_size=page_size, max_model_len=max_len,
        prefill_chunk=int(cfg.get("prefill_chunk", 32)),
        dtype=cfg.get("dtype", "float32"),
        decode_block=int(cfg.get("decode_block", 4))))
    eng.warmup()
    sat = estimate_saturation_rps(eng, prompt_rng, gen_rng, mcfg.vocab_size)
    rate = float(cfg.get("overload_factor", 2.0)) * sat

    # tier policy: deadlines track the evaluation SLO (interactive must
    # answer inside it, standard gets slack, batch has none and rides the
    # backlog); the batch admission partition is shallow so overflow is
    # absorbed there — by policy, not by arrival luck; reserved interactive
    # slots make the protected tier's TTFT load-independent (dispatch
    # shapes are padded, so service time is constant — slot wait was the
    # only load-dependent term)
    tiers = resolve_tiers(cfg.get("tiers") or {
        "interactive": {"ttft_deadline_s": slo_s / 2,
                        "deadline_s": 4 * slo_s,
                        "reserved_slots": max(1, slots // 8)},
        "standard": {"ttft_deadline_s": 2 * slo_s,
                     "deadline_s": 8 * slo_s},
        "batch": {"max_queue": max(2, slots // 2)},
    })

    def sched(tiered: bool) -> ContinuousBatchingScheduler:
        kw = dict(max_queue=4 * slots,
                  max_queued_tokens=eng.hbm_token_slots())
        if tiered:
            kw.update(tiers=tiers,
                      brownout=BrownoutConfig(
                          window_s=float(cfg.get("brownout_window_s", 5.0)),
                          min_dwell_s=float(cfg.get("brownout_dwell_s",
                                                    0.5))))
        return ContinuousBatchingScheduler(
            executor=eng, num_slots=eng.num_slots, num_pages=eng.num_pages,
            page_size=page_size, pages_per_seq=eng.serving.pages_per_seq,
            decode_block=eng.serving.decode_block, max_context=max_len, **kw)

    shares = cfg.get("tier_shares") or {"interactive": 0.15,
                                        "standard": 0.25, "batch": 0.6}

    def workload(rps: float, shaped: bool = True):
        return make_tiered_workload(n_per_tier, rps, prompt_rng, gen_rng,
                                    mcfg.vocab_size, seed=seed,
                                    shares=shares if shaped else None)

    # the unloaded interactive-TTFT floor: the SAME tier policy at half
    # saturation, even shares (nothing sheds, nothing queues long)
    light = run_continuous(eng, workload(0.5 * sat, shaped=False),
                           max_wall_s=wall,
                           slo_s=slo_s, scheduler=sched(True))
    wl_on, wl_off = workload(rate), workload(rate)
    on_sched = sched(True)
    on = run_continuous(eng, wl_on, max_wall_s=wall, slo_s=slo_s,
                        scheduler=on_sched)
    off = run_continuous(eng, wl_off, max_wall_s=wall, slo_s=slo_s,
                         scheduler=sched(False))

    # bounded wait: every batch request terminal (finished / typed shed /
    # typed expiry) — the ladder may delay or shed batch, never strand it
    batch_on = [r for r in wl_on if r.tier == "batch"]
    stranded = [r.rid for r in batch_on
                if r.t_done is None
                and r.state not in (RequestState.REJECTED,
                                    RequestState.EXPIRED)]
    assert not stranded, f"batch requests stranded: {stranded}"

    # greedy agreement over the common prefix, tiered vs untiered (same
    # seeded workload; pairs where both sides produced tokens)
    pairs = [(a, b) for a, b in zip(wl_on, wl_off)
             if a.t_done is not None and b.t_done is not None]
    match = 0
    for a, b in pairs:
        ta, tb = a.tokens[:a.max_new_tokens], b.tokens[:b.max_new_tokens]
        n = min(len(ta), len(tb))
        match += ta[:n] == tb[:n]

    on_int = (on.get("by_tier") or {}).get("interactive") or {}
    light_int = (light.get("by_tier") or {}).get("interactive") or {}
    on_batch = (on.get("by_tier") or {}).get("batch") or {}
    off_int = (off.get("by_tier") or {}).get("interactive") or {}
    light_p99 = light_int.get("ttft_p99_ms") or float("nan")
    on_p99 = on_int.get("ttft_p99_ms") or float("nan")
    batch_shed_share = (on_batch.get("shed", 0) / on["shed"]
                        if on.get("shed") else None)
    return {
        "config": cfg["name"], "kind": "serving_tiered",
        "platform": platform, "model": cfg["model"], "num_slots": slots,
        "saturation_rps": round(sat, 3), "rate_rps": round(rate, 3),
        "slo_s": slo_s, "requests": 3 * n_per_tier,
        "tiers": sorted(tiers), "tier_shares": shares,
        "interactive_reserved_slots": tiers["interactive"].reserved_slots,
        # the headline: interactive under 2x overload vs its unloaded self
        "interactive_ttft_p99_ms": on_p99,
        "light_load_interactive_ttft_p99_ms": light_p99,
        "interactive_ttft_inflation": (round(on_p99 / light_p99, 3)
                                       if light_p99 == light_p99
                                       and light_p99 else None),
        "interactive_ttft_within_15pct": bool(on_p99 <= 1.15 * light_p99)
        if on_p99 == on_p99 and light_p99 == light_p99 else None,
        "interactive_miss_rate": on_int.get("deadline_miss_rate"),
        # who absorbed the overload
        "shed": on["shed"], "batch_shed": on_batch.get("shed"),
        "batch_shed_share": (round(batch_shed_share, 4)
                             if batch_shed_share is not None else None),
        "batch_finished": on_batch.get("finished"),
        "batch_preemptions": on_batch.get("preemptions"),
        "batch_stranded": 0,
        "brownout_transitions": on_sched.counters.get("tier_brownout", 0),
        "goodput_tokens_per_sec": on["goodput_tokens_per_sec"],
        "pool_audit_ok": on["pool_audit_ok"] and off["pool_audit_ok"]
        and light["pool_audit_ok"],
        # the tier-blind baseline on the same stream
        "untiered_interactive_ttft_p99_ms": off_int.get("ttft_p99_ms"),
        "untiered_interactive_miss_rate": off_int.get("deadline_miss_rate"),
        "untiered_shed": off["shed"],
        "untiered_goodput_tokens_per_sec": off["goodput_tokens_per_sec"],
        "greedy_match_rate": round(match / max(len(pairs), 1), 4),
        "greedy_pairs_compared": len(pairs),
        "tiered": on, "untiered": off, "light_load": light,
    }


def _worker_serving_lever(cfg: dict) -> dict:
    """A/B one serving-capacity lever on the SAME 2x-saturation Poisson
    workload (docs/SERVING.md "KV quantization & prefix caching"):

    - ``lever="kv8"`` — dense vs int8 KV pools at EQUAL HBM BYTES: the
      quantized pool re-divides the same byte budget into ~2x (fp32: 4x)
      the pages AND the decode slot count scales with it — the same
      KV-bytes-bound sizing the AOT fit ladder applies on a real chip
      (``serving_admission_limit(kv_bits=8)``), emulated here because CPU
      slots are not genuinely HBM-bound. More resident tokens + more slots
      = less queueing at saturation = higher goodput. Greedy agreement
      with the dense run is reported (the documented quantization
      tolerance: per-page int8 can flip rare near-tie argmaxes).
    - ``lever="prefix"`` — copy-on-write shared-prefix caching OFF vs ON on
      a chat-style workload (every request opens with the same
      ``prefix_len``-token system prompt): physical pages < logical pages,
      byte-identical outputs.
    - ``lever="spec"`` — speculative decoding OFF vs ON (n-gram
      self-drafting, adaptive k) at equal slots/pages. The row runs both
      sides at ``decode_block=1``: on CPU both the scan block and
      speculation amortize the same per-dispatch overhead, so the A/B
      isolates the speculation lever itself — the regime that stands in
      for the TPU's weight-bound decode, where a k+1-token verify reads
      the weights once and the block scan k+1 times (that orthogonal win
      is the TPU flagship row's). Reports ``accept_rate`` and
      ``tokens_per_dispatch`` next to the goodput/TTFT deltas, with
      greedy_match_rate as the equivalence gate (the verify fallback is
      bit-identical per position to sequential decode on dense pools, so
      the gate is expected at exactly 1.0).

    All variants report max-slots/pool pages, tokens/s + goodput, TTFT
    p50/p99, and the physical-vs-logical page ratio."""
    import numpy as np

    import jax

    from deepspeed_tpu.inference.serving import (Request, ServingConfig,
                                                 ServingEngine,
                                                 estimate_saturation_rps,
                                                 make_open_loop_workload,
                                                 run_continuous)
    from deepspeed_tpu.models import gpt as gpt_mod

    platform = jax.devices()[0].platform
    lever = cfg.get("lever", "kv8")
    mcfg = gpt_mod.PRESETS[cfg["model"]]
    params = gpt_mod.init_params(mcfg, jax.random.PRNGKey(0))
    slots = int(cfg.get("slots", 4))
    page_size = int(cfg.get("page_size", 16))
    max_len = int(cfg.get("max_model_len", 96))
    prompt_rng = tuple(cfg.get("prompt_range", (8, 24)))
    gen_rng = tuple(cfg.get("gen_range", (8, 24)))
    n_req = int(cfg.get("requests", 16))
    slo_s = float(cfg.get("slo_s", 3.0))
    dtype = cfg.get("dtype", "float32")
    prefix_len = int(cfg.get("prefix_len", 2 * page_size))
    # pool overcommitted (half of every-slot-maxes-out) so capacity actually
    # binds at 2x saturation — the regime the levers exist for
    base_kw = dict(page_size=page_size, max_model_len=max_len,
                   prefill_chunk=int(cfg.get("prefill_chunk", 32)),
                   dtype=dtype, max_queue=8 * slots,
                   request_deadline_s=slo_s,
                   decode_block=int(cfg.get("decode_block", 4)))
    pages_per_seq = -(-max_len // page_size)
    dense_pages = int(cfg.get("pool_pages",
                              max(pages_per_seq + 1,
                                  slots * pages_per_seq // 2)))

    def build(kv_bits=None, prefix=False, pages=dense_pages,
              num_slots=slots, spec=False):
        eng = ServingEngine(mcfg, params, ServingConfig(
            num_slots=num_slots, num_pages=pages + 1, kv_bits=kv_bits,
            enable_prefix_cache=prefix,
            spec_drafter=("ngram" if spec else None),
            spec_k=int(cfg.get("spec_k", 4)),
            spec_equivalence_harness=spec,  # this row IS the harness: it
            # reports greedy_match_rate against the spec-off side
            **base_kw))
        eng.warmup()
        return eng

    base_eng = build()
    sat = estimate_saturation_rps(base_eng, prompt_rng, gen_rng,
                                  mcfg.vocab_size)
    rate = float(cfg.get("overload_factor", 2.0)) * sat
    seed = int(cfg.get("seed", 5))

    def workload():
        wl = make_open_loop_workload(n_req, rate, prompt_rng, gen_rng,
                                     mcfg.vocab_size, seed=seed)
        if lever == "prefix":
            sysp = (np.arange(prefix_len, dtype=np.int32) * 7 + 3) \
                % mcfg.vocab_size
            wl = [Request(prompt=np.concatenate([sysp, r.prompt]),
                          max_new_tokens=r.max_new_tokens,
                          arrival_time=r.arrival_time) for r in wl]
        return wl

    wall = float(cfg.get("max_wall_s", 120.0))
    if lever == "kv8":
        # equal HBM BYTES: the int8 pool holds budget // bytes-per-page
        # pages (int8 payload + fp32 per-page scales), and the decode slot
        # count scales with the pool — the KV-bytes-bound sizing the AOT
        # fit ladder (serving_admission_limit(kv_bits=8)) applies on chip
        budget = dense_pages * page_size * base_eng.kv_bytes_per_token()
        q_per_tok = gpt_mod.paged_kv_bytes_per_token(mcfg, 8, page_size)
        q_pages = max(pages_per_seq + 1, int(budget
                                             // (page_size * q_per_tok)))
        q_slots = max(slots + 1, q_pages * slots // dense_pages)
        lever_eng = build(kv_bits=8, pages=q_pages, num_slots=q_slots)
    elif lever == "spec":
        # equal slots, equal pages: the ONLY difference is the drafter
        lever_eng = build(spec=True)
    else:
        lever_eng = build(prefix=True)
    wl_base, wl_lever = workload(), workload()
    base = run_continuous(base_eng, wl_base, max_wall_s=wall, slo_s=slo_s)
    lever_rep = run_continuous(lever_eng, wl_lever, max_wall_s=wall,
                               slo_s=slo_s)

    # greedy agreement request-by-request (both runs replay the same seeded
    # workload; requests unfinished on either side are skipped). Exact
    # per-request match is the strict bar; the mean common-prefix fraction
    # separates "rare near-tie argmax flip, then a diverged tail" from
    # genuinely different behavior (one early flip cascades the sequence)
    pairs = [(a, b) for a, b in zip(wl_base, wl_lever)
             if a.t_done is not None and b.t_done is not None]
    match = sum(a.tokens[:a.max_new_tokens] == b.tokens[:b.max_new_tokens]
                for a, b in pairs)
    prefix_agree = []
    for a, b in pairs:
        ta, tb = a.tokens[:a.max_new_tokens], b.tokens[:b.max_new_tokens]
        n = min(len(ta), len(tb))
        same = next((i for i in range(n) if ta[i] != tb[i]), n)
        prefix_agree.append(same / max(n, 1))

    spec_rep = lever_rep.get("spec") or {}
    return {
        "config": cfg["name"], "kind": "serving_lever", "lever": lever,
        "accept_rate": spec_rep.get("accept_rate"),
        "tokens_per_dispatch": spec_rep.get("tokens_per_dispatch"),
        "drafter": spec_rep.get("drafter"),
        "platform": platform, "model": cfg["model"],
        "num_slots": slots, "lever_num_slots": lever_eng.num_slots,
        "saturation_rps": round(sat, 3),
        "rate_rps": round(rate, 3), "slo_s": slo_s, "requests": n_req,
        "dense_pool_pages": dense_pages,
        "lever_pool_pages": lever_eng.num_pages - 1,
        "hbm_bytes_per_token_dense": round(base_eng.kv_bytes_per_token()),
        "hbm_bytes_per_token_lever": round(lever_eng.kv_bytes_per_token()),
        "tokens_per_sec": lever_rep["tokens_per_sec"],
        "goodput_tokens_per_sec": lever_rep["goodput_tokens_per_sec"],
        "ttft_p50_ms": lever_rep["ttft_p50_ms"],
        "ttft_p99_ms": lever_rep["ttft_p99_ms"],
        "physical_logical_page_ratio":
            lever_rep["physical_logical_page_ratio"],
        "preemptions": lever_rep["preemptions"],
        "baseline_tokens_per_sec": base["tokens_per_sec"],
        "baseline_goodput_tokens_per_sec": base["goodput_tokens_per_sec"],
        "baseline_ttft_p50_ms": base["ttft_p50_ms"],
        "baseline_ttft_p99_ms": base["ttft_p99_ms"],
        "baseline_preemptions": base["preemptions"],
        "pool_audit_ok": base["pool_audit_ok"] and lever_rep["pool_audit_ok"],
        "greedy_match_rate": round(match / max(len(pairs), 1), 4),
        "greedy_token_prefix_agreement": round(
            float(np.mean(prefix_agree)) if prefix_agree else 1.0, 4),
        "greedy_pairs_compared": len(pairs),
        "lever_run": lever_rep, "baseline_run": base,
    }


def _worker_serving_fleet(cfg: dict) -> dict:
    """Fleet overload A/B at 2x saturation (docs/SERVING.md "Fleet"):
    ``replicas`` router-fronted replica WORKER PROCESSES of ``slots``
    slots each versus ONE engine with the same total slots, pool pages,
    and admission bounds, on the same 2x-calibrated-saturation Poisson
    workload scored against one SLO. Each replica owns its compute (a
    process here, a chip allocation in production), and the router's
    two-phase pump runs their steps concurrently — so one replica's
    prefill never stalls another's decode, where the single engine
    serializes every prefill against all of its running slots. The chaos
    variant replays the same workload and SIGKILLs one replica
    mid-stream: the row reports survivor page audits, re-route counts,
    and the greedy match rate of surviving requests against the
    fault-free fleet run. ``replica_env`` ({name: value-with-{i}}) pins
    per-replica devices on multi-chip hosts."""
    import dataclasses as _dc
    from concurrent.futures import ThreadPoolExecutor

    import jax

    from deepspeed_tpu.inference.fleet import (FleetConfig, ReplicaRouter,
                                               SubprocessReplica, run_fleet)
    from deepspeed_tpu.inference.serving import (ServingConfig, ServingEngine,
                                                 estimate_saturation_rps,
                                                 make_open_loop_workload,
                                                 run_continuous)
    from deepspeed_tpu.models import gpt as gpt_mod

    platform = jax.devices()[0].platform
    mcfg = gpt_mod.PRESETS[cfg["model"]]
    params = gpt_mod.init_params(mcfg, jax.random.PRNGKey(0))
    n_rep = int(cfg.get("replicas", 2))
    slots = int(cfg.get("slots", 2))          # per replica
    page_size = int(cfg.get("page_size", 16))
    max_len = int(cfg.get("max_model_len", 96))
    prompt_rng = tuple(cfg.get("prompt_range", (8, 32)))
    gen_rng = tuple(cfg.get("gen_range", (8, 24)))
    n_req = int(cfg.get("requests", 24))
    slo_s = float(cfg.get("slo_s", 3.0))
    dtype = cfg.get("dtype", "float32")
    pages_per_seq = -(-max_len // page_size)
    # per-replica pool, overcommitted so capacity binds at 2x saturation
    pool = int(cfg.get("pool_pages",
                       max(pages_per_seq + 1, slots * pages_per_seq // 2)))

    def serving_kw(num_slots, pages):
        # queues deep enough that the TTFT deadline — not the depth cap —
        # is the binding overload control: the A/B compares deadline
        # behavior, and a shallow cap would shed everything first
        return dict(
            num_slots=num_slots, num_pages=pages + 1, page_size=page_size,
            max_model_len=max_len,
            prefill_chunk=int(cfg.get("prefill_chunk", 32)), dtype=dtype,
            max_queue=int(cfg.get("queue_per_slot", 4)) * num_slots,
            ttft_deadline_s=slo_s / 2, request_deadline_s=slo_s)

    def build_engine(num_slots, pages):
        eng = ServingEngine(mcfg, params,
                            ServingConfig(**serving_kw(num_slots, pages)))
        eng.warmup()
        return eng

    model_dict = _dc.asdict(mcfg)

    def spawn(i):
        env = {k: str(v).format(i=i)
               for k, v in (cfg.get("replica_env") or {}).items()}
        return SubprocessReplica(f"r{i}", model_dict,
                                 serving_kw(slots, pool), seed=0,
                                 env=env or None)

    def build_fleet():
        # spawn concurrently: each ctor blocks on its worker's warmup
        with ThreadPoolExecutor(n_rep) as ex:
            reps = list(ex.map(spawn, range(n_rep)))
        return ReplicaRouter(reps, FleetConfig(
            reroute_budget=2, heartbeat_deadline_s=120.0))

    # equal-resources baseline: one scheduler over ALL the slots and pages
    single_eng = build_engine(n_rep * slots, n_rep * pool)
    sat = estimate_saturation_rps(single_eng, prompt_rng, gen_rng,
                                  mcfg.vocab_size)
    rate = float(cfg.get("overload_factor", 2.0)) * sat
    seed = int(cfg.get("seed", 5))

    def workload():
        return make_open_loop_workload(n_req, rate, prompt_rng, gen_rng,
                                       mcfg.vocab_size, seed=seed)

    wall = float(cfg.get("max_wall_s", 120.0))
    wl_single = workload()
    single = run_continuous(single_eng, wl_single, max_wall_s=wall,
                            slo_s=slo_s)

    router = build_fleet()
    wl_fleet = workload()
    fleet = run_fleet(router, wl_fleet, max_wall_s=wall, slo_s=slo_s)
    router.close()

    # chaos variant: identical workload, one replica killed mid-stream
    chaos_router = build_fleet()
    wl_chaos = workload()
    killed = {"done": False}
    kill_after = int(cfg.get("kill_after_tokens", 40))

    def on_step(rt, produced_total):
        if not killed["done"] and produced_total >= kill_after:
            victim = rt.replica("r0")
            if victim is not None and victim.alive:
                victim.kill()
                killed["done"] = True

    chaos = run_fleet(chaos_router, wl_chaos, max_wall_s=wall, slo_s=slo_s,
                      on_step=on_step)
    chaos_audit = chaos_router.audit_survivors()
    chaos_drained = all(r["allocated"] == 0
                        for r in chaos_audit["replicas"].values())
    chaos_router.close()
    # surviving (finished in both the fault-free fleet run and the
    # killed-replica run) requests must be greedy-IDENTICAL: failover is
    # recompute, not approximation
    pairs = [(a, b) for a, b in zip(wl_fleet, wl_chaos)
             if a.t_done is not None and b.t_done is not None]
    match = sum(a.tokens[:a.max_new_tokens] == b.tokens[:b.max_new_tokens]
                for a, b in pairs)

    return {
        "config": cfg["name"], "kind": "serving_fleet",
        "platform": platform, "model": cfg["model"],
        "replicas": n_rep, "slots_per_replica": slots,
        "total_slots": n_rep * slots, "pool_pages_per_replica": pool,
        "saturation_rps": round(sat, 3), "rate_rps": round(rate, 3),
        "slo_s": slo_s, "requests": n_req,
        "goodput_tokens_per_sec": fleet["goodput_tokens_per_sec"],
        "deadline_miss_rate": fleet["deadline_miss_rate"],
        "ttft_p50_ms": fleet["ttft_p50_ms"],
        "ttft_p99_ms": fleet["ttft_p99_ms"],
        "shed_rate": fleet["shed_rate"],
        "single_goodput_tokens_per_sec": single["goodput_tokens_per_sec"],
        "single_deadline_miss_rate": single["deadline_miss_rate"],
        "single_ttft_p50_ms": single["ttft_p50_ms"],
        "single_ttft_p99_ms": single["ttft_p99_ms"],
        "single_shed_rate": single["shed_rate"],
        "fleet_beats_single_goodput":
            fleet["goodput_tokens_per_sec"]
            > single["goodput_tokens_per_sec"],
        "fleet_beats_single_miss_rate":
            fleet["deadline_miss_rate"] < single["deadline_miss_rate"],
        "fleet_audit_ok": fleet["fleet_audit_ok"],
        # chaos: replica r0 killed mid-stream
        "chaos_killed": killed["done"],
        "chaos_reroutes": chaos["reroutes"],
        "chaos_survivor_audit_ok": bool(chaos_audit["ok"]),
        "chaos_survivor_pools_drained": bool(chaos_drained),
        "chaos_goodput_tokens_per_sec": chaos["goodput_tokens_per_sec"],
        "greedy_match_rate": round(match / max(len(pairs), 1), 4),
        "greedy_pairs_compared": len(pairs),
        "fleet_run": fleet, "single_run": single, "chaos_run": chaos,
    }


def _worker_serving_disagg(cfg: dict) -> dict:
    """Disaggregated prefill/decode A/B at 2x saturation (docs/SERVING.md
    "Tensor parallel & disaggregation"): a prefill-specialist replica
    fills KV pages and hands each request off to a decode-specialist
    over the subprocess wire, versus a COLOCATED fleet (same replica
    count, role="both") at equal TOTAL slots and pool pages on the same
    2x-calibrated-saturation prefill-heavy workload. Handoff is
    ownership transfer — the prefill worker exports the request's pages
    (quantized pages + per-page scales when kv_bits is set, so the wire
    payload shrinks with the pool) and frees them only after the decode
    side imports. Disaggregation also unlocks PER-ROLE sizing inside the
    fixed budget: the prefill specialist runs few slots and a small pool
    (pages live there only until handoff), the decode specialist takes
    the rest. The chaos variant replays the workload and SIGKILLs the
    prefill replica mid-stream: in-flight handoffs are orphaned, victims
    re-route through the role-fallback path (the decode survivor
    re-prefills them), and the row reports survivor audits + drained
    pools — zero page leaks. ``replica_env`` ({name: value-with-{i}})
    pins per-replica devices; ``tp`` shards each replica over chips."""
    import dataclasses as _dc
    from concurrent.futures import ThreadPoolExecutor

    import jax

    from deepspeed_tpu.inference.fleet import (FleetConfig, ReplicaRouter,
                                               SubprocessReplica, run_fleet)
    from deepspeed_tpu.inference.serving import (ServingConfig, ServingEngine,
                                                 estimate_saturation_rps,
                                                 make_open_loop_workload)
    from deepspeed_tpu.models import gpt as gpt_mod

    platform = jax.devices()[0].platform
    mcfg = gpt_mod.PRESETS[cfg["model"]]
    params = gpt_mod.init_params(mcfg, jax.random.PRNGKey(0))
    slots = int(cfg.get("slots", 2))          # per colocated replica
    page_size = int(cfg.get("page_size", 16))
    max_len = int(cfg.get("max_model_len", 96))
    prompt_rng = tuple(cfg.get("prompt_range", (64, 112)))
    gen_rng = tuple(cfg.get("gen_range", (4, 8)))
    n_req = int(cfg.get("requests", 24))
    slo_s = float(cfg.get("slo_s", 4.0))
    dtype = cfg.get("dtype", "float32")
    kv_bits = cfg.get("kv_bits")
    tp = int(cfg.get("tp", 1))
    pages_per_seq = -(-max_len // page_size)
    pool = int(cfg.get("pool_pages",
                       max(pages_per_seq + 1, slots * pages_per_seq // 2)))
    # per-role split of the SAME total budget (2*slots, 2*pool). Equal by
    # default: the prefill side holds each request only until handoff,
    # but a staged handoff keeps BOTH its slot and its pages parked until
    # the router forwards it (export-before-free), so starving the
    # prefill replica of either serializes admissions. The knobs let a
    # row skew the split where the roles' residencies actually differ.
    p_slots = int(cfg.get("prefill_slots", slots))
    d_slots = 2 * slots - p_slots
    p_pool = int(cfg.get("prefill_pool", pool))
    d_pool = 2 * pool - p_pool

    def serving_kw(num_slots, pages, role="both"):
        # queue depth = admission control, the binding overload lever at
        # 2x saturation: per-replica front doors on both sides so the
        # excess sheds early and accepted requests stay inside the SLO.
        # The one exception is the decode specialist: its queue is NOT an
        # admission door — the router only forwards staged handoffs
        # there, and a refusal costs a re-prefill fallback on the
        # bottleneck prefill replica — so it gets system depth and must
        # never refuse.
        qps = int(cfg.get("queue_per_slot", 4))
        kw = dict(
            num_slots=num_slots, num_pages=pages + 1, page_size=page_size,
            max_model_len=max_len,
            max_queue=qps * (2 * slots if role == "decode" else num_slots),
            prefill_chunk=int(cfg.get("prefill_chunk", 32)), dtype=dtype,
            ttft_deadline_s=slo_s / 2, request_deadline_s=slo_s, role=role)
        if kv_bits:
            kw["kv_bits"] = int(kv_bits)
        if tp > 1:
            kw["tp"] = tp
        return kw

    model_dict = _dc.asdict(mcfg)

    def spawn(i, role, num_slots, pages):
        env = {k: str(v).format(i=i)
               for k, v in (cfg.get("replica_env") or {}).items()}
        return SubprocessReplica(f"{role[0]}{i}", model_dict,
                                 serving_kw(num_slots, pages, role), seed=0,
                                 env=env or None)

    def build_fleet(specs):
        with ThreadPoolExecutor(len(specs)) as ex:
            reps = list(ex.map(lambda s: spawn(*s), specs))
        return ReplicaRouter(reps, FleetConfig(
            reroute_budget=2, heartbeat_deadline_s=120.0))

    coloc_specs = [(0, "both", slots, pool), (1, "both", slots, pool)]
    disagg_specs = [(0, "prefill", p_slots, p_pool),
                    (1, "decode", d_slots, d_pool)]

    # calibrate saturation once on an equal-total-resources local engine
    cal = ServingEngine(mcfg, params,
                        ServingConfig(**serving_kw(2 * slots, 2 * pool)))
    cal.warmup()
    sat = estimate_saturation_rps(cal, prompt_rng, gen_rng, mcfg.vocab_size)
    del cal
    rate = float(cfg.get("overload_factor", 2.0)) * sat
    seed = int(cfg.get("seed", 5))

    def workload():
        return make_open_loop_workload(n_req, rate, prompt_rng, gen_rng,
                                       mcfg.vocab_size, seed=seed)

    wall = float(cfg.get("max_wall_s", 120.0))

    coloc_router = build_fleet(coloc_specs)
    wl_coloc = workload()
    coloc = run_fleet(coloc_router, wl_coloc, max_wall_s=wall, slo_s=slo_s)
    coloc_router.close()

    disagg_router = build_fleet(disagg_specs)
    wl_disagg = workload()
    disagg = run_fleet(disagg_router, wl_disagg, max_wall_s=wall, slo_s=slo_s)
    disagg_router.close()

    # chaos variant: identical workload, prefill specialist SIGKILLed
    # mid-stream — orphaned handoffs and queued victims must re-route to
    # the decode survivor through role fallback, with no leaked pages
    chaos_router = build_fleet(disagg_specs)
    wl_chaos = workload()
    killed = {"done": False}
    kill_after = int(cfg.get("kill_after_tokens", 8))

    def on_step(rt, produced_total):
        if not killed["done"] and produced_total >= kill_after:
            victim = rt.replica("p0")
            if victim is not None and victim.alive:
                victim.kill()
                killed["done"] = True

    chaos = run_fleet(chaos_router, wl_chaos, max_wall_s=wall, slo_s=slo_s,
                      on_step=on_step)
    chaos_audit = chaos_router.audit_survivors()
    chaos_drained = all(r["allocated"] == 0
                        for r in chaos_audit["replicas"].values())
    chaos_router.close()
    # surviving requests (finished in both the fault-free disagg run and
    # the killed-prefill run) must be greedy-IDENTICAL: failover is
    # re-prefill of the kept tokens, not approximation
    pairs = [(a, b) for a, b in zip(wl_disagg, wl_chaos)
             if a.t_done is not None and b.t_done is not None]
    match = sum(a.tokens[:a.max_new_tokens] == b.tokens[:b.max_new_tokens]
                for a, b in pairs)

    return {
        "config": cfg["name"], "kind": "serving_disagg",
        "platform": platform, "model": cfg["model"],
        "tp": tp, "kv_bits": kv_bits,
        "total_slots": 2 * slots, "total_pool_pages": 2 * pool,
        "prefill_slots": p_slots, "decode_slots": d_slots,
        "prefill_pool_pages": p_pool, "decode_pool_pages": d_pool,
        "saturation_rps": round(sat, 3), "rate_rps": round(rate, 3),
        "slo_s": slo_s, "requests": n_req,
        "handoffs_forwarded":
            disagg["fleet_counters"].get("handoff_forwarded", 0),
        "handoff_fallbacks":
            disagg["fleet_counters"].get("handoff_fallback", 0),
        "goodput_tokens_per_sec": disagg["goodput_tokens_per_sec"],
        "deadline_miss_rate": disagg["deadline_miss_rate"],
        "ttft_p50_ms": disagg["ttft_p50_ms"],
        "ttft_p99_ms": disagg["ttft_p99_ms"],
        "shed_rate": disagg["shed_rate"],
        "colocated_goodput_tokens_per_sec": coloc["goodput_tokens_per_sec"],
        "colocated_deadline_miss_rate": coloc["deadline_miss_rate"],
        "colocated_ttft_p50_ms": coloc["ttft_p50_ms"],
        "colocated_ttft_p99_ms": coloc["ttft_p99_ms"],
        "colocated_shed_rate": coloc["shed_rate"],
        "disagg_beats_colocated_goodput":
            disagg["goodput_tokens_per_sec"]
            >= coloc["goodput_tokens_per_sec"],
        "disagg_beats_colocated_ttft_p99":
            disagg["ttft_p99_ms"] < coloc["ttft_p99_ms"],
        "disagg_audit_ok": disagg["fleet_audit_ok"],
        "colocated_audit_ok": coloc["fleet_audit_ok"],
        # chaos: prefill specialist p0 killed mid-stream
        "chaos_killed": killed["done"],
        "chaos_reroutes": chaos["reroutes"],
        "chaos_orphaned_handoffs":
            chaos["fleet_counters"].get("handoff_fallback", 0),
        "chaos_survivor_audit_ok": bool(chaos_audit["ok"]),
        "chaos_survivor_pools_drained": bool(chaos_drained),
        "chaos_goodput_tokens_per_sec": chaos["goodput_tokens_per_sec"],
        "greedy_match_rate": round(match / max(len(pairs), 1), 4),
        "greedy_pairs_compared": len(pairs),
        "disagg_run": disagg, "colocated_run": coloc, "chaos_run": chaos,
    }


def _worker_diffusion(cfg: dict) -> dict:
    """Stable-Diffusion latent inference (BASELINE.json config #5) on the
    FAITHFUL SD-1.x architecture (CrossAttn UNet + AutoencoderKL decoder):
    full DDIM scan + CFG + VAE decode as one compiled program; reports
    per-image latency. ``arch: "skeleton"`` selects the lightweight model."""
    import numpy as np

    import jax

    platform = jax.devices()[0].platform
    if cfg.get("arch", "sd15") == "skeleton":
        from deepspeed_tpu.models.diffusion import (
            StableDiffusionPipeline, UNetConfig, VAEDecoderConfig)

        pipe = StableDiffusionPipeline.init_random(
            jax.random.PRNGKey(0),
            unet_cfg=UNetConfig(base_channels=cfg.get("base_channels", 128),
                                channel_mults=(1, 2, 4),
                                text_dim=cfg.get("text_dim", 256), n_head=8),
            vae_cfg=VAEDecoderConfig(base_channels=64, upsamples=3),
            latent_size=cfg.get("latent", 32))
        text_dim = pipe.unet_cfg.text_dim
    else:
        from deepspeed_tpu.models.sd_unet import (
            SDPipeline, SDUNetConfig, SDVAEDecoderConfig, init_sd_unet,
            init_sd_vae_decoder)

        chans = tuple(cfg.get("channels", (128, 256, 512)))
        groups = min(32, min(chans))
        ucfg = SDUNetConfig(
            block_out_channels=chans,
            cross_attn=tuple(i < len(chans) - 1 for i in range(len(chans))),
            cross_attention_dim=cfg.get("text_dim", 512), n_head=8,
            norm_groups=groups)
        vcfg = SDVAEDecoderConfig(
            block_out_channels=tuple(max(c // 2, groups) for c in chans),
            norm_groups=groups)
        k1, k2 = jax.random.split(jax.random.PRNGKey(0))
        pipe = SDPipeline(ucfg, vcfg, init_sd_unet(ucfg, k1),
                          init_sd_vae_decoder(vcfg, k2),
                          latent_size=cfg.get("latent", 32))
        text_dim = ucfg.cross_attention_dim
    rng = np.random.default_rng(0)
    B, S = cfg.get("batch", 1), 77
    text = np.asarray(rng.normal(size=(B, S, text_dim)), np.float32)
    uncond = np.asarray(rng.normal(size=(B, S, text_dim)), np.float32)
    steps = cfg.get("ddim_steps", 20)
    img = pipe(text, uncond, num_steps=steps)  # warmup/compile
    lat = []
    for i in range(cfg.get("reps", 3)):
        t0 = time.perf_counter()
        img = pipe(text, uncond, num_steps=steps, seed=i)
        lat.append((time.perf_counter() - t0) / B * 1e3)
    lat.sort()
    return {
        "config": cfg["name"], "kind": "diffusion", "platform": platform,
        "image_ms_p50": round(lat[len(lat) // 2], 1),
        "ddim_steps": steps, "batch": B,
        "image_px": int(img.shape[1]),
    }


def _worker_kernels_aot(cfg: dict) -> dict:
    """Mosaic-compile every Pallas kernel against the v5e TPU compiler on the
    host — the chip-session 'kernel smoke' without a chip. A kernel that
    fails HERE would fail on hardware (same compiler); green rows mean the
    first tunnel-up window spends zero time on compile regressions."""
    import numpy as np

    import jax
    import jax.numpy as jnp
    from jax.experimental import topologies
    from jax.sharding import NamedSharding, PartitionSpec as P

    from deepspeed_tpu.runtime.topology import MeshTopology, mesh_context

    os.environ["DS_TPU_PALLAS_INTERPRET"] = "0"
    td = topologies.get_topology_desc(
        platform="tpu", topology_name=cfg.get("topology", "v5e:2x2"))
    topo = MeshTopology.create(dp=1, devices=list(td.devices)[:1])
    rep = NamedSharding(topo.mesh, P())

    def a(shape, dtype=jnp.bfloat16):
        return jax.ShapeDtypeStruct(shape, dtype, sharding=rep)

    B, H, S, Dh = 4, 16, 1024, 64
    q4 = a((B, S, H, Dh))
    results, failed = {}, []

    def check(name, fn, *args):
        try:
            t0 = time.perf_counter()
            with mesh_context(topo.mesh):
                jax.jit(fn).lower(*args).compile()
            results[name] = {"ok": True,
                             "compile_s": round(time.perf_counter() - t0, 1)}
        except Exception as e:
            results[name] = {"ok": False, "error": str(e)[-300:]}
            failed.append(name)

    from deepspeed_tpu.ops.pallas.blocksparse_attention import (
        blocksparse_attention)
    from deepspeed_tpu.ops.pallas.decode_attention import decode_attention
    from deepspeed_tpu.ops.pallas.flash_attention import flash_attention
    from deepspeed_tpu.ops.sparse_attention import FixedSparsityConfig

    check("flash_attention",
          lambda q, k, v: flash_attention(q, k, v, causal=True), q4, q4, q4)
    check("flash_attention_bwd",
          jax.grad(lambda q, k, v: flash_attention(q, k, v, causal=True)
                   .astype(jnp.float32).sum()), q4, q4, q4)
    check("flash_attention_stochastic",
          lambda q, k, v: flash_attention(q, k, v, causal=True,
                                          stochastic_mode=True), q4, q4, q4)
    check("decode_attention",
          lambda q, k, v, n: decode_attention(q, k, v, n),
          a((B, 1, H, Dh)), a((B, H, S, Dh)), a((B, H, S, Dh)),
          a((), jnp.int32))
    layout = np.asarray(
        FixedSparsityConfig(num_heads=H, block=128).make_layout(S))
    check("blocksparse_attention",
          lambda q, k, v: blocksparse_attention(q, k, v, layout=layout,
                                                block=128), q4, q4, q4)
    check("blocksparse_attention_bwd",
          jax.grad(lambda q, k, v: blocksparse_attention(
              q, k, v, layout=layout, block=128)
              .astype(jnp.float32).sum()), q4, q4, q4)
    from deepspeed_tpu.ops.pallas.int8_matmul import int4_matmul, int8_matmul

    check("int8_matmul",
          lambda x, qq, s: int8_matmul(x, qq, s, group_size=128),
          a((8, 512)), a((512, 1536), jnp.int8),
          a((512 * 1536 // 128,), jnp.float32))
    check("int4_matmul",
          lambda x, qq, s: int4_matmul(x, qq, s, group_size=128),
          a((8, 512)), a((512, 1536), jnp.int8),
          a((512 * 3072 // 128,), jnp.float32))
    out = {"config": cfg["name"], "kind": "kernels_aot",
           "platform": "tpu-compile-only", "kernels": results}
    if failed:
        out["error"] = "Mosaic v5e compile failed: " + ", ".join(failed)
    return out


def _worker_infinity_aot(cfg: dict) -> dict:
    """AOT evidence for the ZeRO-Infinity streaming schedule: the five
    stream programs plus the schedule's two peak MOMENTS compiled whole
    (all resident buffers as program arguments), so peak_bytes is the XLA
    compiler's own accounting, with a fragmentation-margin verdict (core:
    deepspeed_tpu.runtime.aot.infinity_program_report — closes the r4
    'peak_bytes: null / est' gap, VERDICT r4 next #4)."""
    from deepspeed_tpu.runtime.aot import infinity_program_report

    rep = infinity_program_report(
        cfg.get("model", "gpt-neox-6.7b"),
        topology=cfg.get("topology", "v5e:2x2"),
        micro_bs=int(cfg.get("micro_bs", 8)), seq=int(cfg.get("seq", 1024)),
        keep_layers=int(cfg.get("keep_layers", 2)),
        # streamed-schedule accounting (docs/OFFLOAD.md): the fit verdict
        # includes the d in-flight prefetch buffers, itemized under "stream"
        prefetch_depth=int(cfg.get("prefetch_depth", 2)),
        quantized_fetch=bool(cfg.get("quantized_fetch", False)))
    return {"config": cfg["name"], "kind": "infinity_aot",
            "platform": "tpu-compile-only", **rep}


def _aot_fused_step(model, optimizer, gas: int = 1, k_steps: int = 1):
    """Engine-shaped fused step; single definition lives in the package
    (deepspeed_tpu.runtime.aot.fused_train_step) so every AOT producer —
    these bench rows, bin/ds_aot, tests — compiles identical semantics."""
    from deepspeed_tpu.runtime.aot import fused_train_step

    return fused_train_step(model, optimizer, gas=gas, k_steps=k_steps)


def _aot_report(compiled, compile_s: float) -> dict:
    from deepspeed_tpu.runtime.aot import report_from_compiled

    return report_from_compiled(compiled, compile_s)


def _worker_pipeline_aot(cfg: dict) -> dict:
    """AOT-compile the pp=2 SPMD pipeline training step against a REAL TPU
    (v5e) topology — the XLA TPU compiler runs on the host, no chips or tunnel
    needed — and report the compiler's per-device memory analysis + program
    FLOPs (VERDICT r3 next #4). The program is the engine-shaped fused step:
    pipelined loss (collective-permute schedule), grads, global-norm clip,
    AdamW on the fp32 master, bf16 copy-back, ZeRO-1 sharded optimizer state.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import topologies
    from jax.sharding import NamedSharding, PartitionSpec as P

    from deepspeed_tpu.models import build_gpt
    from deepspeed_tpu.models import gpt as gpt_mod
    from deepspeed_tpu.ops.optimizers import get_optimizer
    from deepspeed_tpu.runtime.topology import MeshTopology, mesh_context
    from deepspeed_tpu.runtime.zero.config import DeepSpeedZeroConfig
    from deepspeed_tpu.runtime.zero.policy import ZeroShardingPolicy

    import dataclasses

    topo_name = cfg.get("topology", "v5e:2x2")
    pp, dp = int(cfg.get("pp", 2)), int(cfg.get("dp", 2))
    td = topologies.get_topology_desc(platform="tpu", topology_name=topo_name)
    topo = MeshTopology.create(dp=dp, pp=pp, devices=list(td.devices))

    # compile the REAL chip program: Mosaic flash kernels, not the CPU-process
    # interpret fallback (which would misrepresent memory AND OOM the compiler
    # on [T,T] dense-attention scores)
    os.environ["DS_TPU_PALLAS_INTERPRET"] = "0"
    mcfg = gpt_mod.PRESETS[cfg.get("model", "gpt2-350m")]
    mcfg = dataclasses.replace(mcfg, remat=True, use_flash=True)
    base_model, _ = build_gpt(mcfg)
    M = int(cfg.get("num_micro", 2 * pp))
    model = base_model.to_pipeline(pp, M)
    micro_bs, seq = int(cfg.get("micro_bs", 8)), int(cfg.get("seq", 1024))
    B = micro_bs * M * dp

    rng = jax.random.PRNGKey(0)
    shapes = jax.eval_shape(model.init, rng)
    base_specs = model.specs(shapes)
    policy = ZeroShardingPolicy(topo, DeepSpeedZeroConfig(stage=1))
    tmap = jax.tree_util.tree_map
    pspec = tmap(lambda s, b: policy.param_spec(s.shape, b), shapes, base_specs)
    ospec = tmap(lambda s, b: policy.opt_spec(s.shape, b), shapes, base_specs)
    sh = lambda spec: NamedSharding(topo.mesh, spec)  # noqa: E731
    optimizer = get_optimizer("AdamW", {"lr": 3e-4, "weight_decay": 0.1})
    opt_shapes = jax.eval_shape(optimizer.init, shapes)
    step = _aot_fused_step(model, optimizer)

    def abstract(tree_shapes, spec_tree, dtype=None):
        return tmap(
            lambda s, p: jax.ShapeDtypeStruct(
                s.shape, dtype or s.dtype, sharding=sh(p)),
            tree_shapes, spec_tree)

    a_params = abstract(shapes, pspec, jnp.bfloat16)
    a_master = abstract(shapes, ospec, jnp.float32)
    # optimizer-state placement EXACTLY as the engine does it
    # (engine.py state_spec call): per-param leaves carry the opt specs
    # (incl. the pp placement of block moments), scalars replicate
    opt_spec_tree = optimizer.state_spec(
        tmap(lambda p: sh(p), ospec), sh(P()))
    a_opt = tmap(
        lambda s, shd: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=shd),
        opt_shapes, opt_spec_tree)
    a_batch = {"input_ids": jax.ShapeDtypeStruct(
        (B, seq), jnp.int32, sharding=sh(topo.batch_spec(1)))}
    a_rng = jax.ShapeDtypeStruct((2,), jnp.uint32, sharding=sh(P()))

    with mesh_context(topo.mesh):
        t0 = time.perf_counter()
        try:
            # donation mirrors the engine's fused step (state buffers aliased)
            compiled = jax.jit(step, donate_argnums=(0, 1, 2)).lower(
                a_params, a_master, a_opt, a_batch, a_rng).compile()
        except Exception as e:
            return {"config": cfg["name"], "kind": "pipeline_aot",
                    "platform": "tpu-compile-only", "topology": topo_name,
                    "pp": pp, "dp": dp, "num_micro": M, "micro_bs": micro_bs,
                    "seq": seq, "model": cfg.get("model", "gpt2-350m"),
                    **_aot_oom_row(e)}
        compile_s = time.perf_counter() - t0
    # note: the pipeline bubble M/(M+pp-1) is already in the program's schedule
    return {
        "config": cfg["name"], "kind": "pipeline_aot",
        "platform": "tpu-compile-only", "topology": topo_name,
        "pp": pp, "dp": dp, "num_micro": M, "micro_bs": micro_bs, "seq": seq,
        "model": cfg.get("model", "gpt2-350m"),
        **_aot_report(compiled, compile_s),
    }


def _worker_train_aot(cfg: dict) -> dict:
    """AOT-compile a dense training config against the v5e topology (no
    chips/tunnel needed): per-device HBM breakdown + program FLOPs, or a
    structured compile-time OOM verdict. Core lives in
    deepspeed_tpu.runtime.aot.train_program_report (also behind bin/ds_aot)."""
    from deepspeed_tpu.runtime.aot import train_program_report

    rep = train_program_report(
        cfg["model"],
        topology=cfg.get("topology", "v5e:2x2"),
        dp=int(cfg.get("dp", 1)), tp=int(cfg.get("tp", 1)),
        sp=int(cfg.get("sp", 1)), stage=int(cfg.get("stage", 1)),
        micro_bs=int(cfg.get("micro_bs", 16)), seq=int(cfg.get("seq", 1024)),
        gas=int(cfg.get("gas", 1)), k_steps=int(cfg.get("k_steps", 1)),
        remat_policy=cfg.get("remat_policy"),
        loss_chunk=int(cfg.get("loss_chunk", 0)),
        seq_parallel_impl=cfg.get("seq_parallel_impl"))
    return {"config": cfg["name"], "kind": "train_aot",
            "platform": "tpu-compile-only", **rep}


def _worker_infer_aot(cfg: dict) -> dict:
    """AOT-compile the generate-shaped decode program against the v5e
    topology: KV-cache-dominated HBM fit + per-token FLOPs evidence with no
    chips (core: deepspeed_tpu.runtime.aot.decode_program_report)."""
    from deepspeed_tpu.runtime.aot import decode_program_report

    rep = decode_program_report(
        cfg.get("model", "gpt2-350m"),
        topology=cfg.get("topology", "v5e:2x2"),
        batch=int(cfg.get("batch", 1)), prompt=int(cfg.get("prompt", 128)),
        gen=int(cfg.get("gen", 64)),
        cache_dtype=cfg.get("cache_dtype", "bfloat16"),
        quantize_bits=int(cfg.get("quantize_bits", 0)))
    return {"config": cfg["name"], "kind": "infer_aot",
            "platform": "tpu-compile-only", **rep}


def _worker_sd_aot(cfg: dict) -> dict:
    """AOT-compile the full SD inference program (DDIM scan + CFG UNet + VAE
    decode) against the v5e topology (core: runtime.aot.sd_program_report)."""
    from deepspeed_tpu.runtime.aot import sd_program_report

    rep = sd_program_report(
        topology=cfg.get("topology", "v5e:2x2"),
        batch=int(cfg.get("batch", 1)), latent=int(cfg.get("latent", 32)),
        ddim_steps=int(cfg.get("ddim_steps", 20)),
        channels=tuple(cfg.get("channels", (128, 256, 512))),
        text_dim=int(cfg.get("text_dim", 512)))
    return {"config": cfg["name"], "kind": "sd_aot",
            "platform": "tpu-compile-only", **rep}


def _aot_oom_row(e: Exception) -> dict:
    from deepspeed_tpu.runtime.aot import oom_row

    return oom_row(e)


def _worker_moe_aot(cfg: dict) -> dict:
    """AOT-compile the MoE expert-parallel training step (ep over the v5e
    mesh: expert bank sharded, gating all-to-alls over ICI) against the v5e
    compiler — BASELINE config #4's program shape, no chips needed."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import topologies
    from jax.sharding import NamedSharding, PartitionSpec as P

    from deepspeed_tpu.models import build_gpt_moe
    from deepspeed_tpu.ops.optimizers import get_optimizer
    from deepspeed_tpu.runtime.topology import MeshTopology, mesh_context
    from deepspeed_tpu.runtime.zero.config import DeepSpeedZeroConfig
    from deepspeed_tpu.runtime.zero.policy import ZeroShardingPolicy

    os.environ["DS_TPU_PALLAS_INTERPRET"] = "0"
    td = topologies.get_topology_desc(
        platform="tpu", topology_name=cfg.get("topology", "v5e:2x2"))
    ep, dp = int(cfg.get("ep", 4)), int(cfg.get("dp", 1))
    topo = MeshTopology.create(dp=dp, ep=ep, devices=list(td.devices)[:dp * ep])
    model, mcfg = build_gpt_moe(cfg.get("model", "moe-125m-8e"))
    micro_bs = int(cfg.get("micro_bs", 4))
    seq = int(cfg.get("seq", 1024))
    B = micro_bs * dp * ep  # batch rides the (dp, ep) axes

    shapes = jax.eval_shape(model.init, jax.random.PRNGKey(0))
    base_specs = model.specs(shapes)
    policy = ZeroShardingPolicy(topo, DeepSpeedZeroConfig(
        stage=int(cfg.get("stage", 1))))
    tmap = jax.tree_util.tree_map
    sh = lambda spec: NamedSharding(topo.mesh, spec)  # noqa: E731
    pspec = tmap(lambda s, b: policy.param_spec(s.shape, b), shapes, base_specs)
    ospec = tmap(lambda s, b: policy.opt_spec(s.shape, b), shapes, base_specs)
    optimizer = get_optimizer("AdamW", {"lr": 3e-4, "weight_decay": 0.1})
    opt_shapes = jax.eval_shape(optimizer.init, shapes)
    step = _aot_fused_step(model, optimizer)

    def abstract(tree_shapes, spec_tree, dtype=None):
        return tmap(lambda s, p: jax.ShapeDtypeStruct(
            s.shape, dtype or s.dtype, sharding=sh(p)), tree_shapes, spec_tree)

    opt_spec_tree = optimizer.state_spec(tmap(lambda p: sh(p), ospec), sh(P()))
    a_opt = tmap(lambda s, shd: jax.ShapeDtypeStruct(
        s.shape, s.dtype, sharding=shd), opt_shapes, opt_spec_tree)
    a_batch = {"input_ids": jax.ShapeDtypeStruct(
        (B, seq), jnp.int32, sharding=sh(topo.batch_spec(1)))}
    a_rng = jax.ShapeDtypeStruct((2,), jnp.uint32, sharding=sh(P()))
    out = {"config": cfg["name"], "kind": "moe_aot",
           "platform": "tpu-compile-only",
           "model": cfg.get("model", "moe-125m-8e"),
           "ep": ep, "dp": dp, "micro_bs": micro_bs, "seq": seq}
    with mesh_context(topo.mesh):
        t0 = time.perf_counter()
        try:
            compiled = jax.jit(step, donate_argnums=(0, 1, 2)).lower(
                abstract(shapes, pspec, jnp.bfloat16),
                abstract(shapes, ospec, jnp.float32),
                a_opt, a_batch, a_rng).compile()
        except Exception as e:
            out.update(_aot_oom_row(e))
            return out
        compile_s = time.perf_counter() - t0
    out.update(_aot_report(compiled, compile_s))
    return out


def _worker_pipeline_schedule(cfg: dict) -> dict:
    """Static schedule comparison (ISSUE 18): generate 1F1B, interleaved,
    and zero-bubble IRs at equal microbatches on the 8-device mesh shape,
    prove each with the pipeline-schedule prover, and report the static
    bubble %% + priced peak residency side by side. Pure host math — the
    whole point is that this verdict is available before any compile or
    dispatch."""
    import jax

    from deepspeed_tpu.analysis.schedule import prove_schedule
    from deepspeed_tpu.runtime.aot import pipeline_schedule_report
    from deepspeed_tpu.runtime.pipe.mpmd import (
        generate_1f1b_ir, generate_interleaved_ir, generate_zero_bubble_ir)

    platform = jax.devices()[0].platform
    S = int(cfg.get("stages", 8))
    M = int(cfg.get("num_micro", 16))
    V = int(cfg.get("vstages", 2))
    mb = int(cfg.get("micro_bs", 4))
    seq = int(cfg.get("seq", 1024))
    d_model = int(cfg.get("d_model", 1024))
    act_bytes = mb * seq * d_model * 2  # one bf16 stage-input activation

    rows = {}
    for ir in (generate_1f1b_ir(M, S),
               generate_interleaved_ir(M, S, num_vstages=V),
               generate_zero_bubble_ir(M, S)):
        rep = pipeline_schedule_report(ir, activation_bytes=act_bytes)
        kind = ir.name.split("[")[0]
        rows[kind] = {
            "schedule": ir.name,
            "proof_ok": rep["proof_ok"],
            "n_findings": len(rep["findings"]),
            "bubble_frac": rep["bubble_frac"],
            "peak_activation_buffers": rep["peak_activation_buffers"],
            "peak_schedule_bytes": rep["peak_schedule_bytes"],
            "confidence": rep.get("confidence"),
        }
    zb, il, f1 = (rows["zero-bubble"]["bubble_frac"],
                  rows["interleaved"]["bubble_frac"],
                  rows["1f1b"]["bubble_frac"])
    return {
        "config": cfg["name"], "kind": "pipeline_schedule",
        "platform": platform, "n_devices": len(jax.devices()),
        "num_stages": S, "num_micro": M, "vstages": V,
        "activation_bytes": act_bytes,
        "schedules": rows,
        "all_proven": all(r["proof_ok"] for r in rows.values()),
        "zero_bubble_beats_1f1b": bool(zb < f1),
        "interleaved_beats_1f1b": bool(il < f1),
        "bubble_reduction_vs_1f1b": {
            "interleaved": round(1.0 - il / f1, 4) if f1 else None,
            "zero-bubble": round(1.0 - zb / f1, 4) if f1 else None,
        },
    }


def _worker_pipeline_mpmd(cfg: dict) -> dict:
    """MPMD 1F1B interpreter dispatch microbench (VERDICT r3 weak #5): run a
    2-stage PipelineModule's slot loop on the available device(s) and compare
    its steady-state step time against ONE fused jit doing the identical
    compute — the gap is the per-slot host-dispatch + buffer-rotation cost the
    Python interpreter adds. Stages share a device when only one chip exists
    (correctness-preserving; the overhead measurement is what matters here)."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.runtime.pipe.module import LayerSpec, PipelineModule
    from deepspeed_tpu.runtime.pipe.mpmd import MPMDPipelineEngine

    platform = jax.devices()[0].platform
    d = int(cfg.get("d_model", 1024))
    n_blocks = int(cfg.get("n_blocks", 24))
    S, M = int(cfg.get("stages", 2)), int(cfg.get("num_micro", 4))
    mb, T = int(cfg.get("micro_bs", 4)), int(cfg.get("seq", 512))
    steps = int(cfg.get("steps", 8))

    def mlp_init(rng):
        k1, k2 = jax.random.split(rng)
        return {"w1": jax.random.normal(k1, (d, 4 * d), jnp.bfloat16) * 0.02,
                "w2": jax.random.normal(k2, (4 * d, d), jnp.bfloat16) * 0.02}

    def mlp_apply(w, x):
        return x + jnp.tanh(x @ w["w1"]) @ w["w2"]

    def loss_fn(y, mb_):
        return jnp.mean(y.astype(jnp.float32) ** 2)

    specs = [LayerSpec(mlp_init, mlp_apply, name=f"blk{i}",
                       param_count=8 * d * d) for i in range(n_blocks)]
    module = PipelineModule(specs, num_stages=S, partition_method="uniform",
                            loss_fn=loss_fn)
    devs = [jax.devices()[i % len(jax.devices())] for i in range(S)]
    eng = MPMDPipelineEngine(
        module, num_micro=M, devices=devs,
        optimizer=(lambda p: (), lambda g, s, p=None: (g, s)))
    params = eng.init(jax.random.PRNGKey(0))
    opt_state = eng.init_optimizer(params)
    # batch leaves are [M, mb, ...]; a bare array feeds stage 0 directly
    x = jnp.asarray(np.random.default_rng(0).standard_normal(
        (M, mb, T, d)), jnp.bfloat16)

    _, _, metrics = eng.train_batch(params, opt_state, x, apply_update=False)
    jax.block_until_ready(metrics["loss"])  # warmup/compile
    t0 = time.perf_counter()
    for _ in range(steps):
        _, _, metrics = eng.train_batch(params, opt_state, x,
                                        apply_update=False)
    jax.block_until_ready(
        (metrics["loss"], jax.tree_util.tree_leaves(metrics["grads"])[0]))
    mpmd_ms = (time.perf_counter() - t0) / steps * 1e3

    # identical compute as ONE fused program: all blocks, all micro-batches
    stacked = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *[mlp_init(k) for k in jax.random.split(
            jax.random.PRNGKey(0), n_blocks)])

    def fused(w, xs):
        def body(h, lw):
            return mlp_apply(lw, h), None

        def one(mb_x):
            h, _ = jax.lax.scan(body, mb_x, w)
            return jnp.mean(h.astype(jnp.float32) ** 2)

        return jnp.mean(jax.vmap(one)(xs))

    fused_vg = jax.jit(jax.value_and_grad(fused))
    l2, g2 = fused_vg(stacked, x)
    jax.block_until_ready(l2)
    t0 = time.perf_counter()
    for _ in range(steps):
        l2, g2 = fused_vg(stacked, x)
    jax.block_until_ready((l2, jax.tree_util.tree_leaves(g2)[0]))
    fused_ms = (time.perf_counter() - t0) / steps * 1e3

    return {
        "config": cfg["name"], "kind": "pipeline_mpmd", "platform": platform,
        "stages": S, "num_micro": M, "micro_bs": mb, "seq": T, "d_model": d,
        "n_blocks": n_blocks, "devices": len(set(devs)),
        "mpmd_step_ms": round(mpmd_ms, 1),
        "fused_step_ms": round(fused_ms, 1),
        "dispatch_overhead_ms": round(mpmd_ms - fused_ms, 1),
        "overhead_pct": round((mpmd_ms - fused_ms) / fused_ms * 100, 1),
    }


# ---------------------------------------------------------------- parent side

def tpu_core_configs() -> list:
    """The measured TPU sweep (order = evidence priority) + AOT fit rows."""
    model = os.environ.get("BENCH_MODEL", "gpt2-350m")
    bs = int(os.environ.get("BENCH_BS", "16"))
    seq = int(os.environ.get("BENCH_SEQ", "1024"))
    # k_steps=8 + fewer outer dispatches: same measured optimizer steps,
    # 1/8th the dispatches — the per-dispatch tunnel RTT (~350ms, r4
    # measured) otherwise reads as fake MFU loss. k_steps (full steps
    # scanned in-program) not gas: the gas-8 fp32 accumulator AOT-OOMs
    # the lead 760M geometries.
    steps = int(os.environ.get("BENCH_STEPS", "5"))
    kst = int(os.environ.get("BENCH_K_STEPS", "8"))
    big = os.environ.get("BENCH_BIG_MODEL", "gpt2-760m")
    big_bs = int(os.environ.get("BENCH_BIG_BS", "16"))
    # Compiles on this setup run 10-25+ min per NEW program (r4 measured:
    # 3 of 4 chunk-loss grid rows died on compile, not execution), so the
    # DEFAULT sweep is the completable high-value core; BENCH_FULL=1
    # restores the wide grid. Row order = evidence priority.
    full = os.environ.get("BENCH_FULL", "0") == "1"
    return [
        {"kind": "kernels", "name": "pallas-kernel-smoke"},
        # the two strongest measured train rows (r4 chip grid), k8-fused
        {"kind": "train", "name": f"{big}-zero1-selrm12", "model": big,
         "micro_bs": 12, "seq": seq, "stage": 1, "steps": steps,
         "k_steps": kst, "timeout": 2700,
         "remat_policy": "save_attn_mlp_out"},
        {"kind": "train", "name": f"{model}-zero1", "model": model,
         "micro_bs": bs, "seq": seq, "stage": 1,
         "steps": steps, "k_steps": kst, "timeout": 2700,
         "remat_policy": "save_attn_mlp_out"},
        {"kind": "inference", "name": f"{model}-decode", "model": model,
         "batch": 1, "prompt": 128, "gen": 64, "timeout": 2700},
        # batched decode: amortized per-token throughput
        {"kind": "inference", "name": f"{model}-decode-b8", "model": model,
         "batch": 8, "prompt": 128, "gen": 64, "timeout": 2700},
        # the weight-bandwidth lever, measured: packed int4 quarters the
        # bytes per decoded token
        {"kind": "inference", "name": f"{model}-decode-b8-int4",
         "model": model, "batch": 8, "prompt": 128, "gen": 64,
         "quantize_bits": 4, "timeout": 2700},
        # continuous-batching serving row (ROADMAP item 1): open-loop
        # arrivals through the paged decode stack, A/B'd against static
        # generate batches on the same seeded workload — reports p50/p99
        # TTFT + aggregate tokens/s and the speedup_vs_static bar
        {"kind": "serving", "name": f"{model}-serving-cb", "model": model,
         "slots": 16, "page_size": 128, "max_model_len": 512,
         "prefill_chunk": 128, "requests": 32, "rate_rps": 8.0,
         "prompt_range": (32, 160), "gen_range": (8, 128),
         "timeout": 2700},
        # serving-era flagship lever row: int8 KV pages vs dense at equal
        # HBM bytes, 2x saturation — the capacity-vs-SLO axis measured on
        # the chip (the next chip run's first serving-era bench point)
        {"kind": "serving_lever", "name": f"{model}-serving-cb-kv8",
         "lever": "kv8", "model": model, "slots": 16, "page_size": 128,
         "max_model_len": 512, "prefill_chunk": 128, "requests": 32,
         "slo_s": 6.0, "prompt_range": (32, 160), "gen_range": (8, 128),
         "dtype": "bfloat16", "timeout": 2700},
        # speculative-decoding flagship: n-gram self-drafting + adaptive k
        # vs spec-off at equal slots/pages on the chip, where decode is
        # weight-bound — the k+1-token verify reads each weight matrix
        # once, so accepted tokens per dispatch is the direct multiplier
        # the Gemma serving paper frames capacity around. decode_block=1
        # on both sides isolates the lever (the scan block's win is
        # host-round-trip amortization, already measured by -serving-cb)
        {"kind": "serving_lever", "name": f"{model}-serving-cb-spec",
         "lever": "spec", "model": model, "slots": 16, "page_size": 128,
         "max_model_len": 512, "prefill_chunk": 128, "requests": 32,
         "slo_s": 6.0, "spec_k": 4, "decode_block": 1,
         "prompt_range": (32, 160), "gen_range": (8, 128),
         "dtype": "bfloat16", "timeout": 2700},
        # multi-tenancy flagship: the 3-tier SLO contract at 2x saturation
        # on the chip — WFQ + brownout ladder holding interactive p99 TTFT
        # at its light-load floor while batch absorbs the shed, vs the
        # tier-blind scheduler on the same stream
        {"kind": "serving_tiered", "name": f"{model}-serving-tiers",
         "model": model, "slots": 16, "page_size": 128,
         "max_model_len": 512, "prefill_chunk": 128,
         "requests_per_tier": 12, "slo_s": 6.0,
         "prompt_range": (32, 160), "gen_range": (8, 128),
         "dtype": "bfloat16", "timeout": 2700},
        # fleet flagship: 2 router-fronted replica processes vs one engine
        # at equal total slots at 2x saturation + the replica-kill chaos
        # variant — graceful degradation a single replica cannot produce.
        # Prefill-heavy (TTFT-bound) shape; replica_env pins one chip per
        # worker so replicas own their compute (two processes cannot share
        # one TPU runtime)
        {"kind": "serving_fleet", "name": f"{model}-serving-fleet",
         "model": model, "replicas": 2, "slots": 8, "page_size": 128,
         "max_model_len": 512, "prefill_chunk": 128, "requests": 32,
         "slo_s": 6.0, "prompt_range": (128, 384), "gen_range": (8, 32),
         "replica_env": {"TPU_VISIBLE_DEVICES": "{i}"},
         "dtype": "bfloat16", "timeout": 2700},
        # tensor-parallel serving flagship: the SAME continuous-batching
        # row sharded over 2 chips (tp=2 weight stacks + paged pools,
        # one psum per block) — greedy-identical outputs, ~2x the
        # weight bandwidth per decoded token where decode is weight-bound
        {"kind": "serving", "name": f"{model}-serving-tp2", "model": model,
         "tp": 2, "slots": 16, "page_size": 128, "max_model_len": 512,
         "prefill_chunk": 128, "requests": 32, "rate_rps": 8.0,
         "prompt_range": (32, 160), "gen_range": (8, 128),
         "timeout": 2700},
        # disaggregated prefill/decode flagship: prefill + decode
        # specialist worker processes (one chip each via replica_env) vs
        # the colocated fleet at equal total slots/pages — page-handoff
        # ownership transfer over the wire, int8 pages to shrink the
        # payload, plus the prefill-kill chaos phase (zero survivor
        # page leaks, greedy-identical failover)
        {"kind": "serving_disagg", "name": f"{model}-serving-disagg",
         "model": model, "slots": 8, "page_size": 128,
         "max_model_len": 512, "prefill_chunk": 128, "kv_bits": 8,
         "requests": 32, "slo_s": 6.0, "prompt_range": (128, 384),
         "gen_range": (8, 32),
         "replica_env": {"TPU_VISIBLE_DEVICES": "{i}"},
         "dtype": "bfloat16", "timeout": 2700},
        {"kind": "diffusion", "name": "sd-ddim20", "latent": 32,
         "ddim_steps": 20, "timeout": 2700},
        # measured MoE row (VERDICT r4 next #5): single-chip expert bank,
        # same gating/dispatch program as ep>1
        {"kind": "moe_train", "name": "moe-125m-8e-train",
         "model": "moe-125m-8e", "micro_bs": 8, "seq": seq, "steps": steps,
         "timeout": 2700},
        # the overlap target row (ROADMAP item 2): quantized ZeRO-3 gathers
        # pipelined under compute on the flagship geometry, with a profiled
        # step reporting the exposed-vs-overlapped collective-time column —
        # the ≥0.45 MFU bar is judged here
        {"kind": "train", "name": f"{big}-zero3-qw8-overlap", "model": big,
         "micro_bs": 12, "seq": seq, "stage": 3, "steps": steps,
         "k_steps": kst, "quantized_weights": True, "measure_overlap": True,
         "remat_policy": "save_attn_mlp_out", "timeout": 2700},
        # chunked loss drops the fp32 logits buffer — AOT-verified to fit
        # where unchunked OOMs; longest compile, so last of the core rows
        {"kind": "train", "name": f"{big}-zero1-selrm16-chunk",
         "model": big, "micro_bs": 16, "seq": seq, "stage": 1,
         "steps": steps, "k_steps": kst, "timeout": 2700,
         "remat_policy": "save_attn_mlp_out", "loss_chunk": 128},
    ] + (([
        {"kind": "train", "name": f"{model}-zero{s}", "model": model,
         "micro_bs": bs, "seq": seq, "stage": s, "steps": steps,
         "k_steps": kst, "timeout": 2700}
        for s in (2, 3)
    ] + [
        {"kind": "train", "name": f"{big}-zero{s}", "model": big,
         "micro_bs": big_bs, "seq": seq, "stage": s, "steps": steps,
         "k_steps": kst, "timeout": 2700}
        for s in (1, 3)
    ] + [
        {"kind": "train", "name": f"{big}-zero1-bs24-chunk", "model": big,
         "micro_bs": 24, "seq": seq, "stage": 1, "steps": steps,
         "k_steps": kst, "loss_chunk": 128, "timeout": 2700},
    ]) if full else []) + (
        # pipeline_aot + AOT rows are force_cpu (host-side v5e compiler):
        # cheap chip-independent fit evidence; pipeline_mpmd is a short
        # on-chip dispatch microbench. Infinity rows (long, host-streamed)
        # only under BENCH_FULL.
        PIPELINE_CONFIGS + AOT_TRAIN_CONFIGS
        + QUANTIZED_ZERO_CONFIGS
        + (INFINITY_CONFIGS if full else []))


def cpu_fallback_configs() -> list:
    """Forced-CPU fallback: tiny measured shapes + chip-independent AOT rows.

    The measured rows carry force_cpu explicitly: they are forced-CPU
    measurements BY DESIGN, so a mid-sweep tunnel recovery (which flips the
    run's platform to tpu) cannot silently re-route a still-queued
    'cpu-fallback-*' row onto the real backend and mislabel it as evidence."""
    return [
        {"kind": "train", "name": f"cpu-fallback-zero{s}", "model": "gpt2-125m",
         "micro_bs": 2, "seq": 128, "stage": s, "steps": 3, "force_cpu": True}
        for s in (1, 2)
    ] + [
        # quantized ZeRO-3 wire evidence is chip-independent (the ledger
        # records at trace time), so the fallback sweep measures it too
        {"kind": "train", "name": "cpu-fallback-zero3-qw8",
         "model": "gpt2-125m", "micro_bs": 2, "seq": 128, "stage": 3,
         "steps": 3, "precision": "fp32", "quantized_weights": True,
         "force_cpu": True},
    ] + [
        # streamed ZeRO-Infinity A/B (docs/OFFLOAD.md): the same host-
        # streamed step with the depth-2 prefetch pipeline vs fetch-on-
        # demand. Numerics are bitwise-identical by construction (same
        # units, same order — asserted in tests/test_infinity_stream.py);
        # the rows report the host-DMA column (exposed_wait_s,
        # overlapped_frac) so the schedule's latency hiding is a measured
        # number, and step_ms must be no worse than inline
        {"kind": "train", "name": "cpu-fallback-infinity-streamed",
         "model": "gpt2-125m", "micro_bs": 1, "seq": 64, "steps": 2,
         "offload": "param_stream", "keep_layers": 2,
         "offload_prefetch_depth": 2, "force_cpu": True, "timeout": 900},
        {"kind": "train", "name": "cpu-fallback-infinity-inline",
         "model": "gpt2-125m", "micro_bs": 1, "seq": 64, "steps": 2,
         "offload": "param_stream", "keep_layers": 2,
         "offload_stream": False, "force_cpu": True, "timeout": 900},
    ] + [
        # MTTR evidence: NaN at a known cursor -> sentinel rollback ->
        # poisoned-batch skip -> rejoin; the heal mechanics are
        # chip-independent (host-side detection + checkpoint restore)
        {"kind": "chaos_mttr", "name": "cpu-chaos-nan-mttr",
         "model": "gpt2-125m", "micro_bs": 2, "seq": 128, "steps": 5,
         "nan_at": 3, "force_cpu": True},
    ] + [
        # SDC evidence (docs/RESILIENCE.md "Data integrity"): a real bit
        # flip in a cpu-offloaded optimizer shard AND in a prefix-shared
        # KV page, both detected and healed (training replay step-exact,
        # serving re-prefill generate-identical) with the scan overhead
        # measured at the default budget (must be ≤5% of step time). The
        # flip lands at step 17: the default scan_interval=16 budget has
        # stamped its first blocks at the step-16 boundary, so detection
        # rides the production cadence, not a cranked-up test one
        {"kind": "chaos_sdc", "name": "cpu-chaos-sdc",
         "model": "gpt2-125m", "micro_bs": 2, "seq": 128, "steps": 20,
         "flip_at": 17, "force_cpu": True, "timeout": 900},
    ] + [
        # continuous-batching A/B is measurable on CPU once the model is
        # compute-bound (125m): slot recycling + exact-length decode beat
        # the padded static scan ~1.7x on tokens/s at equal HBM tokens,
        # with ~7x better TTFT p50 (measured while building the row)
        {"kind": "serving", "name": "cpu-serving-cb", "model": "gpt2-125m",
         "slots": 8, "page_size": 16, "max_model_len": 128,
         "prefill_chunk": 64, "requests": 12, "rate_rps": 50.0,
         "hbm_tokens": 640, "prompt_range": (8, 48), "gen_range": (2, 48),
         "dtype": "float32", "force_cpu": True, "timeout": 900},
    ] + [
        # overload A/B at 2x saturation: with admission control ON, p99
        # TTFT of accepted requests stays bounded and goodput holds; the
        # uncontrolled baseline's queue (and tail) grows with the load
        {"kind": "serving_overload", "name": "cpu-serving-overload",
         "model": "gpt2-125m", "slots": 4, "page_size": 16,
         "max_model_len": 96, "prefill_chunk": 32, "requests": 16,
         "slo_s": 3.0, "prompt_range": (8, 24), "gen_range": (8, 24),
         "dtype": "float32", "force_cpu": True, "timeout": 900},
    ] + [
        # multi-tenant SLO-tier A/B at 2x saturation (docs/SERVING.md
        # "Multi-tenancy & SLO tiers"): 3-tier mixed-tenant stream, tiered
        # (WFQ + per-tier partitions + brownout ladder) vs untiered on the
        # same workload — interactive p99 TTFT held near its light-load
        # floor while the batch tier absorbs the shed; batch bounded-wait
        # asserted; greedy_match_rate 1.0 (prioritization must never
        # change the tokens). 125m because the within-15% TTFT bar is only
        # meaningful where TTFT is service-dominated (a dispatch-bound
        # tiny model turns 2x overload into a sub-second burst and the
        # comparison into scheduler-jitter noise); the SLO and wall are
        # sized for a 1-core CI host serving 125m at ~0.4 rps saturation
        {"kind": "serving_tiered", "name": "cpu-serving-tiers",
         "model": "gpt2-125m", "slots": 4, "page_size": 16,
         "max_model_len": 96, "prefill_chunk": 32, "decode_block": 2,
         "requests_per_tier": 10, "slo_s": 30.0, "max_wall_s": 240.0,
         "prompt_range": (8, 24), "gen_range": (8, 24),
         "dtype": "float32", "force_cpu": True, "timeout": 900},
    ] + [
        # serving-lever A/B rows at 2x saturation (docs/SERVING.md "KV
        # quantization & prefix caching"): int8 KV pages at equal HBM bytes
        # (4x the fp32 pool pages -> fewer preemptions, higher goodput),
        # and copy-on-write prefix caching on a shared-system-prompt
        # workload (physical pages < logical, outputs byte-identical)
        {"kind": "serving_lever", "name": "cpu-serving-cb-kv8",
         "lever": "kv8", "model": "gpt2-125m", "slots": 4, "page_size": 16,
         "max_model_len": 96, "prefill_chunk": 32, "requests": 16,
         "slo_s": 3.0, "prompt_range": (8, 24), "gen_range": (8, 24),
         "dtype": "float32", "force_cpu": True, "timeout": 900},
        {"kind": "serving_lever", "name": "cpu-serving-cb-prefix",
         "lever": "prefix", "model": "gpt2-125m", "slots": 4,
         "page_size": 16, "max_model_len": 96, "prefill_chunk": 64,
         "requests": 16, "slo_s": 3.0, "prefix_len": 32,
         "prompt_range": (4, 16), "gen_range": (8, 24),
         "dtype": "float32", "force_cpu": True, "timeout": 900},
        # speculative decoding A/B at 2x saturation: n-gram self-drafting +
        # adaptive k against the spec-off scheduler at EQUAL slots/pages,
        # decode_block=1 on both sides (on CPU the scan block and the
        # verify window amortize the same dispatch overhead; block=1
        # isolates the lever — the dispatch-bound "tiny" model is the
        # honest CPU stand-in for the TPU's weight-bound regime, where
        # verify reads the weights once per k+1 tokens). Gate:
        # greedy_match_rate == 1.0 — speculation must be invisible in the
        # outputs, visible only in goodput/TTFT/tokens_per_dispatch
        # (measured while building: goodput 4056-4616 vs 3251-3423 tok/s,
        # TTFT p50 33 vs 43-49ms / p99 66-78 vs 120-124ms across seeds,
        # accept_rate ~0.90, tokens_per_dispatch ~12.3, greedy_match_rate
        # 1.0 — longer generations give the drafter loops to lock onto)
        {"kind": "serving_lever", "name": "cpu-serving-cb-spec",
         "lever": "spec", "model": "tiny", "slots": 4, "page_size": 16,
         "max_model_len": 96, "prefill_chunk": 32, "requests": 24,
         "slo_s": 3.0, "spec_k": 4, "decode_block": 1, "gen_range": (16, 48),
         "prompt_range": (8, 24), "dtype": "float32", "force_cpu": True,
         "timeout": 900},
    ] + [
        # fleet overload A/B at 2x saturation (docs/SERVING.md "Fleet"):
        # 2 router-fronted replica PROCESSES vs one engine at equal total
        # slots — the fleet must beat the single scheduler on goodput AND
        # deadline-miss rate, and the replica-kill chaos variant must show
        # zero survivor page leaks with greedy_match_rate 1.0. The
        # workload is prefill-heavy (long prompts, short gens — the
        # TTFT-bound chat shape): that is where per-replica compute bites,
        # because a single engine serializes every prefill against all of
        # its running slots while replicas prefill concurrently
        {"kind": "serving_fleet", "name": "cpu-serving-fleet",
         "model": "gpt2-125m", "replicas": 2, "slots": 2, "page_size": 16,
         "max_model_len": 128, "prefill_chunk": 64, "pool_pages": 16,
         "requests": 48, "slo_s": 4.0, "prompt_range": (64, 112),
         "gen_range": (4, 8), "dtype": "float32", "force_cpu": True,
         "timeout": 1200},
    ] + [
        # disaggregated prefill/decode A/B at 2x saturation (docs/
        # SERVING.md "Tensor parallel & disaggregation"): 1 prefill + 1
        # decode specialist vs 2 colocated replicas at equal TOTAL
        # slots/pages on the fleet row's prefill-heavy (TTFT-bound)
        # shape, int8 KV pages keeping the handoff wire payload small
        # (pages + per-page scales). Measured while building the row
        # (single-core CI host): TTFT p99 strictly better in 6/8 runs
        # (e.g. 12.7s vs 13.8s, 13.2s vs 19.7s — the prefill
        # specialist's first tokens never queue behind decode slot
        # commitments), chaos phase (prefill specialist SIGKILLed
        # mid-stream) always zero survivor page leaks with
        # greedy_match_rate 1.0. The goodput >= colocated bar is judged
        # on the CHIP row: on a one-core host every replica process
        # timeshares the same CPU, so disagg pays the handoff wire cost
        # without collecting its win (prefill and decode no longer
        # stealing each other's compute) — that win needs replicas that
        # own their chips (replica_env)
        {"kind": "serving_disagg", "name": "cpu-serving-disagg",
         "model": "gpt2-125m", "slots": 2, "page_size": 16,
         "max_model_len": 128, "prefill_chunk": 64, "pool_pages": 16,
         "kv_bits": 8, "requests": 24, "slo_s": 12.0,
         "prompt_range": (64, 112), "gen_range": (4, 8),
         "max_wall_s": 300.0,
         "dtype": "float32", "force_cpu": True, "timeout": 1800},
    ] + [{"kind": "inference", "name": "cpu-fallback-decode", "model": "gpt2-125m",
          "batch": 1, "prompt": 32, "gen": 16, "reps": 3, "force_cpu": True},
         # real-TPU-compiler evidence even when the tunnel is down
         PIPELINE_CONFIGS[0]] + AOT_TRAIN_CONFIGS


def main() -> None:
    platform, n_chips, probe_errors = probe_backend()
    for e in probe_errors:
        print(f"[bench] {e}", file=sys.stderr)
    # evidence banked by PREVIOUS sweeps: spliced into every summary so a
    # sweep that dies early (or starts after one that did) still reports the
    # newest completed row per config (r05: rc=124 stranded a whole sweep's
    # rows in the ledger with no final report carrying them)
    banked = _load_banked_rows()
    # run delimiter so a reader of the append-only ledger can attribute rows
    # to the sweep (and round) that produced them
    _persist_row({"run_start": True, "platform": platform, "argv": sys.argv[1:],
                  "probe_errors": probe_errors[-2:]})

    configs = list(tpu_core_configs() if platform == "tpu"
                   else cpu_fallback_configs())

    sweep, errors = [], list(probe_errors)

    def _flush_on_term(signum, frame):
        # an external `timeout`/driver kill mid-row must still leave a final
        # summary on stdout (the r05 failure mode)
        errors.append(f"killed by signal {signum} mid-sweep")
        _persist_row({"killed_by_signal": signum, "rows_completed": len(sweep)})
        print(json.dumps(_summarize(platform, sweep, errors, banked=banked)),
              flush=True)
        sys.exit(124)

    signal.signal(signal.SIGTERM, _flush_on_term)

    deadline = time.time() + TOTAL_BUDGET if TOTAL_BUDGET else None
    recovered = False
    recovery_probes = 0
    last_probe_t = time.time()
    i = 0
    while i < len(configs):
        cfg = configs[i]
        i += 1
        if deadline is not None:
            remaining = deadline - time.time()
            if remaining < ROW_RESERVE:
                # banking a skip beats an rc=124 with the row half-run
                r = {"config": cfg.get("name"),
                     "skipped": "global_budget_exhausted",
                     "remaining_s": round(max(0.0, remaining), 1)}
                sweep.append(r)
                _persist_row(r)
                print(f"[bench] {json.dumps(r)}", file=sys.stderr)
                continue
            cfg = dict(cfg)
            cfg["timeout"] = int(min(cfg.get("timeout", WORKER_TIMEOUT),
                                     max(ROW_RESERVE, remaining - ROW_RESERVE)))
        r = run_worker(cfg, platform)
        sweep.append(r)
        _persist_row(r)
        if "error" in r:
            errors.append(f"{cfg['name']}: {r['error']}")
        print(f"[bench] {json.dumps(r)}", file=sys.stderr)
        # refresh the stdout artifact after EVERY row: if the sweep is killed
        # mid-run (driver budget, tunnel hang), the last complete line is
        # still a valid summary of everything measured so far
        print(json.dumps(_summarize(platform, sweep, errors, banked=banked)),
              flush=True)

        # VERDICT r4 'next' #6: a tunnel that comes back MID-sweep must be
        # caught by the driver run itself. While on the fallback, re-probe
        # between rows (rate-limited, watchdogged); on recovery, splice the
        # cache-warmed measured TPU rows in RIGHT AFTER the current row so
        # they run before the tunnel can flap again.
        if (platform == "cpu" and not recovered
                and recovery_probes < MAX_RECOVERY_PROBES
                and time.time() - last_probe_t > RECOVERY_PROBE_EVERY):
            recovery_probes += 1
            last_probe_t = time.time()
            if quick_probe():
                recovered = True
                platform = "tpu"
                measured = [c for c in tpu_core_configs()
                            if not c.get("force_cpu")]
                configs[i:i] = measured
                note = {"recovery": True, "after_rows": len(sweep),
                        "spliced_rows": [c["name"] for c in measured]}
                _persist_row(note)
                print(f"[bench] tunnel recovered mid-sweep: {json.dumps(note)}",
                      file=sys.stderr)

    print(json.dumps(_summarize(platform, sweep, errors, banked=banked)))


def _load_banked_rows(path: str = None, limit: int = 24) -> list:
    """Completed rows banked in the append-only partial ledger by previous
    sweeps — deduped by config name keeping the newest, error/skip rows
    dropped. Malformed ledger content degrades to no banked evidence."""
    path = path or PARTIAL_PATH
    try:
        with open(path) as f:
            lines = f.readlines()[-600:]
    except OSError:
        return []
    rows = {}
    for line in lines:
        try:
            r = json.loads(line)
        except ValueError:
            continue
        if (not isinstance(r, dict) or "config" not in r or "error" in r
                or r.get("skipped")):
            continue
        rows.pop(r["config"], None)  # re-insert so newest keeps file order
        rows[r["config"]] = r
    return list(rows.values())[-limit:]


# chip-evidence sources, newest first (module-level so tests can pin one)
CHIP_EVIDENCE_SOURCES = [
    (os.path.join(REPO, "window_run_results.json"),
     "window_run_results.json (in-round tunnel-window orchestrator, "
     "scripts/window_run.py)"),
    (os.path.join(REPO, "docs", "CHIP_SESSION_r05.json"),
     "docs/CHIP_SESSION_r05.json (r5 tunnel-window results, "
     "watcher-committed)"),
    (os.path.join(REPO, "docs", "CHIP_SESSION_r04_window1.json"),
     "docs/CHIP_SESSION_r04_window1.json (tunnel window 2026-07-31 "
     "03:45-06:50Z, 10 dispatches/row incl. ~350ms RTT each)"),
]


def _load_chip_evidence(sources=None):
    """Newest chip-measured rows available on disk: this round's tunnel-watch
    orchestrator ledger first (window_run_results.json), else the last
    committed chip-session doc. Returns (rows, source_label, kernel_ok) or
    (None, None, None); kernel_ok is None when the source carries no
    kernel-smoke row (unknown, not failed)."""
    for path, label in (sources or CHIP_EVIDENCE_SOURCES):
        # malformed evidence must degrade to "no evidence", never crash the
        # sweep driver (this runs inside _summarize after EVERY row)
        try:
            with open(path) as f:
                chip = json.load(f)
            if not isinstance(chip, list):
                continue
            rows = []
            for c in chip:
                if not isinstance(c, dict):
                    continue
                res = c.get("result") or {}
                if c.get("rc") != 0 or not isinstance(res, dict):
                    continue
                if res.get("platform") == "cpu":
                    continue  # a fallback row is not chip evidence
                keep = {k: res[k] for k in
                        ("mfu", "step_ms", "tok_s", "tokens_per_sec_chip",
                         "decode_p50_ms", "decode_p90_ms", "tokens_per_sec",
                         "image_ms_p50")
                        if k in res}
                if any(k in keep for k in ("mfu", "decode_p50_ms",
                                           "image_ms_p50")):
                    row = {"tag": c.get("tag", "?"), **keep}
                    if c.get("ts"):  # provenance in multi-window ledgers
                        row["ts"] = c["ts"]
                    rows.append(row)
            if rows:
                kernel_rows = [c for c in chip if isinstance(c, dict)
                               and "kernel" in str(c.get("tag", ""))]
                kernel_ok = (any(c.get("rc") == 0 for c in kernel_rows)
                             if kernel_rows else None)
                return rows, label, kernel_ok
        except (OSError, ValueError, KeyError, TypeError):
            continue
    return None, None, None


def _summarize(platform: str, sweep: list, errors: list,
               banked: list = None) -> dict:
    train_ok = [r for r in sweep if r.get("kind") in ("train", "moe_train")
                and "error" not in r]
    infer_ok = [r for r in sweep if r.get("kind") == "inference" and "error" not in r]
    result = {"platform": platform, "sweep": sweep}
    if banked:
        # prior sweeps' banked evidence (bench_partial.jsonl splice): listed,
        # not ranked — the headline metric stays this run's measurements.
        # Only a real measurement supersedes a banked row: an error or
        # budget-skip this run must not hide the last completed evidence.
        done = {r.get("config") for r in sweep
                if "error" not in r and not r.get("skipped")}
        spliced = [r for r in banked if r.get("config") not in done]
        if spliced:
            result["banked"] = spliced
    if errors:
        result["errors"] = errors[-4:]
    if train_ok:
        best = max(train_ok, key=lambda r: r.get("mfu", 0.0))
        # vs_baseline from the ROW's platform, not the sweep's: a tunnel that
        # recovered mid-sweep yields real TPU rows inside a "cpu" run
        result.update({
            "metric": f"{best['config']} bf16 training tokens/sec/chip",
            "value": best["tokens_per_sec_chip"],
            "unit": "tokens/sec/chip",
            "vs_baseline": (round(best["mfu"] / 0.45, 3)
                            if best.get("platform") not in (None, "cpu")
                            else 0.0),
            "mfu": best["mfu"],
        })
    else:
        result.update({
            "metric": "training throughput (all configs failed)",
            "value": 0.0, "unit": "tokens/sec/chip", "vs_baseline": 0.0,
        })
    if infer_ok:
        result["decode_p50_ms"] = infer_ok[0]["decode_p50_ms"]
        result["decode_tokens_per_sec"] = infer_ok[0].get("tokens_per_sec")
        # the reference's published decode bar, embedded so the artifact is
        # self-describing even when nobody writes the comparison up by hand
        # — only for rows with hardware provenance (a cpu-fallback row must
        # not be described as a chip decode)
        if infer_ok[0].get("platform") not in (None, "cpu"):
            result["decode_reference_bar"] = {
                "zero_inference_opt30b_tok_s": 43,
                "hardware": "1x V100-32GB, full CPU offload",
                "source": "docs/_posts/2022-09-10-zero-inference.md:52",
                "note": ("this row decodes a chip-RESIDENT model on one "
                         "v5e; the reference bar is the host-offload "
                         "regime — compare decode_tokens_per_sec directly")}
    # a measured chip-RESIDENT big-model decode (13B int8 / 20B int4) is its
    # own headline: the reference's answer at this size is host offload
    big = [r for r in infer_ok if r.get("quantize_bits")
           and r.get("platform") not in (None, "cpu")
           and any(m in str(r.get("config", "")) for m in ("13b", "20b"))]
    if big:
        result["resident_big_decode"] = big[0]
    diff_ok = [r for r in sweep if r.get("kind") == "diffusion"
               and "error" not in r]
    if diff_ok:
        result["sd_image_ms_p50"] = diff_ok[0]["image_ms_p50"]
    # compile-only evidence digest: real-v5e-compiler fit verdicts survive in
    # the headline artifact even when the tunnel ate the measured rows
    aot_rows = [r for r in sweep
                if str(r.get("kind", "")).endswith("_aot") and "config" in r]
    if aot_rows:
        result["aot_evidence"] = [
            {"config": r["config"], "kind": r["kind"],
             "fits_v5e_hbm": r.get("fits_v5e_hbm"),
             "peak_bytes": (r.get("per_device_bytes") or {}).get("peak"),
             # margin-aware: "marginal" = compiles but inside the
             # fragmentation margin — a prediction needing runtime confirm
             "fit_confidence": (r.get("fit") or {}).get("confidence"),
             "kernels_ok": (all(k.get("ok") for k in r["kernels"].values())
                            if "kernels" in r else None)}
            for r in aot_rows]
    measured_tpu_train = any(r.get("platform") not in (None, "cpu")
                             for r in train_ok)
    if not measured_tpu_train:
        # No driver-measured TPU train row this run (tunnel outage): attach
        # the newest CHIP-measured rows on disk — this round's tunnel-watch
        # orchestrator ledger if it ran, else the last committed window doc —
        # clearly labeled with their source.
        rows, src, kernel_ok = _load_chip_evidence()
        if rows:
            result["chip_window_evidence"] = {
                "source": src, "rows": rows, "kernel_smoke_ok": kernel_ok}
            train_rows = [r for r in rows if "mfu" in r
                          and ("tok_s" in r or "tokens_per_sec_chip" in r)]
            if train_rows:
                best = max(train_rows, key=lambda r: r["mfu"])
                sweep_note = ("sweep below ran on cpu fallback"
                              if platform == "cpu"
                              else "this tpu sweep's train rows failed")
                result.update({
                    "metric": f"{best['tag']} bf16 training (chip-measured "
                              f"in-round window; {sweep_note})",
                    "value": best.get("tok_s",
                                      best.get("tokens_per_sec_chip")),
                    "unit": "tokens/sec/chip",
                    "mfu": best["mfu"],
                    "vs_baseline": round(best["mfu"] / 0.45, 3),
                })
            dec = next((r for r in rows if "decode_p50_ms" in r), None)
            if dec and "decode_p50_ms" not in result:
                result["decode_p50_ms"] = dec["decode_p50_ms"]
                result["decode_source"] = "chip_window"
            sd = next((r for r in rows if "image_ms_p50" in r), None)
            if sd and "sd_image_ms_p50" not in result:
                result["sd_image_ms_p50"] = sd["image_ms_p50"]
                result["sd_source"] = "chip_window"
    return result


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--worker":
        _worker(json.loads(sys.argv[2]))
    else:
        main()
