// Async file I/O thread pool for ZeRO-Infinity-style swapping.
//
// Capability parity with the reference's AIO stack (csrc/aio/common/
// deepspeed_aio_common.cpp, csrc/aio/py_lib/deepspeed_aio_thread.cpp:84,
// deepspeed_py_aio_handle.cpp:282): a pool of worker threads servicing
// read/write requests against files, with completion tracking, powering
// optimizer-state/param swap to local SSD and async checkpoint writes.
//
// TPU-native framing: on TPU VMs the swap target is the local SSD / ramdisk;
// the host side of ZeRO-Infinity is identical to the GPU case. Plain
// pread/pwrite on the pool (portable; io_uring/libaio are kernel-config
// dependent) — the concurrency model (queue + N workers + wait handles)
// mirrors deepspeed_aio_thread.cpp.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <fcntl.h>
#include <mutex>
#include <string>
#include <thread>
#include <unistd.h>
#include <unordered_set>
#include <vector>

namespace {

struct Request {
  int id;
  bool write;
  std::string path;
  void* buf;
  int64_t nbytes;
  int64_t offset;
  bool fsync;
};

struct Pool {
  std::vector<std::thread> workers;
  std::deque<Request> queue;
  std::mutex mu;
  std::condition_variable cv;
  std::condition_variable done_cv;
  std::atomic<bool> stop{false};
  int next_id = 1;
  // completed request ids with status (0 ok, negative errno); `pending` tracks
  // submitted-but-unfinished ids so wait() can distinguish "still running"
  // from "already completed and its record consumed/discarded"
  std::mutex done_mu;
  std::vector<std::pair<int, int>> done;
  std::unordered_set<int> pending;
  std::atomic<int> inflight{0};

  void push_done(int id, int status) {
    std::lock_guard<std::mutex> g(done_mu);
    pending.erase(id);
    done.emplace_back(id, status);
  }

  // Returns the status (<= 0) if finished, 1 if still pending, -EINVAL if
  // unknown (already waited on, discarded by drain, or never submitted) —
  // callers must hold each id's result exactly once or use drain().
  int take_status(int id) {
    std::lock_guard<std::mutex> g(done_mu);
    for (auto it = done.begin(); it != done.end(); ++it) {
      if (it->first == id) {
        int s = it->second;
        done.erase(it);
        return s;
      }
    }
    return pending.count(id) ? 1 : -EINVAL;
  }
};

int do_io(const Request& r) {
  int flags = r.write ? (O_WRONLY | O_CREAT) : O_RDONLY;
  int fd = ::open(r.path.c_str(), flags, 0644);
  if (fd < 0) return -errno;
  char* p = (char*)r.buf;
  int64_t left = r.nbytes;
  int64_t off = r.offset;
  while (left > 0) {
    ssize_t n = r.write ? ::pwrite(fd, p, left, off) : ::pread(fd, p, left, off);
    if (n < 0) {
      int e = errno;
      ::close(fd);
      return -e;
    }
    if (n == 0) break;  // EOF on read
    p += n;
    left -= n;
    off += n;
  }
  int rc = 0;
  if (left != 0) rc = -EIO;
  if (r.write && r.fsync && rc == 0 && ::fsync(fd) != 0) rc = -errno;
  ::close(fd);
  return rc;
}

void worker(Pool* pool) {
  for (;;) {
    Request r;
    {
      std::unique_lock<std::mutex> lk(pool->mu);
      pool->cv.wait(lk, [&] { return pool->stop || !pool->queue.empty(); });
      if (pool->stop && pool->queue.empty()) return;
      r = pool->queue.front();
      pool->queue.pop_front();
    }
    int rc = do_io(r);
    pool->push_done(r.id, rc);
    pool->inflight.fetch_sub(1);
    pool->done_cv.notify_all();
  }
}

}  // namespace

extern "C" {

void* ds_aio_create(int num_threads) {
  auto* pool = new Pool();
  if (num_threads < 1) num_threads = 1;
  for (int i = 0; i < num_threads; ++i) pool->workers.emplace_back(worker, pool);
  return pool;
}

void ds_aio_destroy(void* h) {
  auto* pool = (Pool*)h;
  {
    std::lock_guard<std::mutex> g(pool->mu);
    pool->stop = true;
  }
  pool->cv.notify_all();
  for (auto& t : pool->workers) t.join();
  delete pool;
}

static int submit(Pool* pool, bool write, const char* path, void* buf,
                  int64_t nbytes, int64_t offset, int fsync) {
  int id;
  {
    std::lock_guard<std::mutex> g(pool->mu);
    id = pool->next_id++;
  }
  // bookkeeping BEFORE the request becomes runnable, or a fast worker could
  // complete it and erase a pending entry that was never inserted
  {
    std::lock_guard<std::mutex> g(pool->done_mu);
    pool->pending.insert(id);
  }
  pool->inflight.fetch_add(1);
  {
    std::lock_guard<std::mutex> g(pool->mu);
    pool->queue.push_back(
        Request{id, write, path, buf, nbytes, offset, fsync != 0});
  }
  pool->cv.notify_one();
  return id;
}

// Submit async ops; returns a request id. The buffer must stay alive until wait.
int ds_aio_pread(void* h, const char* path, void* buf, int64_t nbytes,
                 int64_t offset) {
  return submit((Pool*)h, false, path, buf, nbytes, offset, 0);
}

int ds_aio_pwrite(void* h, const char* path, const void* buf, int64_t nbytes,
                  int64_t offset, int fsync) {
  return submit((Pool*)h, true, path, (void*)buf, nbytes, offset, fsync);
}

// Block until request `id` completes; returns 0 on success, -errno on failure.
int ds_aio_wait(void* h, int id) {
  auto* pool = (Pool*)h;
  for (;;) {
    int s = pool->take_status(id);
    if (s <= 0) return s;
    std::unique_lock<std::mutex> lk(pool->done_mu);
    pool->done_cv.wait_for(lk, std::chrono::milliseconds(50));
  }
}

// Block until every submitted request completes. Discards completion records
// nobody waited on (fire-and-forget writes) so the done list cannot grow
// without bound — but COUNTS discarded failures: returns 0 if everything
// succeeded, -N if N discarded requests had failed since the last drain.
int ds_aio_drain(void* h) {
  auto* pool = (Pool*)h;
  while (pool->inflight.load() > 0) {
    std::unique_lock<std::mutex> lk(pool->done_mu);
    pool->done_cv.wait_for(lk, std::chrono::milliseconds(50));
  }
  int failures = 0;
  {
    std::lock_guard<std::mutex> g(pool->done_mu);
    for (auto& rec : pool->done)
      if (rec.second < 0) ++failures;
    pool->done.clear();
  }
  return -failures;
}

int ds_aio_version() { return 1; }
}
