// Host-side SIMD optimizers for ZeRO-Offload.
//
// Capability parity with the reference's csrc/adam/cpu_adam.cpp (AVX-vectorized
// Adam with async fp16 copy-back, driving ZeRO-Offload) and
// csrc/adagrad/cpu_adagrad.cpp. TPU-native framing: the device computes grads in
// one XLA program; this library performs the optimizer step on the TPU VM's host
// CPU over the fp32 master copy, writing a bf16 view for the device push-back in
// the same pass (the analog of the reference's fp16 copy-back at
// csrc/adam/cpu_adam.cpp:216-239).
//
// Built JIT by deepspeed_tpu/ops/op_builder (g++ -O3 -mavx2 -mfma -fopenmp when
// available; scalar fallback otherwise), loaded via ctypes.

#include <cmath>
#include <cstdint>
#include <cstring>

#if defined(__AVX2__)
#include <immintrin.h>
#endif

extern "C" {

// bf16 = upper half of fp32 with round-to-nearest-even.
static inline uint16_t fp32_to_bf16(float f) {
  uint32_t x;
  memcpy(&x, &f, 4);
  uint32_t lsb = (x >> 16) & 1u;
  x += 0x7fffu + lsb;
  return (uint16_t)(x >> 16);
}

// Fused Adam/AdamW step over a contiguous fp32 span.
//   p, m, v: fp32 master param / first / second moment (updated in place)
//   g:       fp32 gradient
//   bc1/bc2: bias-correction denominators (1 - beta^t), precomputed by caller
//   adamw:   1 = decoupled weight decay, 0 = L2 into the gradient
//   bf16_out: optional bf16 copy-back buffer (may be null)
void ds_adam_step(float* p, float* m, float* v, const float* g, int64_t n,
                  float lr, float beta1, float beta2, float eps, float wd,
                  float bc1, float bc2, int adamw, uint16_t* bf16_out) {
  const float om1 = 1.0f - beta1, om2 = 1.0f - beta2;
  const float inv_bc1 = 1.0f / bc1;
  const float inv_sqrt_bc2 = 1.0f / sqrtf(bc2);

#pragma omp parallel for schedule(static)
  for (int64_t blk = 0; blk < (n + 16383) / 16384; ++blk) {
    int64_t i = blk * 16384;
    int64_t i1 = i + 16384 < n ? i + 16384 : n;

#if defined(__AVX2__) && defined(__FMA__)
    const __m256 vb1 = _mm256_set1_ps(beta1);
    const __m256 vb2 = _mm256_set1_ps(beta2);
    const __m256 vom1 = _mm256_set1_ps(om1);
    const __m256 vom2 = _mm256_set1_ps(om2);
    const __m256 veps = _mm256_set1_ps(eps);
    const __m256 vlr = _mm256_set1_ps(lr);
    const __m256 vwd = _mm256_set1_ps(wd);
    const __m256 vibc1 = _mm256_set1_ps(inv_bc1);
    const __m256 visb2 = _mm256_set1_ps(inv_sqrt_bc2);
    for (; i + 8 <= i1; i += 8) {
      __m256 gi = _mm256_loadu_ps(g + i);
      __m256 pi = _mm256_loadu_ps(p + i);
      if (wd != 0.0f && !adamw) gi = _mm256_fmadd_ps(vwd, pi, gi);
      __m256 mi = _mm256_fmadd_ps(vom1, gi, _mm256_mul_ps(vb1, _mm256_loadu_ps(m + i)));
      __m256 vi = _mm256_fmadd_ps(vom2, _mm256_mul_ps(gi, gi),
                                  _mm256_mul_ps(vb2, _mm256_loadu_ps(v + i)));
      __m256 denom = _mm256_add_ps(
          _mm256_mul_ps(_mm256_sqrt_ps(vi), visb2), veps);
      __m256 upd = _mm256_div_ps(_mm256_mul_ps(mi, vibc1), denom);
      if (wd != 0.0f && adamw) upd = _mm256_fmadd_ps(vwd, pi, upd);
      pi = _mm256_fnmadd_ps(vlr, upd, pi);
      _mm256_storeu_ps(p + i, pi);
      _mm256_storeu_ps(m + i, mi);
      _mm256_storeu_ps(v + i, vi);
    }
#endif
    for (; i < i1; ++i) {
      float gi = g[i];
      float pi = p[i];
      if (wd != 0.0f && !adamw) gi += wd * pi;
      float mi = beta1 * m[i] + om1 * gi;
      float vi = beta2 * v[i] + om2 * gi * gi;
      float upd = (mi * inv_bc1) / (sqrtf(vi) * inv_sqrt_bc2 + eps);
      if (wd != 0.0f && adamw) upd += wd * pi;
      pi -= lr * upd;
      p[i] = pi;
      m[i] = mi;
      v[i] = vi;
    }
    if (bf16_out) {
      for (int64_t j = blk * 16384; j < i1; ++j) bf16_out[j] = fp32_to_bf16(p[j]);
    }
  }
}

// Adagrad step (parity: csrc/adagrad/cpu_adagrad.cpp).
void ds_adagrad_step(float* p, float* a, const float* g, int64_t n, float lr,
                     float eps, float wd, uint16_t* bf16_out) {
#pragma omp parallel for schedule(static)
  for (int64_t blk = 0; blk < (n + 16383) / 16384; ++blk) {
    int64_t i = blk * 16384;
    int64_t i1 = i + 16384 < n ? i + 16384 : n;
    for (; i < i1; ++i) {
      float gi = g[i] + wd * p[i];
      float ai = a[i] + gi * gi;
      float pi = p[i] - lr * gi / (sqrtf(ai) + eps);
      p[i] = pi;
      a[i] = ai;
      if (bf16_out) bf16_out[i] = fp32_to_bf16(pi);
    }
  }
}

// Probe symbol so the builder can verify the load.
int ds_cpu_ops_version() { return 1; }

// Reports whether this build actually used the AVX2+FMA path.
int ds_cpu_ops_simd() {
#if defined(__AVX2__) && defined(__FMA__)
  return 2;
#else
  return 0;
#endif
}
}
