"""runtime.aot: host-side TPU-topology compile reports (the bin/ds_aot core).

These run the REAL XLA TPU compiler on the host (jax.experimental.topologies)
— no accelerator needed — which is exactly the product claim being tested.
"""

import json
import subprocess
import sys

import pytest


@pytest.fixture(scope="module")
def tiny_report():
    from deepspeed_tpu.runtime.aot import train_program_report

    return train_program_report("gpt2-125m", micro_bs=2, seq=256, stage=1)


@pytest.mark.slow
def test_report_fields_and_fit(tiny_report):
    r = tiny_report
    assert r["fits_v5e_hbm"] is True
    pd = r["per_device_bytes"]
    assert pd["peak"] > 0 and pd["arguments"] > 0
    # 125M params: bf16 params + fp32 master + 2x fp32 moments ~ 1.8 GB args
    assert 0.5 * 2**30 < pd["arguments"] < 4 * 2**30
    # analytic (trustworthy) flops: ~6*N*tokens; the raw XLA count is
    # scan-body-once and much lower
    assert r["analytic_flops_per_program"] > 1e11
    assert r["xla_cost_analysis_flops"] > 0
    assert r["topology"] == "v5e:2x2"
    json.dumps(r)


@pytest.mark.slow
def test_k_steps_peak_matches_single_step(tiny_report):
    """train_batches' scan must not grow peak HBM (no cross-step accumulator)
    — the property that made k_steps the dispatch-amortization choice."""
    from deepspeed_tpu.runtime.aot import train_program_report

    r8 = train_program_report("gpt2-125m", micro_bs=2, seq=256, stage=1,
                              k_steps=4)
    assert r8["fits_v5e_hbm"]
    # within 5%: scan bookkeeping only, no extra full-size buffer
    assert r8["per_device_bytes"]["peak"] < \
        tiny_report["per_device_bytes"]["peak"] * 1.05


@pytest.mark.slow
def test_gas_adds_accumulator(tiny_report):
    """gas DOES add a full fp32 grad accumulator across the scan — the
    documented reason bench rows use k_steps instead."""
    from deepspeed_tpu.runtime.aot import train_program_report

    rg = train_program_report("gpt2-125m", micro_bs=2, seq=256, stage=1,
                              gas=4)
    n_param_bytes = 125e6 * 4
    grown = (rg["per_device_bytes"]["peak"]
             - tiny_report["per_device_bytes"]["peak"])
    assert grown > 0.5 * n_param_bytes


@pytest.mark.slow
def test_cli_ds_aot():
    p = subprocess.run(
        [sys.executable, "/root/repo/bin/ds_aot", "--model", "gpt2-125m",
         "--micro-bs", "2", "--seq", "256"],
        capture_output=True, text=True, timeout=900)
    assert p.returncode == 0, p.stderr[-300:]
    rep = json.loads(p.stdout.strip().splitlines()[-1])
    assert rep["fits_v5e_hbm"] is True


@pytest.mark.slow
def test_decode_report():
    from deepspeed_tpu.runtime.aot import decode_program_report

    r = decode_program_report("gpt2-125m", batch=2, prompt=32, gen=8)
    assert r["fits_v5e_hbm"] is True
    # ~2*(non-embedding params) per decode token: 125M total - ~39M embedding
    # tables -> ~172M; require the right order of magnitude
    assert 1e8 < r["flops_per_token"] < 5e8  # from xla count (unrolled-ish here)
    # KV bytes: 2 tensors * L * B * H * S * Dh * 2B
    assert r["kv_cache_bytes"] == 2 * 12 * 2 * 12 * (32 + 8 + 8) * 64 * 2
    json.dumps(r)


@pytest.mark.slow
def test_decode_report_paged_kv8():
    """The serving-shaped paged probe: the quantized pool's bytes are the
    payload actually allocated (int8 + fp32 per-page scales), roughly half
    the dense bf16 pool — what lets the kv-aware ladder admit ~2x."""
    from deepspeed_tpu.runtime.aot import decode_program_report

    rd = decode_program_report("tiny", batch=4, prompt=32, gen=8,
                               page_size=16, paged=True)
    r8 = decode_program_report("tiny", batch=4, prompt=32, gen=8,
                               page_size=16, kv_bits=8)
    assert rd["paged"] and r8["paged"] and r8["kv_bits"] == 8
    assert rd["fits_v5e_hbm"] and r8["fits_v5e_hbm"]
    pages = 4 * (-(-(32 + 8 + 8) // 16)) + 1
    per_tok = 2 * 2 * 4 * 16  # 2 tensors * L * H * Dh (tiny: 2/4/16)
    assert rd["kv_cache_bytes"] == per_tok * pages * 16 * 2  # bf16
    assert r8["kv_cache_bytes"] == (per_tok * pages * 16
                                    + 2 * 2 * 4 * 4 * pages)  # int8+scales
    assert r8["kv_cache_bytes"] < 0.6 * rd["kv_cache_bytes"]
    json.dumps(rd), json.dumps(r8)


@pytest.mark.slow
def test_find_max_batch_ladder():
    from deepspeed_tpu.runtime.aot import find_max_batch

    r = find_max_batch("gpt2-125m", lo=1, hi=4, seq=256, stage=1)
    # tiny model at short seq: everything in [1,4] fits -> ladder tops out
    assert r["max_micro_bs"] == 4
    assert r["report"]["fits_v5e_hbm"] is True
    assert r["trace"][0] == {"micro_bs": 1, "fits": True}


@pytest.mark.slow
def test_sd_report_tiny():
    from deepspeed_tpu.runtime.aot import sd_program_report

    r = sd_program_report(batch=1, latent=16, ddim_steps=2,
                          channels=(32, 64), text_dim=64)
    assert r["fits_v5e_hbm"] is True
    assert r["flops_per_image"] > 0
    json.dumps(r)


@pytest.mark.slow
def test_decode_report_int8_shrinks_arguments():
    from deepspeed_tpu.runtime.aot import decode_program_report

    bf = decode_program_report("gpt2-125m", batch=1, prompt=32, gen=4)
    q8 = decode_program_report("gpt2-125m", batch=1, prompt=32, gen=4,
                               quantize_bits=8)
    assert q8["fits_v5e_hbm"]
    # int8 weight stack (+ scales) must be well under the bf16 arguments
    assert q8["per_device_bytes"]["arguments"] < \
        0.75 * bf["per_device_bytes"]["arguments"]


@pytest.mark.slow
def test_cli_batch_mode(tmp_path):
    specs = tmp_path / "specs.jsonl"
    specs.write_text(
        '{"kind":"train","name":"t","model":"gpt2-125m","micro_bs":2,'
        '"seq":256}\n')
    out = tmp_path / "out.jsonl"
    p = subprocess.run(
        [sys.executable, "/root/repo/bin/ds_aot", "--batch", str(specs),
         "--out", str(out)],
        capture_output=True, text=True, timeout=900)
    assert p.returncode == 0, p.stderr[-300:]
    rows = [json.loads(l) for l in out.read_text().splitlines()]
    assert rows and rows[0]["name"] == "t" and rows[0]["fits_v5e_hbm"]


def test_fit_verdict_margins():
    """VERDICT r4 next #4: no 'fits' within the fragmentation margin of the
    ceiling without an explicit marginal label."""
    from deepspeed_tpu.runtime.aot import fit_verdict

    v = fit_verdict(10e9, hbm_bytes=15.75e9, margin_bytes=1e9)
    assert v["confidence"] == "fits" and "note" not in v
    v = fit_verdict(15.2e9, hbm_bytes=15.75e9, margin_bytes=1e9)
    assert v["confidence"] == "marginal"
    assert "prediction" in v["note"]
    assert v["headroom_bytes"] == int(15.75e9 - 15.2e9)
    v = fit_verdict(16.5e9, hbm_bytes=15.75e9, margin_bytes=1e9)
    assert v["confidence"] == "oom"


@pytest.mark.slow
def test_infinity_program_report_whole_moments():
    """The streaming schedule's peak is compiler-accounted (residents are
    program ARGUMENTS of the compiled moment), not an arithmetic sum."""
    from deepspeed_tpu.runtime.aot import infinity_program_report

    r = infinity_program_report("gpt2-125m", micro_bs=1, seq=128,
                                keep_layers=2)
    assert set(r["moments"]) == {"head_moment", "layer_bwd_moment"}
    assert all(m["ok"] for m in r["moments"].values())
    assert all(p["ok"] for p in r["programs"].values())
    # the whole-moment peak must dominate every single-program peak, and its
    # arguments must cover the resident activation stack + unit window
    assert r["whole_run_peak_bytes"] >= max(
        p["peak"] for p in r["programs"].values())
    lm = r["moments"]["layer_bwd_moment"]
    assert lm["arguments"] > 4 * r["layer_unit_bytes"]  # keep+2 window + acts
    assert r["fit"]["confidence"] in ("fits", "marginal")
    assert r["per_device_bytes"]["peak"] == r["whole_run_peak_bytes"]


@pytest.mark.slow
def test_find_max_decode_batch_ladder(monkeypatch):
    """Binary search over decode batch with compile-time verdicts (the
    serving-capacity analog of find_max_batch); probes are mocked so the
    search logic is tested exactly."""
    from deepspeed_tpu.runtime import aot

    calls = []

    def fake_report(model, *, batch, **kw):
        calls.append(batch)
        return {"fits_v5e_hbm": batch <= 11, "batch": batch}

    monkeypatch.setattr(aot, "decode_program_report", fake_report)
    r = aot.find_max_decode_batch("gpt2-125m", lo=1, hi=32)
    assert r["max_batch"] == 11
    assert r["report"]["batch"] == 11
    assert all(t["fits"] == (t["batch"] <= 11) for t in r["trace"])

    def never_fits(model, *, batch, **kw):
        return {"fits_v5e_hbm": False}

    monkeypatch.setattr(aot, "decode_program_report", never_fits)
    r = aot.find_max_decode_batch("gpt2-125m", lo=1, hi=8)
    assert r["max_batch"] == 0 and r["report"] is None


@pytest.mark.slow
def test_fused_train_step_matches_engine_semantics():
    """Every AOT report compiles runtime/aot.fused_train_step and presents
    its memory/flops as THE engine program's. Pin the semantics: one step of
    the fused function from the engine's own initial state must produce the
    same loss and the same updated master as engine.train_batch."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    import deepspeed_tpu
    from deepspeed_tpu.models import build_gpt
    from deepspeed_tpu.models.gpt import GPTConfig
    from deepspeed_tpu.ops.optimizers import get_optimizer
    from deepspeed_tpu.runtime.aot import fused_train_step

    from deepspeed_tpu.runtime.topology import MeshTopology

    model, _ = build_gpt(GPTConfig(vocab_size=128, n_layer=2, n_head=2,
                                   d_model=32, max_seq_len=32))
    # dp=1: an 8-way grad psum reorders float sums, and first-step Adam
    # amplifies that noise to full +/-lr on near-zero-grad leaves — the
    # semantic pin needs bitwise-comparable reductions
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model,
        topology=MeshTopology.create(dp=1, devices=jax.devices()[:1]),
        config={"train_micro_batch_size_per_gpu": 8,
                "optimizer": {"type": "AdamW",
                              "params": {"lr": 3e-4, "weight_decay": 0.1}},
                "bf16": {"enabled": True},
                "zero_optimization": {"stage": 0},
                "gradient_clipping": 1.0,
                "steps_per_print": 0})
    tmap = jax.tree_util.tree_map
    state0 = {k: tmap(jnp.copy, engine.state[k])
              for k in ("params", "master", "opt")}
    batch = {"input_ids": np.random.default_rng(0).integers(
        0, 128, (8, 32), dtype=np.int32)}

    m = engine.train_batch(batch)
    eng_loss = float(m["loss"])

    step = fused_train_step(model, get_optimizer(
        "AdamW", {"lr": 3e-4, "weight_decay": 0.1}))
    _, new_master, _, loss, _ = jax.jit(step)(
        state0["params"], state0["master"], state0["opt"],
        {"input_ids": jnp.asarray(batch["input_ids"])},
        jax.random.PRNGKey(0))
    assert abs(float(loss) - eng_loss) < 1e-3, (float(loss), eng_loss)
    assert (jax.tree_util.tree_structure(new_master)
            == jax.tree_util.tree_structure(engine.state["master"]))
    for a, b in zip(jax.tree_util.tree_leaves(new_master),
                    jax.tree_util.tree_leaves(engine.state["master"]),
                    strict=True):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-4)
