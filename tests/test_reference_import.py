"""torch-DeepSpeed checkpoint interop (VERDICT r2 'next' #7).

Synthesizes checkpoints in the reference's EXACT on-disk layout (torch-pickled
``mp_rank_XX_model_states.pt`` + per-dp-rank ``*_optim_states.pt`` with flat
fp32 master partitions — the format written by
``/root/reference/deepspeed/runtime/engine.py:3284,3398`` and read back by its
``zero_to_fp32.py``) and asserts our importer reconstructs the exact fp32
weights for ZeRO-1/2, ZeRO-3, and no-ZeRO cases, plus end-to-end import of a
GPT-2-named checkpoint into a runnable model.
"""

import collections
import os

import numpy as np
import pytest

torch = pytest.importorskip("torch")

from deepspeed_tpu.checkpoint.reference_import import (
    get_fp32_state_dict_from_reference_checkpoint,
    load_reference_checkpoint,
)


def _rand_sd(rng, spec):
    return collections.OrderedDict(
        (name, rng.normal(size=shape).astype(np.float32))
        for name, shape in spec)


def _write_model_states(tag_dir, sd, param_groups, stage, buffers=()):
    """param_groups: list of lists of names, defining the group split."""
    os.makedirs(tag_dir, exist_ok=True)
    param_shapes = [
        collections.OrderedDict(
            (name, torch.Size(sd[name].shape)) for name in group)
        for group in param_groups
    ]
    fname = ("zero_pp_rank_0_mp_rank_00_model_states.pt" if stage == 3
             else "mp_rank_00_model_states.pt")
    module = {k: torch.from_numpy(v) for k, v in sd.items()}
    if stage == 3:  # params are placeholders under zero-3; keep buffers real
        module = {k: (module[k] if k in buffers else torch.zeros(1))
                  for k in module}
    torch.save({
        "module": module,
        "buffer_names": list(buffers),
        "param_shapes": param_shapes,
        "ds_version": "0.8.1",
    }, os.path.join(tag_dir, fname))


def _write_zero12(tag_dir, sd, param_groups, world):
    """Per-rank files: each group's flat fp32 vector padded to 2*world and
    split into equal rank partitions (the reference's stage-1/2 layout)."""
    parts_per_rank = [[] for _ in range(world)]
    for group in param_groups:
        flat = np.concatenate([sd[n].reshape(-1) for n in group])
        align = 2 * world
        padded = int(np.ceil(flat.size / align)) * align
        flat = np.pad(flat, (0, padded - flat.size))
        for r, chunk in enumerate(np.split(flat, world)):
            parts_per_rank[r].append(torch.from_numpy(chunk.copy()))
    for r in range(world):
        torch.save({"optimizer_state_dict": {
            "zero_stage": 2,
            "partition_count": world,
            "single_partition_of_fp32_groups": parts_per_rank[r],
        }}, os.path.join(tag_dir, f"zero_pp_rank_{r}_mp_rank_00_optim_states.pt"))


def _write_zero3(tag_dir, sd, param_groups, world):
    """Per-rank files: one flat tensor per group, the rank's ceil(numel/world)
    slice of every param concatenated (the reference's stage-3 layout)."""
    rank_flats = [[[] for _ in param_groups] for _ in range(world)]
    for g, group in enumerate(param_groups):
        for name in group:
            flat = sd[name].reshape(-1)
            pn = -(-flat.size // world)
            padded = np.pad(flat, (0, pn * world - flat.size))
            for r in range(world):
                rank_flats[r][g].append(padded[r * pn:(r + 1) * pn])
    for r in range(world):
        groups = [torch.from_numpy(np.concatenate(chunks))
                  for chunks in rank_flats[r]]
        torch.save({"optimizer_state_dict": {
            "zero_stage": 3,
            "partition_count": world,
            "fp32_flat_groups": groups,
        }}, os.path.join(tag_dir, f"bf16_zero_pp_rank_{r}_mp_rank_00_optim_states.pt"))


def _finish(ckpt_dir, tag):
    with open(os.path.join(ckpt_dir, "latest"), "w") as f:
        f.write(tag)


SPEC = [
    ("embed.weight", (13, 8)),
    ("layer.0.w", (8, 8)),
    ("layer.0.b", (8,)),
    ("layer.1.w", (8, 7)),  # odd sizes exercise the padding paths
    ("head.weight", (7, 5)),
]
GROUPS = [["embed.weight", "layer.0.w", "layer.0.b"],
          ["layer.1.w", "head.weight"]]


@pytest.mark.parametrize("world", [1, 2, 4])
def test_zero2_roundtrip(tmp_path, world):
    rng = np.random.default_rng(world)
    sd = _rand_sd(rng, SPEC)
    tag_dir = str(tmp_path / "global_step5")
    _write_model_states(tag_dir, sd, GROUPS, stage=2)
    _write_zero12(tag_dir, sd, GROUPS, world)
    _finish(str(tmp_path), "global_step5")

    got = get_fp32_state_dict_from_reference_checkpoint(str(tmp_path))
    assert set(got) == set(sd)
    for k in sd:
        np.testing.assert_array_equal(got[k], sd[k])


@pytest.mark.parametrize("world", [2, 3])
def test_zero3_roundtrip(tmp_path, world):
    rng = np.random.default_rng(10 + world)
    sd = _rand_sd(rng, SPEC)
    tag_dir = str(tmp_path / "global_step9")
    _write_model_states(tag_dir, sd, GROUPS, stage=3)
    _write_zero3(tag_dir, sd, GROUPS, world)
    _finish(str(tmp_path), "global_step9")

    got = get_fp32_state_dict_from_reference_checkpoint(str(tmp_path))
    for k in sd:
        np.testing.assert_array_equal(got[k], sd[k])


def test_no_zero_checkpoint(tmp_path):
    rng = np.random.default_rng(0)
    sd = _rand_sd(rng, SPEC)
    tag_dir = str(tmp_path / "epoch1")
    os.makedirs(tag_dir)
    torch.save({"module": {k: torch.from_numpy(v) for k, v in sd.items()},
                "ds_version": "0.8.1"},
               os.path.join(tag_dir, "mp_rank_00_model_states.pt"))
    _finish(str(tmp_path), "epoch1")
    got = get_fp32_state_dict_from_reference_checkpoint(str(tmp_path))
    for k in sd:
        np.testing.assert_array_equal(got[k], sd[k])


def test_incomplete_save_detected(tmp_path):
    rng = np.random.default_rng(0)
    sd = _rand_sd(rng, SPEC)
    tag_dir = str(tmp_path / "global_step1")
    _write_model_states(tag_dir, sd, GROUPS, stage=2)
    _write_zero12(tag_dir, sd, GROUPS, world=4)
    os.remove(os.path.join(tag_dir, "zero_pp_rank_3_mp_rank_00_optim_states.pt"))
    _finish(str(tmp_path), "global_step1")
    with pytest.raises(ValueError, match="incomplete"):
        get_fp32_state_dict_from_reference_checkpoint(str(tmp_path))


def test_gpt2_checkpoint_end_to_end(tmp_path, rng):
    """A ZeRO-2 checkpoint of an HF-GPT-2-named module imports into a runnable
    model whose forward matches the policy applied to the original weights."""
    import jax

    from deepspeed_tpu.models import gpt
    from deepspeed_tpu.module_inject.replace_module import HF_POLICIES

    L, D, H, V, T = 2, 16, 2, 32, 24
    names = (["transformer.wte.weight", "transformer.wpe.weight"]
             + [f"transformer.h.{i}.{p}" for i in range(L) for p in
                ("ln_1.weight", "ln_1.bias", "attn.c_attn.weight",
                 "attn.c_attn.bias", "attn.c_proj.weight", "attn.c_proj.bias",
                 "ln_2.weight", "ln_2.bias", "mlp.c_fc.weight", "mlp.c_fc.bias",
                 "mlp.c_proj.weight", "mlp.c_proj.bias")]
             + ["transformer.ln_f.weight", "transformer.ln_f.bias"])
    shapes = {
        "ln_1.weight": (D,), "ln_1.bias": (D,),
        "attn.c_attn.weight": (D, 3 * D), "attn.c_attn.bias": (3 * D,),
        "attn.c_proj.weight": (D, D), "attn.c_proj.bias": (D,),
        "ln_2.weight": (D,), "ln_2.bias": (D,),
        "mlp.c_fc.weight": (D, 4 * D), "mlp.c_fc.bias": (4 * D,),
        "mlp.c_proj.weight": (4 * D, D), "mlp.c_proj.bias": (D,),
    }
    spec = []
    for n in names:
        if n == "transformer.wte.weight":
            spec.append((n, (V, D)))
        elif n == "transformer.wpe.weight":
            spec.append((n, (T, D)))
        elif n.startswith("transformer.ln_f"):
            spec.append((n, (D,)))
        else:
            spec.append((n, shapes[n.split(".", 3)[-1]]))
    sd = _rand_sd(np.random.default_rng(7), spec)
    sd = {k: (v * 0.05 if v.ndim > 1 else v) for k, v in sd.items()}

    tag_dir = str(tmp_path / "global_step3")
    groups = [[n for n, _ in spec]]
    _write_model_states(tag_dir, collections.OrderedDict(sd), groups, stage=2)
    _write_zero12(tag_dir, sd, groups, world=2)
    _finish(str(tmp_path), "global_step3")

    hf_config = dict(vocab_size=V, n_layer=L, n_head=H, n_embd=D,
                     n_positions=T, layer_norm_epsilon=1e-5,
                     activation_function="gelu_new")
    cfg, params = load_reference_checkpoint(str(tmp_path), hf_config)

    import types

    ref_cfg, ref_params = HF_POLICIES["GPT2LMHeadModel"](
        types.SimpleNamespace(**hf_config), sd)
    assert cfg == ref_cfg
    for a, b in zip(jax.tree_util.tree_leaves(params),
                    jax.tree_util.tree_leaves(ref_params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    ids = rng.integers(0, V, size=(2, 8)).astype(np.int32)
    logits = gpt.forward(cfg, params, np.asarray(ids), train=False)
    assert np.all(np.isfinite(np.asarray(logits)))
