"""Config-system tests: DeepSpeed JSON compatibility + batch triangle."""

import pytest

from deepspeed_tpu.runtime.config import DeepSpeedConfig
from deepspeed_tpu.runtime.zero.config import ZeroStageEnum


def test_batch_triangle_completion():
    c = DeepSpeedConfig.load({"train_batch_size": 32}, world_size=8)
    assert c.train_micro_batch_size_per_gpu == 4
    assert c.gradient_accumulation_steps == 1

    c = DeepSpeedConfig.load(
        {"train_batch_size": 64, "train_micro_batch_size_per_gpu": 2}, world_size=8)
    assert c.gradient_accumulation_steps == 4

    c = DeepSpeedConfig.load(
        {"train_micro_batch_size_per_gpu": 2, "gradient_accumulation_steps": 4},
        world_size=8)
    assert c.train_batch_size == 64


def test_batch_triangle_violation():
    with pytest.raises(ValueError):
        DeepSpeedConfig.load(
            {"train_batch_size": 100, "train_micro_batch_size_per_gpu": 3,
             "gradient_accumulation_steps": 7}, world_size=8)


def test_deepspeed_json_parses():
    """A realistic DeepSpeed config from the wild parses unchanged."""
    ds_json = {
        "train_batch_size": 16,
        "steps_per_print": 2000,
        "optimizer": {
            "type": "Adam",
            "params": {"lr": 0.001, "betas": [0.8, 0.999], "eps": 1e-8,
                       "weight_decay": 3e-7},
        },
        "scheduler": {
            "type": "WarmupLR",
            "params": {"warmup_min_lr": 0, "warmup_max_lr": 0.001,
                       "warmup_num_steps": 1000},
        },
        "gradient_clipping": 1.0,
        "prescale_gradients": False,
        "fp16": {"enabled": True, "loss_scale": 0, "loss_scale_window": 500,
                 "hysteresis": 2, "min_loss_scale": 1, "initial_scale_power": 15},
        "zero_optimization": {
            "stage": 2,
            "allgather_partitions": True,
            "allgather_bucket_size": 2.5e8,
            "overlap_comm": True,
            "reduce_scatter": True,
            "reduce_bucket_size": 5e8,
            "contiguous_gradients": True,
            "cpu_offload": False,
        },
        "wall_clock_breakdown": False,
    }
    c = DeepSpeedConfig.load(ds_json, world_size=8)
    assert c.zero_optimization.stage == ZeroStageEnum.gradients
    assert c.fp16.enabled and c.fp16.dynamic_loss_scale
    assert c.fp16.initial_scale_power == 15
    assert c.optimizer.params["betas"] == [0.8, 0.999]
    assert c.scheduler.type == "WarmupLR"


def test_legacy_cpu_offload_migration():
    c = DeepSpeedConfig.load(
        {"train_batch_size": 8,
         "zero_optimization": {"stage": 2, "cpu_offload": True}}, world_size=8)
    assert c.zero_optimization.offload_optimizer_device == "cpu"


def test_fp16_bf16_exclusive():
    with pytest.raises(ValueError):
        DeepSpeedConfig.load(
            {"train_batch_size": 8, "fp16": {"enabled": True},
             "bf16": {"enabled": True}}, world_size=8)


def test_unknown_key_warns_not_fails():
    c = DeepSpeedConfig.load({"train_batch_size": 8, "bogus_key": 1}, world_size=8)
    assert c.train_batch_size == 8
