"""ZeRO memory estimators + XLA compiled memory analysis."""

import numpy as np

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.runtime.zero import (
    compiled_memory_analysis,
    estimate_zero2_model_states_mem_needs,
    estimate_zero2_model_states_mem_needs_all_live,
    estimate_zero3_model_states_mem_needs,
    estimate_zero3_model_states_mem_needs_all_live,
)
from deepspeed_tpu.runtime.zero.mem_estimator import (
    _largest_layer_of,
    _params_of,
)

import pytest


def test_zero2_math_scales_with_chips():
    n = 1_000_000_000
    host1, chip1 = estimate_zero2_model_states_mem_needs(
        n, num_chips_per_host=4, num_hosts=1, cpu_offload=False)
    host8, chip8 = estimate_zero2_model_states_mem_needs(
        n, num_chips_per_host=4, num_hosts=8, cpu_offload=False)
    # optimizer shard shrinks with the dp extent; replicated bf16+grad doesn't
    assert chip8 < chip1
    assert chip8 >= 6 * n
    # offloaded: device keeps only bf16 params + transient grads
    _, chip_off = estimate_zero2_model_states_mem_needs(
        n, num_chips_per_host=4, num_hosts=1, cpu_offload=True)
    assert chip_off == 6 * n


def test_zero3_math_working_set_is_one_layer():
    n, layer = 1_000_000_000, 50_000_000
    host, chip, largest = estimate_zero3_model_states_mem_needs(
        n, layer, num_chips_per_host=4, num_hosts=8, cpu_offload=False)
    assert largest == 6 * layer
    assert chip == largest + int(18 * n / 32)
    # full offload: chip holds just the gathered layer
    _, chip_full, _ = estimate_zero3_model_states_mem_needs(
        n, layer, cpu_offload=True, cpu_offload_params=True)
    assert chip_full == 6 * layer


def test_counting_helpers_on_stacked_tree():
    tree = {
        "wte": jnp.zeros((100, 8)),
        "blocks": {"qkv_w": jnp.zeros((4, 8, 24)), "mlp_w": jnp.zeros((4, 8, 32))},
    }
    assert _params_of(tree) == 100 * 8 + 4 * 8 * 24 + 4 * 8 * 32
    # per-layer slice: 8*24 + 8*32 = 448; wte = 800 is larger
    assert _largest_layer_of(tree) == 800


def test_all_live_prints_table(capsys):
    from deepspeed_tpu.models import build_gpt
    from deepspeed_tpu.models.gpt import GPTConfig

    model, _ = build_gpt(GPTConfig(
        vocab_size=64, d_model=32, n_layer=2, n_head=2, max_seq_len=16))
    estimate_zero2_model_states_mem_needs_all_live(model, num_chips_per_host=4)
    estimate_zero3_model_states_mem_needs_all_live(model, num_chips_per_host=4)
    out = capsys.readouterr().out
    assert "per chip" in out and "offload_optimizer=True" in out
    assert "largest layer" in out


@pytest.mark.slow
def test_compiled_memory_analysis_exact():
    from deepspeed_tpu.models import build_gpt
    from deepspeed_tpu.models.gpt import GPTConfig

    model, _ = build_gpt(GPTConfig(
        vocab_size=64, d_model=32, n_layer=2, n_head=2, max_seq_len=16))
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model,
        config={
            "train_micro_batch_size_per_gpu": 1,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 2},
            "steps_per_print": 0,
        })
    b = {"input_ids": np.zeros((8, 16), np.int32)}
    ma = compiled_memory_analysis(engine, b)
    if ma is None:  # backend without memory_analysis support
        return
    assert ma.get("temp_size_in_bytes", 0) >= 0
    assert sum(ma.values()) > 0
    # the engine still trains after the AOT lowering (no state was disturbed)
    m = engine.train_batch(b)
    assert np.isfinite(float(m["loss"]))
