"""Paged KV cache: allocator free-list + copy-on-write refcount properties,
prefix-index hash chains, prompt-KV scatter semantics (dense and quantized),
and paged-vs-dense logits equivalence at mixed lengths."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.inference.serving.paging import (PageAllocator,
                                                    PrefixIndex,
                                                    RESERVED_PAGE, pages_for,
                                                    prefix_chain_hashes)
from deepspeed_tpu.models import gpt as G

CFG = G.GPTConfig(vocab_size=64, d_model=32, n_layer=2, n_head=4,
                  max_seq_len=128)


@pytest.fixture(scope="module")
def params():
    return G.init_params(CFG, jax.random.PRNGKey(0))


# ---------------------------------------------------------------- allocator
def test_pages_for():
    assert pages_for(0, 8) == 0
    assert pages_for(1, 8) == 1
    assert pages_for(8, 8) == 1
    assert pages_for(9, 8) == 2


def test_allocator_never_double_allocates():
    """Property test: random alloc/free interleavings never hand out a page
    twice, never lose a page, and never touch the reserved sink."""
    rng = np.random.default_rng(0)
    alloc = PageAllocator(64)
    held = []  # lists of page ids we own
    for _ in range(2000):
        if held and rng.random() < 0.45:
            pages = held.pop(rng.integers(len(held)))
            alloc.free(pages)
        else:
            n = int(rng.integers(1, 6))
            pages = alloc.alloc(n)
            if pages is None:
                assert alloc.free_pages < n  # refusal only under pressure
                continue
            assert len(pages) == n
            held.append(pages)
        outstanding = [p for ps in held for p in ps]
        assert len(outstanding) == len(set(outstanding)), "double allocation"
        assert RESERVED_PAGE not in outstanding
        assert alloc.free_pages + len(outstanding) == 63  # conservation
    for ps in held:
        alloc.free(ps)
    assert alloc.free_pages == 63
    assert alloc.allocated_pages == 0


def test_allocator_free_is_checked():
    alloc = PageAllocator(8)
    pages = alloc.alloc(3)
    alloc.free(pages)
    with pytest.raises(ValueError, match="double-free"):
        alloc.free(pages)
    with pytest.raises(ValueError, match="reserved"):
        alloc.free([RESERVED_PAGE])
    with pytest.raises(ValueError):
        PageAllocator(1)  # nothing left after the sink


def test_allocator_all_or_nothing():
    alloc = PageAllocator(6)  # 5 usable
    assert alloc.alloc(7) is None
    assert alloc.free_pages == 5  # a failed alloc takes nothing
    got = alloc.alloc(5)
    assert got is not None and alloc.free_pages == 0


def test_allocator_audit_conservation():
    """audit() is clean through arbitrary alloc/free churn, and names the
    violated invariant when the ledger is corrupted."""
    rng = np.random.default_rng(3)
    alloc = PageAllocator(32)
    held = []
    for _ in range(300):
        if held and rng.random() < 0.5:
            alloc.free(held.pop(rng.integers(len(held))))
        else:
            pages = alloc.alloc(int(rng.integers(1, 4)))
            if pages is not None:
                held.append(pages)
        rep = alloc.audit()
        assert rep["ok"], rep
        assert rep["free"] + rep["allocated"] == rep["total"] == 31
    assert alloc.allocated_ids == frozenset(p for ps in held for p in ps)

    # corruptions the audit must name: a page leaked out of both sets,
    # a duplicate in the free list, and a page in both sets at once
    a = PageAllocator(8)
    del a._ref[a.alloc(2)[0]]
    rep = a.audit()
    assert not rep["ok"] and any("conservation" in e for e in rep["errors"])
    b = PageAllocator(8)
    b._free.append(b._free[0])
    assert any("duplicate" in e for e in b.audit()["errors"])
    c = PageAllocator(8)
    c._ref[c._free[0]] = 1
    assert any("both free and allocated" in e for e in c.audit()["errors"])


# ------------------------------------------------------------ copy-on-write
def test_cow_share_free_materialize_cycles():
    """Property test: random alloc/share/free/materialize interleavings
    conserve pages, a page only returns to the free list when its LAST
    reference dies, and materialize trades a shared reference for a fresh
    private page."""
    rng = np.random.default_rng(7)
    alloc = PageAllocator(48)
    held = []  # independent references: [pages]
    for _ in range(1500):
        r = rng.random()
        if held and r < 0.30:
            released = alloc.free(held.pop(rng.integers(len(held))))
            for p in released:
                assert alloc.refcount(p) == 0
        elif held and r < 0.55:  # share an existing reference
            ref = held[rng.integers(len(held))]
            alloc.share(ref)
            held.append(list(ref))
        elif held and r < 0.65:  # copy-on-write a random held page
            ref = held[rng.integers(len(held))]
            i = rng.integers(len(ref))
            before = alloc.refcount(ref[i])
            got = alloc.materialize(ref[i])
            if got is None:
                assert alloc.free_pages == 0  # refusal only when empty
            elif before == 1:
                assert got == ref[i]  # already private
            else:
                assert got != ref[i] and alloc.refcount(got) == 1
                assert alloc.refcount(ref[i]) == before - 1
                ref[i] = got
        else:
            pages = alloc.alloc(int(rng.integers(1, 4)))
            if pages is not None:
                held.append(pages)
        rep = alloc.audit()
        assert rep["ok"], rep
        # every held reference is backed by exactly that many refcounts
        from collections import Counter

        want = Counter(p for ref in held for p in ref)
        assert all(alloc.refcount(p) == n for p, n in want.items())
        assert set(want) == set(alloc.allocated_ids)
    for ref in held:
        alloc.free(ref)
    assert alloc.allocated_pages == 0 and alloc.free_pages == 47


def test_cow_double_free_on_shared_pages():
    """A shared page survives its first free (the other holder's reference
    is live) and only over-freeing past the refcount raises."""
    alloc = PageAllocator(8)
    pages = alloc.alloc(2)
    alloc.share(pages)  # refcount 2 on both
    assert alloc.free(pages) == []      # nothing released yet
    assert all(alloc.refcount(p) == 1 for p in pages)
    assert sorted(alloc.free(pages)) == sorted(pages)  # last refs die
    with pytest.raises(ValueError, match="double-free"):
        alloc.free(pages)
    with pytest.raises(ValueError, match="unallocated"):
        alloc.share(pages)
    with pytest.raises(ValueError, match="unallocated"):
        alloc.materialize(pages[0])
    with pytest.raises(ValueError, match="reserved"):
        alloc.share([RESERVED_PAGE])


def test_cow_audit_catches_leaked_refcount():
    """A refcount that leaks to < 1 while the page stays in the allocated
    set must be named by the audit (the bug class where a free path
    decrements without recycling)."""
    alloc = PageAllocator(8)
    p = alloc.alloc(1)[0]
    alloc._ref[p] = 0  # corrupt: allocated but zero references
    rep = alloc.audit()
    assert not rep["ok"]
    assert any("refcount" in e for e in rep["errors"]), rep["errors"]


# ------------------------------------------------------------- prefix index
def test_prefix_chain_hashes_commit_to_whole_prefix():
    ps = 4
    a = prefix_chain_hashes([1, 2, 3, 4, 5, 6, 7, 8], ps)
    b = prefix_chain_hashes([1, 2, 3, 4, 9, 9, 9, 9], ps)
    c = prefix_chain_hashes([0, 2, 3, 4, 5, 6, 7, 8], ps)
    assert len(a) == 2
    assert a[0] == b[0]          # same first block
    assert a[1] != b[1]          # diverging second block
    assert a[0] != c[0]          # block 0 differs -> whole chain differs
    assert a[1] != c[1]          # ... even where block 1's tokens match
    assert prefix_chain_hashes([1, 2, 3], ps) == []  # partial block: none


def test_prefix_index_register_lookup_forget():
    ps = 4
    idx = PrefixIndex(ps)
    prompt = np.arange(10, dtype=np.int32)  # 2 full blocks + partial
    assert idx.lookup(prompt) == []
    idx.register(prompt, [5, 9, 13])  # page 13 covers the partial block:
    assert len(idx) == 2              # never indexed
    assert idx.lookup(prompt) == [5, 9]
    # longest-prefix semantics: same first block, new second block
    other = np.concatenate([prompt[:4], np.full(6, 50, np.int32)])
    assert idx.lookup(other) == [5]
    # first writer wins; a second registration cannot steal the chain
    idx.register(prompt, [21, 22])
    assert idx.lookup(prompt) == [5, 9]
    # forget only invalidates the released page's entry
    idx.forget([9])
    assert idx.lookup(prompt) == [5]
    idx.forget([5])
    assert idx.lookup(prompt) == [] and len(idx) == 0


# ---------------------------------------------------------------- scatter
def test_write_prompt_kv_drops_padding_and_respects_tables(params):
    """Bucket padding past `length` must not touch the pool; valid tokens
    land exactly in the pages the table names."""
    ps, P = 8, 16
    paged = G.init_paged_cache(CFG, P, ps, jnp.float32)
    dense = G.init_cache(CFG, 1, 32, jnp.float32)
    ids = jnp.asarray(np.arange(32, dtype=np.int32)[None] % 64)
    _, dense = G.forward_with_cache(CFG, params, ids, dense)
    table = jnp.asarray(np.array([3, 9, 0, 0], np.int32))
    length = 11  # pages 3 (8 tokens) + 9 (3 tokens)
    out = G.write_prompt_kv(paged, dense, table, jnp.int32(length))
    k_pages = np.asarray(out["k_pages"])  # [L, H, P, ps, Dh]
    k_dense = np.asarray(dense["k"])      # [L, 1, H, S, Dh]
    np.testing.assert_array_equal(k_pages[:, :, 3], k_dense[:, 0, :, :8])
    np.testing.assert_array_equal(k_pages[:, :, 9, :3], k_dense[:, 0, :, 8:11])
    # everything else (including rest of page 9 and the whole pool) untouched
    assert (k_pages[:, :, 9, 3:] == 0).all()
    mask = np.ones(16, bool)
    mask[[3, 9]] = False
    assert (k_pages[:, :, mask] == 0).all()


# ------------------------------------------------------ paged == dense logits
@pytest.mark.parametrize("rotary", [False, True])
def test_paged_decode_logits_match_dense_cache(params, rotary, rng):
    """The paged decode step must reproduce the contiguous-cache decode
    logits at mixed sequence lengths — per row, to fp tolerance."""
    cfg = CFG if not rotary else G.GPTConfig(
        vocab_size=64, d_model=32, n_layer=2, n_head=4, max_seq_len=128,
        rotary=True, rotary_pct=0.5)
    p = params if not rotary else G.init_params(cfg, jax.random.PRNGKey(0))
    B, ps, MP, P = 3, 8, 4, 16
    prompt_lens = [5, 9, 3]
    paged = G.init_paged_cache(cfg, P, ps, jnp.float32)
    tables = np.zeros((B, MP), np.int32)
    free = list(range(1, P))
    lengths = np.zeros(B, np.int32)
    prompts = [rng.integers(0, 64, (n,)).astype(np.int32)
               for n in prompt_lens]
    for b in range(B):
        ids = np.zeros((1, 16), np.int32)
        ids[0, :prompt_lens[b]] = prompts[b]
        dense = G.init_cache(cfg, 1, 16, jnp.float32)
        _, dense = G.forward_with_cache(cfg, p, jnp.asarray(ids), dense)
        for i in range(pages_for(prompt_lens[b] + 4, ps)):
            tables[b, i] = free.pop()
        paged = G.write_prompt_kv(paged, dense, jnp.asarray(tables[b]),
                                  jnp.int32(prompt_lens[b]))
        lengths[b] = prompt_lens[b]

    toks = rng.integers(0, 64, (B, 3)).astype(np.int32)
    paged_logits = []
    for t in range(3):
        lg, paged = G.paged_decode_step(cfg, p, jnp.asarray(toks[:, t]),
                                        paged, jnp.asarray(tables),
                                        jnp.asarray(lengths), impl="gather")
        paged_logits.append(np.asarray(lg))
        lengths += 1

    for b in range(B):
        dense = G.init_cache(cfg, 1, 32, jnp.float32)
        _, dense = G.forward_with_cache(cfg, p, jnp.asarray(prompts[b][None]),
                                        dense)
        for t in range(3):
            lg, dense = G.forward_with_cache(
                cfg, p, jnp.asarray(toks[b:b + 1, t:t + 1]), dense)
            np.testing.assert_allclose(paged_logits[t][b],
                                       np.asarray(lg)[0, 0],
                                       atol=2e-4, rtol=2e-3)


def test_paged_decode_rejects_alibi():
    cfg = G.GPTConfig(vocab_size=32, d_model=16, n_layer=1, n_head=2,
                      alibi=True)
    p = G.init_params(cfg, jax.random.PRNGKey(0))
    paged = G.init_paged_cache(cfg, 4, 8, jnp.float32)
    with pytest.raises(ValueError, match="alibi"):
        G.paged_decode_step(cfg, p, jnp.zeros(2, jnp.int32), paged,
                            jnp.zeros((2, 2), jnp.int32),
                            jnp.zeros(2, jnp.int32))


def test_paged_decode_quantized_stack(params, rng):
    """The int8 weight stack (decode's weight-bandwidth lever) must flow
    through the paged step exactly like the contiguous one: quantized paged
    logits == quantized dense-cache logits."""
    qparams = G.quantize_for_inference(CFG, params, bits=8, group_size=128)
    assert G._is_qleaf(qparams["blocks"]["qkv_w"])  # the stack did quantize
    B, ps, P = 2, 8, 16
    prompts = [rng.integers(0, 64, (6,)).astype(np.int32) for _ in range(B)]
    paged = G.init_paged_cache(CFG, P, ps, jnp.float32)
    tables = np.zeros((B, 4), np.int32)
    free = list(range(1, P))
    for b in range(B):
        ids = np.zeros((1, 8), np.int32)
        ids[0, :6] = prompts[b]
        dense = G.init_cache(CFG, 1, 8, jnp.float32)
        _, dense = G.forward_with_cache(CFG, qparams, jnp.asarray(ids), dense)
        tables[b, 0] = free.pop()
        paged = G.write_prompt_kv(paged, dense, jnp.asarray(tables[b]),
                                  jnp.int32(6))
    lengths = np.full(B, 6, np.int32)
    tok = rng.integers(0, 64, (B,)).astype(np.int32)
    lg, _ = G.paged_decode_step(CFG, qparams, jnp.asarray(tok), paged,
                                jnp.asarray(tables), jnp.asarray(lengths),
                                impl="gather")
    for b in range(B):
        dense = G.init_cache(CFG, 1, 16, jnp.float32)
        _, dense = G.forward_with_cache(CFG, qparams,
                                        jnp.asarray(prompts[b][None]), dense)
        ref, _ = G.forward_with_cache(CFG, qparams,
                                      jnp.asarray(tok[b:b + 1][None]), dense)
        np.testing.assert_allclose(np.asarray(lg)[b], np.asarray(ref)[0, 0],
                                   atol=2e-4, rtol=2e-3)


# ------------------------------------------------ quantized KV pools (kv_bits)
def _dequant_cache(paged, bits):
    """Rebuild a DENSE paged cache from a quantized one's payload — the
    dequantize-then-dense reference the quantized step is judged against."""
    from deepspeed_tpu.ops.pallas.decode_attention import unpack_kv_int4

    def side(pages, scales):
        q = np.asarray(pages)
        if bits == 4:
            q = np.asarray(unpack_kv_int4(jnp.asarray(q)))
        return jnp.asarray(q.astype(np.float32)
                           * np.asarray(scales)[..., None, None])

    return {"k_pages": side(paged["k_pages"], paged["k_scales"]),
            "v_pages": side(paged["v_pages"], paged["v_scales"])}


@pytest.mark.parametrize("bits", [8, 4])
@pytest.mark.parametrize("rotary", [False, True])
@pytest.mark.slow
def test_quantized_paged_decode_matches_dequant_dense(params, rng, bits,
                                                      rotary):
    """The quantized paged step == paged decode over DEQUANTIZED pools, to
    fp tolerance, at mixed per-row lengths ± rotary — the only difference
    between quantized and dense serving is the quantization itself (the
    appended token additionally quantizes in the quantized step, so the
    comparison carries the per-page quantization tolerance)."""
    cfg = CFG if not rotary else G.GPTConfig(
        vocab_size=64, d_model=32, n_layer=2, n_head=4, max_seq_len=128,
        rotary=True, rotary_pct=0.5)
    p = params if not rotary else G.init_params(cfg, jax.random.PRNGKey(0))
    B, ps, MP, P = 3, 8, 4, 16
    prompt_lens = [5, 9, 3]
    paged = G.init_paged_cache(cfg, P, ps, jnp.float32, kv_bits=bits)
    assert paged["k_pages"].dtype == jnp.int8
    assert paged["k_pages"].shape[-1] == (4 if bits == 4 else 8)
    tables = np.zeros((B, MP), np.int32)
    free = list(range(1, P))
    lengths = np.zeros(B, np.int32)
    for b in range(B):
        prompt = rng.integers(0, 64, (prompt_lens[b],)).astype(np.int32)
        ids = np.zeros((1, 16), np.int32)
        ids[0, :prompt_lens[b]] = prompt
        dense = G.init_cache(cfg, 1, 16, jnp.float32)
        _, dense = G.forward_with_cache(cfg, p, jnp.asarray(ids), dense)
        for i in range(pages_for(prompt_lens[b] + 4, ps)):
            tables[b, i] = free.pop()
        paged = G.write_prompt_kv(paged, dense, jnp.asarray(tables[b]),
                                  jnp.int32(prompt_lens[b]))
        lengths[b] = prompt_lens[b]
    toks = rng.integers(0, 64, (B, 3)).astype(np.int32)
    tol = dict(atol=2e-2, rtol=2e-2) if bits == 4 else dict(atol=2e-3,
                                                            rtol=2e-3)
    for t in range(3):
        ref, _ = G.paged_decode_step(cfg, p, jnp.asarray(toks[:, t]),
                                     _dequant_cache(paged, bits),
                                     jnp.asarray(tables),
                                     jnp.asarray(lengths), impl="gather")
        lg, paged = G.paged_decode_step(cfg, p, jnp.asarray(toks[:, t]),
                                        paged, jnp.asarray(tables),
                                        jnp.asarray(lengths), impl="gather")
        np.testing.assert_allclose(np.asarray(lg), np.asarray(ref), **tol)
        # greedy choices must agree — the bar serving equivalence rides on
        np.testing.assert_array_equal(np.argmax(np.asarray(lg), -1),
                                      np.argmax(np.asarray(ref), -1))
        lengths += 1


def test_quantized_scatter_handles_scratch_longer_than_table(params, rng):
    """The quantized scatter must survive a dense scratch spanning MORE
    pages than the block table (the engine's chunked long-prompt path pads
    its scratch to whole prefill chunks, which overshoots max_model_len
    whenever it is not chunk-divisible). Regression: the per-page scale
    scatter used to raise a broadcast error at trace time."""
    ps, P = 8, 16
    paged = G.init_paged_cache(CFG, P, ps, jnp.float32, kv_bits=8)
    dense = G.init_cache(CFG, 1, 32, jnp.float32)  # 4 pages of scratch
    ids = jnp.asarray(rng.integers(0, 64, (1, 32)).astype(np.int32))
    _, dense = G.forward_with_cache(CFG, params, ids, dense)
    table = jnp.asarray(np.array([3, 9], np.int32))  # only 2 table columns
    out = G.write_prompt_kv(paged, dense, table, jnp.int32(12))
    k_dense = np.asarray(dense["k"])
    ks = np.asarray(out["k_scales"])
    kq = np.asarray(out["k_pages"]).astype(np.float32)
    # pages 3 and 9 hold quantized positions 0..11; everything else untouched
    deq3 = kq[:, :, 3] * ks[:, :, 3][..., None, None]
    np.testing.assert_allclose(deq3, k_dense[:, 0, :, :8], atol=3e-2,
                               rtol=3e-2)
    mask = np.ones(P, bool)
    mask[[3, 9]] = False
    assert (np.asarray(out["k_pages"])[:, :, mask] == 0).all()


def test_quantized_append_grows_scale_without_clipping(params):
    """A decode append whose K/V absmax exceeds the page's prefill-time
    scale must GROW the scale (requantizing the page) instead of clipping
    the new token — the scale monotonically covers every token written."""
    cfg = CFG
    ps, P = 8, 8
    paged = G.init_paged_cache(cfg, P, ps, jnp.float32, kv_bits=8)
    # page 1 starts with a tiny-scale fill: scatter a 1-token prompt
    dense = G.init_cache(cfg, 1, 8, jnp.float32)
    ids = jnp.zeros((1, 8), jnp.int32)
    _, dense = G.forward_with_cache(cfg, params, ids, dense)
    table = jnp.asarray(np.array([1, 0], np.int32))
    paged = G.write_prompt_kv(paged, dense, table, jnp.int32(1))
    s_before = np.asarray(paged["k_scales"])[:, :, 1].copy()
    # one decode step appends token KV into page 1 at offset 1
    lg, paged2 = G.paged_decode_step(
        cfg, params, jnp.asarray(np.array([13], np.int32)), paged,
        table[None], jnp.asarray(np.array([1], np.int32)), impl="gather")
    s_after = np.asarray(paged2["k_scales"])[:, :, 1]
    assert (s_after >= s_before - 1e-7).all()  # scales never shrink
    assert np.isfinite(np.asarray(lg)).all()


def test_quantized_append_resets_scale_when_opening_a_page(params, rng):
    """A decode token OPENING a fresh page (page-aligned context) must
    establish the page scale from its own absmax — not max() against the
    pool's garbage there (the 1.0 init, or a recycled page's previous
    tenant). Regression: a page-aligned prompt used to decode its first
    tokens at scale >= 1.0, quantizing K/V of magnitude ~0.1-1 to {-1,0,1}
    and flipping greedy argmax."""
    cfg = CFG
    ps, P = 8, 16
    prompt = rng.integers(0, 64, (8,)).astype(np.int32)  # exactly one page
    paged = G.init_paged_cache(cfg, P, ps, jnp.float32, kv_bits=8)
    # poison page 2's scale as if a previous tenant left a huge value
    paged["k_scales"] = paged["k_scales"].at[:, :, 2].set(37.0)
    paged["v_scales"] = paged["v_scales"].at[:, :, 2].set(37.0)
    dense = G.init_cache(cfg, 1, 8, jnp.float32)
    _, dense = G.forward_with_cache(cfg, params, jnp.asarray(prompt[None]),
                                    dense)
    tables = np.array([[1, 2, 0, 0]], np.int32)
    paged = G.write_prompt_kv(paged, dense, jnp.asarray(tables[0]),
                              jnp.int32(8))
    lengths = np.array([8], np.int32)
    toks = rng.integers(0, 64, (4,)).astype(np.int32)
    for t in range(4):
        ref, _ = G.paged_decode_step(cfg, params, jnp.asarray(toks[t:t + 1]),
                                     _dequant_cache(paged, 8),
                                     jnp.asarray(tables),
                                     jnp.asarray(lengths), impl="gather")
        lg, paged = G.paged_decode_step(cfg, params, jnp.asarray(toks[t:t + 1]),
                                        paged, jnp.asarray(tables),
                                        jnp.asarray(lengths), impl="gather")
        np.testing.assert_array_equal(np.argmax(np.asarray(lg), -1),
                                      np.argmax(np.asarray(ref), -1))
        lengths += 1
    # the opened page's scales were re-established from real tokens, not
    # inherited: far below both the poison and the 1.0 init ceiling
    k_s = np.asarray(paged["k_scales"])[:, :, 2]
    assert (k_s < 1.0).all(), k_s.max()
    assert np.isfinite(np.asarray(lg)).all()


def test_scatter_start_skips_shared_prefix_pages(params, rng):
    """write_prompt_kv with ``start`` must leave pages below the start
    position untouched (they are BORROWED shared-prefix pages) and place
    positions >= start exactly as a start-less scatter would."""
    ps, P = 8, 16
    paged = G.init_paged_cache(CFG, P, ps, jnp.float32)
    # pre-poison page 3 so an illegal write would be visible
    poison = jnp.full((2, 4, ps, 8), 7.0, jnp.float32)  # [L, H, ps, Dh]
    paged["k_pages"] = paged["k_pages"].at[:, :, 3].set(poison)
    dense = G.init_cache(CFG, 1, 32, jnp.float32)
    ids = jnp.asarray(rng.integers(0, 64, (1, 32)).astype(np.int32))
    _, dense = G.forward_with_cache(CFG, params, ids, dense)
    table = jnp.asarray(np.array([3, 9, 11, 0], np.int32))
    out = G.write_prompt_kv(paged, dense, table, jnp.int32(20),
                            start=jnp.int32(8))
    k_pages = np.asarray(out["k_pages"])
    k_dense = np.asarray(dense["k"])
    # page 3 (positions 0..7, below start) keeps its poison bytes
    np.testing.assert_array_equal(k_pages[:, :, 3], np.asarray(poison))
    # pages 9/11 hold positions 8..19 exactly
    np.testing.assert_array_equal(k_pages[:, :, 9], k_dense[:, 0, :, 8:16])
    np.testing.assert_array_equal(k_pages[:, :, 11, :4],
                                  k_dense[:, 0, :, 16:20])


def test_batch_scatter_matches_serial(params, rng):
    """write_prompt_kv_batch == per-row write_prompt_kv (the admission-batch
    prefill path must place identical bytes)."""
    ps, P, F, S = 8, 32, 3, 16
    dense = G.init_cache(CFG, F, S, jnp.float32)
    ids = jnp.asarray(rng.integers(0, 64, (F, S)).astype(np.int32))
    _, dense = G.forward_with_cache(CFG, params, ids, dense)
    lengths = np.array([5, 16, 1], np.int32)
    tables = np.zeros((F, 2), np.int32)
    free = list(range(1, P))
    for f in range(F):
        for i in range(pages_for(int(lengths[f]), ps)):
            tables[f, i] = free.pop()
    batch = G.write_prompt_kv_batch(
        G.init_paged_cache(CFG, P, ps, jnp.float32), dense,
        jnp.asarray(tables), jnp.asarray(lengths))
    serial = G.init_paged_cache(CFG, P, ps, jnp.float32)
    for f in range(F):
        serial = G.write_prompt_kv(serial, dense, jnp.asarray(tables[f]),
                                   jnp.int32(lengths[f]), row=f)
    np.testing.assert_array_equal(np.asarray(batch["k_pages"]),
                                  np.asarray(serial["k_pages"]))
    np.testing.assert_array_equal(np.asarray(batch["v_pages"]),
                                  np.asarray(serial["v_pages"]))