"""Paged KV cache: allocator free-list properties, prompt-KV scatter
semantics, and paged-vs-dense logits equivalence at mixed lengths."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.inference.serving.paging import (PageAllocator,
                                                    RESERVED_PAGE, pages_for)
from deepspeed_tpu.models import gpt as G

CFG = G.GPTConfig(vocab_size=64, d_model=32, n_layer=2, n_head=4,
                  max_seq_len=128)


@pytest.fixture(scope="module")
def params():
    return G.init_params(CFG, jax.random.PRNGKey(0))


# ---------------------------------------------------------------- allocator
def test_pages_for():
    assert pages_for(0, 8) == 0
    assert pages_for(1, 8) == 1
    assert pages_for(8, 8) == 1
    assert pages_for(9, 8) == 2


def test_allocator_never_double_allocates():
    """Property test: random alloc/free interleavings never hand out a page
    twice, never lose a page, and never touch the reserved sink."""
    rng = np.random.default_rng(0)
    alloc = PageAllocator(64)
    held = []  # lists of page ids we own
    for _ in range(2000):
        if held and rng.random() < 0.45:
            pages = held.pop(rng.integers(len(held)))
            alloc.free(pages)
        else:
            n = int(rng.integers(1, 6))
            pages = alloc.alloc(n)
            if pages is None:
                assert alloc.free_pages < n  # refusal only under pressure
                continue
            assert len(pages) == n
            held.append(pages)
        outstanding = [p for ps in held for p in ps]
        assert len(outstanding) == len(set(outstanding)), "double allocation"
        assert RESERVED_PAGE not in outstanding
        assert alloc.free_pages + len(outstanding) == 63  # conservation
    for ps in held:
        alloc.free(ps)
    assert alloc.free_pages == 63
    assert alloc.allocated_pages == 0


def test_allocator_free_is_checked():
    alloc = PageAllocator(8)
    pages = alloc.alloc(3)
    alloc.free(pages)
    with pytest.raises(ValueError, match="double-free"):
        alloc.free(pages)
    with pytest.raises(ValueError, match="reserved"):
        alloc.free([RESERVED_PAGE])
    with pytest.raises(ValueError):
        PageAllocator(1)  # nothing left after the sink


def test_allocator_all_or_nothing():
    alloc = PageAllocator(6)  # 5 usable
    assert alloc.alloc(7) is None
    assert alloc.free_pages == 5  # a failed alloc takes nothing
    got = alloc.alloc(5)
    assert got is not None and alloc.free_pages == 0


def test_allocator_audit_conservation():
    """audit() is clean through arbitrary alloc/free churn, and names the
    violated invariant when the ledger is corrupted."""
    rng = np.random.default_rng(3)
    alloc = PageAllocator(32)
    held = []
    for _ in range(300):
        if held and rng.random() < 0.5:
            alloc.free(held.pop(rng.integers(len(held))))
        else:
            pages = alloc.alloc(int(rng.integers(1, 4)))
            if pages is not None:
                held.append(pages)
        rep = alloc.audit()
        assert rep["ok"], rep
        assert rep["free"] + rep["allocated"] == rep["total"] == 31
    assert alloc.allocated_ids == frozenset(p for ps in held for p in ps)

    # corruptions the audit must name: a page leaked out of both sets,
    # a duplicate in the free list, and a page in both sets at once
    a = PageAllocator(8)
    a._allocated.discard(a.alloc(2)[0])
    rep = a.audit()
    assert not rep["ok"] and any("conservation" in e for e in rep["errors"])
    b = PageAllocator(8)
    b._free.append(b._free[0])
    assert any("duplicate" in e for e in b.audit()["errors"])
    c = PageAllocator(8)
    c._allocated.add(c._free[0])
    assert any("both free and allocated" in e for e in c.audit()["errors"])


# ---------------------------------------------------------------- scatter
def test_write_prompt_kv_drops_padding_and_respects_tables(params):
    """Bucket padding past `length` must not touch the pool; valid tokens
    land exactly in the pages the table names."""
    ps, P = 8, 16
    paged = G.init_paged_cache(CFG, P, ps, jnp.float32)
    dense = G.init_cache(CFG, 1, 32, jnp.float32)
    ids = jnp.asarray(np.arange(32, dtype=np.int32)[None] % 64)
    _, dense = G.forward_with_cache(CFG, params, ids, dense)
    table = jnp.asarray(np.array([3, 9, 0, 0], np.int32))
    length = 11  # pages 3 (8 tokens) + 9 (3 tokens)
    out = G.write_prompt_kv(paged, dense, table, jnp.int32(length))
    k_pages = np.asarray(out["k_pages"])  # [L, H, P, ps, Dh]
    k_dense = np.asarray(dense["k"])      # [L, 1, H, S, Dh]
    np.testing.assert_array_equal(k_pages[:, :, 3], k_dense[:, 0, :, :8])
    np.testing.assert_array_equal(k_pages[:, :, 9, :3], k_dense[:, 0, :, 8:11])
    # everything else (including rest of page 9 and the whole pool) untouched
    assert (k_pages[:, :, 9, 3:] == 0).all()
    mask = np.ones(16, bool)
    mask[[3, 9]] = False
    assert (k_pages[:, :, mask] == 0).all()


# ------------------------------------------------------ paged == dense logits
@pytest.mark.parametrize("rotary", [False, True])
def test_paged_decode_logits_match_dense_cache(params, rotary, rng):
    """The paged decode step must reproduce the contiguous-cache decode
    logits at mixed sequence lengths — per row, to fp tolerance."""
    cfg = CFG if not rotary else G.GPTConfig(
        vocab_size=64, d_model=32, n_layer=2, n_head=4, max_seq_len=128,
        rotary=True, rotary_pct=0.5)
    p = params if not rotary else G.init_params(cfg, jax.random.PRNGKey(0))
    B, ps, MP, P = 3, 8, 4, 16
    prompt_lens = [5, 9, 3]
    paged = G.init_paged_cache(cfg, P, ps, jnp.float32)
    tables = np.zeros((B, MP), np.int32)
    free = list(range(1, P))
    lengths = np.zeros(B, np.int32)
    prompts = [rng.integers(0, 64, (n,)).astype(np.int32)
               for n in prompt_lens]
    for b in range(B):
        ids = np.zeros((1, 16), np.int32)
        ids[0, :prompt_lens[b]] = prompts[b]
        dense = G.init_cache(cfg, 1, 16, jnp.float32)
        _, dense = G.forward_with_cache(cfg, p, jnp.asarray(ids), dense)
        for i in range(pages_for(prompt_lens[b] + 4, ps)):
            tables[b, i] = free.pop()
        paged = G.write_prompt_kv(paged, dense, jnp.asarray(tables[b]),
                                  jnp.int32(prompt_lens[b]))
        lengths[b] = prompt_lens[b]

    toks = rng.integers(0, 64, (B, 3)).astype(np.int32)
    paged_logits = []
    for t in range(3):
        lg, paged = G.paged_decode_step(cfg, p, jnp.asarray(toks[:, t]),
                                        paged, jnp.asarray(tables),
                                        jnp.asarray(lengths), impl="gather")
        paged_logits.append(np.asarray(lg))
        lengths += 1

    for b in range(B):
        dense = G.init_cache(cfg, 1, 32, jnp.float32)
        _, dense = G.forward_with_cache(cfg, p, jnp.asarray(prompts[b][None]),
                                        dense)
        for t in range(3):
            lg, dense = G.forward_with_cache(
                cfg, p, jnp.asarray(toks[b:b + 1, t:t + 1]), dense)
            np.testing.assert_allclose(paged_logits[t][b],
                                       np.asarray(lg)[0, 0],
                                       atol=2e-4, rtol=2e-3)


def test_paged_decode_rejects_alibi():
    cfg = G.GPTConfig(vocab_size=32, d_model=16, n_layer=1, n_head=2,
                      alibi=True)
    p = G.init_params(cfg, jax.random.PRNGKey(0))
    paged = G.init_paged_cache(cfg, 4, 8, jnp.float32)
    with pytest.raises(ValueError, match="alibi"):
        G.paged_decode_step(cfg, p, jnp.zeros(2, jnp.int32), paged,
                            jnp.zeros((2, 2), jnp.int32),
                            jnp.zeros(2, jnp.int32))


def test_paged_decode_quantized_stack(params, rng):
    """The int8 weight stack (decode's weight-bandwidth lever) must flow
    through the paged step exactly like the contiguous one: quantized paged
    logits == quantized dense-cache logits."""
    qparams = G.quantize_for_inference(CFG, params, bits=8, group_size=128)
    assert G._is_qleaf(qparams["blocks"]["qkv_w"])  # the stack did quantize
    B, ps, P = 2, 8, 16
    prompts = [rng.integers(0, 64, (6,)).astype(np.int32) for _ in range(B)]
    paged = G.init_paged_cache(CFG, P, ps, jnp.float32)
    tables = np.zeros((B, 4), np.int32)
    free = list(range(1, P))
    for b in range(B):
        ids = np.zeros((1, 8), np.int32)
        ids[0, :6] = prompts[b]
        dense = G.init_cache(CFG, 1, 8, jnp.float32)
        _, dense = G.forward_with_cache(CFG, qparams, jnp.asarray(ids), dense)
        tables[b, 0] = free.pop()
        paged = G.write_prompt_kv(paged, dense, jnp.asarray(tables[b]),
                                  jnp.int32(6))
    lengths = np.full(B, 6, np.int32)
    tok = rng.integers(0, 64, (B,)).astype(np.int32)
    lg, _ = G.paged_decode_step(CFG, qparams, jnp.asarray(tok), paged,
                                jnp.asarray(tables), jnp.asarray(lengths),
                                impl="gather")
    for b in range(B):
        dense = G.init_cache(CFG, 1, 16, jnp.float32)
        _, dense = G.forward_with_cache(CFG, qparams,
                                        jnp.asarray(prompts[b][None]), dense)
        ref, _ = G.forward_with_cache(CFG, qparams,
                                      jnp.asarray(tok[b:b + 1][None]), dense)
        np.testing.assert_allclose(np.asarray(lg)[b], np.asarray(ref)[0, 0],
                                   atol=2e-4, rtol=2e-3)


def test_batch_scatter_matches_serial(params, rng):
    """write_prompt_kv_batch == per-row write_prompt_kv (the admission-batch
    prefill path must place identical bytes)."""
    ps, P, F, S = 8, 32, 3, 16
    dense = G.init_cache(CFG, F, S, jnp.float32)
    ids = jnp.asarray(rng.integers(0, 64, (F, S)).astype(np.int32))
    _, dense = G.forward_with_cache(CFG, params, ids, dense)
    lengths = np.array([5, 16, 1], np.int32)
    tables = np.zeros((F, 2), np.int32)
    free = list(range(1, P))
    for f in range(F):
        for i in range(pages_for(int(lengths[f]), ps)):
            tables[f, i] = free.pop()
    batch = G.write_prompt_kv_batch(
        G.init_paged_cache(CFG, P, ps, jnp.float32), dense,
        jnp.asarray(tables), jnp.asarray(lengths))
    serial = G.init_paged_cache(CFG, P, ps, jnp.float32)
    for f in range(F):
        serial = G.write_prompt_kv(serial, dense, jnp.asarray(tables[f]),
                                   jnp.int32(lengths[f]), row=f)
    np.testing.assert_array_equal(np.asarray(batch["k_pages"]),
                                  np.asarray(serial["k_pages"]))
    np.testing.assert_array_equal(np.asarray(batch["v_pages"]),
                                  np.asarray(serial["v_pages"]))