"""Quantization ops + compression-in-training (MoQ/pruning) + inference quant."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.compression import (
    CompressionScheduler,
    init_compression,
    quantize_params_for_inference,
)
from deepspeed_tpu.compression.compress import _prune_l1, layer_reduction_map
from deepspeed_tpu.ops.quantizer import dequantize, fake_quant, quantize


# ------------------------------------------------------------------- quant ops
def test_quantize_dequantize_roundtrip_error_bounded(rng):
    x = jnp.asarray(rng.normal(size=(64, 32)), jnp.float32)
    q, s = quantize(x, bits=8, num_groups=8)
    assert q.dtype == jnp.int8 and s.shape == (8,)
    xr = dequantize(q, s)
    # int8 symmetric: error bounded by scale/2 per group
    err = np.abs(np.asarray(xr - x))
    bound = np.repeat(np.asarray(s) / 2, x.size // 8).reshape(x.shape)
    assert (err <= bound + 1e-6).all()


def test_quantize_preserves_zero_and_extremes():
    x = jnp.asarray([[0.0, 1.0, -1.0, 0.5]], jnp.float32)
    q, s = quantize(x, bits=8, num_groups=1)
    xr = np.asarray(dequantize(q, s))
    assert xr[0, 0] == 0.0
    np.testing.assert_allclose(xr[0, 1], 1.0, rtol=1e-2)
    np.testing.assert_allclose(xr[0, 2], -1.0, rtol=1e-2)


def test_fake_quant_straight_through_gradient(rng):
    x = jnp.asarray(rng.normal(size=(32,)), jnp.float32)

    def loss(x):
        return (fake_quant(x, 8, 1) ** 2).sum()

    g = jax.grad(loss)(x)
    # STE: grad flows as if identity around the quantizer; d/dx (q(x))^2 = 2*q(x)
    np.testing.assert_allclose(np.asarray(g), 2 * np.asarray(fake_quant(x, 8, 1)),
                               rtol=1e-5)


def test_lower_bits_higher_error(rng):
    x = jnp.asarray(rng.normal(size=(256,)), jnp.float32)
    e8 = float(jnp.abs(fake_quant(x, 8, 1) - x).mean())
    e4 = float(jnp.abs(fake_quant(x, 4, 1) - x).mean())
    e2 = float(jnp.abs(fake_quant(x, 2, 1) - x).mean())
    assert e8 < e4 < e2


# ------------------------------------------------------------------- pruning
def test_prune_l1_density(rng):
    x = jnp.asarray(rng.normal(size=(100,)), jnp.float32)
    xp = _prune_l1(x, 0.3)
    nnz = int((np.asarray(xp) != 0).sum())
    assert nnz == 30
    # survivors are the largest-magnitude entries
    kept = np.abs(np.asarray(x))[np.asarray(xp) != 0]
    dropped = np.abs(np.asarray(x))[np.asarray(xp) == 0]
    assert kept.min() >= dropped.max() - 1e-6


def test_layer_reduction_map():
    assert layer_reduction_map(12, 4) == [0, 4, 7, 11]
    assert layer_reduction_map(12, 1) == [11]
    assert layer_reduction_map(6, 3, teacher_layer=[1, 3, 5]) == [1, 3, 5]


# ------------------------------------------------------------------- scheduler
def _param_tree(rng):
    return {
        "blocks": {"qkv_w": jnp.asarray(rng.normal(size=(4, 8, 8)), jnp.float32)},
        "wte": jnp.asarray(rng.normal(size=(16, 8)), jnp.float32),
        "lnf_scale": jnp.ones((8,), jnp.float32),
    }


def test_scheduler_plans_matmul_weights_only(rng):
    tree = _param_tree(rng)
    sched = CompressionScheduler({
        "weight_quantization": {
            "shared_parameters": {"enabled": True, "schedule_offset": 5},
            "different_groups": {"g0": {"params": {"start_bits": 8,
                                                   "quantize_groups": 4}}},
        }}, tree)
    assert sched.enabled
    assert "blocks/qkv_w" in sched.plan
    assert "wte" not in sched.plan  # embedding excluded
    assert "lnf_scale" not in sched.plan  # 1-D excluded


def test_scheduler_gates_on_step(rng):
    tree = _param_tree(rng)
    sched = CompressionScheduler({
        "weight_quantization": {
            "shared_parameters": {"enabled": True, "schedule_offset": 5},
            "different_groups": {}}}, tree)
    before = sched.transform(tree, jnp.int32(0))
    np.testing.assert_array_equal(np.asarray(before["blocks"]["qkv_w"]),
                                  np.asarray(tree["blocks"]["qkv_w"]))
    after = sched.transform(tree, jnp.int32(10))
    assert not np.array_equal(np.asarray(after["blocks"]["qkv_w"]),
                              np.asarray(tree["blocks"]["qkv_w"]))


# ------------------------------------------------------------------- engine
@pytest.mark.slow
def test_engine_qat_trains():
    from deepspeed_tpu.models import build_gpt
    from deepspeed_tpu.models.gpt import GPTConfig

    model, cfg = build_gpt(GPTConfig(
        vocab_size=64, d_model=32, n_layer=1, n_head=2, max_seq_len=16))
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model,
        config={
            "train_micro_batch_size_per_gpu": 1,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "compression_training": {
                "weight_quantization": {
                    "shared_parameters": {"enabled": True, "schedule_offset": 2},
                    "different_groups": {
                        "g0": {"params": {"start_bits": 8, "quantize_groups": 1}}},
                }},
            "steps_per_print": 0,
        })
    assert engine._compression is not None
    r = np.random.default_rng(0)
    b = {"input_ids": r.integers(0, 64, size=(8, 16), dtype=np.int32)}
    losses = [float(engine.train_batch(b)["loss"]) for _ in range(6)]
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]  # still learns through quantization


# ------------------------------------------------------------------- inference quant
def test_quantize_params_for_inference(rng):
    tree = _param_tree(rng)
    qtree, scales, meta = quantize_params_for_inference(tree, bits=8, num_groups=4)
    assert qtree["blocks"]["qkv_w"].dtype == jnp.int8
    assert qtree["wte"].dtype == jnp.float32  # excluded stays
    assert meta["quantized"] == ["blocks/qkv_w"]
    deq = meta["dequantize"](dtype=jnp.float32)
    np.testing.assert_allclose(np.asarray(deq["blocks"]["qkv_w"]),
                               np.asarray(tree["blocks"]["qkv_w"]), atol=0.05)


# ------------------------------------------------------- progressive MoQ anneal
def test_annealed_bits_drop_points():
    from deepspeed_tpu.ops.quantizer import annealed_bits

    # period 4, factor 1: drops at t=4, 8, 16 (doubling), clamped at target
    for t, want in ((0, 8), (3, 8), (4, 7), (7, 7), (8, 6), (15, 6), (16, 5),
                    (1000, 5)):
        got = float(annealed_bits(t, 8, 5, 4, 1.0))
        assert got == want, (t, got, want)
    # factor 5 (max curvature): first drop still at period, then 10x spacing
    assert float(annealed_bits(4, 8, 5, 4, 5.0)) == 7
    assert float(annealed_bits(39, 8, 5, 4, 5.0)) == 7
    assert float(annealed_bits(40, 8, 5, 4, 5.0)) == 6
    # per-layer vector factor broadcasts
    out = np.asarray(annealed_bits(8, 8, 5, 4, jnp.asarray([1.0, 5.0])))
    np.testing.assert_array_equal(out, [6.0, 7.0])
    # no-anneal config is exact
    assert float(annealed_bits(10_000, 8, 8, 4, 1.0)) == 8


def test_fake_quant_dynamic_matches_static_and_coarsens(rng):
    from deepspeed_tpu.ops.quantizer import fake_quant, fake_quant_dynamic

    x = jnp.asarray(rng.normal(size=(4, 64)), jnp.float32)
    np.testing.assert_allclose(
        np.asarray(fake_quant_dynamic(x, jnp.float32(8.0), 4)),
        np.asarray(fake_quant(x, 8, 4)), rtol=1e-6)
    err8 = float(jnp.abs(fake_quant_dynamic(x, jnp.float32(8.0), 4) - x).mean())
    err4 = float(jnp.abs(fake_quant_dynamic(x, jnp.float32(4.0), 4) - x).mean())
    assert err4 > err8 > 0
    # per-layer bits: layer 0 at 8 bits must be finer than layer 1 at 3 bits
    out = fake_quant_dynamic(x.reshape(2, 2, 64),
                             jnp.asarray([8.0, 3.0]), 2)
    e0 = float(jnp.abs(out[0] - x.reshape(2, 2, 64)[0]).mean())
    e1 = float(jnp.abs(out[1] - x.reshape(2, 2, 64)[1]).mean())
    assert e1 > e0
    # straight-through gradient
    g = jax.grad(lambda t: fake_quant_dynamic(t, jnp.float32(6.0), 4).sum())(x)
    np.testing.assert_array_equal(np.asarray(g), np.ones_like(np.asarray(g)))


def test_engine_progressive_anneal_trains():
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import build_gpt
    from deepspeed_tpu.models.gpt import GPTConfig

    model, _ = build_gpt(GPTConfig(
        vocab_size=64, d_model=32, n_layer=1, n_head=2, max_seq_len=16))
    engine, _, _, _ = ds.initialize(
        model=model,
        config={
            "train_micro_batch_size_per_gpu": 1,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "compression_training": {
                "weight_quantization": {
                    "shared_parameters": {"enabled": True, "schedule_offset": 1},
                    "different_groups": {
                        "g0": {"params": {"start_bits": 12, "target_bits": 8,
                                          "quantization_period": 2,
                                          "quantize_groups": 1}}},
                }},
            "steps_per_print": 0,
        })
    sched = engine._compression
    entry = next(iter(sched.plan.values()))
    assert entry["quant_target_bits"] == 8 and entry["quant_period"] == 2
    r = np.random.default_rng(0)
    b = {"input_ids": r.integers(0, 64, size=(8, 16), dtype=np.int32)}
    losses = [float(engine.train_batch(b)["loss"]) for _ in range(8)]
    assert all(np.isfinite(l) for l in losses)


# ------------------------------------------------------- structured pruning
def test_row_pruning_zeroes_whole_output_units(rng):
    tree = {"blocks": {"mlp_up_w": jnp.asarray(rng.normal(size=(2, 8, 16)),
                                               jnp.float32)}}
    sched = CompressionScheduler({
        "row_pruning": {"shared_parameters": {"enabled": True,
                                              "schedule_offset": 3},
                        "different_groups": {
                            "r0": {"params": {"dense_ratio": 0.5}}}}}, tree)
    before = np.asarray(sched.transform(tree, jnp.int32(0))
                        ["blocks"]["mlp_up_w"])
    np.testing.assert_array_equal(before,
                                  np.asarray(tree["blocks"]["mlp_up_w"]))
    after = np.asarray(sched.transform(tree, jnp.int32(5))
                       ["blocks"]["mlp_up_w"])
    col_zero = np.all(after == 0, axis=(0, 1))
    assert col_zero.sum() == 8  # half of 16 output units zeroed, whole column
    assert np.all(np.any(after[:, :, ~col_zero] != 0, axis=(0, 1)))


def test_head_pruning_zeroes_whole_heads(rng):
    H, Dh, D = 4, 4, 16
    tree = {"blocks": {"attn_out_w": jnp.asarray(
        rng.normal(size=(2, H * Dh, D)), jnp.float32)}}
    sched = CompressionScheduler({
        "head_pruning": {"shared_parameters": {"enabled": True,
                                               "schedule_offset": 0,
                                               "num_heads": H},
                         "different_groups": {
                             "h0": {"params": {"dense_ratio": 0.5}}}}}, tree)
    out = np.asarray(sched.transform(tree, jnp.int32(1))
                     ["blocks"]["attn_out_w"])
    per_head = out.reshape(2, H, Dh, D)
    zero_heads = np.all(per_head == 0, axis=(2, 3))  # [L, H]
    assert (zero_heads.sum(axis=1) == 2).all()  # exactly half per layer


def test_head_pruning_requires_num_heads(rng):
    tree = {"blocks": {"attn_out_w": jnp.ones((2, 16, 16), jnp.float32)}}
    with pytest.raises(ValueError, match="num_heads"):
        CompressionScheduler({
            "head_pruning": {"shared_parameters": {"enabled": True},
                             "different_groups": {}}}, tree)


def test_channel_pruning_on_conv_kernels(rng):
    tree = {"conv_w": jnp.asarray(rng.normal(size=(3, 3, 8, 16)), jnp.float32)}
    sched = CompressionScheduler({
        "channel_pruning": {"shared_parameters": {"enabled": True,
                                                  "schedule_offset": 0},
                            "different_groups": {
                                "c0": {"params": {"dense_ratio": 0.25}}}}},
        tree)
    out = np.asarray(sched.transform(tree, jnp.int32(1))["conv_w"])
    zero_ch = np.all(out == 0, axis=(0, 1, 2))
    assert zero_ch.sum() == 12  # 75% of 16 output channels zeroed


def test_activation_quantization_refused():
    with pytest.raises(NotImplementedError, match="activation_quantization"):
        CompressionScheduler({
            "activation_quantization": {
                "shared_parameters": {"enabled": True},
                "different_groups": {}}}, {"w": jnp.ones((4, 4))})


def test_redundancy_clean_bakes_final_transform(rng):
    from deepspeed_tpu.compression import redundancy_clean

    tree = {"blocks": {"qkv_w": jnp.asarray(rng.normal(size=(2, 16, 16)),
                                            jnp.float32)}}
    cfg = {"compression_training": {
        "weight_quantization": {
            "shared_parameters": {"enabled": True, "schedule_offset": 5},
            "different_groups": {
                "g0": {"params": {"start_bits": 12, "target_bits": 4,
                                  "quantization_period": 10,
                                  "quantize_groups": 1}}}},
        "sparse_pruning": {
            "shared_parameters": {"enabled": True, "schedule_offset": 5},
            "different_groups": {"s0": {"params": {"dense_ratio": 0.5}}}},
    }}
    out = redundancy_clean(tree, cfg)
    w = np.asarray(out["blocks"]["qkv_w"])
    ref = np.asarray(tree["blocks"]["qkv_w"])
    # pruned to half density
    assert (w == 0).mean() >= 0.5
    # survivors quantized at the TARGET bits: few distinct magnitudes per tensor
    nz = np.abs(w[w != 0])
    assert len(np.unique(np.round(nz / nz.min(), 4))) <= 16  # 4-bit grid
    assert not np.array_equal(w, ref)
    # no compression config: identity
    same = redundancy_clean(tree, {"compression_training": {}})
    np.testing.assert_array_equal(np.asarray(same["blocks"]["qkv_w"]), ref)


def test_redundancy_clean_accepts_config_object(rng):
    import deepspeed_tpu as ds
    from deepspeed_tpu.compression import redundancy_clean
    from deepspeed_tpu.runtime.config import DeepSpeedConfig

    tree = {"blocks": {"qkv_w": jnp.asarray(rng.normal(size=(1, 8, 8)),
                                            jnp.float32)}}
    cfg = DeepSpeedConfig(**{
        "train_micro_batch_size_per_gpu": 1,
        "compression_training": {
            "weight_quantization": {
                "shared_parameters": {"enabled": True, "schedule_offset": 0},
                "different_groups": {
                    "g0": {"params": {"start_bits": 4,
                                      "quantize_groups": 1}}}}}})
    out = redundancy_clean(tree, cfg)
    assert not np.array_equal(np.asarray(out["blocks"]["qkv_w"]),
                              np.asarray(tree["blocks"]["qkv_w"]))
