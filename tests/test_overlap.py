"""Compute/communication overlap for quantized ZeRO collectives.

The overlap schedules must be *free* numerically: the pipelined gather scan
issues the same gathers feeding the same body in the same order (bitwise
equality is asserted engine-level on the 8-device CPU mesh), and the bucketed
gradient exchange is the same ZeRO++ RS+AG math per layer bucket. These tests
pin: the scan restructuring (trip counts), bitwise loss equality pipelined vs
inline at prefetch depth 1 and 2 (per-layer and k=2 windows), per-bucket
error-feedback convergence, the grad-bucket tap against the dense pmean, the
dequant-fused matmul kernel, the exposed-vs-overlapped ledger arithmetic, and
the dslint gate that the hot path stays overlapped.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import deepspeed_tpu as ds
from deepspeed_tpu.models import build_gpt, gpt
from deepspeed_tpu.runtime.zero.config import DeepSpeedZeroConfig
from deepspeed_tpu.runtime.zero.gather import (
    gather_window,
    overlap_depth,
    zero3_layer_scan,
)


def _scan_lengths(jaxpr) -> list:
    out = []
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "scan":
            out.append(eqn.params["length"])
            out.extend(_scan_lengths(eqn.params["jaxpr"].jaxpr))
        elif "jaxpr" in eqn.params:
            inner = eqn.params["jaxpr"]
            out.extend(_scan_lengths(getattr(inner, "jaxpr", inner)))
    return out


# --------------------------------------------------------------------- config
def test_overlap_knob_resolution():
    assert DeepSpeedZeroConfig(stage=3).overlap_comm_effective is True
    assert DeepSpeedZeroConfig(
        stage=3, overlap_comm=False).overlap_comm_effective is False
    assert DeepSpeedZeroConfig(
        stage=3, overlap_comm=True).overlap_comm_effective is True
    with gather_window(DeepSpeedZeroConfig(stage=3)):
        assert overlap_depth() == 1
    with gather_window(DeepSpeedZeroConfig(stage=3, overlap_comm=False)):
        assert overlap_depth() == 0
    with gather_window(DeepSpeedZeroConfig(stage=3, overlap_prefetch_depth=3)):
        assert overlap_depth() == 3
    with gather_window(DeepSpeedZeroConfig(stage=2)):
        assert overlap_depth() == 0  # below stage 3: nothing to prefetch
    assert overlap_depth() == 0  # no bound config


# ------------------------------------------------------------- scan structure
def test_pipelined_scan_structure_and_numerics():
    """Depth d turns the length-L layer loop into a length-(L-d) pipelined
    scan plus d drained windows; values and grads match the plain scan."""
    blocks = {"w": jnp.asarray(
        np.random.default_rng(0).normal(size=(8, 4, 4)), jnp.float32)}
    x0 = jnp.ones((4,), jnp.float32)
    spec = {"w": P()}

    def body(c, w):
        return jnp.tanh(w["w"] @ c), None

    def run(cfg):
        def f(blocks):
            with gather_window(cfg):
                return jnp.sum(zero3_layer_scan(body, x0, blocks,
                                                gathered_spec=spec))
        return f

    plain = run(DeepSpeedZeroConfig(stage=3, overlap_comm=False))
    lens_plain = _scan_lengths(jax.make_jaxpr(plain)(blocks))
    assert 8 in lens_plain

    for depth, want in ((1, 7), (2, 6)):
        pf = run(DeepSpeedZeroConfig(stage=3, overlap_prefetch_depth=depth))
        lens = _scan_lengths(jax.make_jaxpr(pf)(blocks))
        assert want in lens and 8 not in lens, (depth, lens)
        v1, g1 = jax.value_and_grad(plain)(blocks)
        v2, g2 = jax.value_and_grad(pf)(blocks)
        np.testing.assert_allclose(float(v1), float(v2), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(g1["w"]), np.asarray(g2["w"]),
                                   rtol=1e-5)


def test_max_live_clamps_prefetch_depth():
    """A stage3_max_live_parameters cap that only fits one window must clamp
    the pipeline back to the inline schedule (no silent OOM-by-default)."""
    blocks = {"w": jnp.ones((4, 8, 8), jnp.float32)}  # 64 params/layer
    spec = {"w": P()}

    def body(c, w):
        return c + jnp.sum(w["w"]), None

    def trace(cfg):
        def f(blocks):
            with gather_window(cfg):
                return zero3_layer_scan(body, jnp.float32(0), blocks,
                                        gathered_spec=spec)
        return _scan_lengths(jax.make_jaxpr(f)(blocks))

    # cap = exactly one layer live -> inline length-4 scan, no pipeline
    lens = trace(DeepSpeedZeroConfig(stage=3, stage3_max_live_parameters=64))
    assert 4 in lens and 3 not in lens
    # two layers live -> depth-1 pipeline engages
    lens = trace(DeepSpeedZeroConfig(stage=3, stage3_max_live_parameters=128))
    assert 3 in lens


# --------------------------------------------------------- engine-level bitwise
def _make_engine(zero_cfg, n_layer=4):
    model, _ = build_gpt(gpt.GPTConfig(
        vocab_size=64, n_layer=n_layer, n_head=2, d_model=32, max_seq_len=32))
    engine, _, _, _ = ds.initialize(model=model, config={
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": zero_cfg,
        "mesh": {"dp": 8},
        "bf16": {"enabled": False},
        "steps_per_print": 0,
    })
    return engine


def _losses(engine, steps=2):
    ids = np.random.default_rng(0).integers(0, 64, size=(8, 16), dtype=np.int32)
    out = []
    for _ in range(steps):
        m = engine.train_batch({"input_ids": ids})
        out.append((float(m["loss"]), float(m["grad_norm"])))
    return out


@pytest.mark.slow
def test_pipelined_quantized_gathers_bitwise():
    """The acceptance bar: the pipelined quantized-gather FORWARD is bitwise
    identical to the inline schedule (same gathers, same quantize/dequantize,
    same consumption order — only the issue point moves), at prefetch depth 1
    and 2. The backward restructures the loop (scan-carried windows + drained
    epilogue), and XLA fuses the per-layer cotangent matmuls differently
    there, so gradients — and with them the multi-step trajectory — agree to
    float32 resolution rather than bitwise: the same divergence class as
    remat-vs-plain backward (see test_activation_checkpointing's note), not a
    schedule bug. Step-1 loss on identical state is the bitwise invariant."""
    base = {"stage": 3, "zero_quantized_weights": True,
            "stage3_param_persistence_threshold": 0}
    inline = _losses(_make_engine({**base, "overlap_comm": False}), steps=3)
    for depth in (1, 2):
        pf = _losses(_make_engine({**base, "overlap_prefetch_depth": depth}),
                     steps=3)
        assert pf[0][0] == inline[0][0], (depth, pf[0], inline[0])  # bitwise
        # ulp-level backward differences compound through Adam over steps;
        # a real schedule bug would sit orders of magnitude above these
        for (pl, pg), (il, ig) in zip(pf, inline):
            np.testing.assert_allclose(pl, il, rtol=1e-5)
            np.testing.assert_allclose(pg, ig, rtol=1e-3)


@pytest.mark.slow
def test_pipelined_windowed_gathers_bitwise():
    """Same bar with k=2 layer windows (stage3_prefetch_bucket_size):
    pipelining composes with gather windowing."""
    model, _ = build_gpt(gpt.GPTConfig(
        vocab_size=64, n_layer=4, n_head=2, d_model=32, max_seq_len=32))
    params = gpt.init_params(model.gpt_config, jax.random.PRNGKey(0))
    per_layer = sum(int(np.prod(x.shape))
                    for x in jax.tree_util.tree_leaves(params["blocks"])) // 4
    base = {"stage": 3, "zero_quantized_weights": True,
            "stage3_param_persistence_threshold": 0,
            "stage3_prefetch_bucket_size": 2 * per_layer,
            "stage3_max_live_parameters": 10**9}
    inline = _losses(_make_engine({**base, "overlap_comm": False}))
    pf = _losses(_make_engine(base))
    assert pf[0][0] == inline[0][0], (pf[0], inline[0])  # bitwise fwd
    for (pl, pg), (il, ig) in zip(pf, inline):
        np.testing.assert_allclose(pl, il, rtol=1e-5)
        np.testing.assert_allclose(pg, ig, rtol=1e-3)


def test_pipelined_gathers_record_pf_marker():
    from deepspeed_tpu.comm.runtime_accounting import wire_ledger

    before = wire_ledger.snapshot()
    _losses(_make_engine({"stage": 3, "zero_quantized_weights": True,
                          "stage3_param_persistence_threshold": 0}), steps=1)
    delta = wire_ledger.delta(before)
    assert any(k.startswith("qgather[zero3/pf]") for k in delta), delta
    assert not any(k.startswith("qgather[zero3]") for k in delta), delta


# ------------------------------------------------------------- grad buckets
def test_grad_bucket_reduce_matches_pmean():
    """The tap's backward = per-bucket quantized RS+AG mean-reduce: grads
    come out reduced across dp, within int8 block-quantization tolerance of
    the dense pmean."""
    from deepspeed_tpu.comm.quantized import grad_bucket_reduce
    from deepspeed_tpu.runtime.topology import MeshTopology
    from deepspeed_tpu.utils.jax_compat import shard_map

    topo = MeshTopology.create(dp=8)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(8, 64)), jnp.float32)   # per-rank data
    w = {"a": jnp.asarray(rng.normal(size=(64,)), jnp.float32),
         "b": jnp.asarray(rng.normal(size=(3, 8)), jnp.float32)}

    def loss(w, xr):
        return jnp.sum(jnp.tanh(xr @ w["a"])) + jnp.sum(w["b"] ** 2)

    def body(w, xs):
        def tapped_loss(q):
            q = grad_bucket_reduce(q, None, None)
            return loss(q, xs)
        return jax.grad(tapped_loss)(w)

    g = shard_map(body, mesh=topo.mesh, in_specs=(P(), P("dp", None)),
                  out_specs=P(), check_vma=False)(w, x)
    g_dense = jax.grad(
        lambda q: float(0) + jnp.mean(
            jax.vmap(lambda xr: loss(q, xr[None]))(x)))(w)
    for k in g:
        np.testing.assert_allclose(np.asarray(g[k]), np.asarray(g_dense[k]),
                                   rtol=0.05, atol=0.05)


def test_bucketed_grad_engine_matches_dense():
    """Engine-level: bucketed overlapped qgrads track the dense fp engine's
    loss trajectory (same tolerance class as the monolithic exchange), and
    the per-bucket collectives land in the wire ledger."""
    from deepspeed_tpu.comm.runtime_accounting import wire_ledger

    before = wire_ledger.snapshot()
    dense = _losses(_make_engine({"stage": 2}), steps=4)
    buck = _losses(_make_engine({"stage": 2, "zero_quantized_gradients": True}),
                   steps=4)
    delta = wire_ledger.delta(before)
    assert any(k.startswith("qgrad_bucket_rs") for k in delta), delta
    assert any(k.startswith("qgrad_bucket_ag") for k in delta), delta
    for (dl, _), (bl, _) in zip(dense, buck):
        np.testing.assert_allclose(bl, dl, rtol=0.02)
    assert buck[-1][0] < buck[0][0]  # it trains


def test_bucketed_error_feedback_converges():
    """Per-bucket EF: residual state exists per layer bucket, is finite, and
    the EF run stays at least as close to the dense trajectory as plain
    stochastic-free quantization at the final step."""
    e = _make_engine({"stage": 2, "zero_quantized_gradients": True,
                      "zero_quantize_error_feedback": True})
    assert "qgrad_bucket_residual" in e.state
    losses = _losses(e, steps=5)
    resid = np.asarray(e.state["qgrad_bucket_residual"])
    assert resid.shape[0] == 4  # one bucket per layer
    assert np.isfinite(resid).all()
    assert np.abs(resid).sum() > 0  # EF actually captured quantization error
    assert losses[-1][0] < losses[0][0]


def test_bucket_mode_falls_back_monolithic_when_disabled():
    e = _make_engine({"stage": 2, "zero_quantized_gradients": True,
                      "overlap_comm": False})
    assert e._qgrad_bucket_key is None
    e2 = _make_engine({"stage": 2, "zero_quantized_gradients": True,
                       "zero_quantize_stochastic": True})
    assert e2._qgrad_bucket_key is None  # stochastic has no per-bucket rng


# ------------------------------------------------------------- fused dequant
def test_dequant_matmul_fallback_and_kernel():
    from deepspeed_tpu.comm.quantized import (
        dequantize_blockwise,
        quantize_blockwise,
    )
    from deepspeed_tpu.ops.pallas.dequant_matmul import dequant_matmul

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(8, 256)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(256, 512)), jnp.float32)
    q, s, z = quantize_blockwise(w, bits=8, block_size=256)
    ref = x @ dequantize_blockwise(q, s, z, bits=8, orig_size=512)

    out = dequant_matmul(x, q, s, z, orig_size=512)  # CPU: XLA fallback
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5)

    old = os.environ.get("DS_TPU_PALLAS_INTERPRET")
    os.environ["DS_TPU_PALLAS_INTERPRET"] = "1"  # Pallas path, interpreted
    try:
        out_k = dequant_matmul(x, q, s, z, orig_size=512)
    finally:
        if old is None:
            os.environ.pop("DS_TPU_PALLAS_INTERPRET", None)
        else:
            os.environ["DS_TPU_PALLAS_INTERPRET"] = old
    np.testing.assert_allclose(np.asarray(out_k), np.asarray(ref),
                               rtol=1e-5, atol=1e-4)


def test_quantized_matmul_reshard_values_and_straight_through():
    from deepspeed_tpu.comm.quantized import (
        dequantize_blockwise,
        quantize_blockwise,
        quantized_matmul_reshard,
    )

    rng = np.random.default_rng(1)
    h = jnp.asarray(rng.normal(size=(4, 6, 128)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(128, 384)), jnp.float32)
    q, s, z = quantize_blockwise(w, bits=8, block_size=128)
    w_hat = dequantize_blockwise(q, s, z, bits=8, orig_size=384)
    ref = jnp.einsum("btd,df->btf", h, w_hat)

    out = quantized_matmul_reshard(h, w, P(), bits=8, block_size=128)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-4,
                               atol=1e-4)

    # straight-through: d_w == h^T g exactly (no dequant/quant jacobian),
    # d_h comes from the dequantized weight
    g_h, g_w = jax.grad(
        lambda hh, ww: jnp.sum(
            quantized_matmul_reshard(hh, ww, P(), 8, 128)),
        argnums=(0, 1))(h, w)
    h2 = np.asarray(h).reshape(-1, 128)
    np.testing.assert_allclose(np.asarray(g_w), h2.T @ np.ones((24, 384)),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(
        np.asarray(g_h).reshape(-1, 128), np.ones((24, 384)) @ np.asarray(w_hat).T,
        rtol=1e-4, atol=1e-4)


@pytest.mark.slow
def test_quantized_head_engine():
    """zero_quantized_head: the LM-head gather goes through the dequant-fused
    matmul — ledger records the qmatmul op, loss stays in the quantized-weight
    tolerance class of the unquantized-head engine, and it trains."""
    from deepspeed_tpu.comm.runtime_accounting import wire_ledger

    base = {"stage": 3, "zero_quantized_weights": True,
            "stage3_param_persistence_threshold": 0}
    plain = _losses(_make_engine(base), steps=3)
    before = wire_ledger.snapshot()
    qhead = _losses(_make_engine({**base, "zero_quantized_head": True}),
                    steps=3)
    delta = wire_ledger.delta(before)
    assert any(k.startswith("qmatmul[lm_head]") for k in delta), delta
    np.testing.assert_allclose(qhead[0][0], plain[0][0], rtol=2e-2)
    assert qhead[-1][0] < qhead[0][0]


# ------------------------------------------------------------ overlap ledger
def test_overlap_accounting_sums_to_step_time():
    """The ledger invariants, on a synthetic device timeline:
    exposed + overlapped == collective, and busy == compute + exposed —
    the accounting always explains where the step time went."""
    from deepspeed_tpu.comm.runtime_accounting import overlap_from_events

    events = [
        # lane 0: 100us compute, an async gather 50-110 (50 hidden, 10 exposed)
        {"ph": "X", "pid": 0, "name": "fusion.1", "ts": 0.0, "dur": 100.0},
        {"ph": "X", "pid": 0, "name": "all-gather-start.1", "ts": 50.0,
         "dur": 60.0},
        {"ph": "X", "pid": 0, "name": "all-gather-done.1", "ts": 110.0,
         "dur": 5.0},  # skipped: the -start carries the transfer
        # lane 1: a bare sync all-reduce, fully exposed
        {"ph": "X", "pid": 1, "name": "all-reduce.2", "ts": 0.0, "dur": 40.0},
        # non-X metadata must be ignored
        {"ph": "M", "pid": 0, "name": "process_name"},
    ]
    st = overlap_from_events(events, n_devices=2)
    assert st.collective_us == pytest.approx(100.0)
    assert st.overlapped_us == pytest.approx(50.0)
    assert st.exposed_us == pytest.approx(50.0)
    assert st.compute_us == pytest.approx(100.0)
    assert st.busy_us == pytest.approx(150.0)
    # the two identities the bench column relies on
    assert st.exposed_us + st.overlapped_us == pytest.approx(st.collective_us)
    assert st.compute_us + st.exposed_us == pytest.approx(st.busy_us)
    assert st.hidden_frac == pytest.approx(0.5)
    d = st.to_dict()
    assert d["hidden_frac"] == pytest.approx(0.5)


def test_wire_ledger_overlap_column_renders():
    from deepspeed_tpu.comm.runtime_accounting import WireLedger

    led = WireLedger()
    led.record("qgather[zero3/pf]", 1000, 250)
    led.set_overlap({"collective_us": 100.0, "exposed_us": 25.0,
                     "overlapped_us": 75.0, "hidden_frac": 0.75})
    out = led.summary()
    assert "overlap (measured)" in out and "75" in out


@pytest.mark.slow
def test_engine_measure_overlap_end_to_end():
    e = _make_engine({"stage": 3, "zero_quantized_weights": True,
                      "stage3_param_persistence_threshold": 0})
    ids = np.random.default_rng(0).integers(0, 64, size=(8, 16), dtype=np.int32)
    e.train_batch({"input_ids": ids})  # compile outside the profile
    st = e.measure_overlap({"input_ids": ids})
    assert st.collective_us > 0
    assert st.exposed_us + st.overlapped_us == pytest.approx(
        st.collective_us, rel=1e-6)
    from deepspeed_tpu.comm.runtime_accounting import wire_ledger

    assert wire_ledger.overlap is not None


# ------------------------------------------------------------------- dslint
def test_dslint_unoverlapped_rule():
    """ERROR on the inline schedules, silent on the overlapped defaults."""
    def rules_fired(zc):
        e = _make_engine(zc)
        ids = np.random.default_rng(0).integers(0, 64, size=(8, 16),
                                                dtype=np.int32)
        rep = e.analyze(batch={"input_ids": ids})
        return [f for f in rep.findings
                if f.rule_id == "collective/unoverlapped-quantized-collective"]

    assert rules_fired({"stage": 3, "zero_quantized_weights": True,
                        "stage3_param_persistence_threshold": 0,
                        "overlap_comm": False})
    assert not rules_fired({"stage": 3, "zero_quantized_weights": True,
                            "stage3_param_persistence_threshold": 0})
    assert rules_fired({"stage": 2, "zero_quantized_gradients": True,
                        "overlap_comm": False})
    assert not rules_fired({"stage": 2, "zero_quantized_gradients": True})
