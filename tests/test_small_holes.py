"""Round-3 'small holes' (VERDICT r2 'next' #9): comm benchmarks + ds_bench,
sparse embedding gradients, the WandB monitor backend, and the diffusers
(Stable-Diffusion) inference skeleton."""

import sys
import types

import numpy as np
import pytest

import jax
import jax.numpy as jnp


# ----------------------------------------------------------------- comm bench
def test_comm_bench_all_ops_produce_sane_records(devices):
    from deepspeed_tpu.benchmarks.communication import OPS, run_collective_bench

    for op in OPS:
        recs = run_collective_bench(op, [1 << 12], dtype=jnp.float32,
                                    trials=2, warmups=1)
        (r,) = recs
        assert r["op"] == op and r["world"] == 8
        assert r["latency_us"] > 0
        assert r["busbw_GBps"] > 0
        if op == "all_reduce":
            # records are rounded to 3 decimals; ratio is approximate
            np.testing.assert_allclose(r["busbw_GBps"] / r["algbw_GBps"],
                                       2 * 7 / 8, rtol=0.1)


def test_comm_bench_collectives_are_correct(devices):
    """The timed programs must compute the real collective, not a no-op."""
    from deepspeed_tpu.benchmarks.communication import _collective_fn
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.asarray(devices), ("bench",))
    x = jnp.arange(8 * 128, dtype=jnp.float32).reshape(8, 128)
    xs = jax.device_put(x, NamedSharding(mesh, P("bench")))
    ar = np.asarray(_collective_fn("all_reduce", mesh)(xs))
    want = x.sum(axis=0)
    for row in ar.reshape(8, 128):
        np.testing.assert_allclose(row, want, rtol=1e-6)
    ag = np.asarray(_collective_fn("all_gather", mesh)(xs))
    np.testing.assert_allclose(ag, x.reshape(-1), rtol=1e-6)


def test_ds_bench_cli_json(devices, capsys):
    from deepspeed_tpu.benchmarks.communication import main

    rc = main(["--ops", "all_reduce", "--minsize", "4096", "--maxsize", "4096",
               "--trials", "2", "--json"])
    assert rc == 0
    import json

    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["world"] == 8 and out["results"][0]["op"] == "all_reduce"


# ----------------------------------------------------------------- sparse grads
def test_sparse_tensor_dense_equivalence(rng):
    from deepspeed_tpu.runtime.sparse_tensor import SparseTensor

    V, D = 16, 8
    ids = jnp.asarray(rng.integers(0, V, size=(2, 5)), jnp.int32)
    rows = jnp.asarray(rng.normal(size=(2, 5, D)), jnp.float32)
    st = SparseTensor.from_embedding_grad(ids, rows, V)
    dense = np.zeros((V, D), np.float32)
    for i, r in zip(np.asarray(ids).reshape(-1), np.asarray(rows).reshape(-1, D)):
        dense[i] += r
    np.testing.assert_allclose(np.asarray(st.to_dense()), dense, rtol=1e-6)
    # sparse add == dense add
    st2 = st.add(st)
    np.testing.assert_allclose(np.asarray(st2.to_dense()), 2 * dense, rtol=1e-6)
    assert st.nbytes < V * D * 4  # smaller than the dense gradient


def test_sparse_all_reduce_matches_dense_psum(devices, rng):
    from deepspeed_tpu.utils.jax_compat import shard_map
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from deepspeed_tpu.runtime.sparse_tensor import SparseTensor, sparse_all_reduce

    V, D, n = 16, 4, 8
    mesh = Mesh(np.asarray(devices), ("dp",))
    ids = jnp.asarray(rng.integers(0, V, size=(n, 6)), jnp.int32)
    vals = jnp.asarray(rng.normal(size=(n, 6, D)), jnp.float32)

    def body(ids, vals):
        st = SparseTensor(ids.reshape(-1), vals.reshape(-1, D), (V, D))
        return sparse_all_reduce(st, "dp").to_dense()

    fn = jax.jit(shard_map(
        body, mesh=mesh, in_specs=(P("dp"), P("dp")), out_specs=P(None),
        check_vma=False))
    got = np.asarray(fn(ids, vals))

    dense = np.zeros((V, D), np.float32)
    for r in range(n):
        for i, v in zip(np.asarray(ids[r]), np.asarray(vals[r])):
            dense[i] += v / n
    np.testing.assert_allclose(got, dense, rtol=1e-5, atol=1e-6)


# ----------------------------------------------------------------- wandb
def test_wandb_monitor_backend(monkeypatch):
    from deepspeed_tpu.monitor.monitor import MonitorMaster
    from deepspeed_tpu.runtime.config import MonitorConfig

    calls = {"init": [], "log": []}
    fake = types.ModuleType("wandb")
    fake.init = lambda **kw: calls["init"].append(kw)
    fake.log = lambda d, step=None: calls["log"].append((d, step))
    monkeypatch.setitem(sys.modules, "wandb", fake)

    cfg = MonitorConfig(wandb={"enabled": True, "project": "p", "group": "g"})
    assert cfg.enabled
    mm = MonitorMaster(cfg)
    mm.write_events([("Train/loss", 1.5, 3)])
    assert calls["init"] == [{"entity": None, "group": "g", "project": "p"}]
    assert calls["log"] == [({"Train/loss": 1.5}, 3)]


def test_wandb_missing_package_degrades_gracefully(monkeypatch):
    from deepspeed_tpu.monitor.monitor import MonitorMaster
    from deepspeed_tpu.runtime.config import MonitorConfig

    monkeypatch.setitem(sys.modules, "wandb", None)  # import -> ImportError
    mm = MonitorMaster(MonitorConfig(wandb={"enabled": True}))
    mm.write_events([("Train/loss", 1.0, 1)])  # must not raise
    assert mm.backends == []


# ----------------------------------------------------------------- diffusion
@pytest.mark.slow
def test_unet_shapes_and_determinism(rng):
    from deepspeed_tpu.models.diffusion import UNetConfig, apply_unet, init_unet

    cfg = UNetConfig(base_channels=16, channel_mults=(1, 2), text_dim=12,
                     n_head=2, time_dim=32)
    params = init_unet(cfg, jax.random.PRNGKey(0))
    lat = jnp.asarray(rng.normal(size=(2, 8, 8, 4)), jnp.float32)
    t = jnp.asarray([10, 500], jnp.int32)
    txt = jnp.asarray(rng.normal(size=(2, 5, 12)), jnp.float32)
    out = apply_unet(cfg, params, lat, t, txt)
    assert out.shape == (2, 8, 8, 4)
    out2 = apply_unet(cfg, params, lat, t, txt)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(out2))
    # timestep conditioning is live
    out3 = apply_unet(cfg, params, lat, jnp.asarray([11, 501], jnp.int32), txt)
    assert np.abs(np.asarray(out) - np.asarray(out3)).max() > 0
    # text conditioning is live (cross-attention)
    out4 = apply_unet(cfg, params, lat, t, txt + 1.0)
    assert np.abs(np.asarray(out) - np.asarray(out4)).max() > 0


@pytest.mark.slow
def test_stable_diffusion_pipeline_end_to_end(rng):
    from deepspeed_tpu.models.diffusion import (
        StableDiffusionPipeline,
        UNetConfig,
        VAEDecoderConfig,
    )

    pipe = StableDiffusionPipeline.init_random(
        jax.random.PRNGKey(0),
        unet_cfg=UNetConfig(base_channels=16, channel_mults=(1, 2),
                            text_dim=12, n_head=2, time_dim=32),
        vae_cfg=VAEDecoderConfig(base_channels=16, upsamples=2),
        latent_size=8)
    txt = jnp.asarray(rng.normal(size=(1, 5, 12)), jnp.float32)
    un = jnp.zeros_like(txt)
    img = pipe(txt, un, num_steps=4, guidance_scale=3.0)
    assert img.shape == (1, 32, 32, 3)
    assert np.all(np.isfinite(img)) and np.abs(img).max() <= 1.0
    # guidance scale changes the output (classifier-free guidance is live)
    img2 = pipe(txt, un, num_steps=4, guidance_scale=1.0)
    assert np.abs(img - img2).max() > 0


@pytest.mark.slow
def test_engine_emits_full_event_set():
    """The gas-boundary monitor events must include loss/lr/grad_norm (and
    loss_scale under fp16) — the reference's engine.py:2183-2206 set."""
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import build_gpt
    from deepspeed_tpu.models.gpt import GPTConfig

    from deepspeed_tpu.monitor.monitor import CallbackMonitor, MonitorMaster
    from deepspeed_tpu.runtime.config import MonitorConfig

    events = []
    model, _ = build_gpt(GPTConfig(vocab_size=64, d_model=32, n_layer=1,
                                   n_head=2, max_seq_len=16))
    engine, _, _, _ = ds.initialize(model=model, config={
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "fp16": {"enabled": True},
        "mesh": {"dp": 8},
        "steps_per_print": 0,
    })
    engine._monitor = MonitorMaster(
        MonitorConfig(), extra_backends=[CallbackMonitor(events.extend)])
    engine.train_batch({"input_ids": np.zeros((8, 16), np.int32)})
    keys = {name for name, _, _ in events}
    assert {"Train/loss", "Train/lr", "Train/grad_norm",
            "Train/loss_scale"} <= keys


def test_wall_clock_breakdown_logs_fused_timers(caplog, monkeypatch):
    import logging

    import deepspeed_tpu as ds
    from deepspeed_tpu.models import build_gpt
    from deepspeed_tpu.models.gpt import GPTConfig
    from deepspeed_tpu.utils.logging import logger as ds_logger

    model, _ = build_gpt(GPTConfig(vocab_size=64, d_model=32, n_layer=1,
                                   n_head=2, max_seq_len=16))
    engine, _, _, _ = ds.initialize(model=model, config={
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "mesh": {"dp": 8},
        "wall_clock_breakdown": True,
        "steps_per_print": 1,
    })
    monkeypatch.setattr(ds_logger, "propagate", True)
    with caplog.at_level(logging.INFO, logger=ds_logger.name):
        engine.train_batch({"input_ids": np.zeros((8, 16), np.int32)})
    joined = "\n".join(r.message for r in caplog.records)
    assert "train_batch" in joined and "batch_input" in joined
