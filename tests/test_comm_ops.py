"""Rooted comm facade ops (reduce/gather/scatter/monitored_barrier parity)."""

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from deepspeed_tpu import comm


def _mesh(devices):
    return Mesh(np.asarray(devices[:4]), ("dp",))


def test_reduce_lands_on_dst_only(devices):
    mesh = _mesh(devices)
    x = jnp.arange(4, dtype=jnp.float32)  # shard i holds [i]

    def f(xs):
        return comm.reduce(xs, "dp", dst_index=2)

    out = shard_map(f, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))(x)
    np.testing.assert_array_equal(np.asarray(out), [0, 0, 6, 0])


def test_gather_concatenates_on_dst(devices):
    mesh = _mesh(devices)
    x = jnp.arange(4, dtype=jnp.float32)

    def f(xs):
        return comm.gather(xs, "dp", dst_index=1)

    out = shard_map(f, mesh=mesh, in_specs=P("dp"),
                    out_specs=P("dp"))(x)
    got = np.asarray(out).reshape(4, 4)
    np.testing.assert_array_equal(got[1], [0, 1, 2, 3])
    np.testing.assert_array_equal(got[0], np.zeros(4))


def test_scatter_distributes_src_chunks(devices):
    mesh = _mesh(devices)
    # every rank holds a full [8] array; src rank 0's is authoritative
    x = jnp.tile(jnp.arange(8, dtype=jnp.float32)[None], (4, 1))

    def f(xs):
        return comm.scatter(xs[0], "dp", src_index=0)

    out = shard_map(f, mesh=mesh, in_specs=P("dp", None),
                    out_specs=P("dp"))(x)
    np.testing.assert_array_equal(np.asarray(out), np.arange(8))


def test_monitored_barrier_returns_wait():
    dt = comm.monitored_barrier("test", timeout_s=10.0)
    assert dt >= 0.0


def test_gather_scatter_support_pytrees(devices):
    mesh = _mesh(devices)
    x = {"a": jnp.arange(4, dtype=jnp.float32),
         "b": jnp.arange(8, dtype=jnp.float32).reshape(4, 2)}

    def g(xs):
        return comm.gather(xs, "dp", dst_index=0)

    out = shard_map(g, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))(x)
    got_a = np.asarray(out["a"]).reshape(4, 4)
    np.testing.assert_array_equal(got_a[0], [0, 1, 2, 3])

    full = {"w": jnp.tile(jnp.arange(8, dtype=jnp.float32)[None], (4, 1))}

    def sc(xs):
        return comm.scatter({"w": xs["w"][0]}, "dp", src_index=0)

    out2 = shard_map(sc, mesh=mesh, in_specs=P("dp", None),
                     out_specs=P("dp"))(full)
    np.testing.assert_array_equal(np.asarray(out2["w"]), np.arange(8))
