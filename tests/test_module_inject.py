"""HF-model import policies + AutoTP + int8 inference.

The strongest parity check available: build tiny randomly-initialized HF models
locally (no network), import their weights, and compare our logits against the
HF torch forward — mirroring the reference's test_inference.py discipline of
comparing injected kernels against the HF pipeline output.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.module_inject import auto_tp_specs, import_hf_model

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


def _compare_logits(hf_model, input_ids: np.ndarray, atol=2e-3):
    cfg, params = import_hf_model(hf_model)
    from deepspeed_tpu.models import gpt as G

    ours = np.asarray(G.forward(cfg, params, jnp.asarray(input_ids), train=False))
    with torch.no_grad():
        theirs = hf_model(torch.from_numpy(input_ids).long()).logits.numpy()
    np.testing.assert_allclose(ours, theirs, atol=atol, rtol=1e-3)
    return cfg, params


@pytest.mark.slow
def test_gpt2_import_matches_hf(rng):
    hf_cfg = transformers.GPT2Config(
        vocab_size=97, n_positions=64, n_embd=32, n_layer=2, n_head=4)
    torch.manual_seed(0)
    model = transformers.GPT2LMHeadModel(hf_cfg).eval()
    ids = rng.integers(0, 97, size=(2, 12)).astype(np.int64)
    cfg, _ = _compare_logits(model, ids)
    assert cfg.activation == "gelu" and not cfg.rotary


def test_gptneox_import_matches_hf(rng):
    hf_cfg = transformers.GPTNeoXConfig(
        vocab_size=91, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, rotary_pct=0.25,
        use_parallel_residual=True)
    torch.manual_seed(0)
    model = transformers.GPTNeoXForCausalLM(hf_cfg).eval()
    ids = rng.integers(0, 91, size=(2, 10)).astype(np.int64)
    cfg, _ = _compare_logits(model, ids)
    assert cfg.rotary and cfg.parallel_residual and not cfg.tie_embeddings


def test_opt_import_matches_hf(rng):
    hf_cfg = transformers.OPTConfig(
        vocab_size=99, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, ffn_dim=64, max_position_embeddings=64,
        do_layer_norm_before=True, activation_function="relu",
        word_embed_proj_dim=32)
    torch.manual_seed(0)
    model = transformers.OPTForCausalLM(hf_cfg).eval()
    ids = rng.integers(0, 99, size=(2, 10)).astype(np.int64)
    cfg, _ = _compare_logits(model, ids)
    assert cfg.activation == "relu" and cfg.pos_offset == 2


def test_bloom_import_matches_hf(rng):
    hf_cfg = transformers.BloomConfig(
        vocab_size=93, hidden_size=32, n_layer=2, n_head=4,
        layer_norm_epsilon=1e-5)
    torch.manual_seed(0)
    model = transformers.BloomForCausalLM(hf_cfg).eval()
    ids = rng.integers(0, 93, size=(2, 10)).astype(np.int64)
    cfg, _ = _compare_logits(model, ids)
    assert cfg.alibi and cfg.embed_layernorm and cfg.tie_embeddings


def test_gptj_import_matches_hf(rng):
    hf_cfg = transformers.GPTJConfig(
        vocab_size=95, n_positions=64, n_embd=32, n_layer=2, n_head=4,
        rotary_dim=4)
    torch.manual_seed(0)
    model = transformers.GPTJForCausalLM(hf_cfg).eval()
    ids = rng.integers(0, 95, size=(2, 10)).astype(np.int64)
    cfg, _ = _compare_logits(model, ids)
    assert cfg.rotary_interleaved and cfg.parallel_residual and cfg.lm_head_bias


def test_unknown_architecture_raises():
    class Fake:
        pass

    with pytest.raises(ValueError, match="no import policy"):
        import_hf_model(Fake())


def test_init_inference_accepts_hf_model(rng):
    hf_cfg = transformers.GPT2Config(
        vocab_size=97, n_positions=64, n_embd=32, n_layer=2, n_head=4)
    torch.manual_seed(0)
    model = transformers.GPT2LMHeadModel(hf_cfg).eval()
    engine = deepspeed_tpu.init_inference(model, dtype="float32")
    ids = rng.integers(0, 97, size=(1, 8)).astype(np.int32)
    out = engine.generate(ids, max_new_tokens=4)
    assert out.shape == (1, 12)
    # greedy continuation matches HF's own greedy generate
    with torch.no_grad():
        ref = model.generate(torch.from_numpy(ids).long(), max_new_tokens=4,
                             do_sample=False).numpy()
    np.testing.assert_array_equal(out, ref)


# ------------------------------------------------------------------- AutoTP
def test_auto_tp_specs_heuristics(rng):
    from jax.sharding import PartitionSpec as P

    params = {
        "wte": jnp.zeros((64, 16)),
        "h": {"qkv_w": jnp.zeros((16, 48)), "attn_out_w": jnp.zeros((16, 16)),
              "c_fc_w": jnp.zeros((16, 64)), "c_proj_w": jnp.zeros((64, 16)),
              "ln_scale": jnp.zeros((16,))},
    }
    specs = auto_tp_specs(params)
    assert specs["wte"] == P("tp", None)  # vocab-parallel
    assert specs["h"]["qkv_w"] == P(None, "tp")  # column
    assert specs["h"]["c_fc_w"] == P(None, "tp")  # column
    assert specs["h"]["c_proj_w"] == P("tp", None)  # row
    assert specs["h"]["ln_scale"] == P(None)


def test_auto_tp_skips_indivisible():
    from jax.sharding import PartitionSpec as P

    params = {"odd_w": jnp.zeros((16, 17))}
    specs = auto_tp_specs(params, tp_size=4)
    assert specs["odd_w"] == P(None, None)


def test_auto_tp_engine_runs_on_mesh(rng):
    """Unknown adapter without partition_specs: AutoTP shards it over tp=2."""
    from deepspeed_tpu.inference.engine import InferenceEngine, for_gpt
    from deepspeed_tpu.models.gpt import GPTConfig, init_params

    cfg = GPTConfig(vocab_size=64, d_model=32, n_layer=2, n_head=4, max_seq_len=32)
    params = init_params(cfg, jax.random.PRNGKey(0))

    class NoSpecs:
        """Adapter without partition_specs — forces the AutoTP path."""

        def __init__(self, inner):
            self.params = inner.params
            self._inner = inner

        def init_cache(self, *a, **k):
            return self._inner.init_cache(*a, **k)

        def prefill(self, *a, **k):
            return self._inner.prefill(*a, **k)

    from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig
    from deepspeed_tpu.runtime.topology import MeshTopology

    topo = MeshTopology.create(dp=4, tp=2)
    engine = InferenceEngine(
        NoSpecs(for_gpt(cfg, params)),
        DeepSpeedInferenceConfig(dtype="float32", tensor_parallel={"tp_size": 2}),
        topology=topo)
    ids = rng.integers(0, 64, size=(1, 8)).astype(np.int32)
    out = engine.generate(ids, max_new_tokens=4)
    assert out.shape == (1, 12)


# ------------------------------------------------------------------- int8
def test_int8_inference_close_to_fp(rng):
    from deepspeed_tpu.inference.engine import InferenceEngine, for_gpt
    from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig
    from deepspeed_tpu.models.gpt import GPTConfig, init_params

    cfg = GPTConfig(vocab_size=64, d_model=32, n_layer=2, n_head=4, max_seq_len=32)
    params = init_params(cfg, jax.random.PRNGKey(0))
    ids = rng.integers(0, 64, size=(1, 8)).astype(np.int32)

    e_fp = InferenceEngine(for_gpt(cfg, params),
                           DeepSpeedInferenceConfig(dtype="float32"))
    e_q = InferenceEngine(for_gpt(cfg, params),
                          DeepSpeedInferenceConfig(
                              dtype="float32",
                              quant={"enabled": True, "bits": 8, "group_size": 32}))
    # the GPT adapter uses the per-layer in-scan dequant path (int8 {q,s}
    # leaves in the stored tree), not the flat whole-tree scales fallback
    assert e_q._per_layer_quant and e_q._quant_scales is None
    assert e_q.params["blocks"]["qkv_w"]["q"].dtype == jnp.int8
    l_fp = np.asarray(e_fp.forward(ids))
    l_q = np.asarray(e_q.forward(ids))
    # int8 weights: logits close but not identical
    assert not np.array_equal(l_fp, l_q)
    np.testing.assert_allclose(l_q, l_fp, atol=0.5, rtol=0.1)
    # same argmax on most positions (weight-only int8 keeps predictions)
    agree = (l_fp.argmax(-1) == l_q.argmax(-1)).mean()
    assert agree >= 0.8


# --------------------------------------------------- sharded checkpoint loading
def _write_sharded_checkpoint(tmpdir, hf_model, n_shards=2, fmt="safetensors"):
    """Write an HF-style multi-file sharded checkpoint dir (index + shards)."""
    import json
    import os

    sd = {k: v.detach().clone() for k, v in hf_model.state_dict().items()
          if not k.endswith((".attn.masked_bias", ".attn.bias"))}
    names = sorted(sd)
    chunk = (len(names) + n_shards - 1) // n_shards
    weight_map = {}
    for i in range(n_shards):
        part = names[i * chunk:(i + 1) * chunk]
        if fmt == "safetensors":
            fname = f"model-{i + 1:05d}-of-{n_shards:05d}.safetensors"
            from safetensors.torch import save_file

            save_file({k: sd[k].contiguous() for k in part},
                      os.path.join(tmpdir, fname))
        else:
            fname = f"pytorch_model-{i + 1:05d}-of-{n_shards:05d}.bin"
            torch.save({k: sd[k] for k in part}, os.path.join(tmpdir, fname))
        weight_map.update({k: fname for k in part})
    idx_name = ("model.safetensors.index.json" if fmt == "safetensors"
                else "pytorch_model.bin.index.json")
    with open(os.path.join(tmpdir, idx_name), "w") as f:
        json.dump({"weight_map": weight_map}, f)
    cfg_dict = hf_model.config.to_dict()
    cfg_dict["architectures"] = [type(hf_model).__name__]
    with open(os.path.join(tmpdir, "config.json"), "w") as f:
        json.dump(cfg_dict, f)


@pytest.mark.parametrize("fmt", ["safetensors", "bin"])
def test_sharded_checkpoint_streams_from_disk(tmp_path, rng, fmt):
    """VERDICT r1 #4: multi-file checkpoint dir loads leaf-by-leaf with no torch
    model in memory, matching the in-memory import exactly."""
    from deepspeed_tpu.module_inject.load_checkpoint import load_hf_checkpoint

    hf_cfg = transformers.GPT2Config(
        vocab_size=61, n_positions=32, n_embd=32, n_layer=3, n_head=4)
    torch.manual_seed(1)
    model = transformers.GPT2LMHeadModel(hf_cfg).eval()
    _write_sharded_checkpoint(str(tmp_path), model, n_shards=2, fmt=fmt)

    cfg_mem, params_mem = import_hf_model(model)
    cfg_disk, params_disk = load_hf_checkpoint(str(tmp_path))
    assert cfg_disk == cfg_mem
    for a, b in zip(jax.tree_util.tree_leaves(params_disk),
                    jax.tree_util.tree_leaves(params_mem)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_init_inference_from_checkpoint_dir_tp2(tmp_path, rng):
    """init_inference(checkpoint=<dir>) under tp=2 generates identically to the
    in-memory import path (parity: ref inference/engine.py:380 checkpoint flow)."""
    hf_cfg = transformers.GPT2Config(
        vocab_size=61, n_positions=64, n_embd=32, n_layer=2, n_head=4)
    torch.manual_seed(2)
    model = transformers.GPT2LMHeadModel(hf_cfg).eval()
    _write_sharded_checkpoint(str(tmp_path), model, n_shards=2)

    ids = rng.integers(0, 61, size=(1, 8)).astype(np.int32)
    eng_disk = deepspeed_tpu.init_inference(
        checkpoint=str(tmp_path), dtype="float32",
        tensor_parallel={"tp_size": 2}, max_out_tokens=32)
    eng_mem = deepspeed_tpu.init_inference(
        model, dtype="float32", tensor_parallel={"tp_size": 2},
        max_out_tokens=32)
    out_disk = np.asarray(eng_disk.generate(ids, max_new_tokens=8,
                                            temperature=0.0))
    out_mem = np.asarray(eng_mem.generate(ids, max_new_tokens=8,
                                          temperature=0.0))
    np.testing.assert_array_equal(out_disk, out_mem)


def test_mp_checkpoint_roundtrip_and_mesh_placement(tmp_path, rng):
    """save_mp_checkpoint/load_mp_checkpoint: tp-presharded export reloads both
    to host (concat) and directly onto a tp=2 mesh with correct shard placement
    (parity: ref save_mp_checkpoint_path resharding)."""
    from deepspeed_tpu.models import gpt as G
    from deepspeed_tpu.module_inject.load_checkpoint import (
        load_mp_checkpoint, save_mp_checkpoint)
    from deepspeed_tpu.runtime.topology import MeshTopology

    cfg = G.GPTConfig(vocab_size=32, n_layer=2, n_head=4, d_model=16,
                      max_seq_len=16)
    params = G.init_params(cfg, jax.random.PRNGKey(0))
    shapes = jax.tree_util.tree_map(lambda x: x, params)
    specs = G.partition_specs(cfg, shapes)
    save_mp_checkpoint(str(tmp_path / "mp"), params, specs, tp_size=2,
                       model_config=cfg)

    # host reload
    host = load_mp_checkpoint(str(tmp_path / "mp"), params, specs, mesh=None)
    for a, b in zip(jax.tree_util.tree_leaves(host),
                    jax.tree_util.tree_leaves(params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=0)

    # direct-to-mesh reload
    topo = MeshTopology.create(tp=2, devices=jax.devices()[:2])
    on_mesh = load_mp_checkpoint(str(tmp_path / "mp"), params, specs,
                                 mesh=topo.mesh)
    qkv = on_mesh["blocks"]["qkv_w"]
    assert not qkv.sharding.is_fully_replicated
    for a, b in zip(jax.tree_util.tree_leaves(on_mesh),
                    jax.tree_util.tree_leaves(params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=0)


def test_streamed_checkpoint_preserves_bf16(tmp_path):
    """A bf16 checkpoint must stream as bf16 (host memory ~= checkpoint size,
    not 2x via an fp32 upcast)."""
    from deepspeed_tpu.module_inject.load_checkpoint import load_hf_checkpoint

    hf_cfg = transformers.GPT2Config(
        vocab_size=32, n_positions=16, n_embd=16, n_layer=1, n_head=2)
    torch.manual_seed(3)
    model = transformers.GPT2LMHeadModel(hf_cfg).to(torch.bfloat16).eval()
    _write_sharded_checkpoint(str(tmp_path), model, n_shards=2)
    _, params = load_hf_checkpoint(str(tmp_path))
    assert params["wte"].dtype == jnp.bfloat16, params["wte"].dtype
    assert params["blocks"]["qkv_w"].dtype == jnp.bfloat16


def test_distilbert_import_matches_hf(rng):
    from deepspeed_tpu.models import bert as B

    hf_cfg = transformers.DistilBertConfig(
        vocab_size=89, dim=32, n_layers=2, n_heads=4, hidden_dim=64,
        max_position_embeddings=64, dropout=0.0, attention_dropout=0.0)
    torch.manual_seed(0)
    model = transformers.DistilBertForMaskedLM(hf_cfg).eval()
    cfg, params = import_hf_model(model)
    ids = rng.integers(0, 89, size=(2, 10)).astype(np.int64)
    hidden = B.encode(cfg, params, jnp.asarray(ids))
    ours = np.asarray(B.mlm_logits(cfg, params, hidden))
    with torch.no_grad():
        theirs = model(torch.from_numpy(ids).long()).logits.numpy()
    np.testing.assert_allclose(ours, theirs, atol=2e-3, rtol=1e-3)


def test_gptneo_import_matches_hf(rng):
    """GPT-Neo's alternating global/local attention must match HF exactly —
    the windowed layers are the point of this policy."""
    hf_cfg = transformers.GPTNeoConfig(
        vocab_size=87, hidden_size=32, num_layers=4, num_heads=4,
        intermediate_size=64, max_position_embeddings=64,
        attention_types=[[["global", "local"], 2]], window_size=8,
        activation_function="gelu_new",
        attention_dropout=0.0, embed_dropout=0.0, resid_dropout=0.0)
    torch.manual_seed(0)
    model = transformers.GPTNeoForCausalLM(hf_cfg).eval()
    # sequence LONGER than the window so local masking is actually exercised
    ids = rng.integers(0, 87, size=(2, 24)).astype(np.int64)
    cfg, _ = _compare_logits(model, ids)
    assert cfg.local_attention_period == 2 and cfg.window_size == 8


@pytest.mark.slow
def test_gptneo_cached_decode_matches_full_forward(rng):
    """The cached (generate) path must honor the local-attention window too."""
    from deepspeed_tpu.models import gpt as G

    hf_cfg = transformers.GPTNeoConfig(
        vocab_size=61, hidden_size=32, num_layers=2, num_heads=4,
        intermediate_size=64, max_position_embeddings=64,
        attention_types=[[["global", "local"], 1]], window_size=4,
        attention_dropout=0.0, embed_dropout=0.0, resid_dropout=0.0)
    torch.manual_seed(0)
    cfg, params = import_hf_model(transformers.GPTNeoForCausalLM(hf_cfg).eval())
    ids = rng.integers(0, 61, size=(2, 12)).astype(np.int32)
    full = np.asarray(G.forward(cfg, params, jnp.asarray(ids), train=False))

    cache = G.init_cache(cfg, 2, 16, jnp.float32)
    pre, cache = G.forward_with_cache(cfg, params, jnp.asarray(ids[:, :8]), cache)
    np.testing.assert_allclose(np.asarray(pre), full[:, :8], atol=2e-4, rtol=1e-3)
    for t in range(8, 12):
        step, cache = G.forward_with_cache(
            cfg, params, jnp.asarray(ids[:, t:t + 1]), cache)
        np.testing.assert_allclose(np.asarray(step[:, 0]), full[:, t],
                                   atol=2e-4, rtol=1e-3)


def test_clip_text_import_matches_hf(rng):
    """CLIP text tower (SD's conditioning encoder) hidden states match HF."""
    from deepspeed_tpu.models.diffusion import clip_text_embeddings

    hf_cfg = transformers.CLIPTextConfig(
        vocab_size=77, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=32, hidden_act="quick_gelu")
    torch.manual_seed(0)
    model = transformers.CLIPTextModel(hf_cfg).eval()
    cfg, params = import_hf_model(model)
    assert cfg.activation == "quick_gelu"
    ids = rng.integers(0, 77, size=(2, 10)).astype(np.int64)
    ours = np.asarray(clip_text_embeddings(cfg, params, jnp.asarray(ids)))
    with torch.no_grad():
        theirs = model(torch.from_numpy(ids).long()).last_hidden_state.numpy()
    np.testing.assert_allclose(ours, theirs, atol=2e-3, rtol=1e-3)


def test_clip_text_logits_path_refuses(rng):
    from deepspeed_tpu.models import gpt as G

    hf_cfg = transformers.CLIPTextConfig(
        vocab_size=61, hidden_size=32, num_hidden_layers=1,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=16, hidden_act="quick_gelu")
    torch.manual_seed(0)
    cfg, params = import_hf_model(transformers.CLIPTextModel(hf_cfg).eval())
    ids = jnp.zeros((1, 8), jnp.int32)
    with pytest.raises(ValueError, match="pure encoder"):
        G.forward(cfg, params, ids, train=False)


def test_imported_gpt2_greedy_generate_matches_hf():
    """End-to-end migration check: import a tiny HF GPT-2 and reproduce HF's
    own greedy generate token-for-token through the AOT decode loop."""
    import torch

    from deepspeed_tpu.inference import DeepSpeedInferenceConfig, InferenceEngine
    from deepspeed_tpu.inference.engine import for_gpt
    from deepspeed_tpu.module_inject import import_hf_model

    hf_cfg = transformers.GPT2Config(
        vocab_size=96, n_positions=64, n_embd=32, n_layer=2, n_head=2)
    torch.manual_seed(0)
    hf = transformers.GPT2LMHeadModel(hf_cfg).eval()
    cfg, params = import_hf_model(hf)
    eng = InferenceEngine(for_gpt(cfg, params),
                          DeepSpeedInferenceConfig(dtype="float32",
                                                   max_out_tokens=40))
    ids = np.random.default_rng(3).integers(0, 96, (2, 6), np.int32)
    with torch.no_grad():
        theirs = hf.generate(torch.from_numpy(ids).long(), max_new_tokens=8,
                             do_sample=False,
                             pad_token_id=0).numpy()
    ours = np.asarray(eng.generate(ids, max_new_tokens=8))
    np.testing.assert_array_equal(ours, theirs)


@pytest.mark.parametrize("family", ["gptneox", "opt", "bloom", "gptj", "gptneo"])
def test_imported_model_greedy_generate_matches_hf(family):
    """Rope (NeoX) and offset-positions (OPT) decode paths also reproduce
    HF's greedy generate on imported weights."""
    import torch

    from deepspeed_tpu.inference import DeepSpeedInferenceConfig, InferenceEngine
    from deepspeed_tpu.inference.engine import for_gpt
    from deepspeed_tpu.module_inject import import_hf_model

    torch.manual_seed(1)
    if family == "gptneox":
        hf = transformers.GPTNeoXForCausalLM(transformers.GPTNeoXConfig(
            vocab_size=96, hidden_size=32, num_hidden_layers=2,
            num_attention_heads=2, intermediate_size=64,
            max_position_embeddings=64, rotary_pct=1.0)).eval()
    elif family == "opt":
        hf = transformers.OPTForCausalLM(transformers.OPTConfig(
            vocab_size=96, hidden_size=32, num_hidden_layers=2,
            num_attention_heads=2, ffn_dim=64,
            max_position_embeddings=64, do_layer_norm_before=True)).eval()
    elif family == "bloom":
        hf = transformers.BloomForCausalLM(transformers.BloomConfig(
            vocab_size=96, hidden_size=32, n_layer=2, n_head=2)).eval()
    elif family == "gptj":
        hf = transformers.GPTJForCausalLM(transformers.GPTJConfig(
            vocab_size=96, n_embd=32, n_layer=2, n_head=2, rotary_dim=16,
            n_positions=64)).eval()
    else:
        hf = transformers.GPTNeoForCausalLM(transformers.GPTNeoConfig(
            vocab_size=96, hidden_size=32, num_layers=2, num_heads=2,
            attention_types=[[["global", "local"], 1]], window_size=4,
            max_position_embeddings=64)).eval()
    cfg, params = import_hf_model(hf)
    eng = InferenceEngine(for_gpt(cfg, params),
                          DeepSpeedInferenceConfig(dtype="float32",
                                                   max_out_tokens=40))
    ids = np.random.default_rng(4).integers(5, 90, (1, 6), np.int32)
    with torch.no_grad():
        theirs = hf.generate(torch.from_numpy(ids).long(), max_new_tokens=6,
                             do_sample=False, pad_token_id=0).numpy()
    ours = np.asarray(eng.generate(ids, max_new_tokens=6))
    np.testing.assert_array_equal(ours, theirs)
