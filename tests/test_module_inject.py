"""HF-model import policies + AutoTP + int8 inference.

The strongest parity check available: build tiny randomly-initialized HF models
locally (no network), import their weights, and compare our logits against the
HF torch forward — mirroring the reference's test_inference.py discipline of
comparing injected kernels against the HF pipeline output.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.module_inject import auto_tp_specs, import_hf_model

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


def _compare_logits(hf_model, input_ids: np.ndarray, atol=2e-3):
    cfg, params = import_hf_model(hf_model)
    from deepspeed_tpu.models import gpt as G

    ours = np.asarray(G.forward(cfg, params, jnp.asarray(input_ids), train=False))
    with torch.no_grad():
        theirs = hf_model(torch.from_numpy(input_ids).long()).logits.numpy()
    np.testing.assert_allclose(ours, theirs, atol=atol, rtol=1e-3)
    return cfg, params


def test_gpt2_import_matches_hf(rng):
    hf_cfg = transformers.GPT2Config(
        vocab_size=97, n_positions=64, n_embd=32, n_layer=2, n_head=4)
    torch.manual_seed(0)
    model = transformers.GPT2LMHeadModel(hf_cfg).eval()
    ids = rng.integers(0, 97, size=(2, 12)).astype(np.int64)
    cfg, _ = _compare_logits(model, ids)
    assert cfg.activation == "gelu" and not cfg.rotary


def test_gptneox_import_matches_hf(rng):
    hf_cfg = transformers.GPTNeoXConfig(
        vocab_size=91, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, rotary_pct=0.25,
        use_parallel_residual=True)
    torch.manual_seed(0)
    model = transformers.GPTNeoXForCausalLM(hf_cfg).eval()
    ids = rng.integers(0, 91, size=(2, 10)).astype(np.int64)
    cfg, _ = _compare_logits(model, ids)
    assert cfg.rotary and cfg.parallel_residual and not cfg.tie_embeddings


def test_opt_import_matches_hf(rng):
    hf_cfg = transformers.OPTConfig(
        vocab_size=99, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, ffn_dim=64, max_position_embeddings=64,
        do_layer_norm_before=True, activation_function="relu",
        word_embed_proj_dim=32)
    torch.manual_seed(0)
    model = transformers.OPTForCausalLM(hf_cfg).eval()
    ids = rng.integers(0, 99, size=(2, 10)).astype(np.int64)
    cfg, _ = _compare_logits(model, ids)
    assert cfg.activation == "relu" and cfg.pos_offset == 2


def test_bloom_import_matches_hf(rng):
    hf_cfg = transformers.BloomConfig(
        vocab_size=93, hidden_size=32, n_layer=2, n_head=4,
        layer_norm_epsilon=1e-5)
    torch.manual_seed(0)
    model = transformers.BloomForCausalLM(hf_cfg).eval()
    ids = rng.integers(0, 93, size=(2, 10)).astype(np.int64)
    cfg, _ = _compare_logits(model, ids)
    assert cfg.alibi and cfg.embed_layernorm and cfg.tie_embeddings


def test_gptj_import_matches_hf(rng):
    hf_cfg = transformers.GPTJConfig(
        vocab_size=95, n_positions=64, n_embd=32, n_layer=2, n_head=4,
        rotary_dim=4)
    torch.manual_seed(0)
    model = transformers.GPTJForCausalLM(hf_cfg).eval()
    ids = rng.integers(0, 95, size=(2, 10)).astype(np.int64)
    cfg, _ = _compare_logits(model, ids)
    assert cfg.rotary_interleaved and cfg.parallel_residual and cfg.lm_head_bias


def test_unknown_architecture_raises():
    class Fake:
        pass

    with pytest.raises(ValueError, match="no import policy"):
        import_hf_model(Fake())


def test_init_inference_accepts_hf_model(rng):
    hf_cfg = transformers.GPT2Config(
        vocab_size=97, n_positions=64, n_embd=32, n_layer=2, n_head=4)
    torch.manual_seed(0)
    model = transformers.GPT2LMHeadModel(hf_cfg).eval()
    engine = deepspeed_tpu.init_inference(model, dtype="float32")
    ids = rng.integers(0, 97, size=(1, 8)).astype(np.int32)
    out = engine.generate(ids, max_new_tokens=4)
    assert out.shape == (1, 12)
    # greedy continuation matches HF's own greedy generate
    with torch.no_grad():
        ref = model.generate(torch.from_numpy(ids).long(), max_new_tokens=4,
                             do_sample=False).numpy()
    np.testing.assert_array_equal(out, ref)


# ------------------------------------------------------------------- AutoTP
def test_auto_tp_specs_heuristics(rng):
    from jax.sharding import PartitionSpec as P

    params = {
        "wte": jnp.zeros((64, 16)),
        "h": {"qkv_w": jnp.zeros((16, 48)), "attn_out_w": jnp.zeros((16, 16)),
              "c_fc_w": jnp.zeros((16, 64)), "c_proj_w": jnp.zeros((64, 16)),
              "ln_scale": jnp.zeros((16,))},
    }
    specs = auto_tp_specs(params)
    assert specs["wte"] == P("tp", None)  # vocab-parallel
    assert specs["h"]["qkv_w"] == P(None, "tp")  # column
    assert specs["h"]["c_fc_w"] == P(None, "tp")  # column
    assert specs["h"]["c_proj_w"] == P("tp", None)  # row
    assert specs["h"]["ln_scale"] == P(None)


def test_auto_tp_skips_indivisible():
    from jax.sharding import PartitionSpec as P

    params = {"odd_w": jnp.zeros((16, 17))}
    specs = auto_tp_specs(params, tp_size=4)
    assert specs["odd_w"] == P(None, None)


def test_auto_tp_engine_runs_on_mesh(rng):
    """Unknown adapter without partition_specs: AutoTP shards it over tp=2."""
    from deepspeed_tpu.inference.engine import InferenceEngine, for_gpt
    from deepspeed_tpu.models.gpt import GPTConfig, init_params

    cfg = GPTConfig(vocab_size=64, d_model=32, n_layer=2, n_head=4, max_seq_len=32)
    params = init_params(cfg, jax.random.PRNGKey(0))

    class NoSpecs:
        """Adapter without partition_specs — forces the AutoTP path."""

        def __init__(self, inner):
            self.params = inner.params
            self._inner = inner

        def init_cache(self, *a, **k):
            return self._inner.init_cache(*a, **k)

        def prefill(self, *a, **k):
            return self._inner.prefill(*a, **k)

    from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig
    from deepspeed_tpu.runtime.topology import MeshTopology

    topo = MeshTopology.create(dp=4, tp=2)
    engine = InferenceEngine(
        NoSpecs(for_gpt(cfg, params)),
        DeepSpeedInferenceConfig(dtype="float32", tensor_parallel={"tp_size": 2}),
        topology=topo)
    ids = rng.integers(0, 64, size=(1, 8)).astype(np.int32)
    out = engine.generate(ids, max_new_tokens=4)
    assert out.shape == (1, 12)


# ------------------------------------------------------------------- int8
def test_int8_inference_close_to_fp(rng):
    from deepspeed_tpu.inference.engine import InferenceEngine, for_gpt
    from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig
    from deepspeed_tpu.models.gpt import GPTConfig, init_params

    cfg = GPTConfig(vocab_size=64, d_model=32, n_layer=2, n_head=4, max_seq_len=32)
    params = init_params(cfg, jax.random.PRNGKey(0))
    ids = rng.integers(0, 64, size=(1, 8)).astype(np.int32)

    e_fp = InferenceEngine(for_gpt(cfg, params),
                           DeepSpeedInferenceConfig(dtype="float32"))
    e_q = InferenceEngine(for_gpt(cfg, params),
                          DeepSpeedInferenceConfig(
                              dtype="float32",
                              quant={"enabled": True, "bits": 8, "group_size": 32}))
    assert e_q._quant_scales is not None
    l_fp = np.asarray(e_fp.forward(ids))
    l_q = np.asarray(e_q.forward(ids))
    # int8 weights: logits close but not identical
    assert not np.array_equal(l_fp, l_q)
    np.testing.assert_allclose(l_q, l_fp, atol=0.5, rtol=0.1)
    # same argmax on most positions (weight-only int8 keeps predictions)
    agree = (l_fp.argmax(-1) == l_q.argmax(-1)).mean()
    assert agree >= 0.8
