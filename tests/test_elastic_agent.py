"""Elastic agent: kill-and-resume at a different dp (VERDICT r2 'next' #6).

Parity: ``DSElasticAgent`` (``/root/reference/deepspeed/elasticity/
elastic_agent.py:23``) — worker failure triggers a restart; a membership change
relaunches at the new world size with the SAME effective batch (elastic batch
math) and training resumes from the universal checkpoint with continuing loss.
"""

import json
import os
import sys

import numpy as np
import pytest

from deepspeed_tpu.elasticity import ElasticityError
from deepspeed_tpu.elasticity.elastic_agent import DSElasticAgent, WorkerSpec

ELASTIC_CONFIG = {
    "elasticity": {
        "enabled": True,
        "max_train_batch_size": 16,
        "micro_batch_sizes": [2, 4],
        "min_gpus": 1,
        "max_gpus": 8,
        "prefer_larger_batch": True,
        "version": 0.2,
    }
}


def test_resolve_keeps_effective_batch():
    agent = DSElasticAgent(lambda s: ["true"], ELASTIC_CONFIG)
    s4 = agent.resolve(4)
    s2 = agent.resolve(2)
    assert s4.global_batch == s2.global_batch == 16
    assert s4.micro_batch * s4.gas * s4.world_size == 16
    assert s2.micro_batch * s2.gas * s2.world_size == 16
    # world 3 is not a valid size: falls back to the largest valid <= 3
    s3 = agent.resolve(3)
    assert s3.world_size == 2
    with pytest.raises(ElasticityError):
        agent.resolve(0)


@pytest.mark.slow
def test_kill_and_resume_at_new_dp(tmp_path):
    """Worker crashes mid-run at world=4; the cluster 'shrinks' to 2; the agent
    relaunches at dp=2 with identical effective batch and the loss continues
    from the checkpoint instead of restarting."""
    ckpt = tmp_path / "ckpt"
    log = tmp_path / "log.jsonl"
    worker = os.path.join(os.path.dirname(__file__), "elastic_worker.py")
    world_file = tmp_path / "world"
    world_file.write_text("4")

    total_steps, crash_at = 6, 2

    def device_count():
        return int(world_file.read_text())

    launches = []

    def make_cmd(spec: WorkerSpec):
        launches.append(spec)
        if len(launches) == 1:
            # the first worker crashes mid-run AND shrinks the cluster at the
            # moment of the crash (a lost node): the agent must re-resolve
            crash = ["--crash-at", str(crash_at),
                     "--on-crash-write", f"{world_file}:2"]
        else:
            crash = []
        env_clean = [sys.executable, worker,
                     "--ckpt-dir", str(ckpt), "--log", str(log),
                     "--steps", str(total_steps),
                     "--elastic-world", str(spec.world_size),
                     "--elastic-micro", str(spec.micro_batch),
                     "--elastic-gas", str(spec.gas)]
        return env_clean + crash

    agent = DSElasticAgent(make_cmd, ELASTIC_CONFIG,
                           device_count_fn=device_count, max_restarts=3,
                           poll_interval=0.2)
    result = agent.run()
    assert result.state == "SUCCEEDED"
    # the crash arrived WITH the membership change: budget-free (like a
    # drained preemption), counted as a membership change, not a restart
    assert result.restarts == 0
    assert result.membership_changes == 1
    assert [s.world_size for s in launches] == [4, 2]

    records = [json.loads(ln) for ln in log.read_text().splitlines()]
    # identical effective batch across the resize
    assert {r["effective"] for r in records} == {16}
    # run 2 resumed from the checkpoint: steps continue, no reset to 1
    steps = [r["step"] for r in records]
    assert steps == sorted(steps)
    run2 = [r for r in records if r["world"] == 2]
    run1 = [r for r in records if r["world"] == 4]
    assert run1 and run2
    assert run2[0]["step"] == crash_at + 1
    assert run2[-1]["step"] == total_steps
    # loss continues (training on random data: resumed loss stays below the
    # cold-start loss and remains finite)
    assert run2[0]["loss"] < run1[0]["loss"]
    assert all(np.isfinite(r["loss"]) for r in records)


# ----------------------------------------------------------- resilience (PR 4)
def _fake_committed_ckpt(ckpt_dir, tags):
    """Minimal committed tags + latest pointer, no engine involved."""
    from deepspeed_tpu.resilience import commit_tag, write_latest

    for i, t in enumerate(tags):
        tag_dir = os.path.join(str(ckpt_dir), t)
        os.makedirs(os.path.join(tag_dir, "state"), exist_ok=True)
        with open(os.path.join(tag_dir, "state", "state.msgpack"), "wb") as f:
            f.write(bytes([i]) * 64)
        commit_tag(tag_dir)
    write_latest(str(ckpt_dir), tags[-1])


def test_crash_loop_quarantines_poisoned_tag(tmp_path):
    """K consecutive failures while 'latest' points at one tag quarantine it:
    the next resume falls back to the previous committed tag instead of
    crash-looping on the poisoned one until max_restarts."""
    from deepspeed_tpu.resilience import is_committed, read_events, read_latest

    ckpt = tmp_path / "ckpt"
    _fake_committed_ckpt(ckpt, ["global_step1", "global_step2"])
    agent = DSElasticAgent(
        lambda s: [sys.executable, "-c", "import sys; sys.exit(3)"],
        ELASTIC_CONFIG, device_count_fn=lambda: 4, max_restarts=3,
        poll_interval=0.05, checkpoint_dir=str(ckpt), crash_loop_threshold=2,
        backoff_base=0.01, backoff_max=0.05)
    result = agent.run()
    assert result.state == "FAILED"
    assert result.quarantined == ["global_step2"]
    assert read_latest(str(ckpt)) == "global_step1"
    assert not is_committed(str(ckpt / "global_step2"))
    assert is_committed(str(ckpt / "global_step1"))
    events = {e["event"] for e in read_events(str(ckpt))}
    assert {"worker_restart", "tag_quarantined"} <= events


def test_preempted_exit_spends_no_restart_budget(tmp_path):
    """Exit code 83 (drained preemption) relaunches immediately and does not
    count as a failure — even with max_restarts=0."""
    from deepspeed_tpu.resilience import read_events
    from deepspeed_tpu.resilience.preemption import PREEMPTED_EXIT_CODE

    ckpt = tmp_path / "ckpt"
    ckpt.mkdir()
    marker = tmp_path / "first_launch_done"
    script = (
        "import os, sys\n"
        f"p = {str(marker)!r}\n"
        "if not os.path.exists(p):\n"
        f"    open(p, 'w').write('x'); sys.exit({PREEMPTED_EXIT_CODE})\n"
        "sys.exit(0)\n")
    agent = DSElasticAgent(
        lambda s: [sys.executable, "-c", script],
        ELASTIC_CONFIG, device_count_fn=lambda: 4, max_restarts=0,
        poll_interval=0.05, checkpoint_dir=str(ckpt))
    result = agent.run()
    assert result.state == "SUCCEEDED"
    assert result.restarts == 0
    assert result.preemptions == 1
    assert any(e["event"] == "preemption_restart"
               for e in read_events(str(ckpt)))
