"""Pipeline parallelism tests.

Mirrors the reference's ``tests/unit/pipe/`` coverage: schedule semantics
(CPU-only math), partitioning, and — the TPU upgrade — end-to-end numerics of the
SPMD collective-permute pipeline vs the dense single-program model on a simulated
mesh (sharded == unsharded discipline, SURVEY.md §4).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.models import gpt, gpt_pipe
from deepspeed_tpu.runtime.pipe import (
    DataParallelSchedule,
    InferenceSchedule,
    LayerSpec,
    PipelineModule,
    TiedLayerSpec,
    TrainSchedule,
    bubble_fraction,
    partition_balanced,
    partition_uniform,
    pipelined_apply,
    split_microbatches,
    stack_stage_params,
    unstack_stage_params,
)
from deepspeed_tpu.runtime.pipe.schedule import (
    BackwardPass,
    ForwardPass,
    verify_schedule,
)
from deepspeed_tpu.runtime.topology import MeshTopology, mesh_context


# ----------------------------------------------------------------- schedule math
@pytest.mark.parametrize("micro,stages", [(4, 2), (8, 4), (3, 3), (1, 2), (8, 1)])
def test_train_schedule_covers_all_microbatches(micro, stages):
    for stage in range(stages):
        sched = TrainSchedule(micro_batches=micro, stages=stages, stage_id=stage)
        assert verify_schedule(sched.steps(), micro, is_train=True)


@pytest.mark.parametrize("micro,stages", [(4, 2), (8, 4), (2, 2)])
def test_inference_schedule_covers_all_microbatches(micro, stages):
    for stage in range(stages):
        sched = InferenceSchedule(micro_batches=micro, stages=stages, stage_id=stage)
        assert verify_schedule(sched.steps(), micro, is_train=False)


def test_train_schedule_1f1b_order():
    # once warm, fwd/bwd alternate; bwd of micro i on last stage directly follows fwd i
    sched = TrainSchedule(micro_batches=4, stages=2, stage_id=1)
    seq = []
    for cmds in sched.steps():
        for c in cmds:
            if isinstance(c, (ForwardPass, BackwardPass)):
                seq.append((type(c).__name__, c.buffer_id))
    # last stage: F0 B0 F1 B1 ... (1F1B)
    kinds = [k for k, _ in seq]
    assert kinds[:4] == ["ForwardPass", "BackwardPass", "ForwardPass", "BackwardPass"]


def test_train_schedule_buffer_counts():
    assert TrainSchedule(8, 4, 0).num_pipe_buffers() == 4
    assert TrainSchedule(8, 4, 3).num_pipe_buffers() == 2
    assert TrainSchedule(1, 4, 0).num_pipe_buffers() == 2
    assert DataParallelSchedule(4, 1, 0).num_pipe_buffers() == 1


def test_bubble_fraction():
    assert bubble_fraction(8, 1) == 0.0
    assert np.isclose(bubble_fraction(4, 4), 3 / 7)


# ----------------------------------------------------------------- partitioning
def test_partition_uniform():
    assert partition_uniform(8, 4) == [0, 2, 4, 6, 8]
    assert partition_uniform(7, 3) == [0, 3, 5, 7]


def test_partition_balanced():
    bounds = partition_balanced([1, 1, 1, 1, 100], 2)
    assert bounds == [0, 4, 5]  # heavy item isolated
    bounds = partition_balanced([1] * 8, 4)
    assert bounds == [0, 2, 4, 6, 8]


def test_pipeline_module_partition_and_tied():
    def make_layer(i):
        return LayerSpec(
            init=lambda rng: {"w": jnp.ones((2, 2)) * i},
            apply=lambda w, x: x @ w["w"],
            name=f"block{i}", param_count=4)

    specs = [TiedLayerSpec("embed", lambda rng: {"e": jnp.ones((2,))},
                           lambda w, x: x, name="embed", param_count=2)]
    specs += [make_layer(i) for i in range(4)]
    specs += [TiedLayerSpec("embed", lambda rng: {"e": jnp.zeros((2,))},
                            lambda w, x: x, name="head", param_count=2)]
    pm = PipelineModule(specs, num_stages=2, partition_method="uniform")
    assert pm.parts[0] == 0 and pm.parts[-1] == 6
    assert pm.tied_keys == ["embed"]
    params = pm.init(jax.random.PRNGKey(0))
    # tied built once, first spec wins
    assert float(params["tied"]["embed"]["e"][0]) == 1.0
    out = pm.apply(params, jnp.eye(2))
    assert out.shape == (2, 2)


def test_pipeline_module_type_regex_partition():
    specs = [LayerSpec(lambda rng: {}, lambda w, x: x, name="embed")]
    specs += [LayerSpec(lambda rng: {}, lambda w, x: x, name=f"transformerlayer{i}",
                        param_count=10) for i in range(4)]
    pm = PipelineModule(specs, num_stages=2, partition_method="type:transformer")
    # both stages get 2 transformer layers each
    counts = [sum("transformer" in s.name for s in pm.stage_layers(i)) for i in range(2)]
    assert counts == [2, 2]


# ----------------------------------------------------------------- spmd executor
def test_stack_unstack_roundtrip():
    tree = {"w": jnp.arange(24.0).reshape(8, 3)}
    stacked = stack_stage_params(tree, 4)
    assert stacked["w"].shape == (4, 2, 3)
    back = unstack_stage_params(stacked)
    np.testing.assert_array_equal(back["w"], tree["w"])


def test_pipelined_apply_matches_sequential():
    """The rotating-buffer pipeline == applying all layers sequentially."""
    S, L_per, D, M, mb = 4, 2, 8, 4, 2
    rng = jax.random.PRNGKey(0)
    w = jax.random.normal(rng, (S, L_per, D, D)) * 0.3
    x = jax.random.normal(jax.random.PRNGKey(1), (M, mb, D))

    def stage_fn(w, x, micro_id, stage_id):
        def body(x, lw):
            return jnp.tanh(x @ lw), None
        x, _ = jax.lax.scan(body, x, w)
        return x

    out = jax.jit(lambda w, x: pipelined_apply(stage_fn, w, x, S, remat=False))(w, x)

    # sequential reference
    ref = x
    for s in range(S):
        ref = jax.vmap(lambda xm: stage_fn(w[s], xm, 0, 0))(ref)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_pipelined_apply_grads_match_sequential():
    S, L_per, D, M, mb = 2, 1, 4, 4, 2
    w = jax.random.normal(jax.random.PRNGKey(0), (S, L_per, D, D)) * 0.3
    x = jax.random.normal(jax.random.PRNGKey(1), (M, mb, D))

    def stage_fn(w, x, micro_id, stage_id):
        def body(x, lw):
            return jnp.tanh(x @ lw), None
        x, _ = jax.lax.scan(body, x, w)
        return x

    def loss_pipe(w):
        return jnp.sum(pipelined_apply(stage_fn, w, x, S, remat=True) ** 2)

    def loss_seq(w):
        y = x
        for s in range(S):
            y = jax.vmap(lambda xm: stage_fn(w[s], xm, 0, 0))(y)
        return jnp.sum(y ** 2)

    g_pipe = jax.jit(jax.grad(loss_pipe))(w)
    g_seq = jax.jit(jax.grad(loss_seq))(w)
    np.testing.assert_allclose(np.asarray(g_pipe), np.asarray(g_seq),
                               rtol=2e-4, atol=2e-5)


# ----------------------------------------------------------------- gpt end-to-end
def test_gpt_pipe_matches_dense_on_mesh():
    """Pipelined GPT (pp=4, dp=2) forward loss == dense GPT (dp=8) loss."""
    cfg = gpt.GPTConfig(vocab_size=64, n_layer=4, n_head=2, d_model=32,
                        max_seq_len=32, dropout=0.0)
    rng = jax.random.PRNGKey(0)
    dense_params = gpt.init_params(cfg, rng)
    ids = np.random.default_rng(0).integers(0, 64, size=(8, 16), dtype=np.int32)
    batch = {"input_ids": jnp.asarray(ids)}

    dense_loss, _ = jax.jit(
        lambda p: gpt.loss_fn(cfg, p, batch, train=False))(dense_params)

    topo = MeshTopology.create(pp=4, dp=2)
    pipe_params = dict(dense_params)
    pipe_params["blocks"] = stack_stage_params(dense_params["blocks"], 4)
    module, _ = gpt_pipe.build(cfg, num_stages=4, num_micro=4)
    with mesh_context(topo.mesh):
        pipe_loss, _ = jax.jit(
            lambda p: module.apply(p, batch, train=False))(pipe_params)
    np.testing.assert_allclose(float(pipe_loss), float(dense_loss), rtol=1e-4)


@pytest.mark.slow
def test_gpt_pipe_trains_with_engine():
    """Full engine integration: ZeRO-1 + pp=2 mesh; loss decreases."""
    import deepspeed_tpu as ds

    cfg = gpt.GPTConfig(vocab_size=64, n_layer=2, n_head=2, d_model=32,
                        max_seq_len=32, dropout=0.0)
    module, _ = gpt_pipe.build(cfg, num_stages=2, num_micro=2)
    config = {
        "train_micro_batch_size_per_gpu": 1,
        "train_batch_size": 4,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 1},
        "mesh": {"pp": 2, "dp": 4},
        "bf16": {"enabled": False},
    }
    engine, _, _, _ = ds.initialize(model=module, config=config)
    r = np.random.default_rng(0)
    losses = []
    ids = r.integers(0, 64, size=(4, 16), dtype=np.int32)
    for _ in range(8):
        m = engine.train_batch({"input_ids": ids})
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0], losses


# ----------------------------------------------------------------- MPMD 1F1B
def _tiny_lm_module(vocab=31, d=16, n_mlp=6, num_stages=4):
    """Heterogeneous pipeline: tied embedding -> residual MLPs -> tied head."""
    from deepspeed_tpu.runtime.pipe.mpmd import MPMDPipelineEngine  # noqa: F401

    def emb_init(rng):
        return jax.random.normal(rng, (vocab, d), jnp.float32) * 0.05

    def emb_apply(w, ids):
        return w[ids]

    def head_apply(w, x):
        return x @ w.T

    def mlp_init(rng):
        k1, k2 = jax.random.split(rng)
        return {"w1": jax.random.normal(k1, (d, 2 * d), jnp.float32) * 0.05,
                "w2": jax.random.normal(k2, (2 * d, d), jnp.float32) * 0.05}

    def mlp_apply(w, x):
        return x + jnp.tanh(x @ w["w1"]) @ w["w2"]

    def loss_fn(logits, mb):
        ids = mb["input_ids"]
        logp = jax.nn.log_softmax(logits[:, :-1], -1)
        tgt = ids[:, 1:]
        nll = -jnp.take_along_axis(logp, tgt[..., None], -1)
        return jnp.mean(nll)

    specs = [TiedLayerSpec("emb", emb_init, emb_apply, name="embed",
                           param_count=vocab * d)]
    specs += [LayerSpec(mlp_init, mlp_apply, name=f"mlp{i}",
                        param_count=4 * d * d) for i in range(n_mlp)]
    specs += [TiedLayerSpec("emb", emb_init, head_apply, name="head",
                            param_count=vocab * d)]
    return PipelineModule(specs, num_stages=num_stages,
                          partition_method="uniform", loss_fn=loss_fn), loss_fn


@pytest.mark.slow
def test_mpmd_1f1b_matches_dense_and_residency():
    """VERDICT r1 #3: the executed 1F1B schedule must (a) reproduce the dense
    loss/grads and (b) hold at most min(stages - stage_id, M) live activation
    buffers per stage — the TrainSchedule.num_pipe_buffers bound (parity:
    reference runtime/pipe/schedule.py:243), NOT GPipe's M."""
    from deepspeed_tpu.runtime.pipe.mpmd import MPMDPipelineEngine

    S, M, mb, T = 4, 8, 2, 12
    module, loss_fn = _tiny_lm_module(num_stages=S)
    eng = MPMDPipelineEngine(module, num_micro=M, devices=jax.devices()[:S])
    params = eng.init(jax.random.PRNGKey(0))

    r = np.random.default_rng(0)
    batch = {"input_ids": r.integers(0, 31, size=(M, mb, T), dtype=np.int32)}

    opt_state = eng.init_optimizer(params)
    new_params, opt_state, metrics = eng.train_batch(
        params, opt_state, batch, apply_update=True)

    # (b) 1F1B residency bound, per stage
    assert eng.peak_live_buffers == [min(S - s, M) for s in range(S)], \
        eng.peak_live_buffers

    # (a) dense reference: same params flattened, mean loss over micros
    full = module.init(jax.random.PRNGKey(0))

    def dense_loss(full_params):
        losses = []
        for m in range(M):
            out = module.apply(full_params, batch["input_ids"][m])
            losses.append(loss_fn(out, {"input_ids": batch["input_ids"][m]}))
        return jnp.mean(jnp.stack(losses))

    ref_loss, ref_grads = jax.value_and_grad(dense_loss)(full)
    np.testing.assert_allclose(float(metrics["loss"]), float(ref_loss),
                               rtol=2e-5)
    # grads match per stage and for tied weights
    for s in range(S):
        lo, hi = module.parts[s], module.parts[s + 1]
        got = jax.tree_util.tree_leaves(metrics["grads"]["stages"][s])
        want = jax.tree_util.tree_leaves([ref_grads["layers"][i]
                                          for i in range(lo, hi)])
        for g, w in zip(got, want):
            np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                       rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(metrics["grads"]["tied"]["emb"]),
                               np.asarray(ref_grads["tied"]["emb"]),
                               rtol=1e-4, atol=1e-6)
    # the step actually moved the params
    moved = jax.tree_util.tree_leaves(
        jax.tree_util.tree_map(lambda a, b: jnp.max(jnp.abs(a - b)),
                               new_params["tied"], params["tied"]))
    assert any(float(x) > 0 for x in moved)


def test_mpmd_heterogeneous_stage_loss_decreases():
    """Heterogeneous stages (embed | mlps | mlps | head) train end to end."""
    from deepspeed_tpu.runtime.pipe.mpmd import MPMDPipelineEngine

    S, M, mb, T = 3, 4, 2, 10
    module, _ = _tiny_lm_module(vocab=23, d=12, n_mlp=4, num_stages=S)
    eng = MPMDPipelineEngine(module, num_micro=M, devices=jax.devices()[:S],
                             lr=0.1)
    params = eng.init(jax.random.PRNGKey(1))
    opt_state = eng.init_optimizer(params)
    r = np.random.default_rng(1)
    batch = {"input_ids": r.integers(0, 23, size=(M, mb, T), dtype=np.int32)}
    losses = []
    for _ in range(6):
        params, opt_state, metrics = eng.train_batch(params, opt_state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0], losses


def test_mpmd_inference_schedule_forward():
    from deepspeed_tpu.runtime.pipe.mpmd import MPMDPipelineEngine

    S, M, mb, T = 4, 4, 2, 8
    module, _ = _tiny_lm_module(num_stages=S)
    eng = MPMDPipelineEngine(module, num_micro=M, devices=jax.devices()[:S])
    params = eng.init(jax.random.PRNGKey(0))
    r = np.random.default_rng(2)
    batch = {"input_ids": r.integers(0, 31, size=(M, mb, T), dtype=np.int32)}
    out = eng.forward_batch(params, batch)
    full = module.init(jax.random.PRNGKey(0))
    for m in range(M):
        ref = module.apply(full, batch["input_ids"][m])
        np.testing.assert_allclose(np.asarray(out[m]), np.asarray(ref),
                                   rtol=1e-5, atol=1e-6)
