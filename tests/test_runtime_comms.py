"""Measured (runtime) collective accounting from jax.profiler traces.

Parity target: the reference's per-op runtime comms log
(``utils/comms_logging.py:56``) — VERDICT r3 next #8. These run on the
8-device CPU mesh; the trace parser sees the same Chrome-trace thunk names
XLA emits on TPU.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import deepspeed_tpu
from deepspeed_tpu.comm import profile_collectives
from deepspeed_tpu.models import build_gpt
from deepspeed_tpu.models.gpt import GPTConfig


@pytest.mark.slow
def test_profile_collectives_sees_psum():
    # GSPMD formulation: a sharded->replicated reduction lowers to an
    # all-reduce thunk, which is what appears on the device timeline (the
    # shard_map psum lowers to a host rendezvous on the CPU backend and is
    # deliberately not asserted here)
    mesh = Mesh(np.array(jax.devices()), ("x",))

    @jax.jit
    def fn(x):
        x = jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P("x")))
        return jax.lax.with_sharding_constraint(
            jnp.sum(x ** 2), NamedSharding(mesh, P()))

    x = jax.device_put(jnp.ones((len(jax.devices()), 128)),
                       NamedSharding(mesh, P("x")))
    fn(x).block_until_ready()  # compile outside the trace
    prof = profile_collectives(lambda: fn(x))
    assert "all-reduce" in prof.ops, prof.ops
    assert prof.ops["all-reduce"].count >= 1
    assert prof.ops["all-reduce"].time_us >= 0.0
    assert "all-reduce" in prof.summary()


@pytest.mark.slow
def test_engine_comms_verify_reports_measured():
    model, cfg = build_gpt(GPTConfig(
        vocab_size=64, d_model=32, n_layer=2, n_head=2, max_seq_len=32))
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config={
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 2},
        "steps_per_print": 0})
    b = {"input_ids": np.random.default_rng(0).integers(
        0, 64, (16, 16), dtype=np.int32)}
    engine.train_batch(b)  # compile outside the trace
    out = engine.comms_verify(b)
    assert "measured collectives" in out
    # ZeRO-2 over dp=8 must reduce gradients: GSPMD-inserted collectives are
    # exactly what trace-time facade accounting cannot see
    assert any(k in out for k in ("all-reduce", "reduce-scatter",
                                  "all-gather"))


@pytest.mark.slow
def test_ds_bench_verify_flag(capsys):
    from deepspeed_tpu.benchmarks.communication import main

    rc = main(["--ops", "all_reduce", "--maxsize", "4096", "--trials", "2",
               "--verify", "--json"])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()[-1]
    import json

    rows = json.loads(out)["verify"]
    assert rows[0]["op"] == "all_reduce"
    assert rows[0]["est_latency_us"] > 0
    # on the CPU backend shard_map collectives run as host rendezvous (no
    # device thunks), so measured_ops may be empty here; on TPU the XLA
    # collective thunks appear (structure asserted, contents backend-specific)
    assert isinstance(rows[0]["measured_ops"], dict)
    assert rows[0]["measured_device_us"] >= 0
