"""Streamed ZeRO-Infinity host offload (docs/OFFLOAD.md): the double-buffered
host<->HBM DMA pipeline against the layer scan.

Contracts under test:
- the pipelined schedule (``prefetch_schedule`` / ``UnitFetchStream``) issues
  ahead and consumes in order — streamed training is BITWISE identical to
  fetch-on-demand at depths 1 and 2;
- quantized host fetches are tolerance-gated and ledger-recorded (the
  ``qpush[host-dma]`` ratio);
- an injected DMA hang (``FaultPlan.stall_offload_at``) trips the
  ``offload_fetch`` watchdog deadline;
- a SIGKILL mid host-shard flush leaves the previous committed tag loadable
  and resume from it is step-exact;
- the ``offload/unstreamed-host-fetch`` dslint rule fires/stays silent.
"""

import json
import os
import signal
import subprocess
import sys
import time
from types import SimpleNamespace

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.runtime.zero.gather import prefetch_schedule
from deepspeed_tpu.runtime.zero.stream import UnitFetchStream

WORKER = os.path.join(os.path.dirname(__file__), "offload_worker.py")


def _engine(config_extra=None, vocab=64, n_layer=4):
    from deepspeed_tpu.models import build_gpt
    from deepspeed_tpu.models.gpt import GPTConfig

    model, cfg = build_gpt(GPTConfig(
        vocab_size=vocab, d_model=32, n_layer=n_layer, n_head=2,
        max_seq_len=32))
    config = {
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "steps_per_print": 0,
    }
    config.update(config_extra or {})
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)
    return engine, cfg


def _batch(cfg, seed=0, bs=16, seq=16):
    r = np.random.default_rng(seed)
    return {"input_ids": r.integers(0, cfg.vocab_size, size=(bs, seq),
                                    dtype=np.int32)}


def _stream_cfg(**op):
    # buffer_count=1: only one layer cached for backward, so the backward
    # pass genuinely streams (the default 5 would cache every test layer)
    return {"zero_optimization": {"offload_param": {
        "device": "cpu", "buffer_count": 1, **op}}}


# ------------------------------------------------------------------ schedule
def test_prefetch_schedule_orders():
    for n, d in [(5, 0), (5, 1), (5, 2), (5, 4), (3, 8), (0, 2), (1, 0)]:
        events = list(prefetch_schedule(n, d))
        issues = [i for k, i in events if k == "issue"]
        consumes = [i for k, i in events if k == "consume"]
        assert issues == list(range(n)), (n, d)
        assert consumes == list(range(n)), (n, d)
        # every unit's issue precedes its consume, by exactly min(d, ...) slots
        for i in range(n):
            assert events.index(("issue", i)) < events.index(("consume", i))
        # at consume i, units 0..min(i+d, n-1) have been issued (the carry
        # holds d windows in flight — zero3_layer_scan's pbody, on the host)
        for i in range(n):
            pos = events.index(("consume", i))
            issued = {j for k, j in events[:pos] if k == "issue"}
            assert issued == set(range(min(i + max(d, 0) + 1, n))), (n, d, i)


def test_unit_fetch_stream_mechanics():
    issued = []

    def fetch(name):
        issued.append(name)
        return np.zeros(2)

    s = UnitFetchStream(fetch, ["a", "b", "c", "d"], depth=2)
    out = s.take("a")
    assert isinstance(out, np.ndarray)
    # depth 2: consuming "a" means a, b AND c's fetches are out already
    assert issued == ["a", "b", "c"]
    s.take("b")
    assert issued == ["a", "b", "c", "d"]
    with pytest.raises(ValueError, match="out-of-order"):
        s.take("b")
    s.take("c")
    s.take("d")

    # depth 0 = fetch-on-demand: nothing issued before the consume point
    issued.clear()
    s0 = UnitFetchStream(fetch, ["a", "b"], depth=0)
    assert issued == []
    s0.take("a")
    assert issued == ["a"]

    # prime() pushes the prologue out before the first take
    issued.clear()
    sp = UnitFetchStream(fetch, ["a", "b", "c"], depth=2)
    sp.prime()
    assert issued == ["a", "b"]
    sp.take("a")
    assert issued == ["a", "b", "c"]


# ------------------------------------------------------------------ numerics
@pytest.mark.parametrize("depth", [1, 2])
def test_streamed_bitwise_matches_fetch_on_demand(depth):
    """Same seed -> identical host masters; the streamed schedule must then
    reproduce the inline trajectory BITWISE (same units, same order — only
    the DMA issue points move)."""
    e_str, cfg = _engine(_stream_cfg(prefetch_depth=depth))
    e_inl, _ = _engine(_stream_cfg(stream=False))
    assert e_str._param_stream.prefetch_depth == depth
    assert e_inl._param_stream.prefetch_depth == 0
    for i in range(3):
        b = _batch(cfg, seed=i)
        m1 = e_str.train_batch(b)
        m2 = e_inl.train_batch(b)
        assert float(m1["loss"]) == float(m2["loss"])
        assert float(m1["grad_norm"]) == float(m2["grad_norm"])
    # updated host masters agree bitwise too
    s1, s2 = e_str._param_stream, e_inl._param_stream
    for i in range(len(s1._leaves)):
        np.testing.assert_array_equal(s1._state[i][0], s2._state[i][0])
    dma = s1.last_stats["host_dma"]
    assert dma["prefetch_depth"] == depth
    assert dma["pushes"] > 0 and dma["waits"] > 0


def test_np_quantize_matches_jnp():
    from deepspeed_tpu.comm.quantized import (
        dequantize_blockwise,
        np_dequantize_blockwise,
        np_quantize_blockwise,
        quantize_blockwise,
    )

    r = np.random.default_rng(0)
    for shape, bits in [((4, 300), 8), ((4, 300), 4), ((7,), 8),
                        ((2, 32), 8)]:
        x = r.normal(size=shape).astype(np.float32)
        qn, sn, zn = np_quantize_blockwise(x, bits=bits, block_size=64)
        qj, sj, zj = quantize_blockwise(x, bits=bits, block_size=64)
        np.testing.assert_array_equal(qn, np.asarray(qj))
        np.testing.assert_array_equal(sn, np.asarray(sj))
        np.testing.assert_array_equal(zn, np.asarray(zj))
        # host and device dequantizers reconstruct identically
        back_n = np_dequantize_blockwise(qn, sn, zn, bits=bits,
                                         orig_size=shape[-1])
        back_j = np.asarray(dequantize_blockwise(qj, sj, zj, bits=bits,
                                                 orig_size=shape[-1]))
        np.testing.assert_array_equal(back_n, back_j)
        assert np.max(np.abs(back_n - x)) <= np.max(sn) * 0.5 + 1e-6


def test_quantized_fetch_tolerance_and_ledger():
    from deepspeed_tpu.comm.runtime_accounting import wire_ledger

    wire_ledger.reset()
    e_q, cfg = _engine(_stream_cfg(quantized_fetch=True))
    e_x, _ = _engine(_stream_cfg())
    for i in range(2):
        b = _batch(cfg, seed=i)
        mq = e_q.train_batch(b)
        mx = e_x.train_batch(b)
        # int8 blocks perturb weights by <= scale/2 — tolerance-gated, never
        # bitwise (that is the exact path's bar)
        assert float(mq["loss"]) == pytest.approx(float(mx["loss"]), rel=0.05)
    assert "qpush[host-dma]" in wire_ledger.records
    # fp32 logical vs int8+scales wire: > 3x even at these short rows
    assert wire_ledger.ratio("qpush") > 3.0
    dma = e_q._param_stream.last_stats["host_dma"]
    assert dma["quantized"] and dma["ratio"] > 3.0
    wire_ledger.reset()


# ------------------------------------------------------------------ watchdog
def test_watchdog_flags_injected_dma_hang(tmp_path):
    from deepspeed_tpu.resilience.chaos import FaultPlan, install_plan
    from deepspeed_tpu.resilience.events import read_events

    e, cfg = _engine({
        **_stream_cfg(prefetch_depth=1),
        "resilience": {"enabled": True, "save_dir": str(tmp_path),
                       "watchdog": {"enabled": True,
                                    "poll_interval_s": 0.05,
                                    "offload_fetch_deadline_s": 0.3,
                                    "escalate": False}}})
    try:
        install_plan(FaultPlan(stall_offload_at=0,
                               stall_offload_seconds=1.2))
        e.train_batch(_batch(cfg))
        deadline = time.monotonic() + 3.0
        while e._watchdog.stall_count == 0 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert e._watchdog.stall_count >= 1
        assert e._watchdog.last_stall[0] == "offload_fetch"
        events = [ev for ev in read_events(
            os.path.join(str(tmp_path), "recovery_events.jsonl"))
            if ev.get("event") == "watchdog_stall"]
        assert events and events[-1]["phase"] == "offload_fetch"
    finally:
        install_plan(None)
        if e._watchdog is not None:
            e._watchdog.stop()


def test_nested_phase_stack_keeps_outer_deadline():
    """offload_fetch nests inside step: the outer phase's deadline must stay
    armed while (and after) the inner one runs."""
    from deepspeed_tpu.resilience.watchdog import HealthWatchdog

    wd = HealthWatchdog({"step": 0.2, "offload_fetch": 10.0},
                        poll_interval=0.03)
    wd.start()
    try:
        with wd.phase("step"):
            with wd.phase("offload_fetch"):
                time.sleep(0.05)
            time.sleep(0.4)  # outer overruns AFTER the inner exited
        deadline = time.monotonic() + 2.0
        while wd.stall_count == 0 and time.monotonic() < deadline:
            time.sleep(0.03)
        assert wd.stall_count >= 1
        assert wd.last_stall[0] == "step"
    finally:
        wd.stop()


# ----------------------------------------------------------------- shards
def test_host_shards_committed_under_manifest(tmp_path):
    e, cfg = _engine(_stream_cfg())
    e.train_batch(_batch(cfg))
    ckpt = e.save_checkpoint(str(tmp_path))
    host_dir = os.path.join(ckpt, "host_state")
    shards = sorted(f for f in os.listdir(host_dir) if f.endswith(".npz"))
    # one shard per unit: embed + L layers + final
    assert len(shards) == e._param_stream.stream.n_layer + 2
    with open(os.path.join(ckpt, "MANIFEST.json")) as f:
        manifest = json.load(f)
    for s in shards:
        assert f"host_state/{s}" in manifest["files"]
    assert os.path.exists(os.path.join(ckpt, "COMMIT"))
    # roundtrip through the sharded format is exact
    e2, _ = _engine(_stream_cfg())
    e2.load_checkpoint(str(tmp_path))
    ref = float(e.train_batch(_batch(cfg, seed=7))["loss"])
    got = float(e2.train_batch(_batch(cfg, seed=7))["loss"])
    assert ref == got


@pytest.mark.slow
def test_zero_to_fp32_recovers_sharded_host_state(tmp_path):
    """The standalone recovery script (auto-copied into every tag) must read
    the sharded host_state/ format: param-stream checkpoints export their
    host masters keyed `unit/name` (the weights exist NOWHERE else), and
    optimizer-offload checkpoints keep the positional master mapping."""
    from deepspeed_tpu.utils.zero_to_fp32 import (
        get_fp32_state_dict_from_zero_checkpoint,
    )

    e, cfg = _engine(_stream_cfg())
    e.train_batch(_batch(cfg))
    e.save_checkpoint(str(tmp_path / "stream"))
    sd = get_fp32_state_dict_from_zero_checkpoint(str(tmp_path / "stream"))
    runner = e._param_stream
    leaf_by = {(u, n): i for i, (u, n, _) in enumerate(runner._leaves)}
    assert "layer_1/qkv_w" in sd and "embed/wte" in sd
    np.testing.assert_array_equal(
        sd["layer_1/qkv_w"], runner._state[leaf_by[("layer_1", "qkv_w")]][0])

    # optimizer offload (RAM mode -> host_state shards): positional mapping
    e2, cfg2 = _engine({"zero_optimization": {
        "stage": 1, "offload_optimizer": {"device": "cpu"}}})
    e2.train_batch(_batch(cfg2))
    e2.save_checkpoint(str(tmp_path / "opt"))
    assert os.path.isdir(tmp_path / "opt" / "global_step1" / "host_state")
    sd2 = get_fp32_state_dict_from_zero_checkpoint(str(tmp_path / "opt"))
    for i, key in enumerate(sd2):  # insertion order == leaves order
        np.testing.assert_array_equal(sd2[key].ravel(),
                                      np.asarray(e2._offload.master[i]).ravel())


def _run_worker(ckpt_dir, steps, log, env_extra=None, timeout=240):
    env = {**os.environ, **(env_extra or {})}
    return subprocess.run(
        [sys.executable, WORKER, "--ckpt-dir", str(ckpt_dir),
         "--steps", str(steps), "--log", str(log)],
        env=env, capture_output=True, text=True, timeout=timeout)


def _read_log(path):
    with open(path) as f:
        return {row["step"]: row for row in map(json.loads, f)}


@pytest.mark.slow
def test_sigkill_mid_flush_resumes_step_exact(tmp_path):
    """A SIGKILL inside the per-unit host-shard flush (save #2, after shard 1
    of the step-3 tag) must leave the step-2 tag the newest COMMITTED one;
    auto-resume from it reproduces the uninterrupted run bitwise."""
    plan = json.dumps({"kill_at_phase": "host-shard:1", "kill_at_save": 2})
    r = _run_worker(tmp_path / "ckpt", 4, tmp_path / "killed.jsonl",
                    env_extra={"DS_FAULT_PLAN": plan})
    assert r.returncode in (-signal.SIGKILL, 128 + signal.SIGKILL), r.stderr
    # the torn tag has no COMMIT; the step-2 tag stays committed
    tags = sorted(os.listdir(tmp_path / "ckpt"))
    assert "global_step2" in tags
    assert os.path.exists(tmp_path / "ckpt" / "global_step2" / "COMMIT")
    assert not os.path.exists(tmp_path / "ckpt" / "global_step3" / "COMMIT")
    # resume (no plan): runs steps 3..4 from the committed step-2 state
    r2 = _run_worker(tmp_path / "ckpt", 4, tmp_path / "resumed.jsonl",
                     env_extra={"DS_FAULT_PLAN": ""})
    assert r2.returncode == 0, r2.stderr + r2.stdout
    # uninterrupted reference
    r3 = _run_worker(tmp_path / "clean", 4, tmp_path / "clean.jsonl",
                     env_extra={"DS_FAULT_PLAN": ""})
    assert r3.returncode == 0, r3.stderr
    resumed, clean = _read_log(tmp_path / "resumed.jsonl"), _read_log(
        tmp_path / "clean.jsonl")
    for step in (3, 4):
        assert resumed[step]["loss"] == clean[step]["loss"], step
        assert resumed[step]["grad_norm"] == clean[step]["grad_norm"], step


# ------------------------------------------------------------------ dslint
def _rule_ctx(n_params=2_000_000_000, engine_present=True, **op):
    from deepspeed_tpu.analysis import AnalysisContext
    from deepspeed_tpu.runtime.zero.config import DeepSpeedZeroConfig

    zero = DeepSpeedZeroConfig(
        stage=0, offload_param={"device": "cpu", **op})
    cfg = SimpleNamespace(zero_optimization=zero)
    eng = None
    if engine_present:
        model_cfg = SimpleNamespace(num_params=lambda: n_params)
        eng = SimpleNamespace(
            _param_stream=SimpleNamespace(
                stream=SimpleNamespace(cfg=model_cfg)),
            state={"params": {}})
    return AnalysisContext(engine=eng, config=cfg)


def test_unstreamed_host_fetch_rule_fires():
    from deepspeed_tpu.analysis.rules_offload import UnstreamedHostFetchRule

    rule = UnstreamedHostFetchRule()
    found = list(rule.check_context(_rule_ctx(stream=False)))
    assert len(found) == 1
    assert found[0].rule_id == "offload/unstreamed-host-fetch"
    assert "stream=false" in found[0].message
    found = list(rule.check_context(_rule_ctx(prefetch_depth=0)))
    assert len(found) == 1 and "prefetch_depth=0" in found[0].message


def test_unstreamed_host_fetch_rule_silent():
    from deepspeed_tpu.analysis.rules_offload import UnstreamedHostFetchRule

    rule = UnstreamedHostFetchRule()
    # streaming on (the default): silent regardless of size
    assert not list(rule.check_context(_rule_ctx()))
    # small model: exposed DMA is cheap — silent
    assert not list(rule.check_context(
        _rule_ctx(n_params=125_000_000, stream=False)))
    # unknown model size (no engine): a size-gated rule must not guess
    assert not list(rule.check_context(
        _rule_ctx(engine_present=False, stream=False)))


def test_rule_registered_in_default_set():
    from deepspeed_tpu.analysis import default_rules

    assert any(r.rule_id == "offload/unstreamed-host-fetch"
               for r in default_rules())


# ------------------------------------------------------------------ aot
@pytest.mark.slow
def test_infinity_report_streamed_peak():
    """The fit verdict includes the d in-flight prefetch buffers, itemized
    (streamed peak = compiled moment peak + d * unit buffer bytes). One
    compiled report (the TPU-topology compiles are multi-minute); the
    depth-0 and quantized variants differ only in the itemized arithmetic,
    asserted against the report's own fields."""
    from deepspeed_tpu.comm.quantized import wire_bytes_per_element
    from deepspeed_tpu.runtime.aot import fit_verdict, infinity_program_report

    r2 = infinity_program_report("gpt2-125m", micro_bs=1, seq=128,
                                 keep_layers=1, prefetch_depth=2)
    assert r2["peak_source"] == "compiled_moments+stream_buffers"
    assert r2["stream"]["prefetch_depth"] == 2
    assert not r2["stream"]["quantized_fetch"]
    # in-flight units are COMPUTE-DTYPE resident (dequantized at issue time)
    assert r2["stream"]["unit_buffer_bytes"] == r2["layer_unit_bytes"]
    assert r2["stream"]["unit_wire_bytes"] == r2["layer_unit_bytes"]
    assert r2["stream"]["buffer_bytes"] == 2 * r2["stream"]["unit_buffer_bytes"]
    assert (r2["whole_run_peak_bytes"]
            == r2["moment_peak_bytes"] + r2["stream"]["buffer_bytes"])
    assert r2["fit"] == fit_verdict(r2["whole_run_peak_bytes"])
    # a quantized fetch shrinks the WIRE (DMA traffic), and ADDS its payload
    # transiently to residency — it never shrinks the in-flight buffer
    elems = r2["layer_unit_bytes"] // 2
    wire = int(elems * wire_bytes_per_element(8, 256))
    assert wire < r2["layer_unit_bytes"]  # the DMA saving
    # residency formula mirrored from infinity_program_report:
    # quantized unit_buffer = compute bytes + wire bytes > compute bytes
