"""SLO-tier multi-tenancy tests (docs/SERVING.md "Multi-tenancy & SLO
tiers"): WFQ starvation-freedom, tier-aware preemption ordering, brownout
enter/exit hysteresis, token-bucket refill, per-tenant ledger schema, the
noisy-neighbor chaos injection, fleet-wide per-tenant event attribution,
and the ``serving/untiered-multi-tenant`` dslint rule — all device-free on
the fake executor like tests/test_serving_chaos.py."""

import numpy as np
import pytest

from deepspeed_tpu.analysis import analyze_compile_log
from deepspeed_tpu.inference.serving import (BrownoutConfig,
                                             BrownoutController,
                                             ContinuousBatchingScheduler,
                                             Request, RequestState,
                                             ServingConfig,
                                             StartTimeFairQueue, TierConfig,
                                             TokenBucket, default_tiers,
                                             resolve_tenants, resolve_tiers,
                                             sacrifice_key, tier_rank)
from deepspeed_tpu.resilience import FaultPlan, RecoveryLog, install_plan


class FakeExecutor:
    """Same arithmetic executor as tests/test_serving_chaos.py: greedy
    outputs are a pure function of the prompt, so tiered/untiered/flooded
    runs are directly comparable."""

    def prefill(self, slot, tokens, table_row):
        return (int(tokens[-1]) + 1) % 97

    def decode(self, tokens, tables, lengths, active, steps=1):
        return np.stack([(tokens + k + 1) % 97 for k in range(steps)])


class ManualClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _tiered_kw(tiers_spec=True, tenants_spec=None):
    tiers = resolve_tiers(tiers_spec)
    return dict(tiers=tiers,
                tenants=resolve_tenants(tenants_spec, tiers))


def _sched(num_slots=2, num_pages=64, page_size=4, pages_per_seq=8,
           decode_block=1, **kw):
    return ContinuousBatchingScheduler(
        FakeExecutor(), num_slots=num_slots, num_pages=num_pages,
        page_size=page_size, pages_per_seq=pages_per_seq,
        decode_block=decode_block, **kw)


def _req(n=3, m=4, tenant=None, tier=None):
    return Request(prompt=np.arange(1, n + 1, dtype=np.int32),
                   max_new_tokens=m, tenant_id=tenant, tier=tier)


@pytest.fixture(autouse=True)
def _no_leftover_plan():
    install_plan(None)
    yield
    install_plan(None)


# ------------------------------------------------------------------ WFQ
def test_wfq_tags_chain_per_flow():
    """A deep backlog pushes only its OWN flow's tags out: another flow
    submitting later still stamps near the virtual time, not behind the
    backlog."""
    q = StartTimeFairQueue()
    for _ in range(10):
        q.stamp("bulk", 1.0, 8.0)          # finish tags reach 80
    s, f = q.stamp("fresh", 8.0, 8.0)
    assert s == 0.0 and f == 1.0           # cost/weight, not behind bulk


def test_wfq_starvation_freedom_under_interactive_saturation():
    """Sustained interactive saturation: a batch request submitted into the
    storm still completes while interactive backlog remains — and within a
    weight-proportional number of interactive completions (w_i/w_b = 8)."""
    s = _sched(num_slots=1, **_tiered_kw(
        tenants_spec={"i": "interactive", "b": "batch"}))
    interactive = [_req(3, 4, tenant="i") for _ in range(24)]
    for r in interactive[:4]:
        s.submit(r)
    for _ in range(6):
        s.step()
    batch = _req(3, 4, tenant="b")
    assert s.submit(batch).admitted
    done_at_batch_finish = None
    i = 4
    for _ in range(2000):
        # keep the interactive flow saturated: top the queue back up the
        # moment it dips, so batch never sees an idle slot for free
        while i < len(interactive) and len(s.queue) < 3:
            s.submit(interactive[i])
            i += 1
        s.step()
        if (batch.state is RequestState.FINISHED
                and done_at_batch_finish is None):
            done_at_batch_finish = sum(
                r.state is RequestState.FINISHED for r in interactive)
        if s.idle and i >= len(interactive):
            break
    assert batch.state is RequestState.FINISHED
    assert done_at_batch_finish is not None
    # not starved until the storm ended...
    assert done_at_batch_finish < len(interactive)
    # ...and served within the weight-proportional bound (8x weight ratio
    # at equal cost, +2 slack for the requests already in flight)
    assert done_at_batch_finish <= 10, done_at_batch_finish
    assert s.audit()["ok"]


def test_untiered_scheduler_keeps_fifo_order():
    """tiers=None is the seed scheduler: strict FIFO service order."""
    s = _sched(num_slots=1)
    reqs = [_req(3, 2), _req(4, 2), _req(5, 2)]
    for r in reqs:
        s.submit(r)
    s.run_to_completion(max_steps=200)
    finishes = [r.rid for r in sorted(reqs, key=lambda r: r.t_done)]
    assert finishes == [r.rid for r in reqs]


# ----------------------------------------------------- tiered preemption
def test_preemption_sacrifices_batch_before_interactive():
    """Pool pressure preempts the batch slot even when the interactive slot
    is newer — tier rank outranks admit recency (untiered keeps pure
    newest-first via the same key shape)."""
    assert sacrifice_key("batch", 0) > sacrifice_key("interactive", 99)
    assert tier_rank(None) == tier_rank("standard")
    # pool: 1 reserved + 6 usable pages; two requests of 1 prompt page each
    # growing 3+ pages force an allocation failure mid-decode
    s = _sched(num_slots=2, num_pages=7, page_size=4, pages_per_seq=6,
               **_tiered_kw(tenants_spec={"i": "interactive",
                                          "b": "batch"}))
    batch = _req(4, 14, tenant="b")
    inter = _req(4, 14, tenant="i")
    s.submit(batch)   # batch admitted FIRST (oldest — seed policy would
    s.submit(inter)   # have preempted the newer interactive request)
    s.run_to_completion(max_steps=500)
    assert batch.state is RequestState.FINISHED
    assert inter.state is RequestState.FINISHED
    assert batch.preemptions >= 1
    assert inter.preemptions == 0
    assert s.audit()["ok"]


def test_latency_preemption_displaces_batch_within_budget():
    """A queued interactive request does not wait out a batch decode: the
    batch slot is displaced (kept-token requeue, tokens unchanged), but
    only ``latency_preempt_budget`` times — after that the victim is
    immune and finishes ahead of later interactive arrivals (the WFQ
    starvation-freedom bound), and standard-tier arrivals never displace
    anyone."""
    def build(budget):
        return _sched(num_slots=1, latency_preempt_budget=budget,
                      **_tiered_kw(tenants_spec={"i": "interactive",
                                                 "s": "standard",
                                                 "b": "batch"}))

    # clean reference: the arithmetic executor's outputs are a pure
    # function of the prompt, so the displaced run must reproduce them
    ref = build(1)
    ref_batch = _req(3, 12, tenant="b")
    ref.submit(ref_batch)
    ref.run_to_completion(max_steps=200)

    s = build(1)
    batch = _req(3, 12, tenant="b")
    s.submit(batch)
    s.step()                      # batch running, holds the only slot
    inter1 = _req(4, 3, tenant="i")
    s.submit(inter1)
    s.step()
    assert batch.state is RequestState.QUEUED      # displaced...
    assert inter1.state is RequestState.RUNNING    # ...same cycle
    assert batch.preemptions == 1
    # drive until the batch request is back in its slot
    for _ in range(50):
        s.step()
        if batch.state is RequestState.RUNNING:
            break
    assert batch.state is RequestState.RUNNING
    inter2 = _req(4, 3, tenant="i")
    s.submit(inter2)
    s.step()
    # budget spent: the victim is immune, the new interactive waits
    assert batch.state is RequestState.RUNNING
    assert batch.preemptions == 1
    s.run_to_completion(max_steps=500)
    assert all(r.state is RequestState.FINISHED
               for r in (batch, inter1, inter2))
    assert batch.t_done < inter2.t_done
    assert list(batch.tokens) == list(ref_batch.tokens)
    assert s.audit()["ok"]

    # standard never triggers displacement
    s2 = build(8)
    b2 = _req(3, 12, tenant="b")
    s2.submit(b2)
    s2.step()
    s2.submit(_req(4, 3, tenant="s"))
    s2.step()
    assert b2.state is RequestState.RUNNING
    assert b2.preemptions == 0
    s2.run_to_completion(max_steps=500)
    assert s2.audit()["ok"]


def test_reserved_slots_hold_capacity_for_interactive():
    """``TierConfig.reserved_slots``: lower tiers are admitted only while
    enough free slots remain to cover the protected tier's unmet
    reservation — an interactive arrival finds a slot open without
    displacing anyone, and the reserved slot is a floor on availability,
    not a cap on interactive's use of the rest."""
    tiers = resolve_tiers({"interactive": {"reserved_slots": 1}})
    kw = dict(tiers=tiers,
              tenants=resolve_tenants({"i": "interactive", "b": "batch"},
                                      tiers))
    s = _sched(num_slots=2, **kw)
    b1, b2 = _req(3, 10, tenant="b"), _req(3, 10, tenant="b")
    s.submit(b1)
    s.submit(b2)
    s.step()
    # only one batch slot admitted: the other slot is interactive's floor
    assert b1.state is RequestState.RUNNING
    assert b2.state is RequestState.QUEUED
    inter = _req(4, 3, tenant="i")
    s.submit(inter)
    s.step()
    assert inter.state is RequestState.RUNNING   # no wait, no displacement
    assert b1.preemptions == 0
    s.run_to_completion(max_steps=500)
    assert all(r.state is RequestState.FINISHED for r in (b1, b2, inter))
    assert s.audit()["ok"]

    # a reservation table that eats every slot is a config error
    with pytest.raises(ValueError):
        _sched(num_slots=1, **dict(
            kw, tiers=resolve_tiers({"interactive": {"reserved_slots": 1}})))


# ------------------------------------------------------------- brownout
def test_brownout_enters_and_exits_with_hysteresis():
    ctl = BrownoutController(BrownoutConfig(
        window_s=5.0, enter_shed_rate=0.25, enter_misses=2,
        exit_shed_rate=0.05, min_dwell_s=1.0))
    for _ in range(8):
        ctl.observe("submit", 0.0)
    for _ in range(4):
        ctl.observe("shed", 0.0)           # shed rate 0.5
    assert ctl.decide(0.5) == 1            # escalate one stage
    assert ctl.decide(1.0) == 1            # dwell gate: no double-step
    assert ctl.decide(1.6) == 2            # still pressured: next stage
    assert ctl.stage_name == "clamp_batch"
    # window drains; quiet -> step back DOWN one stage per dwell
    assert ctl.decide(10.0) == 1
    assert ctl.decide(10.5) == 1           # dwell gates the exit too
    assert ctl.decide(11.5) == 0
    assert ctl.stage_name == "normal"


def test_brownout_miss_trigger_and_max_stage():
    ctl = BrownoutController(BrownoutConfig(min_dwell_s=0.1))
    ctl.observe("miss", 0.0)
    ctl.observe("miss", 0.0)
    for i, expect in enumerate((1, 2, 3)):
        # misses stay in the window: the ladder walks to its ceiling and
        # stops (never past hold_standard)
        ctl.observe("miss", i * 0.2)
        assert ctl.decide(0.15 + i * 0.2) == expect
    ctl.observe("miss", 1.0)
    ctl.observe("miss", 1.0)
    assert ctl.decide(1.0) == 3            # MAX_STAGE is a ceiling


def test_brownout_scheduler_sheds_batch_and_recovers():
    """Integration: organic sheds latch the ladder, batch admissions draw
    'brownout' verdicts while interactive stays open, and the ladder steps
    back down when pressure clears — each transition audited."""
    ck = ManualClock()
    tiers = resolve_tiers({"batch": {"max_queue": 1}})
    s = _sched(num_slots=1, clock=ck, tiers=tiers,
               tenants=resolve_tenants({"b": "batch", "i": "interactive"},
                                       tiers),
               brownout=BrownoutConfig(window_s=5.0, enter_shed_rate=0.25,
                                       enter_misses=99, min_dwell_s=1.0))
    # saturate the batch partition (max_queue=1): organic queue_full sheds
    verdicts = [s.submit(_req(3, 4, tenant="b")) for _ in range(6)]
    assert sum(v.admitted for v in verdicts) <= 2
    assert any(v.reason == "queue_full" for v in verdicts)
    ck.t = 1.0
    s.step()
    assert s.brownout_stage >= 1
    assert s.counters.get("tier_brownout", 0) >= 1
    # batch now shed at the front door with the BROWNOUT verdict...
    v = s.submit(_req(3, 4, tenant="b"))
    assert not v.admitted and v.reason == "brownout"
    # ...while interactive admission stays open
    inter = _req(3, 4, tenant="i")
    assert s.submit(inter).admitted
    s.run_to_completion(max_steps=300)
    assert inter.state is RequestState.FINISHED
    # pressure cleared: the ladder steps fully back down
    for k in range(1, 30):
        ck.t = 10.0 + k
        s.step()
        if s.brownout_stage == 0:
            break
    assert s.brownout_stage == 0
    assert s.audit()["ok"]


# ----------------------------------------------------------- token bucket
def test_token_bucket_refill_and_burst_cap():
    b = TokenBucket(rate_tokens_per_s=10.0, burst_tokens=20.0)
    assert b.try_take(20, now=0.0)          # full burst available
    assert not b.try_take(1, now=0.0)       # empty
    assert b.try_take(10, now=1.0)          # 1s refilled exactly 10
    assert not b.try_take(1, now=1.0)
    assert b.try_take(20, now=100.0)        # refill is capped at burst
    assert not b.try_take(25, now=200.0)    # can never exceed burst


def test_scheduler_rate_limits_per_tenant():
    ck = ManualClock()
    tiers = resolve_tiers(True)
    s = _sched(clock=ck, tiers=tiers, tenants=resolve_tenants(
        {"slow": {"tier": "standard", "rate_tokens_per_s": 7.0,
                  "rate_burst_tokens": 7.0}}, tiers))
    assert s.submit(_req(3, 4, tenant="slow")).admitted   # cost 7 = burst
    v = s.submit(_req(3, 4, tenant="slow"))
    assert not v.admitted and v.reason == "rate_limited"
    assert s.counters["request_shed"] == 1
    ck.t = 1.0                                            # refill 7 tokens
    assert s.submit(_req(3, 4, tenant="slow")).admitted
    # other tenants are not throttled by the slow tenant's bucket
    assert s.submit(_req(3, 4, tenant="other")).admitted


# ------------------------------------------------------ per-tenant ledger
def test_per_tenant_ledger_schema(tmp_path):
    """Recovery events carry tenant_id/tier for tenanted traffic and keep
    the pre-tier schema (no tenant keys at all) for untenanted traffic."""
    from deepspeed_tpu.resilience.events import read_events

    log = RecoveryLog(str(tmp_path / "ev.jsonl"), role="serving",
                      prefix="Serving")
    s = _sched(recovery_log=log,
               **_tiered_kw(tenants_spec={"a": "interactive"}))
    r1 = _req(3, 4, tenant="a")
    r2 = _req(4, 3)                        # untenanted rides along
    s.submit(r1)
    s.submit(r2)
    s.run_to_completion(max_steps=200)
    evs = read_events(str(tmp_path / "ev.jsonl"))
    fin = {e.get("rid"): e for e in evs if e["event"] == "request_finished"}
    assert fin[r1.rid]["tenant_id"] == "a"
    assert fin[r1.rid]["tier"] == "interactive"
    assert fin[r1.rid]["tokens"] == len(r1.tokens)
    assert "tenant_id" not in fin[r2.rid]
    assert s.tenants_seen == {"a"}


def test_report_breaks_down_by_tier_and_tenant():
    """_report: REJECTED requests count against their OWN group's shed
    rate; a victim tier's misses stay its own."""
    from deepspeed_tpu.inference.serving.bench import _report

    reqs = []
    for k in range(4):
        r = _req(3, 4, tenant="flood", tier="batch")
        r.arrival_time = 0.0
        if k < 3:
            r.state = RequestState.REJECTED   # the flooder eats its sheds
        reqs.append(r)
    ok = _req(3, 4, tenant="vip", tier="interactive")
    ok.arrival_time = 0.0
    ok.t_first_token, ok.t_done = 0.1, 0.2
    ok.tokens = [1, 2, 3, 4]
    reqs.append(ok)
    row = _report(reqs, t0=0.0, t_end=1.0, mode="continuous", slo_s=5.0)
    assert row["by_tenant"]["flood"]["shed"] == 3
    assert row["by_tenant"]["flood"]["shed_rate"] == 0.75
    assert row["by_tenant"]["vip"]["shed"] == 0
    assert row["by_tenant"]["vip"]["deadline_misses"] == 0
    assert row["by_tier"]["interactive"]["goodput_tokens"] == 4
    # the fleet aggregate still counts every shed once
    assert row["shed"] == 3


# -------------------------------------------------- noisy-neighbor chaos
def test_tenant_flood_chaos_interactive_unharmed():
    """FaultPlan.tenant_flood_at injects a batch burst mid-stream: the
    interactive outputs are greedy-identical to an un-flooded run, the
    flood is not fully starved, and the allocator audit is clean."""
    def build(tiered=True):
        kw = _tiered_kw(tenants_spec={"i": "interactive"}) if tiered else {}
        s = _sched(num_slots=2, num_pages=64, **kw)
        reqs = [_req(3, 6, tenant="i"), _req(5, 4, tenant="i"),
                _req(2, 8, tenant="i")]
        return s, reqs

    # clean run: no plan installed
    s0, clean = build()
    for r in clean:
        s0.submit(r)
    s0.run_to_completion(max_steps=500)

    install_plan(FaultPlan(tenant_flood_at=2, tenant_flood_requests=5,
                           tenant_flood_prompt=6, tenant_flood_max_new=4))
    s1, reqs = build()
    for r in reqs:
        s1.submit(r)
    s1.run_to_completion(max_steps=2000)
    assert s1.counters.get("tenant_flood") == 1
    assert [list(r.tokens) for r in reqs] == [list(r.tokens) for r in clean]
    assert all(r.state is RequestState.FINISHED for r in reqs)
    # bounded wait: the flood's batch-tier requests were served (or shed
    # with a typed verdict), never silently starved in the queue
    flood = [r for r in s1.finished + s1.shed
             if r.tenant_id == "flooder"]
    assert len(flood) == 5
    assert any(r.state is RequestState.FINISHED for r in flood)
    assert s1.audit()["ok"]
    assert s1.allocator.allocated_pages == 0
    assert "flooder" in s1.tenants_seen


def test_fleet_summary_attributes_by_tenant():
    """summarize_events merges tenant-stamped rows fleet-wide, and the
    AutoscalePolicy scale-up trigger reads the interactive-tier miss trend
    specifically."""
    from deepspeed_tpu.inference.fleet.autoscale import (AutoscalePolicy,
                                                         summarize_events)

    now = 100.0
    events = [
        {"unix_time": 99.0, "event": "request_finished", "tokens": 8,
         "tenant_id": "a", "tier": "interactive"},
        {"unix_time": 99.0, "event": "request_shed",
         "tenant_id": "b", "tier": "batch"},
        {"unix_time": 92.0, "event": "deadline_miss",
         "tenant_id": "a", "tier": "interactive"},
        {"unix_time": 99.5, "event": "deadline_miss",
         "tenant_id": "a", "tier": "interactive"},
        {"unix_time": 99.6, "event": "deadline_miss",
         "tenant_id": "a", "tier": "interactive"},
    ]
    s = summarize_events(events, now, window_s=10.0)
    assert s["by_tenant"]["a"]["goodput_tokens"] == 8.0
    assert s["by_tenant"]["b"]["shed"] == 1
    assert s["by_tier"]["interactive"]["deadline_misses"] == 3
    assert s["interactive_misses"] == 3
    assert s["interactive_miss_trend"] == 2 - 1
    pol = AutoscalePolicy(miss_floor=2, shed_rate_up=1.0)
    assert pol.decide(s, num_replicas=1, occupancy=0.5,
                      now=now) == "scale_up"
    # flat interactive trend (and quiet fleet trend): hold
    quiet = summarize_events(
        [{"unix_time": 92.0, "event": "deadline_miss",
          "tier": "interactive"}], now, 10.0)
    assert pol.decide(quiet, 1, 0.9, now) == "hold"


def test_tier_rides_fleet_wire_spec():
    """request_spec/LocalReplica.submit round-trip tenant_id + tier, so a
    re-route or handoff keeps the request's SLO class."""
    from deepspeed_tpu.inference.fleet.replica import (LocalReplica,
                                                       request_spec)

    req = _req(3, 4, tenant="gold", tier="interactive")
    spec = request_spec(req)
    assert spec["tenant_id"] == "gold" and spec["tier"] == "interactive"
    rep = LocalReplica("r0", scheduler=_sched(**_tiered_kw()))
    assert rep.submit(spec)["admitted"]
    inner = rep.sched.queue[0]
    assert inner.tenant_id == "gold" and inner.tier == "interactive"


# ----------------------------------------------------------- dslint rule
def test_untiered_multi_tenant_rule_fires_and_stays_silent():
    """serving/untiered-multi-tenant: WARNING when >=2 tenants were served
    with no tier config armed; silent with tiers armed, with <2 tenants,
    and on engines that never built a scheduler."""
    class Eng:
        compile_log = []

        def __init__(self, cfg, sched=None):
            self.serving = cfg
            self.last_scheduler = sched

    class Sched:
        def __init__(self, tenants):
            self.tenants_seen = set(tenants)
            self.tiers = None

    safe = dict(max_queue=8)  # keep unbounded-admission out of the frame
    f = analyze_compile_log(
        Eng(ServingConfig(**safe), Sched({"a", "b"}))).findings
    assert [x.rule_id for x in f] == ["serving/untiered-multi-tenant"]
    assert f[0].severity.name == "WARNING"
    # tiers armed -> silent
    assert not analyze_compile_log(
        Eng(ServingConfig(tiers=True, **safe), Sched({"a", "b"}))).findings
    # single tenant -> silent
    assert not analyze_compile_log(
        Eng(ServingConfig(**safe), Sched({"a"}))).findings
    # no scheduler ever built -> silent
    assert not analyze_compile_log(Eng(ServingConfig(**safe))).findings
    # live tiered scheduler with two tenants seen -> silent end to end
    live = _sched(**_tiered_kw())
    for t in ("a", "b"):
        live.submit(_req(3, 2, tenant=t))
    live.run_to_completion(max_steps=100)
    assert not analyze_compile_log(
        Eng(ServingConfig(tiers=True, **safe), live)).findings


def test_tier_config_validation():
    with pytest.raises(ValueError):
        resolve_tiers({"interactive": {"weight": -1.0}})
    with pytest.raises(ValueError):
        resolve_tiers({"gold": {}})       # unknown tier name
    tiers = default_tiers()
    with pytest.raises(ValueError):
        resolve_tenants({"a": "gold"}, tiers)   # unknown tier for tenant
    assert isinstance(tiers["batch"], TierConfig)
    assert tiers["interactive"].weight > tiers["batch"].weight
