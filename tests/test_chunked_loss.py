"""Chunked cross entropy (GPTConfig.loss_chunk): the fp32 [B,T,V] logits
never materialize; the loss and gradients must match the whole-sequence path.

Motivated by the v5e AOT fit analysis (docs/MFU_NOTES.md round 4): the fp32
logits are the largest single buffer at the HBM fit boundary.
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.models.gpt import GPTConfig, init_params, loss_fn


def _setup(chunk=0, **kw):
    cfg = GPTConfig(vocab_size=97, d_model=32, n_layer=2, n_head=2,
                    max_seq_len=32, loss_chunk=chunk, **kw)
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _batch(cfg, bs=3, seq=32, with_mask=False, with_labels=False, seed=0):
    r = np.random.default_rng(seed)
    b = {"input_ids": jnp.asarray(
        r.integers(0, cfg.vocab_size, (bs, seq)), jnp.int32)}
    if with_labels:
        b["labels"] = jnp.asarray(
            r.integers(0, cfg.vocab_size, (bs, seq)), jnp.int32)
    if with_mask:
        b["loss_mask"] = jnp.asarray(
            (r.random((bs, seq)) > 0.3).astype(np.float32))
    return b


@pytest.mark.parametrize("with_mask", [False, True])
@pytest.mark.parametrize("with_labels", [False, True])
def test_chunked_matches_whole_sequence(with_mask, with_labels):
    cfg0, params = _setup(chunk=0)
    cfg8 = dataclasses.replace(cfg0, loss_chunk=8)
    b = _batch(cfg0, with_mask=with_mask, with_labels=with_labels)
    l0, _ = loss_fn(cfg0, params, b, train=False)
    l8, _ = loss_fn(cfg8, params, b, train=False)
    np.testing.assert_allclose(float(l0), float(l8), rtol=1e-6)


@pytest.mark.slow
def test_chunked_gradients_match():
    cfg0, params = _setup(chunk=0)
    cfg8 = dataclasses.replace(cfg0, loss_chunk=8)
    b = _batch(cfg0)

    g0 = jax.grad(lambda p: loss_fn(cfg0, p, b, train=False)[0])(params)
    g8 = jax.grad(lambda p: loss_fn(cfg8, p, b, train=False)[0])(params)
    for (k, a), (_, c) in zip(
            jax.tree_util.tree_leaves_with_path(g0),
            jax.tree_util.tree_leaves_with_path(g8)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(c), rtol=1e-5, atol=1e-7,
            err_msg=jax.tree_util.keystr(k))


def test_chunked_untied_head_with_bias():
    cfg, _ = _setup(chunk=0, tie_embeddings=False, lm_head_bias=True)
    params = init_params(cfg, jax.random.PRNGKey(1))
    cfg8 = dataclasses.replace(cfg, loss_chunk=16)
    b = _batch(cfg)
    l0, _ = loss_fn(cfg, params, b, train=False)
    l8, _ = loss_fn(cfg8, params, b, train=False)
    np.testing.assert_allclose(float(l0), float(l8), rtol=1e-6)


def test_chunked_seq_plus_one_packing():
    """seq+1 token packing (inputs longer than max_seq_len)."""
    cfg0, params = _setup(chunk=0)
    cfg8 = dataclasses.replace(cfg0, loss_chunk=8)
    b = _batch(cfg0, seq=33)  # max_seq_len + 1
    l0, _ = loss_fn(cfg0, params, b, train=False)
    l8, _ = loss_fn(cfg8, params, b, train=False)
    np.testing.assert_allclose(float(l0), float(l8), rtol=1e-6)


def test_chunk_must_divide_seq():
    cfg, params = _setup(chunk=7)
    with pytest.raises(ValueError, match="divide"):
        loss_fn(cfg, params, _batch(cfg, seq=32), train=False)


def test_pipelined_model_honors_loss_chunk():
    """gpt_pipe must route through the same chunked head (a silently dropped
    loss_chunk would re-materialize the logits the knob exists to avoid)."""
    from deepspeed_tpu.models import gpt_pipe

    cfg0, params0 = _setup(chunk=0)
    cfg8 = dataclasses.replace(cfg0, loss_chunk=8)
    b = _batch(cfg0, bs=4, seq=32)
    pipe_params = gpt_pipe.init_params(cfg8, 2, jax.random.PRNGKey(0))
    l_chunk, _ = gpt_pipe.loss_fn(cfg8, 2, 2, pipe_params, b, train=False)
    l_whole, _ = gpt_pipe.loss_fn(cfg0, 2, 2, pipe_params, b, train=False)
    np.testing.assert_allclose(float(l_whole), float(l_chunk), rtol=1e-5)


@pytest.mark.slow
def test_moe_model_honors_loss_chunk():
    from deepspeed_tpu.models.gpt_moe import (PRESETS, init_params as moe_init,
                                              loss_fn as moe_loss)

    cfg = PRESETS["tiny-moe"]
    params = moe_init(cfg, jax.random.PRNGKey(0))
    cfg8 = dataclasses.replace(cfg, base=dataclasses.replace(
        cfg.base, loss_chunk=16))
    b = {"input_ids": jnp.asarray(np.random.default_rng(0).integers(
        0, cfg.base.vocab_size, (2, 32)), jnp.int32)}
    l0, aux0 = moe_loss(cfg, params, b, train=False)
    l8, aux8 = moe_loss(cfg8, params, b, train=False)
    np.testing.assert_allclose(float(l0), float(l8), rtol=1e-5)
    np.testing.assert_allclose(float(aux0["moe_aux_loss"]),
                               float(aux8["moe_aux_loss"]), rtol=1e-6)


def test_num_tokens_matches_whole_sequence_path():
    from deepspeed_tpu.models.gpt import next_token_loss

    cfg0, params = _setup(chunk=0)
    cfg8 = dataclasses.replace(cfg0, loss_chunk=8)
    b = _batch(cfg0)
    _, aux0 = loss_fn(cfg0, params, b, train=False)
    _, aux8 = loss_fn(cfg8, params, b, train=False)
    assert aux8["num_tokens"] == aux0["num_tokens"]


@pytest.mark.slow
def test_engine_trains_with_chunked_loss():
    import deepspeed_tpu
    from deepspeed_tpu.models import build_gpt

    model, cfg = build_gpt(GPTConfig(
        vocab_size=128, d_model=32, n_layer=2, n_head=2, max_seq_len=32,
        loss_chunk=8))
    e, _, _, _ = deepspeed_tpu.initialize(model=model, config={
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 1}, "steps_per_print": 0})
    b = {"input_ids": np.random.default_rng(0).integers(
        0, 128, (16, 32), dtype=np.int32)}
    losses = [float(e.train_batch(b)["loss"]) for _ in range(5)]
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]
