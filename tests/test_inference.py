"""Inference engine tests: KV-cache decode == full forward; generate shapes;
TP-sharded generation."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.inference import InferenceEngine, DeepSpeedInferenceConfig, for_gpt
from deepspeed_tpu.models import GPTConfig
from deepspeed_tpu.models import gpt as gpt_mod

CFG = GPTConfig(vocab_size=128, n_layer=2, n_head=4, d_model=64, max_seq_len=64)


@pytest.fixture(scope="module")
def params():
    return gpt_mod.init_params(CFG, jax.random.PRNGKey(0))


@pytest.mark.slow
def test_cache_decode_matches_full_forward(params, devices):
    """Incremental KV-cache decoding must reproduce the dense forward logits."""
    ids = np.array(np.random.default_rng(0).integers(0, 128, (2, 16)), np.int32)
    full = gpt_mod.forward(CFG, params, jnp.asarray(ids), train=False)

    cache = gpt_mod.init_cache(CFG, 2, 32, jnp.float32)
    # prefill 10, then decode 6 one-by-one
    logits_a, cache = gpt_mod.forward_with_cache(CFG, params, jnp.asarray(ids[:, :10]), cache)
    outs = [logits_a]
    for t in range(10, 16):
        step_logits, cache = gpt_mod.forward_with_cache(
            CFG, params, jnp.asarray(ids[:, t:t + 1]), cache)
        outs.append(step_logits)
    inc = jnp.concatenate(outs, axis=1)
    np.testing.assert_allclose(np.asarray(full), np.asarray(inc), rtol=2e-4, atol=2e-4)


def test_generate_greedy(params, devices):
    eng = InferenceEngine(for_gpt(CFG, params),
                          DeepSpeedInferenceConfig(dtype="float32", max_out_tokens=8))
    prompt = np.zeros((2, 4), np.int32)
    out = eng.generate(prompt, max_new_tokens=8)
    assert out.shape == (2, 12)
    assert (out[:, :4] == prompt).all()
    # greedy is deterministic
    out2 = eng.generate(prompt, max_new_tokens=8)
    np.testing.assert_array_equal(out, out2)


def test_generate_tp(params, devices):
    cfg = DeepSpeedInferenceConfig(dtype="float32", tensor_parallel={"tp_size": 2})
    eng = InferenceEngine(for_gpt(CFG, params), cfg)
    assert eng.topo.axes["tp"] == 2
    out = eng.generate(np.zeros((2, 4), np.int32), max_new_tokens=4)
    assert out.shape == (2, 8)
    # TP result equals single-device result
    eng1 = InferenceEngine(for_gpt(CFG, params),
                           DeepSpeedInferenceConfig(dtype="float32"))
    out1 = eng1.generate(np.zeros((2, 4), np.int32), max_new_tokens=4)
    np.testing.assert_array_equal(out, out1)


def test_generate_sampling_and_eos(params, devices):
    eng = InferenceEngine(for_gpt(CFG, params),
                          DeepSpeedInferenceConfig(dtype="float32"))
    out = eng.generate(np.zeros((1, 4), np.int32), max_new_tokens=6,
                       temperature=1.0, top_k=5, seed=1)
    assert out.shape == (1, 10)


def test_init_inference_api(params, devices):
    eng = deepspeed_tpu.init_inference(
        model=for_gpt(CFG, params), config={"dtype": "float32"})
    logits = eng.forward(np.zeros((1, 8), np.int32))
    assert logits.shape == (1, 8, 128)


@pytest.mark.slow
def test_generate_top_p_nucleus_sampling():
    """top_p ~ 0 degenerates to greedy; top_p = 0.999 still samples."""
    from deepspeed_tpu.inference import DeepSpeedInferenceConfig, InferenceEngine
    from deepspeed_tpu.inference.engine import for_gpt
    from deepspeed_tpu.models import gpt as gpt_mod

    cfg = gpt_mod.GPTConfig(vocab_size=128, d_model=32, n_layer=1, n_head=2,
                            max_seq_len=64)
    params = gpt_mod.init_params(cfg, jax.random.PRNGKey(0))
    eng = InferenceEngine(for_gpt(cfg, params),
                          DeepSpeedInferenceConfig(dtype="float32",
                                                   max_out_tokens=40))
    ids = np.random.default_rng(0).integers(0, 128, (1, 8), np.int32)
    greedy = np.asarray(eng.generate(ids, max_new_tokens=8))
    tiny_p = np.asarray(eng.generate(ids, max_new_tokens=8, temperature=1.0,
                                     top_p=1e-6))
    np.testing.assert_array_equal(tiny_p, greedy)  # nucleus of one = argmax
    wide_p = np.asarray(eng.generate(ids, max_new_tokens=8, temperature=1.0,
                                     top_p=0.999, seed=3))
    assert wide_p.shape == greedy.shape
    assert np.isfinite(wide_p).all()


@pytest.mark.slow
def test_beam_search_beats_or_matches_greedy_logprob():
    """num_beams=1-equivalence and score dominance: the beam-4 sequence's
    total logprob must be >= the greedy sequence's under the same model."""
    from deepspeed_tpu.inference import DeepSpeedInferenceConfig, InferenceEngine
    from deepspeed_tpu.inference.engine import for_gpt
    from deepspeed_tpu.models import gpt as gpt_mod

    cfg = gpt_mod.GPTConfig(vocab_size=64, d_model=32, n_layer=2, n_head=2,
                            max_seq_len=96)
    params = gpt_mod.init_params(cfg, jax.random.PRNGKey(1))
    eng = InferenceEngine(for_gpt(cfg, params),
                          DeepSpeedInferenceConfig(dtype="float32",
                                                   max_out_tokens=48))
    ids = np.random.default_rng(1).integers(0, 64, (2, 8), np.int32)
    T, N = 8, 6
    greedy = np.asarray(eng.generate(ids, max_new_tokens=N))
    beam = np.asarray(eng.generate(ids, max_new_tokens=N, num_beams=4))
    assert beam.shape == greedy.shape == (2, T + N)
    np.testing.assert_array_equal(beam[:, :T], ids)

    def seq_logprob(seq):
        # score continuations under the dense forward
        logits = gpt_mod.forward(cfg, params, jnp.asarray(seq), train=False)
        logp = jax.nn.log_softmax(np.asarray(logits, np.float32), axis=-1)
        tot = np.zeros(seq.shape[0])
        for b in range(seq.shape[0]):
            for t in range(T - 1, T + N - 1):
                tot[b] += float(logp[b, t, seq[b, t + 1]])
        return tot

    g, bm = seq_logprob(greedy), seq_logprob(beam)
    assert (bm >= g - 1e-4).all(), (bm, g)

    with pytest.raises(ValueError, match="deterministic"):
        eng.generate(ids, max_new_tokens=4, num_beams=2, temperature=1.0)


def test_repetition_penalty_reduces_repeats():
    from deepspeed_tpu.inference import DeepSpeedInferenceConfig, InferenceEngine
    from deepspeed_tpu.inference.engine import for_gpt
    from deepspeed_tpu.models import gpt as gpt_mod

    cfg = gpt_mod.GPTConfig(vocab_size=32, d_model=16, n_layer=1, n_head=2,
                            max_seq_len=96)
    params = gpt_mod.init_params(cfg, jax.random.PRNGKey(0))
    eng = InferenceEngine(for_gpt(cfg, params),
                          DeepSpeedInferenceConfig(dtype="float32",
                                                   max_out_tokens=64))
    ids = np.zeros((1, 4), np.int32)
    plain = np.asarray(eng.generate(ids, max_new_tokens=24))[0, 4:]
    pen = np.asarray(eng.generate(ids, max_new_tokens=24,
                                  repetition_penalty=5.0))[0, 4:]
    # a tiny random model degenerates into loops greedily; a strong penalty
    # must strictly increase the distinct-token count
    assert len(np.unique(pen)) > len(np.unique(plain))


def test_generate_enforces_batch_and_token_bounds():
    from deepspeed_tpu.inference import DeepSpeedInferenceConfig, InferenceEngine
    from deepspeed_tpu.inference.engine import for_gpt
    from deepspeed_tpu.models import gpt as gpt_mod

    cfg = gpt_mod.GPTConfig(vocab_size=32, d_model=16, n_layer=1, n_head=2,
                            max_seq_len=64)
    params = gpt_mod.init_params(cfg, jax.random.PRNGKey(0))
    eng = InferenceEngine(for_gpt(cfg, params),
                          DeepSpeedInferenceConfig(dtype="float32",
                                                   max_out_tokens=32,
                                                   min_out_tokens=4,
                                                   max_batch_size=2))
    with pytest.raises(ValueError, match="max_batch_size"):
        eng.generate(np.zeros((3, 4), np.int32), max_new_tokens=8)
    with pytest.raises(ValueError, match="min_out_tokens"):
        eng.generate(np.zeros((1, 4), np.int32), max_new_tokens=2)
    out = eng.generate(np.zeros((2, 4), np.int32), max_new_tokens=4)
    assert out.shape == (2, 8)


def test_generate_zero_max_new_tokens_rejected():
    from deepspeed_tpu.inference import DeepSpeedInferenceConfig, InferenceEngine
    from deepspeed_tpu.inference.engine import for_gpt
    from deepspeed_tpu.models import gpt as gpt_mod

    cfg = gpt_mod.GPTConfig(vocab_size=32, d_model=16, n_layer=1, n_head=2,
                            max_seq_len=64)
    params = gpt_mod.init_params(cfg, jax.random.PRNGKey(0))
    eng = InferenceEngine(for_gpt(cfg, params),
                          DeepSpeedInferenceConfig(dtype="float32",
                                                   max_out_tokens=32,
                                                   min_out_tokens=1))
    with pytest.raises(ValueError, match="min_out_tokens"):
        eng.generate(np.zeros((1, 4), np.int32), max_new_tokens=0)
