"""End-to-end data-integrity defense (docs/RESILIENCE.md "Data integrity").

Silent-corruption detection, containment, and healing: the fingerprint
primitive and its parity with the checkpoint manifest, the budgeted
IntegrityMonitor scan, each state domain's flip -> detect -> heal cycle
(device-free where the domain allows it), the dp fingerprint vote, the
trust-boundary verifies (checkpoint save, handoff payload, shared-page
audit), and the config plumbing that arms it all.
"""

import os
import zlib

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import GPTConfig, build_gpt
from deepspeed_tpu.resilience import (
    FaultPlan,
    IntegrityMonitor,
    SDCError,
    blockwise_fingerprints,
    fingerprint_array,
    fingerprint_bytes,
    fingerprint_vote,
    install_plan,
    payload_fingerprints,
    sdc_flip_fault,
    verify_payload_fingerprints,
)
from deepspeed_tpu.resilience.fingerprint import (
    CHECKSUMS,
    checksum_file,
    crc32c,
    preferred_checksum,
)

TINY = GPTConfig(vocab_size=256, n_layer=2, n_head=4, d_model=64,
                 max_seq_len=64)


@pytest.fixture(autouse=True)
def _clear_fault_plan():
    yield
    install_plan(None)


def make_engine(save_dir=None, extra=None):
    model, _ = build_gpt(TINY)
    cfg = {
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "steps_per_print": 0,
    }
    if save_dir is not None:
        cfg["resilience"] = {
            "enabled": True, "save_dir": str(save_dir),
            "install_signal_handlers": False,
            "sentinel": {"enabled": True, "checkpoint_interval": 2,
                         "cursor_checkpointable": True},
            "integrity": {"enabled": True, "scan_interval": 1,
                          "blocks_per_scan": 8, "block_bytes": 4096},
        }
    cfg.update(extra or {})
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=cfg)
    return engine


def batch(seed=0, n=16):
    rng = np.random.default_rng(seed)
    return {"input_ids": rng.integers(0, 256, size=(n, 32), dtype=np.int32)}


def _corrupt(path: str) -> None:
    with open(path, "r+b") as f:
        f.seek(os.path.getsize(path) // 2)
        chunk = f.read(8) or b"\0"
        f.seek(-len(chunk), os.SEEK_CUR)
        f.write(bytes(b ^ 0xFF for b in chunk))


# ---------------------------------------------------------------- primitive
def test_fingerprint_dispatch_parity_with_manifest(tmp_path):
    """ONE checksum primitive: the manifest's dispatch and the integrity
    fingerprints must be the same functions, byte for byte."""
    from deepspeed_tpu.resilience import manifest

    data = b"the quick brown fox jumps over the lazy dog" * 100
    assert manifest.crc32c is crc32c
    assert manifest.CHECKSUMS is CHECKSUMS
    assert fingerprint_bytes(data, "crc32c") == crc32c(data)
    assert fingerprint_bytes(data, "crc32") == zlib.crc32(data)
    assert fingerprint_bytes(data) == CHECKSUMS[preferred_checksum()](data)
    p = tmp_path / "blob.bin"
    p.write_bytes(data)
    algo = preferred_checksum()
    crc, size = checksum_file(str(p), algo)
    assert (crc, size) == (fingerprint_bytes(data, algo), len(data))


def test_fingerprint_array_views_bytes():
    a = np.arange(1000, dtype=np.float32)
    assert fingerprint_array(a) == fingerprint_bytes(a.tobytes())
    # non-contiguous views fingerprint their logical content
    assert fingerprint_array(a[::2]) == fingerprint_bytes(
        np.ascontiguousarray(a[::2]).tobytes())


def test_blockwise_fingerprints_bounds_and_locality():
    a = np.zeros(3000, np.uint8)
    fps = blockwise_fingerprints(a, block_bytes=1024)
    assert len(fps) == 3  # ceil(3000/1024)
    b = a.copy()
    b[2900] = 7  # flip in the LAST block only
    fps2 = blockwise_fingerprints(b, block_bytes=1024)
    assert fps[:2] == fps2[:2] and fps[2] != fps2[2]
    # empty array still yields one (empty-block) fingerprint
    assert blockwise_fingerprints(np.empty(0, np.uint8), block_bytes=1024)


# ------------------------------------------------------------------ monitor
def _monitor(units, **kw):
    mon = IntegrityMonitor(scan_interval=1, blocks_per_scan=4,
                           block_bytes=256, **kw)
    mon.register_domain("host_shards", lambda: units)
    return mon


def test_monitor_scan_budget_bound():
    units = {f"u{i}": np.random.default_rng(i).integers(
        0, 255, 2000, dtype=np.uint8).astype(np.uint8) for i in range(3)}
    mon = _monitor(units)
    stamped = mon.stamp_next()
    assert 0 < stamped <= 4  # never more than blocks_per_scan
    assert len(mon._pending) == stamped
    assert mon.verify_pending() == []  # clean state verifies clean
    assert not mon._pending  # verify clears the pending set
    # round-robin coverage: repeated scans touch every unit
    seen = set()
    for _ in range(20):
        mon.stamp_next()
        seen |= {u for (_, u, _) in mon._pending}
        mon.verify_pending()
    assert seen == set(units)


def test_monitor_flip_detect_names_block():
    units = {"m": np.zeros(4096, np.uint8), "v": np.zeros(4096, np.uint8)}
    mon = _monitor(units)
    mon.stamp_next()
    detail = mon.inject_flip("host_shards")
    assert detail["domain"] == "host_shards"
    mismatches = mon.verify_pending()
    assert len(mismatches) == 1
    m = mismatches[0]
    assert (m["domain"], m["unit"], m["block"]) == (
        "host_shards", detail["unit"], detail["block"])
    assert m["expected"] != m["actual"]
    assert mon.report()["mismatches"] == 1
    err = SDCError(mismatches)
    assert detail["unit"] in str(err)


def test_monitor_flip_without_pending_stamps_first():
    units = {"m": np.zeros(1024, np.uint8)}
    mon = _monitor(units)
    assert not mon._pending
    mon.inject_flip("host_shards")  # must stamp, then flip inside the stamp
    assert mon.verify_pending()


def test_monitor_invalidate_voids_stamps():
    units = {"m": np.zeros(1024, np.uint8)}
    mon = _monitor(units)
    mon.stamp_next()
    units["m"][:] = 9  # legitimate replacement...
    mon.invalidate("reshard")  # ...announced: stamps are void, not stale
    assert mon.verify_pending() == []
    # vanished units are skipped silently (replaced state, not corruption)
    mon.stamp_next()
    del units["m"]
    assert mon.verify_pending() == []


def test_monitor_spot_check_accounting():
    mon = _monitor({"m": np.zeros(64, np.uint8)})
    mon.record_spot_check(True, step=1)
    assert mon.report()["spot_mismatches"] == 0
    mon.record_spot_check(False, step=2)
    rep = mon.report()
    assert rep["spot_checks"] == 2 and rep["spot_mismatches"] == 1


# ---------------------------------------------------------------- dp voting
def test_fingerprint_vote_names_deviant():
    rows = [{"hostname": f"h{i}", "process_index": i, "fingerprint": 42}
            for i in range(4)]
    rows[2]["fingerprint"] = 7  # the deviant host
    majority, deviants = fingerprint_vote(rows)
    assert majority == 42
    assert [d["hostname"] for d in deviants] == ["h2"]
    # no strict majority -> nobody is accused
    tie = [{"hostname": "a", "fingerprint": 1},
           {"hostname": "b", "fingerprint": 2}]
    majority, deviants = fingerprint_vote(tie)
    assert majority is None and deviants == []


def test_allgather_host_stats_single_process_noop():
    # the vote needs >1 host; single-process runs skip the collective
    # entirely (with or without the piggybacked fingerprint)
    from deepspeed_tpu.resilience.watchdog import allgather_host_stats

    assert allgather_host_stats(0.25, fingerprint=0xDEADBEEF) is None
    assert allgather_host_stats(0.25) is None


# ------------------------------------------------------- handoff trust stamp
def _wire_tensors():
    r = np.random.default_rng(0)
    return {k: {"dtype": "float32", "shape": [2, 4],
                "data": r.normal(size=(2, 4)).astype(np.float32).tobytes()}
            for k in ("k", "v")}


def test_payload_fingerprints_roundtrip_and_tamper():
    tensors = _wire_tensors()
    stamp = payload_fingerprints(tensors)
    assert stamp["algo"] == preferred_checksum()
    assert verify_payload_fingerprints(tensors, stamp) == []
    # bit flip in one tensor's bytes names exactly that key
    bad = {k: dict(v) for k, v in tensors.items()}
    raw = bytearray(bad["v"]["data"])
    raw[3] ^= 0x01
    bad["v"]["data"] = bytes(raw)
    assert verify_payload_fingerprints(bad, stamp) == ["v"]
    # key-set mismatch and unknown algo both refuse (non-empty verdict)
    assert verify_payload_fingerprints({"k": tensors["k"]}, stamp)
    assert verify_payload_fingerprints(
        tensors, {"algo": "md5??", "tensors": stamp["tensors"]})


def test_serving_import_refuses_tampered_payload():
    """The decode-side trust boundary: a stamped payload whose bytes rotted
    in flight must be refused, not installed."""
    import jax

    from deepspeed_tpu.inference.serving import ServingConfig, ServingEngine
    from deepspeed_tpu.models import gpt as G

    cfg = G.GPTConfig(vocab_size=64, d_model=32, n_layer=2, n_head=4,
                      max_seq_len=64)
    params = G.init_params(cfg, jax.random.PRNGKey(0))
    eng = ServingEngine(cfg, params, ServingConfig(
        num_slots=2, page_size=8, max_model_len=32, prefill_chunk=8,
        dtype="float32", max_queue=8, page_fingerprints=True))
    payload = eng.export_pages([1, 2])
    assert "fingerprints" in payload  # exporter stamped
    eng.import_pages([1, 2], payload)  # clean round-trip installs
    key = sorted(payload["tensors"])[0]
    raw = bytearray(payload["tensors"][key]["data"])
    raw[len(raw) // 2] ^= 0x01
    payload["tensors"][key]["data"] = bytes(raw)
    with pytest.raises(ValueError, match="fingerprint"):
        eng.import_pages([1, 2], payload)


def test_fleet_wire_codec_preserves_fingerprints():
    from deepspeed_tpu.inference.fleet.replica import (decode_kv_payload,
                                                       encode_kv_payload)

    tensors = _wire_tensors()
    payload = {"page_ids": [1], "tensors": tensors,
               "fingerprints": payload_fingerprints(tensors)}
    out = decode_kv_payload(encode_kv_payload(payload))
    assert out["fingerprints"] == payload["fingerprints"]


# ------------------------------------------------------- allocator audit sweep
def test_page_allocator_audit_fingerprint_sweep():
    from deepspeed_tpu.inference.serving.paging import PageAllocator

    alloc = PageAllocator(8)
    pages = alloc.alloc(3)
    alloc.share(pages[:1])  # refcount 2 -> the only sweepable page
    content = {p: 100 + p for p in pages}

    def fp_fn(ids):
        return [content[p] for p in ids]

    expected = {pages[0]: 100 + pages[0]}
    rep = alloc.audit(expected_fingerprints=expected, fingerprint_fn=fp_fn)
    assert rep["ok"] and rep["fingerprinted"] == 1 and not rep["mismatches"]
    rep = alloc.audit(expected_fingerprints={pages[0]: -1},
                      fingerprint_fn=fp_fn)
    assert not rep["ok"] and rep["mismatches"] == [pages[0]]
    # unstamped/unshared pages are out of scope for the sweep
    rep = alloc.audit(expected_fingerprints={pages[2]: -1},
                      fingerprint_fn=fp_fn)
    assert rep["ok"] and rep["fingerprinted"] == 0


# ----------------------------------------------------------------- chaos plan
def test_sdc_flip_scope_routing_and_one_shot():
    install_plan(FaultPlan(flip_bit_at=3, flip_bit_domain="host_shards"))
    assert sdc_flip_fault(2, scope="training") is None  # not yet
    assert sdc_flip_fault(3, scope="serving") is None   # wrong scope
    assert sdc_flip_fault(3, scope="training") == "host_shards"
    assert sdc_flip_fault(4, scope="training") is None  # one-shot
    install_plan(FaultPlan(flip_bit_at=0, flip_bit_domain="kv_page"))
    assert sdc_flip_fault(5, scope="training") is None  # kv_page is serving
    assert sdc_flip_fault(5, scope="serving") == "kv_page"


# -------------------------------------------------------------------- config
def test_integrity_config_requires_resilience():
    from deepspeed_tpu.runtime.config import DeepSpeedConfig

    base = dict(train_micro_batch_size_per_gpu=2,
                optimizer={"type": "adam", "params": {"lr": 1e-3}})
    with pytest.raises(ValueError, match="resilience.integrity"):
        DeepSpeedConfig(**base, resilience={
            "enabled": False, "integrity": {"enabled": True}})
    with pytest.raises(Exception):
        DeepSpeedConfig(**base, resilience={
            "enabled": True, "save_dir": "/tmp/x",
            "integrity": {"enabled": True, "scan_interval": 0}})
    cfg = DeepSpeedConfig(**base, resilience={
        "enabled": True, "save_dir": "/tmp/x",
        "integrity": {"enabled": True}})
    assert cfg.resilience.integrity.scan_interval == 16
    assert cfg.resilience.integrity.blocks_per_scan == 4


# ----------------------------------------------------------------- dslint
def _serving_ctx(**kw):
    from deepspeed_tpu.analysis.core import AnalysisContext
    from deepspeed_tpu.inference.serving import ServingConfig

    class Eng:
        serving = ServingConfig(num_slots=2, page_size=8, max_model_len=32,
                                prefill_chunk=8, max_queue=8, **kw)

    return AnalysisContext(engine=Eng())


def _offload_ctx(integrity: bool):
    from deepspeed_tpu.analysis.core import AnalysisContext
    from deepspeed_tpu.runtime.config import DeepSpeedConfig

    kw = ({"resilience": {"enabled": True, "save_dir": "/tmp/x",
                          "integrity": {"enabled": True}}}
          if integrity else {})
    cfg = DeepSpeedConfig(
        train_micro_batch_size_per_gpu=2,
        optimizer={"type": "adam", "params": {"lr": 1e-3}},
        zero_optimization={"stage": 2,
                           "offload_optimizer": {"device": "cpu"}},
        **kw)
    return AnalysisContext(config=cfg)


def test_unverified_trust_boundary_rule_fires():
    from deepspeed_tpu.analysis.rules_resilience import (
        UnverifiedTrustBoundaryRule)

    rule = UnverifiedTrustBoundaryRule()
    # shared pages without fingerprints: the borrower-poisoning shape
    found = list(rule.check_context(_serving_ctx(enable_prefix_cache=True)))
    assert [f.rule_id for f in found] == [
        "resilience/unverified-trust-boundary"]
    assert "enable_prefix_cache" in found[0].message
    # disaggregated role ships payloads: the torn-transfer shape
    found = list(rule.check_context(_serving_ctx(role="prefill")))
    assert len(found) == 1 and "role='prefill'" in found[0].message
    # cpu-offloaded shards with no integrity scan armed
    found = list(rule.check_context(_offload_ctx(integrity=False)))
    assert len(found) == 1 and "offload_optimizer" in found[0].message


def test_unverified_trust_boundary_rule_silent():
    from deepspeed_tpu.analysis.rules_resilience import (
        UnverifiedTrustBoundaryRule)

    rule = UnverifiedTrustBoundaryRule()
    # verification armed on the sharing surface -> silent
    assert not list(rule.check_context(
        _serving_ctx(enable_prefix_cache=True, page_fingerprints=True)))
    # no sharing surface armed -> nothing to verify, silent
    assert not list(rule.check_context(_serving_ctx()))
    # offload with the integrity scan armed -> silent
    assert not list(rule.check_context(_offload_ctx(integrity=True)))


def test_unverified_trust_boundary_registered_in_default_set():
    from deepspeed_tpu.analysis import default_rules

    assert any(r.rule_id == "resilience/unverified-trust-boundary"
               for r in default_rules())


# ------------------------------------------------------------- engine cycles
def test_engine_master_flip_detect_rollback_stepexact(tmp_path):
    """HBM master/opt domain (no offload): a flipped bit in a stamped block
    must be detected at the next boundary, roll back to the committed
    anchor, and REPLAY (not skip) to a step-exact final loss."""
    def run(sub, flip):
        install_plan(FaultPlan(flip_bit_at=4, flip_bit_domain="master")
                     if flip else None)
        eng = make_engine(save_dir=tmp_path / sub)
        saw_sdc = False
        while eng.global_steps < 6:
            m = eng.train_batch(batch(eng.data_cursor))
            saw_sdc = saw_sdc or "sdc" in m
        counters = dict(eng._recovery_log.counters)
        install_plan(None)
        return float(m["loss"]), saw_sdc, counters

    ref_loss, ref_sdc, ref_counters = run("ref", flip=False)
    assert not ref_sdc and not ref_counters.get("sdc_detected")
    assert ref_counters.get("integrity_scan")  # the scan actually ran
    loss, saw_sdc, counters = run("flip", flip=True)
    assert saw_sdc and counters.get("sdc_detected")
    assert counters.get("sdc_rollback")
    assert loss == ref_loss  # replayed batches, bitwise-identical heal


def test_engine_corrupt_anchor_falls_back_older(tmp_path):
    """SDC containment re-verifies anchors through the manifest loader: a
    corrupt newest tag is rejected and the rollback lands on the older
    committed one instead of trusting rotten bytes."""
    eng = make_engine(save_dir=tmp_path)
    while eng.global_steps < 4:
        eng.train_batch(batch(eng.data_cursor))
    # anchors at steps 2 and 4 — rot the newest tag's array payload
    newest = tmp_path / "global_step4" / "state" / "arrays"
    victim = sorted(os.listdir(newest))[0]
    _corrupt(str(newest / victim))
    info = eng._health.sdc_rollback(
        {"domain": "master", "unit": "u", "block": 0})
    assert info["to_step"] == 2  # fell back past the corrupt anchor
    assert info["skip_cursors"] == []  # replay, never skip, on SDC
    assert eng._recovery_log.counters.get("tag_rejected_on_load")


def test_engine_save_checkpoint_verifies_pending(tmp_path):
    """The checkpoint trust boundary: bytes about to be blessed into an
    anchor are verified first — a pending mismatch raises instead of
    committing corruption."""
    eng = make_engine(save_dir=tmp_path)
    eng.train_batch(batch(0))
    eng.train_batch(batch(1))
    detail = eng._integrity.inject_flip()  # flip inside a pending stamp
    assert detail is not None
    with pytest.raises(SDCError, match="silent data corruption"):
        eng.save_checkpoint(str(tmp_path / "out"))


def test_engine_spot_check_quiet_on_clean_run(tmp_path):
    eng = make_engine(save_dir=tmp_path, extra={"resilience": {
        "enabled": True, "save_dir": str(tmp_path),
        "install_signal_handlers": False,
        "sentinel": {"enabled": True, "checkpoint_interval": 2,
                     "cursor_checkpointable": True},
        "integrity": {"enabled": True, "scan_interval": 1,
                      "blocks_per_scan": 4, "block_bytes": 4096,
                      "spot_check_interval": 2}}})
    while eng.global_steps < 5:
        eng.train_batch(batch(eng.data_cursor))
    rep = eng._integrity.report()
    assert rep["spot_checks"] >= 2
    assert rep["spot_mismatches"] == 0
    assert not eng._recovery_log.counters.get("sdc_detected")
    assert rep["overhead_frac"] < 1.0  # accounting is sane


def test_engine_host_shard_flip_detect_heal(tmp_path):
    """The offload domain on the real engine: the chaos smoke's training
    cycle in miniature — cpu-offloaded opt shards, flip, detect, heal."""
    extra = {"zero_optimization": {"stage": 2,
                                   "offload_optimizer": {"device": "cpu"}}}
    install_plan(FaultPlan(flip_bit_at=3, flip_bit_domain="host_shards"))
    eng = make_engine(save_dir=tmp_path, extra=extra)
    assert "host_shards" in eng._integrity.report()["domains"]
    saw = False
    while eng.global_steps < 5:
        m = eng.train_batch(batch(eng.data_cursor))
        saw = saw or "sdc" in m
    assert saw
    assert eng._recovery_log.counters.get("sdc_detected")
    assert np.isfinite(float(m["loss"]))
