"""Test-depth round-up (VERDICT r2 'next' #8).

- load_mp_checkpoint rank mapping hardened: multi-axis-sharded leaves (tp
  composed with dp on the same or different dims) reload exactly (weak #8);
- fixed-seed convergence test with loss-curve bounds (the reference's
  ``tests/model/`` discipline scaled to CI);
- key engine paths exercised at world sizes {2, 4, 8} (the reference's
  ``DistributedTest.world_size`` lists).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import deepspeed_tpu as ds
from deepspeed_tpu.models import build_gpt, gpt
from deepspeed_tpu.runtime.topology import MeshTopology


# -------------------------------------------------------- mp reload, multi-axis
def _roundtrip(tmp_path, params, specs, topo):
    from deepspeed_tpu.module_inject.load_checkpoint import (
        load_mp_checkpoint,
        save_mp_checkpoint,
    )

    save_mp_checkpoint(str(tmp_path), params, specs, tp_size=2)
    shapes = jax.eval_shape(lambda: params)
    loaded = load_mp_checkpoint(str(tmp_path), shapes, specs, mesh=topo.mesh)
    for key in params:
        np.testing.assert_array_equal(
            np.asarray(loaded[key]), np.asarray(params[key]), err_msg=key)
        got_spec = tuple(loaded[key].sharding.spec)
        want = tuple(specs[key])
        assert got_spec == want, (key, got_spec, want)


def test_load_mp_checkpoint_multi_axis_sharding(tmp_path, devices):
    """Leaves sharded over ('dp','tp') on ONE dim, tp+dp on different dims,
    and plain tp must all reload bitwise-correctly (weak #8: the old mapping
    silently placed rank-0 data for composite shardings)."""
    rng = np.random.default_rng(0)
    topo = MeshTopology.create(dp=4, tp=2, devices=devices)
    params = {
        "combined": jnp.asarray(rng.normal(size=(16, 6)), jnp.float32),
        "two_dims": jnp.asarray(rng.normal(size=(8, 12)), jnp.float32),
        "plain_tp": jnp.asarray(rng.normal(size=(4, 10)), jnp.float32),
        "replicated": jnp.asarray(rng.normal(size=(5,)), jnp.float32),
    }
    specs = {
        "combined": P(("dp", "tp"), None),   # one dim, composed axes
        "two_dims": P("tp", "dp"),           # tp dim0, dp dim1
        "plain_tp": P(None, "tp"),
        "replicated": P(None),
    }
    _roundtrip(tmp_path, params, specs, topo)


def test_load_mp_checkpoint_composed_order_and_downshard(tmp_path, devices):
    """(a) a ('dp','tp')-composed reload of a tp=4 export is data-correct (any
    aligned sub-slice lies inside one tp file); (b) reloading at a SMALLER tp
    than exported merges spanned files per device slice (the merge direction
    of the reference's state-dict factory, state_dict_factory.py:474)."""
    from deepspeed_tpu.module_inject.load_checkpoint import (
        load_mp_checkpoint,
        save_mp_checkpoint,
    )

    rng = np.random.default_rng(1)
    params = {"w": jnp.asarray(rng.normal(size=(16, 4)), jnp.float32)}
    save_mp_checkpoint(str(tmp_path), params, {"w": P("tp", None)}, tp_size=4)
    shapes = jax.eval_shape(lambda: params)

    topo = MeshTopology.create(dp=2, tp=4, devices=devices)
    loaded = load_mp_checkpoint(str(tmp_path), shapes,
                                {"w": P(("dp", "tp"), None)}, mesh=topo.mesh)
    np.testing.assert_array_equal(np.asarray(loaded["w"]),
                                  np.asarray(params["w"]))

    # downshard: tp=4 export onto a tp=2 mesh — each device slice spans two
    # files and is assembled by concatenation
    topo2 = MeshTopology.create(dp=4, tp=2, devices=devices)
    merged = load_mp_checkpoint(str(tmp_path), shapes, {"w": P("tp", None)},
                                mesh=topo2.mesh)
    np.testing.assert_array_equal(np.asarray(merged["w"]),
                                  np.asarray(params["w"]))
    assert tuple(merged["w"].sharding.spec) == ("tp", None)

    # full merge: tp=1 view (replicated) of the tp=4 export
    solo = load_mp_checkpoint(str(tmp_path), shapes, {"w": P(None, None)},
                              mesh=topo2.mesh)
    np.testing.assert_array_equal(np.asarray(solo["w"]),
                                  np.asarray(params["w"]))


# -------------------------------------------------------- convergence
@pytest.mark.slow
def test_fixed_seed_convergence():
    """Small GPT memorizes a fixed batch: the loss curve must fall below
    bounds at fixed step marks (parity: tests/model convergence checks)."""
    model, _ = build_gpt(gpt.GPTConfig(
        vocab_size=128, n_layer=2, n_head=4, d_model=64, max_seq_len=64))
    engine, _, _, _ = ds.initialize(model=model, seed=7, config={
        "train_micro_batch_size_per_gpu": 8,
        "optimizer": {"type": "Adam", "params": {"lr": 3e-3}},
        "zero_optimization": {"stage": 2},
        "mesh": {"dp": 8},
        "bf16": {"enabled": False},
        "steps_per_print": 0,
    })
    r = np.random.default_rng(3)
    batch = {"input_ids": r.integers(0, 128, size=(8, 32), dtype=np.int32)}
    losses = [float(engine.train_batch(batch)["loss"]) for _ in range(30)]
    assert losses[0] > 4.0  # ~ln(128) cold
    assert losses[9] < losses[0]
    assert losses[29] < 1.0, losses[-5:]  # memorization bound
    assert all(np.isfinite(l) for l in losses)


# -------------------------------------------------------- world-size sweep
@pytest.mark.parametrize("world", [2, 4, 8])
@pytest.mark.slow
def test_zero3_train_and_checkpoint_at_world_sizes(world, tmp_path, devices):
    """The reference runs key suites at several world sizes
    (DistributedTest.world_size lists); sweep ZeRO-3 train + ckpt round-trip."""
    model, _ = build_gpt(gpt.GPTConfig(
        vocab_size=64, n_layer=2, n_head=2, d_model=32, max_seq_len=32))
    topo = MeshTopology.create(dp=world, devices=devices[:world])
    config = {
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 3, "stage3_param_persistence_threshold": 0},
        "mesh": {"dp": world},
        "bf16": {"enabled": False},
        "steps_per_print": 0,
    }
    engine, _, _, _ = ds.initialize(model=model, topology=topo, config=config)
    r = np.random.default_rng(0)
    ids = r.integers(0, 64, size=(world, 16), dtype=np.int32)
    losses = [float(engine.train_batch({"input_ids": ids})["loss"])
              for _ in range(3)]
    assert losses[-1] < losses[0]
    engine.save_checkpoint(str(tmp_path / f"w{world}"))
    ref = float(engine.train_batch({"input_ids": ids})["loss"])

    model2, _ = build_gpt(gpt.GPTConfig(
        vocab_size=64, n_layer=2, n_head=2, d_model=32, max_seq_len=32))
    engine2, _, _, _ = ds.initialize(
        model=model2, topology=MeshTopology.create(dp=world, devices=devices[:world]),
        config=config)
    engine2.load_checkpoint(str(tmp_path / f"w{world}"))
    got = float(engine2.train_batch({"input_ids": ids})["loss"])
    np.testing.assert_allclose(got, ref, rtol=1e-5)


@pytest.mark.parametrize("world,tp", [(4, 2), (8, 4)])
@pytest.mark.slow
def test_tp_worlds(world, tp, devices):
    model, _ = build_gpt(gpt.GPTConfig(
        vocab_size=64, n_layer=2, n_head=4, d_model=32, max_seq_len=32))
    topo = MeshTopology.create(dp=world // tp, tp=tp, devices=devices[:world])
    engine, _, _, _ = ds.initialize(model=model, topology=topo, config={
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 1},
        "mesh": {"dp": world // tp, "tp": tp},
        "bf16": {"enabled": False},
        "steps_per_print": 0,
    })
    r = np.random.default_rng(0)
    ids = r.integers(0, 64, size=(2 * (world // tp), 16), dtype=np.int32)
    m = engine.train_batch({"input_ids": ids})
    assert np.isfinite(float(m["loss"]))
    qkv = engine.state["params"]["blocks"]["qkv_w"]
    assert "tp" in str(qkv.sharding.spec)
