"""Pipeline training through the PUBLIC initialize() API (VERDICT r2 'next' #3).

Parity target: ``deepspeed.initialize`` returning a ``PipelineEngine`` for a
``PipelineModule`` (``/root/reference/deepspeed/__init__.py:124-148``) with the
full engine contract — real optimizer, precision, DP grad handling, pipeline
checkpointing (``/root/reference/deepspeed/runtime/pipe/engine.py:37``,
``module.py:533-590``).

Two public paths:
- SPMD: mesh.pp > 1 + a pipeline-capable Module (``Module.to_pipeline``) →
  the dense engine trains the collective-permute pipeline; ZeRO/precision/
  checkpointing unchanged. Exercised at pp=2 x dp=2 x tp=2.
- MPMD: a PipelineModule (heterogeneous layer specs) → PipelineEngine with the
  configured optimizer, bf16 master/compute split, DP replicas, checkpointing.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu as ds
from deepspeed_tpu.models import build_gpt, gpt
from deepspeed_tpu.runtime.pipe.engine import PipelineEngine

from test_pipe import _tiny_lm_module


def _tiny_cfg():
    return gpt.GPTConfig(vocab_size=64, n_layer=2, n_head=2, d_model=32,
                         max_seq_len=32, dropout=0.0)


# ------------------------------------------------------------------- SPMD path
@pytest.mark.slow
def test_initialize_auto_pipelines_plain_model():
    """A PLAIN build_gpt model + mesh.pp>1 must train pipelined end to end:
    initialize() converts it via Module.to_pipeline (pp=2 x dp=2 x tp=2, ZeRO-1,
    bf16 off for exact ckpt comparison)."""
    model, _ = build_gpt(_tiny_cfg())
    assert model.to_pipeline is not None
    config = {
        "train_micro_batch_size_per_gpu": 4,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 1},
        "mesh": {"pp": 2, "dp": 2, "tp": 2},
        "pipeline": {"micro_batches": 2},
        "bf16": {"enabled": False},
        "steps_per_print": 0,
    }
    engine, _, _, _ = ds.initialize(model=model, config=config)
    r = np.random.default_rng(0)
    ids = r.integers(0, 64, size=(4, 16), dtype=np.int32)
    losses = [float(engine.train_batch({"input_ids": ids})["loss"])
              for _ in range(8)]
    assert losses[-1] < losses[0], losses


def test_initialize_pp_without_pipeline_model_raises():
    from deepspeed_tpu.models.api import Module

    bare = Module(init=lambda rng: {}, apply=lambda p, b, **k: (jnp.float32(0), {}))
    with pytest.raises(ValueError, match="pipeline-capable"):
        ds.initialize(model=bare, config={
            "train_micro_batch_size_per_gpu": 1, "mesh": {"pp": 2, "dp": 4}})


@pytest.mark.slow
def test_pp_dp_tp_zero3_checkpoint_roundtrip(tmp_path):
    """pp=2 x dp=2 x tp=2 with ZeRO-3 param sharding: train, checkpoint, reload
    into a FRESH engine, and the restored state must continue identically."""
    model, _ = build_gpt(_tiny_cfg())
    config = {
        "train_micro_batch_size_per_gpu": 4,
        "optimizer": {"type": "AdamW", "params": {"lr": 5e-3}},
        "zero_optimization": {"stage": 3, "stage3_param_persistence_threshold": 0},
        "mesh": {"pp": 2, "dp": 2, "tp": 2},
        "pipeline": {"micro_batches": 2},
        "bf16": {"enabled": False},
        "steps_per_print": 0,
    }
    r = np.random.default_rng(1)
    ids = r.integers(0, 64, size=(4, 16), dtype=np.int32)

    engine, _, _, _ = ds.initialize(model=model, config=config)
    for _ in range(3):
        m = engine.train_batch({"input_ids": ids})
    engine.save_checkpoint(str(tmp_path))
    ref = float(engine.train_batch({"input_ids": ids})["loss"])

    model2, _ = build_gpt(_tiny_cfg())
    engine2, _, _, _ = ds.initialize(model=model2, config=config)
    path, _ = engine2.load_checkpoint(str(tmp_path))
    assert path is not None
    got = float(engine2.train_batch({"input_ids": ids})["loss"])
    np.testing.assert_allclose(got, ref, rtol=1e-5)


# ------------------------------------------------------------------- MPMD path
def _mpmd_config(dp=1, micro=4, lr=1e-2, opt="Adam"):
    return {
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": opt, "params": {"lr": lr}},
        "mesh": {"dp": dp},
        "pipeline": {"micro_batches": micro},
        "gradient_clipping": 1.0,
        "bf16": {"enabled": False},
        "steps_per_print": 0,
    }


def test_initialize_returns_pipeline_engine_for_pipeline_module():
    module, _ = _tiny_lm_module(num_stages=4)
    engine, opt, _, _ = ds.initialize(model=module, config=_mpmd_config())
    assert isinstance(engine, PipelineEngine)
    assert opt is engine.optimizer

    r = np.random.default_rng(0)
    batch = {"input_ids": r.integers(0, 31, size=(8, 12), dtype=np.int32)}
    losses = [float(engine.train_batch(batch)["loss"]) for _ in range(6)]
    assert losses[-1] < losses[0], losses
    assert np.isfinite(engine.get_global_grad_norm())
    # 1F1B residency bound still holds through the public engine
    S = module.num_stages
    assert engine.peak_live_buffers == [min(S - s, 4) for s in range(S)]


def test_pipeline_engine_dp_replicas_match_single():
    """dp=2 replica-averaged grads == one replica over the concatenated batch
    (same loss trajectory, the pipeline-boundary DP allreduce semantics)."""
    r = np.random.default_rng(0)
    batch = {"input_ids": r.integers(0, 31, size=(8, 12), dtype=np.int32)}

    module1, _ = _tiny_lm_module(num_stages=2)
    e1, _, _, _ = ds.initialize(model=module1, config=_mpmd_config(dp=1, micro=4))
    module2, _ = _tiny_lm_module(num_stages=2)
    e2, _, _, _ = ds.initialize(model=module2, config=_mpmd_config(dp=2, micro=2))

    for _ in range(3):
        m1 = e1.train_batch(batch)
        m2 = e2.train_batch(batch)
        np.testing.assert_allclose(m1["loss"], m2["loss"], rtol=1e-5)
        np.testing.assert_allclose(m1["grad_norm"], m2["grad_norm"], rtol=1e-4)


def test_pipeline_engine_checkpoint_roundtrip(tmp_path):
    module, _ = _tiny_lm_module(num_stages=2)
    engine, _, _, _ = ds.initialize(model=module, config=_mpmd_config())
    r = np.random.default_rng(0)
    batch = {"input_ids": r.integers(0, 31, size=(8, 12), dtype=np.int32)}
    for _ in range(3):
        engine.train_batch(batch)
    engine.save_checkpoint(str(tmp_path))
    ref = float(engine.train_batch(batch)["loss"])

    module2, _ = _tiny_lm_module(num_stages=2)
    engine2, _, _, _ = ds.initialize(model=module2, config=_mpmd_config())
    path, _ = engine2.load_checkpoint(str(tmp_path))
    assert path is not None
    got = float(engine2.train_batch(batch)["loss"])
    np.testing.assert_allclose(got, ref, rtol=1e-5)


def test_pipeline_engine_eval_batch():
    module, _ = _tiny_lm_module(num_stages=2)
    engine, _, _, _ = ds.initialize(model=module, config=_mpmd_config())
    r = np.random.default_rng(0)
    batch = {"input_ids": r.integers(0, 31, size=(8, 12), dtype=np.int32)}
    out = engine.eval_batch(batch)
    assert out.shape[0] == 4  # M micro-batches stacked
    assert np.all(np.isfinite(np.asarray(out)))
