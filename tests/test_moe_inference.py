"""Expert-parallel MoE inference (VERDICT r2 'next' #5).

Parity: the reference's MoE inference layer
(``/root/reference/deepspeed/ops/transformer/inference/moe_inference.py``) —
generate with the expert bank sharded over the ``ep`` mesh axis, the
dispatch/combine all-to-alls running inside every decode step.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.inference import DeepSpeedInferenceConfig, InferenceEngine
from deepspeed_tpu.inference.engine import for_gpt_moe
from deepspeed_tpu.models import gpt_moe
from deepspeed_tpu.models.gpt import GPTConfig
from deepspeed_tpu.models.gpt_moe import GPTMoEConfig


CFG = GPTMoEConfig(
    base=GPTConfig(vocab_size=64, n_layer=4, n_head=2, d_model=32,
                   max_seq_len=64),
    num_experts=4, moe_freq=2, capacity_factor=2.0, eval_capacity_factor=2.0)


@pytest.fixture(scope="module")
def moe_params():
    return gpt_moe.init_params(CFG, jax.random.PRNGKey(0))


@pytest.mark.slow
def test_cached_forward_matches_full_forward(moe_params, rng):
    """Prefill + stepwise decode logits == full uncached forward logits."""
    ids = rng.integers(0, 64, size=(2, 10)).astype(np.int32)
    full_logits, _aux = gpt_moe.forward(CFG, moe_params, jnp.asarray(ids),
                                        train=False)

    cache = gpt_moe.init_cache(CFG, 2, 16, jnp.float32)
    pre_logits, cache = gpt_moe.forward_with_cache(
        CFG, moe_params, jnp.asarray(ids[:, :7]), cache)
    np.testing.assert_allclose(np.asarray(pre_logits),
                               np.asarray(full_logits[:, :7]),
                               atol=2e-4, rtol=1e-3)
    for t in range(7, 10):
        step_logits, cache = gpt_moe.forward_with_cache(
            CFG, moe_params, jnp.asarray(ids[:, t:t + 1]), cache)
        np.testing.assert_allclose(np.asarray(step_logits[:, 0]),
                                   np.asarray(full_logits[:, t]),
                                   atol=2e-4, rtol=1e-3)


def test_ep_generate_matches_replicated(moe_params, rng):
    """Generate on an ep=4 mesh == generate replicated (same tokens)."""
    ids = rng.integers(0, 64, size=(2, 8)).astype(np.int32)

    def run(ep):
        eng = InferenceEngine(
            for_gpt_moe(CFG, moe_params),
            DeepSpeedInferenceConfig(
                dtype="float32", max_out_tokens=32,
                moe={"ep_size": ep}))
        return eng.generate(ids, max_new_tokens=8)

    out_rep = run(ep=1)
    out_ep = run(ep=4)
    np.testing.assert_array_equal(out_rep, out_ep)
    assert out_ep.shape == (2, 16)


def test_ep_generate_expert_sharding_is_real(moe_params):
    """The placed expert weights must actually be ep-sharded on the mesh."""
    eng = InferenceEngine(
        for_gpt_moe(CFG, moe_params),
        DeepSpeedInferenceConfig(dtype="float32", max_out_tokens=16,
                                 moe={"ep_size": 4}))
    up_w = eng.params["moe_blocks"]["moe"]["experts"]["up_w"]
    spec = tuple(up_w.sharding.spec)
    assert "ep" in str(spec), spec
    assert not up_w.sharding.is_fully_replicated


@pytest.mark.slow
def test_moe_beam_search_runs():
    """Beam search's cache-reorder gather works on the MoE cache stacks too
    (both [L, B, H, S, Dh] layouts, batch axis 1)."""
    from deepspeed_tpu.inference import DeepSpeedInferenceConfig, InferenceEngine
    from deepspeed_tpu.inference.engine import for_gpt_moe
    from deepspeed_tpu.models import gpt_moe
    from deepspeed_tpu.models.gpt import GPTConfig

    cfg = gpt_moe.GPTMoEConfig(
        base=GPTConfig(vocab_size=64, d_model=32, n_layer=2, n_head=2,
                       max_seq_len=96),
        num_experts=2, moe_freq=2)
    params = gpt_moe.init_params(cfg, jax.random.PRNGKey(0))
    eng = InferenceEngine(for_gpt_moe(cfg, params),
                          DeepSpeedInferenceConfig(dtype="float32",
                                                   max_out_tokens=32))
    ids = np.random.default_rng(0).integers(0, 64, (1, 6), np.int32)
    out = np.asarray(eng.generate(ids, max_new_tokens=5, num_beams=3))
    assert out.shape == (1, 11)
    np.testing.assert_array_equal(out[:, :6], ids)
