"""Tensor-parallel serving replicas + disaggregated prefill/decode.

Two layers of coverage (docs/SERVING.md "Tensor parallel & disaggregation"):

- **Real engines on the simulated 8-device CPU mesh** — a tp=2 replica must
  be *invisible* in the outputs: greedy token streams identical to tp=1
  for dense pools AND for the quantized+speculative stack, with the
  sharded-pool audit clean even when pool pressure drives the recompute
  preemption path. Disaggregated serving (one prefill-role + one
  decode-role replica behind the router) must generate exactly what a
  colocated replica generates, including after the prefill replica is
  killed mid-handoff.
- **Device-free scheduler/router tests over the arithmetic fake executor**
  (test_fleet.py idiom) — the handoff ownership-transfer protocol itself:
  staging after the first token, export-before-free, abort/idempotency,
  import-side admission, role-aware placement, and kill-mid-handoff
  failover with zero page leaks on survivors.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.inference.fleet import (FleetConfig, LocalReplica,
                                           ReplicaDeadError, ReplicaRouter)
from deepspeed_tpu.inference.serving import (ContinuousBatchingScheduler,
                                             Request, RequestState,
                                             ServingConfig, ServingEngine,
                                             make_open_loop_workload,
                                             run_continuous)
from deepspeed_tpu.models import gpt as G

CFG = G.GPTConfig(vocab_size=64, d_model=32, n_layer=2, n_head=4,
                  max_seq_len=128)


@pytest.fixture(scope="module")
def params():
    return G.init_params(CFG, jax.random.PRNGKey(0))


def _engine(params, tp=None, role="both", **kw):
    kw.setdefault("num_slots", 3)
    kw.setdefault("num_pages", 48)
    kw.setdefault("page_size", 8)
    kw.setdefault("max_model_len", 64)
    kw.setdefault("prefill_chunk", 16)
    kw.setdefault("dtype", "float32")
    kw.setdefault("max_queue", 64)
    eng = ServingEngine(CFG, params, ServingConfig(tp=tp, role=role, **kw))
    eng.warmup()
    return eng


def _workload(seed=3, n=6):
    wl = make_open_loop_workload(n, rate_rps=1e4, prompt_len=(3, 30),
                                 max_new=(2, 8), vocab_size=64, seed=seed)
    # one multi-chunk prompt for the serial chunked-prefill path
    wl.append(Request(prompt=np.arange(20, dtype=np.int32) + 1,
                      max_new_tokens=4))
    return wl


# --------------------------------------------------- tp2 == tp1 (real mesh)
@pytest.fixture(scope="module")
def tp_pair_dense(params):
    """tp1/tp2 engines with a PAGE-TIGHT pool, so the run also exercises
    the recompute-preemption recovery path under sharding."""
    kw = dict(num_pages=12)
    return _engine(params, **kw), _engine(params, tp=2, **kw)


@pytest.fixture(scope="module")
def tp_pair_kv8_spec(params):
    kw = dict(kv_bits=8, spec_drafter="ngram", spec_k=4)
    return _engine(params, **kw), _engine(params, tp=2, **kw)


def _run_pair(e1, e2, wl_fn):
    wl1, wl2 = wl_fn(), wl_fn()
    r1, r2 = run_continuous(e1, wl1), run_continuous(e2, wl2)
    assert r1["finished"] == len(wl1) and r2["finished"] == len(wl2)
    for a, b in zip(wl1, wl2):
        assert list(a.tokens) == list(b.tokens), (a.rid, a.tokens, b.tokens)
    return r1, r2


def test_tp2_greedy_identical_dense_with_preemption(tp_pair_dense):
    """Head-sharded attention + row/col-split MLP over the 2-chip mesh must
    not change a single greedy token — including through recompute
    preemptions (the page-tight pool forces them identically on both sides,
    since the scheduler is host-pure), and the sharded pool must pass the
    page audit afterwards."""
    e1, e2 = tp_pair_dense

    def wl():
        w = _workload(3)
        # growers: 1 page at admission, 4 pages at completion — three of
        # them outgrow the 11-page pool together, forcing recompute
        # preemption identically on both sides
        for i in range(3):
            w.append(Request(
                prompt=(np.arange(6, dtype=np.int32) + 1 + 5 * i) % 63 + 1,
                max_new_tokens=26))
        return w

    r1, r2 = _run_pair(e1, e2, wl)
    assert r1["recovery_counters"].get("preemption", 0) >= 1
    assert r1["recovery_counters"] == r2["recovery_counters"]
    assert r1["pool_audit_ok"] and r2["pool_audit_ok"]


def test_tp2_greedy_identical_quantized_speculative(tp_pair_kv8_spec):
    """The full serving stack — int8 KV pages + n-gram speculation with
    paged multi-token verify — stays greedy-identical under tp=2."""
    e1, e2 = tp_pair_kv8_spec
    r1, r2 = _run_pair(e1, e2, lambda: _workload(5))
    assert r1["pool_audit_ok"] and r2["pool_audit_ok"]


def test_tp_sharded_page_export_import_roundtrip(tp_pair_kv8_spec):
    """Pages exported from a SHARDED quantized pool survive the wire
    round-trip (int8 payload + fp32 per-page scales through the base64
    transport form) bit-exactly across a tp2 -> tp1 transfer, and import
    re-pins the tp sharding on the receiving pool."""
    from deepspeed_tpu.inference.fleet.replica import (decode_kv_payload,
                                                       encode_kv_payload)

    e1, e2 = tp_pair_kv8_spec
    p2 = e2.export_pages([1, 2])
    wire = decode_kv_payload(encode_kv_payload(p2))
    e1.import_pages([3, 4], wire)
    back = e1.export_pages([3, 4])
    assert set(back["tensors"]) == set(p2["tensors"])
    for key in p2["tensors"]:
        assert back["tensors"][key]["data"] == p2["tensors"][key]["data"], key
    e2.import_pages([3, 4], wire)
    specs = e2.tp_context.cache_specs(e2.paged_cache)
    for k, arr in e2.paged_cache.items():
        assert arr.sharding.spec == specs[k], k


# ----------------------------------------- disaggregation with real engines
@pytest.fixture(scope="module")
def disagg_engines(params):
    """colocated-reference / prefill-specialist / decode-specialist, all
    over int8 KV pages (the payload wire the handoff quantizes)."""
    kw = dict(kv_bits=8)
    return (_engine(params, role="both", **kw),
            _engine(params, role="prefill", **kw),
            _engine(params, role="decode", **kw))


def _route(replicas, wl):
    router = ReplicaRouter(replicas, FleetConfig(reroute_budget=2))
    reqs = []
    for r in wl:
        assert router.submit(r).admitted
        reqs.append(r)
    router.run_to_completion(max_steps=10_000)
    return router, [list(r.tokens) for r in reqs]


def test_disagg_generate_identical_to_colocated(disagg_engines):
    """Prefill-specialist fills the pages, hands them off over the wire
    protocol, decode-specialist continues — outputs identical to one
    colocated replica, quantized payloads and all."""
    colo_eng, pre_eng, dec_eng = disagg_engines
    _, ref = _route([LocalReplica("colo", engine=colo_eng)], _workload(7))
    router, got = _route([LocalReplica("pre", engine=pre_eng),
                          LocalReplica("dec", engine=dec_eng)], _workload(7))
    assert got == ref
    assert router.counters.get("handoff_forwarded", 0) == len(ref)
    audit = router.audit_survivors()
    assert audit["ok"], audit


def test_disagg_prefill_killed_mid_handoff_heals(disagg_engines):
    """The prefill replica dies with handoffs staged but never delivered
    (the SIGKILL-mid-handoff model: pages exported, ack never arrives, the
    pool dies with the process). Victims re-route with kept tokens; the
    decode specialist re-prefills them (role fallback) and the outputs
    still match the colocated reference; the survivor audits clean."""
    colo_eng, pre_eng, dec_eng = disagg_engines
    _, ref = _route([LocalReplica("colo", engine=colo_eng)], _workload(9))

    class DiesMidHandoff(LocalReplica):
        def pump(self, max_steps=1):
            super().pump(max_steps)  # stages + pops handoffs internally
            self._alive = False      # ... but the report never lands
            raise ReplicaDeadError("SIGKILL mid-handoff")

    router, got = _route([DiesMidHandoff("pre", engine=pre_eng),
                          LocalReplica("dec", engine=dec_eng)], _workload(9))
    assert got == ref
    assert router.counters.get("replica_dead", 0) == 1
    assert router.counters.get("request_rerouted", 0) >= 1
    audit = router.audit_survivors()
    assert audit["ok"], audit


# ------------------------------------- scheduler-level handoff (device-free)
class FakeExecutor:
    """test_fleet.py's arithmetic executor + the disaggregation protocol:
    export/import move a deterministic per-page byte payload so the test
    can assert the transport carried exactly the staged pages."""

    def __init__(self):
        self.exported = []
        self.imported = []

    def prefill(self, slot, tokens, table_row):
        return (int(tokens[-1]) + 1) % 97

    def decode(self, tokens, tables, lengths, active, steps=1):
        return np.stack([(tokens + k + 1) % 97 for k in range(steps)])

    def export_pages(self, page_ids):
        ids = [int(p) for p in page_ids]
        self.exported.append(ids)
        return {"page_ids": ids,
                "tensors": {"k_pages": {
                    "dtype": "int32", "shape": [1, 1, len(ids)],
                    "data": np.asarray(ids, np.int32).tobytes()}}}

    def import_pages(self, page_ids, payload):
        self.imported.append(([int(p) for p in page_ids], payload))


def mk_sched(num_slots=2, num_pages=32, page_size=4, pages_per_seq=8, **kw):
    return ContinuousBatchingScheduler(
        FakeExecutor(), num_slots=num_slots, num_pages=num_pages,
        page_size=page_size, pages_per_seq=pages_per_seq, **kw)


def test_prefill_role_stages_handoff_after_first_token():
    sched = mk_sched(role="prefill")
    req = Request(prompt=np.arange(1, 6, dtype=np.int32), max_new_tokens=8)
    assert sched.submit(req).admitted
    sched.step()
    assert req.state is RequestState.HANDOFF
    assert req.tokens == [6]                    # last+1, exactly one token
    assert sched.pending_handoff_rids == {req.rid}
    assert not sched.idle                       # staged pages still owned
    (entry,) = sched.pop_handoffs()
    # live KV = context_len - 1: the first token's KV is unwritten (the
    # decode side writes it at its first decode step)
    assert entry["context_len"] == len(req.prompt)
    assert len(entry["page_ids"]) == 2          # ceil(5/4) pages
    assert sched.pop_handoffs() == []           # popped entries not re-sent
    assert sched.audit()["ok"]
    free_before = sched.allocator.free_pages
    assert sched.complete_handoff(req.rid, ok=True)
    assert sched.allocator.free_pages == free_before + 2
    assert sched.idle and sched.audit()["ok"]
    assert not sched.complete_handoff(req.rid)  # idempotent


def test_handoff_abort_frees_pages():
    sched = mk_sched(role="prefill")
    req = Request(prompt=np.arange(1, 4, dtype=np.int32), max_new_tokens=4)
    sched.submit(req)
    sched.step()
    assert sched.complete_handoff(req.rid, ok=False)
    assert sched.counters.get("handoff_aborted", 0) == 1
    assert sched.allocator.allocated_pages == 0
    assert sched.idle and sched.audit()["ok"]


def test_import_admission_continues_identically():
    """A decode-side scheduler admitting via kv_payload must produce the
    same continuation a colocated run produces, without ever prefilling."""
    prompt = np.arange(1, 6, dtype=np.int32)
    ref = Request(prompt=prompt.copy(), max_new_tokens=6)
    colo = mk_sched()
    colo.submit(ref)
    colo.run_to_completion(max_steps=100)

    pre = mk_sched(role="prefill")
    req = Request(prompt=prompt.copy(), max_new_tokens=6)
    pre.submit(req)
    pre.step()
    (entry,) = pre.pop_handoffs()
    payload = pre.executor.export_pages(entry["page_ids"])
    pre.complete_handoff(req.rid, ok=True)

    dec = mk_sched(role="decode")
    cont = Request(prompt=prompt.copy(), max_new_tokens=6, rid=req.rid)
    cont.tokens = list(req.tokens)
    cont.kv_payload = payload
    assert dec.submit(cont).admitted
    dec.run_to_completion(max_steps=100)
    assert cont.tokens == ref.tokens
    # the import claimed pages and fed the transport the staged payload
    (ids, got) = dec.executor.imported[0]
    assert got is payload and len(ids) == len(entry["page_ids"])
    assert cont.kv_payload is None   # consumed: preemption re-prefills
    assert dec.audit()["ok"] and pre.audit()["ok"]


def test_router_role_aware_placement_and_forwarding():
    """Fresh requests land only on prefill-capable replicas; handoffs are
    forwarded only to decode-capable ones; every stream matches the
    single-scheduler reference."""
    spec = ((3, 6), (5, 4), (2, 8), (4, 3))

    def workload():
        return [Request(prompt=np.arange(1, n + 1, dtype=np.int32),
                        max_new_tokens=m) for n, m in spec]

    ref_sched = mk_sched(num_slots=4)
    refs = workload()
    for r in refs:
        ref_sched.submit(r)
    ref_sched.run_to_completion(max_steps=500)

    pre = LocalReplica("pre", scheduler=mk_sched(num_slots=4,
                                                 role="prefill"))
    dec = LocalReplica("dec", scheduler=mk_sched(num_slots=4, role="decode"))
    router = ReplicaRouter([pre, dec])
    reqs = workload()
    for r in reqs:
        assert router.submit(r).admitted
        assert router._assignment[r.rid] == "pre"
    router.run_to_completion()
    assert [list(r.tokens) for r in reqs] == [list(r.tokens) for r in refs]
    assert pre.sched.counters["handoff_staged"] == len(spec)
    assert pre.sched.counters["handoff_complete"] == len(spec)
    assert dec.sched.counters["handoff_import"] == len(spec)
    assert router.counters["handoff_forwarded"] == len(spec)
    assert router.audit_survivors()["ok"]


def test_router_handoff_falls_back_to_reprefill_when_no_decode_capacity():
    """Every decode-capable sibling refusing degrades to the kept-token
    re-prefill contract: the source frees the staged pages and the request
    re-places normally (here back onto the prefill-capable pool, which
    re-prefills and re-stages until capacity frees up — with NO decode
    replica at all, role fallback lets the prefill replica finish it)."""
    pre = LocalReplica("pre", scheduler=mk_sched(num_slots=2,
                                                 role="prefill"))
    router = ReplicaRouter([pre])
    req = Request(prompt=np.arange(1, 5, dtype=np.int32), max_new_tokens=4)
    assert router.submit(req).admitted
    router.run_to_completion()
    # no decode-capable replica exists: the handoff aborts, the request
    # re-routes to the only live replica, which (being prefill-role)
    # stages again — the reroute budget caps the ping-pong and the fleet
    # rejects rather than loops forever. Either terminal state is a
    # CORRECT degraded outcome; what must hold is conservation:
    assert req.state in (RequestState.FINISHED, RequestState.REJECTED)
    assert pre.sched.counters.get("handoff_aborted", 0) >= 1
    assert router.audit_survivors()["ok"]
    assert pre.sched.idle


# ------------------------------------------------------------------ dslint
def test_tp_collective_order_rule_silent_on_shipped_programs(
        tp_pair_kv8_spec):
    from deepspeed_tpu.analysis import analyze_compile_log

    _, e2 = tp_pair_kv8_spec
    assert e2.tp_context is not None and e2.tp_context.captured
    rep = analyze_compile_log(e2)
    assert not [f for f in rep.findings
                if f.rule_id == "serving/tp-collective-order"], rep.findings


def test_tp_collective_order_rule_fires():
    from jax.sharding import PartitionSpec as P

    from deepspeed_tpu.analysis import analyze_fn
    from deepspeed_tpu.analysis.rules_collectives import TpCollectiveOrderRule
    from deepspeed_tpu.utils.jax_compat import shard_map

    mesh = jax.make_mesh((2,), ("tp",))

    def guarded_psum(x, flag):
        def body(x, flag):
            return jax.lax.cond(flag > 0,
                                lambda v: jax.lax.psum(v, "tp"),
                                lambda v: v, x)
        return shard_map(body, mesh=mesh, in_specs=(P("tp"), P()),
                         out_specs=P("tp"), check_vma=False)(x, flag)

    rep = analyze_fn(guarded_psum, jnp.zeros((8,)), jnp.int32(1),
                     name="guarded", rules=[TpCollectiveOrderRule()])
    assert [f for f in rep.findings
            if f.rule_id == "serving/tp-collective-order"], rep.findings

    def while_psum(x):
        def body(x):
            def cond(c):
                return jax.lax.psum(c[1].sum(), "tp") > 0

            def step(c):
                return c[0] + 1, c[1] - 1.0

            return jax.lax.while_loop(cond, step, (0, x))[1]
        return shard_map(body, mesh=mesh, in_specs=(P("tp"),),
                         out_specs=P("tp"), check_vma=False)(x)

    rep = analyze_fn(while_psum, jnp.ones((8,)), name="while_pred",
                     rules=[TpCollectiveOrderRule()])
    assert [f for f in rep.findings
            if f.rule_id == "serving/tp-collective-order"], rep.findings


def test_tp_collective_order_rule_silent_on_collective_free_cond():
    from jax.sharding import PartitionSpec as P

    from deepspeed_tpu.analysis import analyze_fn
    from deepspeed_tpu.analysis.rules_collectives import TpCollectiveOrderRule
    from deepspeed_tpu.utils.jax_compat import shard_map

    mesh = jax.make_mesh((2,), ("tp",))

    def hoisted(x, flag):
        def body(x, flag):
            y = jax.lax.cond(flag > 0, lambda v: v * 2, lambda v: v, x)
            return jax.lax.psum(y, "tp")
        return shard_map(body, mesh=mesh, in_specs=(P("tp"), P()),
                         out_specs=P(), check_vma=False)(x, flag)

    rep = analyze_fn(hoisted, jnp.zeros((8,)), jnp.int32(1), name="hoisted",
                     rules=[TpCollectiveOrderRule()])
    assert not [f for f in rep.findings
                if f.rule_id == "serving/tp-collective-order"], rep.findings


# --------------------------------------------------------------- aot sizing
def test_fleet_replica_plan_roles_and_tp(monkeypatch):
    from deepspeed_tpu.runtime import aot

    seen = {}

    def fake_limit(model, **kw):
        seen.update(kw)
        return {"model": model, "max_slots": 4, "max_decode_batch": 4,
                "fit": "fits", "trace": [], "tp": int(kw.get("tp", 1) or 1),
                "role": kw.get("role", "both")}

    monkeypatch.setattr(aot, "serving_admission_limit", fake_limit)
    plan = aot.fleet_replica_plan("gpt2-125m", target_total_slots=10,
                                  tp=2, role="prefill")
    assert seen["tp"] == 2 and seen["role"] == "prefill"
    assert plan["tp"] == 2 and plan["role"] == "prefill"
    assert plan["replicas"] == 3
    assert plan["chips"] == plan["replicas"] * 2


def test_serving_admission_limit_prefill_pricing(monkeypatch):
    """A prefill-role replica is priced at gen=1 (it never decodes past the
    first token) with speculation dropped — more slots per chip."""
    from deepspeed_tpu.runtime import aot

    calls = []

    def fake_find(model, lo=1, hi=64, **kw):
        calls.append(kw)
        return {"model": model, "max_batch": 8, "trace": [],
                "report": {"fit": {"confidence": "fits"}}}

    monkeypatch.setattr(aot, "find_max_decode_batch", fake_find)
    # the drafter is DROPPED for prefill replicas, so the verdict goes
    # through the plain (non-speculative) ladder at gen=1
    out = aot.serving_admission_limit("gpt2-125m", role="prefill",
                                      draft_model="gpt2-125m", spec_k=4)
    assert out["role"] == "prefill" and out["tp"] == 1
    assert out["max_slots"] == 8 and "speculation" not in out
    assert calls and all(kw.get("gen") == 1 for kw in calls)
    with pytest.raises(ValueError, match="role"):
        aot.serving_admission_limit("gpt2-125m", role="bogus")
