"""MoE: gating math, dispatch/combine consistency, expert-parallel training.

Parity model: the reference's MoE unit tests (``tests/unit/moe/test_moe.py``) —
mechanics (shapes, capacity, aux loss, EP-sharded training step) on a simulated
8-device mesh.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.moe import (
    GateConfig,
    MoEConfig,
    apply_moe,
    compute_capacity,
    count_moe_params,
    gate,
    init_moe,
    split_moe_params,
    top1gating,
    top2gating,
)
from deepspeed_tpu.runtime.topology import MeshTopology


def test_capacity_math():
    assert compute_capacity(64, 8, 1.0) == 8
    assert compute_capacity(64, 8, 1.25) == 10
    assert compute_capacity(8, 8, 1.0, min_capacity=4) == 4


@pytest.mark.parametrize("k", [1, 2])
def test_gating_shapes_and_consistency(k):
    G, N, E, C = 2, 32, 4, 16
    rng = jax.random.PRNGKey(0)
    logits = jax.random.normal(rng, (G, N, E))
    fn = top1gating if k == 1 else top2gating
    aux, combine, dispatch, counts = fn(logits, C, train=False)
    assert combine.shape == (G, N, E, C)
    assert dispatch.shape == (G, N, E, C)
    assert counts.shape == (G, E)
    assert np.isfinite(float(aux))
    # dispatch is exactly where combine > 0
    np.testing.assert_array_equal(np.asarray(dispatch), np.asarray(combine) > 0)
    # each token occupies at most k slots
    per_token = np.asarray(jnp.sum(dispatch, axis=(2, 3)))
    assert (per_token <= k).all()
    # each (expert, slot) holds at most one token
    per_slot = np.asarray(jnp.sum(dispatch, axis=1))
    assert (per_slot <= 1).all()
    # combine weights per token sum to <= 1 (softmax mass of routed experts)
    w = np.asarray(jnp.sum(combine, axis=(2, 3)))
    assert (w <= 1.0 + 1e-5).all()


def test_top2_weights_normalized():
    G, N, E = 1, 16, 4
    logits = jax.random.normal(jax.random.PRNGKey(1), (G, N, E))
    # huge capacity: nothing dropped -> weights sum to exactly 1
    aux, combine, dispatch, _ = top2gating(logits, capacity=N * 2, train=False)
    w = np.asarray(jnp.sum(combine, axis=(2, 3)))
    np.testing.assert_allclose(w, 1.0, atol=1e-5)


def test_capacity_drops_tokens():
    G, N, E, C = 1, 32, 2, 4  # way under capacity: must drop
    logits = jnp.zeros((G, N, E)).at[:, :, 0].set(10.0)  # all want expert 0
    aux, combine, dispatch, counts = top1gating(logits, C, train=False)
    kept = int(jnp.sum(dispatch))
    assert kept == C  # expert 0 fills its C slots, everyone else dropped


@pytest.mark.slow
def test_single_expert_equals_dense_mlp():
    """E=1, cap covering all tokens: MoE == plain FFN (up to gate weighting = 1)."""
    cfg = MoEConfig(d_model=16, d_ff=32, num_experts=1, capacity_factor=1.0,
                    min_capacity=1024, eval_capacity_factor=1.0)
    params = init_moe(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 16))
    y, aux, counts = apply_moe(cfg, params, x, train=False)
    w = params["experts"]
    h = x @ w["up_w"][0] + w["up_b"][0]
    h = jax.nn.gelu(h, approximate=True)
    expect = h @ w["down_w"][0] + w["down_b"][0]
    np.testing.assert_allclose(np.asarray(y), np.asarray(expect), atol=1e-4)


def test_residual_moe():
    cfg = MoEConfig(d_model=16, d_ff=32, num_experts=2, use_residual=True,
                    min_capacity=64)
    params = init_moe(jax.random.PRNGKey(0), cfg)
    assert "residual_mlp" in params and "coefficient" in params
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 16))
    y, aux, _ = apply_moe(cfg, params, x, train=False)
    assert y.shape == x.shape
    assert np.isfinite(np.asarray(y)).all()


def test_moe_param_split():
    cfg = MoEConfig(d_model=8, d_ff=16, num_experts=2)
    params = {"moe": init_moe(jax.random.PRNGKey(0), cfg), "dense_w": jnp.ones((4, 4))}
    dense, moe = split_moe_params(params)
    assert dense["dense_w"] is not None and dense["moe"]["experts"]["up_w"] is None
    assert moe["moe"]["experts"]["up_w"] is not None and moe["dense_w"] is None
    counts = count_moe_params(params)
    assert counts["expert"] == 2 * (8 * 16 + 16 + 16 * 8 + 8)


@pytest.mark.slow
def test_gpt_moe_trains_with_ep_sharding(devices):
    """Full engine step on dp=4 x ep=2: loss finite, experts sharded over ep,
    aux loss reported."""
    from deepspeed_tpu.models import build_gpt_moe

    model, cfg = build_gpt_moe("tiny-moe")
    topo = MeshTopology.create(dp=4, ep=2, devices=devices)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, topology=topo,
        config={
            "train_micro_batch_size_per_gpu": 1,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 1},
            "mesh": {"dp": 4, "ep": 2},
            "steps_per_print": 0,
        })
    up_w = engine.state["params"]["moe_blocks"]["moe"]["experts"]["up_w"]
    assert "ep" in str(up_w.sharding.spec), f"experts not ep-sharded: {up_w.sharding.spec}"
    rng = np.random.default_rng(0)
    losses = []
    for _ in range(3):
        batch = {"input_ids": rng.integers(0, 256, size=(8, 64), dtype=np.int32)}
        m = engine.train_batch(batch)
        losses.append(float(m["loss"]))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]  # training moves


@pytest.mark.slow
def test_gpt_moe_all_layers_moe(devices):
    """moe_freq=1 path (every MLP is MoE)."""
    from deepspeed_tpu.models.gpt import GPTConfig
    from deepspeed_tpu.models.gpt_moe import GPTMoEConfig, build

    cfg = GPTMoEConfig(
        base=GPTConfig(vocab_size=128, n_layer=2, n_head=2, d_model=32,
                       max_seq_len=64),
        num_experts=2, moe_freq=1, capacity_factor=2.0)
    model, _ = build(cfg)
    params = model.init(jax.random.PRNGKey(0))
    loss, aux = model.apply(params, {"input_ids": jnp.zeros((2, 16), jnp.int32)},
                            train=False)
    assert np.isfinite(float(loss))
    assert "moe_aux_loss" in aux
