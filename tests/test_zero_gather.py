"""ZeRO-3 gather/release knobs are real (VERDICT r2 'next' #4).

Parity: the reference's PartitionedParameterCoordinator honors
``stage3_max_live_parameters`` / ``stage3_prefetch_bucket_size``
(``runtime/zero/partitioned_param_coordinator.py:44``). Here the knobs window
the layer scan (runtime/zero/gather.py): these tests assert (a) the window
math, (b) that the knobs CHANGE the compiled program structure (outer scan trip
count drops to L/k, i.e. gathers are batched k layers at a time), and (c) that
numerics are invariant to the window.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu as ds
from deepspeed_tpu.models import build_gpt, gpt
from deepspeed_tpu.runtime.zero.config import DeepSpeedZeroConfig
from deepspeed_tpu.runtime.zero.gather import (
    gather_window,
    window_size,
    zero3_layer_scan,
)


def _blocks(L=8, d=4):
    return {"w": jnp.ones((L, d, d)), "b": jnp.zeros((L, d))}


def test_window_size_math():
    blocks = _blocks(L=8, d=4)  # per-layer = 4*4 + 4 = 20 params
    def cfg(prefetch, max_live, stage=3):
        return DeepSpeedZeroConfig(
            stage=stage, stage3_prefetch_bucket_size=prefetch,
            stage3_max_live_parameters=max_live)

    with gather_window(cfg(prefetch=40, max_live=10**9)):
        assert window_size(blocks, 8) == 2  # 40 // 20
    with gather_window(cfg(prefetch=10**9, max_live=10**9)):
        assert window_size(blocks, 8) == 8  # uncapped -> whole stack
    with gather_window(cfg(prefetch=10**9, max_live=45)):
        assert window_size(blocks, 8) == 2  # max_live caps: 45 // 20
    with gather_window(cfg(prefetch=0, max_live=10**9)):
        assert window_size(blocks, 8) == 1  # no prefetch -> per-layer
    with gather_window(cfg(prefetch=10**9, max_live=10**9, stage=2)):
        assert window_size(blocks, 8) == 1  # stage < 3 -> untouched
    with gather_window(cfg(prefetch=65, max_live=10**9)):
        assert window_size(blocks, 8) == 2  # 65//20 = 3 -> divisor of 8 -> 2
    assert window_size(blocks, 8) == 1  # no active config
    # opt-in: a bare {"stage": 3} (knobs at pydantic defaults, not user-set)
    # keeps the minimal-residency per-layer schedule
    with gather_window(DeepSpeedZeroConfig(stage=3)):
        assert window_size(blocks, 8) == 1
    # a cap-only config expresses a LIMIT, not a prefetch request: no windowing
    with gather_window(DeepSpeedZeroConfig(
            stage=3, stage3_max_live_parameters=10**9)):
        assert window_size(blocks, 8) == 1


def test_zero3_layer_scan_numerics_invariant():
    """Chunked scan == plain scan, values and grads."""
    blocks = {"w": jnp.asarray(np.random.default_rng(0).normal(size=(8, 4, 4)),
                               jnp.float32)}
    x0 = jnp.ones((4,), jnp.float32)

    def body(c, w):
        return jnp.tanh(w["w"] @ c), None

    def run(cfg):
        def f(blocks):
            with gather_window(cfg):
                return jnp.sum(zero3_layer_scan(body, x0, blocks))
        return jax.value_and_grad(f)(blocks)

    v1, g1 = run(None)
    v2, g2 = run(DeepSpeedZeroConfig(
        stage=3, stage3_prefetch_bucket_size=100, stage3_max_live_parameters=10**9))
    np.testing.assert_allclose(float(v1), float(v2), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(g1["w"]), np.asarray(g2["w"]), rtol=1e-5)


def _scan_lengths(jaxpr) -> list:
    out = []
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "scan":
            out.append(eqn.params["length"])
            out.extend(_scan_lengths(eqn.params["jaxpr"].jaxpr))
        elif "jaxpr" in eqn.params:
            inner = eqn.params["jaxpr"]
            out.extend(_scan_lengths(getattr(inner, "jaxpr", inner)))
    return out


def test_knobs_change_program_structure():
    """With a 2-layer window the traced program's layer loop becomes an outer
    scan of L/2 chunks with an inner scan of 2 — the gather is batched 2 layers
    at a time (the prefetch window)."""
    cfg = gpt.GPTConfig(vocab_size=64, n_layer=4, n_head=2, d_model=32,
                        max_seq_len=32)
    params = gpt.init_params(cfg, jax.random.PRNGKey(0))
    per_layer = sum(int(np.prod(x.shape))
                    for x in jax.tree_util.tree_leaves(params["blocks"])) // 4
    batch = {"input_ids": jnp.zeros((2, 16), jnp.int32)}

    def trace(zcfg):
        with gather_window(zcfg):
            return jax.make_jaxpr(
                lambda p: gpt.loss_fn(cfg, p, batch, train=False)[0])(params)

    plain = _scan_lengths(trace(None).jaxpr)
    assert 4 in plain and 2 not in plain

    windowed = _scan_lengths(trace(DeepSpeedZeroConfig(
        stage=3, stage3_prefetch_bucket_size=2 * per_layer,
        stage3_max_live_parameters=10**9)).jaxpr)
    assert 2 in windowed, windowed  # L/k = 2 outer chunks (and k = 2 inner)
    assert 4 not in windowed, windowed


@pytest.mark.slow
def test_engine_zero3_knobs_end_to_end():
    """Through initialize(): same seed/data, window on vs off -> same loss; the
    windowed program really ran stage-3 sharded params."""
    def make(prefetch):
        model, _ = build_gpt(gpt.GPTConfig(
            vocab_size=64, n_layer=4, n_head=2, d_model=32, max_seq_len=32))
        engine, _, _, _ = ds.initialize(model=model, config={
            "train_micro_batch_size_per_gpu": 1,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
            "zero_optimization": {
                "stage": 3,
                "stage3_param_persistence_threshold": 0,
                "stage3_prefetch_bucket_size": prefetch,
                "stage3_max_live_parameters": 10**9,
            },
            "mesh": {"dp": 8},
            "bf16": {"enabled": False},
            "steps_per_print": 0,
        })
        return engine

    r = np.random.default_rng(0)
    ids = r.integers(0, 64, size=(8, 16), dtype=np.int32)
    e_win, e_plain = make(prefetch=10**9), make(prefetch=0)
    assert not e_win.state["params"]["blocks"]["qkv_w"].sharding.is_fully_replicated
    for _ in range(2):
        m_win = e_win.train_batch({"input_ids": ids})
        m_plain = e_plain.train_batch({"input_ids": ids})
        np.testing.assert_allclose(float(m_win["loss"]), float(m_plain["loss"]),
                                   rtol=1e-5)
