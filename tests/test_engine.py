"""End-to-end engine tests on the simulated 8-device mesh.

Mirrors the reference's test discipline (SURVEY.md §4): assert *mechanics* — losses
decrease, ZeRO stages agree with each other, fwd/bwd/step API matches train_batch —
on small fixture models, not convergence.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.models import GPTConfig, build_gpt

TINY = GPTConfig(vocab_size=256, n_layer=2, n_head=4, d_model=64, max_seq_len=64)


def base_config(stage=0, gas=1, micro=4, **over):
    cfg = {
        "train_micro_batch_size_per_gpu": micro,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": stage},
        "gradient_clipping": 1.0,
        "steps_per_print": 0,
    }
    cfg.update(over)
    return cfg


def make_batch(seed, micro, seq=32, gas=1, world=8):
    rng = np.random.default_rng(seed)
    n = micro * world
    shape = (n, seq) if gas == 1 else (gas, n, seq)
    return {"input_ids": rng.integers(0, 256, size=shape, dtype=np.int32)}


def make_engine(stage=0, gas=1, micro=4, **over):
    model, _ = build_gpt(TINY)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, config=base_config(stage=stage, gas=gas, micro=micro, **over))
    return engine


@pytest.mark.parametrize("stage", [0, 1, 2, 3])
def test_train_batch_loss_decreases(stage, devices):
    engine = make_engine(stage=stage)
    losses = []
    for i in range(8):
        m = engine.train_batch(make_batch(i % 2, 4))
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0], losses
    assert np.isfinite(losses).all()


@pytest.mark.slow
def test_zero_stages_agree(devices):
    """ZeRO is an exact re-layout: every stage must produce identical losses."""
    traces = {}
    for stage in [0, 1, 2, 3]:
        engine = make_engine(stage=stage)
        losses = []
        for i in range(4):
            m = engine.train_batch(make_batch(i, 4))
            losses.append(float(m["loss"]))
        traces[stage] = losses
    for stage in [1, 2, 3]:
        np.testing.assert_allclose(traces[stage], traces[0], rtol=2e-4), stage


def test_zero_shardings_actually_shard(devices):
    engine3 = make_engine(
        stage=3,
        zero_optimization={"stage": 3, "stage3_param_persistence_threshold": 0})
    qkv = engine3.state["params"]["blocks"]["qkv_w"]
    assert not qkv.sharding.is_fully_replicated
    engine1 = make_engine(stage=1)
    qkv1 = engine1.state["params"]["blocks"]["qkv_w"]
    assert qkv1.sharding.is_fully_replicated
    mu = engine1.state["opt"].mu["blocks"]["qkv_w"]
    assert not mu.sharding.is_fully_replicated


@pytest.mark.slow
def test_forward_backward_step_matches_train_batch(devices):
    e1 = make_engine(stage=1, gas=2, micro=2)
    e2 = make_engine(stage=1, gas=2, micro=2)
    batch = make_batch(0, 2, gas=2)
    m = e1.train_batch(batch)
    # same data through the imperative API
    mb0 = {k: v[0] for k, v in batch.items()}
    mb1 = {k: v[1] for k, v in batch.items()}
    l0 = e2.forward(mb0)
    e2.backward(l0)
    e2.step()  # not at boundary: no-op
    assert int(e2.state["step"]) == 0
    l1 = e2.forward(mb1)
    e2.backward(l1)
    e2.step()
    assert int(e2.state["step"]) == 1
    np.testing.assert_allclose(
        float(m["loss"]), (float(l0) + float(l1)) / 2, rtol=1e-5)
    # params must match bitwise-ish between the two paths
    p1 = jax.tree_util.tree_leaves(e1.state["params"])
    p2 = jax.tree_util.tree_leaves(e2.state["params"])
    for a, b in zip(p1, p2):
        np.testing.assert_allclose(np.asarray(a, np.float32), np.asarray(b, np.float32),
                                   rtol=1e-5, atol=1e-6)


def test_bf16_master_weights(devices):
    engine = make_engine(stage=2, bf16={"enabled": True})
    assert engine.state["params"]["wte"].dtype == jnp.bfloat16
    assert engine.state["master"]["wte"].dtype == jnp.float32
    m = engine.train_batch(make_batch(0, 4))
    assert np.isfinite(float(m["loss"]))


def test_fp16_loss_scaling_overflow_skip(devices):
    engine = make_engine(stage=0, fp16={"enabled": True, "initial_scale_power": 4})
    s0 = engine.get_loss_scale()
    assert s0 == 2.0 ** 4
    m = engine.train_batch(make_batch(0, 4))
    assert np.isfinite(float(m["loss"]))


def test_tp_mesh_training(devices):
    model, _ = build_gpt(TINY)
    cfg = base_config(stage=1)
    cfg["mesh"] = {"tp": 2}
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=cfg)
    assert engine.topo.axes["tp"] == 2 and engine.topo.axes["dp"] == 4
    qkv = engine.state["params"]["blocks"]["qkv_w"]
    assert not qkv.sharding.is_fully_replicated  # tp-sharded
    m = engine.train_batch(make_batch(0, 4, world=4))
    assert np.isfinite(float(m["loss"]))


def test_lr_schedule_in_step(devices):
    engine = make_engine(
        stage=0,
        scheduler={"type": "WarmupLR",
                   "params": {"warmup_min_lr": 0.0, "warmup_max_lr": 1e-3,
                              "warmup_num_steps": 10}})
    m1 = engine.train_batch(make_batch(0, 4))
    m2 = engine.train_batch(make_batch(1, 4))
    assert float(m2["lr"]) > float(m1["lr"])


# ----------------------------------------------------- comm-dtype / prescale
@pytest.mark.slow
def test_prescale_and_comm_dtype_numerics_match_default(rng):
    """prescale_gradients + gradient_predivide_factor and a bf16
    communication_data_type must leave fp32 training numerics (approximately)
    unchanged — they are range/bandwidth knobs, not semantics changes."""
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import build_gpt, gpt as gpt_mod

    def run(extra):
        model, _ = build_gpt(gpt_mod.GPTConfig(
            vocab_size=64, n_layer=2, n_head=2, d_model=32, max_seq_len=32))
        engine, _, _, _ = ds.initialize(model=model, seed=11, config={
            "train_micro_batch_size_per_gpu": 1,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
            "zero_optimization": {"stage": 2},
            "mesh": {"dp": 8},
            "bf16": {"enabled": False},
            "steps_per_print": 0,
            **extra,
        })
        ids = np.random.default_rng(5).integers(0, 64, size=(8, 16), dtype=np.int32)
        return [float(engine.train_batch({"input_ids": ids})["grad_norm"])
                for _ in range(2)]

    base = run({})
    pre = run({"prescale_gradients": True, "gradient_predivide_factor": 32.0})
    np.testing.assert_allclose(pre, base, rtol=1e-4)
    # comm dtype below the compute dtype cannot change the fused reduction's
    # wire dtype on TPU (HLO-verified) — refused, not faked
    with pytest.raises(ValueError, match="communication_data_type"):
        run({"communication_data_type": "bf16"})
    # matching (or wider) requests are naturally satisfied
    base2 = run({"communication_data_type": "fp32"})
    np.testing.assert_allclose(base2, base, rtol=1e-6)


@pytest.mark.slow
def test_remat_policies_loss_and_grad_parity():
    """Every remat policy (incl. the named selective save_attn_mlp_out) is a
    pure memory/recompute trade — loss and grads must match no-remat exactly."""
    import dataclasses

    from deepspeed_tpu.models.gpt import GPTConfig, init_params, loss_fn

    cfg = GPTConfig(vocab_size=128, d_model=64, n_layer=2, n_head=4,
                    max_seq_len=32)
    batch = {"input_ids": np.random.default_rng(0).integers(
        0, 128, (2, 32), np.int32)}
    outs = {}
    for pol in (None, "nothing_saveable", "save_attn_mlp_out",
                "dots_with_no_batch_dims_saveable"):
        c = dataclasses.replace(cfg, remat=pol is not None,
                                remat_policy=pol or "nothing_saveable")
        params = init_params(c, jax.random.PRNGKey(0))
        loss, grads = jax.value_and_grad(
            lambda p: loss_fn(c, p, batch, train=False)[0])(params)
        gsum = float(jax.tree_util.tree_reduce(
            lambda a, b: a + jnp.abs(b).sum(), grads, jnp.float32(0.0)))
        outs[pol] = (float(loss), gsum)
    ref = outs[None]
    for pol, v in outs.items():
        np.testing.assert_allclose(v[0], ref[0], rtol=1e-6, err_msg=str(pol))
        np.testing.assert_allclose(v[1], ref[1], rtol=1e-4, err_msg=str(pol))
