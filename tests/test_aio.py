"""Native AIO library + NVMe optimizer swapping (ZeRO-Infinity path)."""

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.ops.aio import AsyncIOHandle
from deepspeed_tpu.runtime.swap_tensor import NVMeLeafStore


def test_aio_roundtrip(tmp_path, rng):
    h = AsyncIOHandle(num_threads=2)
    data = rng.normal(size=4096).astype(np.float32)
    path = str(tmp_path / "blob.bin")
    rid = h.pwrite(path, data, fsync=True)
    assert h.wait(rid) == 0
    out = np.empty_like(data)
    rid = h.pread(path, out)
    assert h.wait(rid) == 0
    np.testing.assert_array_equal(out, data)
    h.close()


def test_aio_many_concurrent(tmp_path, rng):
    h = AsyncIOHandle(num_threads=4)
    blobs = [rng.normal(size=1024).astype(np.float32) for _ in range(16)]
    rids = [h.pwrite(str(tmp_path / f"b{i}.bin"), b) for i, b in enumerate(blobs)]
    h.drain()
    outs = [np.empty_like(b) for b in blobs]
    rids = [h.pread(str(tmp_path / f"b{i}.bin"), o) for i, o in enumerate(outs)]
    for rid in rids:
        assert h.wait(rid) == 0
    for o, b in zip(outs, blobs):
        np.testing.assert_array_equal(o, b)
    h.close()


def test_aio_read_missing_file_fails(tmp_path):
    h = AsyncIOHandle(num_threads=1)
    buf = np.empty(16, np.float32)
    rid = h.pread(str(tmp_path / "nope.bin"), buf)
    assert h.wait(rid) < 0
    h.close()


def test_leaf_store_roundtrip(tmp_path, rng):
    store = NVMeLeafStore(str(tmp_path / "opt"), aio_threads=2)
    leaves = [rng.normal(size=(8, 4)).astype(np.float32),
              rng.normal(size=(16,)).astype(np.float32)]
    store.write_init(leaves)
    m0, mm0, vv0 = store.get(0)
    np.testing.assert_array_equal(m0, leaves[0])
    np.testing.assert_array_equal(mm0, np.zeros_like(leaves[0]))
    m0 += 1.0
    store.writeback(0, m0, mm0, vv0)
    store.drain()
    m1, _, _ = store.get(1)
    np.testing.assert_array_equal(m1, leaves[1])
    m0b, _, _ = store.get(0)
    np.testing.assert_array_equal(m0b, leaves[0] + 1.0)


@pytest.mark.slow
def test_nvme_offload_training_matches_cpu_offload(tmp_path):
    from deepspeed_tpu.models import build_gpt
    from deepspeed_tpu.models.gpt import GPTConfig

    def make(dev_cfg):
        model, cfg = build_gpt(GPTConfig(
            vocab_size=128, d_model=32, n_layer=2, n_head=2, max_seq_len=32))
        engine, _, _, _ = deepspeed_tpu.initialize(model=model, config={
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 2, "offload_optimizer": dev_cfg},
            "steps_per_print": 0,
        })
        return engine, cfg

    e_nvme, cfg = make({"device": "nvme", "nvme_path": str(tmp_path)})
    e_cpu, _ = make({"device": "cpu"})
    assert e_nvme._offload.store is not None
    r = np.random.default_rng(0)
    for i in range(3):
        b = {"input_ids": r.integers(0, 128, size=(16, 16), dtype=np.int32)}
        m1 = e_nvme.train_batch(b)
        m2 = e_cpu.train_batch(b)
        # identical math, different storage medium
        np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-6)
    # state actually lives on disk
    import os

    files = os.listdir(str(tmp_path / "optimizer"))
    assert any(f.startswith("leaf_0_") for f in files)
