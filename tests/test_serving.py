"""Continuous-batching serving: scheduler mechanics (device-free), the
ServingEngine end-to-end greedy equivalence, shape buckets, compile-event
logging, and the serving dslint rule."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.inference.serving import (ContinuousBatchingScheduler,
                                             PrefixIndex, Request,
                                             RequestState, bucket_for,
                                             default_buckets)
from deepspeed_tpu.models import gpt as G


class FakeExecutor:
    """Deterministic device-free executor: prefill answers last+1, decode
    answers prev+1 (mod 97). Lets the scheduler be tested alone. ``start``
    is only passed by prefix-cache schedulers (borrowed-page admissions)."""

    def __init__(self):
        self.prefills = []
        self.decode_calls = 0

    def prefill(self, slot, tokens, table_row, start=0):
        self.prefills.append((slot, len(tokens), int(start)))
        return (int(tokens[-1]) + 1) % 97

    def decode(self, tokens, tables, lengths, active, steps=1):
        self.decode_calls += 1
        return np.stack([(tokens + k + 1) % 97 for k in range(steps)])


def _sched(ex=None, num_slots=2, num_pages=16, page_size=4,
           pages_per_seq=8, decode_block=1, **kw):
    return ContinuousBatchingScheduler(
        ex or FakeExecutor(), num_slots=num_slots, num_pages=num_pages,
        page_size=page_size, pages_per_seq=pages_per_seq,
        decode_block=decode_block, **kw)


# ---------------------------------------------------------------- scheduler
def test_mixed_stream_admit_evict_finish():
    s = _sched(num_slots=2)
    reqs = [Request(prompt=np.arange(n, dtype=np.int32), max_new_tokens=m)
            for n, m in [(3, 4), (7, 2), (2, 6), (5, 3), (1, 1)]]
    for r in reqs:
        s.submit(r)
    s.run_to_completion()
    assert all(r.state is RequestState.FINISHED for r in reqs)
    assert [len(r.tokens) for r in reqs] == [4, 2, 6, 3, 1]
    # FIFO: earlier submissions never finish after strictly-later ones start
    assert all(r.t_first_token is not None and r.t_done is not None
               for r in reqs)
    assert s.allocator.allocated_pages == 0  # every page returned
    assert s.idle


def test_deterministic_token_stream():
    """The fake decode chain is prev+1: generated tokens must be the exact
    arithmetic continuation regardless of which slot/step served them."""
    s = _sched(num_slots=3)
    r = Request(prompt=np.array([10, 20], np.int32), max_new_tokens=5)
    s.submit(r)
    s.run_to_completion()
    assert r.tokens == [21, 22, 23, 24, 25]


def test_preemption_requeues_and_completes():
    """Pool pressure mid-decode preempts the newest slot; the preempted
    request re-prefills with its kept tokens and still finishes with the
    right continuation."""
    ex = FakeExecutor()
    # 7 usable pages, page_size 2: two long requests cannot both hold their
    # full contexts — growth must preempt
    s = _sched(ex, num_slots=2, num_pages=8, page_size=2, pages_per_seq=8)
    a = Request(prompt=np.array([1, 2, 3], np.int32), max_new_tokens=8)
    b = Request(prompt=np.array([50, 51, 52], np.int32), max_new_tokens=8)
    s.submit(a)
    s.submit(b)
    s.run_to_completion(max_steps=200)
    assert a.tokens == [(4 + i) % 97 for i in range(8)]
    assert b.tokens == [(53 + i) % 97 for i in range(8)]
    assert a.preemptions + b.preemptions >= 1
    # newest-admitted yields first: the OLDER request is never the victim
    # while a younger active slot exists
    assert a.preemptions == 0 and b.preemptions >= 1
    assert s.allocator.allocated_pages == 0


def test_admission_rejects_oversized_request():
    s = _sched(pages_per_seq=2, page_size=4)  # capacity: 8 tokens
    r = Request(prompt=np.zeros(6, np.int32), max_new_tokens=4)
    v = s.submit(r)
    assert not v and v.reason == "unservable" and "exceeds" in v.detail
    assert r.state is RequestState.REJECTED
    assert not s.queue  # never enqueued


def test_admission_rejects_request_larger_than_pool():
    """A request needing more pages than EXIST must be rejected at submit —
    admitted, it would head-of-line-block forever (or self-preempt in an
    infinite recompute loop once it outgrew the pool)."""
    s = _sched(num_pages=3, page_size=4, pages_per_seq=8)  # pool: 2 pages
    v = s.submit(Request(prompt=np.zeros(8, np.int32), max_new_tokens=4))
    assert not v and v.reason == "unservable" and "pool" in v.detail
    # a fitting request still serves
    r = Request(prompt=np.zeros(4, np.int32), max_new_tokens=3)
    assert s.submit(r)
    s.run_to_completion()
    assert len(r.tokens) == 3


def test_eos_finishes_early_and_frees_slot():
    ex = FakeExecutor()
    s = _sched(ex, num_slots=1)
    # prefill returns 1; decode chain 2, 3, ... eos=4 cuts at 4 tokens
    r = Request(prompt=np.zeros(1, np.int32), max_new_tokens=20,
                eos_token_id=4)
    s.submit(r)
    s.run_to_completion()
    assert r.tokens[-1] == 4 and len(r.tokens) == 4
    assert s.allocator.allocated_pages == 0


def test_decode_block_batches_steps_without_changing_tokens():
    ex1, ex4 = FakeExecutor(), FakeExecutor()
    out = []
    for ex, block in ((ex1, 1), (ex4, 4)):
        s = _sched(ex, num_slots=2, decode_block=block)
        reqs = [Request(prompt=np.arange(3, dtype=np.int32),
                        max_new_tokens=9) for _ in range(2)]
        for r in reqs:
            s.submit(r)
        s.run_to_completion()
        out.append([r.tokens for r in reqs])
    assert out[0] == out[1]
    assert ex4.decode_calls < ex1.decode_calls  # blocks actually batched


def test_scheduler_uses_prefill_many_when_available():
    class BatchExec(FakeExecutor):
        def __init__(self):
            super().__init__()
            self.batches = []

        def prefill_many(self, items):
            self.batches.append([slot for slot, _, _ in items])
            return {slot: (int(t[-1]) + 1) % 97 for slot, t, _ in items}

    ex = BatchExec()
    s = _sched(ex, num_slots=3)
    for i in range(3):
        s.submit(Request(prompt=np.array([i], np.int32), max_new_tokens=2))
    s.step()
    assert ex.batches and len(ex.batches[0]) == 3  # one batched admission
    assert not ex.prefills  # serial path unused


# ------------------------------------------------ copy-on-write prefix reuse
PREFIX = (np.arange(8, dtype=np.int32) + 1)  # 2 full pages at page_size=4


def _prefix_reqs(n=3, max_new=4):
    return [Request(prompt=np.concatenate(
        [PREFIX, np.array([40 + i], np.int32)]), max_new_tokens=max_new)
        for i in range(n)]


def test_prefix_sharing_reuses_physical_pages_and_keeps_outputs():
    """Requests sharing a page-aligned prompt prefix must reuse the first
    writer's physical pages (physical < logical, shared counted), pass the
    borrowed-page count to the executor as the scatter start, and produce
    byte-identical outputs to a no-sharing run."""
    a = _prefix_reqs()
    s1 = _sched(num_slots=3, num_pages=32)
    for r in a:
        s1.submit(r)
    s1.run_to_completion()
    assert s1.page_stats["physical"] == s1.page_stats["logical"]

    ex = FakeExecutor()
    s2 = _sched(ex, num_slots=3, num_pages=32,
                prefix_cache=PrefixIndex(4))
    b = _prefix_reqs()
    s2.submit(b[0])
    s2.step()  # first writer admits alone -> its prefix pages register
    for r in b[1:]:
        s2.submit(r)
    s2.run_to_completion()
    assert [r.tokens for r in a] == [r.tokens for r in b]
    # requests 2 and 3 each borrowed the 2 full prefix pages
    assert s2.page_stats["shared"] == 4
    assert s2.page_stats["physical"] < s2.page_stats["logical"]
    # sharers scatter from position 8 (2 borrowed pages x page_size 4)
    assert sorted(st for _, _, st in ex.prefills) == [0, 8, 8]
    rep = s2.audit()
    assert rep["ok"], rep
    assert s2.allocator.allocated_pages == 0  # all refs drained
    assert len(s2.prefix_cache) == 0          # entries died with the pages


def test_prefix_sharing_preemption_keeps_audit_clean_and_outputs():
    """Pool pressure preempting a request that HOLDS shared prefix pages:
    the shared refcounts unwind correctly (audit clean after every step),
    re-admission re-shares, and outputs equal the no-sharing run."""
    def run(prefix_cache):
        reqs = _prefix_reqs(n=2, max_new=16)
        # 8 usable pages vs ~12 of joint peak demand (a 25-token context
        # holds 6): BOTH runs must preempt — in the sharing run the victim
        # is a request holding borrowed prefix pages, exactly the unwind
        # the refcount audit has to survive
        s = _sched(FakeExecutor(), num_slots=2, num_pages=9,
                   prefix_cache=prefix_cache)
        s.submit(reqs[0])
        s.step()
        s.submit(reqs[1])
        for _ in range(200):
            if s.idle:
                break
            s.step()
            rep = s.audit()
            assert rep["ok"], rep
        assert s.idle
        assert all(r.state is RequestState.FINISHED for r in reqs)
        assert s.allocator.allocated_pages == 0
        return [r.tokens for r in reqs], s, reqs

    out_plain, s_plain, r_plain = run(None)
    out_shared, s_shared, r_shared = run(PrefixIndex(4))
    assert out_plain == out_shared
    assert s_shared.page_stats["shared"] > 0
    # both runs hit pool pressure; the sharing run preempted a request
    # that was HOLDING shared prefix pages and still unwound cleanly
    assert sum(r.preemptions for r in r_plain) >= 1
    assert sum(r.preemptions for r in r_shared) >= 1
    # sharing holds fewer physical pages, so pressure preempts no MORE
    assert (sum(r.preemptions for r in r_shared)
            <= sum(r.preemptions for r in r_plain))


def test_prefix_sharing_deadline_evict_frees_borrowed_pages():
    """A deadline-evicted request holding shared prefix pages must drop
    only ITS references: the first writer keeps serving from the same
    physical pages and the audit stays clean."""
    t = {"now": 0.0}
    s = _sched(FakeExecutor(), num_slots=2, num_pages=32,
               prefix_cache=PrefixIndex(4), clock=lambda: t["now"])
    keeper = Request(prompt=np.concatenate([PREFIX, np.array([40], np.int32)]),
                     max_new_tokens=12)
    s.submit(keeper)
    s.step()
    doomed = Request(prompt=np.concatenate([PREFIX, np.array([41], np.int32)]),
                     max_new_tokens=12, deadline_s=0.5)
    s.submit(doomed)
    s.step()  # doomed admits, borrowing the 2 prefix pages
    assert s.page_stats["shared"] == 2
    shared_pages = s.prefix_cache.lookup(PREFIX)
    assert all(s.allocator.refcount(p) == 2 for p in shared_pages)
    t["now"] = 1.0  # past the e2e deadline
    s.step()
    assert doomed.state is RequestState.EXPIRED
    rep = s.audit()
    assert rep["ok"], rep
    # the keeper still holds exactly one reference on the prefix pages
    assert all(s.allocator.refcount(p) == 1 for p in shared_pages)
    s.run_to_completion()
    assert keeper.state is RequestState.FINISHED
    assert len(keeper.tokens) == 12
    assert s.allocator.allocated_pages == 0


def test_prefix_sharing_never_blocks_pool_exhaustion_unwind():
    """When the UNSHARED remainder cannot be allocated, the claimed shared
    references must unwind (no refcount leak) and admission head-of-line
    blocks as before."""
    s = _sched(FakeExecutor(), num_slots=2, num_pages=6,  # 5 usable
               prefix_cache=PrefixIndex(4))
    big = Request(prompt=np.concatenate([PREFIX, np.arange(7, dtype=np.int32)]),
                  max_new_tokens=2)  # 15+1 tokens -> 4 pages
    s.submit(big)
    s.step()  # running, 4 pages held, prefix registered
    second = Request(prompt=np.concatenate([PREFIX,
                                            np.arange(8, dtype=np.int32)]),
                     max_new_tokens=4)  # needs 5 pages, 2 shared + 3 own
    s.submit(second)
    s.step()  # only 1 free page: claim must fail and fully unwind
    rep = s.audit()
    assert rep["ok"], rep
    shared_pages = s.prefix_cache.lookup(PREFIX)
    assert all(s.allocator.refcount(p) == 1 for p in shared_pages)
    s.run_to_completion()
    assert second.state is RequestState.FINISHED
    assert s.allocator.allocated_pages == 0


# ---------------------------------------------------------------- buckets
def test_buckets():
    assert default_buckets(32, 256) == (32, 64, 128, 256)
    assert default_buckets(32, 200) == (32, 64, 128, 256)
    assert bucket_for(1, (32, 64)) == 32
    assert bucket_for(33, (32, 64)) == 64
    with pytest.raises(ValueError, match="exceeds"):
        bucket_for(65, (32, 64))


# ------------------------------------------------------------- end to end
CFG = G.GPTConfig(vocab_size=64, d_model=32, n_layer=2, n_head=4,
                  max_seq_len=128)


@pytest.fixture(scope="module")
def tiny_engine():
    from deepspeed_tpu.inference.serving import ServingConfig, ServingEngine

    params = G.init_params(CFG, jax.random.PRNGKey(0))
    # max_queue armed: the overload-safe configuration every production
    # config should use (and the unbounded-admission rule stays silent on)
    return ServingEngine(CFG, params, ServingConfig(
        num_slots=3, page_size=8, max_model_len=64, prefill_chunk=16,
        dtype="float32", decode_block=4, max_queue=64)), params


@pytest.mark.slow
def test_serving_greedy_matches_generate(tiny_engine):
    """Continuous batching must be invisible in the outputs: every request's
    greedy tokens == InferenceEngine.generate on the same prompt (covers
    paged attention, batched/chunked prefill, decode blocks, admission)."""
    from deepspeed_tpu.inference import (DeepSpeedInferenceConfig,
                                         InferenceEngine)
    from deepspeed_tpu.inference.engine import for_gpt
    from deepspeed_tpu.inference.serving import (make_open_loop_workload,
                                                 run_continuous)

    eng, params = tiny_engine
    wl = make_open_loop_workload(6, rate_rps=1e4, prompt_len=(3, 30),
                                 max_new=(2, 8), vocab_size=64, seed=3)
    # one multi-chunk prompt (> prefill_chunk) for the serial chunked path
    wl.append(Request(prompt=np.arange(20, dtype=np.int32) + 1,
                      max_new_tokens=4))
    rep = run_continuous(eng, wl)
    assert rep["finished"] == len(wl)
    ie = InferenceEngine(for_gpt(CFG, params), DeepSpeedInferenceConfig(
        dtype="float32", max_out_tokens=64))
    for r in wl:
        ref = np.asarray(ie.generate(np.asarray(r.prompt)[None],
                                     max_new_tokens=r.max_new_tokens))
        np.testing.assert_array_equal(ref[0, len(r.prompt):],
                                      np.asarray(r.tokens[:r.max_new_tokens]))


@pytest.mark.slow
def test_warmup_covers_unaligned_final_chunk_buckets():
    """A bucket only reachable through a capped remainder (prefill_chunk + b
    > max_model_len) must still warm — a legal long prompt's final chunk
    must never pay a mid-traffic compile."""
    from deepspeed_tpu.inference.serving import ServingConfig, ServingEngine

    params = G.init_params(CFG, jax.random.PRNGKey(0))
    eng = ServingEngine(CFG, params, ServingConfig(
        num_slots=2, page_size=8, max_model_len=100, prefill_chunk=64,
        dtype="float32", decode_block=2))
    eng.warmup()
    before = len(eng.compile_log)
    # remainder 36 -> bucket 64, whose natural warm length 64+64 > 100
    eng.prefill(0, np.zeros(100, np.int32), np.zeros(13, np.int32))
    assert len(eng.compile_log) == before, eng.compile_log[before:]

    # non-power-of-two prefill_chunk: the top bucket exceeds prefill_chunk,
    # but short prompts still take the fused path — warmup must have
    # compiled it (regression: the warm probe used to overshoot into the
    # chunked path and skip the fused program)
    params = G.init_params(CFG, jax.random.PRNGKey(0))
    eng2 = ServingEngine(CFG, params, ServingConfig(
        num_slots=2, page_size=8, max_model_len=64, prefill_chunk=24,
        dtype="float32", decode_block=2))
    eng2.warmup()
    before = len(eng2.compile_log)
    eng2.prefill(0, np.zeros(20, np.int32), np.zeros(8, np.int32))
    assert len(eng2.compile_log) == before, eng2.compile_log[before:]


def test_serving_compile_log_is_bounded(tiny_engine):
    """After warmup, serving traffic must hit only cached programs."""
    from deepspeed_tpu.inference.serving import (make_open_loop_workload,
                                                 run_continuous)

    eng, _ = tiny_engine
    eng.warmup()
    before = len(eng.compile_log)
    run_continuous(eng, make_open_loop_workload(
        5, 1e4, (3, 30), (2, 8), 64, seed=11))
    assert len(eng.compile_log) == before, eng.compile_log[before:]


# ---------------------------------------------------------------- dslint
def test_unbucketed_decode_rule_fires_and_stays_silent(tiny_engine):
    from deepspeed_tpu.analysis import analyze_compile_log

    broken = [{"kind": "decode", "shape": (1, 5 + i)} for i in range(5)]
    errs = analyze_compile_log(broken).errors()
    assert errs and errs[0].rule_id == "serving/unbucketed-decode-shape"
    # a stride change mid-stream starts a NEW run from that pair: the creep
    # (6,7,8) after the +2 pair (4,6) must fire without a 5th compile
    mixed = [{"kind": "decode", "shape": (1, n)} for n in (4, 6, 7, 8)]
    assert analyze_compile_log(mixed).errors()
    # bucketed shape sets (powers of two) never fire
    ok = [{"kind": "generate", "shape": (2, 4, b)} for b in (8, 16, 32, 64)]
    assert not analyze_compile_log(ok).findings
    # the live serving engine's log is clean
    eng, _ = tiny_engine
    assert not analyze_compile_log(eng).findings


def test_unbounded_admission_rule_fires_and_stays_silent():
    """WARNING on a serving config with no admission bound and no deadlines
    (the overload-unsafe default); silent the moment ANY of the four knobs
    is armed, and silent on non-serving engines / raw compile logs."""
    from deepspeed_tpu.analysis import analyze_compile_log
    from deepspeed_tpu.inference.serving import ServingConfig

    class Eng:  # duck-typed: the rule only reads .serving (+ compile_log)
        compile_log = []

        def __init__(self, cfg):
            self.serving = cfg

    naked = analyze_compile_log(Eng(ServingConfig())).findings
    assert [f.rule_id for f in naked] == ["serving/unbounded-admission"]
    assert naked[0].severity.name == "WARNING"
    for armed in (dict(max_queue=8), dict(max_queued_tokens=4096),
                  dict(ttft_deadline_s=1.0), dict(request_deadline_s=30.0)):
        assert not analyze_compile_log(Eng(ServingConfig(**armed))).findings, \
            armed
    # non-serving contexts: raw log lists never fire
    assert not analyze_compile_log(
        [{"kind": "decode", "shape": (2, 4)}]).findings


def test_dense_kv_at_capacity_rule_fires_and_stays_silent():
    """WARNING when a serving config runs dense KV pages while either the
    weight stacks are quantized or the last run showed pool-capacity
    pressure; silent once kv_bits is set, and silent with no evidence."""
    from deepspeed_tpu.analysis import analyze_compile_log
    from deepspeed_tpu.inference.serving import ServingConfig

    class Sched:
        def __init__(self, **counters):
            self.counters = counters

    class Eng:  # duck-typed: the rule reads .serving/.params/.last_scheduler
        compile_log = []

        def __init__(self, cfg, params=None, sched=None):
            self.serving = cfg
            self.params = params or {"blocks": {"qkv_w": object()}}
            self.last_scheduler = sched

    q_params = {"blocks": {"qkv_w": {"q": 0, "s": 0}}}
    safe = dict(max_queue=8)  # keep unbounded-admission out of the frame

    # fires: quantized weights, dense KV
    f = analyze_compile_log(Eng(ServingConfig(**safe), q_params)).findings
    assert [x.rule_id for x in f] == ["serving/dense-kv-at-capacity"]
    assert f[0].severity.name == "WARNING"
    # fires: pool pressure evidence (preemptions / sheds) on dense KV
    for counters in (dict(preemption=3), dict(request_shed=2)):
        f = analyze_compile_log(
            Eng(ServingConfig(**safe), None, Sched(**counters))).findings
        assert [x.rule_id for x in f] == ["serving/dense-kv-at-capacity"], \
            counters
    # silent: kv_bits armed (either evidence kind present)
    assert not analyze_compile_log(
        Eng(ServingConfig(kv_bits=8, **safe), q_params,
            Sched(preemption=5))).findings
    # silent: dense weights, no pressure
    assert not analyze_compile_log(
        Eng(ServingConfig(**safe), None, Sched())).findings
    # silent: non-serving contexts
    assert not analyze_compile_log(
        [{"kind": "decode", "shape": (2, 4)}]).findings


@pytest.mark.slow
def test_serving_kv8_greedy_matches_generate():
    """int8 KV pages end-to-end through the serving stack: every request's
    greedy tokens == InferenceEngine.generate on DENSE caches (the
    documented per-page quantization tolerance does not flip any argmax on
    this model/seed — the serving A/B's equivalence bar)."""
    from deepspeed_tpu.inference import (DeepSpeedInferenceConfig,
                                         InferenceEngine)
    from deepspeed_tpu.inference.engine import for_gpt
    from deepspeed_tpu.inference.serving import (ServingConfig, ServingEngine,
                                                 make_open_loop_workload,
                                                 run_continuous)

    params = G.init_params(CFG, jax.random.PRNGKey(0))
    eng = ServingEngine(CFG, params, ServingConfig(
        num_slots=3, page_size=8, max_model_len=64, prefill_chunk=16,
        dtype="float32", decode_block=4, max_queue=64, kv_bits=8))
    assert eng.paged_cache["k_pages"].dtype == jnp.int8
    assert eng.kv_bytes_per_token() < 4 * CFG.n_layer * CFG.n_head \
        * CFG.head_dim  # < half the fp32 dense bytes
    wl = make_open_loop_workload(6, rate_rps=1e4, prompt_len=(3, 30),
                                 max_new=(2, 8), vocab_size=64, seed=3)
    rep = run_continuous(eng, wl)
    assert rep["finished"] == len(wl)
    ie = InferenceEngine(for_gpt(CFG, params), DeepSpeedInferenceConfig(
        dtype="float32", max_out_tokens=64))
    for r in wl:
        ref = np.asarray(ie.generate(np.asarray(r.prompt)[None],
                                     max_new_tokens=r.max_new_tokens))
        np.testing.assert_array_equal(
            ref[0, len(r.prompt):], np.asarray(r.tokens[:r.max_new_tokens]))


def test_inference_engine_decode_buckets_and_log():
    from deepspeed_tpu.inference import (DeepSpeedInferenceConfig,
                                         InferenceEngine)
    from deepspeed_tpu.inference.engine import for_gpt

    cfg = G.GPTConfig(vocab_size=64, d_model=32, n_layer=1, n_head=2,
                      max_seq_len=128)
    params = G.init_params(cfg, jax.random.PRNGKey(0))
    eng = InferenceEngine(for_gpt(cfg, params), DeepSpeedInferenceConfig(
        dtype="float32", max_out_tokens=64, decode_buckets=[8, 16]))
    ids = np.zeros((2, 4), np.int32)
    o5 = eng.generate(ids, max_new_tokens=5)
    o7 = eng.generate(ids, max_new_tokens=7)  # same bucket: cache hit
    assert o5.shape == (2, 9) and o7.shape == (2, 11)
    assert len(eng.compile_log) == 1
    np.testing.assert_array_equal(o5, o7[:, :9])  # greedy prefix stable
    events = []

    class Sink:
        def write_events(self, evs):
            events.extend(evs)

    eng.set_monitor(Sink())
    eng.generate(ids, max_new_tokens=12)  # bucket 16: new compile, logged
    assert len(eng.compile_log) == 2
    assert events and events[0][0] == "Inference/compile_events"


def test_serving_admission_limit_plumbing(monkeypatch):
    from deepspeed_tpu.runtime import aot

    seen = {}

    def fake_ladder(model, lo=1, hi=64, **kw):
        seen.update(kw)
        return {"model": model, "max_batch": 12,
                "trace": [{"batch": 1, "fits": True}],
                "report": {"fit": {"confidence": "fits"}}}

    monkeypatch.setattr(aot, "find_max_decode_batch", fake_ladder)
    lim = aot.serving_admission_limit("gpt2-350m", safety_margin=0.75)
    assert lim["max_slots"] == 9
    assert lim["max_decode_batch"] == 12
    assert lim["fit"] == {"confidence": "fits"}
    assert lim["kv_bits"] == 0
    # kv_bits + page_size flow through to the compiled probe, so "auto"
    # slots are sized from QUANTIZED pool bytes, not dense pages
    lim = aot.serving_admission_limit("gpt2-350m", kv_bits=8, page_size=32)
    assert seen["kv_bits"] == 8 and seen["page_size"] == 32
    assert lim["kv_bits"] == 8


def test_num_slots_auto_uses_quantized_ladder(monkeypatch):
    """ServingConfig(num_slots='auto', kv_bits=8) must resolve through the
    kv-aware fit ladder (the dense ladder under-admits ~2x at int8)."""
    from deepspeed_tpu.inference.serving import ServingConfig, ServingEngine
    from deepspeed_tpu.runtime import aot

    seen = {}

    def fake_limit(model, **kw):
        seen.update(kw, model=model)
        return {"max_slots": 2, "max_decode_batch": 2, "fit": None,
                "kv_bits": kw.get("kv_bits", 0), "trace": []}

    monkeypatch.setattr(aot, "serving_admission_limit", fake_limit)
    params = G.init_params(CFG, jax.random.PRNGKey(0))
    eng = ServingEngine(CFG, params, ServingConfig(
        num_slots="auto", model_name="tiny", page_size=8, max_model_len=64,
        prefill_chunk=16, dtype="float32", max_queue=8, kv_bits=8))
    assert eng.num_slots == 2
    assert seen["kv_bits"] == 8 and seen["page_size"] == 8
    assert seen["model"] == "tiny"