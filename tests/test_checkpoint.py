"""Checkpoint round-trip tests. Parity model: tests/unit/checkpoint/ in the
reference — bitwise state match after save/load, topology-change reload."""

import numpy as np

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.models import GPTConfig, build_gpt
import pytest

TINY = GPTConfig(vocab_size=256, n_layer=2, n_head=4, d_model=64, max_seq_len=64)


def make_engine(stage, tmp_seed=0, mesh=None):
    model, _ = build_gpt(TINY)
    cfg = {
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": stage},
        "steps_per_print": 0,
    }
    if mesh:
        cfg["mesh"] = mesh
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=cfg)
    return engine


def batch(seed=0, n=16):
    rng = np.random.default_rng(seed)
    return {"input_ids": rng.integers(0, 256, size=(n, 32), dtype=np.int32)}


def tree_equal(a, b):
    for x, y in zip(jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


@pytest.mark.slow
def test_roundtrip_bitwise(tmp_path, devices):
    e = make_engine(stage=2)
    for i in range(3):
        e.train_batch(batch(i))
    e.save_checkpoint(str(tmp_path), client_state={"note": "hi"})

    e2 = make_engine(stage=2)
    path, client = e2.load_checkpoint(str(tmp_path))
    assert path is not None and client == {"note": "hi"}
    tree_equal(e.state["params"], e2.state["params"])
    tree_equal(e.state["opt"], e2.state["opt"])
    assert int(e2.state["step"]) == 3

    # training continues identically from the restore point
    m1 = e.train_batch(batch(99))
    m2 = e2.train_batch(batch(99))
    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-6)


@pytest.mark.slow
def test_topology_free_reload(tmp_path, devices):
    """A checkpoint from a stage-3 sharded engine loads into a stage-0 engine
    (the reference needs the universal-checkpoint converter for this)."""
    e3 = make_engine(stage=3)
    e3.train_batch(batch(0))
    e3.save_checkpoint(str(tmp_path))

    e0 = make_engine(stage=0)
    e0.load_checkpoint(str(tmp_path))
    tree_equal(e3.state["params"], e0.state["params"])
    # and into a tp=2 mesh
    etp = make_engine(stage=0, mesh={"tp": 2})
    etp.load_checkpoint(str(tmp_path))
    tree_equal(e3.state["params"], etp.state["params"])


def test_latest_tag_and_missing(tmp_path, devices):
    e = make_engine(stage=1)
    e.train_batch(batch(0))
    e.save_checkpoint(str(tmp_path), tag="my_tag")
    assert (tmp_path / "latest").read_text() == "my_tag"
    path, _ = e.load_checkpoint(str(tmp_path))
    assert path.endswith("my_tag")
    path, client = e.load_checkpoint(str(tmp_path / "nonexistent"))
    assert path is None


@pytest.mark.slow
def test_mid_accumulation_roundtrip(tmp_path, devices):
    """Saving between forward() calls must preserve accumulated grads (review
    finding): resumed training matches uninterrupted training exactly."""
    model, _ = build_gpt(TINY)
    mk = lambda: deepspeed_tpu.initialize(model=model, config={
        "train_micro_batch_size_per_gpu": 2, "gradient_accumulation_steps": 2,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "steps_per_print": 0})[0]
    b0, b1 = batch(0), batch(1)

    e_ref = mk()
    l = e_ref.forward(b0); e_ref.backward(l); e_ref.step()
    l = e_ref.forward(b1); e_ref.backward(l); e_ref.step()
    assert int(e_ref.state["step"]) == 1

    e_a = mk()
    l = e_a.forward(b0); e_a.backward(l); e_a.step()
    e_a.save_checkpoint(str(tmp_path))  # micro=1: mid-accumulation
    e_b = mk()
    e_b.load_checkpoint(str(tmp_path))
    l = e_b.forward(b1); e_b.backward(l); e_b.step()
    assert int(e_b.state["step"]) == 1
    tree_equal(e_ref.state["params"], e_b.state["params"])


def test_checkpoint_embeds_standalone_recovery_script(tmp_path):
    """Every checkpoint carries zero_to_fp32.py (parity: the reference's
    auto-copy) and the copy runs standalone against its own directory."""
    import os
    import subprocess
    import sys

    import deepspeed_tpu as ds
    from deepspeed_tpu.models import build_gpt
    from deepspeed_tpu.models.gpt import GPTConfig

    model, _ = build_gpt(GPTConfig(vocab_size=64, d_model=32, n_layer=1,
                                   n_head=2, max_seq_len=16))
    engine, _, _, _ = ds.initialize(model=model, config={
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "mesh": {"dp": 8}, "steps_per_print": 0})
    engine.train_batch({"input_ids": np.zeros((8, 16), np.int32)})
    ckpt = engine.save_checkpoint(str(tmp_path))
    script = os.path.join(ckpt, "zero_to_fp32.py")
    assert os.path.exists(script)
    out = str(tmp_path / "fp32.npz")
    p = subprocess.run([sys.executable, script, str(tmp_path), out],
                       capture_output=True, text=True)
    assert p.returncode == 0, p.stderr[-400:]
    assert len(np.load(out).files) > 0
