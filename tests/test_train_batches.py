"""engine.train_batches: K complete optimizer steps in one compiled program.

Must be bit-equivalent in trajectory to K sequential train_batch calls (same
per-step batches and rng stream), advance counters/schedulers identically, and
refuse the host-runner paths.
"""

import numpy as np
import pytest

import jax

import deepspeed_tpu
from deepspeed_tpu.models import build_gpt
from deepspeed_tpu.models.gpt import GPTConfig

TINY = GPTConfig(vocab_size=64, n_layer=2, n_head=2, d_model=32, d_ff=64,
                 max_seq_len=32, rotary=False)


def _engine(gas=1, stage=1, **extra):
    model, _ = build_gpt(TINY)
    cfg = {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": stage},
        "gradient_clipping": 1.0,
        "scheduler": {"type": "WarmupLR",
                      "params": {"warmup_num_steps": 10}},
    }
    cfg.update(extra)
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=cfg)
    return engine


def _batches(k, gas, seq=16, seed=0):
    bs = 2 * jax.device_count()  # micro_bs_per_gpu x dp extent
    rng = np.random.default_rng(seed)
    shape = (k, gas, bs, seq) if gas > 1 else (k, bs, seq)
    return rng.integers(0, TINY.vocab_size, size=shape, dtype=np.int32)


@pytest.mark.parametrize("gas", [1, 2])
@pytest.mark.slow
def test_matches_sequential_train_batch(gas):
    k = 3
    ids = _batches(k, gas)
    e1, e2 = _engine(gas=gas), _engine(gas=gas)
    # identical rng streams: both engines start from the same seed config
    e1._rng = jax.random.PRNGKey(7)
    e2._rng = jax.random.PRNGKey(7)
    seq_metrics = [e1.train_batch({"input_ids": ids[i]}) for i in range(k)]
    multi = e2.train_batches({"input_ids": ids})
    np.testing.assert_allclose(float(multi["loss"]),
                               float(seq_metrics[-1]["loss"]),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(float(multi["grad_norm"]),
                               float(seq_metrics[-1]["grad_norm"]),
                               rtol=2e-4, atol=2e-5)
    expect_mean = np.mean([float(m["loss"]) for m in seq_metrics])
    np.testing.assert_allclose(multi["mean_loss"], expect_mean,
                               rtol=2e-5, atol=2e-5)
    # trajectory equivalence: the parameters themselves match
    p1 = jax.tree_util.tree_leaves(e1.state["params"])
    p2 = jax.tree_util.tree_leaves(e2.state["params"])
    for a, b in zip(p1, p2):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=1e-2, atol=1e-3)


@pytest.mark.slow
def test_counters_and_lr_advance_per_step():
    k = 4
    e = _engine()
    m = e.train_batches({"input_ids": _batches(k, 1)})
    assert e.global_steps == k
    assert e.micro_steps == k
    # WarmupLR: lr after 4 steps must equal the schedule's step-4 value, i.e.
    # the in-program counter advanced per scan iteration, not per dispatch
    e_seq = _engine()
    for i in range(k):
        m_seq = e_seq.train_batch({"input_ids": _batches(k, 1)[i]})
    np.testing.assert_allclose(float(m["lr"]), float(m_seq["lr"]),
                               rtol=1e-6)


def test_refuses_host_runner_paths():
    e = _engine(zero_optimization={"stage": 1,
                                   "offload_optimizer": {"device": "cpu"}})
    with pytest.raises(ValueError, match="train_batch"):
        e.train_batches({"input_ids": _batches(2, 1)})
