"""Autotuning experiment scheduler (parity: reference autotuning/scheduler.py
ResourceManager — VERDICT r3 missing #3): queued jobs over a host pool with
the file-based exp.json/metrics.json contract, plus the shape-only model-info
profile."""

import json
import os
import sys

import pytest

from deepspeed_tpu.autotuning import (Node, ResourceManager,
                                      profile_model_info)
from deepspeed_tpu.models import build_gpt
from deepspeed_tpu.models.gpt import GPTConfig


@pytest.mark.slow
def test_scheduler_runs_real_experiments(tmp_path):
    """Two tiny real trials through the actual run_exp job entry, scheduled
    on the local node; metrics parsed, best selected."""
    rm = ResourceManager(results_dir=str(tmp_path), timeout=600,
                         env={**os.environ})
    base = {
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "model_spec": {"preset": "tiny", "seq": 64, "steps": 2},
    }
    rm.schedule_experiments([
        {**base, "zero_optimization": {"stage": 0}},
        {**base, "zero_optimization": {"stage": 1}},
    ], names=["stage0", "stage1"])
    finished = rm.run(poll_s=0.5)
    assert len(finished) == 2
    oks = [e for e in finished if e.ok]
    assert oks, [e.error for e in finished]
    best = rm.best()
    assert best is not None and best.metric_value > 0
    # the job contract: exp.json in, metrics.json out
    m = json.load(open(os.path.join(best.exp_dir, "metrics.json")))
    assert m["metric_value"] == best.metric_value


def test_scheduler_records_failures_without_dying(tmp_path):
    rm = ResourceManager(results_dir=str(tmp_path), timeout=120)
    rm.schedule_experiments([
        {"train_micro_batch_size_per_gpu": 2,
         "optimizer": {"type": "NoSuchOpt", "params": {}},
         "model_spec": {"preset": "tiny", "seq": 32, "steps": 1}},
    ], names=["bad"])
    finished = rm.run(poll_s=0.5)
    assert len(finished) == 1
    assert not finished[0].ok and finished[0].error
    assert rm.best() is None


def test_node_pool_and_ssh_command(tmp_path):
    rm = ResourceManager(hosts=["worker-1", "localhost"],
                         results_dir=str(tmp_path))
    assert [n.is_local for n in rm.nodes] == [False, True]
    rm.schedule_experiments([{"x": 1}], names=["e0"])
    exp = rm.queue[0]
    cmd = rm._command(exp, rm.nodes[0])
    assert cmd[0] == "ssh" and "worker-1" in cmd
    assert "run_exp" in cmd[-1]
    local = rm._command(exp, rm.nodes[1])
    assert local[0] == sys.executable and local[-1].endswith("exp.json")


def test_profile_model_info_shapes_only():
    model, cfg = build_gpt(GPTConfig(
        vocab_size=128, d_model=64, n_layer=4, n_head=4, max_seq_len=64))
    info = profile_model_info(model, [1, 4], seq_len=64,
                              vocab_size=cfg.vocab_size)
    expect = cfg.num_params()
    assert info["num_params"] == expect
    assert info["optimizer_state_bytes_fp32"] == expect * 12
    acts = info["activation_bytes_per_micro_batch"]
    assert acts[4] == 4 * acts[1] > 0
