"""Quantized ZeRO collectives (comm/quantized.py).

Discipline mirrors test_onebit.py: (a) the wire format round-trips within its
analytic error bound, (b) each quantized collective matches its full-precision
counterpart within the bound on a real CPU mesh, (c) error feedback keeps the
cumulative drift bounded over repeated steps, and (d) the engine-level knobs
(zero_quantized_weights / zero_quantized_gradients) produce working training
with the advertised >= 3.5x wire-byte reduction in the accounting ledger.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import deepspeed_tpu
from deepspeed_tpu.comm.quantized import (
    dequantize_blockwise,
    effective_block,
    qall_gather,
    qall_to_all,
    qreduce_scatter,
    quantization_shrinks,
    quantize_blockwise,
    quantized_reshard,
    wire_bytes_per_element,
)
from deepspeed_tpu.comm.runtime_accounting import wire_ledger
from deepspeed_tpu.utils.jax_compat import shard_map

W = 8  # conftest forces an 8-device CPU mesh


@pytest.fixture()
def mesh(devices):
    return Mesh(np.asarray(devices), ("dp",))


# --------------------------------------------------------------------- primitives
@pytest.mark.parametrize("bits", [8, 4])
def test_roundtrip_error_bound(rng, bits):
    """Per-block affine round-trip error is at most half a quantization step:
    (max - min) / (2^bits - 1) / 2 per block."""
    x = jnp.asarray(rng.normal(size=(3, 512)), jnp.float32)
    q, s, z = quantize_blockwise(x, bits=bits, block_size=128)
    xh = dequantize_blockwise(q, s, z, bits=bits, block_size=128, orig_size=512)
    err = np.abs(np.asarray(xh) - np.asarray(x))
    # bound per block, broadcast back over block elements
    step = np.asarray(s)  # scale == (max-min)/levels
    bound = np.repeat(step * 0.5 + 1e-7, 128, axis=-1).reshape(err.shape)
    assert (err <= bound).all()


def test_int4_packs_two_per_byte(rng):
    x = jnp.asarray(rng.normal(size=(256,)), jnp.float32)
    q8, _, _ = quantize_blockwise(x, bits=8, block_size=64)
    q4, _, _ = quantize_blockwise(x, bits=4, block_size=64)
    assert q8.shape == (256,) and q4.shape == (128,)
    assert q8.dtype == jnp.uint8 and q4.dtype == jnp.uint8


def test_stochastic_rounding_unbiased(rng):
    x = jnp.asarray(rng.normal(size=(256,)), jnp.float32)
    outs = []
    for i in range(100):
        q, s, z = quantize_blockwise(x, bits=8, block_size=64, stochastic=True,
                                     rng=jax.random.PRNGKey(i))
        outs.append(np.asarray(dequantize_blockwise(
            q, s, z, bits=8, block_size=64, orig_size=256)))
    bias = np.abs(np.mean(outs, axis=0) - np.asarray(x)).max()
    step = float(np.asarray(s).max())
    assert bias < step  # |E[x_hat] - x| << one quantization step


def test_effective_block_adapts_to_short_rows(rng):
    """A [.., 32] leaf must not pad to 256-blocks (that would INFLATE the
    wire 8x); the effective block shrinks to the row and the shrink predicate
    reports when quantization stops paying."""
    assert effective_block(32, 256) == 32
    assert effective_block(1024, 256) == 256
    assert effective_block(7, 256) == 8
    x = jnp.asarray(rng.normal(size=(16, 32)), jnp.float32)
    q, s, z = quantize_blockwise(x, bits=8, block_size=256)
    assert q.shape == (16, 32) and s.shape == (16, 1)  # one block per row
    assert quantization_shrinks(32, 8, 256, 4)       # fp32: 4 -> 1.25 B/elt
    assert not quantization_shrinks(2, 8, 256, 2)    # bf16 pairs: 2 -> 5 B/elt
    # ratio helper consistency: fp32/int8 at block 256 is the advertised 3.88x
    assert 4 / wire_bytes_per_element(8, 256) == pytest.approx(3.879, abs=1e-2)


# --------------------------------------------------------------------- collectives
def test_qall_gather_matches_all_gather(rng, mesh):
    xs = jnp.asarray(rng.normal(size=(W, 1024)), jnp.float32)

    def body(x):
        return qall_gather(x[0], "dp", axis=0, tiled=True)[None]

    out = jax.jit(shard_map(body, mesh=mesh, in_specs=P("dp", None),
                            out_specs=P("dp", None)))(xs)
    ref = np.asarray(xs).reshape(-1)
    got = np.asarray(out)[0]
    # int8 per-block error: half a step of the worst block
    assert np.abs(got - ref).max() < 0.05
    # every rank sees the same gathered vector
    full = jax.jit(shard_map(lambda x: qall_gather(x[0], "dp")[None],
                             mesh=mesh, in_specs=P("dp", None),
                             out_specs=P("dp", None)))(xs)
    assert np.asarray(full).shape == (W, W * 1024)  # each rank: full vector


@pytest.mark.parametrize("mean", [False, True])
def test_qreduce_scatter_matches_reduce_scatter(rng, mesh, mean):
    xs = jnp.asarray(rng.normal(size=(W, 1024)), jnp.float32)
    ref = np.asarray(xs).sum(0)
    if mean:
        ref = ref / W
    ref = ref.reshape(W, -1)  # rank i holds chunk i

    def body(x):
        return qreduce_scatter(x[0], "dp", axis=0, mean=mean)[None]

    out = jax.jit(shard_map(body, mesh=mesh, in_specs=P("dp", None),
                            out_specs=P("dp", None)))(xs)
    got = np.asarray(out).reshape(W, -1)
    rel = np.abs(got - ref).max() / (np.abs(ref).max() + 1e-9)
    assert rel < 0.02, rel  # documented int8 tolerance (COMM_COMPRESSION.md)


def test_qreduce_scatter_error_feedback_converges(rng, mesh):
    """Repeated quantized reduction of the SAME vector with the residual
    carried: the time-average converges to the true reduction (error feedback
    keeps the drift bounded instead of letting bias accumulate). int4 to make
    the single-shot error visibly large."""
    xs = jnp.asarray(rng.normal(size=(W, 1024)), jnp.float32)
    ref = np.asarray(xs).sum(0).reshape(W, -1)

    def body(x, r):
        o, nr = qreduce_scatter(x[0], "dp", axis=0, residual=r[0],
                                bits=4, block_size=64)
        return o[None], nr[None]

    f = jax.jit(shard_map(body, mesh=mesh,
                          in_specs=(P("dp", None), P("dp", None)),
                          out_specs=(P("dp", None), P("dp", None))))
    resid = jnp.zeros((W, 1024), jnp.float32)
    acc = np.zeros_like(ref)
    errs = []
    for t in range(1, 16):
        o, resid = f(xs, resid)
        acc += np.asarray(o).reshape(W, -1)
        errs.append(np.abs(acc / t - ref).max())
    assert errs[-1] < errs[0] / 3, errs  # time-average error shrinks
    # residual stays bounded (no blow-up)
    assert np.abs(np.asarray(resid)).max() < 10 * float(np.abs(xs).max())


def test_qall_to_all_matches_all_to_all(rng, mesh):
    xs = jnp.asarray(rng.normal(size=(64, 16, 256)), jnp.float32)

    def bodyq(x):
        return qall_to_all(x, "dp", split_axis=0, concat_axis=1)

    def bodyr(x):
        return jax.lax.all_to_all(x, "dp", split_axis=0, concat_axis=1,
                                  tiled=True)

    spec = P("dp", None, None)
    got = jax.jit(shard_map(bodyq, mesh=mesh, in_specs=spec, out_specs=spec))(xs)
    ref = jax.jit(shard_map(bodyr, mesh=mesh, in_specs=spec, out_specs=spec))(xs)
    assert got.shape == ref.shape
    assert np.abs(np.asarray(got) - np.asarray(ref)).max() < 0.05


def test_quantized_reshard_value_and_straight_through_grad(rng, mesh):
    y = jnp.asarray(rng.normal(size=(64, 512)), jnp.float32)
    with mesh:
        val = jax.jit(lambda v: quantized_reshard(v, P(None, None)))(y)
        g = jax.jit(jax.grad(
            lambda v: quantized_reshard(v, P(None, None)).sum()))(y)
    assert np.abs(np.asarray(val) - np.asarray(y)).max() < 0.05
    np.testing.assert_array_equal(np.asarray(g), np.ones_like(y))  # STE
    # dp-sharded input -> replicated output: the actual ZeRO-3 gather shape
    y_sh = jax.device_put(y, NamedSharding(mesh, P("dp", None)))
    with mesh:
        gathered = jax.jit(lambda v: quantized_reshard(v, P(None, None)))(y_sh)
    assert np.abs(np.asarray(gathered) - np.asarray(y)).max() < 0.05


# --------------------------------------------------------------------- config knobs
def test_zero_config_knobs_parse_and_validate():
    from deepspeed_tpu.runtime.config import DeepSpeedConfig
    from deepspeed_tpu.runtime.zero.config import DeepSpeedZeroConfig

    z = DeepSpeedZeroConfig(stage=3, zero_quantized_weights=True,
                            zero_quantize_bits=4,
                            zero_quantize_block_size=128)
    assert z.quantized_comm_enabled and z.zero_quantize_bits == 4
    with pytest.raises(Exception):
        DeepSpeedZeroConfig(zero_quantize_bits=5)
    with pytest.raises(Exception):
        DeepSpeedZeroConfig(zero_quantize_block_size=33)
    # prescale_gradients fights block quantization: refused
    with pytest.raises(ValueError):
        DeepSpeedConfig.load({
            "train_micro_batch_size_per_gpu": 1,
            "prescale_gradients": True,
            "zero_optimization": {"stage": 2,
                                  "zero_quantized_gradients": True},
        }, world_size=8)
    # a DeepSpeed-style JSON block parses unchanged
    cfg = DeepSpeedConfig.load({
        "train_micro_batch_size_per_gpu": 1,
        "zero_optimization": {"stage": 3, "zero_quantized_weights": True,
                              "zero_quantized_gradients": True},
    }, world_size=8)
    assert cfg.zero_optimization.zero_quantized_weights


# --------------------------------------------------------------------- engine paths
def _tiny_engine(zero_cfg, gas=1, d_model=256):
    from deepspeed_tpu.models import build_gpt, gpt

    model, _ = build_gpt(gpt.GPTConfig(
        vocab_size=64, n_layer=4, n_head=2, d_model=d_model, max_seq_len=32))
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model,
        config={
            "train_micro_batch_size_per_gpu": 2,
            "gradient_accumulation_steps": gas,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "zero_optimization": zero_cfg,
            "steps_per_print": 0,
        })
    return engine


def _batch(rng, gas=1):
    shape = (16, 32) if gas == 1 else (gas, 16, 32)
    return {"input_ids": rng.integers(0, 64, size=shape, dtype=np.int32)}


@pytest.mark.slow
def test_zero3_quantized_weights_trains_with_ratio(rng, devices):
    """The acceptance row: ZeRO-3 with zero_quantized_weights matches the
    full-precision step loss within int8 tolerance and the accounting ledger
    reports >= 3.5x wire-byte reduction on the parameter gathers."""
    dense = _tiny_engine({"stage": 3})
    b = _batch(rng)
    l_dense = float(dense.train_batch(b)["loss"])

    wire_ledger.reset()
    q = _tiny_engine({"stage": 3, "zero_quantized_weights": True})
    l_q = float(q.train_batch(b)["loss"])
    assert np.isfinite(l_q)
    assert abs(l_q - l_dense) / abs(l_dense) < 1e-2  # int8 weight-gather noise
    ratio = wire_ledger.ratio("qgather[zero3]")
    assert ratio >= 3.5, wire_ledger.summary_dict()
    # a few more steps actually train
    for _ in range(3):
        m = q.train_batch(_batch(rng))
    assert np.isfinite(float(m["loss"]))


@pytest.mark.slow
def test_quantized_gradients_match_dense_first_step(rng, devices):
    """zero_quantized_gradients replaces the fp psum with the int8 RS+AG
    exchange; the forward is untouched, so the first step's loss must match
    the dense engine's exactly-ish, and the exchange must show in the ledger."""
    dense = _tiny_engine({"stage": 2})
    b = _batch(rng)
    l_dense = float(dense.train_batch(b)["loss"])

    wire_ledger.reset()
    q = _tiny_engine({"stage": 2, "zero_quantized_gradients": True})
    l_q = float(q.train_batch(b)["loss"])
    assert abs(l_q - l_dense) < 1e-4, (l_q, l_dense)
    assert wire_ledger.ratio("qgrad_reduce_scatter") >= 3.5
    assert wire_ledger.ratio("qgrad_all_gather") >= 3.5
    # grad norms stay in the same ballpark (quantized exchange, not garbage)
    gn_d = dense.get_global_grad_norm()
    gn_q = q.get_global_grad_norm()
    assert abs(gn_q - gn_d) / (gn_d + 1e-9) < 0.1, (gn_q, gn_d)


@pytest.mark.slow
def test_quantized_gradients_error_feedback_residual(rng, devices):
    """Error feedback: the persistent residual exists, is updated, and loss
    keeps decreasing over repeated steps (the EF convergence property at the
    engine level, with gas=2 exercising the residual through the scan)."""
    e = _tiny_engine({"stage": 2, "zero_quantized_gradients": True,
                      "zero_quantize_error_feedback": True,
                      "zero_quantize_stochastic": True}, gas=2)
    assert "qgrad_residual" in e.state
    losses = []
    for _ in range(6):
        losses.append(float(e.train_batch(_batch(rng, gas=2))["loss"]))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]  # training converges through the int wire
    resid = np.asarray(e.state["qgrad_residual"])
    assert np.abs(resid).max() > 0  # residual is live, not dead state


def test_qall_gather_untiled_respects_axis(rng, mesh):
    """tiled=False must place the new world dim at ``axis`` exactly like
    lax.all_gather (drop-in parity), not always at the front."""
    xs = jnp.asarray(rng.normal(size=(W, 4, 256)), jnp.float32)

    def bodyq(x):
        return qall_gather(x[0], "dp", axis=1, tiled=False)[None]

    def bodyr(x):
        return jax.lax.all_gather(x[0], "dp", axis=1, tiled=False)[None]

    spec = P("dp", None, None)
    ospec = P("dp", None, None, None)
    got = jax.jit(shard_map(bodyq, mesh=mesh, in_specs=spec,
                            out_specs=ospec))(xs)
    ref = jax.jit(shard_map(bodyr, mesh=mesh, in_specs=spec,
                            out_specs=ospec))(xs)
    assert got.shape == ref.shape == (W, 4, W, 256)
    assert np.abs(np.asarray(got) - np.asarray(ref)).max() < 0.05


def test_overflow_resets_error_feedback_residual(rng, devices):
    """A non-finite residual (the state an fp16 overflow leaves behind) must
    be dropped at the skipped boundary, not carried forward — one bad step
    must not poison the rest of training."""
    from deepspeed_tpu.models import build_gpt, gpt

    model, _ = build_gpt(gpt.GPTConfig(
        vocab_size=64, n_layer=2, n_head=2, d_model=64, max_seq_len=32))
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model,
        config={
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "fp16": {"enabled": True, "loss_scale": 0.0},
            "zero_optimization": {"stage": 2,
                                  "zero_quantized_gradients": True,
                                  "zero_quantize_error_feedback": True},
            "steps_per_print": 0,
        })
    # poison the residual the way an overflow micro-step would
    bad = jnp.full_like(engine.state["qgrad_residual"], jnp.nan)
    engine.state["qgrad_residual"] = jax.device_put(
        bad, engine.state["qgrad_residual"].sharding)
    m1 = engine.train_batch(_batch(rng))
    assert bool(m1["overflow"])  # NaN grads detected, update skipped
    resid = np.asarray(engine.state["qgrad_residual"])
    assert np.isfinite(resid).all()  # residual dropped with the step
    m2 = engine.train_batch(_batch(rng))  # next step recovers
    assert not bool(m2["overflow"]) and np.isfinite(float(m2["loss"]))


def test_gathered_parameters_quantized_host_fetch(rng, devices):
    e = _tiny_engine({"stage": 3, "zero_quantized_weights": True})
    from deepspeed_tpu.runtime.zero.partitioned_params import GatheredParameters

    wire_ledger.reset()
    with GatheredParameters(e, paths=["blocks"], quantized=True) as full:
        key = next(k for k in full if k.endswith("qkv_w") or "w" in k)
        fetched = full[key]
    assert wire_ledger.ratio("qgather[host]") >= 3.5
    ref = np.array(jax.device_get(e.state["params"]["blocks"][key.split(".")[-1]]))
    rel = np.abs(fetched - ref).max() / (np.abs(ref).max() + 1e-9)
    assert rel < 0.02
    with pytest.raises(ValueError):
        GatheredParameters(e, modify=True, quantized=True)


def test_comms_logger_reports_wire_ratio():
    from deepspeed_tpu.comm import comm as c

    logger = c.CommsLogger(enabled=True)
    logger.record("qall_gather[dp]", 4096, wire_bytes=1056)
    logger.record("all_reduce[dp]", 4096)
    out = logger.log_summary()
    assert "wire=1056" in out and "3.88x" in out
    assert "all_reduce" in out
