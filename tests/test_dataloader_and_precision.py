"""Dataloader determinism/sharding + precision edge cases from review findings."""

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.runtime.config import DeepSpeedConfig
from deepspeed_tpu.runtime.dataloader import DeepSpeedDataLoader, RepeatingLoader
from deepspeed_tpu.runtime.precision import PrecisionConfig


def test_dataloader_rank_sharding():
    data = [{"x": np.array([i])} for i in range(32)]
    seen = []
    for rank in range(4):
        dl = DeepSpeedDataLoader(data, batch_size=2, shuffle=True, seed=7,
                                 num_replicas=4, rank=rank)
        for b in dl:
            seen.extend(b["x"].ravel().tolist())
    assert sorted(seen) == list(range(32))  # disjoint cover


def test_dataloader_deterministic():
    data = [np.array([i]) for i in range(16)]
    a = [b.tolist() for b in DeepSpeedDataLoader(data, 4, seed=3, num_replicas=1, rank=0)]
    b = [b.tolist() for b in DeepSpeedDataLoader(data, 4, seed=3, num_replicas=1, rank=0)]
    assert a == b


def test_repeating_loader():
    data = [np.array([i]) for i in range(8)]
    dl = RepeatingLoader(DeepSpeedDataLoader(data, 4, shuffle=False, num_replicas=1, rank=0))
    got = [next(dl) for _ in range(5)]  # 2 batches/epoch -> wraps twice
    assert len(got) == 5


def test_fp16_static_scale_still_scales():
    """Review finding: static loss_scale must still scale + overflow-skip."""
    cfg = DeepSpeedConfig.load(
        {"train_batch_size": 8, "fp16": {"enabled": True, "loss_scale": 4096}},
        world_size=8)
    pc = PrecisionConfig.from_ds_config(cfg)
    assert pc.loss_scaling is True
    assert pc.static_scale == 4096


def test_gas_only_config_respected():
    """Review finding: gradient_accumulation_steps alone must be honored."""
    c = DeepSpeedConfig.load({"gradient_accumulation_steps": 8}, world_size=4)
    assert c.gradient_accumulation_steps == 8
    assert c.train_micro_batch_size_per_gpu == 1
    assert c.train_batch_size == 32


def test_batch_triangle_uses_dp_extent():
    """Review finding: with tp=2 on 8 devices, dp extent is 4."""
    c = DeepSpeedConfig.load(
        {"train_batch_size": 32, "train_micro_batch_size_per_gpu": 4,
         "mesh": {"tp": 2}}, world_size=8)
    assert c.gradient_accumulation_steps == 2  # 32 = 4 * 2 * 4
