"""Two-process jax.distributed worker: real multi-host engine paths on CPU.

Each process owns 2 virtual CPU devices; jax.distributed glues them into one
4-device platform. Exercises the branches a single-process suite never runs:
``comm.init_distributed`` with a live coordinator, cross-process batch
placement, the checkpoint tag-validation barrier, process-0-writes save, and
multi-host load (VERDICT r2 'next' #8)."""

import argparse
import json
import os
import sys

# distinguished from crash codes: "the CPU backend cannot run cross-process
# programs at all" — the driver skips with that exact reason
BACKEND_UNSUPPORTED_EXIT = 76


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--coordinator", required=True)
    p.add_argument("--process-id", type=int, required=True)
    p.add_argument("--num-processes", type=int, default=2)
    p.add_argument("--ckpt-dir", required=True)
    p.add_argument("--out", required=True)
    args = p.parse_args()

    flags = " ".join(
        f for f in os.environ.get("XLA_FLAGS", "").split()
        if not f.startswith("--xla_force_host_platform_device_count"))
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=2"
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["DS_TPU_ACCELERATOR"] = "cpu"
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(
        coordinator_address=args.coordinator,
        num_processes=args.num_processes,
        process_id=args.process_id)
    assert jax.process_count() == args.num_processes
    assert jax.device_count() == 2 * args.num_processes
    assert len(jax.local_devices()) == 2

    import numpy as np

    import deepspeed_tpu as ds
    from deepspeed_tpu.models import build_gpt, gpt

    model, _ = build_gpt(gpt.GPTConfig(
        vocab_size=64, n_layer=2, n_head=2, d_model=32, max_seq_len=32))
    try:
        engine, _, _, _ = ds.initialize(model=model, config={
            "train_micro_batch_size_per_gpu": 1,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
            "zero_optimization": {"stage": 2},
            "mesh": {"dp": 4},
            "bf16": {"enabled": False},
            "steps_per_print": 0,
        })
    except Exception as e:
        # this jaxlib's CPU client refuses cross-process programs outright
        # ("Multiprocess computations aren't implemented on the CPU
        # backend") — a backend capability gap, not a code path under test.
        # Exit with a distinguished code so the driver can skip precisely.
        if "Multiprocess computations aren't implemented" in str(e):
            print(f"MULTIHOST_UNSUPPORTED: {e}", file=sys.stderr)
            return BACKEND_UNSUPPORTED_EXIT
        raise
    r = np.random.default_rng(0)  # same data on every process
    ids = r.integers(0, 64, size=(4, 16), dtype=np.int32)
    losses = [float(engine.train_batch({"input_ids": ids})["loss"])
              for _ in range(3)]

    # multi-host checkpoint: tag barrier + process-0 write + collective gathers
    engine.save_checkpoint(args.ckpt_dir)
    ref = float(engine.train_batch({"input_ids": ids})["loss"])

    model2, _ = build_gpt(gpt.GPTConfig(
        vocab_size=64, n_layer=2, n_head=2, d_model=32, max_seq_len=32))
    engine2, _, _, _ = ds.initialize(model=model2, config={
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 2},
        "mesh": {"dp": 4},
        "bf16": {"enabled": False},
        "steps_per_print": 0,
    })
    path, _ = engine2.load_checkpoint(args.ckpt_dir)
    assert path is not None
    got = float(engine2.train_batch({"input_ids": ids})["loss"])

    with open(args.out, "w") as f:
        json.dump({"process": args.process_id, "losses": losses,
                   "ref": ref, "resumed": got}, f)
    return 0


if __name__ == "__main__":
    sys.exit(main())
