"""Export to the reference (torch-DeepSpeed) checkpoint layout.

The reverse of test_reference_import: weights written here must (a) round-trip
through our own reference importer bit-exactly, (b) load into the matching HF
transformers model, and (c) come straight off a live engine — including the
ZeRO-Infinity param-stream engine whose weights live in host masters.
"""

import numpy as np
import pytest

import jax

import deepspeed_tpu
from deepspeed_tpu.checkpoint import (export_engine_checkpoint,
                                      hf_config_for_export,
                                      save_reference_checkpoint)
from deepspeed_tpu.checkpoint.reference_import import (
    get_fp32_state_dict_from_reference_checkpoint, load_reference_checkpoint)
from deepspeed_tpu.models import build_gpt
from deepspeed_tpu.models.gpt import GPTConfig, init_params


def _cfg():
    return GPTConfig(vocab_size=96, d_model=32, n_layer=2, n_head=2,
                     max_seq_len=24)


@pytest.mark.slow
def test_roundtrip_through_own_importer(tmp_path):
    cfg = _cfg()
    params = jax.tree_util.tree_map(
        lambda x: np.asarray(x, np.float32), init_params(cfg, jax.random.PRNGKey(0)))
    path = save_reference_checkpoint(cfg, params, str(tmp_path), tag="global_step3")
    assert path.endswith("global_step3/mp_rank_00_model_states.pt")

    cfg2, params2 = load_reference_checkpoint(
        str(tmp_path), hf_config_for_export(cfg), "GPT2LMHeadModel")
    assert (cfg2.n_layer, cfg2.n_head, cfg2.d_model,
            cfg2.vocab_size) == (2, 2, 32, 96)
    assert cfg2.activation == cfg.activation
    flat1 = jax.tree_util.tree_leaves_with_path(params)
    flat2 = {jax.tree_util.keystr(k): v
             for k, v in jax.tree_util.tree_leaves_with_path(params2)}
    for k, v in flat1:
        np.testing.assert_array_equal(
            np.asarray(v, np.float32),
            np.asarray(flat2[jax.tree_util.keystr(k)], np.float32),
            err_msg=jax.tree_util.keystr(k))


@pytest.mark.slow
def test_export_loads_into_hf_transformers(tmp_path):
    torch = pytest.importorskip("torch")
    from transformers import GPT2Config, GPT2LMHeadModel

    cfg = _cfg()
    params = init_params(cfg, jax.random.PRNGKey(1))
    path = save_reference_checkpoint(cfg, params, str(tmp_path))
    sd = torch.load(path, map_location="cpu", weights_only=False)["module"]
    hf = GPT2LMHeadModel(GPT2Config(
        vocab_size=96, n_embd=32, n_layer=2, n_head=2, n_positions=24,
        activation_function="gelu_new"))
    missing, unexpected = hf.load_state_dict(sd, strict=False)
    # everything the HF module owns must be provided except attention biases
    # (HF-internal causal-mask buffers, not weights)
    assert not unexpected
    assert all(".attn.bias" in m or ".attn.masked_bias" in m for m in missing), missing
    got = hf.transformer.h[1].mlp.c_fc.weight.detach().numpy()
    np.testing.assert_allclose(
        got, np.asarray(params["blocks"]["mlp_up_w"][1], np.float32),
        rtol=1e-6)


@pytest.mark.slow
def test_export_from_live_engines(tmp_path):
    for extra, sub in [({}, "plain"),
                       ({"zero_optimization": {
                           "offload_param": {"device": "cpu"}}}, "stream")]:
        model, cfg = build_gpt(_cfg())
        engine, _, _, _ = deepspeed_tpu.initialize(model=model, config={
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
            "steps_per_print": 0, **extra})
        b = {"input_ids": np.random.default_rng(0).integers(
            0, cfg.vocab_size, (16, 16), dtype=np.int32)}
        engine.train_batch(b)
        path = export_engine_checkpoint(engine, str(tmp_path / sub))
        sd = get_fp32_state_dict_from_reference_checkpoint(str(tmp_path / sub))
        assert "transformer.h.1.attn.c_attn.weight" in sd
        assert sd["transformer.wte.weight"].shape == (cfg.vocab_size,
                                                      cfg.d_model)


def test_export_rejects_non_gpt2_shapes(tmp_path):
    cfg = GPTConfig(vocab_size=64, d_model=32, n_layer=2, n_head=2,
                    max_seq_len=16, rotary=True)
    params = init_params(cfg, jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="rotary"):
        save_reference_checkpoint(cfg, params, str(tmp_path))
