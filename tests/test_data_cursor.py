"""Data-cursor contract (ISSUE 8 satellite; docs/RESILIENCE.md "In-run
health"): ``engine.data_cursor`` counts consumed global batches, rides
checkpoint meta, and makes resume/rollback land on the exact next batch —
checkpoint→resume is bitwise, and rollback-with-skip provably excludes the
poisoned batch indices from training.
"""

import json

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.checkpoint.serialization import _fetch_full, _flatten_with_paths
from deepspeed_tpu.models import GPTConfig, build_gpt
from deepspeed_tpu.resilience import FaultPlan, install_plan

TINY = GPTConfig(vocab_size=64, n_layer=2, n_head=2, d_model=32, max_seq_len=32)


@pytest.fixture(autouse=True)
def _clear_fault_plan():
    yield
    install_plan(None)


def make_engine(resilience=None):
    model, _ = build_gpt(TINY)
    cfg = {
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "bf16": {"enabled": False},
        "steps_per_print": 0,
        "mesh": {"dp": 8},
    }
    if resilience is not None:
        cfg["resilience"] = resilience
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=cfg)
    return engine


def batch_for(cursor: int):
    r = np.random.default_rng(1000 + cursor)
    return {"input_ids": r.integers(0, 64, size=(8, 16), dtype=np.int32)}


def state_arrays(engine):
    return {key: np.asarray(_fetch_full(leaf))
            for key, leaf in _flatten_with_paths(engine.state)[0]}


def test_cursor_counts_consumed_batches_and_rides_meta(tmp_path):
    engine = make_engine()
    assert engine.data_cursor == 0
    for _ in range(3):
        engine.train_batch(batch_for(engine.data_cursor))
    assert engine.data_cursor == 3
    path = engine.save_checkpoint(str(tmp_path))
    meta = json.load(open(f"{path}/meta.json"))
    assert meta["data_cursor"] == 3

    engine2 = make_engine()
    engine2.load_checkpoint(str(tmp_path))
    assert engine2.data_cursor == 3  # the exact next batch index


@pytest.mark.slow
def test_resume_lands_on_exact_next_batch_bitwise(tmp_path):
    """Continuous 5-step run vs 3 steps + save + fresh-engine resume + 2
    steps, both driven by batch_for(data_cursor): final state is BITWISE
    identical — the cursor (plus the restored rng chain) fully determines
    the remaining trajectory."""
    a = make_engine()
    for _ in range(5):
        a.train_batch(batch_for(a.data_cursor))

    b = make_engine()
    for _ in range(3):
        b.train_batch(batch_for(b.data_cursor))
    b.save_checkpoint(str(tmp_path))

    c = make_engine()
    c.load_checkpoint(str(tmp_path))
    assert c.data_cursor == 3
    for _ in range(2):
        c.train_batch(batch_for(c.data_cursor))

    ref, got = state_arrays(a), state_arrays(c)
    assert sorted(ref) == sorted(got)
    for key in ref:
        np.testing.assert_array_equal(ref[key], got[key], err_msg=key)


def test_rollback_skip_excludes_poisoned_indices(tmp_path):
    """Every executed (weight-updating) batch index is recorded; after a
    NaN at cursor 3 heals, cursor 3 appears in the skip record and is never
    executed again — and the healthy cursors each execute exactly once."""
    engine = make_engine(resilience={
        "enabled": True, "save_dir": str(tmp_path),
        "install_signal_handlers": False,
        "sentinel": {"enabled": True, "warmup_steps": 1,
                     "checkpoint_interval": 1,
                     "cursor_checkpointable": True}})
    install_plan(FaultPlan.from_dict({"nan_at_step": 3}))
    executed = []
    while engine.global_steps < 6:
        cursor = engine.data_cursor
        m = engine.train_batch(batch_for(cursor))
        if m.get("skipped_batch") or m.get("health", {}).get("rolled_back"):
            continue
        executed.append(cursor)
    install_plan(None)
    assert engine._health.skipped_cursors == [3]
    assert 3 not in executed
    # six steps from six distinct healthy cursors, in order
    assert executed == [0, 1, 2, 4, 5, 6]
    assert engine.data_cursor == 7


def test_imperative_api_cursor_counts_boundaries():
    """forward/backward/step: the cursor counts GLOBAL batches — one per
    accumulation boundary, not one per micro-batch."""
    model, _ = build_gpt(TINY)
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config={
        "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": 2,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "bf16": {"enabled": False},
        "steps_per_print": 0,
        "mesh": {"dp": 8},
    })
    for i in range(2):
        engine.forward(batch_for(i))
        engine.backward()
        engine.step()
    assert engine.global_steps == 1
    assert engine.data_cursor == 1


def test_imperative_path_sentinel_and_poison_skip(tmp_path):
    """The sentinel works on forward/backward/step too: boundary metrics
    (which carry no loss) merge the window's forward loss for the loss
    channel, and after a rollback forward() consumes the poison window
    without executing."""
    engine = make_engine(resilience={
        "enabled": True, "save_dir": str(tmp_path),
        "install_signal_handlers": False,
        "sentinel": {"enabled": True, "warmup_steps": 1,
                     "checkpoint_interval": 2,
                     "cursor_checkpointable": True}})

    def one_step():
        loss = engine.forward(batch_for(engine.data_cursor))
        engine.backward()
        engine.step()
        return loss

    for _ in range(3):  # anchors at step 2; no KeyError on any boundary
        one_step()
    assert engine._health.loss_detector.count == 3  # loss channel fed
    assert engine.data_cursor == 3

    rb = engine._health._rollback("test-injected divergence")
    assert rb["to_step"] == 2 and rb["skip_cursors"] == [2]
    assert engine.data_cursor == 2

    # the poisoned cursor is consumed by forward() without executing: no
    # micro advance, step() sees no boundary, no weights change
    params_before = np.asarray(engine.state["params"]["wte"])
    one_step()
    assert engine._health.skipped_cursors == [2]
    assert engine.global_steps == 2  # nothing stepped
    np.testing.assert_array_equal(
        params_before, np.asarray(engine.state["params"]["wte"]))
    # the next healthy cursor trains normally
    one_step()
    assert engine.global_steps == 3 and engine.data_cursor == 4
