"""BERT encoder family: training mechanics + HF logits parity."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.models import bert as B


@pytest.mark.slow
def test_bert_mlm_trains():
    model, cfg = B.build("tiny-bert")
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model,
        config={"train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                "steps_per_print": 0})
    r = np.random.default_rng(0)
    ids = r.integers(0, cfg.vocab_size, size=(16, 32), dtype=np.int32)
    labels = np.full_like(ids, -100)
    mask_pos = r.random(ids.shape) < 0.15
    labels[mask_pos] = ids[mask_pos]
    masked = ids.copy()
    masked[mask_pos] = 3  # [MASK]-ish
    batch = {"input_ids": masked, "labels": labels}
    losses = [float(engine.train_batch(batch)["loss"]) for _ in range(6)]
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]


def test_bert_attention_mask_blocks_padding(rng):
    model, cfg = B.build("tiny-bert")
    params = model.init(jax.random.PRNGKey(0))
    ids = rng.integers(0, cfg.vocab_size, size=(1, 16)).astype(np.int32)
    am = np.ones((1, 16), np.int32)
    am[0, 8:] = 0  # pad the tail
    h_masked = B.encode(cfg, params, jnp.asarray(ids), attention_mask=jnp.asarray(am))
    # changing padded tokens must not change unpadded hidden states
    ids2 = ids.copy()
    ids2[0, 8:] = (ids2[0, 8:] + 7) % cfg.vocab_size
    h2 = B.encode(cfg, params, jnp.asarray(ids2), attention_mask=jnp.asarray(am))
    np.testing.assert_allclose(np.asarray(h_masked[0, :8]), np.asarray(h2[0, :8]),
                               atol=1e-5)


def test_bert_tp_sharded_matches_single(rng):
    from deepspeed_tpu.runtime.topology import MeshTopology, mesh_context
    from jax.sharding import NamedSharding

    model, cfg = B.build("tiny-bert")
    params = model.init(jax.random.PRNGKey(0))
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(2, 16)), jnp.int32)
    ref = B.encode(cfg, params, ids)

    topo = MeshTopology.create(dp=4, tp=2)
    specs = model.specs(jax.eval_shape(lambda: params))
    sharded = jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(topo.mesh, s)), params, specs)
    with mesh_context(topo.mesh):
        out = jax.jit(lambda p, i: B.encode(cfg, p, i))(sharded, ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=1e-4)


@pytest.mark.slow
def test_bert_import_matches_hf(rng):
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    from deepspeed_tpu.module_inject import import_hf_model

    hf_cfg = transformers.BertConfig(
        vocab_size=99, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, type_vocab_size=2)
    torch.manual_seed(0)
    model = transformers.BertForMaskedLM(hf_cfg).eval()
    ids = rng.integers(0, 99, size=(2, 12)).astype(np.int64)
    am = np.ones_like(ids)
    tt = np.zeros_like(ids)

    cfg, params = import_hf_model(model)
    hidden = B.encode(cfg, params, jnp.asarray(ids),
                      attention_mask=jnp.asarray(am),
                      token_type_ids=jnp.asarray(tt))
    ours = np.asarray(B.mlm_logits(cfg, params, hidden))
    with torch.no_grad():
        theirs = model(torch.from_numpy(ids).long(),
                       attention_mask=torch.from_numpy(am).long(),
                       token_type_ids=torch.from_numpy(tt).long()).logits.numpy()
    np.testing.assert_allclose(ours, theirs, atol=2e-3, rtol=1e-3)


@pytest.mark.slow
def test_classification_head_trains():
    from deepspeed_tpu.models.bert import (
        BertConfig, classification_logits, init_classifier, init_params)

    cfg = BertConfig(vocab_size=64, d_model=32, n_layer=1, n_head=2,
                     max_seq_len=16)
    params = init_params(cfg, jax.random.PRNGKey(0))
    head = init_classifier(cfg, 3, jax.random.PRNGKey(1))
    ids = np.random.default_rng(0).integers(0, 64, (4, 16), np.int32)
    labels = np.asarray([0, 1, 2, 1])

    def loss_fn(h):
        logits = classification_logits(cfg, params, h, jnp.asarray(ids))
        lp = jax.nn.log_softmax(logits.astype(jnp.float32))
        return -lp[jnp.arange(4), labels].mean()

    l0 = float(loss_fn(head))
    g = jax.grad(loss_fn)(head)
    head2 = jax.tree_util.tree_map(lambda p, gg: p - 0.5 * gg, head, g)
    assert float(loss_fn(head2)) < l0  # the head learns
    logits = classification_logits(cfg, params, head, jnp.asarray(ids),
                                   attention_mask=np.ones((4, 16), np.int32))
    assert logits.shape == (4, 3)
