"""Fleet router tests (docs/SERVING.md "Fleet") — device-free.

Replicas are real ``ContinuousBatchingScheduler``s over the deterministic
arithmetic fake executor (prefill answers last+1, decode prev+1 mod 97),
wrapped in ``LocalReplica`` handles, so every fleet behavior — placement
scoring, session affinity + spill, backpressure shed-to-sibling,
kill-mid-decode re-route, drain-then-retire, autoscaling — is exercised
against the true scheduler/page machinery with outputs directly comparable
to a fault-free single-scheduler run.
"""

import time

import numpy as np
import pytest

from deepspeed_tpu.analysis import analyze_compile_log
from deepspeed_tpu.inference.fleet import (AutoscalePolicy, FleetAutoscaler,
                                           FleetConfig, LocalReplica,
                                           ReplicaDeadError, ReplicaRouter,
                                           run_fleet, summarize_events)
from deepspeed_tpu.inference.serving import (ContinuousBatchingScheduler,
                                             Request, RequestState)
from deepspeed_tpu.resilience.events import RecoveryLog, read_events


class FakeExecutor:
    """prefill -> last+1, decode -> prev+1 (mod 97): greedy outputs are a
    pure function of the prompt, so healed and fault-free runs compare."""

    def prefill(self, slot, tokens, table_row):
        return (int(tokens[-1]) + 1) % 97

    def decode(self, tokens, tables, lengths, active, steps=1):
        return np.stack([(tokens + k + 1) % 97 for k in range(steps)])


def mk_sched(num_slots=2, num_pages=32, page_size=4, pages_per_seq=8, **kw):
    return ContinuousBatchingScheduler(
        FakeExecutor(), num_slots=num_slots, num_pages=num_pages,
        page_size=page_size, pages_per_seq=pages_per_seq, **kw)


def mk_replica(rid, **sched_kw):
    return LocalReplica(rid, scheduler=mk_sched(**sched_kw))


SPEC = ((3, 6), (5, 4), (2, 8), (4, 3))


def workload(spec=SPEC, **kw):
    return [Request(prompt=np.arange(1, n + 1, dtype=np.int32),
                    max_new_tokens=m, **kw) for n, m in spec]


def reference_tokens(spec=SPEC):
    sched = mk_sched(num_slots=4)
    reqs = workload(spec)
    for r in reqs:
        sched.submit(r)
    sched.run_to_completion(max_steps=500)
    return [list(r.tokens) for r in reqs]


class KillableReplica(LocalReplica):
    """Dies AFTER making internal decode progress it never reports — the
    SIGKILL-mid-decode-block model: the router's kept-token ledger is a
    strict prefix of the replica's private truth."""

    def __init__(self, *a, die_after_pumps=None, **kw):
        super().__init__(*a, **kw)
        self.die_after_pumps = die_after_pumps
        self.pumps = 0

    def pump(self, max_steps=1):
        self.pumps += 1
        if (self.die_after_pumps is not None
                and self.pumps > self.die_after_pumps):
            super().pump(max_steps)  # progress happens, report never lands
            self._alive = False
            raise ReplicaDeadError("killed mid-decode")
        return super().pump(max_steps)


# --------------------------------------------------------------- placement
def test_least_loaded_placement():
    """Requests land on the replica with the least queued+running work."""
    r0, r1 = mk_replica("r0"), mk_replica("r1")
    router = ReplicaRouter([r0, r1])
    a, b, c = workload(((4, 10), (4, 2), (4, 2)))
    router.submit(a)               # both empty -> r0 (id tie-break)
    assert router._assignment[a.rid] == "r0"
    router.submit(b)               # r0 now holds work -> r1
    assert router._assignment[b.rid] == "r1"
    router.submit(c)               # r0 carries 10 tokens vs r1's 2 -> r1
    assert router._assignment[c.rid] == "r1"
    router.run_to_completion()
    assert [r.state for r in (a, b, c)] == [RequestState.FINISHED] * 3


def test_placement_skips_draining_replica():
    r0, r1 = mk_replica("r0"), mk_replica("r1")
    router = ReplicaRouter([r0, r1])
    router.retire("r0")
    req = workload(((3, 4),))[0]
    assert router.submit(req)
    assert router._assignment[req.rid] == "r1"


# ---------------------------------------------------------------- affinity
def test_session_affinity_sticks():
    """Same session_id keeps landing on the same replica even when a
    sibling is less loaded."""
    r0, r1 = mk_replica("r0", num_slots=4), mk_replica("r1", num_slots=4)
    router = ReplicaRouter([r0, r1])
    first = workload(((4, 8),), session_id="chat-1")[0]
    router.submit(first)
    home = router._assignment[first.rid]
    # pile neutral load onto the OTHER replica's sibling... submit enough
    # sessionless work that the home replica is strictly more loaded
    for r in workload(((4, 2), (4, 2))):
        router.submit(r)
    nxt = workload(((6, 4),), session_id="chat-1")[0]
    router.submit(nxt)
    assert router._assignment[nxt.rid] == home


def test_session_affinity_spills_on_pressure_and_resticks():
    """A sticky replica answering queue_full loses the request to a
    sibling, and the session re-sticks there."""
    # r0: 1 slot, 1-deep queue -> the second same-session request cannot
    # be admitted while the first still sits in r0's queue
    r0 = mk_replica("r0", num_slots=1, max_queue=1)
    r1 = mk_replica("r1", num_slots=1, max_queue=4)
    router = ReplicaRouter([r0, r1])
    first = workload(((4, 12),), session_id="s")[0]
    router.submit(first)
    assert router._assignment[first.rid] == "r0"
    second = workload(((4, 4),), session_id="s")[0]
    verdict = router.submit(second)
    assert verdict.admitted
    assert router._assignment[second.rid] == "r1"     # spilled
    assert router._affinity["s"] == "r1"              # re-stuck
    assert router.counters.get("session_spilled") == 1
    router.run_to_completion()


# ------------------------------------------------------------ backpressure
def test_backpressure_sheds_to_sibling_before_fleet_rejects():
    """queue_full on the least-loaded replica is a spill signal: the
    request lands on the sibling; only ALL replicas refusing is a
    fleet-level reject."""
    r0 = mk_replica("r0", num_slots=1, max_queue=1)
    r1 = mk_replica("r1", num_slots=1, max_queue=2)
    router = ReplicaRouter([r0, r1])
    small = workload(((4, 8),))[0]
    big = workload(((4, 28),))[0]
    router.submit(small)                       # -> r0 (tie-break)
    router.submit(big)                         # -> r1 (least-loaded)
    assert router._assignment[small.rid] == "r0"
    assert router._assignment[big.rid] == "r1"
    spilled = workload(((4, 8),))[0]
    verdict = router.submit(spilled)
    # r0 (less loaded) is probed first but its queue is full -> the
    # verdict is backpressure, and r1 takes the request
    assert verdict.admitted
    assert router._assignment[spilled.rid] == "r1"
    assert r0.sched.counters.get("request_shed", 0) == 1
    rejected = workload(((4, 8),))[0]
    verdict = router.submit(rejected)          # now everyone is full
    assert not verdict.admitted
    assert verdict.reason == "queue_full"
    assert rejected.state is RequestState.REJECTED
    assert router.counters["fleet_reject"] == 1
    router.run_to_completion()
    assert all(r.state is RequestState.FINISHED
               for r in (small, big, spilled))


def test_unservable_rejects_immediately_without_spill():
    r0, r1 = mk_replica("r0"), mk_replica("r1")
    router = ReplicaRouter([r0, r1])
    huge = Request(prompt=np.arange(1, 100, dtype=np.int32),
                   max_new_tokens=100)
    verdict = router.submit(huge)
    assert not verdict.admitted and verdict.reason == "unservable"
    # only ONE replica was probed: the bound is structural
    shed_counts = [r.sched.counters.get("request_shed", 0) for r in (r0, r1)]
    assert sorted(shed_counts) == [0, 1]


# ---------------------------------------------------------------- failover
def test_kill_mid_decode_reroutes_with_kept_tokens():
    """A replica dying mid-decode (progress made, never reported) loses
    nothing: its requests re-route with the router's absorbed tokens and
    finish greedy-identical to a fault-free run; survivors audit clean."""
    clean = reference_tokens()
    reps = [KillableReplica("r0", scheduler=mk_sched(), die_after_pumps=2),
            mk_replica("r1")]
    router = ReplicaRouter(reps, FleetConfig(reroute_budget=2))
    reqs = workload()
    for r in reqs:
        router.submit(r)
    router.run_to_completion()
    assert [list(r.tokens) for r in reqs] == clean
    assert all(r.state is RequestState.FINISHED for r in reqs)
    assert router.counters["replica_dead"] == 1
    assert router.counters["request_rerouted"] >= 1
    rep = router.audit_survivors()
    assert rep["ok"], rep
    assert reps[1].sched.allocator.allocated_pages == 0


def test_simultaneous_failures_reroute_to_healthy_survivor():
    """Two replicas failing in the SAME step must both leave the placement
    set before any victim is re-routed: serial handling would re-place the
    first failure's requests onto the second known-sick replica and burn
    their whole reroute budget with a healthy survivor standing by."""

    class SickReplica(LocalReplica):
        """Stays alive and keeps accepting submissions, but every pump
        after the first raises — the ServingFaultError shape."""

        def __init__(self, *a, **kw):
            super().__init__(*a, **kw)
            self.pumps = 0

        def pump(self, max_steps=1):
            self.pumps += 1
            if self.pumps > 1:
                raise RuntimeError("wedged executor")
            return super().pump(max_steps)

    reps = [SickReplica("r0", scheduler=mk_sched()),
            SickReplica("r1", scheduler=mk_sched()),
            mk_replica("r2")]
    router = ReplicaRouter(reps, FleetConfig(reroute_budget=1))
    reqs = workload(((3, 6), (5, 4)))
    for r in reqs:
        router.submit(r)
    assert {router._assignment[r.rid] for r in reqs} == {"r0", "r1"}
    router.run_to_completion()
    assert router.counters["replica_dead"] == 2
    assert all(r.state is RequestState.FINISHED for r in reqs), \
        [(r.state, r.reject_reason) for r in reqs]
    assert [list(r.tokens) for r in reqs] \
        == reference_tokens(((3, 6), (5, 4)))


def test_reroute_budget_exhaustion_is_typed():
    """Every replica dying faster than the budget allows ends in a typed
    rejection, not an infinite loop."""
    reps = [KillableReplica(f"r{i}", scheduler=mk_sched(),
                            die_after_pumps=0) for i in range(3)]
    router = ReplicaRouter(reps, FleetConfig(reroute_budget=1))
    req = workload(((4, 6),))[0]
    router.submit(req)
    for _ in range(10):
        if router.idle:
            break
        router.step()
    assert req.state is RequestState.REJECTED
    assert req.reject_reason in ("reroute_budget", "no_replicas")
    assert router.counters["replica_dead"] >= 1


def test_hung_replica_fails_over_on_heartbeat():
    """A replica that answers pumps but reports a stale heartbeat is
    evicted and its work re-routed."""

    class HungReplica(LocalReplica):
        def heartbeat_age(self):
            return 999.0

    reps = [HungReplica("r0", scheduler=mk_sched()), mk_replica("r1")]
    router = ReplicaRouter(reps, FleetConfig(heartbeat_deadline_s=1.0,
                                             reroute_budget=2))
    reqs = workload(((3, 6), (5, 4)))
    for r in reqs:
        router.submit(r)
    router.run_to_completion()
    assert router.counters["replica_hung"] == 1
    assert router.counters["replica_dead"] == 1
    assert all(r.state is RequestState.FINISHED for r in reqs)
    assert [list(r.tokens) for r in reqs] \
        == reference_tokens(((3, 6), (5, 4)))


# ------------------------------------------------------- drain-then-retire
def test_scheduler_drain_is_idempotent_and_finishes_accepted_work():
    sched = mk_sched()
    reqs = workload(((3, 6), (5, 4)))
    for r in reqs:
        assert sched.submit(r)
    sched.step()
    sched.drain()
    sched.drain()  # idempotent: one drain_started event
    assert sched.counters["drain_started"] == 1
    late = workload(((2, 3),))[0]
    verdict = sched.submit(late)
    assert not verdict.admitted and verdict.reason == "draining"
    assert late.state is RequestState.REJECTED
    assert not sched.drained  # accepted work still in flight
    sched.run_to_completion(max_steps=200)
    assert sched.drained
    assert all(r.state is RequestState.FINISHED for r in reqs)
    assert sched.allocator.allocated_pages == 0


def test_router_drain_then_retire():
    """retire(): the replica admits nothing new, finishes its accepted
    work, then is closed and removed — zero dropped requests."""
    reps = [mk_replica("r0"), mk_replica("r1")]
    router = ReplicaRouter(reps)
    reqs = workload()
    for r in reqs:
        router.submit(r)
    assert any(owner == "r0" for owner in router._assignment.values())
    assert router.retire("r0")
    router.run_to_completion()
    assert all(r.state is RequestState.FINISHED for r in reqs)
    assert [list(r.tokens) for r in reqs] == reference_tokens()
    assert [r.replica_id for r in router.retired] == ["r0"]
    assert not reps[0].alive
    assert router.counters["replica_retired"] == 1
    # retiring an already-gone replica is a no-op, not an error
    assert not router.retire("r0")


# --------------------------------------------------------------- autoscale
def _events(now, spec):
    """Synthesize a window event stream: spec = [(event, t_offset), ...]."""
    return [{"unix_time": now + dt, "event": ev} for ev, dt in spec]


def test_autoscale_scale_up_on_shed_rate():
    pol = AutoscalePolicy(window_s=10.0, shed_rate_up=0.1, max_replicas=4)
    now = 1000.0
    evs = _events(now, [("request_routed", -i) for i in range(1, 7)]
                  + [("fleet_reject", -1), ("fleet_reject", -2)])
    s = summarize_events(evs, now, pol.window_s)
    assert s["shed_rate"] == pytest.approx(0.25)
    assert pol.decide(s, num_replicas=2, occupancy=0.9, now=now) \
        == "scale_up"
    # clamped at max_replicas
    assert pol.decide(s, num_replicas=4, occupancy=0.9, now=now) == "hold"


def test_autoscale_scale_up_on_deadline_miss_trend():
    pol = AutoscalePolicy(window_s=10.0, miss_floor=2)
    now = 1000.0
    rising = _events(now, [("deadline_miss", -1), ("deadline_miss", -2),
                           ("deadline_miss", -8)])
    s = summarize_events(rising, now, pol.window_s)
    assert s["miss_trend"] > 0
    assert pol.decide(s, 2, 0.9, now) == "scale_up"
    falling = _events(now, [("deadline_miss", -8), ("deadline_miss", -9),
                            ("deadline_miss", -1)])
    s2 = summarize_events(falling, now, pol.window_s)
    assert pol.decide(s2, 2, 0.9, now) == "hold"  # loaded but improving


def test_autoscale_scale_down_needs_quiet_and_headroom():
    pol = AutoscalePolicy(window_s=10.0, down_occupancy=0.7,
                          min_replicas=1)
    now = 1000.0
    quiet = summarize_events(
        _events(now, [("request_routed", -1)]), now, pol.window_s)
    assert pol.decide(quiet, 2, occupancy=0.2, now=now) == "scale_down"
    # projected post-retire occupancy too high -> hold
    assert pol.decide(quiet, 2, occupancy=0.5, now=now) == "hold"
    # min_replicas clamp
    assert pol.decide(quiet, 1, occupancy=0.0, now=now) == "hold"
    # a single miss in the window blocks scale-down
    busy = summarize_events(
        _events(now, [("deadline_miss", -1)]), now, pol.window_s)
    assert pol.decide(busy, 2, occupancy=0.2, now=now) == "hold"


def test_autoscale_cooldown():
    pol = AutoscalePolicy(window_s=10.0, cooldown_s=30.0)
    now = 1000.0
    quiet = summarize_events([], now, pol.window_s)
    assert pol.decide(quiet, 2, 0.1, now, last_action_t=now - 5) == "hold"
    assert pol.decide(quiet, 2, 0.1, now, last_action_t=now - 60) \
        == "scale_down"


def test_fleet_autoscaler_applies_decisions():
    """scale_up spawns through the factory; scale_down drains the
    least-loaded replica and the router retires it once empty."""
    reps = [mk_replica("r0"), mk_replica("r1")]
    router = ReplicaRouter(reps)
    pol = AutoscalePolicy(window_s=5.0, cooldown_s=0.0, min_replicas=1,
                          max_replicas=3, shed_rate_up=0.1)
    made = []

    def factory(rid):
        made.append(rid)
        return mk_replica(rid)

    scaler = FleetAutoscaler(router, pol, factory)
    # overload the window: mostly rejections
    for _ in range(4):
        router._record("fleet_reject", persist=False)
    router._record("request_routed", persist=False)
    assert scaler.tick() == "scale_up"
    assert made == ["scale1"]
    assert len(router.replicas) == 3
    # quiet + idle -> drain one
    router.events.clear()
    router._record("request_routed", persist=False)
    assert scaler.tick() == "scale_down"
    assert sum(r.draining for r in router.replicas) == 1
    router.step()  # idle drained replica retires on the next step
    assert len(router.retired) == 1
    assert len(router.live_replicas) == 2


# ------------------------------------------------------------- dslint rule
def test_fleet_without_failover_rule_fires_and_stays_silent():
    unsafe = ReplicaRouter([mk_replica("r0"), mk_replica("r1")],
                           FleetConfig(heartbeat_deadline_s=None,
                                       reroute_budget=0))
    findings = analyze_compile_log(unsafe).findings
    assert any(f.rule_id == "serving/fleet-without-failover"
               for f in findings), findings
    # reroute budget armed -> silent
    safe = ReplicaRouter([mk_replica("a"), mk_replica("b")],
                         FleetConfig(reroute_budget=2))
    assert not analyze_compile_log(safe).findings
    # heartbeat armed (budget 0) -> silent
    hb = ReplicaRouter([mk_replica("c"), mk_replica("d")],
                       FleetConfig(heartbeat_deadline_s=5.0,
                                   reroute_budget=0))
    assert not analyze_compile_log(hb).findings
    # single replica -> silent even with nothing armed
    solo = ReplicaRouter([mk_replica("e")],
                         FleetConfig(reroute_budget=0))
    assert not analyze_compile_log(solo).findings


# ----------------------------------------------------- events + merge + aot
def test_recovery_log_stamps_replica_id(tmp_path):
    log = RecoveryLog(str(tmp_path / "ev.jsonl"), role="serving",
                      prefix="Serving", replica_id="r7")
    log.record("request_shed", rid=3)
    log.record("deadline_miss", replica_id="override")
    evs = read_events(str(tmp_path / "ev.jsonl"))
    assert evs[0]["replica_id"] == "r7"
    assert evs[1]["replica_id"] == "override"  # explicit field wins


def test_read_events_merges_multi_replica_logs(tmp_path):
    """Two replicas emitting the SAME event names stay distinguishable
    after the merge, and ordering is by time across logs."""
    dirs = []
    for i, rid in enumerate(("r0", "r1")):
        d = tmp_path / rid
        d.mkdir()
        log = RecoveryLog.for_dir(str(d), role="serving",
                                  replica_id=rid if i == 0 else None)
        log.record("request_shed", rid=i)
        time.sleep(0.01)
        dirs.append(str(d))
    merged = read_events(dirs)
    assert [e["event"] for e in merged] == ["request_shed"] * 2
    # r0 stamped by the producer; r1's pre-fleet log stamped from its dir
    assert merged[0]["replica_id"] == "r0"
    assert merged[1]["replica_id"] == "r1"
    times = [e["unix_time"] for e in merged]
    assert times == sorted(times)
    # explicit (replica_id, path) pairs override the fallback
    merged2 = read_events([("east", dirs[1])])
    assert merged2[0]["replica_id"] == "east"


def test_fleet_replica_plan_from_admission_ladder(monkeypatch):
    from deepspeed_tpu.runtime import aot

    monkeypatch.setattr(
        aot, "serving_admission_limit",
        lambda model, **kw: {"model": model, "max_slots": 6,
                             "max_decode_batch": 6, "fit": "fits",
                             "kv_bits": int(kw.get("kv_bits", 0) or 0),
                             "trace": []})
    plan = aot.fleet_replica_plan("gpt2-125m", target_total_slots=20)
    assert plan["slots_per_replica"] == 6
    assert plan["replicas"] == 4          # ceil(20/6)
    assert plan["total_slots"] == 24
    monkeypatch.setattr(
        aot, "serving_admission_limit",
        lambda model, **kw: {"model": model, "max_slots": 0,
                             "max_decode_batch": 0, "fit": None,
                             "trace": []})
    plan0 = aot.fleet_replica_plan("gpt2-125m", target_total_slots=20)
    assert plan0["replicas"] == 0


# ------------------------------------------------------------ fleet driver
def test_run_fleet_report_schema():
    reps = [mk_replica("r0"), mk_replica("r1")]
    router = ReplicaRouter(reps)
    wl = workload()
    rep = run_fleet(router, wl, max_wall_s=30.0, slo_s=5.0)
    assert rep["mode"] == "fleet"
    assert rep["finished"] == len(wl)
    assert rep["fleet_audit_ok"]
    assert rep["replicas_live"] == 2 and rep["replicas_dead"] == 0
    assert rep["deadline_misses"] == 0
