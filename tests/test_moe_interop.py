"""MoE expert-sharded checkpoint interop (reference layout).

Mirrors the reference's expert-file save/load
(``runtime/engine.py:3151`` _save_moe_checkpoint, ``:2560`` load path):
layer_{L}_expert_{E}_mp_rank_00_model_states.pt files with
``deepspeed_moe.experts.deepspeed_experts.{E}`` keys, gate in the dense file.
"""

import os

import numpy as np
import pytest

import jax

from deepspeed_tpu.checkpoint import (load_reference_moe_checkpoint,
                                      save_reference_moe_checkpoint)
from deepspeed_tpu.models.gpt_moe import PRESETS, init_params


def _params():
    cfg = PRESETS["tiny-moe"]
    return cfg, init_params(cfg, jax.random.PRNGKey(0))


def _zeroed_moe(params):
    out = dict(params)
    mb = dict(params["moe_blocks"])
    moe = dict(mb["moe"])
    moe["experts"] = jax.tree_util.tree_map(np.zeros_like, moe["experts"])
    moe["gate_w"] = np.zeros_like(moe["gate_w"])
    mb["moe"] = moe
    out["moe_blocks"] = mb
    return out


@pytest.mark.slow
def test_roundtrip_restores_bank_and_gate(tmp_path):
    cfg, params = _params()
    files = save_reference_moe_checkpoint(
        params, str(tmp_path), tag="global_step7", moe_freq=cfg.moe_freq)
    # one file per (moe layer, expert) + the dense/gate file, reference naming
    S, E = np.asarray(params["moe_blocks"]["moe"]["experts"]["up_w"]).shape[:2]
    assert len(files) == S * E + 1
    assert os.path.exists(
        tmp_path / "global_step7" / "layer_0_expert_0_mp_rank_00_model_states.pt")

    restored = load_reference_moe_checkpoint(_zeroed_moe(params), str(tmp_path))
    for leaf in ("up_w", "up_b", "down_w", "down_b"):
        np.testing.assert_allclose(
            np.asarray(restored["moe_blocks"]["moe"]["experts"][leaf]),
            np.asarray(params["moe_blocks"]["moe"]["experts"][leaf],
                       np.float32), rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(restored["moe_blocks"]["moe"]["gate_w"]),
        np.asarray(params["moe_blocks"]["moe"]["gate_w"], np.float32),
        rtol=1e-6)


def test_import_synthetic_reference_layout(tmp_path):
    """Files written the way the reference writes them (torch Linear [out,in],
    Megatron expert names, arbitrary module prefix) import correctly."""
    import torch

    cfg, params = _params()
    experts = params["moe_blocks"]["moe"]["experts"]
    S, E = np.asarray(experts["up_w"]).shape[:2]
    d = cfg.base.d_model
    f = cfg.base.ffn_dim
    tag_dir = tmp_path / "global_step0"
    os.makedirs(tag_dir)
    rng = np.random.default_rng(0)
    want_up = rng.normal(size=(S, E, d, f)).astype(np.float32)
    for s in range(S):
        for e in range(E):
            mod = (f"model.language_model.encoder.layers.{s}.mlp"
                   f".deepspeed_moe.experts.deepspeed_experts.{e}")
            torch.save({
                f"{mod}.dense_h_to_4h.weight": torch.from_numpy(want_up[s, e].T.copy()),
                f"{mod}.dense_h_to_4h.bias": torch.zeros(f),
                f"{mod}.dense_4h_to_h.weight": torch.zeros(d, f),
                f"{mod}.dense_4h_to_h.bias": torch.zeros(d),
            }, tag_dir / f"layer_{s}_expert_{e}_mp_rank_00_model_states.pt")
    with open(tmp_path / "latest", "w") as fh:
        fh.write("global_step0")
    restored = load_reference_moe_checkpoint(params, str(tmp_path))
    np.testing.assert_allclose(
        np.asarray(restored["moe_blocks"]["moe"]["experts"]["up_w"]),
        want_up, rtol=1e-6)
    # gate untouched when the checkpoint carries no dense/gate file
    np.testing.assert_allclose(
        np.asarray(restored["moe_blocks"]["moe"]["gate_w"]),
        np.asarray(params["moe_blocks"]["moe"]["gate_w"], np.float32))


def test_gate_read_from_module_wrapped_file(tmp_path):
    """Real reference dense files nest weights under 'module' — gates load."""
    import torch

    cfg, params = _params()
    save_reference_moe_checkpoint(params, str(tmp_path), moe_freq=cfg.moe_freq)
    dense = tmp_path / "global_step0" / "mp_rank_00_model_states.pt"
    sd = torch.load(dense, map_location="cpu", weights_only=False)
    assert "module" in sd and any("gate.wg.weight" in k for k in sd["module"])
    restored = load_reference_moe_checkpoint(_zeroed_moe(params), str(tmp_path))
    np.testing.assert_allclose(
        np.asarray(restored["moe_blocks"]["moe"]["gate_w"]),
        np.asarray(params["moe_blocks"]["moe"]["gate_w"], np.float32),
        rtol=1e-6)


def test_moe_export_merges_with_dense_export(tmp_path):
    """MoE gate save must not clobber a prior dense export of the same tag."""
    import torch

    from deepspeed_tpu.checkpoint import save_reference_checkpoint
    from deepspeed_tpu.models.gpt import GPTConfig, init_params as gpt_init

    dense_cfg = GPTConfig(vocab_size=64, d_model=32, n_layer=2, n_head=2,
                          max_seq_len=16)
    save_reference_checkpoint(dense_cfg, gpt_init(dense_cfg, jax.random.PRNGKey(0)),
                              str(tmp_path), tag="global_step0")
    cfg, params = _params()
    save_reference_moe_checkpoint(params, str(tmp_path), tag="global_step0",
                                  moe_freq=cfg.moe_freq)
    sd = torch.load(tmp_path / "global_step0" / "mp_rank_00_model_states.pt",
                    map_location="cpu", weights_only=False)["module"]
    assert "transformer.wte.weight" in sd  # dense survived
    assert any("gate.wg.weight" in k for k in sd)  # gates added


def test_import_rejects_missing_and_mismatched(tmp_path):
    import torch

    cfg, params = _params()
    tag_dir = tmp_path / "t0"
    os.makedirs(tag_dir)
    with open(tmp_path / "latest", "w") as fh:
        fh.write("t0")
    with pytest.raises(FileNotFoundError, match="expert file"):
        load_reference_moe_checkpoint(params, str(tmp_path))
    # wrong embedded expert id
    d, f = cfg.base.d_model, cfg.base.ffn_dim
    mod = "x.deepspeed_moe.experts.deepspeed_experts.3"
    torch.save({f"{mod}.dense_h_to_4h.weight": torch.zeros(f, d),
                f"{mod}.dense_h_to_4h.bias": torch.zeros(f),
                f"{mod}.dense_4h_to_h.weight": torch.zeros(d, f),
                f"{mod}.dense_4h_to_h.bias": torch.zeros(d)},
               tag_dir / "layer_0_expert_0_mp_rank_00_model_states.pt")
    with pytest.raises(ValueError, match="expert id"):
        load_reference_moe_checkpoint(params, str(tmp_path))


def test_imported_bank_runs_forward(tmp_path):
    """Imported params must drive the MoE forward (shape/transpose sanity)."""
    from deepspeed_tpu.models import build_gpt_moe

    cfg, params = _params()
    save_reference_moe_checkpoint(params, str(tmp_path), moe_freq=cfg.moe_freq)
    restored = load_reference_moe_checkpoint(_zeroed_moe(params), str(tmp_path))
    model, _ = build_gpt_moe(cfg)
    ids = np.random.default_rng(0).integers(
        0, cfg.base.vocab_size, (2, 16), dtype=np.int32)
    restored = jax.tree_util.tree_map(lambda x: np.asarray(x, np.float32),
                                      restored)
    loss, _ = model.apply(restored, {"input_ids": ids},
                          rngs={"dropout": jax.random.PRNGKey(0)}, train=True)
    assert np.isfinite(float(loss))
