"""ZeRO-Offload: native cpu_adam numerics + host-offloaded training.

Mirrors the reference's tests/unit/ops/adam (kernel-vs-reference numerical
comparison) and the cpu_offload engine paths.
"""

import numpy as np
import pytest

import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.ops.adam import DeepSpeedCPUAdam, DeepSpeedCPUAdagrad
from deepspeed_tpu.ops.op_builder import get_builder


def _ref_adam(p, m, v, g, t, lr, b1, b2, eps, wd, adamw):
    if wd and not adamw:
        g = g + wd * p
    m = b1 * m + (1 - b1) * g
    v = b2 * v + (1 - b2) * g * g
    upd = (m / (1 - b1 ** t)) / (np.sqrt(v / (1 - b2 ** t)) + eps)
    if wd and adamw:
        upd = upd + wd * p
    return p - lr * upd, m, v


def test_native_builds_and_reports_simd():
    b = get_builder("ds_cpu_ops")
    assert b.is_compatible()
    lib = b.load()
    assert lib.ds_cpu_ops_version() == 1
    # on x86 CI we expect the AVX2+FMA path; scalar fallback is allowed elsewhere
    assert lib.ds_cpu_ops_simd() in (0, 2)


@pytest.mark.parametrize("adamw", [True, False])
@pytest.mark.parametrize("wd", [0.0, 0.01])
def test_cpu_adam_matches_reference(rng, adamw, wd):
    n = 10_001  # odd size: exercises the SIMD remainder loop
    p = rng.normal(size=n).astype(np.float32)
    g = rng.normal(size=n).astype(np.float32)
    m = np.zeros(n, np.float32)
    v = np.zeros(n, np.float32)
    pr, mr, vr = p.copy(), m.copy(), v.copy()

    opt = DeepSpeedCPUAdam(lr=1e-3, weight_decay=wd, adamw_mode=adamw)
    for t in range(1, 4):
        opt.step(p, m, v, g, t)
        pr, mr, vr = _ref_adam(pr, mr, vr, g, t, 1e-3, 0.9, 0.999, 1e-8, wd, adamw)
    np.testing.assert_allclose(p, pr, rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(m, mr, rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(v, vr, rtol=2e-5, atol=2e-6)


def test_cpu_adam_bf16_copyback(rng):
    n = 64
    p = rng.normal(size=n).astype(np.float32)
    bf16 = np.zeros(n, np.uint16)
    opt = DeepSpeedCPUAdam(lr=1e-3)
    opt.step(p, np.zeros(n, np.float32), np.zeros(n, np.float32),
             rng.normal(size=n).astype(np.float32), 1, bf16_out=bf16)
    import ml_dtypes

    recon = bf16.view(ml_dtypes.bfloat16).astype(np.float32)
    np.testing.assert_allclose(recon, p, rtol=1e-2)  # bf16 has ~3 decimal digits


def test_cpu_adagrad_runs(rng):
    n = 1000
    p = rng.normal(size=n).astype(np.float32)
    a = np.zeros(n, np.float32)
    g = rng.normal(size=n).astype(np.float32)
    p0 = p.copy()
    DeepSpeedCPUAdagrad(lr=1e-2).step(p, a, g)
    assert not np.allclose(p, p0)
    np.testing.assert_allclose(a, g * g, rtol=1e-6)


# --------------------------------------------------------------------- engine path
def _engine(config_extra=None, vocab=128):
    from deepspeed_tpu.models import build_gpt
    from deepspeed_tpu.models.gpt import GPTConfig

    model, cfg = build_gpt(GPTConfig(
        vocab_size=vocab, d_model=32, n_layer=2, n_head=2, max_seq_len=32))
    config = {
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "steps_per_print": 0,
    }
    config.update(config_extra or {})
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)
    return engine, cfg


def _batch(cfg, seed=0, bs=16, seq=16):
    r = np.random.default_rng(seed)
    return {"input_ids": r.integers(0, cfg.vocab_size, size=(bs, seq), dtype=np.int32)}


@pytest.mark.slow
def test_offload_matches_device_adam():
    """cpu-offloaded AdamW must track the on-device AdamW trajectory closely."""
    e_off, cfg = _engine({
        "zero_optimization": {"stage": 2, "offload_optimizer": {"device": "cpu"}}})
    e_dev, _ = _engine({"zero_optimization": {"stage": 2}})
    assert e_off._offload is not None
    for i in range(4):
        b = _batch(cfg, seed=i)
        m1 = e_off.train_batch(b)
        m2 = e_dev.train_batch(b)
        np.testing.assert_allclose(
            float(m1["loss"]), float(m2["loss"]), rtol=2e-4)
    assert int(e_off.state["step"]) == 4


def test_offload_device_state_is_empty():
    e_off, _ = _engine({
        "zero_optimization": {"stage": 1, "offload_optimizer": {"device": "cpu"}}})
    assert e_off.state["opt"] == {}
    assert e_off.state["master"] == {}


def test_offload_legacy_cpu_offload_flag():
    e_off, _ = _engine({"zero_optimization": {"stage": 2, "cpu_offload": True}})
    assert e_off._offload is not None


def test_offload_bf16_training():
    e, cfg = _engine({
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 2, "offload_optimizer": {"device": "cpu"}}})
    losses = [float(e.train_batch(_batch(cfg, seed=0))["loss"]) for _ in range(5)]
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]  # overfits the repeated batch
    assert e.state["params"]["wte"].dtype == jnp.bfloat16


@pytest.mark.slow
def test_offload_checkpoint_roundtrip(tmp_path):
    e, cfg = _engine({
        "zero_optimization": {"stage": 2, "offload_optimizer": {"device": "cpu"}}})
    b = _batch(cfg)
    for _ in range(3):
        e.train_batch(b)
    m_before = e._offload.m[0].copy()
    e.save_checkpoint(str(tmp_path))

    e2, _ = _engine({
        "zero_optimization": {"stage": 2, "offload_optimizer": {"device": "cpu"}}})
    path, _ = e2.load_checkpoint(str(tmp_path))
    assert path is not None
    np.testing.assert_array_equal(e2._offload.m[0], m_before)
    assert e2._offload.count == 3
    # both continue identically
    m1 = e.train_batch(b)
    m2 = e2.train_batch(b)
    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-5)
