"""Every accepted config knob must act (or refuse loudly) — no decorative
fields. Covers the round-3 audit: consecutive_hysteresis, auto_cast,
prof_all/prof_ops, zero_allow_untested_optimizer, sparse_gradients,
dump_state, load_universal_checkpoint, data_efficiency curriculum."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu as ds
from deepspeed_tpu.models import build_gpt
from deepspeed_tpu.models.gpt import GPTConfig


def _tiny():
    return build_gpt(GPTConfig(vocab_size=64, d_model=32, n_layer=1,
                               n_head=2, max_seq_len=16))[0]


def _init(extra, **kw):
    return ds.initialize(model=_tiny(), config={
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "steps_per_print": 0,
        **extra,
    }, **kw)[0]


# ----------------------------------------------------------- loss-scaler knob
def test_consecutive_hysteresis_controls_refill():
    from deepspeed_tpu.runtime.precision import (
        PrecisionConfig, ScalerState, init_scaler_state, update_scaler)

    def pc(consecutive):
        return PrecisionConfig(
            compute_dtype=jnp.float16, master_weights=True, loss_scaling=True,
            hysteresis=3, consecutive_hysteresis=consecutive)

    for consecutive in (False, True):
        p = pc(consecutive)
        s = init_scaler_state(p)
        s = update_scaler(p, s, jnp.bool_(False))  # overflow: budget 3 -> 2
        assert int(s.hysteresis) == 2
        s = update_scaler(p, s, jnp.bool_(True))   # good step
        assert int(s.hysteresis) == (3 if consecutive else 2)


# ----------------------------------------------------------------- auto_cast
def test_fp16_auto_cast_casts_float_inputs():
    engine = _init({"fp16": {"enabled": True, "auto_cast": True},
                    "mesh": {"dp": 8}})
    placed = engine._place_batch({
        "input_ids": np.zeros((8, 16), np.int32),
        "emb": np.zeros((8, 16), np.float32)})
    assert placed["input_ids"].dtype == jnp.int32  # ints untouched
    assert placed["emb"].dtype == jnp.float16
    # without the knob, floats keep their dtype
    engine2 = _init({"fp16": {"enabled": True, "auto_cast": False},
                     "mesh": {"dp": 8}})
    assert engine2._place_batch(
        {"x": np.zeros((8, 4), np.float32)})["x"].dtype == jnp.float32


# -------------------------------------------------------------- comms filter
def test_prof_ops_filters_recorded_ops():
    from deepspeed_tpu.comm.comm import CommsLogger

    lg = CommsLogger(enabled=True, prof_all=False, prof_ops=["all_reduce"])
    lg.record("all_reduce[dp]", 100)
    lg.record("all_gather[tp]", 100)
    assert list(lg.records) == ["all_reduce[dp]"]
    lg2 = CommsLogger(enabled=True, prof_all=True, prof_ops=["all_reduce"])
    lg2.record("all_gather[tp]", 100)
    assert "all_gather[tp]" in lg2.records


# ------------------------------------------------- client optimizer under ZeRO
def test_zero_client_optimizer_requires_allow_flag():
    from deepspeed_tpu.ops.optimizers import get_optimizer

    opt = get_optimizer("Adam", {"lr": 1e-3})
    with pytest.raises(ValueError, match="zero_allow_untested_optimizer"):
        _init({"zero_optimization": {"stage": 2}, "mesh": {"dp": 8}},
              optimizer=opt)
    engine = _init({"zero_optimization": {"stage": 2}, "mesh": {"dp": 8},
                    "zero_allow_untested_optimizer": True},
                   optimizer=opt)
    m = engine.train_batch({"input_ids": np.zeros((8, 16), np.int32)})
    assert np.isfinite(float(m["loss"]))


# ----------------------------------------------------------- sparse gradients
def test_sparse_gradients_rejected_with_zero2():
    with pytest.raises(ValueError, match="sparse_gradients"):
        _init({"sparse_gradients": True, "zero_optimization": {"stage": 2},
               "mesh": {"dp": 8}})
    engine = _init({"sparse_gradients": True,
                    "zero_optimization": {"stage": 1}, "mesh": {"dp": 8}})
    assert engine.config.sparse_gradients


# ------------------------------------------------------------------ dump_state
def test_dump_state_prints_config(caplog, monkeypatch):
    import logging

    from deepspeed_tpu.utils.logging import logger as ds_logger

    monkeypatch.setattr(ds_logger, "propagate", True)  # let caplog see it
    with caplog.at_level(logging.INFO, logger=ds_logger.name):
        _init({"dump_state": True, "mesh": {"dp": 8}})
    assert any("config state dump" in r.message for r in caplog.records)


def test_load_universal_checkpoint_accessor():
    engine = _init({"load_universal_checkpoint": True, "mesh": {"dp": 8}})
    assert engine.load_universal_checkpoint() is True


# ---------------------------------------------------- data_efficiency schema
def test_data_efficiency_seqlen_curriculum_truncates():
    engine = _init({"mesh": {"dp": 8}, "data_efficiency": {
        "enabled": True,
        "data_sampling": {"enabled": True, "curriculum_learning": {
            "enabled": True,
            "curriculum_metrics": {"seqlen": {
                "min_difficulty": 4, "max_difficulty": 16,
                "schedule_type": "fixed_linear",
                "schedule_config": {"total_curriculum_step": 10,
                                    "difficulty_step": 4}}}}}}})
    assert engine.curriculum_scheduler is not None
    b = engine._apply_curriculum({"input_ids": np.zeros((8, 16), np.int32)})
    assert b["input_ids"].shape[-1] < 16  # early steps truncate


def test_data_efficiency_unknown_metric_refused():
    with pytest.raises(NotImplementedError, match="unsupported"):
        _init({"mesh": {"dp": 8}, "data_efficiency": {
            "enabled": True,
            "data_sampling": {"enabled": True, "curriculum_learning": {
                "enabled": True,
                "curriculum_metrics": {"vocabularyrarity": {
                    "min_difficulty": 1, "max_difficulty": 100}}}}}})


# ------------------------------------------------------ elastic batch resize
def test_set_train_batch_size_adjusts_gas():
    engine = _init({"mesh": {"dp": 8},
                    "gradient_accumulation_steps": 1})
    assert engine.gas == 1
    b2 = {"input_ids": np.zeros((2, 8, 16), np.int32)}  # [gas, batch, T]
    engine.set_train_batch_size(16)  # micro 1 x dp 8 x gas 2
    assert engine.gas == 2
    m = engine.train_batch(b2)
    assert np.isfinite(float(m["loss"]))
    with pytest.raises(ValueError, match="divisible"):
        engine.set_train_batch_size(12)


def test_hysteresis_refills_at_scale_growth():
    """Default (non-consecutive) hysteresis refills when the scale grows, so
    isolated overflows far apart never permanently strip the protection."""
    from deepspeed_tpu.runtime.precision import (
        PrecisionConfig, init_scaler_state, update_scaler)

    p = PrecisionConfig(compute_dtype=jnp.float16, master_weights=True,
                        loss_scaling=True, hysteresis=2, scale_window=3)
    s = init_scaler_state(p)
    s = update_scaler(p, s, jnp.bool_(False))   # deplete: 2 -> 1
    assert int(s.hysteresis) == 1
    for _ in range(3):                          # ride to a growth boundary
        s = update_scaler(p, s, jnp.bool_(True))
    assert int(s.hysteresis) == 2               # refilled at growth
