"""In-run health acceptance suite (ISSUE 8; docs/RESILIENCE.md "In-run
health"): the three self-healing pillars proven against injected faults.

1. Numerical sentinels: an injected NaN at a known data cursor triggers
   automatic rollback to the newest committed checkpoint plus a
   deterministic skip of the poisoned cursor, and the loss trajectory
   rejoins the clean run.
2. Hang watchdog: an injected collective stall is detected within the
   configured deadline, dumps stacks, and escalates through the drain path
   to a COMMITTED emergency save.
3. Graceful degradation: forced error-feedback overflows demote the
   quantized gradient exchange to the fp32 wire (visible in
   ``comms_summary``), and a clean window re-promotes it; failed monitor
   and checkpoint I/O buffer in memory instead of killing the step.
"""

import math
import os
import time

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.comm.runtime_accounting import wire_ledger
from deepspeed_tpu.models import GPTConfig, build_gpt
from deepspeed_tpu.resilience import (
    FaultPlan,
    PREEMPTED_EXIT_CODE,
    STACKS_FILENAME,
    SpikeDetector,
    committed_tags,
    identify_stragglers,
    install_plan,
    read_events,
)

TINY = GPTConfig(vocab_size=64, n_layer=2, n_head=2, d_model=32, max_seq_len=32)


@pytest.fixture(autouse=True)
def _clear_fault_plan():
    yield
    install_plan(None)


def make_engine(save_dir, *, sentinel=None, watchdog=None, degraded=None,
                zero=None, extra=None):
    model, _ = build_gpt(TINY)
    res = {"enabled": True, "save_dir": str(save_dir),
           "install_signal_handlers": False}
    if sentinel is not None:
        res["sentinel"] = sentinel
    if watchdog is not None:
        res["watchdog"] = watchdog
    if degraded is not None:
        res["degraded"] = degraded
    cfg = {
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "bf16": {"enabled": False},
        "steps_per_print": 0,
        "mesh": {"dp": 8},
        "resilience": res,
    }
    if zero is not None:
        cfg["zero_optimization"] = zero
    cfg.update(extra or {})
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=cfg)
    return engine


def batch_for(cursor: int):
    r = np.random.default_rng(1000 + cursor)
    return {"input_ids": r.integers(0, 64, size=(8, 16), dtype=np.int32)}


def drive(engine, steps: int):
    """Cursor-driven training loop (the contract sentinel rollback assumes);
    returns {step: loss} of executed (non-skipped, non-rolled-back) steps."""
    losses = {}
    while engine.global_steps < steps:
        m = engine.train_batch(batch_for(engine.data_cursor))
        if m.get("skipped_batch") or m.get("health", {}).get("rolled_back"):
            continue
        losses[engine.global_steps] = float(m["loss"])
    return losses


# ------------------------------------------------------------ spike detector
def test_spike_detector_fires_on_nan_and_spike_only():
    det = SpikeDetector(zscore=4.0, beta=0.9, warmup=5, min_rel=0.1)
    # warmup + stable stream: no detection, statistics build
    for i in range(20):
        assert det.update(4.0 + 0.01 * ((-1) ** i)) is None
    mean_before = det.mean
    # ordinary wobble on a flat curve: huge z (variance collapsed) but under
    # the relative floor -> calm
    assert det.update(4.03) is None
    # a real spike: both sigma and relative floor exceeded
    reason = det.update(8.0)
    assert reason is not None and "spike" in reason
    # the spike was NOT absorbed into the EMA baseline
    assert det.mean < 4.1 and abs(det.mean - mean_before) < 0.1
    # non-finite fires immediately, even during warmup
    fresh = SpikeDetector(warmup=100)
    assert "non-finite" in fresh.update(float("nan"))
    assert "non-finite" in fresh.update(float("inf"))


def test_spike_detector_warmup_gates_spikes():
    det = SpikeDetector(zscore=2.0, warmup=10, min_rel=0.0)
    assert det.update(1.0) is None
    assert det.update(100.0) is None  # count=1 < warmup: spike not judged


# ------------------------------------------------- pillar 1: NaN -> rollback
@pytest.mark.slow
def test_nan_rollback_skips_poison_and_rejoins(tmp_path):
    """Acceptance: injected NaN at data cursor 4 -> auto-rollback + cursor
    skip; the healed trajectory rejoins the clean run's loss level."""
    clean = drive(make_engine(tmp_path / "clean"), steps=8)

    engine = make_engine(
        tmp_path / "chaos",
        sentinel={"enabled": True, "warmup_steps": 1,
                  "checkpoint_interval": 1, "cursor_checkpointable": True})
    install_plan(FaultPlan.from_dict({"nan_at_step": 4}))
    healed = drive(engine, steps=8)
    install_plan(None)

    h = engine._health
    assert h.rollbacks == 1
    assert h.skipped_cursors == [4]          # exactly the poison, nothing else
    assert engine.data_cursor == 9           # 8 stepped + 1 skipped
    events = {e["event"] for e in read_events(str(tmp_path / "chaos"))}
    assert {"divergence_rollback", "poison_skip"} <= events
    rb = [e for e in read_events(str(tmp_path / "chaos"))
          if e["event"] == "divergence_rollback"][0]
    assert rb["skip_cursors"] == [4] and rb["from_step"] == 5
    # rejoin: every loss after the heal is finite, and the final level
    # matches the clean run within a small tolerance (the healed run trained
    # on one fewer batch, so bitwise equality is impossible by construction)
    assert all(math.isfinite(v) for v in healed.values())
    assert abs(healed[8] - clean[8]) < 0.05 * abs(clean[8])


def test_rollback_budget_exhaustion_raises(tmp_path):
    """A poison the skip cannot clear (sentinel armed but skipping disabled)
    must fail LOUDLY once the budget is spent, not thrash forever."""
    from deepspeed_tpu.resilience import DivergenceError

    engine = make_engine(
        tmp_path,
        sentinel={"enabled": True, "warmup_steps": 1, "max_rollbacks": 2,
                  "checkpoint_interval": 1, "skip_poisoned_batches": False,
                  "cursor_checkpointable": True})
    engine.train_batch(batch_for(engine.data_cursor))
    install_plan(FaultPlan.from_dict({"nan_at_step": 1}))
    with pytest.raises(DivergenceError, match="budget"):
        for _ in range(6):
            engine.train_batch(batch_for(engine.data_cursor))
    assert engine._health.rollbacks == 2


# ----------------------------------------------- pillar 2: stall -> watchdog
def test_stall_detected_within_deadline_and_emergency_save(tmp_path):
    """Acceptance: an injected collective stall is detected within the
    watchdog deadline, dumps stacks, and escalates through the drain path to
    a committed emergency save + preemption exit."""
    engine = make_engine(
        tmp_path,
        watchdog={"enabled": True, "poll_interval_s": 0.05,
                  "collective_deadline_s": 0.3})
    engine.train_batch(batch_for(0))
    install_plan(FaultPlan.from_dict(
        {"stall_collective": 1.2, "stall_collective_at_step": 1}))
    t0 = time.monotonic()
    with pytest.raises(SystemExit) as exc:
        engine.train_batch(batch_for(1))
    assert exc.value.code == PREEMPTED_EXIT_CODE
    assert engine._watchdog.stall_count == 1
    phase, elapsed = engine._watchdog.last_stall
    assert phase == "collective"
    assert elapsed < 1.0  # detected within the deadline, not at stall end
    assert time.monotonic() - t0 < 30
    # the escalation produced a COMMITTED emergency save
    tags = committed_tags(str(tmp_path))
    assert tags, "no committed emergency checkpoint"
    events = {e["event"] for e in read_events(str(tmp_path))}
    assert {"watchdog_stall", "watchdog_recovered", "emergency_save"} <= events
    stall = [e for e in read_events(str(tmp_path))
             if e["event"] == "watchdog_stall"][0]
    assert stall["phase"] == "collective"
    # the stack dump exists and names this test's frames
    stacks = (tmp_path / STACKS_FILENAME).read_text()
    assert "watchdog stall: phase=collective" in stacks
    assert "train_batch" in stacks
    engine._watchdog.stop()


def test_watchdog_quiet_on_healthy_run(tmp_path):
    engine = make_engine(
        tmp_path,
        watchdog={"enabled": True, "poll_interval_s": 0.05,
                  "step_deadline_s": 120.0, "collective_deadline_s": 120.0})
    for _ in range(2):
        engine.train_batch(batch_for(engine.data_cursor))
    time.sleep(0.2)  # several poll cycles with no phase active
    assert engine._watchdog.stall_count == 0
    engine._watchdog.stop()


def test_identify_stragglers_pure():
    assert identify_stragglers([10.0, 10.5, 31.0, 9.8], factor=2.0) == [2]
    assert identify_stragglers([10.0, 10.5, 11.0, 9.8], factor=2.0) == []
    # 2-host pod: the lower median makes the slow host detectable (the
    # upper median would be the straggler's own duration — never flaggable)
    assert identify_stragglers([1.0, 30.0], factor=2.0) == [1]
    # half-sick even pod: both slow hosts flagged, not hidden by each other
    assert identify_stragglers([1.0, 1.1, 10.0, 10.5], factor=2.0) == [2, 3]
    # tiny steps: 2x of nothing is noise, the absolute floor keeps it quiet
    assert identify_stragglers([0.01, 0.025, 0.012], factor=2.0) == []
    assert identify_stragglers([5.0]) == []  # single host: nothing to compare


# --------------------------------------- pillar 3: overflow -> wire demotion
@pytest.mark.slow
def test_ef_overflow_demotes_then_repromotes(tmp_path):
    """Acceptance: repeated forced EF overflows demote the quantized
    gradient exchange to the fp32 wire (recorded in comms_summary); a clean
    window re-promotes it and the quantized wire records traffic again."""
    wire_ledger.reset()
    engine = make_engine(
        tmp_path,
        zero={"stage": 2, "zero_quantized_gradients": True,
              "zero_quantize_error_feedback": True},
        degraded={"demote_after": 2, "repromote_after": 3})
    engine.train_batch(batch_for(0))
    assert not engine._qgrad_demoted

    install_plan(FaultPlan.from_dict({"ef_overflow_steps": 2}))
    engine.train_batch(batch_for(1))
    assert not engine._qgrad_demoted  # one overflow is weather, not climate
    m = engine.train_batch(batch_for(2))
    install_plan(None)
    assert engine._qgrad_demoted
    assert m["health"]["wire"] == "demoted"
    assert wire_ledger.demoted_ops() == ["qgrad"]
    summary = engine.comms_summary()
    assert "degraded wire: qgrad -> full-precision" in summary
    assert "STILL DEMOTED" in summary

    # overflow micro-steps are visible in the run record (satellite: no
    # silent skips)
    events = [e["event"] for e in read_events(str(tmp_path))]
    assert events.count("overflow_skip") == 2
    assert "wire_demoted" in events

    qgrad_traces = wire_ledger.records["qgrad_reduce_scatter[dp]"].count
    for c in (3, 4):
        engine.train_batch(batch_for(c))
        assert engine._qgrad_demoted  # clean window not yet complete
    m = engine.train_batch(batch_for(5))
    assert not engine._qgrad_demoted
    assert m["health"]["wire"] == "repromoted"
    # EF residuals were reset for the fresh quantized start
    assert float(np.abs(np.asarray(engine.state["qgrad_residual"])).max()) == 0
    engine.train_batch(batch_for(6))
    # the re-promotion retraced the quantized exchange: new ledger records
    assert wire_ledger.records["qgrad_reduce_scatter[dp]"].count > qgrad_traces
    summary = engine.comms_summary()
    assert "re-promoted at step" in summary
    assert "wire_repromoted" in [e["event"] for e in read_events(str(tmp_path))]
    wire_ledger.reset()


# ------------------------------------------------ degradation: monitor + ckpt
def test_monitor_degrades_to_memory_buffer_and_reflushes():
    from deepspeed_tpu.monitor.monitor import MonitorMaster, _SafeBackend
    from deepspeed_tpu.runtime.config import MonitorConfig

    sunk, fail = [], {"on": True}

    class Flaky:
        def write_events(self, events):
            if fail["on"]:
                raise OSError("disk full")
            sunk.extend(events)

    mm = MonitorMaster(MonitorConfig(), extra_backends=[Flaky()])
    mm.write_events([("Train/loss", 1.0, 1)])  # must not raise
    mm.write_events([("Train/loss", 2.0, 2)])
    assert mm.degraded and sunk == []
    fail["on"] = False
    mm.write_events([("Train/loss", 3.0, 3)])
    assert not mm.degraded
    # buffered events flushed in order, nothing lost
    assert [e[1] for e in sunk] == [1.0, 2.0, 3.0]

    # bounded buffer: oldest events drop first
    sb = _SafeBackend(Flaky(), buffer_limit=2)
    fail["on"] = True
    for i in range(5):
        sb.write_events([("x", float(i), i)])
    assert len(sb._buffer) == 2 and sb.dropped == 3
    assert [e[1] for e in sb._buffer] == [3.0, 4.0]


def test_checkpoint_io_degrades_to_memory_anchor(tmp_path, monkeypatch):
    """Periodic-save I/O failure must not kill the step: the anchor degrades
    to the in-memory snapshot, and a later divergence still heals from it."""
    engine = make_engine(
        tmp_path,
        sentinel={"enabled": True, "warmup_steps": 1,
                  "checkpoint_interval": 1, "cursor_checkpointable": True})

    def broken_save(save_dir, *a, **k):
        raise OSError("filesystem went away")

    monkeypatch.setattr(engine, "save_checkpoint", broken_save)
    engine.train_batch(batch_for(0))  # auto-save fails -> degraded, no raise
    engine.train_batch(batch_for(1))
    h = engine._health
    assert h.checkpoint_io_degraded
    assert h._memory_snapshot is not None
    events = [e["event"] for e in read_events(str(tmp_path))]
    assert "checkpoint_io_degraded" in events

    install_plan(FaultPlan.from_dict({"nan_at_step": 2}))
    m = engine.train_batch(batch_for(2))
    install_plan(None)
    rb = m["health"]["rolled_back"]
    assert rb["source"] == "memory"  # no committed tag exists on disk
    # healed from the memory anchor: training continues
    m = engine.train_batch(batch_for(engine.data_cursor))
    assert m.get("skipped_batch")  # the poisoned cursor is consumed first
    m = engine.train_batch(batch_for(engine.data_cursor))
    assert math.isfinite(float(m["loss"]))


# ------------------------------------------------------------ config guards
def test_sentinel_requires_resilience_block():
    model, _ = build_gpt(TINY)
    with pytest.raises(Exception, match="resilience.sentinel"):
        deepspeed_tpu.initialize(model=model, config={
            "train_micro_batch_size_per_gpu": 1,
            "mesh": {"dp": 8},
            "resilience": {"sentinel": {"enabled": True}},
        })


def test_overflow_skip_event_without_resilience_block(tmp_path):
    """The Resilience/overflow_skip scalar reaches the monitor even when the
    resilience block (and its recovery log) is off."""
    from deepspeed_tpu.monitor.monitor import CallbackMonitor, MonitorMaster
    from deepspeed_tpu.runtime.config import MonitorConfig

    model, _ = build_gpt(TINY)
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config={
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "bf16": {"enabled": False},
        "mesh": {"dp": 8},
        "steps_per_print": 0,
    })
    events = []
    engine._monitor = MonitorMaster(
        MonitorConfig(), extra_backends=[CallbackMonitor(events.extend)])
    install_plan(FaultPlan.from_dict({"ef_overflow_steps": 1}))
    engine.train_batch(batch_for(0))
    install_plan(None)
    assert ("Resilience/overflow_skip", 1.0, 1) in events
    assert engine.skipped_steps == 1
