"""Data efficiency: curriculum scheduler, data sampler, random-LTD."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.runtime.data_pipeline import (
    CurriculumScheduler,
    DeepSpeedDataSampler,
    RandomLTDScheduler,
    random_ltd_gather,
    random_ltd_scatter,
)
from deepspeed_tpu.runtime.data_pipeline.data_routing.random_ltd import random_ltd_layer


# ------------------------------------------------------------------- curriculum
def test_fixed_linear_schedule():
    s = CurriculumScheduler({
        "curriculum_type": "seqlen", "min_difficulty": 8, "max_difficulty": 64,
        "schedule_type": "fixed_linear",
        "schedule_config": {"total_curriculum_step": 100, "difficulty_step": 8}})
    assert s.get_difficulty(0) == 8
    assert s.get_difficulty(50) == 8 + (64 - 8) // 2 // 8 * 8  # quantized midpoint
    assert s.get_difficulty(100) == 64
    assert s.get_difficulty(10_000) == 64
    # monotone non-decreasing
    vals = [s.get_difficulty(t) for t in range(0, 120, 5)]
    assert all(a <= b for a, b in zip(vals, vals[1:]))


def test_fixed_root_schedule_grows_faster_early():
    lin = CurriculumScheduler({
        "min_difficulty": 8, "max_difficulty": 512,
        "schedule_type": "fixed_linear",
        "schedule_config": {"total_curriculum_step": 1000, "difficulty_step": 8}})
    root = CurriculumScheduler({
        "min_difficulty": 8, "max_difficulty": 512,
        "schedule_type": "fixed_root",
        "schedule_config": {"total_curriculum_step": 1000, "difficulty_step": 8,
                            "root_degree": 2}})
    assert root.get_difficulty(100) > lin.get_difficulty(100)
    assert root.get_difficulty(1000) == lin.get_difficulty(1000) == 512


def test_fixed_discrete_schedule():
    s = CurriculumScheduler({
        "min_difficulty": 8, "max_difficulty": 64,
        "schedule_type": "fixed_discrete",
        "schedule_config": {"difficulty": [8, 32, 64], "max_step": [10, 20]}})
    assert s.get_difficulty(5) == 8
    assert s.get_difficulty(15) == 32
    assert s.get_difficulty(25) == 64


def test_scheduler_state_roundtrip():
    s = CurriculumScheduler({
        "min_difficulty": 8, "max_difficulty": 64,
        "schedule_type": "fixed_linear",
        "schedule_config": {"total_curriculum_step": 100, "difficulty_step": 8}})
    s.update_difficulty(57)
    sd = s.state_dict()
    s2 = CurriculumScheduler({
        "min_difficulty": 8, "max_difficulty": 64,
        "schedule_type": "fixed_linear",
        "schedule_config": {"total_curriculum_step": 100, "difficulty_step": 8}})
    s2.load_state_dict(sd)
    assert s2.get_current_difficulty() == s.get_current_difficulty()


# ------------------------------------------------------------------- sampler
def test_sampler_partitions_ranks_disjointly():
    batches = {}
    for rank in range(2):
        s = DeepSpeedDataSampler(
            total_samples=64, micro_batch_size=4,
            data_parallel_rank=rank, data_parallel_size=2, seed=7)
        batches[rank] = list(s)
    assert len(batches[0]) == len(batches[1]) == 8
    for b0, b1 in zip(batches[0], batches[1]):
        assert set(b0).isdisjoint(b1)
    seen = set().union(*[set(b) for b in batches[0] + batches[1]])
    assert seen == set(range(64))  # full epoch coverage


def test_sampler_deterministic_and_resumable():
    s1 = DeepSpeedDataSampler(total_samples=32, micro_batch_size=4, seed=3)
    all1 = list(s1)
    s2 = DeepSpeedDataSampler(total_samples=32, micro_batch_size=4, seed=3)
    # consume 3 batches, checkpoint, resume
    it = iter(s2)
    first3 = [next(it) for _ in range(3)]
    sd = s2.state_dict()
    s3 = DeepSpeedDataSampler(total_samples=32, micro_batch_size=4, seed=3)
    s3.load_state_dict(sd)
    rest = list(s3)
    assert first3 + rest == all1


def test_sampler_curriculum_gates_difficulty():
    sched = CurriculumScheduler({
        "min_difficulty": 10, "max_difficulty": 100,
        "schedule_type": "fixed_linear",
        "schedule_config": {"total_curriculum_step": 10, "difficulty_step": 10}})
    step = {"n": 0}
    s = DeepSpeedDataSampler(
        total_samples=50, micro_batch_size=4, seed=1,
        curriculum_scheduler=sched, difficulty_fn=lambda i: i,
        global_steps_fn=lambda: step["n"])
    it = iter(s)
    b = next(it)
    assert all(i <= 10 for i in b)  # early: only easy samples
    step["n"] = 10
    hard_seen = any(any(i > 10 for i in next(it)) for _ in range(5))
    assert hard_seen  # after the ramp, hard samples flow


def test_sampler_curriculum_resume_no_duplicates():
    """Gated consumption is out of permutation order; resume must not repeat
    consumed samples nor drop deferred ones."""
    def make(step_box):
        sched = CurriculumScheduler({
            "min_difficulty": 20, "max_difficulty": 100,
            "schedule_type": "fixed_linear",
            "schedule_config": {"total_curriculum_step": 4, "difficulty_step": 20}})
        return DeepSpeedDataSampler(
            total_samples=40, micro_batch_size=4, seed=5,
            curriculum_scheduler=sched, difficulty_fn=lambda i: i,
            global_steps_fn=lambda: step_box["n"])

    step = {"n": 0}
    s = make(step)
    it = iter(s)
    consumed = []
    for _ in range(3):
        consumed += next(it)
        step["n"] += 1
    sd = s.state_dict()

    step2 = {"n": step["n"]}
    s2 = make(step2)
    s2.load_state_dict(sd)
    rest = []
    for b in s2:
        rest += b
        step2["n"] += 1
    # no duplicates across the resume point, full epoch coverage
    assert set(consumed).isdisjoint(rest)
    assert len(consumed + rest) == len(set(consumed + rest))
    assert set(consumed + rest) == set(range(40))


# ------------------------------------------------------------------- random-ltd
def test_random_ltd_gather_scatter_roundtrip(rng):
    x = jnp.asarray(rng.normal(size=(2, 16, 8)), jnp.float32)
    kept, idx = random_ltd_gather(x, 6, jax.random.PRNGKey(0))
    assert kept.shape == (2, 6, 8)
    assert (np.diff(np.asarray(idx), axis=1) > 0).all()  # sorted order kept
    out = random_ltd_scatter(kept, idx, x)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))  # identity layer


def test_random_ltd_layer_passthrough(rng):
    x = jnp.asarray(rng.normal(size=(2, 16, 8)), jnp.float32)
    double = lambda t: t * 2.0
    out = random_ltd_layer(double, x, 6, jax.random.PRNGKey(1))
    doubled = np.isclose(np.asarray(out), 2 * np.asarray(x)).all(axis=-1)
    untouched = np.isclose(np.asarray(out), np.asarray(x)).all(axis=-1)
    assert doubled.sum() == 2 * 6  # exactly keep tokens per row doubled
    assert (doubled | untouched).all()
    # keep >= T: whole layer applies
    out_full = random_ltd_layer(double, x, 16, jax.random.PRNGKey(1))
    np.testing.assert_allclose(np.asarray(out_full), 2 * np.asarray(x))


def test_random_ltd_scheduler_ramps():
    s = RandomLTDScheduler({
        "random_ltd_schedule": {
            "min_value": 64, "max_value": 256,
            "schedule_config": {"seq_per_step": 32, "require_steps": 100}}})
    assert s.get_value(0) == 64
    assert s.get_value(100) == 256
    vals = [s.get_value(t) for t in range(0, 120, 10)]
    assert all(a <= b for a, b in zip(vals, vals[1:]))
    assert all(v % 32 == 0 for v in vals)


# ------------------------------------------------------------------- engine hook
@pytest.mark.slow
def test_engine_curriculum_truncates_seqlen():
    from deepspeed_tpu.models import build_gpt
    from deepspeed_tpu.models.gpt import GPTConfig

    model, cfg = build_gpt(GPTConfig(
        vocab_size=64, d_model=32, n_layer=1, n_head=2, max_seq_len=32))
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model,
        config={
            "train_micro_batch_size_per_gpu": 1,
            "curriculum_learning": {
                "enabled": True, "curriculum_type": "seqlen",
                "min_difficulty": 8, "max_difficulty": 32,
                "schedule_type": "fixed_linear",
                "schedule_config": {"total_curriculum_step": 4, "difficulty_step": 8},
            },
            "steps_per_print": 0,
        })
    r = np.random.default_rng(0)
    batch = {"input_ids": r.integers(0, 64, size=(8, 32), dtype=np.int32)}
    m = engine.train_batch(batch)
    assert np.isfinite(float(m["loss"]))
    assert engine.curriculum_scheduler.get_current_difficulty() == 8
    for _ in range(4):
        m = engine.train_batch(batch)
    assert engine.curriculum_scheduler.get_current_difficulty() == 32


# ----------------------------------------------------------- data analyzer
def test_data_analyzer_shards_merge_and_feed_curriculum(tmp_path, rng):
    """Parity: data_sampling/data_analyzer.py + indexed_dataset.py — sharded
    analysis, merged indexed store, consumed by the curriculum sampler."""
    from deepspeed_tpu.runtime.data_pipeline import (
        CurriculumScheduler,
        DataAnalyzer,
        DeepSpeedDataSampler,
        IndexedMetricStore,
        seqlen_metric,
    )

    lengths = rng.integers(4, 33, size=23)
    dataset = [{"input_ids": np.zeros(l, np.int32)} for l in lengths]

    out = str(tmp_path / "analysis")
    for w in range(3):  # 3 analysis workers over 23 samples
        DataAnalyzer({"seqlen": seqlen_metric}, worker_id=w,
                     num_workers=3).run(dataset, out)
    store = DataAnalyzer.merge(out)
    assert store.num_samples == 23 and store.metrics == ["seqlen"]
    np.testing.assert_array_equal(np.asarray(store.values("seqlen")),
                                  lengths.astype(np.float32))

    # random access without loading (mmap) + bucket map
    buckets = store.buckets("seqlen", edges=[16])
    assert sorted(np.concatenate(list(buckets.values()))) == list(range(23))
    assert all(lengths[i] < 16 for i in buckets[0])

    # incomplete merges fail loudly
    import os

    os.remove(str(tmp_path / "analysis" / "shard1.json"))
    with pytest.raises(ValueError, match="incomplete"):
        DataAnalyzer.merge(out)

    # the store drives curriculum sampling (difficulty gate = stored metric)
    sched = CurriculumScheduler({
        "enabled": True, "curriculum_type": "seqlen",
        "min_difficulty": 8, "max_difficulty": 33,
        "schedule_type": "fixed_linear",
        "schedule_config": {"total_curriculum_step": 10, "difficulty_step": 1}})
    step = {"n": 1}
    sampler = DeepSpeedDataSampler(
        total_samples=23, micro_batch_size=4,
        curriculum_scheduler=sched,
        difficulty_fn=store.difficulty_fn("seqlen"),
        global_steps_fn=lambda: step["n"])
    level = sched.update_difficulty(step["n"])
    batch = next(iter(sampler))
    assert level < 33  # curriculum still ramping at step 1
    assert all(lengths[i] <= level for i in batch)  # only easy-enough samples


# ---------------------------------------------------- model/engine integration
@pytest.mark.slow
def test_gpt_random_ltd_layers_drop_tokens(rng):
    import dataclasses

    from deepspeed_tpu.models.gpt import GPTConfig, init_params, loss_fn

    base = GPTConfig(vocab_size=64, d_model=32, n_layer=3, n_head=2,
                     max_seq_len=32)
    params = init_params(base, jax.random.PRNGKey(0))
    batch = {"input_ids": np.random.default_rng(0).integers(
        0, 64, (2, 32), np.int32)}
    dense, _ = loss_fn(base, params, batch, train=True)
    ltd_cfg = dataclasses.replace(base, random_ltd_layer_ids=(1,),
                                  random_ltd_keep=16)
    ltd, _ = loss_fn(ltd_cfg, params, batch, train=True)
    assert np.isfinite(float(ltd))
    assert abs(float(ltd) - float(dense)) > 1e-7  # layer 1 saw fewer tokens
    # eval path ignores LTD entirely
    e1, _ = loss_fn(base, params, batch, train=False)
    e2, _ = loss_fn(ltd_cfg, params, batch, train=False)
    np.testing.assert_allclose(float(e1), float(e2), rtol=1e-6)
    # gradients flow through the gather/scatter
    g = jax.grad(lambda p: loss_fn(ltd_cfg, p, batch, train=True)[0])(params)
    gsum = float(jax.tree_util.tree_reduce(
        lambda a, b: a + jnp.abs(b).sum(), g, jnp.float32(0.0)))
    assert np.isfinite(gsum) and gsum > 0


@pytest.mark.slow
def test_engine_random_ltd_schedule_rebuilds_buckets():
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import build_gpt
    from deepspeed_tpu.models.gpt import GPTConfig

    model, _ = build_gpt(GPTConfig(vocab_size=64, d_model=32, n_layer=3,
                                   n_head=2, max_seq_len=32))
    engine, _, _, _ = ds.initialize(model=model, config={
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "mesh": {"dp": 8},
        "steps_per_print": 0,
        "data_efficiency": {
            "enabled": True,
            "data_routing": {"enabled": True, "random_ltd": {
                "enabled": True,
                "total_layer_num": 3, "random_ltd_layer_num": 1,
                "random_ltd_schedule": {
                    "min_value": 16, "max_value": 32,
                    "schedule_type": "fixed_linear",
                    "schedule_config": {"seq_per_step": 8,
                                        "require_steps": 4}}}}},
    })
    b = {"input_ids": np.random.default_rng(0).integers(
        0, 64, (8, 32), np.int32)}
    keeps = []
    for _ in range(6):
        m = engine.train_batch(b)
        assert np.isfinite(float(m["loss"]))
        keeps.append(engine._ltd_keep)
    assert keeps[0] == 16 and keeps[-1] == 32  # schedule walked the buckets
    assert engine._random_ltd.layer_ids == [1]  # sandwich default


def test_random_ltd_refuses_inert_and_runner_configs():
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import build_gpt
    from deepspeed_tpu.models.gpt import GPTConfig

    def make(extra_rl=None, extra_cfg=None):
        model, _ = build_gpt(GPTConfig(vocab_size=64, d_model=32, n_layer=3,
                                       n_head=2, max_seq_len=32))
        rl = {"enabled": True,
              "random_ltd_schedule": {"min_value": 16, "max_value": 32,
                                      "schedule_config": {"seq_per_step": 8,
                                                          "require_steps": 4}}}
        rl.update(extra_rl or {})
        return ds.initialize(model=model, config={
            "train_micro_batch_size_per_gpu": 1,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "mesh": {"dp": 8}, "steps_per_print": 0,
            "data_efficiency": {"enabled": True,
                                "data_routing": {"enabled": True,
                                                 "random_ltd": rl}},
            **(extra_cfg or {})})

    with pytest.raises(ValueError, match="ZERO layers"):
        make()  # no layer_num/layer_id -> inert; refuse
    with pytest.raises(ValueError, match="ZeRO-Offload"):
        make(extra_rl={"random_ltd_layer_num": 1, "total_layer_num": 3},
             extra_cfg={"zero_optimization": {
                 "stage": 2, "offload_optimizer": {"device": "cpu"}}})


def test_mmap_indexed_dataset_roundtrip(tmp_path, rng):
    """Variable-length mmap store (parity: indexed_dataset.py:381): random
    access without loading, zero rows allowed, builder merge."""
    from deepspeed_tpu.runtime.data_pipeline import (
        MMapIndexedDataset, MMapIndexedDatasetBuilder)

    rows = [rng.integers(0, 1000, size=n).astype(np.int32)
            for n in (5, 0, 3, 128, 1)]
    b = MMapIndexedDatasetBuilder(str(tmp_path / "a"), dtype=np.int32)
    for r in rows:
        b.add_item(r)
    ds = b.finalize()
    assert len(ds) == len(rows)
    for i, r in enumerate(rows):
        assert ds.size(i) == r.size and ds.num_tokens(i) == r.size
        np.testing.assert_array_equal(np.asarray(ds[i]), r)
    with pytest.raises(IndexError):
        ds[len(rows)]
    # reopen from disk (a fresh process would do the same)
    ds2 = MMapIndexedDataset(str(tmp_path / "a"))
    np.testing.assert_array_equal(np.asarray(ds2[3]), rows[3])
    # merge_file_: second store appended row-for-row
    b2 = MMapIndexedDatasetBuilder(str(tmp_path / "b"), dtype=np.int32)
    b2.add_item([7, 8])
    b2.merge_file_(str(tmp_path / "a"))
    merged = b2.finalize()
    assert len(merged) == 1 + len(rows)
    np.testing.assert_array_equal(np.asarray(merged[0]), [7, 8])
    np.testing.assert_array_equal(np.asarray(merged[4]), rows[3])


def test_metric_to_sample_inverted_index(tmp_path):
    """Row v of the inverted store = sample ids with metric value v
    (parity: data_analyzer.py:291 merge_metric_to_sample)."""
    from deepspeed_tpu.runtime.data_pipeline import build_metric_to_sample

    vals = np.asarray([3, 1, 3, 0, 1, 1], np.float32)
    ds = build_metric_to_sample(vals, str(tmp_path / "m2s"))
    assert len(ds) == 4  # values 0..3
    np.testing.assert_array_equal(np.asarray(ds[0]), [3])
    np.testing.assert_array_equal(np.asarray(ds[1]), [1, 4, 5])
    np.testing.assert_array_equal(np.asarray(ds[2]), [])
    np.testing.assert_array_equal(np.asarray(ds[3]), [0, 2])
    with pytest.raises(ValueError, match="integer-valued"):
        build_metric_to_sample(np.asarray([0.5]), str(tmp_path / "bad"))


def test_analyzer_merge_builds_inverted_and_percentiles(tmp_path):
    """merge(build_inverted=True) writes <metric>_to_sample; the store
    exposes percentile summaries (parity: get_metric_value_percentiles)."""
    from deepspeed_tpu.runtime.data_pipeline import DataAnalyzer

    data = [{"input_ids": np.zeros(n, np.int32)}
            for n in (4, 8, 4, 16, 8, 8, 2, 4)]
    out = str(tmp_path / "store")
    for w in range(2):
        DataAnalyzer(worker_id=w, num_workers=2).run(data, out)
    store = DataAnalyzer.merge(out, build_inverted=True)
    pct = store.value_percentiles("seqlen", (0, 50, 100))
    assert pct[0.0] == 2 and pct[100.0] == 16
    inv = store.metric_to_sample("seqlen")
    np.testing.assert_array_equal(np.asarray(inv[4]), [0, 2, 7])
    np.testing.assert_array_equal(np.asarray(inv[8]), [1, 4, 5])
    assert inv.size(16) == 1 and inv.size(3) == 0


def test_mmap_indexed_dataset_edge_cases(tmp_path):
    """Empty stores are valid; mixed-dtype merge is refused (the reference's
    builder asserts dtype equality for the same pointer-math reason)."""
    from deepspeed_tpu.runtime.data_pipeline import (
        MMapIndexedDataset, MMapIndexedDatasetBuilder, build_metric_to_sample)

    empty = build_metric_to_sample(np.asarray([]), str(tmp_path / "empty"))
    assert len(empty) == 0
    b = MMapIndexedDatasetBuilder(str(tmp_path / "allempty"), np.int32)
    b.add_item([])
    b.add_item([])
    ds = b.finalize()
    assert len(ds) == 2 and ds.size(0) == 0
    np.testing.assert_array_equal(np.asarray(ds[1]), [])

    b64 = MMapIndexedDatasetBuilder(str(tmp_path / "i64"), np.int64)
    b64.add_item([1, 2, 3])
    b64.finalize()
    b32 = MMapIndexedDatasetBuilder(str(tmp_path / "i32"), np.int32)
    with pytest.raises(ValueError, match="dtype mismatch"):
        b32.merge_file_(str(tmp_path / "i64"))


def test_merge_skips_uninvertible_metrics(tmp_path):
    """A negative-sentinel integer metric must not abort the merge; it is
    simply not inverted."""
    from deepspeed_tpu.runtime.data_pipeline import (
        DataAnalyzer, MMapIndexedDataset)

    data = [{"input_ids": np.zeros(4, np.int32)} for _ in range(4)]
    out = str(tmp_path / "neg")
    DataAnalyzer({"score": lambda s: -1.0, "seqlen":
                  lambda s: float(len(s["input_ids"]))}).run(data, out)
    store = DataAnalyzer.merge(out, build_inverted=True)
    assert not MMapIndexedDataset.exists(
        str(tmp_path / "neg" / "score_to_sample"))
    assert store.metric_to_sample("seqlen").size(4) == 4


def test_merge_caps_idlike_metric_inversion(tmp_path):
    """An id-like integer metric (huge max) must not explode the merge into
    a dense O(max_value) inverted store."""
    from deepspeed_tpu.runtime.data_pipeline import (
        DataAnalyzer, MMapIndexedDataset)

    data = [{"input_ids": np.zeros(4, np.int32)} for _ in range(3)]
    out = str(tmp_path / "ids")
    DataAnalyzer({"sample_id": lambda s: 1e8,
                  "seqlen": lambda s: 4.0}).run(data, out)
    DataAnalyzer.merge(out, build_inverted=True)
    assert not MMapIndexedDataset.exists(
        str(tmp_path / "ids" / "sample_id_to_sample"))
    assert MMapIndexedDataset.exists(
        str(tmp_path / "ids" / "seqlen_to_sample"))
