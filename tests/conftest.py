"""Test harness: simulated 8-device CPU mesh.

The reference's trick (SURVEY.md §4) is ``DistributedTest`` spawning N real processes
over NCCL on one box. The TPU-native equivalent is *simpler*: JAX can present N
virtual CPU devices in a single process (``xla_force_host_platform_device_count``),
so every sharding/collective path compiles and runs exactly as it would on an N-chip
mesh — no process spawning, no fake backends. These env vars MUST be set before jax
is imported anywhere in the test process.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8")
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["DS_TPU_ACCELERATOR"] = "cpu"
# AOT-report tests load libtpu for compile-only topology work, in-process AND
# in CLI subprocesses — skip libtpu's single-process lockfile
os.environ.setdefault("ALLOW_MULTIPLE_LIBTPU_LOAD", "true")

import jax  # noqa: E402

# The image's sitecustomize imports jax at interpreter start (latching
# JAX_PLATFORMS from the outer env), so the env var alone is too late — force the
# platform through the config as well, before any backend is initialized.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 simulated devices, got {len(devs)}"
    return devs


@pytest.fixture()
def rng():
    return np.random.default_rng(0)


def random_batch(rng, batch_size: int, seq_len: int, vocab: int = 256, gas: int = 1):
    shape = (batch_size, seq_len) if gas == 1 else (gas, batch_size, seq_len)
    return {"input_ids": rng.integers(0, vocab, size=shape, dtype=np.int32)}
