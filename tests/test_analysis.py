"""dslint static analyzer: every rule family fires on a deliberately-broken
program and stays silent on a known-good one.

The broken programs are minimal renderings of the real bug classes:
replicated big param under ZeRO-3, fp32 matmul leak out of a bf16 path,
missed donation of a state-sized buffer, cond branches disagreeing on their
collective order inside shard_map, and a quantization knob the traced program
contradicts. The clean baseline is the shipped TINY GPT engine.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

import deepspeed_tpu
from deepspeed_tpu.analysis import (
    AnalysisError,
    AnalysisOptions,
    Severity,
    analyze_engine,
    analyze_fn,
)
from deepspeed_tpu.models import GPTConfig, build_gpt
from deepspeed_tpu.models.api import Module

TINY = GPTConfig(vocab_size=256, n_layer=2, n_head=4, d_model=64,
                 max_seq_len=64)


def tiny_engine(stage=3, micro=4, **zero_over):
    model, _ = build_gpt(TINY)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model,
        config={
            "train_micro_batch_size_per_gpu": micro,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "bf16": {"enabled": True},
            "zero_optimization": {"stage": stage, **zero_over},
            "steps_per_print": 0,
        })
    return engine


def flat_module(shape=(64, 96), n=1):
    """A Module with ``n`` weight leaves of ``shape`` and a quadratic loss —
    small, no gather machinery, no gpt_config."""

    def init(rng):
        return {f"w{i}": jnp.zeros(shape, jnp.float32) for i in range(n)}

    def apply(params, batch, rngs=None, train=True, **kw):
        x = batch["x"]
        loss = sum(jnp.mean((x @ w[:x.shape[-1], :x.shape[-1]]) ** 2)
                   for w in params.values()) + jnp.mean(x ** 2)
        return loss, {}

    return Module(init=init, apply=apply)


# --------------------------------------------------------------------- clean
def test_clean_engine_no_findings(devices):
    """The shipped engine must lint clean: no WARNING/ERROR on any family."""
    engine = tiny_engine(stage=3)
    report = analyze_engine(engine, compile=True)
    bad = [f for f in report.findings if f.severity >= Severity.WARNING]
    assert not bad, report.render()


def test_clean_quantized_engine_no_errors(devices):
    """qw8 engine: int wire present, so the config rule stays silent."""
    engine = tiny_engine(stage=3, zero_quantized_weights=True)
    report = analyze_engine(engine)
    assert not report.errors(), report.render()
    assert not report.by_rule("config/quantized-wire-missing")


# ------------------------------------------------------------------ sharding
def test_replicated_large_array_fires_once(devices):
    """ZeRO-3 declared, but the single param leaf has no mesh-divisible dim
    (7 x 513) — the policy falls back to replication and the rule must say
    so."""
    model = flat_module(shape=(7, 513))
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model,
        config={"train_micro_batch_size_per_gpu": 1,
                # SGD without momentum: no opt-state leaves, so the single
                # param leaf is the only replicated buffer to flag
                "optimizer": {"type": "SGD", "params": {"lr": 1e-3}},
                "zero_optimization": {
                    "stage": 3, "stage3_param_persistence_threshold": 0},
                "steps_per_print": 0})
    batch = {"x": jax.ShapeDtypeStruct((8, 7), jnp.float32)}
    report = analyze_engine(
        engine, batch=batch,
        options=AnalysisOptions(replicated_bytes=1024, donation_bytes=1 << 30))
    hits = report.by_rule("sharding/replicated-large-array")
    assert len(hits) == 1, report.render()
    assert hits[0].severity == Severity.ERROR


def test_replicated_rule_silent_when_policy_shards(devices):
    engine = tiny_engine(stage=3)
    report = analyze_engine(
        engine, options=AnalysisOptions(replicated_bytes=1024))
    assert not report.by_rule("sharding/replicated-large-array"), \
        report.render()


# ----------------------------------------------------------------- precision
def test_fp32_leak_fires_once(devices):
    def leaky(x, w):
        h = x.astype(jnp.float32) @ w.astype(jnp.float32)  # the leak
        return jnp.sum(h.astype(jnp.bfloat16))

    x = jax.ShapeDtypeStruct((128, 128), jnp.bfloat16)
    w = jax.ShapeDtypeStruct((128, 128), jnp.bfloat16)
    report = analyze_fn(leaky, x, w, name="leaky")
    hits = report.by_rule("precision/fp32-leak")
    assert len(hits) == 1, report.render()


def test_fp32_leak_silent_on_clean_bf16(devices):
    def clean(x, w):
        h = x @ w  # stays bf16; fp32 only after the matmul
        return jnp.sum(h.astype(jnp.float32))

    x = jax.ShapeDtypeStruct((128, 128), jnp.bfloat16)
    w = jax.ShapeDtypeStruct((128, 128), jnp.bfloat16)
    report = analyze_fn(clean, x, w, name="clean")
    assert not report.by_rule("precision/fp32-leak"), report.render()


def test_low_precision_accumulation_fires(devices):
    """The realistic rendering: the backward of a broadcast-add sums 4M bf16
    cotangents in bf16 (jnp.sum itself upcasts its accumulator — the forward
    path is fine; the cotangent reduction is where the tail gets dropped)."""

    def fwd(x, b):
        return jnp.sum(((x + b).astype(jnp.float32)) ** 2)

    x = jax.ShapeDtypeStruct((2048, 2048), jnp.bfloat16)
    b = jax.ShapeDtypeStruct((2048,), jnp.bfloat16)
    report = analyze_fn(jax.grad(fwd, argnums=1), x, b, name="bcast-bwd")
    assert len(report.by_rule("precision/low-precision-accumulation")) == 1, \
        report.render()


# ----------------------------------------------------------------- host-sync
def test_callback_in_step_fires_once(devices):
    def with_callback(x):
        y = jax.pure_callback(
            lambda v: np.asarray(v) * 2, jax.ShapeDtypeStruct(x.shape, x.dtype), x)
        return jnp.sum(y)

    x = jax.ShapeDtypeStruct((8, 8), jnp.float32)
    report = analyze_fn(with_callback, x, name="cb")
    hits = report.by_rule("host-sync/callback-in-step")
    assert len(hits) == 1, report.render()
    assert hits[0].severity == Severity.ERROR


def test_donation_miss_fires_once_and_donating_fixes_it(devices):
    def step(state, batch):
        return state + batch.sum(), jnp.mean(batch)

    state = jax.ShapeDtypeStruct((1024, 1024), jnp.float32)  # 4 MB
    batch = jax.ShapeDtypeStruct((16, 16), jnp.float32)
    report = analyze_fn(step, state, batch, name="nodonate")
    assert len(report.by_rule("host-sync/donation-miss")) == 1, report.render()

    fixed = analyze_fn(step, state, batch, name="donated",
                       donate_argnums=(0,))
    assert not fixed.by_rule("host-sync/donation-miss"), fixed.render()


# ----------------------------------------------------- collective order
def test_divergent_branch_collectives_fires_once(devices):
    from deepspeed_tpu.utils.jax_compat import shard_map

    mesh = Mesh(np.array(jax.devices()), ("dp",))

    def body(x, flag):
        def with_psum(v):
            return jax.lax.psum(v, "dp")

        def without(v):
            return v * 2.0

        return jax.lax.cond(flag[0] > 0, with_psum, without, x)

    fn = shard_map(body, mesh=mesh, in_specs=(P("dp"), P()),
                   out_specs=P("dp"), check_vma=False)
    x = jax.ShapeDtypeStruct((8, 4), jnp.float32)
    flag = jax.ShapeDtypeStruct((1,), jnp.int32)
    report = analyze_fn(fn, x, flag, name="divergent", mesh=mesh)
    hits = report.by_rule("collective/divergent-branch-order")
    assert len(hits) == 1, report.render()
    assert hits[0].severity == Severity.ERROR


def test_balanced_branch_collectives_silent(devices):
    from deepspeed_tpu.utils.jax_compat import shard_map

    mesh = Mesh(np.array(jax.devices()), ("dp",))

    def body(x, flag):
        def a(v):
            return jax.lax.psum(v * 2.0, "dp")

        def b(v):
            return jax.lax.psum(v + 1.0, "dp")

        return jax.lax.cond(flag[0] > 0, a, b, x)

    fn = shard_map(body, mesh=mesh, in_specs=(P("dp"), P()),
                   out_specs=P("dp"), check_vma=False)
    x = jax.ShapeDtypeStruct((8, 4), jnp.float32)
    flag = jax.ShapeDtypeStruct((1,), jnp.int32)
    report = analyze_fn(fn, x, flag, name="balanced", mesh=mesh)
    assert not report.by_rule("collective/divergent-branch-order"), \
        report.render()


def test_collective_in_while_predicate_fires(devices):
    from deepspeed_tpu.utils.jax_compat import shard_map

    mesh = Mesh(np.array(jax.devices()), ("dp",))

    def body(x):
        def cond(c):
            return jax.lax.psum(jnp.sum(c), "dp") < 100.0

        return jax.lax.while_loop(cond, lambda c: c * 2.0, x)

    fn = shard_map(body, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"),
                   check_vma=False)
    x = jax.ShapeDtypeStruct((8, 4), jnp.float32)
    report = analyze_fn(fn, x, name="whilecoll", mesh=mesh)
    assert len(report.by_rule("collective/collective-in-while-predicate")) == 1


# -------------------------------------------------------------------- config
def test_quantized_wire_missing_fires_once(devices):
    """zero_quantized_weights promised, but the model has no gather path —
    the traced step moves no int payload and the knob is inert."""
    model = flat_module(shape=(64, 96))
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model,
        config={"train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 3,
                                      "zero_quantized_weights": True},
                "steps_per_print": 0})
    batch = {"x": jax.ShapeDtypeStruct((8, 64), jnp.float32)}
    report = analyze_engine(engine, batch=batch)
    hits = report.by_rule("config/quantized-wire-missing")
    assert len(hits) == 1, report.render()
    assert hits[0].severity == Severity.ERROR


def test_quantized_weights_below_stage3_warns(devices):
    engine = tiny_engine(stage=2, zero_quantized_weights=True)
    report = analyze_engine(engine)
    assert report.by_rule("config/quantized-weights-below-stage3")
    # inert-wire is the ERROR-level companion: below stage 3 the gathers the
    # knob targets don't exist, so the wire is empty too
    assert report.by_rule("config/quantized-wire-missing")


# ------------------------------------------------------------- engine gating
def test_analysis_config_block_runs_at_init(devices):
    model, _ = build_gpt(TINY)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model,
        config={"train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "bf16": {"enabled": True},
                "zero_optimization": {"stage": 3},
                "analysis": {"enabled": True},
                "steps_per_print": 0})
    assert engine._analysis_pending is False  # ran at init (gpt batch synth)


def test_analysis_fail_on_error_raises_at_first_step(devices):
    """Non-GPT model: init defers (no batch to synthesize); the first
    train_batch analyzes with the real batch and raises on the inert-knob
    ERROR before executing anything."""
    model = flat_module(shape=(64, 96))
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model,
        config={"train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 3,
                                      "zero_quantized_weights": True},
                "analysis": {"enabled": True},
                "steps_per_print": 0})
    assert engine._analysis_pending is True
    with pytest.raises(AnalysisError):
        engine.train_batch({"x": np.zeros((8, 64), np.float32)})


# ------------------------------------------------------------------- pipe/CLI
def test_mpmd_schedule_pairing_sound():
    from deepspeed_tpu.runtime.pipe.mpmd import validate_schedule_pairing

    for m, s in [(2, 2), (4, 2), (8, 4), (3, 3)]:
        assert validate_schedule_pairing(m, s) == [], (m, s)


def test_cli_lists_bench_configs():
    from deepspeed_tpu.analysis.cli import DEFAULT_BENCH, load_bench_rows

    rows = load_bench_rows()
    names = [r["name"] for r in rows]
    assert DEFAULT_BENCH in names


def test_profiler_reports_static_flops(devices):
    from deepspeed_tpu.profiling import profile_compiled_fn

    a = jnp.ones((64, 64), jnp.float32)
    prof = profile_compiled_fn(lambda x: x @ x, a)
    assert prof["flops"] > 0
    assert prof["flops_source"] in ("compiled", "lowered")


# ------------------------------------------------------------ config/resilience
def test_checkpoint_uncommitted_load_rule(tmp_path):
    """Resume config pointing at a COMMIT-less tag warns at lint time; a
    committed tag (or nothing to resume) stays silent."""
    from deepspeed_tpu.analysis.core import AnalysisContext
    from deepspeed_tpu.analysis.rules_config import CheckpointUncommittedLoadRule
    from deepspeed_tpu.resilience import commit_tag
    from deepspeed_tpu.runtime.config import DeepSpeedConfig

    rule = CheckpointUncommittedLoadRule()
    tag_dir = tmp_path / "global_step5"
    (tag_dir / "state").mkdir(parents=True)
    (tag_dir / "state" / "state.msgpack").write_bytes(b"x" * 32)
    cfg = DeepSpeedConfig.load({
        "train_micro_batch_size_per_gpu": 1,
        "resilience": {"enabled": True, "save_dir": str(tmp_path),
                       "resume_tag": "global_step5"}})
    findings = list(rule.check_context(AnalysisContext(config=cfg)))
    assert len(findings) == 1
    assert "COMMIT" in findings[0].message
    assert findings[0].severity == Severity.WARNING

    commit_tag(str(tag_dir))  # now committed -> silent
    assert not list(rule.check_context(AnalysisContext(config=cfg)))

    # resume_tag naming a directory that does not exist -> flagged
    cfg_missing = DeepSpeedConfig.load({
        "train_micro_batch_size_per_gpu": 1,
        "resilience": {"enabled": True, "save_dir": str(tmp_path),
                       "resume_tag": "global_step99"}})
    findings = list(rule.check_context(AnalysisContext(config=cfg_missing)))
    assert len(findings) == 1 and "does not exist" in findings[0].message

    # fresh run (no latest, no pin): nothing to resume, nothing to flag
    fresh = tmp_path / "fresh"
    fresh.mkdir()
    cfg_fresh = DeepSpeedConfig.load({
        "train_micro_batch_size_per_gpu": 1,
        "resilience": {"enabled": True, "save_dir": str(fresh)}})
    assert not list(rule.check_context(AnalysisContext(config=cfg_fresh)))


def test_rollback_without_data_cursor_rule(tmp_path):
    """Divergence rollback armed without a cursor-checkpointable dataloader
    warns; declaring the cursor (config flag or resume_state_provider)
    silences it, as does leaving the sentinel off."""
    from deepspeed_tpu.analysis.core import AnalysisContext
    from deepspeed_tpu.analysis.rules_config import RollbackWithoutDataCursorRule
    from deepspeed_tpu.runtime.config import DeepSpeedConfig

    rule = RollbackWithoutDataCursorRule()

    def cfg(sentinel):
        return DeepSpeedConfig.load({
            "train_micro_batch_size_per_gpu": 1,
            "resilience": {"enabled": True, "save_dir": str(tmp_path),
                           "sentinel": sentinel}})

    armed = cfg({"enabled": True})
    findings = list(rule.check_context(AnalysisContext(config=armed)))
    assert len(findings) == 1
    assert findings[0].severity == Severity.WARNING
    assert findings[0].rule_id == "config/rollback-without-data-cursor"

    # declared cursor-checkpointable -> silent
    declared = cfg({"enabled": True, "cursor_checkpointable": True})
    assert not list(rule.check_context(AnalysisContext(config=declared)))

    # a registered resume_state_provider on the engine -> silent
    class _Eng:
        resume_state_provider = staticmethod(lambda: {"cursor": 0})

    assert not list(rule.check_context(
        AnalysisContext(config=armed, engine=_Eng())))

    # sentinel off -> nothing armed, nothing to flag
    off = cfg({"enabled": False})
    assert not list(rule.check_context(AnalysisContext(config=off)))
