"""dslint static analyzer: every rule family fires on a deliberately-broken
program and stays silent on a known-good one.

The broken programs are minimal renderings of the real bug classes:
replicated big param under ZeRO-3, fp32 matmul leak out of a bf16 path,
missed donation of a state-sized buffer, cond branches disagreeing on their
collective order inside shard_map, and a quantization knob the traced program
contradicts. The clean baseline is the shipped TINY GPT engine.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

import deepspeed_tpu
from deepspeed_tpu.analysis import (
    AnalysisError,
    AnalysisOptions,
    Severity,
    analyze_engine,
    analyze_fn,
)
from deepspeed_tpu.models import GPTConfig, build_gpt
from deepspeed_tpu.models.api import Module

TINY = GPTConfig(vocab_size=256, n_layer=2, n_head=4, d_model=64,
                 max_seq_len=64)


def tiny_engine(stage=3, micro=4, **zero_over):
    model, _ = build_gpt(TINY)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model,
        config={
            "train_micro_batch_size_per_gpu": micro,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "bf16": {"enabled": True},
            "zero_optimization": {"stage": stage, **zero_over},
            "steps_per_print": 0,
        })
    return engine


def flat_module(shape=(64, 96), n=1):
    """A Module with ``n`` weight leaves of ``shape`` and a quadratic loss —
    small, no gather machinery, no gpt_config."""

    def init(rng):
        return {f"w{i}": jnp.zeros(shape, jnp.float32) for i in range(n)}

    def apply(params, batch, rngs=None, train=True, **kw):
        x = batch["x"]
        loss = sum(jnp.mean((x @ w[:x.shape[-1], :x.shape[-1]]) ** 2)
                   for w in params.values()) + jnp.mean(x ** 2)
        return loss, {}

    return Module(init=init, apply=apply)


# --------------------------------------------------------------------- clean
def test_clean_engine_no_findings(devices):
    """The shipped engine must lint clean: no WARNING/ERROR on any family."""
    engine = tiny_engine(stage=3)
    report = analyze_engine(engine, compile=True)
    bad = [f for f in report.findings if f.severity >= Severity.WARNING]
    assert not bad, report.render()


def test_clean_quantized_engine_no_errors(devices):
    """qw8 engine: int wire present, so the config rule stays silent."""
    engine = tiny_engine(stage=3, zero_quantized_weights=True)
    report = analyze_engine(engine)
    assert not report.errors(), report.render()
    assert not report.by_rule("config/quantized-wire-missing")


# ------------------------------------------------------------------ sharding
def test_replicated_large_array_fires_once(devices):
    """ZeRO-3 declared, but the single param leaf has no mesh-divisible dim
    (7 x 513) — the policy falls back to replication and the rule must say
    so."""
    model = flat_module(shape=(7, 513))
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model,
        config={"train_micro_batch_size_per_gpu": 1,
                # SGD without momentum: no opt-state leaves, so the single
                # param leaf is the only replicated buffer to flag
                "optimizer": {"type": "SGD", "params": {"lr": 1e-3}},
                "zero_optimization": {
                    "stage": 3, "stage3_param_persistence_threshold": 0},
                "steps_per_print": 0})
    batch = {"x": jax.ShapeDtypeStruct((8, 7), jnp.float32)}
    report = analyze_engine(
        engine, batch=batch,
        options=AnalysisOptions(replicated_bytes=1024, donation_bytes=1 << 30))
    hits = report.by_rule("sharding/replicated-large-array")
    assert len(hits) == 1, report.render()
    assert hits[0].severity == Severity.ERROR


def test_replicated_rule_silent_when_policy_shards(devices):
    engine = tiny_engine(stage=3)
    report = analyze_engine(
        engine, options=AnalysisOptions(replicated_bytes=1024))
    assert not report.by_rule("sharding/replicated-large-array"), \
        report.render()


# ----------------------------------------------------------------- precision
def test_fp32_leak_fires_once(devices):
    def leaky(x, w):
        h = x.astype(jnp.float32) @ w.astype(jnp.float32)  # the leak
        return jnp.sum(h.astype(jnp.bfloat16))

    x = jax.ShapeDtypeStruct((128, 128), jnp.bfloat16)
    w = jax.ShapeDtypeStruct((128, 128), jnp.bfloat16)
    report = analyze_fn(leaky, x, w, name="leaky")
    hits = report.by_rule("precision/fp32-leak")
    assert len(hits) == 1, report.render()


def test_fp32_leak_silent_on_clean_bf16(devices):
    def clean(x, w):
        h = x @ w  # stays bf16; fp32 only after the matmul
        return jnp.sum(h.astype(jnp.float32))

    x = jax.ShapeDtypeStruct((128, 128), jnp.bfloat16)
    w = jax.ShapeDtypeStruct((128, 128), jnp.bfloat16)
    report = analyze_fn(clean, x, w, name="clean")
    assert not report.by_rule("precision/fp32-leak"), report.render()


def test_low_precision_accumulation_fires(devices):
    """The realistic rendering: the backward of a broadcast-add sums 4M bf16
    cotangents in bf16 (jnp.sum itself upcasts its accumulator — the forward
    path is fine; the cotangent reduction is where the tail gets dropped)."""

    def fwd(x, b):
        return jnp.sum(((x + b).astype(jnp.float32)) ** 2)

    x = jax.ShapeDtypeStruct((2048, 2048), jnp.bfloat16)
    b = jax.ShapeDtypeStruct((2048,), jnp.bfloat16)
    report = analyze_fn(jax.grad(fwd, argnums=1), x, b, name="bcast-bwd")
    assert len(report.by_rule("precision/low-precision-accumulation")) == 1, \
        report.render()


# ----------------------------------------------------------------- host-sync
def test_callback_in_step_fires_once(devices):
    def with_callback(x):
        y = jax.pure_callback(
            lambda v: np.asarray(v) * 2, jax.ShapeDtypeStruct(x.shape, x.dtype), x)
        return jnp.sum(y)

    x = jax.ShapeDtypeStruct((8, 8), jnp.float32)
    report = analyze_fn(with_callback, x, name="cb")
    hits = report.by_rule("host-sync/callback-in-step")
    assert len(hits) == 1, report.render()
    assert hits[0].severity == Severity.ERROR


def test_donation_miss_fires_once_and_donating_fixes_it(devices):
    def step(state, batch):
        return state + batch.sum(), jnp.mean(batch)

    state = jax.ShapeDtypeStruct((1024, 1024), jnp.float32)  # 4 MB
    batch = jax.ShapeDtypeStruct((16, 16), jnp.float32)
    report = analyze_fn(step, state, batch, name="nodonate")
    assert len(report.by_rule("host-sync/donation-miss")) == 1, report.render()

    fixed = analyze_fn(step, state, batch, name="donated",
                       donate_argnums=(0,))
    assert not fixed.by_rule("host-sync/donation-miss"), fixed.render()


# ----------------------------------------------------- collective order
def test_divergent_branch_collectives_fires_once(devices):
    from deepspeed_tpu.utils.jax_compat import shard_map

    mesh = Mesh(np.array(jax.devices()), ("dp",))

    def body(x, flag):
        def with_psum(v):
            return jax.lax.psum(v, "dp")

        def without(v):
            return v * 2.0

        return jax.lax.cond(flag[0] > 0, with_psum, without, x)

    fn = shard_map(body, mesh=mesh, in_specs=(P("dp"), P()),
                   out_specs=P("dp"), check_vma=False)
    x = jax.ShapeDtypeStruct((8, 4), jnp.float32)
    flag = jax.ShapeDtypeStruct((1,), jnp.int32)
    report = analyze_fn(fn, x, flag, name="divergent", mesh=mesh)
    hits = report.by_rule("collective/divergent-branch-order")
    assert len(hits) == 1, report.render()
    assert hits[0].severity == Severity.ERROR


def test_balanced_branch_collectives_silent(devices):
    from deepspeed_tpu.utils.jax_compat import shard_map

    mesh = Mesh(np.array(jax.devices()), ("dp",))

    def body(x, flag):
        def a(v):
            return jax.lax.psum(v * 2.0, "dp")

        def b(v):
            return jax.lax.psum(v + 1.0, "dp")

        return jax.lax.cond(flag[0] > 0, a, b, x)

    fn = shard_map(body, mesh=mesh, in_specs=(P("dp"), P()),
                   out_specs=P("dp"), check_vma=False)
    x = jax.ShapeDtypeStruct((8, 4), jnp.float32)
    flag = jax.ShapeDtypeStruct((1,), jnp.int32)
    report = analyze_fn(fn, x, flag, name="balanced", mesh=mesh)
    assert not report.by_rule("collective/divergent-branch-order"), \
        report.render()


def test_collective_in_while_predicate_fires(devices):
    from deepspeed_tpu.utils.jax_compat import shard_map

    mesh = Mesh(np.array(jax.devices()), ("dp",))

    def body(x):
        def cond(c):
            return jax.lax.psum(jnp.sum(c), "dp") < 100.0

        return jax.lax.while_loop(cond, lambda c: c * 2.0, x)

    fn = shard_map(body, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"),
                   check_vma=False)
    x = jax.ShapeDtypeStruct((8, 4), jnp.float32)
    report = analyze_fn(fn, x, name="whilecoll", mesh=mesh)
    assert len(report.by_rule("collective/collective-in-while-predicate")) == 1


# -------------------------------------------------------------------- config
def test_quantized_wire_missing_fires_once(devices):
    """zero_quantized_weights promised, but the model has no gather path —
    the traced step moves no int payload and the knob is inert."""
    model = flat_module(shape=(64, 96))
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model,
        config={"train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 3,
                                      "zero_quantized_weights": True},
                "steps_per_print": 0})
    batch = {"x": jax.ShapeDtypeStruct((8, 64), jnp.float32)}
    report = analyze_engine(engine, batch=batch)
    hits = report.by_rule("config/quantized-wire-missing")
    assert len(hits) == 1, report.render()
    assert hits[0].severity == Severity.ERROR


def test_quantized_weights_below_stage3_warns(devices):
    engine = tiny_engine(stage=2, zero_quantized_weights=True)
    report = analyze_engine(engine)
    assert report.by_rule("config/quantized-weights-below-stage3")
    # inert-wire is the ERROR-level companion: below stage 3 the gathers the
    # knob targets don't exist, so the wire is empty too
    assert report.by_rule("config/quantized-wire-missing")


# ------------------------------------------------------------- engine gating
def test_analysis_config_block_runs_at_init(devices):
    model, _ = build_gpt(TINY)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model,
        config={"train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "bf16": {"enabled": True},
                "zero_optimization": {"stage": 3},
                "analysis": {"enabled": True},
                "steps_per_print": 0})
    assert engine._analysis_pending is False  # ran at init (gpt batch synth)


def test_analysis_fail_on_error_raises_at_first_step(devices):
    """Non-GPT model: init defers (no batch to synthesize); the first
    train_batch analyzes with the real batch and raises on the inert-knob
    ERROR before executing anything."""
    model = flat_module(shape=(64, 96))
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model,
        config={"train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 3,
                                      "zero_quantized_weights": True},
                "analysis": {"enabled": True},
                "steps_per_print": 0})
    assert engine._analysis_pending is True
    with pytest.raises(AnalysisError):
        engine.train_batch({"x": np.zeros((8, 64), np.float32)})


# ------------------------------------------------------------------- pipe/CLI
def test_mpmd_schedule_pairing_sound():
    from deepspeed_tpu.runtime.pipe.mpmd import validate_schedule_pairing

    for m, s in [(2, 2), (4, 2), (8, 4), (3, 3)]:
        assert validate_schedule_pairing(m, s) == [], (m, s)


def test_cli_lists_bench_configs():
    from deepspeed_tpu.analysis.cli import DEFAULT_BENCH, load_bench_rows

    rows = load_bench_rows()
    names = [r["name"] for r in rows]
    assert DEFAULT_BENCH in names


def test_profiler_reports_static_flops(devices):
    from deepspeed_tpu.profiling import profile_compiled_fn

    a = jnp.ones((64, 64), jnp.float32)
    prof = profile_compiled_fn(lambda x: x @ x, a)
    assert prof["flops"] > 0
    assert prof["flops_source"] in ("compiled", "lowered")


# ------------------------------------------------------------ config/resilience
def test_checkpoint_uncommitted_load_rule(tmp_path):
    """Resume config pointing at a COMMIT-less tag warns at lint time; a
    committed tag (or nothing to resume) stays silent."""
    from deepspeed_tpu.analysis.core import AnalysisContext
    from deepspeed_tpu.analysis.rules_config import CheckpointUncommittedLoadRule
    from deepspeed_tpu.resilience import commit_tag
    from deepspeed_tpu.runtime.config import DeepSpeedConfig

    rule = CheckpointUncommittedLoadRule()
    tag_dir = tmp_path / "global_step5"
    (tag_dir / "state").mkdir(parents=True)
    (tag_dir / "state" / "state.msgpack").write_bytes(b"x" * 32)
    cfg = DeepSpeedConfig.load({
        "train_micro_batch_size_per_gpu": 1,
        "resilience": {"enabled": True, "save_dir": str(tmp_path),
                       "resume_tag": "global_step5"}})
    findings = list(rule.check_context(AnalysisContext(config=cfg)))
    assert len(findings) == 1
    assert "COMMIT" in findings[0].message
    assert findings[0].severity == Severity.WARNING

    commit_tag(str(tag_dir))  # now committed -> silent
    assert not list(rule.check_context(AnalysisContext(config=cfg)))

    # resume_tag naming a directory that does not exist -> flagged
    cfg_missing = DeepSpeedConfig.load({
        "train_micro_batch_size_per_gpu": 1,
        "resilience": {"enabled": True, "save_dir": str(tmp_path),
                       "resume_tag": "global_step99"}})
    findings = list(rule.check_context(AnalysisContext(config=cfg_missing)))
    assert len(findings) == 1 and "does not exist" in findings[0].message

    # fresh run (no latest, no pin): nothing to resume, nothing to flag
    fresh = tmp_path / "fresh"
    fresh.mkdir()
    cfg_fresh = DeepSpeedConfig.load({
        "train_micro_batch_size_per_gpu": 1,
        "resilience": {"enabled": True, "save_dir": str(fresh)}})
    assert not list(rule.check_context(AnalysisContext(config=cfg_fresh)))


def test_rollback_without_data_cursor_rule(tmp_path):
    """Divergence rollback armed without a cursor-checkpointable dataloader
    warns; declaring the cursor (config flag or resume_state_provider)
    silences it, as does leaving the sentinel off."""
    from deepspeed_tpu.analysis.core import AnalysisContext
    from deepspeed_tpu.analysis.rules_config import RollbackWithoutDataCursorRule
    from deepspeed_tpu.runtime.config import DeepSpeedConfig

    rule = RollbackWithoutDataCursorRule()

    def cfg(sentinel):
        return DeepSpeedConfig.load({
            "train_micro_batch_size_per_gpu": 1,
            "resilience": {"enabled": True, "save_dir": str(tmp_path),
                           "sentinel": sentinel}})

    armed = cfg({"enabled": True})
    findings = list(rule.check_context(AnalysisContext(config=armed)))
    assert len(findings) == 1
    assert findings[0].severity == Severity.WARNING
    assert findings[0].rule_id == "config/rollback-without-data-cursor"

    # declared cursor-checkpointable -> silent
    declared = cfg({"enabled": True, "cursor_checkpointable": True})
    assert not list(rule.check_context(AnalysisContext(config=declared)))

    # a registered resume_state_provider on the engine -> silent
    class _Eng:
        resume_state_provider = staticmethod(lambda: {"cursor": 0})

    assert not list(rule.check_context(
        AnalysisContext(config=armed, engine=_Eng())))

    # sentinel off -> nothing armed, nothing to flag
    off = cfg({"enabled": False})
    assert not list(rule.check_context(AnalysisContext(config=off)))


# ----------------------------------------------- coverage gaps + meta-test
def test_unaccounted_collective_fires_and_silent():
    """Quantized collectives configured, yet the post-GSPMD HLO moves a
    full-precision all-gather: fires with the op + bytes named. Silent when
    the payload is int (that IS the quantized wire) and when no
    quantization is configured."""
    from deepspeed_tpu.analysis.core import AnalysisContext
    from deepspeed_tpu.analysis.ir import ProgramIR
    from deepspeed_tpu.analysis.rules_sharding import UnaccountedCollectiveRule
    from deepspeed_tpu.runtime.config import DeepSpeedConfig

    rule = UnaccountedCollectiveRule()
    cjx = jax.make_jaxpr(lambda x: x)(1.0)

    def prog(hlo):
        return ProgramIR(name="p", closed_jaxpr=cjx, in_avals=[],
                         out_avals=[], donated=[], hlo=hlo)

    qcfg = DeepSpeedConfig.load({
        "train_micro_batch_size_per_gpu": 1,
        "zero_optimization": {"stage": 2, "zero_quantized_gradients": True}})
    f32_ag = ("  %ag = f32[1048576]{0} all-gather(f32[131072]{0} %p0), "
              "dimensions={0}\n")
    hits = list(rule.check_program(prog(f32_ag),
                                   AnalysisContext(config=qcfg)))
    assert len(hits) == 1, hits
    assert hits[0].rule_id == "sharding/unaccounted-collective"
    assert "all-gather" in hits[0].message and "4.0 MB" in hits[0].message

    # int payload: that IS the quantized wire -> silent
    s8_ag = ("  %ag = s8[4194304]{0} all-gather(s8[524288]{0} %p0), "
             "dimensions={0}\n")
    assert not list(rule.check_program(prog(s8_ag),
                                       AnalysisContext(config=qcfg)))
    # no quantization configured -> nothing to cross-check -> silent
    plain = DeepSpeedConfig.load({"train_micro_batch_size_per_gpu": 1})
    assert not list(rule.check_program(prog(f32_ag),
                                       AnalysisContext(config=plain)))


def test_f64_present_fires_and_silent(devices):
    def promoting(x):
        return jnp.sum(x.astype(jnp.float64) * 2.0)

    x = jax.ShapeDtypeStruct((8, 8), jnp.float32)
    with jax.experimental.enable_x64():
        report = analyze_fn(promoting, x, name="f64leak")
    hits = report.by_rule("precision/f64-present")
    assert len(hits) == 1, report.render()
    assert hits[0].severity == Severity.ERROR

    report = analyze_fn(lambda x: jnp.sum(x * 2.0), x, name="f32clean")
    assert not report.by_rule("precision/f64-present"), report.render()


def test_shard_map_signature_inventory_and_silent(devices):
    from deepspeed_tpu.utils.jax_compat import shard_map

    mesh = Mesh(np.array(jax.devices()), ("dp",))

    def body(x):
        return jax.lax.psum(x, "dp")

    fn = shard_map(body, mesh=mesh, in_specs=P("dp"), out_specs=P(),
                   check_vma=False)
    x = jax.ShapeDtypeStruct((8, 4), jnp.float32)
    report = analyze_fn(fn, x, name="smap", mesh=mesh)
    hits = report.by_rule("collective/shard-map-signature")
    assert len(hits) == 1, report.render()
    assert hits[0].severity == Severity.INFO
    assert "psum" in hits[0].message

    # no shard_map in the program -> no inventory line
    report = analyze_fn(lambda x: jnp.sum(x), x, name="plain")
    assert not report.by_rule("collective/shard-map-signature")


def test_loss_scale_dtype_rule_fires_and_silent():
    from types import SimpleNamespace

    from deepspeed_tpu.analysis.core import AnalysisContext
    from deepspeed_tpu.analysis.rules_config import LossScaleDtypeRule

    rule = LossScaleDtypeRule()

    def eng(dtype):
        return SimpleNamespace(
            pc=SimpleNamespace(loss_scaling=True),
            state={"scaler": SimpleNamespace(
                scale=jnp.asarray(1024.0, dtype))})

    hits = list(rule.check_context(AnalysisContext(engine=eng(jnp.bfloat16))))
    assert len(hits) == 1 and hits[0].rule_id == "config/loss-scale-dtype"
    assert not list(rule.check_context(
        AnalysisContext(engine=eng(jnp.float32))))


def test_rules_silent_on_clean_programs(devices):
    """The fire-only-tested rules, pinned silent by id on known-good inputs
    (the other half of the fire/silent contract the meta-test enforces)."""
    from deepspeed_tpu.analysis import analyze_compile_log

    # clean fp32 reduction, no callbacks, no while predicates
    x = jax.ShapeDtypeStruct((2048, 2048), jnp.float32)
    report = analyze_fn(lambda x: jnp.sum(x ** 2), x, name="cleansum")
    for rid in ("precision/low-precision-accumulation",
                "host-sync/callback-in-step",
                "collective/collective-in-while-predicate"):
        assert not report.by_rule(rid), report.render()

    # clean tiny engine: the quantized-collective gates have nothing to flag
    report = analyze_engine(tiny_engine(stage=3))
    for rid in ("collective/unoverlapped-quantized-collective",
                "config/quantized-weights-below-stage3"):
        assert not report.by_rule(rid), report.render()

    # serving: bounded admission and an armed fleet stay out of the report
    from types import SimpleNamespace

    from deepspeed_tpu.inference.serving import ServingConfig

    bounded = SimpleNamespace(serving=ServingConfig(max_queue=8),
                              compile_log=[])
    assert not analyze_compile_log(bounded).by_rule(
        "serving/unbounded-admission")
    fleet = SimpleNamespace(
        replicas=[object(), object()],
        config=SimpleNamespace(heartbeat_deadline_s=None, reroute_budget=2),
        compile_log=[])
    assert not analyze_compile_log(fleet).by_rule(
        "serving/fleet-without-failover")
    bucketed = [{"kind": "decode", "shape": (1, b)} for b in (8, 16, 32, 64)]
    assert not analyze_compile_log(bucketed).by_rule(
        "serving/unbucketed-decode-shape")


def test_meta_every_rule_documented_and_tested():
    """Every shipped rule id (default_rules — the compile-log serving set is
    a subset) must have a docs/STATIC_ANALYSIS.md catalog heading and be
    exercised from tests at least twice (the fire + silent convention),
    referenced by rule id or by rule class name."""
    import glob
    import os

    from deepspeed_tpu.analysis import default_rules

    rules = default_rules()
    ids = [r.rule_id for r in rules]
    assert len(ids) == len(set(ids)), "duplicate rule ids"
    # the pipeline-prover family is registered in the default set
    for rid in ("pipe/unpaired-send-recv", "pipe/schedule-deadlock",
                "pipe/stale-weight-application"):
        assert rid in ids

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(root, "docs", "STATIC_ANALYSIS.md")) as fh:
        doc = fh.read()
    sources = ""
    for path in sorted(glob.glob(os.path.join(root, "tests", "*.py"))):
        with open(path) as fh:
            sources += fh.read()

    missing_doc = [r.rule_id for r in rules
                   if f"### `{r.rule_id}`" not in doc]
    assert not missing_doc, (
        f"rules without a docs/STATIC_ANALYSIS.md heading: {missing_doc}")
    undocumented = [r.rule_id for r in rules if not r.description]
    assert not undocumented, f"rules without a description: {undocumented}"
    untested = [
        r.rule_id for r in rules
        if sources.count(r.rule_id) + sources.count(type(r).__name__) < 2]
    assert not untested, (
        f"rules without a fire + silent test reference: {untested}")


def test_cli_list_json_emits_rule_registry():
    """--list --json: machine-readable per-rule family/severity/doc-anchor,
    with every anchor resolving to a real docs/STATIC_ANALYSIS.md heading."""
    import io
    import json
    from contextlib import redirect_stdout

    from deepspeed_tpu.analysis import cli, default_rules

    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = cli.main(["--list", "--json"])
    assert rc == 0
    data = json.loads(buf.getvalue())
    assert {r["rule_id"] for r in data["rules"]} == {
        r.rule_id for r in default_rules()}
    for r in data["rules"]:
        assert r["family"] == r["rule_id"].split("/")[0]
        assert r["severity"] in ("ERROR", "WARNING", "INFO")
        assert r["description"]
        assert r["doc_anchor"].startswith("docs/STATIC_ANALYSIS.md#"), r
    assert data["configs"] and all("name" in c for c in data["configs"])


def test_cli_json_mode_gates_on_error_findings(monkeypatch):
    """The --json path must exit 2 on ERROR findings exactly like the text
    path (CI parses the JSON *and* trusts the exit code)."""
    import io
    import json
    from contextlib import redirect_stdout

    from deepspeed_tpu.analysis import cli
    from deepspeed_tpu.analysis.core import Finding, Report

    bad = Report(findings=[Finding(
        rule_id="pipe/schedule-deadlock", severity=Severity.ERROR,
        location="x", message="injected")])
    monkeypatch.setattr(cli, "analyze_row", lambda row, **kw: bad)

    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = cli.main(["--json"])
    assert rc == 2
    out = json.loads(buf.getvalue())
    assert out["findings"][0]["severity"] == "ERROR"

    with redirect_stdout(io.StringIO()):
        assert cli.main(["--json", "--fail-on", "never"]) == 0
        assert cli.main([]) == 2  # text path gates identically


def test_cli_schedules_gate_proves_and_prices():
    """--schedules: every generated schedule in the matrix proves clean, and
    both interleaved and zero-bubble beat 1F1B's static bubble at equal
    microbatches (the PR's headline row, CI-gated)."""
    import io
    import json
    from contextlib import redirect_stdout

    from deepspeed_tpu.analysis import cli

    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = cli.main(["--schedules", "--json"])
    assert rc == 0
    for entry in json.loads(buf.getvalue()):
        assert entry["n_errors"] == 0
        by_kind = {rep["schedule"].split("[")[0]: rep
                   for rep in entry["schedules"]}
        assert all(rep["ok"] for rep in by_kind.values())
        b1 = by_kind["1f1b"]["bubble"]["bubble_frac"]
        assert by_kind["interleaved"]["bubble"]["bubble_frac"] < b1
        assert by_kind["zero-bubble"]["bubble"]["bubble_frac"] < b1
