"""Flash attention kernel vs XLA reference (runs in interpret mode on CPU)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.attention import dot_product_attention
from deepspeed_tpu.ops.pallas.flash_attention import flash_attention


def make_qkv(B=2, T=256, H=2, D=64, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (B, T, H, D), dtype)
    k = jax.random.normal(ks[1], (B, T, H, D), dtype)
    v = jax.random.normal(ks[2], (B, T, H, D), dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
def test_forward_matches_reference(causal):
    q, k, v = make_qkv()
    ref = dot_product_attention(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal, block_q=128, block_k=128)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), rtol=2e-3, atol=2e-3)


def test_backward_matches_reference():
    q, k, v = make_qkv(T=128)

    def loss_ref(q, k, v):
        return jnp.sum(dot_product_attention(q, k, v, causal=True) ** 2)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True,
                                       block_q=64, block_k=64) ** 2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_fl = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_ref, g_fl, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-2, atol=2e-2, err_msg=name)


def test_uneven_blocks_rejected():
    q, k, v = make_qkv(T=100)
    with pytest.raises(ValueError):
        flash_attention(q, k, v, block_q=64, block_k=64)


@pytest.mark.slow
def test_cross_length_causal_offset():
    """kv_len != q_len: causal mask must use absolute positions (review finding)."""
    q, k, v = make_qkv(T=128)
    q_short = q[:, -64:]  # last 64 queries attending over all 128 keys
    ref = dot_product_attention(q_short, k, v, causal=True)
    out = flash_attention(q_short, k, v, causal=True, block_q=64, block_k=64)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), rtol=2e-3, atol=2e-3)

    # gradients too
    def loss_ref(q_, k_, v_):
        return jnp.sum(dot_product_attention(q_, k_, v_, causal=True) ** 2)

    def loss_flash(q_, k_, v_):
        return jnp.sum(flash_attention(q_, k_, v_, causal=True,
                                       block_q=64, block_k=64) ** 2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q_short, k, v)
    g_fl = jax.grad(loss_flash, argnums=(0, 1, 2))(q_short, k, v)
    for a, b in zip(g_ref, g_fl):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-2, atol=2e-2)


@pytest.mark.parametrize("dtype", [jnp.bfloat16, jnp.float32])
@pytest.mark.slow
def test_stochastic_mode_close_to_exact(dtype):
    """stochastic_mode (parity: ds_transformer_cuda.cpp:63): bf16 MXU operands
    with fp32 accumulation — close to, but not necessarily bitwise equal to,
    the exact fp32-operand kernel; gradients flow through the same flag."""
    q, k, v = make_qkv(T=256, dtype=dtype)
    exact = flash_attention(q, k, v, causal=True, block_q=128, block_k=128)
    fast = flash_attention(q, k, v, causal=True, block_q=128, block_k=128,
                           stochastic_mode=True)
    np.testing.assert_allclose(
        np.asarray(exact, np.float32), np.asarray(fast, np.float32),
        rtol=2e-2, atol=2e-2)

    def loss(fn_kwargs):
        def f(q_, k_, v_):
            out = flash_attention(q_, k_, v_, causal=True, block_q=128,
                                  block_k=128, **fn_kwargs)
            return jnp.sum(out.astype(jnp.float32) ** 2)
        return f

    g_exact = jax.grad(loss({}), argnums=(0, 1, 2))(q, k, v)
    g_fast = jax.grad(loss({"stochastic_mode": True}),
                      argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_exact, g_fast):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=5e-2, atol=5e-2)


def test_flash_shard_mapped_on_mesh():
    """Mosaic kernels cannot be GSPMD-auto-partitioned: under a bound mesh the
    dispatcher must shard_map over batch (dp) and heads (tp) — found by the
    pipeline AOT compile row, where the bare call crashes XLA."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from deepspeed_tpu.ops.attention import multihead_attention
    from deepspeed_tpu.runtime.topology import mesh_context

    devs = np.array(jax.devices()).reshape(1, 4, 1, 1, 2)
    mesh = Mesh(devs, ("pp", "dp", "ep", "sp", "tp"))
    q, k, v = make_qkv(B=4, T=128, H=2, D=64)
    ref = dot_product_attention(q, k, v, causal=True)

    with mesh_context(mesh):
        spec = NamedSharding(mesh, P(("dp", "ep"), None, "tp", None))
        qs, ks, vs = (jax.device_put(t, spec) for t in (q, k, v))
        out = jax.jit(lambda a, b, c: multihead_attention(
            a, b, c, causal=True, use_flash=True))(qs, ks, vs)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=2e-2, atol=2e-2)


@pytest.mark.parametrize("causal", [True, False])
def test_streamed_multiblock_parity(causal):
    """Many k blocks per q block (the 3D-grid streaming accumulation path):
    fwd and grads must match the XLA reference across 8 streamed blocks."""
    q, k, v = make_qkv(B=1, T=1024, H=1, D=64)
    ref = dot_product_attention(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal, block_q=128, block_k=128)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=2e-3, atol=2e-3)
    g_ref = jax.grad(lambda a, b, c: jnp.sum(
        dot_product_attention(a, b, c, causal=causal) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    g_fl = jax.grad(lambda a, b, c: jnp.sum(
        flash_attention(a, b, c, causal=causal, block_q=128,
                        block_k=128) ** 2), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_fl):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-2, atol=2e-2)
