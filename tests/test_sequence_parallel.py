"""Sequence parallelism: ring attention and Ulysses all-to-all attention.

Discipline mirrors test_pipe.py: the sp-sharded result must match the dense
single-device reference attention to float tolerance, causal and non-causal,
with and without composition with dp/tp axes.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.attention import dot_product_attention
from deepspeed_tpu.parallel import ring_attention, ulysses_attention
from deepspeed_tpu.runtime.topology import MeshTopology


def _qkv(rng, B=2, T=32, H=4, Dh=8):
    shape = (B, T, H, Dh)
    return (jnp.asarray(rng.normal(size=shape), jnp.float32),
            jnp.asarray(rng.normal(size=shape), jnp.float32),
            jnp.asarray(rng.normal(size=shape), jnp.float32))


def _dense_reference(q, k, v, causal):
    return dot_product_attention(q, k, v, causal=causal)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_dense(rng, causal):
    topo = MeshTopology.create(dp=2, sp=4)
    q, k, v = _qkv(rng)
    ref = _dense_reference(q, k, v, causal)
    out = ring_attention(q, k, v, topo.mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=1e-4)


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_attention_matches_dense(rng, causal):
    topo = MeshTopology.create(dp=2, sp=4)
    q, k, v = _qkv(rng)
    ref = _dense_reference(q, k, v, causal)
    out = ulysses_attention(q, k, v, topo.mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=1e-4)


def test_ring_attention_with_tp_heads(rng):
    # sp=2 x tp=2: heads sharded over tp, sequence over sp
    topo = MeshTopology.create(dp=2, sp=2, tp=2)
    q, k, v = _qkv(rng, H=4)
    ref = _dense_reference(q, k, v, True)
    out = ring_attention(q, k, v, topo.mesh, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=1e-4)


@pytest.mark.slow
def test_ring_attention_grads_match_dense(rng):
    topo = MeshTopology.create(dp=1, sp=4, devices=jax.devices()[:4])
    q, k, v = _qkv(rng, B=1, T=16, H=2, Dh=4)

    def loss_ring(q, k, v):
        return (ring_attention(q, k, v, topo.mesh, causal=True) ** 2).sum()

    def loss_dense(q, k, v):
        return (_dense_reference(q, k, v, True) ** 2).sum()

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for gr, gd in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gd), atol=5e-5, rtol=1e-3)


def test_ulysses_grads_match_dense(rng):
    topo = MeshTopology.create(dp=1, sp=4, devices=jax.devices()[:4])
    q, k, v = _qkv(rng, B=1, T=16, H=4, Dh=4)

    def loss_u(q, k, v):
        return (ulysses_attention(q, k, v, topo.mesh, causal=True) ** 2).sum()

    def loss_dense(q, k, v):
        return (_dense_reference(q, k, v, True) ** 2).sum()

    g_u = jax.grad(loss_u, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for gr, gd in zip(g_u, g_dense):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gd), atol=5e-5, rtol=1e-3)


@pytest.mark.slow
def test_engine_sp_ring_and_ulysses_match_dense(devices):
    """Training through initialize() at sp=2 with ring/Ulysses attention must
    reproduce the dense-attention loss (same params, same batch)."""
    import dataclasses

    import deepspeed_tpu as ds
    from deepspeed_tpu.models import build_gpt
    from deepspeed_tpu.models.gpt import GPTConfig
    from deepspeed_tpu.runtime.topology import MeshTopology

    base = GPTConfig(vocab_size=64, d_model=32, n_layer=2, n_head=4,
                     max_seq_len=32, use_flash=False)
    batch = {"input_ids": np.random.default_rng(0).integers(
        0, 64, (8, 32), np.int32)}

    def loss_for(impl):
        model, _ = build_gpt(dataclasses.replace(base,
                                                 seq_parallel_impl=impl))
        engine, _, _, _ = ds.initialize(
            model=model, seed=11,
            topology=MeshTopology.create(dp=4, sp=2, devices=devices),
            config={
                "train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "mesh": {"dp": 4, "sp": 2},
                "steps_per_print": 0,
            })
        return float(engine.train_batch(batch)["loss"])

    dense = loss_for("dense")
    ring = loss_for("ring")
    uly = loss_for("ulysses")
    np.testing.assert_allclose(ring, dense, rtol=2e-5)
    np.testing.assert_allclose(uly, dense, rtol=2e-5)


@pytest.mark.slow
def test_sp_dispatch_survives_a_second_engine(devices):
    """A later engine binding a different topology must NOT downgrade a ring
    SP engine to dense attention: dispatch reads the trace-bound mesh."""
    import dataclasses
    from unittest import mock

    import deepspeed_tpu as ds
    from deepspeed_tpu import parallel as par
    from deepspeed_tpu.models import build_gpt
    from deepspeed_tpu.models.gpt import GPTConfig
    from deepspeed_tpu.runtime.topology import MeshTopology

    base = GPTConfig(vocab_size=64, d_model=32, n_layer=1, n_head=4,
                     max_seq_len=32, use_flash=False)
    model, _ = build_gpt(dataclasses.replace(base, seq_parallel_impl="ring"))
    ring_engine, _, _, _ = ds.initialize(
        model=model,
        topology=MeshTopology.create(dp=4, sp=2, devices=devices),
        config={"train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "mesh": {"dp": 4, "sp": 2}, "steps_per_print": 0})
    # a second, dp-only engine rebinds the global default topology
    other, _, _, _ = ds.initialize(
        model=build_gpt(base)[0],
        topology=MeshTopology.create(dp=8, devices=devices),
        config={"train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "mesh": {"dp": 8}, "steps_per_print": 0})
    calls = {"n": 0}
    real = par.ring_attention

    def spy(*a, **k):
        calls["n"] += 1
        return real(*a, **k)

    with mock.patch("deepspeed_tpu.parallel.ring_attention", side_effect=spy):
        b = {"input_ids": np.zeros((8, 32), np.int32)}
        m = ring_engine.train_batch(b)
    assert np.isfinite(float(m["loss"]))
    assert calls["n"] > 0  # the ring path actually traced
