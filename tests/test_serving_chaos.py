"""Chaos + overload tests for the continuous-batching scheduler
(docs/SERVING.md "Overload & failure"): typed admission verdicts, shed
policies, request deadlines, dispatch fault recovery (retry, preempt-and-
requeue, block-shape quarantine), and the page-conservation audit — all on
the device-free fake executor, each fault case asserting (a) the allocator
audit stays clean and (b) surviving requests' greedy outputs are IDENTICAL
to a fault-free run."""

import numpy as np
import pytest

from deepspeed_tpu.inference.serving import (ContinuousBatchingScheduler,
                                             Request, RequestState,
                                             ServingFaultError)
from deepspeed_tpu.resilience import (FaultPlan, HealthWatchdog, RecoveryLog,
                                      install_plan)


class FakeExecutor:
    """Deterministic device-free executor: prefill answers last+1, decode
    answers prev+1 (mod 97) — greedy outputs are an arithmetic function of
    the prompt alone, so fault-free and healed runs are directly
    comparable."""

    def __init__(self):
        self.prefills = 0
        self.decodes = 0

    def prefill(self, slot, tokens, table_row):
        self.prefills += 1
        return (int(tokens[-1]) + 1) % 97

    def decode(self, tokens, tables, lengths, active, steps=1):
        self.decodes += 1
        return np.stack([(tokens + k + 1) % 97 for k in range(steps)])


class BlockFailExecutor(FakeExecutor):
    """Decode dispatches at block size ``fail_steps`` always raise — the
    shape-specific executor bug the quarantine policy exists for."""

    def __init__(self, fail_steps):
        super().__init__()
        self.fail_steps = fail_steps

    def decode(self, tokens, tables, lengths, active, steps=1):
        if steps == self.fail_steps:
            raise RuntimeError(f"synthetic Mosaic failure at steps={steps}")
        return super().decode(tokens, tables, lengths, active, steps=steps)


def _sched(ex=None, num_slots=2, num_pages=32, page_size=4, pages_per_seq=8,
           decode_block=1, **kw):
    kw.setdefault("retry_base_delay", 0.001)
    kw.setdefault("retry_max_delay", 0.002)
    return ContinuousBatchingScheduler(
        ex or FakeExecutor(), num_slots=num_slots, num_pages=num_pages,
        page_size=page_size, pages_per_seq=pages_per_seq,
        decode_block=decode_block, **kw)


def _workload(spec=((3, 6), (5, 4), (2, 8), (4, 3))):
    return [Request(prompt=np.arange(1, n + 1, dtype=np.int32),
                    max_new_tokens=m) for n, m in spec]


def _run(sched, reqs):
    for r in reqs:
        sched.submit(r)
    sched.run_to_completion(max_steps=500)
    return [list(r.tokens) for r in reqs]


def _clean_outputs(spec=((3, 6), (5, 4), (2, 8), (4, 3)), **sched_kw):
    return _run(_sched(**sched_kw), _workload(spec))


@pytest.fixture(autouse=True)
def _no_leftover_plan():
    install_plan(None)
    yield
    install_plan(None)


# ------------------------------------------------------------ dispatch chaos
def test_dispatch_raise_retries_in_place():
    """A one-shot injected raise is absorbed by the retry (same dispatch
    episode); outputs identical to fault-free, no pages leaked."""
    clean = _clean_outputs()
    install_plan(FaultPlan(dispatch_raise_at=2))
    s = _sched()
    reqs = _workload()
    assert _run(s, reqs) == clean
    assert all(r.state is RequestState.FINISHED for r in reqs)
    assert s.counters.get("dispatch_error") == 1
    assert "dispatch_failed" not in s.counters  # episode never failed
    assert s.audit()["ok"], s.audit()
    assert s.allocator.allocated_pages == 0


def test_dispatch_raise_mid_decode_block_heals_by_requeue():
    """Every retry of one decode-block episode raises: the affected slots
    preempt-and-requeue with kept tokens, and the healed rerun is
    greedy-identical to a fault-free run."""
    clean = _clean_outputs(decode_block=4)
    # attempts = retries+1 = 3; indices 3..5 kill one whole episode
    install_plan(FaultPlan(dispatch_raise_at=3, dispatch_raise_times=3))
    s = _sched(decode_block=4, dispatch_retries=2)
    reqs = _workload()
    assert _run(s, reqs) == clean
    assert s.counters["dispatch_failed"] == 1
    assert s.counters["dispatch_error"] == 3
    assert sum(r.preemptions for r in reqs) >= 1  # requeue happened
    assert s.audit()["ok"]
    assert s.allocator.allocated_pages == 0


def test_failing_block_shape_is_quarantined():
    """A decode block shape that fails K consecutive episodes is quarantined;
    the run completes on smaller blocks with identical outputs."""
    # max_new 9/9: after the prefill token both slots have >=4 remaining,
    # so the scheduler genuinely reaches block size 4
    spec = ((3, 9), (5, 9))
    clean = _clean_outputs(spec=spec, decode_block=4)
    ex = BlockFailExecutor(fail_steps=4)
    s = _sched(ex, decode_block=4, dispatch_retries=1, quarantine_after=2,
               dispatch_failure_budget=8)
    reqs = _workload(spec=spec)
    assert _run(s, reqs) == clean
    assert 4 in s._quarantined_blocks
    assert s.counters["block_quarantined"] == 1
    assert s.counters["dispatch_failed"] == 2  # exactly K episodes burned
    assert s.audit()["ok"]
    assert s.allocator.allocated_pages == 0


def test_dispatch_failure_budget_raises_loudly():
    class DeadExecutor(FakeExecutor):
        def decode(self, *a, **kw):
            raise RuntimeError("executor is gone")

    s = _sched(DeadExecutor(), dispatch_retries=0,
               dispatch_failure_budget=3)
    s.submit(Request(prompt=np.array([1], np.int32), max_new_tokens=4))
    with pytest.raises(ServingFaultError, match="3 consecutive"):
        s.run_to_completion(max_steps=50)
    assert s.audit()["ok"]  # even the give-up path leaks nothing


def test_stalled_prefill_flagged_by_watchdog():
    """An injected prefill stall trips the serving_prefill deadline: the
    watchdog records watchdog_stall (and recovery on completion), the run
    still finishes with fault-free outputs."""
    clean = _clean_outputs()
    log = RecoveryLog()  # counters only
    wd = HealthWatchdog({"serving_prefill": 0.05, "serving_decode": 5.0},
                        poll_interval=0.01, recovery_log=log).start()
    try:
        install_plan(FaultPlan(dispatch_stall_at=0,
                               dispatch_stall_seconds=0.25))
        s = _sched(watchdog=wd, recovery_log=log)
        reqs = _workload()
        assert _run(s, reqs) == clean
    finally:
        wd.stop()
    assert log.count("watchdog_stall") == 1
    assert log.count("watchdog_recovered") == 1  # a stall, not a deadlock
    assert s.audit()["ok"]


def test_alloc_failure_at_admit_degrades_to_queueing():
    """A chaos-failed page alloc at admission looks exactly like pool
    pressure: the request waits one cycle and then serves, outputs
    unchanged."""
    clean = _clean_outputs()
    install_plan(FaultPlan(alloc_fail_at=0, alloc_fail_times=1))
    s = _sched()
    reqs = _workload()
    assert _run(s, reqs) == clean
    assert all(r.state is RequestState.FINISHED for r in reqs)
    assert s.audit()["ok"]
    assert s.allocator.allocated_pages == 0


# ---------------------------------------------------------------- deadlines
class ManualClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_deadline_expiry_under_load_frees_pages_and_spares_survivors():
    """One slot, three requests: the queued ones blow their deadlines while
    the head runs. Expired requests are evicted with pages freed and a
    deadline_miss recorded; the survivor's output matches a fault-free run."""
    clean = _clean_outputs(spec=((3, 6),), num_slots=1)
    ck = ManualClock()
    s = _sched(num_slots=1, clock=ck, deadline_s=10.0)
    head = Request(prompt=np.arange(1, 4, dtype=np.int32), max_new_tokens=6)
    waiters = [Request(prompt=np.array([7], np.int32), max_new_tokens=4)
               for _ in range(2)]
    for r in (head, *waiters):
        assert s.submit(r)
    s.step()          # head admitted + first decode
    ck.t = 11.0       # everyone past the e2e deadline
    s.step()
    assert head.state is RequestState.EXPIRED
    assert all(w.state is RequestState.EXPIRED for w in waiters)
    assert s.counters["deadline_miss"] == 3
    assert s.allocator.allocated_pages == 0
    assert s.audit()["ok"]
    # a fresh request on the SAME scheduler after the sweep is unaffected
    ck.t = 12.0
    survivor = Request(prompt=np.arange(1, 4, dtype=np.int32),
                       max_new_tokens=6)
    assert s.submit(survivor)
    s.run_to_completion()
    assert [list(survivor.tokens)] == clean


def test_ttft_deadline_expires_only_queued_requests():
    ck = ManualClock()
    s = _sched(num_slots=1, clock=ck, ttft_deadline_s=1.0)
    a = Request(prompt=np.array([1], np.int32), max_new_tokens=10)
    b = Request(prompt=np.array([2], np.int32), max_new_tokens=10)
    s.submit(a)
    s.submit(b)       # one slot: b queues behind a
    s.step()          # a admitted (TTFT met); b still queued
    ck.t = 2.0
    s.step()
    assert b.state is RequestState.EXPIRED  # never got its first token
    s.run_to_completion()
    assert a.state is RequestState.FINISHED  # running: TTFT already met
    assert s.audit()["ok"]


def test_ttft_deadline_spares_preempted_requests():
    """A preempted request back in the queue has ALREADY delivered its first
    token — the TTFT sweep must not expire it (regression: the sweep used
    to check only t_submit, killing healthy in-flight work under the
    routine pool-pressure preemption path)."""
    ck = ManualClock()
    # 7 usable pages, page size 2: two growing requests force preemption
    s = _sched(num_slots=2, num_pages=8, page_size=2, pages_per_seq=8,
               clock=ck, ttft_deadline_s=1.0)
    a = Request(prompt=np.array([1, 2, 3], np.int32), max_new_tokens=8)
    b = Request(prompt=np.array([50, 51, 52], np.int32), max_new_tokens=8)
    assert s.submit(a) and s.submit(b)
    while not s.idle:
        s.step()
        ck.t += 2.0  # every wait is "too long" for a fresh TTFT clock
    assert b.preemptions >= 1  # the preemption path genuinely ran
    assert a.state is RequestState.FINISHED
    assert b.state is RequestState.FINISHED  # not expired while requeued
    assert a.tokens == [(4 + i) % 97 for i in range(8)]
    assert b.tokens == [(53 + i) % 97 for i in range(8)]
    assert s.audit()["ok"]


def test_reject_largest_never_sheds_without_admitting():
    """Shedding is only committed when it actually admits the incoming
    request — nobody dies for a rejection (regression: victims used to be
    shed first and the incoming rejected anyway when the freed room was
    insufficient)."""
    s = _sched(num_slots=1, max_queued_tokens=30,
               shed_policy="reject_largest")
    mid1 = Request(prompt=np.ones(8, np.int32), max_new_tokens=6)   # 14
    mid2 = Request(prompt=np.ones(8, np.int32), max_new_tokens=6)   # 14
    assert s.submit(mid1) and s.submit(mid2)                        # 28/30
    # incoming work 12: shedding ONE 14-token victim frees room (28-14+12
    # = 26 <= 30) -> one victim, admitted
    ok = Request(prompt=np.ones(6, np.int32), max_new_tokens=6)
    v = s.submit(ok)
    assert v and v.shed_rid in (mid1.rid, mid2.rid)
    assert s.counters["request_shed"] == 1
    # now queue holds 14 + 12 = 26. An incoming 13-token request cannot be
    # admitted even if every strictly-larger victim (the 14) is shed
    # (12 + 13 = 25... the 12 is not larger, so only the 14 may die:
    # 26-14+13 = 25 <= 30 -> admissible). Build a REAL impossible case:
    # max_queue=1 with a smaller queued request — nothing larger exists,
    # so the incoming must bounce with the queue untouched.
    s2 = _sched(num_slots=1, max_queue=1, shed_policy="reject_largest")
    small = Request(prompt=np.ones(2, np.int32), max_new_tokens=2)
    assert s2.submit(small)
    big = Request(prompt=np.ones(8, np.int32), max_new_tokens=8)
    v2 = s2.submit(big)
    assert not v2 and v2.reason == "queue_full"
    assert small.state is RequestState.QUEUED  # victim NOT sacrificed
    assert s2.counters.get("request_shed", 0) == 1  # only big itself
    assert list(s2.queue) == [small]


def test_per_request_deadline_overrides_scheduler_default():
    ck = ManualClock()
    s = _sched(num_slots=2, clock=ck, deadline_s=100.0)
    tight = Request(prompt=np.array([1], np.int32), max_new_tokens=20,
                    deadline_s=1.0)
    loose = Request(prompt=np.array([2], np.int32), max_new_tokens=4)
    s.submit(tight)
    s.submit(loose)
    s.step()
    ck.t = 2.0
    s.run_to_completion()
    assert tight.state is RequestState.EXPIRED
    assert loose.state is RequestState.FINISHED


# ---------------------------------------------------------- overload control
def test_queue_depth_cap_returns_typed_rejection():
    s = _sched(num_slots=1, max_queue=2)
    ok = [Request(prompt=np.array([1], np.int32), max_new_tokens=2)
          for _ in range(2)]
    for r in ok:
        assert s.submit(r)
    over = Request(prompt=np.array([9], np.int32), max_new_tokens=2)
    v = s.submit(over)
    assert not v and v.reason == "queue_full"
    assert over.state is RequestState.REJECTED
    assert over.reject_reason == "queue_full"
    assert len(s.queue) == 2  # nothing silently enqueued
    s.run_to_completion()
    assert all(r.state is RequestState.FINISHED for r in ok)


def test_token_budget_backpressure():
    s = _sched(num_slots=1, max_queued_tokens=20)
    a = Request(prompt=np.ones(8, np.int32), max_new_tokens=8)   # 16 tokens
    b = Request(prompt=np.ones(4, np.int32), max_new_tokens=4)   # 8 tokens
    assert s.submit(a)
    v = s.submit(b)  # 16 + 8 > 20
    assert not v and v.reason == "token_backlog"
    assert s.queued_tokens == 16


def test_reject_largest_sheds_the_biggest_queued_request():
    s = _sched(num_slots=1, max_queued_tokens=24,
               shed_policy="reject_largest")
    big = Request(prompt=np.ones(12, np.int32), max_new_tokens=8)  # 20
    small = Request(prompt=np.ones(3, np.int32), max_new_tokens=3)  # 6
    assert s.submit(big)
    v = s.submit(small)  # 20 + 6 > 24: big (larger) is shed instead
    assert v and v.shed_rid == big.rid
    assert big.state is RequestState.REJECTED
    assert big.reject_reason == "shed_for_smaller"
    assert s.counters["request_shed"] == 1
    # but an incoming request that is ITSELF the largest gets rejected
    huge = Request(prompt=np.ones(20, np.int32), max_new_tokens=8)
    v2 = s.submit(huge)
    assert not v2 and v2.reason == "token_backlog"


def test_shed_and_expired_requests_never_leak_into_results():
    """End-to-end under a tiny queue cap: rejected/expired requests stay
    terminal, everything admitted finishes with fault-free outputs."""
    clean = _clean_outputs(spec=((3, 6), (5, 4)))
    s = _sched(num_slots=1, max_queue=2)
    reqs = _workload(spec=((3, 6), (5, 4), (2, 8), (4, 3)))
    verdicts = [s.submit(r) for r in reqs]
    assert [bool(v) for v in verdicts] == [True, True, False, False]
    s.run_to_completion()
    assert [list(r.tokens) for r in reqs[:2]] == clean
    assert all(r.state is RequestState.REJECTED for r in reqs[2:])
    assert s.audit()["ok"]


def test_serving_events_reach_the_recovery_log():
    """Scheduler recovery events flow through RecoveryLog with the Serving/*
    scalar prefix — the observable trail the ISSUE's monitor wiring needs."""
    seen = []

    class Mon:
        def write_events(self, evs):
            seen.extend(evs)

    log = RecoveryLog(monitor=Mon(), role="serving", prefix="Serving")
    s = _sched(num_slots=1, max_queue=0, recovery_log=log)
    r = Request(prompt=np.array([1], np.int32), max_new_tokens=2)
    assert not s.submit(r)
    assert log.count("request_shed") == 1
    assert seen and seen[0][0] == "Serving/request_shed"
