"""Megatron-LM monolithic checkpoint policies (module_inject/megatron.py).

Parity targets: ``module_inject/containers/megatron_gpt.py`` (MegatronLayerPolicy)
and ``containers/megatron_gpt_moe.py`` (MegatronMoELayerPolicy). Tests build a
synthetic Megatron-LM state dict by INVERSE-mapping native params (including the
megatron_v2 per-head qkv interleave the reference undoes in
``features/megatron.py:transpose_qkv_alignment``) and assert the import recovers
the exact arrays and yields a runnable model.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.models import gpt as gpt_mod
from deepspeed_tpu.models import gpt_moe as moe_mod
from deepspeed_tpu.module_inject import (import_megatron_gpt,
                                         import_megatron_gpt_moe)

H, DH = 2, 4
D = H * DH
L, F, V, S = 4, 16, 32, 16


def _interleave_qkv(qkv_w, qkv_b):
    """Native [D, 3D] block q|k|v -> Megatron-v2 [3D, D] per-head interleaved."""
    block_w = np.asarray(qkv_w).T            # [3D out, D in], q|k|v row blocks
    meg_w = (block_w.reshape(3, H, DH, D).transpose(1, 0, 2, 3)
             .reshape(3 * D, D))
    meg_b = (np.asarray(qkv_b).reshape(3, H, DH).transpose(1, 0, 2)
             .reshape(3 * D))
    return meg_w, meg_b


def _attn_keys(pre, blk, i, attn="self_attention"):
    meg_w, meg_b = _interleave_qkv(blk["qkv_w"][i], blk["qkv_b"][i])
    return {
        f"{pre}.input_layernorm.weight": np.asarray(blk["ln1_scale"][i]),
        f"{pre}.input_layernorm.bias": np.asarray(blk["ln1_bias"][i]),
        f"{pre}.{attn}.query_key_value.weight": meg_w,
        f"{pre}.{attn}.query_key_value.bias": meg_b,
        f"{pre}.{attn}.dense.weight": np.asarray(blk["attn_out_w"][i]).T,
        f"{pre}.{attn}.dense.bias": np.asarray(blk["attn_out_b"][i]),
        f"{pre}.post_attention_layernorm.weight": np.asarray(blk["ln2_scale"][i]),
        f"{pre}.post_attention_layernorm.bias": np.asarray(blk["ln2_bias"][i]),
    }


def _mlp_keys(pre, blk, i, mlp="mlp"):
    return {
        f"{pre}.{mlp}.dense_h_to_4h.weight": np.asarray(blk["mlp_up_w"][i]).T,
        f"{pre}.{mlp}.dense_h_to_4h.bias": np.asarray(blk["mlp_up_b"][i]),
        f"{pre}.{mlp}.dense_4h_to_h.weight": np.asarray(blk["mlp_down_w"][i]).T,
        f"{pre}.{mlp}.dense_4h_to_h.bias": np.asarray(blk["mlp_down_b"][i]),
    }


def _dense_cfg():
    return gpt_mod.GPTConfig(vocab_size=V, n_layer=L, n_head=H, d_model=D,
                             d_ff=F, max_seq_len=S, rotary=False,
                             tie_embeddings=True)


def _dense_megatron_sd(params, attn="self_attention", prefix="language_model."):
    sd = {
        prefix + "embedding.word_embeddings.weight": np.asarray(params["wte"]),
        prefix + "embedding.position_embeddings.weight":
            np.asarray(params["wpe"]),
        prefix + "transformer.final_layernorm.weight":
            np.asarray(params["lnf_scale"]),
        prefix + "transformer.final_layernorm.bias":
            np.asarray(params["lnf_bias"]),
    }
    for i in range(L):
        pre = prefix + f"transformer.layers.{i}"
        sd.update(_attn_keys(pre, params["blocks"], i, attn))
        sd.update(_mlp_keys(pre, params["blocks"], i))
    return sd


@pytest.mark.slow
def test_dense_roundtrip_exact():
    cfg = _dense_cfg()
    params = gpt_mod.init_params(cfg, jax.random.PRNGKey(0))
    sd = _dense_megatron_sd(params)
    icfg, iparams = import_megatron_gpt(sd, n_head=H)
    assert (icfg.n_layer, icfg.d_model, icfg.n_head, icfg.d_ff) == (L, D, H, F)
    assert not icfg.rotary and icfg.tie_embeddings
    for k in ("qkv_w", "qkv_b", "attn_out_w", "mlp_up_w", "mlp_down_w"):
        np.testing.assert_allclose(iparams["blocks"][k], params["blocks"][k],
                                   rtol=0, atol=0, err_msg=k)
    np.testing.assert_array_equal(iparams["wte"], params["wte"])
    np.testing.assert_array_equal(iparams["wpe"], params["wpe"])
    # imported model is directly runnable
    ids = jnp.zeros((1, 8), jnp.int32)
    logits = gpt_mod.forward(icfg, iparams, ids, train=False)
    assert logits.shape == (1, 8, V)
    ref = gpt_mod.forward(cfg, params, ids, train=False)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref),
                               rtol=1e-6, atol=1e-6)


def test_dense_version0_attention_naming_and_model_prefix():
    """version-0 checkpoints use ``attention.`` and often a ``model.`` wrap."""
    cfg = _dense_cfg()
    params = gpt_mod.init_params(cfg, jax.random.PRNGKey(1))
    sd = _dense_megatron_sd(params, attn="attention",
                            prefix="model.language_model.")
    icfg, iparams = import_megatron_gpt(sd, n_head=H)
    np.testing.assert_array_equal(iparams["blocks"]["qkv_w"],
                                  params["blocks"]["qkv_w"])


def test_dense_v1_no_interleave():
    """megatron_v2=False: qkv rows already q|k|v block-ordered."""
    cfg = _dense_cfg()
    params = gpt_mod.init_params(cfg, jax.random.PRNGKey(2))
    sd = _dense_megatron_sd(params)
    for i in range(L):
        pre = f"language_model.transformer.layers.{i}.self_attention"
        sd[pre + ".query_key_value.weight"] = \
            np.asarray(params["blocks"]["qkv_w"][i]).T
        sd[pre + ".query_key_value.bias"] = \
            np.asarray(params["blocks"]["qkv_b"][i])
    icfg, iparams = import_megatron_gpt(sd, n_head=H, megatron_v2=False)
    np.testing.assert_array_equal(iparams["blocks"]["qkv_w"],
                                  params["blocks"]["qkv_w"])


def _moe_cfg(use_residual=False):
    return moe_mod.GPTMoEConfig(base=_dense_cfg(), num_experts=4, moe_freq=2,
                                use_residual=use_residual)


def _moe_megatron_sd(cfg, params):
    """Scatter native MoE params into the reference's Megatron-MoE naming."""
    prefix = "language_model."
    base = cfg.base
    sd = {
        prefix + "embedding.word_embeddings.weight": np.asarray(params["wte"]),
        prefix + "embedding.position_embeddings.weight":
            np.asarray(params["wpe"]),
        prefix + "transformer.final_layernorm.weight":
            np.asarray(params["lnf_scale"]),
        prefix + "transformer.final_layernorm.bias":
            np.asarray(params["lnf_bias"]),
    }
    moe_pos = [s * cfg.moe_freq + cfg.moe_freq - 1 for s in range(cfg.n_super)]
    dense_i = moe_i = 0
    moe_pre = ("mlp.moe.deepspeed_moe." if cfg.use_residual
               else "mlp.deepspeed_moe.")
    for i in range(base.n_layer):
        pre = prefix + f"transformer.layers.{i}"
        if i in moe_pos:
            blk = params["moe_blocks"]
            sd.update(_attn_keys(pre, blk, moe_i))
            moe = blk["moe"]
            sd[f"{pre}.{moe_pre}gate.wg.weight"] = \
                np.asarray(moe["gate_w"][moe_i]).T
            ex = moe["experts"]
            for e in range(cfg.num_experts):
                epre = f"{pre}.{moe_pre}experts.deepspeed_experts.{e}"
                sd[epre + ".dense_h_to_4h.weight"] = \
                    np.asarray(ex["up_w"][moe_i, e]).T
                sd[epre + ".dense_h_to_4h.bias"] = \
                    np.asarray(ex["up_b"][moe_i, e])
                sd[epre + ".dense_4h_to_h.weight"] = \
                    np.asarray(ex["down_w"][moe_i, e]).T
                sd[epre + ".dense_4h_to_h.bias"] = \
                    np.asarray(ex["down_b"][moe_i, e])
            if cfg.use_residual:
                res = moe["residual_mlp"]
                sd[f"{pre}.mlp.mlp.dense_h_to_4h.weight"] = \
                    np.asarray(res["up_w"][moe_i]).T
                sd[f"{pre}.mlp.mlp.dense_h_to_4h.bias"] = \
                    np.asarray(res["up_b"][moe_i])
                sd[f"{pre}.mlp.mlp.dense_4h_to_h.weight"] = \
                    np.asarray(res["down_w"][moe_i]).T
                sd[f"{pre}.mlp.mlp.dense_4h_to_h.bias"] = \
                    np.asarray(res["down_b"][moe_i])
                sd[f"{pre}.mlp.coefficient.weight"] = \
                    np.asarray(moe["coefficient"][moe_i]).T
            moe_i += 1
        else:
            blk = params["blocks"]
            sd.update(_attn_keys(pre, blk, dense_i))
            sd.update(_mlp_keys(pre, blk, dense_i))
            dense_i += 1
    return sd


@pytest.mark.parametrize("use_residual", [False, True],
                         ids=["standard", "pr-moe"])
@pytest.mark.slow
def test_moe_roundtrip(use_residual):
    cfg = _moe_cfg(use_residual)
    params = moe_mod.init_params(cfg, jax.random.PRNGKey(3))
    sd = _moe_megatron_sd(cfg, params)
    icfg, iparams = import_megatron_gpt_moe(sd, n_head=H)
    assert icfg.num_experts == cfg.num_experts
    assert icfg.moe_freq == cfg.moe_freq
    assert icfg.use_residual == use_residual
    ex, iex = params["moe_blocks"]["moe"]["experts"], \
        iparams["moe_blocks"]["moe"]["experts"]
    for k in ex:
        np.testing.assert_allclose(iex[k], ex[k], rtol=0, atol=0, err_msg=k)
    np.testing.assert_array_equal(iparams["moe_blocks"]["moe"]["gate_w"],
                                  params["moe_blocks"]["moe"]["gate_w"])
    np.testing.assert_array_equal(iparams["blocks"]["mlp_up_w"],
                                  params["blocks"]["mlp_up_w"])
    if use_residual:
        np.testing.assert_array_equal(
            iparams["moe_blocks"]["moe"]["coefficient"],
            params["moe_blocks"]["moe"]["coefficient"])
    ids = jnp.zeros((1, 8), jnp.int32)
    logits, _ = moe_mod.forward(icfg, iparams, ids, train=False)
    ref, _ = moe_mod.forward(cfg, params, ids, train=False)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_dense_import_rejects_moe_and_vice_versa():
    cfg = _moe_cfg()
    params = moe_mod.init_params(cfg, jax.random.PRNGKey(4))
    sd = _moe_megatron_sd(cfg, params)
    with pytest.raises(ValueError, match="import_megatron_gpt_moe"):
        import_megatron_gpt(sd, n_head=H)
    dcfg = _dense_cfg()
    dparams = gpt_mod.init_params(dcfg, jax.random.PRNGKey(5))
    with pytest.raises(ValueError, match="import_megatron_gpt"):
        import_megatron_gpt_moe(_dense_megatron_sd(dparams), n_head=H)


def test_moe_irregular_pattern_rejected():
    cfg = _moe_cfg()
    params = moe_mod.init_params(cfg, jax.random.PRNGKey(6))
    sd = _moe_megatron_sd(cfg, params)
    # rename layer-1's MoE keys to layer 0: dense-first ordering violated
    moved = {(k.replace(".layers.1.", ".layers.0.")
              if ".layers.1.mlp.deepspeed_moe." in k else k): v
             for k, v in sd.items()}
    with pytest.raises(ValueError, match="regular"):
        import_megatron_gpt_moe(moved, n_head=H)


def test_nested_model_optim_rng_structure():
    """Real ``model_optim_rng.pt`` nests dicts: model -> language_model ->
    embedding/encoder sub-dicts with tensor leaves (plus non-model state)."""
    cfg = _dense_cfg()
    params = gpt_mod.init_params(cfg, jax.random.PRNGKey(7))
    flat = _dense_megatron_sd(params, prefix="")
    nested: dict = {"checkpoint_version": np.float64(3.0)}
    lm: dict = {}
    for k, v in flat.items():
        node, parts = lm, k.split(".")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = v
    nested["model"] = {"language_model": lm}
    icfg, iparams = import_megatron_gpt(nested, n_head=H)
    np.testing.assert_array_equal(iparams["blocks"]["qkv_w"],
                                  params["blocks"]["qkv_w"])
    np.testing.assert_array_equal(iparams["wte"], params["wte"])


def test_not_a_megatron_checkpoint():
    with pytest.raises(ValueError, match="language_model"):
        import_megatron_gpt({"transformer.h.0.attn.weight":
                             np.zeros((4, 4))}, n_head=2)
