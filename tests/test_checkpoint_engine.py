"""Checkpoint engines, zero_to_fp32 consolidation, save_16bit_model."""

import os
import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.runtime.checkpoint_engine import (
    AsyncCheckpointEngine,
    NativeCheckpointEngine,
    get_checkpoint_engine,
)
from deepspeed_tpu.utils.zero_to_fp32 import (
    convert_zero_checkpoint_to_fp32_state_dict,
    get_fp32_state_dict_from_zero_checkpoint,
)


def _engine(config_extra=None):
    from deepspeed_tpu.models import build_gpt
    from deepspeed_tpu.models.gpt import GPTConfig

    model, cfg = build_gpt(GPTConfig(
        vocab_size=64, d_model=32, n_layer=2, n_head=2, max_seq_len=16))
    config = {"train_micro_batch_size_per_gpu": 1,
              "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
              "steps_per_print": 0}
    config.update(config_extra or {})
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)
    return engine, cfg


def _batch(cfg, seed=0):
    r = np.random.default_rng(seed)
    return {"input_ids": r.integers(0, cfg.vocab_size, size=(8, 16), dtype=np.int32)}


# --------------------------------------------------------------------- engines
def test_native_engine_roundtrip(tmp_path, rng):
    e = NativeCheckpointEngine()
    sd = {"a": rng.normal(size=(4, 4)).astype(np.float32),
          "b": np.arange(10, dtype=np.int64)}
    path = str(tmp_path / "x.npz")
    e.save(sd, path)
    out = e.load(path)
    np.testing.assert_array_equal(out["a"], sd["a"])
    np.testing.assert_array_equal(out["b"], sd["b"])
    assert e.commit("t") is True


def test_async_engine_overlaps_and_commits(tmp_path, rng):
    e = AsyncCheckpointEngine(writers=2)
    paths = []
    for i in range(8):
        sd = {"a": rng.normal(size=(64, 64)).astype(np.float32)}
        p = str(tmp_path / f"c{i}.npz")
        e.save(sd, p)
        paths.append((p, sd["a"].copy()))
    e.commit("tag")  # durability barrier
    for p, a in paths:
        np.testing.assert_array_equal(NativeCheckpointEngine().load(p)["a"], a)
    e.shutdown()


def test_async_engine_snapshot_isolation(tmp_path):
    e = AsyncCheckpointEngine(writers=1)
    arr = np.ones((32,), np.float32)
    e.save({"a": arr}, str(tmp_path / "snap.npz"))
    arr[:] = -1  # mutate after enqueue: snapshot must have the old value
    e.commit("t")
    out = NativeCheckpointEngine().load(str(tmp_path / "snap.npz"))
    np.testing.assert_array_equal(out["a"], np.ones((32,), np.float32))
    e.shutdown()


def test_get_checkpoint_engine_selection():
    assert isinstance(get_checkpoint_engine(None), NativeCheckpointEngine)
    assert isinstance(get_checkpoint_engine(
        {"checkpoint": {"checkpoint_engine": "async"}}), AsyncCheckpointEngine)
    assert isinstance(get_checkpoint_engine(
        {"checkpoint": {"checkpoint_engine": "nebula"}}), AsyncCheckpointEngine)


@pytest.mark.slow
def test_engine_save_with_async_checkpoint_engine(tmp_path):
    engine, cfg = _engine({"checkpoint": {"checkpoint_engine": "async"}})
    b = _batch(cfg)
    engine.train_batch(b)
    ckpt = engine.save_checkpoint(str(tmp_path))
    assert os.path.exists(os.path.join(ckpt, "state", "state.msgpack"))
    # reload into a fresh engine and continue identically
    e2, _ = _engine({"checkpoint": {"checkpoint_engine": "async"}})
    e2.load_checkpoint(str(tmp_path))
    m1 = engine.train_batch(b)
    m2 = e2.train_batch(b)
    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-6)


# --------------------------------------------------------------------- zero_to_fp32
def test_zero_to_fp32_consolidation(tmp_path):
    engine, cfg = _engine({"bf16": {"enabled": True},
                           "zero_optimization": {"stage": 2}})
    engine.train_batch(_batch(cfg))
    engine.save_checkpoint(str(tmp_path))

    sd = get_fp32_state_dict_from_zero_checkpoint(str(tmp_path))
    assert all(v.dtype == np.float32 for v in sd.values())
    # master copy preferred: values match the training master, full precision
    master_wte = np.asarray(engine.state["master"]["wte"], np.float32)
    np.testing.assert_array_equal(sd["wte"], master_wte)

    out = str(tmp_path / "consolidated.npz")
    convert_zero_checkpoint_to_fp32_state_dict(str(tmp_path), out)
    with np.load(out) as d:
        np.testing.assert_array_equal(d["wte"], master_wte)


def test_zero_to_fp32_cli(tmp_path):
    from deepspeed_tpu.utils.zero_to_fp32 import main

    engine, cfg = _engine()
    engine.train_batch(_batch(cfg))
    engine.save_checkpoint(str(tmp_path))
    out = str(tmp_path / "out.npz")
    assert main([str(tmp_path), out]) == 0
    assert os.path.exists(out)
    assert main([]) == 1  # usage


# --------------------------------------------------------------------- 16bit save
def test_save_16bit_model(tmp_path):
    engine, cfg = _engine({"bf16": {"enabled": True}})
    engine.train_batch(_batch(cfg))
    path = engine.save_16bit_model(str(tmp_path))
    assert os.path.exists(path)
    with np.load(path) as d:
        keys = list(d.keys())
        assert any(k.endswith("::bfloat16") for k in keys)
        wte_key = [k for k in keys if k.startswith("wte")][0]
        import ml_dtypes

        arr = d[wte_key].view(ml_dtypes.bfloat16)
        np.testing.assert_array_equal(
            arr, np.asarray(engine.state["params"]["wte"]))


@pytest.mark.slow
def test_save_16bit_model_stage3_requires_flag(tmp_path):
    engine, cfg = _engine({"bf16": {"enabled": True},
                           "zero_optimization": {"stage": 3}})
    engine.train_batch(_batch(cfg))
    with pytest.raises(ValueError, match="stage3_gather_16bit"):
        engine.save_16bit_model(str(tmp_path))
    engine2, cfg2 = _engine({
        "bf16": {"enabled": True},
        "zero_optimization": {
            "stage": 3, "stage3_gather_16bit_weights_on_model_save": True}})
    engine2.train_batch(_batch(cfg2))
    assert os.path.exists(engine2.save_16bit_model(str(tmp_path)))


# ------------------------------------------------------- crash consistency (PR 4)
def test_native_save_array_is_atomic(tmp_path, monkeypatch, rng):
    """save_array must be tmp-then-replace: a failure between serialize and
    publish leaves NO file (torn or otherwise) under the final name."""
    e = NativeCheckpointEngine()
    arr = rng.normal(size=(16,)).astype(np.float32)
    e.save_array(str(tmp_path / "a.npy"), arr)
    np.testing.assert_array_equal(np.load(tmp_path / "a.npy"), arr)
    assert not list(tmp_path.glob("*.tmp"))

    def boom(src, dst):
        raise OSError("fs died at publish time")

    monkeypatch.setattr(os, "replace", boom)
    from deepspeed_tpu.resilience.retry import RetryingWriter

    e2 = NativeCheckpointEngine()
    e2._writer = RetryingWriter(attempts=2, sleep=lambda d: None)
    with pytest.raises(OSError, match="after 2 attempts"):
        e2.save_array(str(tmp_path / "b.npy"), arr)
    monkeypatch.undo()
    assert not (tmp_path / "b.npy").exists()
    assert not list(tmp_path.glob("*.tmp"))


def test_async_commit_raises_on_background_write_error(tmp_path):
    """A failed background write must fail commit() loudly — a commit that
    returns True over a lost shard is a fabricated durability point."""
    from deepspeed_tpu.runtime.checkpoint_engine.checkpoint_engine import (
        CheckpointWriteError,
    )

    e = AsyncCheckpointEngine(writers=1)
    blocker = tmp_path / "blocker"
    blocker.write_text("a file where a directory is needed")
    e.save({"a": np.ones((4,), np.float32)},
           str(blocker / "sub" / "x.npz"))  # makedirs under a file -> OSError
    with pytest.raises(CheckpointWriteError, match="async checkpoint writes failed"):
        e.commit("tag")
    # errors are consumed by the raise; a subsequent good save commits fine
    e.save({"a": np.ones((4,), np.float32)}, str(tmp_path / "ok.npz"))
    assert e.commit("tag2") is True
    e.shutdown()
