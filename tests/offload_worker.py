"""Offload-stream worker fixture: tiny GPT trained through the ZeRO-Infinity
param stream (host masters, streamed units) on one forced-CPU device, with
the ``resilience`` block enabled (auto-resume). Checkpoints after every
step. Faults are injected via ``DS_FAULT_PLAN`` set by the driver
(test_infinity_stream.py, scripts/offload_smoke.py) — the worker has no
fault-specific code: a ``kill_at_phase: "host-shard:N"`` plan SIGKILLs the
process inside the REAL per-unit host-state flush.

Exit codes: 0 = reached --steps; -9 / 137 = the fault plan's SIGKILL fired.
"""

import argparse
import json
import os
import sys


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--ckpt-dir", required=True)
    p.add_argument("--steps", type=int, required=True)
    p.add_argument("--log", default=None, help="jsonl per-step log")
    p.add_argument("--save-every", type=int, default=1)
    p.add_argument("--prefetch-depth", type=int, default=2)
    args = p.parse_args()

    # single forced-CPU device, independent of the inherited test env
    flags = " ".join(
        f for f in os.environ.get("XLA_FLAGS", "").split()
        if not f.startswith("--xla_force_host_platform_device_count"))
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=1"
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["DS_TPU_ACCELERATOR"] = "cpu"
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import deepspeed_tpu as ds
    from deepspeed_tpu.models import build_gpt, gpt

    model, _ = build_gpt(gpt.GPTConfig(
        vocab_size=64, n_layer=3, n_head=2, d_model=32, max_seq_len=32))
    engine, _, _, _ = ds.initialize(model=model, config={
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "bf16": {"enabled": False},
        "steps_per_print": 0,
        "zero_optimization": {"offload_param": {
            "device": "cpu", "buffer_count": 1,
            "prefetch_depth": args.prefetch_depth}},
        # auto-resume from the newest committed tag
        "resilience": {"enabled": True, "save_dir": args.ckpt_dir},
    })

    def batch_for(step: int):
        r = np.random.default_rng(1000 + step)
        return {"input_ids": r.integers(0, 64, size=(2, 16), dtype=np.int32)}

    while engine.global_steps < args.steps:
        m = engine.train_batch(batch_for(engine.global_steps))
        if args.log:
            with open(args.log, "a") as f:
                f.write(json.dumps({"step": engine.global_steps,
                                    "loss": float(m["loss"]),
                                    "grad_norm": float(m["grad_norm"])})
                        + "\n")
        if engine.global_steps % max(1, args.save_every) == 0:
            engine.save_checkpoint(args.ckpt_dir)
    return 0


if __name__ == "__main__":
    sys.exit(main())
