"""The bench/chip-session config lists must be executable as-is: a malformed
spec discovered at tunnel-up time would burn the measurement window (the
round-3 post-mortem failure mode this guards against)."""

import json

import pytest


def _bench():
    import importlib
    import sys

    sys.path.insert(0, "/root/repo")
    import bench

    return importlib.reload(bench)


def test_all_config_lists_have_registered_kinds_and_serialize():
    bench = _bench()
    kinds = {"train", "inference", "kernels", "diffusion", "pipeline_aot",
             "pipeline_mpmd", "train_aot", "kernels_aot", "infinity_aot",
             "moe_aot", "infer_aot", "sd_aot"}
    for lst in (bench.INFINITY_CONFIGS, bench.PIPELINE_CONFIGS,
                bench.AOT_TRAIN_CONFIGS):
        assert lst, "config list emptied"
        for cfg in lst:
            assert cfg["kind"] in kinds, cfg
            assert cfg["name"]
            json.dumps(cfg)  # the worker boundary is a JSON argv


def test_train_configs_reference_real_presets():
    bench = _bench()
    from deepspeed_tpu.models import gpt
    from deepspeed_tpu.models.gpt_moe import PRESETS as MOE

    for lst in (bench.INFINITY_CONFIGS, bench.PIPELINE_CONFIGS,
                bench.AOT_TRAIN_CONFIGS):
        for cfg in lst:
            model = cfg.get("model")
            if model:
                assert model in gpt.PRESETS or model in MOE, cfg
            if cfg.get("remat_policy") and cfg["remat_policy"] != \
                    "save_attn_mlp_out":
                assert hasattr(__import__("jax").checkpoint_policies,
                               cfg["remat_policy"]), cfg


def test_chip_session_grid_is_executable():
    """Every chip-session sweep spec must parse against mfu_sweep's knobs."""
    import ast
    import os

    src = open("/root/repo/scripts/chip_session.py").read()
    tree = ast.parse(src)
    # find the sweep_grid literal and evaluate it
    grids = [node for node in ast.walk(tree)
             if isinstance(node, ast.Assign)
             and any(getattr(t, "id", None) == "sweep_grid"
                     for t in node.targets)]
    assert grids, "sweep_grid not found in chip_session.py"
    grid = ast.literal_eval(grids[0].value)
    assert len(grid) >= 5
    import jax

    from deepspeed_tpu.models import gpt

    for spec in grid:
        assert spec["model"] in gpt.PRESETS, spec
        assert spec["seq"] % 128 == 0, spec
        policy = spec.get("policy", "nothing_saveable")
        assert (policy == "save_attn_mlp_out"
                or hasattr(jax.checkpoint_policies, policy)), spec
        json.dumps(spec)


def test_window_run_specs_are_executable():
    """window_run.py inlines its mfu/bench specs as call arguments — every
    dict literal passed to mfu()/bench() must parse against the same knobs."""
    import ast

    import jax

    from deepspeed_tpu.models import gpt

    src = open("/root/repo/scripts/window_run.py").read()
    tree = ast.parse(src)
    specs = []
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and getattr(node.func, "id", None) in ("mfu", "bench")
                and node.args and isinstance(node.args[0], ast.Dict)):
            specs.append((getattr(node.func, "id"),
                          ast.literal_eval(node.args[0])))
    assert len([s for f, s in specs if f == "mfu"]) >= 5
    assert len([s for f, s in specs if f == "bench"]) >= 3
    for fn, spec in specs:
        json.dumps(spec)
        model = spec.get("model")
        if model:
            assert model in gpt.PRESETS, spec
        if fn == "mfu":
            assert spec["seq"] % 128 == 0, spec
            policy = spec.get("policy", "nothing_saveable")
            assert (policy == "save_attn_mlp_out"
                    or hasattr(jax.checkpoint_policies, policy)), spec
        else:
            assert spec.get("kind") in ("inference", "diffusion", "train",
                                        "pipeline_mpmd"), spec


def test_fallback_summary_carries_chip_window_evidence():
    """A cpu-fallback sweep must still surface the round's chip-measured rows
    (committed evidence) as the headline, clearly labeled."""
    bench = _bench()
    s = bench._summarize("cpu", [{"kind": "train", "config": "cpu-x",
                                  "tokens_per_sec_chip": 27.0, "mfu": 0.02}],
                         [])
    ev = s.get("chip_window_evidence")
    assert ev and ev["rows"] and ev["kernel_smoke_ok"]
    assert "chip-measured" in s["metric"]
    assert s["mfu"] == max(r["mfu"] for r in ev["rows"])
    assert s["vs_baseline"] == round(s["mfu"] / 0.45, 3)
