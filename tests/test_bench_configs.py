"""The bench/chip-session config lists must be executable as-is: a malformed
spec discovered at tunnel-up time would burn the measurement window (the
round-3 post-mortem failure mode this guards against)."""

import json

import pytest


def _bench():
    import importlib
    import sys

    sys.path.insert(0, "/root/repo")
    import bench

    return importlib.reload(bench)


def test_all_config_lists_have_registered_kinds_and_serialize():
    bench = _bench()
    kinds = {"train", "inference", "kernels", "diffusion", "pipeline_aot",
             "pipeline_mpmd", "pipeline_schedule", "train_aot", "kernels_aot",
             "infinity_aot", "moe_aot", "infer_aot", "sd_aot"}
    for lst in (bench.INFINITY_CONFIGS, bench.PIPELINE_CONFIGS,
                bench.AOT_TRAIN_CONFIGS, bench.QUANTIZED_ZERO_CONFIGS):
        assert lst, "config list emptied"
        for cfg in lst:
            assert cfg["kind"] in kinds, cfg
            assert cfg["name"]
            json.dumps(cfg)  # the worker boundary is a JSON argv


def test_train_configs_reference_real_presets():
    bench = _bench()
    from deepspeed_tpu.models import gpt
    from deepspeed_tpu.models.gpt_moe import PRESETS as MOE

    for lst in (bench.INFINITY_CONFIGS, bench.PIPELINE_CONFIGS,
                bench.AOT_TRAIN_CONFIGS, bench.QUANTIZED_ZERO_CONFIGS):
        for cfg in lst:
            model = cfg.get("model")
            if model:
                assert model in gpt.PRESETS or model in MOE, cfg
            if cfg.get("remat_policy") and cfg["remat_policy"] != \
                    "save_attn_mlp_out":
                assert hasattr(__import__("jax").checkpoint_policies,
                               cfg["remat_policy"]), cfg


def test_chip_session_grid_is_executable():
    """Every chip-session sweep spec must parse against mfu_sweep's knobs."""
    import ast
    import os

    src = open("/root/repo/scripts/chip_session.py").read()
    tree = ast.parse(src)
    # find the sweep_grid literal and evaluate it
    grids = [node for node in ast.walk(tree)
             if isinstance(node, ast.Assign)
             and any(getattr(t, "id", None) == "sweep_grid"
                     for t in node.targets)]
    assert grids, "sweep_grid not found in chip_session.py"
    grid = ast.literal_eval(grids[0].value)
    assert len(grid) >= 5
    import jax

    from deepspeed_tpu.models import gpt

    for spec in grid:
        assert spec["model"] in gpt.PRESETS, spec
        assert spec["seq"] % 128 == 0, spec
        policy = spec.get("policy", "nothing_saveable")
        assert (policy == "save_attn_mlp_out"
                or hasattr(jax.checkpoint_policies, policy)), spec
        json.dumps(spec)


def test_window_run_specs_are_executable():
    """window_run.py inlines its mfu/bench specs as call arguments — every
    dict literal passed to mfu()/bench() must parse against the same knobs."""
    import ast

    import jax

    from deepspeed_tpu.models import gpt
    from deepspeed_tpu.models import gpt_moe

    src = open("/root/repo/scripts/window_run.py").read()
    tree = ast.parse(src)
    specs = []
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and getattr(node.func, "id", None) in ("mfu", "bench")
                and node.args and isinstance(node.args[0], ast.Dict)):
            specs.append((getattr(node.func, "id"),
                          ast.literal_eval(node.args[0])))
    assert len([s for f, s in specs if f == "mfu"]) >= 5
    assert len([s for f, s in specs if f == "bench"]) >= 3
    for fn, spec in specs:
        json.dumps(spec)
        model = spec.get("model")
        if model:
            assert model in gpt.PRESETS or model in gpt_moe.PRESETS, spec
        if fn == "mfu":
            assert spec["seq"] % 128 == 0, spec
            policy = spec.get("policy", "nothing_saveable")
            assert (policy == "save_attn_mlp_out"
                    or hasattr(jax.checkpoint_policies, policy)), spec
        else:
            assert spec.get("kind") in ("inference", "diffusion", "train",
                                        "pipeline_mpmd", "moe_train"), spec


def test_fallback_summary_carries_chip_window_evidence(monkeypatch):
    """A cpu-fallback sweep must still surface the round's chip-measured rows
    (committed evidence) as the headline, clearly labeled. Pin the committed
    r04 doc: a local window_run_results.json (gitignored, machine-local)
    would otherwise make this test depend on uncommitted state."""
    bench = _bench()
    monkeypatch.setattr(bench, "CHIP_EVIDENCE_SOURCES",
                        [bench.CHIP_EVIDENCE_SOURCES[-1]])
    s = bench._summarize("cpu", [{"kind": "train", "config": "cpu-x",
                                  "platform": "cpu",
                                  "tokens_per_sec_chip": 27.0, "mfu": 0.02}],
                         [])
    ev = s.get("chip_window_evidence")
    assert ev and ev["rows"] and ev["kernel_smoke_ok"]
    assert "chip-measured" in s["metric"]
    mfu_rows = [r for r in ev["rows"] if "mfu" in r]
    assert s["mfu"] == max(r["mfu"] for r in mfu_rows)
    assert s["vs_baseline"] == round(s["mfu"] / 0.45, 3)


def test_window_ledger_evidence_shapes(tmp_path, monkeypatch):
    """The in-round window ledger (window_run_results.json) rows: moe_train
    throughput key is tokens_per_sec_chip (not tok_s), decode/SD rows carry
    no mfu, and a ledger without a kernel-tagged row reports kernel_smoke_ok
    None (unknown), not False."""
    bench = _bench()
    ledger = [
        {"tag": "rtt-probe", "rc": 0, "result": {"rtt_ms": 350}},
        {"tag": "moe_train:moe-125m-8e-train", "rc": 0,
         "result": {"platform": "tpu", "mfu": 0.28,
                    "tokens_per_sec_chip": 8000.0, "step_ms": 120.0}},
        {"tag": "inference:gpt2-350m-decode", "rc": 0,
         "result": {"platform": "tpu", "decode_p50_ms": 9.0,
                    "decode_p90_ms": 11.0, "tokens_per_sec": 111.0}},
        {"tag": "diffusion:sd-ddim20", "rc": 0,
         "result": {"platform": "tpu", "image_ms_p50": 900.0}},
        {"tag": "mfu:dead-row", "rc": -1, "error": "timeout"},
    ]
    p = tmp_path / "window_run_results.json"
    p.write_text(json.dumps(ledger))
    monkeypatch.setattr(bench, "CHIP_EVIDENCE_SOURCES",
                        [(str(p), "test ledger")])
    rows, src, kernel_ok = bench._load_chip_evidence()
    assert src == "test ledger" and kernel_ok is None
    assert len(rows) == 3  # probe + dead row dropped
    s = bench._summarize("cpu", [], [])
    assert s["metric"].startswith("moe_train:moe-125m-8e-train")
    assert s["value"] == 8000.0 and s["vs_baseline"] == round(0.28 / 0.45, 3)
    assert s["decode_p50_ms"] == 9.0 and s["decode_source"] == "chip_window"
    assert s["sd_image_ms_p50"] == 900.0


def test_tpu_core_sweep_includes_measured_moe_row():
    """VERDICT r4 'next' #5: the driver sweep itself must carry a measured
    MoE row, not just the moe_aot compile."""
    bench = _bench()
    cfgs = bench.tpu_core_configs()
    moe = [c for c in cfgs if c["kind"] == "moe_train"]
    assert moe and moe[0]["model"] == "moe-125m-8e"
    names = [c["name"] for c in cfgs]
    assert len(names) == len(set(names)), "duplicate config names"
    json.dumps(cfgs)


def test_recovered_tpu_row_sets_vs_baseline_from_row_platform():
    """A TPU train row measured after a mid-sweep tunnel recovery must drive
    vs_baseline even though the sweep-level platform is 'cpu' — and the
    stale chip-window block must NOT override a real measured row."""
    bench = _bench()
    s = bench._summarize("cpu", [
        {"kind": "train", "config": "cpu-x", "platform": "cpu",
         "tokens_per_sec_chip": 27.0, "mfu": 0.02},
        {"kind": "train", "config": "recovered-row", "platform": "tpu",
         "tokens_per_sec_chip": 13000.0, "mfu": 0.40},
    ], [])
    assert s["metric"].startswith("recovered-row")
    assert s["vs_baseline"] == round(0.40 / 0.45, 3)
    assert "chip_window_evidence" not in s


def test_moe_train_row_counts_toward_headline():
    """The measured MoE row competes for the headline like any train row."""
    bench = _bench()
    s = bench._summarize("tpu", [
        {"kind": "moe_train", "config": "moe-row", "platform": "tpu",
         "tokens_per_sec_chip": 9000.0, "mfu": 0.30},
    ], [])
    assert s["metric"].startswith("moe-row")
    assert s["vs_baseline"] == round(0.30 / 0.45, 3)


@pytest.mark.slow
def test_moe_train_worker_end_to_end():
    """The window grid's measured-MoE row must be executable as-is: run the
    actual bench worker subprocess on the tiny preset (a spec typo or engine
    regression here would burn tunnel-window time)."""
    import os
    import subprocess
    import sys

    bench = _bench()
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.run(
        [sys.executable, bench.__file__, "--worker",
         json.dumps({"kind": "moe_train", "name": "tiny-moe-worker",
                     "model": "tiny-moe", "micro_bs": 2, "seq": 32,
                     "steps": 2})],
        capture_output=True, text=True, timeout=420, env=env,
        cwd=os.path.dirname(bench.__file__))
    assert p.returncode == 0, p.stderr[-800:]
    line = next(ln for ln in reversed(p.stdout.strip().splitlines())
                if ln.startswith("{"))
    r = json.loads(line)
    assert r["kind"] == "moe_train" and r["num_experts"] == 4
    assert r["tokens_per_sec_chip"] > 0 and r["mfu"] > 0
    import numpy as np

    assert np.isfinite(r["loss"])


def test_main_recovery_splice(monkeypatch, capsys):
    """End-to-end main() logic with a tunnel that comes back mid-sweep: the
    measured TPU rows are spliced in right after the current row, fallback
    rows keep their forced-CPU labels, and the final summary's vs_baseline
    comes from the recovered row."""
    bench = _bench()
    monkeypatch.setattr(bench, "probe_backend",
                        lambda: ("cpu", 1, ["probe hung (killed)"]))
    monkeypatch.setattr(bench, "RECOVERY_PROBE_EVERY", 0)
    monkeypatch.setattr(bench, "quick_probe", lambda timeout=0: True)
    monkeypatch.setattr(bench, "_persist_row", lambda row: None)
    monkeypatch.setattr(bench, "cpu_fallback_configs", lambda: [
        {"kind": "train", "name": "cpu-fallback-zero1", "force_cpu": True},
        {"kind": "train_aot", "name": "aot-row", "force_cpu": True},
    ])
    monkeypatch.setattr(bench, "tpu_core_configs", lambda: [
        {"kind": "train", "name": "tpu-train"},
        {"kind": "train_aot", "name": "tpu-aot", "force_cpu": True},
    ])
    ran = []

    def fake_worker(cfg, platform, retries=1):
        ran.append((cfg["name"], platform, bool(cfg.get("force_cpu"))))
        if cfg["kind"] == "train":
            plat = "cpu" if cfg.get("force_cpu") else platform
            return {"kind": "train", "config": cfg["name"], "platform": plat,
                    "tokens_per_sec_chip": 100.0 if plat == "cpu" else 9000.0,
                    "mfu": 0.01 if plat == "cpu" else 0.41}
        return {"kind": cfg["kind"], "config": cfg["name"],
                "platform": "tpu-compile-only", "fits_v5e_hbm": True}

    monkeypatch.setattr(bench, "run_worker", fake_worker)
    bench.main()
    # recovery fired after row 1: the measured TPU row (not the force_cpu
    # AOT row, which already runs in the fallback) is spliced NEXT
    assert [n for n, _, _ in ran] == [
        "cpu-fallback-zero1", "tpu-train", "aot-row"]
    # post-recovery, the still-queued fallback row ran under platform "tpu"
    # but carries force_cpu (its env stays forced — label integrity)
    assert ran[2] == ("aot-row", "tpu", True)
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["metric"].startswith("tpu-train")
    assert out["vs_baseline"] == round(0.41 / 0.45, 3)
    assert "chip_window_evidence" not in out


def test_quick_probe_rejects_cpu_backend(monkeypatch):
    """The recovery probe must NOT claim the tunnel is back on a CPU
    backend — a CPU 'success' would splice TPU rows into a chipless sweep.
    The probe subprocess is faked so the platform guard (not a timeout) is
    what's tested."""
    import subprocess as sp

    bench = _bench()

    class Done:
        returncode = 0

        def __init__(self, platform):
            self.stdout = f"PLATFORM={platform} NCHIPS=1\n"

    monkeypatch.setattr(sp, "run", lambda *a, **k: Done("cpu"))
    assert bench.quick_probe(timeout=5) is False
    monkeypatch.setattr(sp, "run", lambda *a, **k: Done("TPU v5 lite"))
    assert bench.quick_probe(timeout=5) is True

    def hang(*a, **k):
        raise sp.TimeoutExpired(cmd="probe", timeout=5)

    monkeypatch.setattr(sp, "run", hang)
    assert bench.quick_probe(timeout=5) is False
