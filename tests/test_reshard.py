"""Elastic world-size reshard (ISSUE 15, docs/RESILIENCE.md "Elastic
membership"): the pure flat-shard repartition properties, the cursor remap,
reshard-on-load through the real engine/checkpoint path, the validated
elasticity config block, the budget-free membership-change agent semantics,
and the ``config/elastic-without-reshard-anchor`` dslint rule.
"""

import json
import os

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.checkpoint.serialization import _fetch_full, _flatten_with_paths
from deepspeed_tpu.models import GPTConfig, build_gpt
from deepspeed_tpu.runtime.config import DeepSpeedConfig
from deepspeed_tpu.runtime.topology import MeshTopology
from deepspeed_tpu.runtime.zero.reshard import (
    ReshardError,
    merge_flat,
    partition_flat,
    partition_host_state,
    repartition_flat,
    repartition_host_state,
    rescale_cursor,
    shard_len,
)

TINY = GPTConfig(vocab_size=64, n_layer=2, n_head=2, d_model=32,
                 max_seq_len=32)


# ------------------------------------------------------------- pure properties
@pytest.mark.parametrize("n,old,new", [
    (17, 4, 3),     # both worlds uneven, non-divisible either way
    (16, 4, 2),     # both divide
    (16, 4, 3),     # old divides, new pads
    (15, 3, 5),     # new divides, old pads
    (1, 4, 2),      # fewer elements than ranks (empty tail shards)
    (7, 1, 6),      # from a single rank
    (7, 6, 1),      # to a single rank
    (1024, 8, 5),
])
def test_repartition_equals_fresh_partition_bitwise(n, old, new):
    rng = np.random.default_rng(n * 31 + old * 7 + new)
    flat = rng.standard_normal(n).astype(np.float32)
    shards = partition_flat(flat, old)
    assert shards.shape == (old, shard_len(n, old))
    # repartition == freshly partitioning the merged logical state, bitwise
    got = repartition_flat(shards, new, n)
    want = partition_flat(flat, new)
    assert got.tobytes() == want.tobytes()
    # N -> M -> N round-trip is the identity, bitwise
    back = repartition_flat(got, old, n)
    assert back.tobytes() == shards.tobytes()
    # merge drops exactly the tail padding
    assert merge_flat(got, n).tobytes() == flat.tobytes()


def test_padded_tail_is_zeros_and_layout_contiguous():
    flat = np.arange(10, dtype=np.int64)
    shards = partition_flat(flat, 4)  # shard_len 3, 2 pad elements
    assert shards.shape == (4, 3)
    assert shards[3, 1] == 0 and shards[3, 2] == 0
    # rank i owns the contiguous slice [i*s, (i+1)*s)
    assert shards[1].tolist() == [3, 4, 5]


def test_repartition_preserves_raw_dtypes():
    # bf16 leaves travel as raw uint16 views in checkpoints; int8 covers the
    # quantized payload case — pure memory movement must never touch bits
    for dtype in (np.uint16, np.int8, np.float64):
        flat = np.frombuffer(np.random.default_rng(3).bytes(
            26 * np.dtype(dtype).itemsize), dtype=dtype).copy()
        got = repartition_flat(partition_flat(flat, 5), 3, flat.size)
        assert got.dtype == dtype
        assert merge_flat(got, flat.size).tobytes() == flat.tobytes()


def test_partition_rejects_bad_shapes():
    with pytest.raises(ReshardError):
        partition_flat(np.zeros((2, 3), np.float32), 2)
    with pytest.raises(ReshardError):
        merge_flat(np.zeros((6,), np.float32), 6)
    with pytest.raises(ReshardError):
        partition_flat(np.zeros((4,), np.float32), 0)
    with pytest.raises(ReshardError):
        merge_flat(np.zeros((2, 2), np.float32), 5)  # fewer elements than logical


def test_host_offload_unit_shards_roundtrip():
    # the PR 11 host_state format: fp32 master/m/v leaves + a scalar counter
    rng = np.random.default_rng(0)
    host = {"count": np.int64(7)}
    for i, shape in enumerate([(33,), (8, 9), (5,), (2, 3, 4)]):
        host[f"master_{i}"] = rng.standard_normal(shape).astype(np.float32)
        host[f"m_{i}"] = rng.standard_normal(shape).astype(np.float32)
        host[f"v_{i}"] = rng.standard_normal(shape).astype(np.float32)
    shards4, sizes = partition_host_state(host, 4)
    shards3 = repartition_host_state(shards4, sizes, 3)
    for key, arr in host.items():
        arr = np.asarray(arr)
        if arr.ndim == 0:
            assert shards3[key] == arr
            continue
        fresh = partition_flat(arr.reshape(-1), 3)
        assert shards3[key].tobytes() == fresh.tobytes()
        assert merge_flat(shards3[key], arr.size).tobytes() == \
            arr.reshape(-1).tobytes()


# ------------------------------------------------------------------ cursor
def test_rescale_cursor_identity_and_exact():
    # the elastic contract: effective batch constant -> cursor is invariant
    assert rescale_cursor(17, 12, 12) == 17
    # exact sample-unit remap across a genuine global-batch change
    assert rescale_cursor(6, 8, 16) == 3
    assert rescale_cursor(3, 16, 8) == 6
    assert rescale_cursor(0, 8, 16) == 0


def test_rescale_cursor_gas_boundary_decompositions():
    # all (micro, gas, world) decompositions of one effective batch consume
    # identical sample counts per cursor tick — the cursor crosses any gas
    # boundary unchanged
    for micro, gas, world in [(1, 3, 4), (3, 1, 4), (4, 1, 3), (2, 2, 3),
                              (2, 3, 2), (12, 1, 1)]:
        assert micro * gas * world == 12
        assert rescale_cursor(5, 12, micro * gas * world) == 5


def test_rescale_cursor_refuses_sample_splits():
    # 5 batches of 12 = 60 samples: not a whole number of 16-sample batches
    with pytest.raises(ReshardError):
        rescale_cursor(5, 12, 16)
    with pytest.raises(ReshardError):
        rescale_cursor(1, 8, 0)


# --------------------------------------------------------------- engine level
def _make_engine(dp: int, micro: int, save_dir: str, qgrad: bool = True,
                 gas: int = 1):
    import jax

    model, _ = build_gpt(TINY)
    topo = MeshTopology.create(dp=dp, devices=jax.devices()[:dp])
    zero = {"stage": 1}
    if qgrad:
        zero.update({"zero_quantized_gradients": True,
                     "zero_quantize_error_feedback": True})
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, topology=topo, config={
        "train_micro_batch_size_per_gpu": micro,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": zero,
        "mesh": {"dp": dp},
        "bf16": {"enabled": False},
        "steps_per_print": 0,
        "resilience": {"enabled": True, "save_dir": save_dir},
    })
    return engine


def _batch(effective: int, cursor: int, gas: int = 1):
    r = np.random.default_rng(1000 + cursor)
    ids = r.integers(0, 64, size=(effective, 16), dtype=np.int32)
    if gas > 1:
        ids = ids.reshape(gas, effective // gas, 16)
    return {"input_ids": ids}


def _state_arrays(engine):
    return {key: np.asarray(_fetch_full(leaf))
            for key, leaf in _flatten_with_paths(engine.state)[0]}


@pytest.mark.slow
def test_reshard_on_load_world_change(tmp_path):
    """dp4 run with quantized-gradient EF -> checkpoint -> dp2 engine loads:
    logical leaves bitwise, EF residual reset to the new decomposition's
    zeros, cursor preserved, ``reshard_applied`` recorded, run continues."""
    save = str(tmp_path / "ckpt")
    a = _make_engine(4, 2, save)
    for _ in range(2):
        a.train_batch(_batch(8, a.data_cursor))
    a.save_checkpoint(save)
    before = _state_arrays(a)
    assert before["qgrad_residual"].shape[0] == 4
    meta = json.load(open(os.path.join(save, "global_step2", "meta.json")))
    assert meta["world_size"] == 4
    assert meta["partition"]["global_batch"] == 8
    assert meta["partition"]["qgrad"]["npad"] >= meta["partition"]["qgrad"]["n"]

    # dp2 engine, same effective batch: auto-resume reshards at init
    b = _make_engine(2, 4, save)
    assert b.global_steps == 2
    assert b.data_cursor == 2
    after = _state_arrays(b)
    for key, arr in after.items():
        if key.startswith("qgrad"):
            # world-coupled EF residue: reset by policy (demotion-reset
            # semantics), never loaded across decompositions
            assert arr.shape[0] == 2
            assert not arr.any()
        else:
            assert arr.tobytes() == before[key].tobytes(), key
    events = [json.loads(ln)
              for ln in open(os.path.join(save, "recovery_events.jsonl"))]
    names = [e["event"] for e in events]
    assert "reshard_applied" in names
    assert "reshard_residual_reset" in names
    applied = next(e for e in events if e["event"] == "reshard_applied")
    assert applied["old_world"] == 4 and applied["new_world"] == 2
    # the resharded engine trains on
    m = b.train_batch(_batch(8, b.data_cursor))
    assert np.isfinite(float(m["loss"]))
    assert b.data_cursor == 3


def test_same_world_load_does_not_reshard(tmp_path):
    save = str(tmp_path / "ckpt")
    a = _make_engine(2, 4, save)
    a.train_batch(_batch(8, 0))
    a.save_checkpoint(save)
    resid = _state_arrays(a)["qgrad_residual"]
    b = _make_engine(2, 4, save)
    # same world: the (generally nonzero) EF residual loads verbatim
    assert _state_arrays(b)["qgrad_residual"].tobytes() == resid.tobytes()
    from deepspeed_tpu.resilience import read_events

    assert not any(e["event"] == "reshard_applied"
                   for e in read_events(save))


@pytest.mark.slow
def test_mid_accum_reshard_drops_window_and_keeps_cursor(tmp_path):
    """A mid-accumulation (imperative) save resharded to a new world drops
    the partial gradient window and keeps the cursor AT that window, so the
    resumed run re-consumes it from the start — sample-exact."""
    save = str(tmp_path / "ckpt")
    a = _make_engine(4, 1, save, qgrad=False, gas=2)
    # one full step, then half a window
    a.train_batch(_batch(8, 0, gas=2))
    assert a.data_cursor == 1
    a.forward({"input_ids": _batch(8, 1)["input_ids"][:4]})
    a.backward()
    assert int(a.state["micro"]) == 1
    a.save_checkpoint(save, tag="mid")
    meta = json.load(open(os.path.join(save, "mid", "meta.json")))
    assert meta["has_grad_acc"] and meta["data_cursor"] == 1

    b = _make_engine(2, 2, save, qgrad=False, gas=2)
    b.load_checkpoint(save, tag="mid")
    assert b._grad_acc is None          # partial window dropped
    assert int(b.state["micro"]) == 0   # window restarts from zero
    assert b.data_cursor == 1           # still pointing AT the window
    # the same-world load keeps the window instead
    c = _make_engine(4, 1, save, qgrad=False, gas=2)
    c.load_checkpoint(save, tag="mid")
    assert c._grad_acc is not None
    assert int(c.state["micro"]) == 1


def test_unknown_world_coupled_leaf_still_raises(tmp_path):
    # only policy-covered keys reshard; any other shape mismatch must fail
    # loudly even mid-reshard
    from deepspeed_tpu.runtime.zero.reshard import load_resolver

    resolve = load_resolver(4, 2)
    with pytest.raises(ReshardError, match="mystery"):
        resolve("opt/mystery", np.zeros((4, 3), np.float32),
                np.zeros((2, 6), np.float32))
    out = resolve("qgrad_residual", np.zeros((4, 8), np.float32),
                  np.zeros((2, 16), np.float32))
    assert out.shape == (2, 16) and not out.any()


# ------------------------------------------------------------- config block
ELASTIC_OK = {
    "enabled": True,
    "max_train_batch_size": 12,
    "micro_batch_sizes": [1, 2, 3, 4],
    "min_world_size": 1,
    "max_world_size": 6,
}


def test_elasticity_block_validated_in_config(monkeypatch):
    monkeypatch.delenv("DS_TPU_ELASTICITY_CONFIG", raising=False)
    monkeypatch.delenv("DEEPSPEED_ELASTICITY_CONFIG", raising=False)
    # a typo'd key no longer rides silently
    with pytest.raises(ValueError, match="max_train_batchsize"):
        DeepSpeedConfig.load({"elasticity": {"enabled": True,
                                             "max_train_batchsize": 16}},
                             world_size=4)
    with pytest.raises(ValueError, match="micro_batch_sizes"):
        DeepSpeedConfig.load(
            {"elasticity": {"enabled": True, "micro_batch_sizes": []}},
            world_size=4)
    with pytest.raises(ValueError, match="world-size range"):
        DeepSpeedConfig.load(
            {"elasticity": {"enabled": True, "min_world_size": 5,
                            "max_world_size": 2}}, world_size=4)
    # disabled blocks are still shape-checked but impose nothing
    cfg = DeepSpeedConfig.load(
        {"elasticity": {"enabled": False},
         "train_micro_batch_size_per_gpu": 2}, world_size=4)
    assert cfg.train_batch_size == 8


def test_elasticity_adopts_ladder_batch(monkeypatch):
    monkeypatch.delenv("DS_TPU_ELASTICITY_CONFIG", raising=False)
    cfg = DeepSpeedConfig.load({"elasticity": dict(ELASTIC_OK)}, world_size=4)
    # world 4 on the 12-batch ladder: micro 3 (largest dividing), gas 1
    assert cfg.train_batch_size == 12
    assert cfg.train_micro_batch_size_per_gpu == 3
    assert cfg.gradient_accumulation_steps == 1
    # explicit knobs consistent with the ladder pass
    cfg = DeepSpeedConfig.load(
        {"elasticity": dict(ELASTIC_OK),
         "train_micro_batch_size_per_gpu": 1,
         "gradient_accumulation_steps": 3}, world_size=4)
    assert cfg.train_batch_size == 12


def test_elasticity_rejects_off_ladder_batch(monkeypatch):
    monkeypatch.delenv("DS_TPU_ELASTICITY_CONFIG", raising=False)
    with pytest.raises(ValueError, match="off the elastic ladder"):
        DeepSpeedConfig.load(
            {"elasticity": dict(ELASTIC_OK), "train_batch_size": 16,
             "train_micro_batch_size_per_gpu": 4}, world_size=4)
    with pytest.raises(ValueError, match="not among the valid"):
        DeepSpeedConfig.load({"elasticity": dict(ELASTIC_OK)}, world_size=5)
    # the explicit escape hatch keeps off-ladder configs loadable
    cfg = DeepSpeedConfig.load(
        {"elasticity": {**ELASTIC_OK, "ignore_non_elastic_batch_info": True},
         "train_batch_size": 16, "train_micro_batch_size_per_gpu": 4},
        world_size=4)
    assert cfg.train_batch_size == 16


def test_elastic_ladder_one_source():
    from deepspeed_tpu.elasticity import elastic_ladder

    ladder = elastic_ladder({"elasticity": dict(ELASTIC_OK)})
    assert (4, 3, 1) in ladder and (3, 4, 1) in ladder
    for world, micro, gas in ladder:
        assert micro * gas * world == 12


def test_validate_block_accepts_reference_aliases():
    from deepspeed_tpu.elasticity import validate_elasticity_block

    block = validate_elasticity_block(
        {"enabled": True, "max_train_batch_size": 8,
         "micro_batch_sizes": [2], "min_gpus": 2, "max_gpus": 4})
    assert block["min_world_size"] == 2 and block["max_world_size"] == 4


# ------------------------------------------------------------------- agent
def test_membership_change_is_budget_free(tmp_path):
    """A worker dying together with a membership change spends NO restart
    budget (max_restarts=0 still succeeds) and records membership_change."""
    import sys

    from deepspeed_tpu.elasticity.elastic_agent import DSElasticAgent
    from deepspeed_tpu.resilience import read_events

    ckpt = tmp_path / "ckpt"
    ckpt.mkdir()
    marker = tmp_path / "first_done"
    launches = []

    def device_count():
        # the first worker's crash IS the lost device: 4 -> 2 at its death
        return 2 if marker.exists() else 4

    def make_cmd(spec):
        launches.append(spec)
        if len(launches) == 1:
            script = f"open({str(marker)!r}, 'w').write('x'); raise SystemExit(9)"
        else:
            script = "raise SystemExit(0)"
        return [sys.executable, "-c", script]

    agent = DSElasticAgent(
        make_cmd, {"elasticity": {"enabled": True, "max_train_batch_size": 16,
                                  "micro_batch_sizes": [2, 4],
                                  "min_world_size": 1, "max_world_size": 8}},
        device_count_fn=device_count, max_restarts=0, poll_interval=0.05,
        checkpoint_dir=str(ckpt))
    result = agent.run()
    assert result.state == "SUCCEEDED"
    assert result.restarts == 0
    assert result.membership_changes == 1
    assert [s.world_size for s in launches] == [4, 2]
    events = [e for e in read_events(str(ckpt))
              if e["event"] == "membership_change"]
    assert len(events) == 1
    assert events[0]["old_world"] == 4 and events[0]["new_world"] == 2


def test_same_world_crash_still_spends_budget(tmp_path):
    import sys

    from deepspeed_tpu.elasticity.elastic_agent import DSElasticAgent

    agent = DSElasticAgent(
        lambda s: [sys.executable, "-c", "raise SystemExit(9)"],
        {"elasticity": {"enabled": True, "max_train_batch_size": 16,
                        "micro_batch_sizes": [2, 4]}},
        device_count_fn=lambda: 4, max_restarts=1, poll_interval=0.05,
        checkpoint_dir=str(tmp_path), backoff_base=0.01, backoff_max=0.02)
    result = agent.run()
    assert result.state == "FAILED"
    assert result.membership_changes == 0
    assert result.restarts == 1


def test_agent_rejects_malformed_block():
    from deepspeed_tpu.elasticity import ElasticityError
    from deepspeed_tpu.elasticity.elastic_agent import DSElasticAgent

    with pytest.raises(ElasticityError, match="unknown elasticity keys"):
        DSElasticAgent(lambda s: ["true"],
                       {"elasticity": {"enabled": True, "maxbatch": 16}})


def test_fault_plan_accepts_lose_worker_key():
    from deepspeed_tpu.resilience import FaultPlan

    plan = FaultPlan.from_dict({"lose_worker_at_step": 3})
    assert plan.lose_worker_at_step == 3
    # disarmed cursors resolve to no-fault without killing anything
    f = plan.training_faults(2)
    assert not f.nan_loss and not f.ef_overflow and f.stall_s == 0.0


# ------------------------------------------------------------------ dslint
def _ctx(config):
    from deepspeed_tpu.analysis.core import AnalysisContext

    return AnalysisContext(config=config)


def test_elastic_anchor_rule_fires_without_anchors(tmp_path, monkeypatch):
    monkeypatch.delenv("DS_TPU_ELASTICITY_CONFIG", raising=False)
    from deepspeed_tpu.analysis.rules_config import (
        ElasticWithoutReshardAnchorRule)

    cfg = DeepSpeedConfig.load({"elasticity": dict(ELASTIC_OK)}, world_size=4)
    findings = list(ElasticWithoutReshardAnchorRule().check_context(_ctx(cfg)))
    assert len(findings) == 1
    f = findings[0]
    assert f.rule_id == "config/elastic-without-reshard-anchor"
    assert "committed anchors" in f.message
    assert "data cursor" in f.message


def test_elastic_anchor_rule_fires_on_missing_cursor_only(tmp_path,
                                                          monkeypatch):
    monkeypatch.delenv("DS_TPU_ELASTICITY_CONFIG", raising=False)
    from deepspeed_tpu.analysis.rules_config import (
        ElasticWithoutReshardAnchorRule)

    cfg = DeepSpeedConfig.load({
        "elasticity": dict(ELASTIC_OK),
        "resilience": {"enabled": True, "save_dir": str(tmp_path),
                       "sentinel": {"enabled": True,
                                    "checkpoint_interval": 5}},
    }, world_size=4)
    findings = list(ElasticWithoutReshardAnchorRule().check_context(_ctx(cfg)))
    assert len(findings) == 1
    assert "data cursor" in findings[0].message
    assert "committed anchors" not in findings[0].message


def test_elastic_anchor_rule_silent_when_anchored(tmp_path, monkeypatch):
    monkeypatch.delenv("DS_TPU_ELASTICITY_CONFIG", raising=False)
    from deepspeed_tpu.analysis.rules_config import (
        ElasticWithoutReshardAnchorRule)

    cfg = DeepSpeedConfig.load({
        "elasticity": dict(ELASTIC_OK),
        "resilience": {"enabled": True, "save_dir": str(tmp_path),
                       "sentinel": {"enabled": True, "checkpoint_interval": 5,
                                    "cursor_checkpointable": True}},
    }, world_size=4)
    assert not list(ElasticWithoutReshardAnchorRule().check_context(_ctx(cfg)))
    # and entirely silent without an elasticity block
    cfg = DeepSpeedConfig.load({"train_micro_batch_size_per_gpu": 2},
                               world_size=4)
    assert not list(ElasticWithoutReshardAnchorRule().check_context(_ctx(cfg)))


def test_elastic_anchor_rule_registered():
    from deepspeed_tpu.analysis import default_rules

    assert any(r.rule_id == "config/elastic-without-reshard-anchor"
               for r in default_rules())
