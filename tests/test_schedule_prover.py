"""Static pipeline-schedule prover: positives over the three shipped
generators, the four mutation counterexamples (each rejected with the exact
stage + instruction index in the finding), the engine's refuse-before-build
gate, and the AOT pricing join.

Everything except the engine test is pure host analysis — no tracing, no
device work — so this file is cheap enough to run whole in tier 1.
"""
import re

import pytest

from deepspeed_tpu.analysis import analyze_schedule
from deepspeed_tpu.analysis.schedule import (
    B,
    F,
    RECV,
    RULE_DEADLOCK,
    RULE_PAIRING,
    RULE_STALE_WEIGHT,
    SEND,
    ScheduleIR,
    W,
    prove_schedule,
    schedule_liveness,
    schedule_report,
    static_bubble,
)
from deepspeed_tpu.runtime.pipe.mpmd import (
    generate_1f1b_ir,
    generate_interleaved_ir,
    generate_zero_bubble_ir,
    validate_schedule_pairing,
)

LOC_RE = re.compile(r"stage (\d+), instr (\d+)")


def _mutated(ir, stages, suffix):
    return ScheduleIR(name=f"{ir.name}+{suffix}", num_stages=ir.num_stages,
                      num_micro=ir.num_micro, stages=stages,
                      num_vstages=ir.num_vstages,
                      w_applies_update=ir.w_applies_update)


def _copy_stages(ir):
    return [list(st) for st in ir.stages]


# ------------------------------------------------------------ positives
@pytest.mark.parametrize("m,s", [(4, 2), (8, 4), (16, 8), (8, 2)])
def test_1f1b_proves_clean(m, s):
    ir = generate_1f1b_ir(m, s)
    assert prove_schedule(ir) == []
    assert validate_schedule_pairing(m, s) == []  # the legacy shim


@pytest.mark.parametrize("m,s,v", [(8, 4, 2), (16, 8, 2), (8, 2, 2),
                                   (16, 4, 2), (16, 4, 4)])
def test_interleaved_proves_clean(m, s, v):
    assert prove_schedule(generate_interleaved_ir(m, s, v)) == []


@pytest.mark.parametrize("m,s", [(4, 2), (8, 4), (16, 8)])
def test_zero_bubble_proves_clean(m, s):
    ir = generate_zero_bubble_ir(m, s)
    assert ir.has_w
    assert prove_schedule(ir) == []


def test_interleaved_requires_divisible_microbatches():
    with pytest.raises(ValueError):
        generate_interleaved_ir(6, 4, 2)


@pytest.mark.parametrize("m,s", [(8, 4), (16, 8), (4, 2), (16, 4)])
def test_1f1b_liveness_matches_engine_bound(m, s):
    """The IR-derived peak activation residency must equal the engine's
    TrainSchedule bound min(S - s, M) per stage — the prover's liveness
    pass prices exactly what the interpreter holds."""
    live = schedule_liveness(generate_1f1b_ir(m, s))
    assert live is not None
    assert [st["peak_activations"] for st in live] == [
        min(s - i, m) for i in range(s)]


def test_zero_bubble_memory_parity_with_1f1b():
    """ZB-H1 property: the B/W split fills the bubble *without* raising
    activation residency over 1F1B."""
    m, s = 8, 4
    zb = schedule_liveness(generate_zero_bubble_ir(m, s))
    f1 = schedule_liveness(generate_1f1b_ir(m, s))
    assert [st["peak_activations"] for st in zb] == [
        st["peak_activations"] for st in f1]
    assert all(st["peak_w_backlog"] >= 1 for st in zb)


@pytest.mark.parametrize("m,s,v", [(8, 4, 2), (16, 8, 2)])
def test_static_bubble_ordering(m, s, v):
    """At equal microbatches: 1F1B pays (S-1)/(M+S-1); interleaving divides
    the warmup/drain term by V; zero-bubble fills the drain with W. Both
    must beat 1F1B, and the closed forms must match the simulation."""
    b1 = static_bubble(generate_1f1b_ir(m, s))["bubble_frac"]
    bi = static_bubble(generate_interleaved_ir(m, s, v))["bubble_frac"]
    bz = static_bubble(generate_zero_bubble_ir(m, s))["bubble_frac"]
    assert bi < b1 and bz < b1, (b1, bi, bz)
    assert b1 == pytest.approx((s - 1) / (m + s - 1))
    ideal = ((s - 1) / v) / (m + (s - 1) / v)
    assert bi == pytest.approx(ideal)


def test_schedule_report_combined():
    rep = schedule_report(generate_zero_bubble_ir(8, 4))
    assert rep["ok"] and rep["findings"] == []
    assert rep["peak_activation_buffers"] == [4, 3, 2, 1]
    assert 0.0 < rep["bubble"]["bubble_frac"] < 1.0


# ------------------------------------- mutation counterexamples (4 of them)
def test_dropped_recv_rejected_with_location():
    """pipe/unpaired-send-recv must fire and name the exact stage +
    instruction of the unmatched message."""
    ir = generate_1f1b_ir(4, 2)
    stages = _copy_stages(ir)
    ri = next(i for i, ins in enumerate(stages[1]) if ins.op == "RECV")
    del stages[1][ri]
    bad = _mutated(ir, stages, "dropped-recv")
    findings = prove_schedule(bad)
    assert findings, "dropped recv must be rejected"
    pairing = [f for f in findings if f.rule_id == "pipe/unpaired-send-recv"]
    assert pairing and all(f.rule_id == RULE_PAIRING for f in pairing)
    locs = [LOC_RE.search(f.location) for f in pairing]
    assert all(locs), [f.location for f in pairing]
    # the stream that kept its extra send is stage 0 — some finding must
    # anchor there with a concrete instruction index
    assert any(m.group(1) == "0" for m in locs)


def test_swapped_channel_order_rejected_with_location():
    """Reordering two sends on one channel breaks the FIFO payload pairing:
    the k-th recv now gets the wrong microbatch."""
    ir = generate_1f1b_ir(4, 2)
    stages = _copy_stages(ir)
    sidx = [i for i, ins in enumerate(stages[0]) if ins.op == "SEND"]
    stages[0][sidx[0]], stages[0][sidx[1]] = (stages[0][sidx[1]],
                                              stages[0][sidx[0]])
    bad = _mutated(ir, stages, "swapped-sends")
    findings = prove_schedule(bad)
    assert findings and all(f.rule_id == RULE_PAIRING for f in findings)
    # the mis-paired recvs are anchored by exact index, and the offending
    # sends are named by exact stage + index in the message
    assert all(LOC_RE.search(f.location) for f in findings)
    named = " | ".join(f.location + " " + f.message for f in findings)
    assert f"stage 0, instr {sidx[0]}" in named
    assert f"stage 0, instr {sidx[1]}" in named


def test_w_before_its_b_rejected_with_location():
    """pipe/stale-weight-application: a W hoisted before its own B applies
    a gradient that does not exist yet."""
    ir = generate_zero_bubble_ir(4, 2)
    stages = _copy_stages(ir)
    st = stages[1]
    wi = next(i for i, ins in enumerate(st) if ins.op == "W")
    bi = next(i for i, ins in enumerate(st)
              if ins.op == "B" and ins.micro == st[wi].micro
              and ins.vstage == st[wi].vstage)
    assert bi < wi
    w = st.pop(wi)
    st.insert(bi, w)
    bad = _mutated(ir, stages, "hoisted-w")
    findings = prove_schedule(bad)
    stale = [f for f in findings
             if f.rule_id == "pipe/stale-weight-application"]
    assert stale and all(f.rule_id == RULE_STALE_WEIGHT for f in stale)
    m = LOC_RE.search(stale[0].location)
    assert m and m.group(1) == "1", stale[0].location
    # the message names both halves' exact indices
    assert f"instr {bi}" in stale[0].location + stale[0].message
    assert "precedes" in stale[0].message


def test_cyclic_cross_wait_rejected_with_cycle_path():
    """Two stages each blocking on a recv whose matching send sits behind
    the other blocked recv: pairing is locally fine, the composition hangs.
    pipe/schedule-deadlock must print the wait cycle."""
    bad = ScheduleIR(
        name="cross-wait", num_stages=2, num_micro=1,
        stages=[
            [RECV(1, "x", 0), F(0), SEND(1, "y", 0)],
            [RECV(0, "y", 0), F(0), SEND(0, "x", 0)],
        ])
    assert not [f for f in prove_schedule(bad)
                if f.rule_id == RULE_PAIRING]  # pairing alone can't see it
    findings = [f for f in prove_schedule(bad)
                if f.rule_id == "pipe/schedule-deadlock"]
    assert findings and findings[0].rule_id == RULE_DEADLOCK
    text = findings[0].location + findings[0].message
    assert "stage 0" in text and "stage 1" in text
    assert LOC_RE.search(findings[0].location)
    # a cyclic schedule has no makespan and no liveness bound
    assert static_bubble(bad) is None
    assert schedule_liveness(bad) is None


# ------------------------------------------------ analyzer / rule plumbing
def test_analyze_schedule_clean_and_firing():
    good = generate_1f1b_ir(4, 2)
    rep = analyze_schedule([good, generate_zero_bubble_ir(4, 2)])
    assert rep.ok and rep.findings == []
    assert good.name in rep.programs

    stages = _copy_stages(good)
    ri = next(i for i, ins in enumerate(stages[1]) if ins.op == "RECV")
    del stages[1][ri]
    rep2 = analyze_schedule(_mutated(good, stages, "dropped-recv"))
    assert not rep2.ok
    assert {f.rule_id for f in rep2.errors()} <= {
        "pipe/unpaired-send-recv", "pipe/schedule-deadlock",
        "pipe/stale-weight-application"}


def test_engine_refuses_prover_rejected_schedule():
    """The MPMD engine must refuse a rejected schedule at construction,
    before building any stage program."""
    import jax

    from deepspeed_tpu.runtime.pipe.mpmd import MPMDPipelineEngine

    from test_pipe import _tiny_lm_module

    ir = generate_1f1b_ir(4, 2)
    stages = _copy_stages(ir)
    ri = next(i for i, ins in enumerate(stages[1]) if ins.op == "RECV")
    del stages[1][ri]
    bad = _mutated(ir, stages, "dropped-recv")

    module, _ = _tiny_lm_module(n_mlp=2, num_stages=2)
    with pytest.raises(ValueError, match="rejected by the static prover"):
        MPMDPipelineEngine(module, num_micro=4, devices=jax.devices()[:2],
                           schedule_ir=bad)


def test_aot_pipeline_schedule_report_prices_before_compile():
    from deepspeed_tpu.runtime.aot import pipeline_schedule_report

    rep = pipeline_schedule_report(generate_zero_bubble_ir(8, 4),
                                   activation_bytes=1 << 20,
                                   stage_param_bytes=1 << 22)
    assert rep["proof_ok"] and rep["findings"] == []
    assert rep["split_backward"] is True
    # peak = params + max-residency * one activation
    assert rep["peak_schedule_bytes"] == (1 << 22) + 4 * (1 << 20)
    assert rep["confidence"] == "fits"
    assert 0.0 < rep["bubble_frac"] < 1.0

    # a cyclic schedule prices as unprovable, not as a number
    bad = ScheduleIR(
        name="cross-wait", num_stages=2, num_micro=1,
        stages=[
            [RECV(1, "x", 0), F(0), SEND(1, "y", 0)],
            [RECV(0, "y", 0), F(0), SEND(0, "x", 0)],
        ])
    rep2 = pipeline_schedule_report(bad, activation_bytes=1 << 20)
    assert not rep2["proof_ok"] and rep2["peak_schedule_bytes"] is None


def test_w_without_b_and_duplicate_w_rejected():
    """The other stale-weight shapes: an orphaned W and a double-applied W
    both carry exact locations."""
    ir = generate_zero_bubble_ir(4, 2)

    stages = _copy_stages(ir)
    wi = next(i for i, ins in enumerate(stages[0]) if ins.op == "W")
    stages[0].append(stages[0][wi])  # duplicate
    dup = [f for f in prove_schedule(_mutated(ir, stages, "dup-w"))
           if f.rule_id == RULE_STALE_WEIGHT]
    assert dup and LOC_RE.search(dup[0].location)

    stages = _copy_stages(ir)
    stages[0].append(W(ir.num_micro + 3))  # B never existed
    orphan = [f for f in prove_schedule(_mutated(ir, stages, "orphan-w"))
              if f.rule_id == RULE_STALE_WEIGHT]
    assert orphan and LOC_RE.search(orphan[0].location)
