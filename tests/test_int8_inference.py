"""int8 inference that actually saves memory (VERDICT r2 'next' #5 / weak #5).

The per-layer path stores the block stacks as int8 ``{"q","s"}`` leaves and
feeds them to the Pallas int8-weight matmul (``ops/pallas/int8_matmul.py``)
inside the decode scan — dequantization happens per VMEM tile, so the compiled
program never materializes a full dequantized weight tree. Parity: the reference's int8
inference kernels consume quantized weights directly
(``csrc/transformer/inference/csrc/dequantize.cu``).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.inference import DeepSpeedInferenceConfig, InferenceEngine
from deepspeed_tpu.inference.engine import for_gpt
from deepspeed_tpu.models import gpt


CFG = gpt.GPTConfig(vocab_size=64, n_layer=4, n_head=2, d_model=32,
                    max_seq_len=64)


@pytest.fixture(scope="module")
def params():
    return gpt.init_params(CFG, jax.random.PRNGKey(0))


def _engine(params, quant: bool, tp: int = 1):
    return InferenceEngine(
        for_gpt(CFG, params),
        DeepSpeedInferenceConfig(
            dtype="float32", max_out_tokens=32,
            tensor_parallel={"tp_size": tp},
            quant={"enabled": quant, "bits": 8, "group_size": 32}))


def test_per_layer_quant_activates(params):
    eng = _engine(params, quant=True)
    assert eng._per_layer_quant
    qkv = eng.params["blocks"]["qkv_w"]
    assert isinstance(qkv, dict) and qkv["q"].dtype == jnp.int8
    # int8 at rest: the quantized stack is half the bf16 bytes, quarter of fp32
    assert qkv["q"].nbytes == CFG.n_layer * CFG.d_model * 3 * CFG.d_model


def test_int8_prefill_close_to_fp32(params, rng):
    ids = rng.integers(0, 64, size=(2, 8)).astype(np.int32)
    ref = np.asarray(_engine(params, quant=False).forward(ids))
    got = np.asarray(_engine(params, quant=True).forward(ids))
    # int8 weight noise is bounded: logits stay close on a tiny model
    assert np.mean(np.abs(got - ref)) < 0.15 * np.mean(np.abs(ref)) + 0.05


def test_int8_generate_runs_and_matches_shapes(params, rng):
    ids = rng.integers(0, 64, size=(2, 6)).astype(np.int32)
    out = _engine(params, quant=True).generate(ids, max_new_tokens=6)
    assert out.shape == (2, 12)
    assert np.all(out[:, :6] == ids)


def test_no_full_dequantized_stack_in_program(params):
    """Structural proof of the memory claim: in the traced prefill program, no
    top-level (outside-scan) op converts a full [L, ...] int8 stack to float —
    dequantization happens only on per-layer slices inside the scan."""
    eng = _engine(params, quant=True)
    qparams = eng.params

    def fn(p, ids):
        cache = gpt.init_cache(CFG, 2, 16, jnp.float32)
        logits, _ = gpt.forward_with_cache(CFG, p, ids, cache)
        return logits

    ids = jnp.zeros((2, 8), jnp.int32)
    jaxpr = jax.make_jaxpr(fn)(qparams, ids)
    L = CFG.n_layer
    for eqn in jaxpr.jaxpr.eqns:  # top level only: scan interiors are fine
        if eqn.primitive.name == "convert_element_type":
            src = eqn.invars[0].aval
            if (getattr(src, "dtype", None) == jnp.int8 and src.ndim >= 3
                    and src.shape[0] == L):
                raise AssertionError(
                    f"full int8 stack dequantized at top level: {eqn}")


def test_int8_with_tensor_parallel(params, rng):
    """int8 q-leaves still shard over tp (quantized_partition_specs)."""
    eng = _engine(params, quant=True, tp=2)
    qkv = eng.params["blocks"]["qkv_w"]
    assert not qkv["q"].sharding.is_fully_replicated
    ids = rng.integers(0, 64, size=(1, 6)).astype(np.int32)
    out = eng.generate(ids, max_new_tokens=4)
    assert out.shape == (1, 10)


def test_int8_beam_search_runs(params):
    """Beam search composes with per-layer int8 weights (cache reorder only
    touches the KV stacks; quantized {'q','s'} leaves pass through)."""
    eng = _engine(params, quant=True)
    ids = np.random.default_rng(0).integers(0, CFG.vocab_size, (1, 6), np.int32)
    out = np.asarray(eng.generate(ids, max_new_tokens=4, num_beams=3))
    assert out.shape == (1, 10)
    np.testing.assert_array_equal(out[:, :6], ids)


# ------------------------------------------------------------------ int4
def _engine4(params, tp: int = 1):
    return InferenceEngine(
        for_gpt(CFG, params),
        DeepSpeedInferenceConfig(
            dtype="float32", max_out_tokens=32,
            tensor_parallel={"tp_size": tp},
            quant={"enabled": True, "bits": 4, "group_size": 32}))


def test_int4_packed_leaves_quarter_bytes(params):
    """bits=4 stores PACKED nibbles: the stack is a quarter of bf16 bytes
    (the capability that makes 20B decode chip-resident on one v5e)."""
    eng = _engine4(params)
    qkv = eng.params["blocks"]["qkv_w"]
    assert isinstance(qkv, dict) and "q4" in qkv
    assert qkv["q4"].dtype == jnp.int8
    assert qkv["q4"].nbytes == CFG.n_layer * CFG.d_model * 3 * CFG.d_model // 2


def test_int4_prefill_close_to_fp32(params, rng):
    ids = rng.integers(0, 64, size=(2, 8)).astype(np.int32)
    ref = np.asarray(_engine(params, quant=False).forward(ids))
    got = np.asarray(_engine4(params).forward(ids))
    # 4-bit noise is larger than 8-bit but still bounded on a tiny model
    assert np.mean(np.abs(got - ref)) < 0.4 * np.mean(np.abs(ref)) + 0.1


def test_int4_generate_runs_and_matches_shapes(params, rng):
    ids = rng.integers(0, 64, size=(2, 6)).astype(np.int32)
    out = _engine4(params).generate(ids, max_new_tokens=6)
    assert out.shape == (2, 12)
    assert np.all(out[:, :6] == ids)


def test_int4_with_tensor_parallel(params, rng):
    eng = _engine4(params, tp=2)
    qkv = eng.params["blocks"]["qkv_w"]
    assert not qkv["q4"].sharding.is_fully_replicated
    ids = rng.integers(0, 64, size=(1, 6)).astype(np.int32)
    out = eng.generate(ids, max_new_tokens=4)
    assert out.shape == (1, 10)


# ------------------------------------------------ host-streamed big-model init
def test_streamed_quantized_init_matches_structure():
    """init_quantized_decode_params builds the same tree SHAPE as
    init_params -> quantize_for_inference, without the fp32 tree ever
    existing (the 20B-on-one-chip enabler)."""
    qp_ref = gpt.quantize_for_inference(
        CFG, gpt.init_params(CFG, jax.random.PRNGKey(0)),
        bits=4, group_size=32)
    qp_str = gpt.init_quantized_decode_params(CFG, bits=4, group_size=32)
    ref_shapes = jax.tree_util.tree_map(lambda x: (x.shape, str(x.dtype)),
                                        qp_ref)
    str_shapes = jax.tree_util.tree_map(lambda x: (x.shape, str(x.dtype)),
                                        qp_str)
    # same structure; dense leaves are bf16 in the streamed tree (engine
    # would cast the fp32 reference tree the same way)
    assert jax.tree_util.tree_structure(ref_shapes) == \
        jax.tree_util.tree_structure(str_shapes)
    assert (qp_str["blocks"]["qkv_w"]["q4"].shape
            == qp_ref["blocks"]["qkv_w"]["q4"].shape)
    assert str(qp_str["blocks"]["qkv_w"]["s"].dtype) == "float32"


def test_streamed_quantize_math_matches_ops_quantizer():
    """The numpy quantizer inside the streamed init is bit-identical to
    ops.quantizer.quantize."""
    from deepspeed_tpu.ops.quantizer import quantize

    rng = np.random.default_rng(0)
    w = rng.standard_normal((64, 96)).astype(np.float32)
    q_ref, s_ref = quantize(jnp.asarray(w), bits=4, num_groups=w.size // 32)
    qmax = 2.0 ** 3 - 1.0
    g = w.reshape(w.size // 32, -1)
    absmax = np.max(np.abs(g), axis=1, keepdims=True)
    scales = np.where(absmax > 0, absmax / qmax, 1.0).astype(np.float32)
    q_np = np.clip(np.round(g / scales), -qmax - 1, qmax).astype(np.int8)
    np.testing.assert_array_equal(np.asarray(q_ref).reshape(q_np.shape), q_np)
    np.testing.assert_allclose(np.asarray(s_ref), scales[:, 0], rtol=1e-7)


def test_engine_accepts_pre_quantized_params(rng):
    """Pre-quantized trees are detected: no re-quantize, scales stay fp32,
    generate runs (the host-streamed 20B decode path end-to-end, tiny)."""
    qp = gpt.init_quantized_decode_params(CFG, bits=4, group_size=32)
    eng = InferenceEngine(
        for_gpt(CFG, qp),
        DeepSpeedInferenceConfig(dtype="bfloat16", max_out_tokens=32))
    assert eng._per_layer_quant
    qkv = eng.params["blocks"]["qkv_w"]
    assert "q4" in qkv and str(qkv["s"].dtype) == "float32"
    ids = rng.integers(0, 64, size=(2, 6)).astype(np.int32)
    out = eng.generate(ids, max_new_tokens=6)
    assert out.shape == (2, 12)
    assert np.all(np.asarray(out)[:, :6] == ids)


def test_streamed_pack_matches_kernel_pack():
    """Value-level pin: the numpy packer inside the streamed init must be
    bit-identical to the kernel's pack_int4 — a divergence would make every
    streamed weight decode to garbage with shapes still green."""
    from deepspeed_tpu.ops.pallas.int8_matmul import pack_int4, unpack_int4

    rng = np.random.default_rng(0)
    q = rng.integers(-8, 8, size=(8, 64)).astype(np.int8)
    F = q.shape[-1]
    lo = q[..., : F // 2].astype(np.int32) & 0xF
    hi = q[..., F // 2:].astype(np.int32)
    np_packed = (lo | (hi << 4)).astype(np.int8)  # np_pack4's exact math
    np.testing.assert_array_equal(np_packed, np.asarray(pack_int4(q)))
    np.testing.assert_array_equal(np.asarray(unpack_int4(np_packed)), q)


def test_streamed_init_decodes_to_same_weights(rng):
    """End-to-end value check: a streamed-init forward equals the forward of
    the SAME quantized weights assembled via the public pack/unpack path."""
    from deepspeed_tpu.ops.pallas.int8_matmul import unpack_int4

    qp = gpt.init_quantized_decode_params(CFG, bits=4, group_size=32)
    leaf = qp["blocks"]["qkv_w"]
    # reconstruct the dense stack from the streamed leaf and compare a
    # matmul against _wm's own dequant route
    w_unpacked = np.asarray(unpack_int4(leaf["q4"]), np.float32)
    L, D, F = w_unpacked.shape
    s = np.asarray(leaf["s"], np.float32)
    w = (w_unpacked.reshape(-1, 32) * s.reshape(-1)[:, None]).reshape(
        L, D, F)
    x = rng.standard_normal((2, D)).astype(np.float32)
    got = gpt._wm(jnp.asarray(x), jax.tree_util.tree_map(
        lambda a: a[0], leaf))
    np.testing.assert_allclose(np.asarray(got), x @ w[0], rtol=2e-2,
                               atol=2e-2)
