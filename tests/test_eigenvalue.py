"""Per-layer Hessian eigenvalue probe (runtime/eigenvalue.py) + MoQ coupling."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.compression import CompressionScheduler
from deepspeed_tpu.runtime.eigenvalue import Eigenvalue


# ----------------------------------------------------------------- power iteration
def test_quadratic_eigenvalues_recovered():
    # loss = sum_l 0.5 * a_l * ||w_l||^2  =>  per-layer Hessian = a_l * I,
    # top eigenvalue a_l; post_process normalizes by the max.
    coefs = np.asarray([1.0, 2.0, 4.0], np.float32)
    params = {"blocks": {"w": jnp.asarray(
        np.random.default_rng(0).normal(size=(3, 8)), jnp.float32)}}

    def loss_fn(p):
        w = p["blocks"]["w"]
        return 0.5 * jnp.sum(jnp.asarray(coefs)[:, None] * w * w)

    ev = Eigenvalue(max_iter=50, tol=1e-4).compute(loss_fn, params)
    np.testing.assert_allclose(ev, coefs / coefs.max(), rtol=1e-2)


def test_anisotropic_hessian_top_eigenvalue():
    # loss_l = 0.5 * (a*x^2 + b*y^2): top eigenvalue max(a, b) per layer
    ab = np.asarray([[1.0, 3.0], [5.0, 2.0]], np.float32)
    params = {"blocks": {"w": jnp.ones((2, 2), jnp.float32)}}

    def loss_fn(p):
        w = p["blocks"]["w"]
        return 0.5 * jnp.sum(jnp.asarray(ab) * w * w)

    ev = Eigenvalue(max_iter=100, tol=1e-5).compute(loss_fn, params)
    np.testing.assert_allclose(ev, np.asarray([3.0, 5.0]) / 5.0, rtol=1e-2)


def test_successive_computes_are_not_stale():
    # a second compute() with a different loss must NOT return the first
    # call's eigenvalues (the compiled HVP takes params as a traced argument
    # and is rebuilt for a new loss-fn object)
    params = {"blocks": {"w": jnp.ones((2, 4), jnp.float32)}}
    ev_obj = Eigenvalue(max_iter=50, tol=1e-4)
    first = ev_obj.compute(
        lambda p: 0.5 * jnp.sum(jnp.asarray([1.0, 2.0])[:, None]
                                * p["blocks"]["w"] ** 2), params)
    second = ev_obj.compute(
        lambda p: 0.5 * jnp.sum(jnp.asarray([8.0, 1.0])[:, None]
                                * p["blocks"]["w"] ** 2), params)
    np.testing.assert_allclose(first, [0.5, 1.0], rtol=1e-2)
    np.testing.assert_allclose(second, [1.0, 0.125], rtol=1e-2)


def test_batch_is_traced_argument():
    # same loss-fn object, different batches: one compiled program, fresh values
    params = {"blocks": {"w": jnp.ones((1, 4), jnp.float32)}}

    def loss_fn(p, b):
        return 0.5 * b * jnp.sum(p["blocks"]["w"] ** 2)

    ev_obj = Eigenvalue(max_iter=20, tol=1e-4)
    a = ev_obj.compute(loss_fn, params, batch=jnp.float32(1.0))
    b = ev_obj.compute(loss_fn, params, batch=jnp.float32(3.0))
    # normalized output is 1.0 either way; the raw iteration must converge for
    # both (i.e. the second batch actually flowed through the cached program)
    np.testing.assert_allclose(a, [1.0])
    np.testing.assert_allclose(b, [1.0])


def test_curvature_scope_excludes_coincident_leaves(rng):
    # a non-layer leaf whose leading dim equals n_layer must use the scalar
    # gate, not the per-layer stretched gate
    tree = {"blocks": {"qkv_w": jnp.asarray(rng.normal(size=(2, 16, 16)),
                                            jnp.float32)},
            "head_w": jnp.asarray(rng.normal(size=(2, 16)), jnp.float32)}
    sched = CompressionScheduler({
        "weight_quantization": {
            "shared_parameters": {"enabled": True, "schedule_offset": 5},
            "different_groups": {}}}, tree)
    curv = jnp.asarray([1.0, 1.0], jnp.float32)  # stretch factor 5 everywhere
    out = sched.transform(tree, jnp.int32(10), curvature=curv)
    # in-scope stacked leaf: offset stretched to 25, still untouched at step 10
    np.testing.assert_array_equal(np.asarray(out["blocks"]["qkv_w"]),
                                  np.asarray(tree["blocks"]["qkv_w"]))
    # out-of-scope leaf: scalar gate (offset 5), quantized at step 10
    assert not np.array_equal(np.asarray(out["head_w"]),
                              np.asarray(tree["head_w"]))


def test_post_process_zero_maps_to_one():
    out = Eigenvalue.post_process([0.0, 2.0, -1.0])
    np.testing.assert_allclose(out, [1.0, 1.0, 0.5])
    # all-zero: every layer conservative
    np.testing.assert_allclose(Eigenvalue.post_process([0.0, 0.0]), [1.0, 1.0])


def test_missing_layer_subtree_raises():
    with pytest.raises(ValueError, match="no stacked layer subtree"):
        Eigenvalue().compute(lambda p: 0.0, {"w": jnp.ones((2, 2))})


def test_layer_num_mismatch_raises():
    params = {"blocks": {"w": jnp.ones((3, 4))}}
    with pytest.raises(ValueError, match="layer_num"):
        Eigenvalue(layer_num=5).compute(lambda p: 0.0, params)


# ----------------------------------------------------------------- MoQ coupling
def test_curvature_stretches_quant_schedule(rng):
    tree = {"blocks": {"qkv_w": jnp.asarray(rng.normal(size=(2, 16, 16)),
                                            jnp.float32)}}
    sched = CompressionScheduler({
        "weight_quantization": {
            "shared_parameters": {"enabled": True, "schedule_offset": 5},
            "different_groups": {}}}, tree)
    # layer 0: factor 1 (offset 5); layer 1: factor 5 (offset 25)
    curv = jnp.asarray([0.0, 1.0], jnp.float32)
    out = sched.transform(tree, jnp.int32(10), curvature=curv)
    got = np.asarray(out["blocks"]["qkv_w"])
    ref = np.asarray(tree["blocks"]["qkv_w"])
    assert not np.array_equal(got[0], ref[0])  # past stretched offset: quantized
    np.testing.assert_array_equal(got[1], ref[1])  # high curvature: untouched
    # far past both offsets, every layer quantizes
    late = np.asarray(sched.transform(tree, jnp.int32(100),
                                      curvature=curv)["blocks"]["qkv_w"])
    assert not np.array_equal(late[1], ref[1])


# ----------------------------------------------------------------- engine hook
@pytest.mark.slow
def test_engine_probes_curvature_and_trains():
    from deepspeed_tpu.models import build_gpt
    from deepspeed_tpu.models.gpt import GPTConfig

    model, cfg = build_gpt(GPTConfig(
        vocab_size=64, d_model=32, n_layer=2, n_head=2, max_seq_len=16))
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model,
        config={
            "train_micro_batch_size_per_gpu": 1,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "compression_training": {
                "weight_quantization": {
                    "shared_parameters": {"enabled": True, "schedule_offset": 2},
                    "different_groups": {
                        "g0": {"params": {"start_bits": 8,
                                          "quantize_groups": 1}}},
                }},
            "eigenvalue": {"enabled": True, "max_iter": 8, "tol": 1e-2,
                           "gas_boundary_resolution": 2},
            "steps_per_print": 0,
        })
    assert engine._eigenvalue is not None
    assert engine.state["curvature"].shape == (2,)
    r = np.random.default_rng(0)
    b = {"input_ids": r.integers(0, 64, size=(8, 16), dtype=np.int32)}
    for _ in range(3):
        m = engine.train_batch(b)
        assert np.isfinite(float(m["loss"]))
    curv = np.asarray(engine.state["curvature"])
    assert curv.shape == (2,)
    assert np.all((curv >= 0.0) & (curv <= 1.0))
    assert curv.max() > 0.0  # the probe ran and produced signal


@pytest.mark.slow
def test_imperative_api_probes_curvature():
    from deepspeed_tpu.models import build_gpt
    from deepspeed_tpu.models.gpt import GPTConfig

    model, cfg = build_gpt(GPTConfig(
        vocab_size=64, d_model=32, n_layer=2, n_head=2, max_seq_len=16))
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model,
        config={
            "train_micro_batch_size_per_gpu": 1,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "eigenvalue": {"enabled": True, "max_iter": 4, "tol": 1e-1},
            "steps_per_print": 0,
        })
    r = np.random.default_rng(0)
    b = {"input_ids": r.integers(0, 64, size=(8, 16), dtype=np.int32)}
    loss = engine.forward(b)
    engine.backward(loss)
    engine.step()
    curv = np.asarray(engine.state["curvature"])
    assert curv.max() > 0.0  # forward/backward/step path probed too


def test_batch_arity_is_part_of_the_cache_key():
    params = {"blocks": {"w": jnp.ones((1, 4), jnp.float32)}}

    def loss_fn(p, b=None):
        w = p["blocks"]["w"]
        base = 0.5 * jnp.sum(w * w)
        return base if b is None else base * b

    ev_obj = Eigenvalue(max_iter=10, tol=1e-3)
    a = ev_obj.compute(loss_fn, params)                    # no batch
    b = ev_obj.compute(loss_fn, params, batch=jnp.float32(2.0))  # with batch
    np.testing.assert_allclose(a, [1.0])
    np.testing.assert_allclose(b, [1.0])  # rebuilt with batch arity, no crash
