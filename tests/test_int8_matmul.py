"""Pallas int8-weight matmul (ops/pallas/int8_matmul.py).

Parity target: the reference's dequant-fused inference GEMMs
(``csrc/transformer/inference/csrc/dequantize.cu`` + pt_binding GEMMs) —
s8 weights consumed directly, dequantized per tile, never materialized.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.pallas.int8_matmul import int8_matmul
from deepspeed_tpu.ops.quantizer import quantize


def _ref(x, q, s, group):
    D, F = q.shape
    w = (np.asarray(q, np.float32).reshape(-1, group)
         * np.asarray(s, np.float32)[:, None]).reshape(D, F)
    return np.asarray(x, np.float32) @ w


@pytest.mark.parametrize("M,D,F,group", [
    (1, 256, 512, 128),     # decode-shaped GEMV
    (8, 512, 1536, 128),    # b8 qkv-shaped
    (5, 256, 512, 128),     # ragged M (sublane padding)
    (2, 256, 512, 256),     # coarser groups
])
def test_matches_dequant_reference(M, D, F, group):
    k1, k2 = jax.random.split(jax.random.PRNGKey(0))
    x = jax.random.normal(k1, (M, D), jnp.float32)
    w = jax.random.normal(k2, (D, F), jnp.float32)
    q, s = quantize(w, bits=8, num_groups=(D * F) // group)
    out = int8_matmul(x, q, s, group_size=group)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               _ref(x, q, s, group), rtol=2e-2, atol=2e-2)


def test_ineligible_group_falls_back():
    # group 64 < lane width: must fall back to XLA dequant (still correct)
    k1, k2 = jax.random.split(jax.random.PRNGKey(1))
    x = jax.random.normal(k1, (2, 128), jnp.float32)
    w = jax.random.normal(k2, (128, 256), jnp.float32)
    q, s = quantize(w, bits=8, num_groups=(128 * 256) // 64)
    out = int8_matmul(x, q, s, group_size=64)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               _ref(x, q, s, 64), rtol=2e-2, atol=2e-2)


def test_bf16_activation_dtype_out():
    k1, k2 = jax.random.split(jax.random.PRNGKey(2))
    x = jax.random.normal(k1, (8, 256), jnp.bfloat16)
    w = jax.random.normal(k2, (256, 512), jnp.float32)
    q, s = quantize(w, bits=8, num_groups=(256 * 512) // 128)
    out = int8_matmul(x, q, s, group_size=128)
    assert out.dtype == jnp.bfloat16 and out.shape == (8, 512)


def test_ragged_F_group_flat_fallback():
    # F % group != 0 (d_model=320-style): flat-group dequant must handle it
    k1, k2 = jax.random.split(jax.random.PRNGKey(3))
    D, F, group = 320, 960, 128
    x = jax.random.normal(k1, (2, D), jnp.float32)
    w = jax.random.normal(k2, (D, F), jnp.float32)
    q, s = quantize(w, bits=8, num_groups=(D * F) // group)
    out = int8_matmul(x, q, s, group_size=group)
    ref = (np.asarray(q, np.float32).reshape(-1, group)
           * np.asarray(s, np.float32)[:, None]).reshape(D, F)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(x, np.float32) @ ref,
                               rtol=2e-2, atol=2e-2)


def test_large_M_falls_back():
    # prefill-sized M must not route into the VMEM-resident kernel
    from deepspeed_tpu.ops.pallas import int8_matmul as mod

    k1, k2 = jax.random.split(jax.random.PRNGKey(4))
    D, F, group, M = 256, 512, 128, 1024
    assert M > mod._MAX_M
    x = jax.random.normal(k1, (M, D), jnp.float32)
    w = jax.random.normal(k2, (D, F), jnp.float32)
    q, s = quantize(w, bits=8, num_groups=(D * F) // group)
    out = int8_matmul(x, q, s, group_size=group)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               _ref(x, q, s, group), rtol=2e-2, atol=2e-2)


# ------------------------------------------------------------------ int4
def test_pack_unpack_int4_roundtrip():
    from deepspeed_tpu.ops.pallas.int8_matmul import pack_int4, unpack_int4

    rng = np.random.default_rng(0)
    w = rng.integers(-8, 8, size=(4, 16, 256)).astype(np.int8)
    packed = pack_int4(jnp.asarray(w))
    assert packed.shape == (4, 16, 128) and packed.dtype == jnp.int8
    np.testing.assert_array_equal(np.asarray(unpack_int4(packed)), w)


def _ref4(x, q, s, group):
    D, F = q.shape
    w = (np.asarray(q, np.float32).reshape(-1, group)
         * np.asarray(s, np.float32)[:, None]).reshape(D, F)
    return np.asarray(x, np.float32) @ w


@pytest.mark.parametrize("M,D,F,group", [
    (1, 256, 1024, 128),    # decode-shaped GEMV
    (8, 512, 3072, 128),    # b8 qkv-shaped (n_f odd at bf512 -> exercises
                            # eligibility; 3072/512=6 even — kernel path)
    (5, 256, 1024, 256),    # ragged M + coarser groups
])
def test_int4_matches_dequant_reference(M, D, F, group):
    from deepspeed_tpu.ops.pallas.int8_matmul import int4_matmul, pack_int4

    k1, k2 = jax.random.split(jax.random.PRNGKey(0))
    x = jax.random.normal(k1, (M, D), jnp.float32)
    w = jax.random.normal(k2, (D, F), jnp.float32)
    q, s = quantize(w, bits=4, num_groups=(D * F) // group)
    out = int4_matmul(x, pack_int4(q), s, group_size=group)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               _ref4(x, q, s, group), rtol=2e-2, atol=2e-1)


def test_int4_odd_f_block_count_falls_back():
    """F=512 at block_f=512 -> a single f-block can't split into halves;
    the XLA fallback must still be exact."""
    from deepspeed_tpu.ops.pallas.int8_matmul import int4_matmul, pack_int4

    k1, k2 = jax.random.split(jax.random.PRNGKey(1))
    x = jax.random.normal(k1, (2, 256), jnp.float32)
    w = jax.random.normal(k2, (256, 512), jnp.float32)
    q, s = quantize(w, bits=4, num_groups=(256 * 512) // 128)
    out = int4_matmul(x, pack_int4(q), s, group_size=128)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               _ref4(x, q, s, 128), rtol=2e-2, atol=2e-1)
