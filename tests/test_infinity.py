"""ZeRO-Infinity parameter streaming (offload_param): host-resident masters
streamed unit-by-unit through device memory.

Mirrors the reference's offload_param coverage
(tests/unit/runtime/zero/test_zero_offloadpp.py + the ZeRO-Infinity configs in
tests/unit/runtime/zero/test_zero.py): correctness vs the in-HBM trajectory,
bf16 training, checkpoint round-trip, and the NVMe master store.
"""

import numpy as np
import pytest

import jax.numpy as jnp

import deepspeed_tpu


def _engine(config_extra=None, vocab=128, tie=True):
    from deepspeed_tpu.models import build_gpt
    from deepspeed_tpu.models.gpt import GPTConfig

    model, cfg = build_gpt(GPTConfig(
        vocab_size=vocab, d_model=32, n_layer=3, n_head=2, max_seq_len=32,
        tie_embeddings=tie))
    config = {
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "steps_per_print": 0,
    }
    config.update(config_extra or {})
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)
    return engine, cfg


def _batch(cfg, seed=0, bs=16, seq=16):
    r = np.random.default_rng(seed)
    return {"input_ids": r.integers(0, cfg.vocab_size, size=(bs, seq),
                                    dtype=np.int32)}


STREAM_CFG = {"zero_optimization": {"offload_param": {"device": "cpu"}}}


def _transplant(runner, device_params):
    """Overwrite the stream runner's host masters with a device param tree."""
    runner.init_host_state()
    dp = {k: np.asarray(v, np.float32) for k, v in device_params.items()
          if k != "blocks"}
    blocks = {k: np.asarray(v, np.float32)
              for k, v in device_params["blocks"].items()}
    for i, (unit, name, shape) in enumerate(runner._leaves):
        if unit == "embed" or unit == "final":
            src = dp[name]
        else:
            layer = int(unit.split("_")[1])
            src = blocks[name][layer]
        assert src.shape == shape, (unit, name, src.shape, shape)
        mst, m, v = runner._state[i]
        mst[...] = src
        runner._refresh_push_buf(i, mst)


@pytest.mark.slow
def test_stream_matches_in_hbm_trajectory():
    """With identical initial weights, the streamed (per-unit recompute) step
    must track the fused in-HBM program's loss and updated params."""
    e_dev, cfg = _engine()
    e_str, _ = _engine(STREAM_CFG)
    assert e_str._param_stream is not None
    _transplant(e_str._param_stream, e_dev.state["params"])

    for i in range(3):
        b = _batch(cfg, seed=i)
        m_str = e_str.train_batch(b)
        m_dev = e_dev.train_batch(b)
        np.testing.assert_allclose(
            float(m_str["loss"]), float(m_dev["loss"]), rtol=2e-4)
        np.testing.assert_allclose(
            float(m_str["grad_norm"]), float(m_dev["grad_norm"]), rtol=2e-3)

    # compare one updated layer-leaf and the embedding against the device run
    runner = e_str._param_stream
    leaf_by_key = {(u, n): i for i, (u, n, _) in enumerate(runner._leaves)}
    wte_stream = runner._state[leaf_by_key[("embed", "wte")]][0]
    np.testing.assert_allclose(
        wte_stream, np.asarray(e_dev.state["params"]["wte"], np.float32),
        rtol=1e-3, atol=2e-5)
    qkv_stream = runner._state[leaf_by_key[("layer_1", "qkv_w")]][0]
    np.testing.assert_allclose(
        qkv_stream, np.asarray(e_dev.state["params"]["blocks"]["qkv_w"][1],
                               np.float32), rtol=1e-3, atol=2e-5)


def test_stream_bf16_loss_falls():
    e, cfg = _engine({**STREAM_CFG, "bf16": {"enabled": True},
                      "gradient_clipping": 1.0})
    b = _batch(cfg, seed=0)
    losses = [float(e.train_batch(b)["loss"]) for _ in range(8)]
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]  # overfits the repeated batch
    stats = e._param_stream.last_stats
    assert stats["n_params"] > 0 and stats["wire_bytes_per_step"] > 0


def test_stream_device_state_is_empty():
    e, _ = _engine(STREAM_CFG)
    assert e.state["params"] == {}
    assert e.state["opt"] == {} and e.state["master"] == {}


def test_stream_untied_head():
    e, cfg = _engine({**STREAM_CFG}, tie=False)
    b = _batch(cfg, seed=0)
    losses = [float(e.train_batch(b)["loss"]) for _ in range(6)]
    assert losses[-1] < losses[0]
    keys = {(u, n) for u, n, _ in e._param_stream._leaves}
    assert ("final", "lm_head") in keys


def test_stream_checkpoint_roundtrip(tmp_path):
    e, cfg = _engine(STREAM_CFG)
    b = _batch(cfg, seed=0)
    for _ in range(2):
        e.train_batch(b)
    e.save_checkpoint(str(tmp_path))
    loss_ref = float(e.train_batch(_batch(cfg, seed=7))["loss"])
    e2, _ = _engine(STREAM_CFG)
    e2.load_checkpoint(str(tmp_path))
    assert int(e2.state["step"]) == 2
    assert e2._param_stream.count == 2
    # replaying the same next batch from the restored state matches exactly
    loss2 = float(e2.train_batch(_batch(cfg, seed=7))["loss"])
    assert loss2 == pytest.approx(loss_ref, rel=1e-6)


def test_stream_checkpoint_requires_optimizer_state(tmp_path):
    e, cfg = _engine(STREAM_CFG)
    e.train_batch(_batch(cfg))
    e.save_checkpoint(str(tmp_path))
    e2, _ = _engine(STREAM_CFG)
    with pytest.raises(ValueError, match="host master"):
        e2.load_checkpoint(str(tmp_path), load_optimizer_states=False)


def test_stream_nvme_masters(tmp_path):
    e, cfg = _engine({"zero_optimization": {"offload_param": {
        "device": "nvme", "nvme_path": str(tmp_path), "buffer_count": 2}}})
    assert e._param_stream.store is not None
    b = _batch(cfg, seed=0)
    losses = [float(e.train_batch(b)["loss"]) for _ in range(4)]
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]


@pytest.mark.slow
def test_stream_labels_and_loss_mask_match_engine():
    """The stream head honors labels/loss_mask exactly like next_token_loss."""
    e_dev, cfg = _engine()
    e_str, _ = _engine(STREAM_CFG)
    _transplant(e_str._param_stream, e_dev.state["params"])
    r = np.random.default_rng(3)
    b = _batch(cfg, seed=3)
    b["loss_mask"] = (r.random(b["input_ids"].shape) > 0.3).astype(np.float32)
    m_str = e_str.train_batch(b)
    m_dev = e_dev.train_batch(b)
    np.testing.assert_allclose(float(m_str["loss"]), float(m_dev["loss"]),
                               rtol=2e-4)


def test_stream_rejects_unknown_batch_keys():
    e, cfg = _engine(STREAM_CFG)
    b = _batch(cfg)
    b["attention_mask"] = np.ones_like(b["input_ids"])
    with pytest.raises(ValueError, match="unknown"):
        e.train_batch(b)


def test_stream_rejects_gas():
    with pytest.raises(ValueError, match="gradient_accumulation_steps"):
        _engine({**STREAM_CFG, "gradient_accumulation_steps": 2})


def test_stream_supersedes_offload_optimizer():
    """A full ZeRO-Infinity config (both offload blocks) routes to the param
    stream runner, which owns the host optimizer itself."""
    e, cfg = _engine({"zero_optimization": {
        "offload_param": {"device": "cpu"},
        "offload_optimizer": {"device": "cpu"}}})
    assert e._param_stream is not None and e._offload is None
    m = e.train_batch(_batch(cfg))
    assert np.isfinite(float(m["loss"]))
