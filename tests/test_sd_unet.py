"""Faithful SD-1.x UNet/VAE (models/sd_unet.py): shapes, forward, import."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.models.sd_unet import (
    SDUNetConfig,
    SDVAEDecoderConfig,
    TINY_UNET,
    TINY_VAE,
    apply_sd_unet,
    apply_sd_vae_decoder,
    import_sd_unet_state,
    import_sd_vae_decoder_state,
    init_sd_unet,
    init_sd_vae_decoder,
    unet_param_shapes,
    vae_decoder_param_shapes,
)


def test_sd15_param_inventory_matches_architecture():
    """The full-size SD-1.5 shape walk must produce the known inventory:
    (320,640,1280,1280) channels, cross-attn in the first three down blocks,
    skip-concat channel math consistent end-to-end."""
    shapes = unet_param_shapes(SDUNetConfig())
    assert shapes["conv_in.weight"] == (3, 3, 4, 320)
    assert shapes["time_embedding.linear_1.weight"] == (320, 1280)
    # last down block has no attentions, others do
    assert "down_blocks.2.attentions.1.norm.weight" in shapes
    assert "down_blocks.3.attentions.0.norm.weight" not in shapes
    # first up resnet concatenates mid output with the deepest skip
    assert shapes["up_blocks.0.resnets.0.conv1.weight"] == (3, 3, 2560, 1280)
    # cross-boundary skip: up block 1's LAST resnet sees the 640 skip
    assert shapes["up_blocks.1.resnets.2.conv1.weight"] == (3, 3, 1920, 1280)
    assert shapes["conv_out.weight"] == (3, 3, 320, 4)
    # cross-attention keys attend over the 768-wide text context
    assert shapes[
        "down_blocks.0.attentions.0.transformer_blocks.0.attn2.to_k.weight"
    ] == (768, 320)
    n_params = sum(int(np.prod(s)) for s in shapes.values())
    assert 8.3e8 < n_params < 9e8  # SD-1.5 UNet is ~860M params


@pytest.mark.slow
def test_tiny_unet_forward_shapes():
    params = init_sd_unet(TINY_UNET, jax.random.PRNGKey(0))
    lat = jnp.zeros((2, 16, 16, 4))
    ctx = jnp.zeros((2, 7, TINY_UNET.cross_attention_dim))
    out = jax.jit(lambda p, l, t, c: apply_sd_unet(TINY_UNET, p, l, t, c))(
        params, lat, jnp.asarray([3, 5]), ctx)
    assert out.shape == (2, 16, 16, 4)
    assert np.isfinite(np.asarray(out)).all()


@pytest.mark.slow
def test_tiny_unet_conditioning_matters():
    params = init_sd_unet(TINY_UNET, jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    lat = jnp.asarray(rng.normal(size=(1, 8, 8, 4)), jnp.float32)
    c1 = jnp.asarray(rng.normal(size=(1, 7, 32)), jnp.float32)
    c2 = jnp.asarray(rng.normal(size=(1, 7, 32)), jnp.float32)
    t = jnp.asarray([10])
    o1 = apply_sd_unet(TINY_UNET, params, lat, t, c1)
    o2 = apply_sd_unet(TINY_UNET, params, lat, t, c2)
    o3 = apply_sd_unet(TINY_UNET, params, lat, jnp.asarray([500]), c1)
    assert np.abs(np.asarray(o1 - o2)).max() > 1e-6  # text conditioning flows
    assert np.abs(np.asarray(o1 - o3)).max() > 1e-6  # time conditioning flows


def test_tiny_vae_decoder_upsamples_8x_equivalent():
    params = init_sd_vae_decoder(TINY_VAE, jax.random.PRNGKey(1))
    lat = jnp.zeros((1, 4, 4, 4))
    img = jax.jit(lambda p, l: apply_sd_vae_decoder(TINY_VAE, p, l))(params, lat)
    # len(chans)-1 = 1 upsample for the tiny config
    assert img.shape == (1, 8, 8, 3)
    assert np.isfinite(np.asarray(img)).all()


def _to_torch_layout(params):
    torch = pytest.importorskip("torch")
    sd = {}
    for k, v in params.items():
        a = np.asarray(v)
        if a.ndim == 4:
            a = a.transpose(3, 2, 0, 1)  # HWIO -> [out, in, kh, kw]
        elif a.ndim == 2:
            a = a.T
        sd[k] = torch.from_numpy(np.ascontiguousarray(a))
    return sd


@pytest.mark.slow
def test_unet_import_roundtrip_and_config_inference():
    params = init_sd_unet(TINY_UNET, jax.random.PRNGKey(2))
    sd = _to_torch_layout(params)
    cfg, got = import_sd_unet_state(sd, n_head=TINY_UNET.n_head,
                                    norm_groups=TINY_UNET.norm_groups)
    assert cfg.block_out_channels == TINY_UNET.block_out_channels
    assert cfg.cross_attn == TINY_UNET.cross_attn
    assert cfg.cross_attention_dim == TINY_UNET.cross_attention_dim
    for k in params:
        np.testing.assert_array_equal(np.asarray(got[k]),
                                      np.asarray(params[k]), err_msg=k)
    # imported weights drive the same forward
    lat = jnp.ones((1, 8, 8, 4))
    ctx = jnp.ones((1, 5, 32))
    np.testing.assert_allclose(
        np.asarray(apply_sd_unet(cfg, got, lat, jnp.asarray([7]), ctx)),
        np.asarray(apply_sd_unet(TINY_UNET, params, lat, jnp.asarray([7]),
                                 ctx)), rtol=1e-6)


def test_vae_import_ignores_encoder_keys():
    torch = pytest.importorskip("torch")
    params = init_sd_vae_decoder(TINY_VAE, jax.random.PRNGKey(3))
    sd = _to_torch_layout(params)
    sd["encoder.conv_in.weight"] = torch.zeros(16, 3, 3, 3)  # must be ignored
    cfg, got = import_sd_vae_decoder_state(
        sd, norm_groups=TINY_VAE.norm_groups)
    assert cfg.block_out_channels == TINY_VAE.block_out_channels
    for k in params:
        np.testing.assert_array_equal(np.asarray(got[k]),
                                      np.asarray(params[k]), err_msg=k)


@pytest.mark.slow
def test_import_rejects_mismatched_state():
    params = init_sd_unet(TINY_UNET, jax.random.PRNGKey(4))
    sd = _to_torch_layout(params)
    sd.pop("conv_out.bias")
    with pytest.raises(ValueError, match="missing"):
        import_sd_unet_state(sd, TINY_UNET)


@pytest.mark.slow
def test_sd_pipeline_from_diffusers_dir(tmp_path):
    """End-to-end: write a diffusers-layout checkpoint dir (safetensors),
    load it, and run the DDIM+CFG+VAE pipeline on the faithful arch."""
    pytest.importorskip("safetensors")
    from safetensors.numpy import save_file

    from deepspeed_tpu.models.sd_unet import SDPipeline

    uparams = init_sd_unet(TINY_UNET, jax.random.PRNGKey(0))
    vparams = init_sd_vae_decoder(TINY_VAE, jax.random.PRNGKey(1))

    def to_torch_layout_np(params):
        out = {}
        for k, v in params.items():
            a = np.asarray(v)
            if a.ndim == 4:
                a = a.transpose(3, 2, 0, 1)
            elif a.ndim == 2:
                a = a.T
            out[k] = np.ascontiguousarray(a)
        return out

    for name, params in (("unet", uparams), ("vae", vparams)):
        (tmp_path / name).mkdir()
        save_file(to_torch_layout_np(params),
                  str(tmp_path / name / "diffusion_pytorch_model.safetensors"))

    pipe = SDPipeline.from_diffusers_dir(
        str(tmp_path), n_head=TINY_UNET.n_head,
        norm_groups=TINY_UNET.norm_groups, latent_size=8)
    ctx_dim = TINY_UNET.cross_attention_dim
    r = np.random.default_rng(0)
    img = pipe(jnp.asarray(r.normal(size=(1, 5, ctx_dim)), jnp.float32),
               jnp.asarray(r.normal(size=(1, 5, ctx_dim)), jnp.float32),
               num_steps=3)
    assert img.shape == (1, 16, 16, 3)  # tiny VAE: one 2x upsample from 8
    assert np.isfinite(img).all()
