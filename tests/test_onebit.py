"""1-bit optimizers + compressed collectives.

Mirrors the reference's tests/onebit/ intent: the compressed allreduce must be an
unbiased-ish error-compensated approximation (error feedback keeps the cumulative
drift bounded), and 1-bit Adam must track dense Adam's loss trajectory through the
warmup→compressed switch.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from deepspeed_tpu.utils.jax_compat import shard_map
from jax.sharding import PartitionSpec as P

import deepspeed_tpu
from deepspeed_tpu.runtime.comm.compressed import (
    compressed_allreduce,
    compression_error_shapes,
    pack_signs,
    unpack_signs,
)
from deepspeed_tpu.runtime.topology import MeshTopology


def test_pack_unpack_roundtrip(rng):
    x = jnp.asarray(rng.normal(size=(64,)), jnp.float32)
    packed = pack_signs(x)
    assert packed.shape == (8,) and packed.dtype == jnp.uint8
    signs = unpack_signs(packed, 64)
    np.testing.assert_array_equal(np.asarray(signs), np.sign(np.asarray(x)) + (np.asarray(x) == 0))


def _run_compressed(xs, werr, serr, mesh, world):
    """xs: [W, n] per-rank vectors."""
    def _body(x, w, s):
        r, w2, s2 = compressed_allreduce(x[0], w[0], s[0], "dp")
        return r, w2[None, :], s2[None, :]

    f = shard_map(
        _body,
        mesh=mesh,
        in_specs=(P("dp", None), P("dp", None), P("dp", None)),
        out_specs=(P(), P("dp", None), P("dp", None)),
        check_vma=False)

    # adapt out shapes: result replicated, errors per-rank
    def g(x, w, s):
        r, w2, s2 = f(x, w, s)
        return r, w2, s2

    return jax.jit(g)(xs, werr, serr)


@pytest.mark.slow
def test_compressed_allreduce_error_feedback_bounded(rng):
    world, n = 4, 256
    topo = MeshTopology.create(dp=world, devices=jax.devices()[:world])
    wn, sn = compression_error_shapes(n, world)
    xs = jnp.asarray(rng.normal(size=(world, n)), jnp.float32)
    werr = jnp.zeros((world, wn))
    serr = jnp.zeros((world, sn // 1))[:, : sn]
    serr = jnp.zeros((world, sn))
    true_mean = np.asarray(xs).mean(axis=0)

    # repeated allreduce of the SAME vectors: error feedback must make the
    # time-average of outputs converge to the true mean (the defining property
    # of error-compensated compression)
    acc = np.zeros(n)
    steps = 60
    for i in range(steps):
        out, w2, s2 = _run_compressed(xs, werr, serr, topo.mesh, world)
        r = np.asarray(out)
        # shard_map out P() gives result from averaging chunks of all server ranks
        acc += r
        werr, serr = w2, s2
    avg = acc / steps
    err0 = np.linalg.norm(np.asarray(_run_compressed(
        xs, jnp.zeros_like(werr), jnp.zeros_like(serr), topo.mesh, world)[0]) - true_mean)
    err_avg = np.linalg.norm(avg - true_mean)
    # time-averaged output is much closer to the truth than any single compressed step
    assert err_avg < err0 * 0.2, (err_avg, err0)


def test_compressed_allreduce_identical_inputs_sign_exact(rng):
    # all ranks hold c * ones: sign compression is EXACT for constant vectors
    world, n = 4, 64
    topo = MeshTopology.create(dp=world, devices=jax.devices()[:world])
    xs = jnp.ones((world, n), jnp.float32) * 0.5
    werr = jnp.zeros((world, n))
    serr = jnp.zeros((world, n // world))
    out, _, _ = _run_compressed(xs, werr, serr, topo.mesh, world)
    np.testing.assert_allclose(np.asarray(out), 0.5 * np.ones(n), rtol=1e-6)


def _tiny_engine(opt_type, opt_params, gas=1):
    from deepspeed_tpu.models import build_gpt
    from deepspeed_tpu.models.gpt import GPTConfig

    model, cfg = build_gpt(GPTConfig(
        vocab_size=128, d_model=32, n_layer=2, n_head=2, max_seq_len=32))
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model,
        config={
            "train_micro_batch_size_per_gpu": 2,
            "gradient_accumulation_steps": gas,
            "optimizer": {"type": opt_type, "params": opt_params},
            "steps_per_print": 0,
        })
    return engine, cfg


def _batches(cfg, n, bs, seq=16, gas=1, seed=0):
    r = np.random.default_rng(seed)
    shape = (bs, seq) if gas == 1 else (gas, bs, seq)
    return [{"input_ids": r.integers(0, cfg.vocab_size, size=shape, dtype=np.int32)}
            for _ in range(n)]


@pytest.mark.parametrize("opt_type", ["OneBitAdam", "ZeroOneAdam", "OneBitLamb"])
@pytest.mark.slow
def test_onebit_trains_through_switch(opt_type):
    engine, cfg = _tiny_engine(opt_type, {
        "lr": 1e-3, "freeze_step": 3, "var_freeze_step": 5})
    # batch = micro_bs * dp(8) = 16; train on ONE repeated batch so the loss
    # must fall if the compressed stage is actually optimizing
    (batch,) = _batches(cfg, 1, 16)
    losses = []
    for _ in range(10):
        m = engine.train_batch(batch)
        losses.append(float(m["loss"]))
        assert np.isfinite(losses[-1])
    # crossed freeze_step=3 into the compressed stage and kept training
    assert engine.global_steps == 10
    assert engine._onebit._compressed_jit is not None
    assert losses[-1] < losses[2], losses  # improving after the switch


@pytest.mark.slow
def test_onebit_matches_dense_during_warmup():
    engine_1b, cfg = _tiny_engine("OneBitAdam", {"lr": 1e-3, "freeze_step": 100})
    engine_d, _ = _tiny_engine("Adam", {"lr": 1e-3})
    for b in _batches(cfg, 3, 16):
        m1 = engine_1b.train_batch(b)
        m2 = engine_d.train_batch(b)
        # warmup phase IS dense adam (adam_w_mode differences aside: wd=0)
        np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-5)


def test_onebit_rejects_zero2_and_fp16():
    from deepspeed_tpu.models import build_gpt
    from deepspeed_tpu.models.gpt import GPTConfig

    model, _ = build_gpt(GPTConfig(
        vocab_size=64, d_model=32, n_layer=1, n_head=2, max_seq_len=16))
    with pytest.raises(ValueError, match="ZeRO"):
        deepspeed_tpu.initialize(model=model, config={
            "train_micro_batch_size_per_gpu": 1,
            "optimizer": {"type": "OneBitAdam", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 2},
        })
    with pytest.raises(RuntimeError, match="train_batch"):
        engine, _, _, _ = deepspeed_tpu.initialize(model=model, config={
            "train_micro_batch_size_per_gpu": 1,
            "optimizer": {"type": "OneBitAdam", "params": {"lr": 1e-3}},
        })
        engine.forward({"input_ids": np.zeros((8, 16), np.int32)})


@pytest.mark.slow
def test_onebit_bf16_updates_master():
    """Compressed stage must step the fp32 master, not the bf16 params."""
    from deepspeed_tpu.models import build_gpt
    from deepspeed_tpu.models.gpt import GPTConfig

    model, cfg = build_gpt(GPTConfig(
        vocab_size=128, d_model=32, n_layer=2, n_head=2, max_seq_len=32))
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model,
        config={
            "train_micro_batch_size_per_gpu": 2,
            "bf16": {"enabled": True},
            "optimizer": {"type": "OneBitAdam",
                          "params": {"lr": 1e-3, "freeze_step": 1}},
            "steps_per_print": 0,
        })
    (batch,) = _batches(cfg, 1, 16)
    engine.train_batch(batch)  # warmup step
    master_before = np.asarray(engine.state["master"]["wte"], np.float32).copy()
    engine.train_batch(batch)  # compressed step
    master_after = np.asarray(engine.state["master"]["wte"], np.float32)
    assert not np.array_equal(master_before, master_after)
    # params follow the master (bf16 rounding of it)
    np.testing.assert_allclose(
        np.asarray(engine.state["params"]["wte"], np.float32), master_after,
        rtol=1e-2)


@pytest.mark.slow
def test_onebit_with_grad_accumulation():
    engine, cfg = _tiny_engine("OneBitAdam", {"lr": 1e-3, "freeze_step": 2}, gas=2)
    for b in _batches(cfg, 4, 16, gas=2):
        m = engine.train_batch(b)
        assert np.isfinite(float(m["loss"]))
    assert engine.global_steps == 4
