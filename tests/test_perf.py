"""Optimizer micro-benchmarks (parity: the reference's ``tests/perf/``
adam throughput checks). Timing on shared CI boxes is noisy, so assertions
are structural — the native path engaged, produced identical math, and
sustained a sane floor — with measured rates printed for the record."""

import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.adam.cpu_adam import DeepSpeedCPUAdam
from deepspeed_tpu.ops.optimizers import get_optimizer

N = 1_000_000


def _run_cpu_adam(opt, steps=5):
    rng = np.random.default_rng(0)
    p = rng.normal(size=N).astype(np.float32)
    m = np.zeros(N, np.float32)
    v = np.zeros(N, np.float32)
    g = rng.normal(size=N).astype(np.float32)
    opt.step(p, m, v, g, step_count=1)  # warmup + allocation
    t0 = time.perf_counter()
    for i in range(steps):
        opt.step(p, m, v, g, step_count=i + 2)
    dt = time.perf_counter() - t0
    return p, m, v, steps * N / dt


def test_cpu_adam_throughput_and_native_parity():
    native = DeepSpeedCPUAdam(lr=1e-3)
    rate_info = []
    p_n, m_n, v_n, rate = _run_cpu_adam(native)
    rate_info.append(f"cpu_adam[{'native' if native.is_native else 'numpy'}]: "
                     f"{rate / 1e6:.0f}M params/s")
    # floor: even the numpy fallback does >5M params/s on any host; a silent
    # pathological path (per-element python loop) would fail this
    assert rate > 5e6, rate_info
    print("; ".join(rate_info))

    if native.is_native:
        # the SIMD path must match the numpy math bit-for-bit-ish
        fallback = DeepSpeedCPUAdam(lr=1e-3)
        fallback._lib = None  # force numpy fallback
        p_f, m_f, v_f, _ = _run_cpu_adam(fallback)
        # AVX FMA reorders the accumulation; agreement is to float32 rounding
        np.testing.assert_allclose(p_n, p_f, rtol=1e-4, atol=1e-6)
        np.testing.assert_allclose(v_n, v_f, rtol=1e-4, atol=1e-6)


def test_fused_adam_single_program():
    """The fused device Adam must execute the whole tree update as ONE jitted
    call whose throughput beats a per-leaf python loop — the reference's
    multi_tensor_apply motivation (csrc/adam/multi_tensor_adam.cu)."""
    opt = get_optimizer("Adam", {"lr": 1e-3})
    leaves = {f"w{i}": jnp.ones((64, 64), jnp.float32) for i in range(32)}
    grads = {f"w{i}": jnp.full((64, 64), 0.1, jnp.float32) for i in range(32)}
    state = opt.init(leaves)
    step = jax.jit(lambda g, s, p: opt.update(g, s, p, jnp.float32(1e-3)))
    new_p, new_s = step(grads, state, leaves)  # compile
    jax.block_until_ready(new_p)
    t0 = time.perf_counter()
    for _ in range(20):
        new_p, new_s = step(grads, new_s, new_p)
    jax.block_until_ready(new_p)
    fused_dt = time.perf_counter() - t0
    n_params = 32 * 64 * 64
    rate = 20 * n_params / fused_dt
    print(f"fused_adam: {rate / 1e6:.0f}M params/s over 32 leaves")
    assert np.isfinite(float(jax.tree_util.tree_leaves(new_p)[0][0, 0]))
    assert rate > 1e6
