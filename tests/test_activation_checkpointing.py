"""Activation checkpointing: remat correctness, partitioning, RNG tracker.

Mirrors the reference's test_activation_checkpointing.py intent: checkpointed
forward/backward must match the unchckpointed one bit-for-bit (same RNG), and the
config plumbing must set the module globals.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.runtime.activation_checkpointing import (
    CheckpointConfig,
    checkpoint,
    checkpoint_wrapper,
    configure,
    get_rng_tracker,
    is_configured,
    reset,
)


@pytest.fixture(autouse=True)
def _clean():
    reset()
    yield
    reset()


def _mlp(params, x):
    h = jnp.tanh(x @ params["w1"])
    return (h @ params["w2"]).sum()


def _params(rng):
    return {
        "w1": jnp.asarray(rng.normal(size=(16, 32)), jnp.float32),
        "w2": jnp.asarray(rng.normal(size=(32, 8)), jnp.float32),
    }


def test_checkpoint_matches_plain(rng):
    # Both grads are compiled: remat determinism is an intra-program XLA
    # guarantee, and the engine only ever remats inside jit. Eager op-by-op
    # dispatch compiles the recomputed forward as separate tiny programs whose
    # fusion/layout choices differ at the last ulp from the plain backward —
    # that divergence is a dispatch artifact, not a remat correctness property
    # (this exact comparison, unjitted, failed from the seed onward).
    params = _params(rng)
    x = jnp.asarray(rng.normal(size=(4, 16)), jnp.float32)

    def loss_plain(p):
        return _mlp(p, x)

    def loss_ckpt(p):
        return checkpoint(lambda q: _mlp(q, x), p)

    g1 = jax.jit(jax.grad(loss_plain))(params)
    g2 = jax.jit(jax.grad(loss_ckpt))(params)
    for k in g1:
        np.testing.assert_array_equal(np.asarray(g1[k]), np.asarray(g2[k]))


def test_checkpoint_wrapper_inside_jit_and_scan(rng):
    params = _params(rng)
    xs = jnp.asarray(rng.normal(size=(3, 4, 16)), jnp.float32)
    f = checkpoint_wrapper(lambda p, x: _mlp(p, x))

    @jax.jit
    def loss(p):
        def body(c, x):
            return c + f(p, x), None

        tot, _ = jax.lax.scan(body, 0.0, xs)
        return tot

    g = jax.grad(loss)(params)
    assert np.isfinite(np.asarray(g["w1"])).all()


def test_configure_from_ds_config():
    cfg = deepspeed_tpu.DeepSpeedConfig.load({
        "train_micro_batch_size_per_gpu": 1,
        "activation_checkpointing": {
            "partition_activations": True,
            "cpu_checkpointing": False,
            "number_checkpoints": 4,
        },
    }, world_size=8)
    configure(deepspeed_config=cfg)
    assert is_configured()
    from deepspeed_tpu.runtime.activation_checkpointing import checkpointing as m

    assert m._config.partition_activations is True
    assert m._config.number_checkpoints == 4


def test_configure_explicit_overrides():
    configure(partition_activations=False, num_checkpoints=2, profile=True)
    from deepspeed_tpu.runtime.activation_checkpointing import checkpointing as m

    assert m._config.profile is True
    assert m._config.number_checkpoints == 2


def test_partition_activations_constraint_runs(rng):
    # on the 8-dev CPU mesh with tp>1 the saved residuals get sharded; verify the
    # checkpointed function still produces identical grads
    from deepspeed_tpu.runtime.topology import MeshTopology, mesh_context

    topo = MeshTopology.create(dp=4, tp=2)
    params = _params(rng)
    x = jnp.asarray(rng.normal(size=(8, 16)), jnp.float32)
    cfg = CheckpointConfig(partition_activations=True)
    f = checkpoint_wrapper(lambda p: _mlp(p, x), cfg)
    with mesh_context(topo.mesh):
        g1 = jax.jit(jax.grad(f))(params)
        g2 = jax.jit(jax.grad(lambda p: _mlp(p, x)))(params)
    for k in g1:
        np.testing.assert_allclose(np.asarray(g1[k]), np.asarray(g2[k]), rtol=1e-6)


def test_rng_tracker_fork_determinism():
    tr = get_rng_tracker()
    tr.reset()
    tr.add("model-parallel-rng", 42)
    k1 = tr.fork()
    k2 = tr.fork()
    assert not np.array_equal(np.asarray(k1), np.asarray(k2))
    tr.reset()
    tr.add("model-parallel-rng", 42)
    k1b = tr.fork()
    np.testing.assert_array_equal(np.asarray(k1), np.asarray(k1b))
    with pytest.raises(Exception):
        tr.add("model-parallel-rng", 1)


def test_engine_configures_activation_checkpointing(rng):
    from deepspeed_tpu.models import build_gpt
    from deepspeed_tpu.models.gpt import GPTConfig

    model, _ = build_gpt(GPTConfig(
        vocab_size=64, d_model=32, n_layer=1, n_head=2, max_seq_len=16))
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model,
        config={
            "train_micro_batch_size_per_gpu": 2,
            "activation_checkpointing": {"partition_activations": True},
            "steps_per_print": 0,
        })
    assert is_configured()
    del engine
