"""REAL 2-process jax.distributed test (VERDICT r2 'next' #8 / weak #6).

The single-process simulated mesh never runs the multi-host branches. This
spawns two actual processes (2 local CPU devices each) glued by
``jax.distributed`` into one 4-device platform and exercises:
``comm.init_distributed`` with a live coordinator, cross-process batch
placement, DP training identical across hosts, the checkpoint tag-validation
barrier (``checkpoint/__init__.py``), process-0 writes with collective
gathers, and multi-host reload. The analog of the reference's
``DistributedTest`` process-spawning harness (``tests/unit/common.py:66``).
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_distributed_train_and_checkpoint(tmp_path):
    worker = os.path.join(os.path.dirname(__file__), "multihost_worker.py")
    port = _free_port()
    # strip the 8-device flag so the workers' own 2-device setting wins
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    procs = [
        subprocess.Popen(
            [sys.executable, worker,
             "--coordinator", f"localhost:{port}",
             "--process-id", str(pid),
             "--ckpt-dir", str(tmp_path / "ckpt"),
             "--out", str(tmp_path / f"out{pid}.json")],
            env=env)
        for pid in range(2)
    ]
    rcs = [p.wait(timeout=550) for p in procs]
    assert rcs == [0, 0]

    outs = [json.loads((tmp_path / f"out{pid}.json").read_text())
            for pid in range(2)]
    # every process computed the SAME global losses (one logical program)
    assert outs[0]["losses"] == outs[1]["losses"]
    assert outs[0]["losses"][-1] < outs[0]["losses"][0]
    # the multi-host checkpoint round-trip continued identically on both
    for o in outs:
        np.testing.assert_allclose(o["resumed"], o["ref"], rtol=1e-6)
