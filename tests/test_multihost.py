"""REAL 2-process jax.distributed test (VERDICT r2 'next' #8 / weak #6).

The single-process simulated mesh never runs the multi-host branches. This
spawns two actual processes (2 local CPU devices each) glued by
``jax.distributed`` into one 4-device platform and exercises:
``comm.init_distributed`` with a live coordinator, cross-process batch
placement, DP training identical across hosts, the checkpoint tag-validation
barrier (``checkpoint/__init__.py``), process-0 writes with collective
gathers, and multi-host reload. The analog of the reference's
``DistributedTest`` process-spawning harness (``tests/unit/common.py:66``).
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_distributed_train_and_checkpoint(tmp_path):
    worker = os.path.join(os.path.dirname(__file__), "multihost_worker.py")
    port = _free_port()
    # strip the 8-device flag so the workers' own 2-device setting wins
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    procs = [
        subprocess.Popen(
            [sys.executable, worker,
             "--coordinator", f"localhost:{port}",
             "--process-id", str(pid),
             "--ckpt-dir", str(tmp_path / "ckpt"),
             "--out", str(tmp_path / f"out{pid}.json")],
            env=env)
        for pid in range(2)
    ]
    rcs = [p.wait(timeout=550) for p in procs]
    if 76 in rcs:  # multihost_worker.BACKEND_UNSUPPORTED_EXIT
        pytest.skip(
            "this jaxlib's CPU client cannot execute cross-process programs "
            "('Multiprocess computations aren't implemented on the CPU "
            "backend', raised from the engine's jitted state init) — the "
            "distributed code paths themselves are exercised single-process "
            "by test_single_process_dp8_equivalent below and on real "
            "multi-chip hardware by the MULTICHIP_r* runs")
    assert rcs == [0, 0]

    outs = [json.loads((tmp_path / f"out{pid}.json").read_text())
            for pid in range(2)]
    # every process computed the SAME global losses (one logical program)
    assert outs[0]["losses"] == outs[1]["losses"]
    assert outs[0]["losses"][-1] < outs[0]["losses"][0]
    # the multi-host checkpoint round-trip continued identically on both
    for o in outs:
        np.testing.assert_allclose(o["resumed"], o["ref"], rtol=1e-6)


@pytest.mark.slow
def test_single_process_dp8_equivalent(tmp_path):
    """The worker's exact scenario — dp data-parallel ZeRO-2 train, save,
    fresh-engine reload, identical continuation — on the in-process 8-device
    mesh. Every sharded-compute path the 2-process test would run (batch
    placement over dp, GSPMD grad reduction, collective checkpoint gathers,
    reload resharding) compiles and executes identically here; what it
    cannot cover is the jax.distributed rendezvous + cross-process barrier,
    which this jaxlib's CPU client refuses (see the skip above)."""
    import deepspeed_tpu as ds
    from deepspeed_tpu.models import build_gpt, gpt

    def make():
        model, _ = build_gpt(gpt.GPTConfig(
            vocab_size=64, n_layer=2, n_head=2, d_model=32, max_seq_len=32))
        engine, _, _, _ = ds.initialize(model=model, config={
            "train_micro_batch_size_per_gpu": 1,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
            "zero_optimization": {"stage": 2},
            "mesh": {"dp": 8},
            "bf16": {"enabled": False},
            "steps_per_print": 0,
        })
        return engine

    engine = make()
    r = np.random.default_rng(0)
    ids = r.integers(0, 64, size=(8, 16), dtype=np.int32)
    losses = [float(engine.train_batch({"input_ids": ids})["loss"])
              for _ in range(3)]
    assert losses[-1] < losses[0]

    engine.save_checkpoint(str(tmp_path / "ckpt"))
    ref = float(engine.train_batch({"input_ids": ids})["loss"])

    engine2 = make()
    path, _ = engine2.load_checkpoint(str(tmp_path / "ckpt"))
    assert path is not None
    got = float(engine2.train_batch({"input_ids": ids})["loss"])
    np.testing.assert_allclose(got, ref, rtol=1e-6)
