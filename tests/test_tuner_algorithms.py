"""Tuner algorithms (autotuning/tuner.py): gridsearch / random / model-based.

Parity targets: reference ``autotuning/tuner/index_based_tuner.py`` and
``model_based_tuner.py`` (cost-model selection with random warmup and an
exploration ratio).
"""

import numpy as np

from deepspeed_tpu.autotuning.autotuner import Autotuner
from deepspeed_tpu.autotuning.tuner import (GridSearchTuner, ModelBasedTuner,
                                            RandomTuner, get_tuner,
                                            ordinal_features)


def test_gridsearch_sequential_and_random_is_permutation():
    g = GridSearchTuner(5)
    order = []
    while (p := g.next_indices(1)):
        order.append(p[0])
        g.update(p[0], 1.0)
    assert order == [0, 1, 2, 3, 4]

    r = RandomTuner(5, seed=3)
    order = []
    while (p := r.next_indices(1)):
        order.append(p[0])
        r.update(p[0], 1.0)
    assert sorted(order) == [0, 1, 2, 3, 4]


def test_model_based_converges_to_good_region():
    """On a smooth landscape the surrogate must concentrate trials near the
    optimum: after warmup, the model-based picks should reach the true best
    config far sooner than its index position."""
    n = 50
    feats = np.arange(n, dtype=np.float64)[:, None]
    true = -((feats[:, 0] - 40.0) ** 2)  # best at index 40
    t = ModelBasedTuner(n, feats, higher_better=True, seed=0,
                        exploration_ratio=0.0)
    measured = []
    for _ in range(10):
        i = t.next_indices(1)[0]
        measured.append(i)
        t.update(i, float(true[i]))
    # linear surrogate on a concave function still ranks the far end top;
    # within 10 trials the best-measured index must be >= 35 (gridsearch
    # would still be at index 9)
    assert max(measured) >= 35, measured


def test_model_based_survives_pruned_trials():
    n = 10
    feats = np.arange(n, dtype=np.float64)[:, None]
    t = ModelBasedTuner(n, feats, higher_better=True, seed=1)
    for _ in range(n):
        i = t.next_indices(1)[0]
        t.update(i, None if i % 2 else float(i))  # odd indices "OOM"
    assert not t.next_indices(1)  # all visited, no crash


def test_get_tuner_fallback_and_unknown():
    import pytest

    assert isinstance(get_tuner("model_based", 3, None, True),
                      GridSearchTuner)  # no features -> fallback
    with pytest.raises(ValueError):
        get_tuner("bayesian", 3, None, True)


def test_autotuner_integration_model_based():
    """End-to-end through Autotuner.tune with a synthetic trial function."""
    at = Autotuner(
        {"autotuning": {"tuner_type": "model_based",
                        "micro_batch_sizes": [1, 2, 4, 8, 16, 32],
                        "zero_stages": [1]}},
        results_dir="/tmp/at_results_test")
    best = at.tune(lambda cfg: float(cfg["train_micro_batch_size_per_gpu"]))
    assert best is not None
    assert best.config["train_micro_batch_size_per_gpu"] == 32
    assert ordinal_features(at.space, at._combos).shape == (6, 2)
