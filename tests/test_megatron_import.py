"""Megatron-DeepSpeed 3D checkpoint import (checkpoint/megatron_import.py)."""

import numpy as np
import pytest

import jax

from deepspeed_tpu.checkpoint.megatron_import import (
    MegatronDSCheckpoint,
    import_megatron_checkpoint,
)
from deepspeed_tpu.models.gpt import GPTConfig, init_params

torch = pytest.importorskip("torch")

H, DH = 4, 8
D = H * DH


def _to_megatron_qkv(qkv_w: np.ndarray, qkv_b: np.ndarray):
    """Our [D, 3D] q|k|v columns -> Megatron [3D, D] per-head-interleaved rows."""
    wt = qkv_w.T  # [3D, D]
    q, k, v = np.split(wt, 3, axis=0)  # each [D, D]
    w = np.stack([q.reshape(H, DH, D), k.reshape(H, DH, D),
                  v.reshape(H, DH, D)], axis=1)  # [H, 3, DH, D]
    bq, bk, bv = np.split(qkv_b, 3)
    b = np.stack([bq.reshape(H, DH), bk.reshape(H, DH),
                  bv.reshape(H, DH)], axis=1)  # [H, 3, DH]
    return w.reshape(3 * D, D), b.reshape(3 * D)


def _write_megatron_ckpt(path, cfg: GPTConfig, params, tp: int):
    """Emit layer_XX-model_YY files the way Megatron-DeepSpeed's pipeline
    module saves them (runtime/pipe/module.py:549 naming; column-parallel
    split on rows, row-parallel on cols, replicated layernorms)."""
    path.mkdir(parents=True, exist_ok=True)
    b = {k: np.asarray(v) for k, v in params["blocks"].items()}

    def save(idx, rank, sd):
        torch.save({k: torch.from_numpy(np.ascontiguousarray(v))
                    for k, v in sd.items()},
                   str(path / f"layer_{idx:02d}-model_{rank:02d}-model_states.pt"))

    for r in range(tp):
        vs = np.asarray(params["wte"]).shape[0] // tp
        save(0, r, {
            "word_embeddings.weight":
                np.asarray(params["wte"])[r * vs:(r + 1) * vs],
            "position_embeddings.weight": np.asarray(params["wpe"]),
        })
    for li in range(cfg.n_layer):
        w_meg, b_meg = _to_megatron_qkv(b["qkv_w"][li], b["qkv_b"][li])
        rows = w_meg.shape[0] // tp  # = heads-per-rank * 3 * DH
        up_rows = b["mlp_up_w"].shape[-1] // tp
        dense_cols = D // tp
        down_cols = b["mlp_down_w"].shape[1] // tp
        for r in range(tp):
            save(2 + li, r, {
                "input_layernorm.weight": b["ln1_scale"][li],
                "input_layernorm.bias": b["ln1_bias"][li],
                "self_attention.query_key_value.weight":
                    w_meg[r * rows:(r + 1) * rows],
                "self_attention.query_key_value.bias":
                    b_meg[r * rows:(r + 1) * rows],
                "self_attention.dense.weight":
                    b["attn_out_w"][li].T[:, r * dense_cols:(r + 1) * dense_cols],
                "self_attention.dense.bias": b["attn_out_b"][li],
                "post_attention_layernorm.weight": b["ln2_scale"][li],
                "post_attention_layernorm.bias": b["ln2_bias"][li],
                "mlp.dense_h_to_4h.weight":
                    b["mlp_up_w"][li].T[r * up_rows:(r + 1) * up_rows],
                "mlp.dense_h_to_4h.bias":
                    b["mlp_up_b"][li][r * up_rows:(r + 1) * up_rows],
                "mlp.dense_4h_to_h.weight":
                    b["mlp_down_w"][li].T[:, r * down_cols:(r + 1) * down_cols],
                "mlp.dense_4h_to_h.bias": b["mlp_down_b"][li],
            })
    for r in range(tp):
        save(2 + cfg.n_layer + 1, r, {
            "weight": np.asarray(params["lnf_scale"]),
            "bias": np.asarray(params["lnf_bias"]),
        })


@pytest.fixture()
def synthetic(tmp_path):
    cfg = GPTConfig(vocab_size=64, n_layer=3, n_head=H, d_model=D,
                    max_seq_len=32, rotary=False)
    params = jax.tree_util.tree_map(
        np.asarray, init_params(cfg, jax.random.PRNGKey(3)))
    # non-degenerate norms/biases so replication handling is actually tested
    r = np.random.default_rng(0)
    for k in ("ln1_scale", "ln1_bias", "ln2_scale", "ln2_bias", "qkv_b",
              "attn_out_b", "mlp_up_b", "mlp_down_b"):
        params["blocks"][k] = r.normal(
            size=params["blocks"][k].shape).astype(np.float32)
    params["lnf_scale"] = r.normal(size=(D,)).astype(np.float32)
    params["lnf_bias"] = r.normal(size=(D,)).astype(np.float32)
    _write_megatron_ckpt(tmp_path, cfg, params, tp=2)
    return tmp_path, cfg, params


def test_discovery_and_tp_degree(synthetic):
    path, cfg, _ = synthetic
    ckpt = MegatronDSCheckpoint(str(path))
    assert ckpt.tp_degree == 2
    assert len(ckpt.layer_indices) == cfg.n_layer + 2  # embed + L + final norm


def test_import_roundtrips_bitwise(synthetic):
    path, cfg, params = synthetic
    got_cfg, got = import_megatron_checkpoint(str(path), n_head=H)
    assert got_cfg.n_layer == cfg.n_layer
    assert got_cfg.d_model == cfg.d_model
    assert got_cfg.vocab_size == cfg.vocab_size
    assert not got_cfg.rotary  # wpe present => learned positions
    flat_want, _ = jax.tree_util.tree_flatten_with_path(params)
    flat_got = dict(jax.tree_util.tree_flatten_with_path(got)[0])
    for kp, want in flat_want:
        np.testing.assert_array_equal(
            flat_got[kp], np.asarray(want),
            err_msg=jax.tree_util.keystr(kp))


def test_imported_model_runs(synthetic):
    path, _, _ = synthetic
    from deepspeed_tpu.models import build_gpt
    from deepspeed_tpu.models.gpt import loss_fn

    cfg, params = import_megatron_checkpoint(str(path), n_head=H)
    build_gpt(cfg)  # config is valid
    batch = {"input_ids": np.random.default_rng(0).integers(
        0, cfg.vocab_size, (2, 16), np.int32)}
    loss, _ = loss_fn(cfg, jax.tree_util.tree_map(np.asarray, params), batch,
                      train=False)
    assert np.isfinite(float(loss))


def test_mismatched_shard_count_raises(synthetic):
    path, _, _ = synthetic
    (path / "layer_02-model_01-model_states.pt").unlink()
    with pytest.raises(ValueError, match="tp shards"):
        MegatronDSCheckpoint(str(path))


def test_empty_dir_raises(tmp_path):
    with pytest.raises(ValueError, match="not a Megatron-DeepSpeed"):
        MegatronDSCheckpoint(str(tmp_path))
