"""Speculative decoding: drafter properties, the multi-token paged verify
kernel vs the dense formula, accept/reject commit semantics vs sequential
decode, scheduler-level rollback/audit under rejection and mid-window
preemption, and end-to-end greedy equivalence spec-on vs spec-off."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.inference.serving import (AdaptiveSpecK,
                                             ContinuousBatchingScheduler,
                                             NGramDrafter, Request,
                                             RequestState, ServingConfig,
                                             ServingEngine,
                                             make_open_loop_workload,
                                             run_continuous, spec_k_ladder)
from deepspeed_tpu.models import gpt as G
from deepspeed_tpu.ops.pallas.decode_attention import (
    paged_decode_attention, paged_verify_attention)


# ------------------------------------------------------------------ drafters
def test_ngram_suffix_match():
    d = NGramDrafter(max_n=3)
    # context ... [7, 8, 9] ... ends with [7, 8]: propose what followed the
    # earlier [7, 8], i.e. [9, 4, 5]
    prompt = np.array([1, 7, 8, 9, 4, 5, 6, 7, 8], np.int32)
    out = d.draft(0, 0, prompt, [], 3)
    assert out.tolist() == [9, 4, 5]


def test_ngram_spans_prompt_and_generated():
    d = NGramDrafter(max_n=2)
    # the suffix match crosses the prompt/generated boundary
    out = d.draft(0, 0, np.array([5, 6, 7], np.int32), [8, 5, 6], 2)
    assert out.tolist() == [7, 8]


def test_ngram_empty_and_tiny_history():
    d = NGramDrafter()
    assert d.draft(0, 0, np.array([3], np.int32), [], 4).size == 0
    assert d.draft(0, 0, np.array([], np.int32), [], 4).size == 0
    assert d.draft(0, 0, np.array([1, 2], np.int32), [], 0).size == 0


def test_ngram_no_match():
    d = NGramDrafter()
    out = d.draft(0, 0, np.arange(10, dtype=np.int32), [], 3)
    assert out.size == 0  # strictly increasing: no repeated suffix


def test_ngram_degenerate_repeats():
    d = NGramDrafter(max_n=3)
    out = d.draft(0, 0, np.full(10, 5, np.int32), [], 3)
    assert out.tolist() == [5, 5, 5]
    # period-2 cycle: the continuation respects the phase
    ctx = np.array([1, 2] * 5, np.int32)          # ends ... 1, 2
    assert d.draft(0, 0, ctx, [], 3).tolist() == [1, 2, 1]


def test_ngram_prefers_full_continuation():
    d = NGramDrafter(max_n=2)
    # two [1, 2] matches, both with k tokens after them: the MOST RECENT
    # full continuation wins
    out = d.draft(0, 0, np.array([1, 2, 9, 8, 7, 1, 2, 3, 1, 2], np.int32),
                  [], 3)
    assert out.tolist() == [3, 1, 2]
    # only the early occurrence has any continuation at all
    out = d.draft(0, 0, np.array([1, 2, 9, 8, 7, 1, 2], np.int32), [], 3)
    assert out.tolist() == [9, 8, 7]


def test_spec_k_ladder():
    assert spec_k_ladder(4) == (1, 2, 4)
    assert spec_k_ladder(1) == (1,)
    assert spec_k_ladder(6) == (1, 2, 4)
    with pytest.raises(ValueError):
        spec_k_ladder(0)


def test_adaptive_k_backoff_and_climb():
    ctl = AdaptiveSpecK(spec_k_ladder(4))
    assert ctl.k == 4                      # starts optimistic
    for _ in range(10):
        ctl.observe(8, 0)                  # nothing accepted
    assert ctl.k == 1                      # collapsed to the floor
    for _ in range(20):
        ctl.observe(8, 8)                  # everything accepted
    assert ctl.k == 4                      # climbed back
    frozen = AdaptiveSpecK(spec_k_ladder(4), adaptive=False)
    for _ in range(10):
        frozen.observe(8, 0)
    assert frozen.k == 4                   # adaptivity off: k pinned


# ------------------------------------------------- verify kernel vs formula
def _dense_verify_ref(q, k_pages, v_pages, lens, tables, wk, wv,
                      k_scales=None, v_scales=None):
    """Materialize history + window per position; plain masked softmax."""
    B, W, H, Dh = q.shape
    ps = k_pages.shape[2]

    def depage(pages, scales, b, t):
        pg = int(tables[b, t // ps])
        off = t % ps
        x = np.asarray(pages[:, pg, off, :], np.float32)
        if scales is not None:
            if x.shape[-1] * 2 == Dh:  # unpack int4 half-split
                lo = (x.astype(np.int8).astype(np.int32) << 28) >> 28
                hi = x.astype(np.int8).astype(np.int32) >> 4
                x = np.concatenate([lo, hi], -1).astype(np.float32)
            x = x * np.asarray(scales)[:, pg, None]
        return x

    out = np.zeros((B, W, H, Dh), np.float32)
    for b in range(B):
        hist_k = [depage(k_pages, k_scales, b, t) for t in range(int(lens[b]))]
        hist_v = [depage(v_pages, v_scales, b, t) for t in range(int(lens[b]))]
        for i in range(W):
            ks = np.stack(hist_k + [np.asarray(wk[b, j], np.float32)
                                    for j in range(i + 1)], 1)
            vs = np.stack(hist_v + [np.asarray(wv[b, j], np.float32)
                                    for j in range(i + 1)], 1)
            s = np.einsum("hd,hsd->hs", np.asarray(q[b, i], np.float32),
                          ks) / np.sqrt(Dh)
            p = np.exp(s - s.max(-1, keepdims=True))
            p /= p.sum(-1, keepdims=True)
            out[b, i] = np.einsum("hs,hsd->hd", p, vs)
    return out


@pytest.mark.parametrize("W", [2, 3, 5])
def test_verify_attention_vs_dense(rng, W):
    B, H, Dh, ps, npages, pps = 3, 4, 16, 8, 32, 4
    k_pages = jnp.asarray(rng.normal(size=(H, npages, ps, Dh)), jnp.float32)
    v_pages = jnp.asarray(rng.normal(size=(H, npages, ps, Dh)), jnp.float32)
    lens = jnp.asarray([0, 5, 17], jnp.int32)   # per-row, incl. empty
    tables = jnp.asarray(
        rng.permutation(np.arange(1, npages))[:B * pps].reshape(B, pps),
        jnp.int32)
    q = jnp.asarray(rng.normal(size=(B, W, H, Dh)), jnp.float32)
    wk = jnp.asarray(rng.normal(size=(B, W, H, Dh)), jnp.float32)
    wv = jnp.asarray(rng.normal(size=(B, W, H, Dh)), jnp.float32)
    ref = _dense_verify_ref(q, k_pages, v_pages, lens, tables, wk, wv)
    got_g = paged_verify_attention(q, k_pages, v_pages, lens, tables,
                                   wk, wv, impl="gather")
    got_k = paged_verify_attention(q, k_pages, v_pages, lens, tables,
                                   wk, wv, impl="kernel")
    np.testing.assert_allclose(np.asarray(got_g), ref, atol=2e-5, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(got_k), ref, atol=2e-5, rtol=1e-4)


@pytest.mark.parametrize("bits", [8, 4])
def test_verify_attention_quantized(rng, bits):
    """int8/int4 pools: kernel and gather dequantize identically; both
    match the dequantize-then-dense reference."""
    B, H, Dh, ps, npages, pps, W = 2, 4, 16, 8, 16, 3, 3
    Dq = Dh // 2 if bits == 4 else Dh
    k_pages = jnp.asarray(rng.integers(-7, 8, (H, npages, ps, Dq)), jnp.int8)
    v_pages = jnp.asarray(rng.integers(-7, 8, (H, npages, ps, Dq)), jnp.int8)
    k_scales = jnp.asarray(rng.uniform(0.05, 0.3, (H, npages)), jnp.float32)
    v_scales = jnp.asarray(rng.uniform(0.05, 0.3, (H, npages)), jnp.float32)
    lens = jnp.asarray([6, 13], jnp.int32)
    tables = jnp.asarray(
        rng.permutation(np.arange(1, npages))[:B * pps].reshape(B, pps),
        jnp.int32)
    q = jnp.asarray(rng.normal(size=(B, W, H, Dh)), jnp.float32)
    wk = jnp.asarray(rng.normal(size=(B, W, H, Dh)), jnp.float32)
    wv = jnp.asarray(rng.normal(size=(B, W, H, Dh)), jnp.float32)
    ref = _dense_verify_ref(q, k_pages, v_pages, lens, tables, wk, wv,
                            k_scales, v_scales)
    got_g = paged_verify_attention(q, k_pages, v_pages, lens, tables, wk, wv,
                                   impl="gather", k_scales=k_scales,
                                   v_scales=v_scales)
    got_k = paged_verify_attention(q, k_pages, v_pages, lens, tables, wk, wv,
                                   impl="kernel", k_scales=k_scales,
                                   v_scales=v_scales)
    np.testing.assert_allclose(np.asarray(got_g), ref, atol=2e-5, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(got_k), np.asarray(got_g),
                               atol=2e-5, rtol=1e-4)


def test_verify_w1_bitwise_vs_single_token_fallback(rng):
    """W=1 verification must reproduce the single-token paged fallback
    BITWISE once the window token is where the pool write would have put
    it — the structural property the greedy-equivalence gate leans on."""
    B, H, Dh, ps, npages, pps = 3, 4, 16, 8, 16, 4
    k_pages = np.asarray(rng.normal(size=(H, npages, ps, Dh)), np.float32)
    v_pages = np.asarray(rng.normal(size=(H, npages, ps, Dh)), np.float32)
    lens = np.asarray([4, 9, 0], np.int32)
    tables = jnp.asarray(
        rng.permutation(np.arange(1, npages))[:B * pps].reshape(B, pps),
        jnp.int32)
    q = jnp.asarray(rng.normal(size=(B, 1, H, Dh)), jnp.float32)
    wk = jnp.asarray(rng.normal(size=(B, 1, H, Dh)), jnp.float32)
    wv = jnp.asarray(rng.normal(size=(B, 1, H, Dh)), jnp.float32)
    got = paged_verify_attention(q, jnp.asarray(k_pages),
                                 jnp.asarray(v_pages), jnp.asarray(lens),
                                 tables, wk, wv, impl="gather")
    # sequential path: append the window token into the pool, lengths + 1
    kp2, vp2 = k_pages.copy(), v_pages.copy()
    for b in range(B):
        pg = int(tables[b, int(lens[b]) // ps])
        off = int(lens[b]) % ps
        kp2[:, pg, off, :] = np.asarray(wk[b, 0])
        vp2[:, pg, off, :] = np.asarray(wv[b, 0])
    ref = paged_decode_attention(q, jnp.asarray(kp2), jnp.asarray(vp2),
                                 jnp.asarray(lens + 1), tables,
                                 impl="gather")
    assert np.array_equal(np.asarray(got[:, 0]), np.asarray(ref[:, 0]))


# ------------------------------------------- verify step + commit semantics
def _tiny(vocab=64):
    return G.GPTConfig(vocab_size=vocab, d_model=32, n_layer=2, n_head=4,
                       max_seq_len=128)


@pytest.mark.parametrize("rotary", [False, True])
def test_verify_step_matches_sequential(rng, rotary):
    """One W-token verify dispatch reproduces W sequential decode steps'
    logits to XLA reduction-tiling noise (different-W executables may tile
    the same reductions differently — observed ~3e-8 on CPU) with every
    argmax IDENTICAL, and committing all W reproduces the sequential pool
    to the same tolerance — speculation is invisible in outputs by
    construction."""
    cfg = G.GPTConfig(vocab_size=64, d_model=32, n_layer=2, n_head=4,
                      max_seq_len=128, rotary=rotary, rotary_pct=0.5)
    params = jax.tree_util.tree_map(
        lambda x: x.astype(jnp.float32), G.init_params(cfg, jax.random.PRNGKey(1)))
    B, ps, npages, pps, W = 3, 8, 32, 6, 3
    paged = G.init_paged_cache(cfg, npages, ps, jnp.float32)
    tables = jnp.asarray(
        np.random.default_rng(3).permutation(
            np.arange(1, npages))[:B * pps].reshape(B, pps), jnp.int32)
    lens = jnp.asarray([4, 7, 2], jnp.int32)
    ids = jnp.asarray(rng.integers(0, 64, (B,)), jnp.int32)
    seq_cache, toks, cur, seq_logits = paged, ids, lens, []
    for _ in range(W):
        lg, seq_cache = G.paged_decode_step(cfg, params, toks, seq_cache,
                                            tables, cur, impl="gather")
        seq_logits.append(lg)
        toks = jnp.argmax(lg, -1).astype(jnp.int32)
        cur = cur + 1
    win = jnp.stack([ids] + [jnp.argmax(seq_logits[i], -1).astype(jnp.int32)
                             for i in range(W - 1)], axis=1)
    vlog, wk, wv = G.paged_verify_step(cfg, params, win, paged, tables,
                                       lens, impl="gather")
    for i in range(W):
        np.testing.assert_allclose(np.asarray(vlog[:, i]),
                                   np.asarray(seq_logits[i]),
                                   atol=1e-5, rtol=1e-5)
        assert bool(jnp.all(jnp.argmax(vlog[:, i], -1)
                            == jnp.argmax(seq_logits[i], -1))), f"pos {i}"
    committed = G.commit_window_kv(paged, wk, wv, tables, lens,
                                   jnp.full(B, W, jnp.int32))
    np.testing.assert_allclose(np.asarray(committed["k_pages"]),
                               np.asarray(seq_cache["k_pages"]), atol=1e-5)
    np.testing.assert_allclose(np.asarray(committed["v_pages"]),
                               np.asarray(seq_cache["v_pages"]), atol=1e-5)


def test_commit_partial_matches_sequential_prefix(rng):
    """Rejection = NOT committing: per-row n_commit writes exactly the
    accepted prefix; the pool equals n sequential appends, bitwise, and
    positions past the frontier stay untouched."""
    cfg = _tiny()
    params = jax.tree_util.tree_map(
        lambda x: x.astype(jnp.float32),
        G.init_params(cfg, jax.random.PRNGKey(2)))
    B, ps, npages, pps, W = 3, 8, 32, 6, 4
    paged = G.init_paged_cache(cfg, npages, ps, jnp.float32)
    tables = jnp.asarray(
        np.random.default_rng(5).permutation(
            np.arange(1, npages))[:B * pps].reshape(B, pps), jnp.int32)
    lens = jnp.asarray([3, 6, 10], jnp.int32)
    win = jnp.asarray(rng.integers(0, 64, (B, W)), jnp.int32)
    _, wk, wv = G.paged_verify_step(cfg, params, win, paged, tables, lens,
                                    impl="gather")
    n = jnp.asarray([0, 2, 4], jnp.int32)
    got = G.commit_window_kv(paged, wk, wv, tables, lens, n)
    # row 0 committed nothing: its pages must be bit-identical to the init
    for j in range(pps):
        pg = int(tables[0, j])
        assert bool(jnp.all(got["k_pages"][:, :, pg] ==
                            paged["k_pages"][:, :, pg]))
    # the one-shot commit equals committing each window step separately
    # (token i at position lens+i for rows still inside their prefix)
    ref = paged
    for i in range(W):
        ref = G.commit_window_kv(
            ref, wk[:, :, i:i + 1], wv[:, :, i:i + 1], tables, lens + i,
            (n > i).astype(jnp.int32))
    assert bool(jnp.all(got["k_pages"] == ref["k_pages"]))
    assert bool(jnp.all(got["v_pages"] == ref["v_pages"]))


@pytest.mark.parametrize("kv_bits", [8, 4])
@pytest.mark.slow
def test_commit_quantized_matches_sequential_appends(rng, kv_bits):
    """Quantized pools: GIVEN the same window K/V values, the one-shot
    commit reproduces per-token sequential ``_append_kv_token`` calls
    BITWISE — payloads AND page scales (opening offsets re-establish,
    mid-page grows requantize; the shared writer cannot drift). Large
    outlier values force actual scale growth mid-page."""
    cfg = _tiny()
    L, H, Dh = cfg.n_layer, cfg.n_head, cfg.head_dim
    B, ps, npages, pps, W = 3, 8, 16, 4, 4
    paged = G.init_paged_cache(cfg, npages, ps, jnp.float32, kv_bits=kv_bits)
    tables = jnp.asarray(
        np.random.default_rng(7).permutation(
            np.arange(1, npages))[:B * pps].reshape(B, pps), jnp.int32)
    # mid-page, page-opening, and page-crossing rows
    lens = jnp.asarray([5, 8, 14], jnp.int32)
    # seed the pool with real prior appends so requantization has payload
    # to move (positions 0..lens-1)
    for t in range(int(jnp.max(lens))):
        live = (t < lens).astype(jnp.int32)
        pos = jnp.minimum(jnp.full((B,), t, jnp.int32), lens - 1)
        page = jnp.where(live > 0, jnp.take_along_axis(
            tables, (pos // ps)[:, None], axis=1)[:, 0], 0)
        tok_k = jnp.asarray(rng.normal(size=(L, H, B, Dh)), jnp.float32)
        tok_v = jnp.asarray(rng.normal(size=(L, H, B, Dh)), jnp.float32)
        for li in range(L):
            kp, ks = G._append_kv_token(paged["k_pages"][li],
                                        paged["k_scales"][li], tok_k[li],
                                        page, pos % ps, kv_bits)
            vp, vs = G._append_kv_token(paged["v_pages"][li],
                                        paged["v_scales"][li], tok_v[li],
                                        page, pos % ps, kv_bits)
            paged = {
                "k_pages": paged["k_pages"].at[li].set(kp),
                "v_pages": paged["v_pages"].at[li].set(vp),
                "k_scales": paged["k_scales"].at[li].set(ks),
                "v_scales": paged["v_scales"].at[li].set(vs)}
    # window values with outliers that grow mid-page scales
    wk = jnp.asarray(rng.normal(size=(L, B, W, H, Dh)) * 3.0, jnp.float32)
    wv = jnp.asarray(rng.normal(size=(L, B, W, H, Dh)) * 3.0, jnp.float32)
    n = jnp.asarray([1, 3, 4], jnp.int32)
    got = G.commit_window_kv(paged, wk, wv, tables, lens, n)
    # sequential reference: per-step _append_kv_token, masked rows -> sink
    ref = {k: v for k, v in paged.items()}
    for i in range(W):
        write = (i < n).astype(jnp.int32)
        pos = lens + i
        pidx = jnp.clip(pos // ps, 0, pps - 1)
        page = jnp.where(write > 0, jnp.take_along_axis(
            tables, pidx[:, None], axis=1)[:, 0], 0)
        off = pos % ps
        for li in range(L):
            kp, ks = G._append_kv_token(
                ref["k_pages"][li], ref["k_scales"][li],
                wk[li, :, i].transpose(1, 0, 2), page, off, kv_bits)
            vp, vs = G._append_kv_token(
                ref["v_pages"][li], ref["v_scales"][li],
                wv[li, :, i].transpose(1, 0, 2), page, off, kv_bits)
            ref = {"k_pages": ref["k_pages"].at[li].set(kp),
                   "v_pages": ref["v_pages"].at[li].set(vp),
                   "k_scales": ref["k_scales"].at[li].set(ks),
                   "v_scales": ref["v_scales"].at[li].set(vs)}
    # page 0 is the reserved sink: masked rows redirect there, and
    # duplicate-index scatters make its (never-read) contents order-
    # dependent — every REAL page's payload must match bitwise; scales to
    # ULP (the compiled scan may fuse amax/qmax into a reciprocal multiply
    # where the eager reference divides — a last-ULP artifact)
    for key in ("k_pages", "v_pages"):
        assert bool(jnp.all(got[key][:, :, 1:] == ref[key][:, :, 1:])), key
    for key in ("k_scales", "v_scales"):
        np.testing.assert_allclose(np.asarray(got[key][:, :, 1:]),
                                   np.asarray(ref[key][:, :, 1:]),
                                   rtol=1e-6, err_msg=key)


# --------------------------------------------------- scheduler-level (fake)
class SpecFakeExecutor:
    """Deterministic device-free executor with the verify protocol: the
    'model' continues any token as prev+1 (mod 97) — matching
    tests/test_serving.FakeExecutor — and acceptance/eos/budget semantics
    mirror the real in-program logic."""

    def __init__(self):
        self.verify_calls = 0
        self.decode_calls = 0

    def prefill(self, slot, tokens, table_row, start=0):
        return (int(tokens[-1]) + 1) % 97

    def decode(self, tokens, tables, lengths, active, steps=1):
        self.decode_calls += 1
        return np.stack([(tokens + k + 1) % 97 for k in range(steps)])

    def verify(self, tokens, tables, lengths, active, eos, budget):
        self.verify_calls += 1
        S, W = tokens.shape
        outs = (tokens + 1) % 97
        agree = (tokens[:, 1:] == outs[:, :-1]).astype(np.int64)
        n = 1 + np.cumprod(agree, axis=1).sum(axis=1)
        is_eos = (outs == eos[:, None]) & (eos[:, None] >= 0)
        has = is_eos.any(axis=1)
        first = np.argmax(is_eos, axis=1)
        n = np.where(has, np.minimum(n, first + 1), n)
        n = np.clip(n, 0, np.maximum(budget, 0))
        return outs, n.astype(np.int64)


class ChainDrafter:
    """Perfect drafter for the fake chain model."""

    kind = "chain"

    def __init__(self):
        self.released = []

    def draft(self, slot, rid, prompt, tokens, k):
        last = tokens[-1] if tokens else int(prompt[-1])
        # the chain model continues t -> t+1, so the tokens after `last`
        # are last+1, last+2, ...
        return np.asarray([(last + 1 + i) % 97 for i in range(k)], np.int32)

    def release(self, slot):
        self.released.append(slot)


class WrongDrafter:
    """Always-wrong drafter: every window is a full reject."""

    kind = "wrong"

    def draft(self, slot, rid, prompt, tokens, k):
        return np.full(k, 96, np.int32)

    def release(self, slot):
        pass


def _sched(ex, drafter=None, num_slots=2, num_pages=32, page_size=4,
           pages_per_seq=8, **kw):
    return ContinuousBatchingScheduler(
        ex, num_slots=num_slots, num_pages=num_pages, page_size=page_size,
        pages_per_seq=pages_per_seq, drafter=drafter, **kw)


def test_spec_scheduler_outputs_match_plain():
    reqs = lambda: [Request(prompt=np.arange(1, n + 2, dtype=np.int32),  # noqa: E731
                            max_new_tokens=m)
                    for n, m in [(3, 9), (6, 4), (2, 7)]]
    plain = reqs()
    s0 = _sched(SpecFakeExecutor())
    for r in plain:
        s0.submit(r)
    s0.run_to_completion()
    spec = reqs()
    ex = SpecFakeExecutor()
    s1 = _sched(ex, drafter=ChainDrafter(), spec_k=4)
    for r in spec:
        s1.submit(r)
    s1.run_to_completion()
    for a, b in zip(plain, spec):
        assert a.tokens == b.tokens
    assert ex.verify_calls > 0
    assert s1.spec_stats["accepted"] > 0
    # the perfect drafter finishes in strictly fewer device dispatches
    assert (ex.verify_calls + ex.decode_calls
            < s0.executor.decode_calls)
    assert s1.audit()["ok"] and s1.allocator.allocated_pages == 0


def test_spec_full_reject_still_progresses_and_audits_clean():
    ex = SpecFakeExecutor()
    s = _sched(ex, drafter=WrongDrafter(), spec_k=4)
    r = Request(prompt=np.array([1, 2], np.int32), max_new_tokens=6)
    s.submit(r)
    s.run_to_completion()
    assert r.tokens == [3, 4, 5, 6, 7, 8]   # chain continuation, unchanged
    assert s.spec_stats["full_reject_windows"] > 0
    assert s.spec_stats["accepted"] == 0
    # adaptive k collapsed to the floor under full rejection
    assert s._spec_ctl.k == 1
    assert s.audit()["ok"] and s.allocator.allocated_pages == 0
    assert r.spec_drafted > 0 and r.spec_accepted == 0


def test_spec_eos_truncates_window():
    ex = SpecFakeExecutor()
    s = _sched(ex, drafter=ChainDrafter(), spec_k=4)
    # chain from 10: 11, 12, 13... eos at 13 must cut generation short
    r = Request(prompt=np.array([10], np.int32), max_new_tokens=20,
                eos_token_id=13)
    s.submit(r)
    s.run_to_completion()
    assert r.tokens[-1] == 13
    assert len(r.tokens) == 3
    assert s.audit()["ok"] and s.allocator.allocated_pages == 0


def test_spec_budget_truncates_window():
    ex = SpecFakeExecutor()
    s = _sched(ex, drafter=ChainDrafter(), spec_k=4)
    r = Request(prompt=np.array([1, 2, 3], np.int32), max_new_tokens=2)
    s.submit(r)
    s.run_to_completion()
    assert r.tokens == [4, 5]               # never past max_new
    assert s.audit()["ok"] and s.allocator.allocated_pages == 0


def test_spec_drafter_released_on_finish():
    d = ChainDrafter()
    s = _sched(SpecFakeExecutor(), drafter=d)
    r = Request(prompt=np.array([1], np.int32), max_new_tokens=3)
    s.submit(r)
    s.run_to_completion()
    assert d.released  # slot state dropped when the request left


def test_spec_no_drafts_falls_back_to_decode():
    class SilentDrafter:
        kind = "silent"

        def draft(self, slot, rid, prompt, tokens, k):
            return np.empty(0, np.int32)

        def release(self, slot):
            pass

    ex = SpecFakeExecutor()
    s = _sched(ex, drafter=SilentDrafter())
    r = Request(prompt=np.array([1, 2], np.int32), max_new_tokens=4)
    s.submit(r)
    s.run_to_completion()
    assert r.tokens == [3, 4, 5, 6]
    assert ex.verify_calls == 0 and ex.decode_calls > 0
    assert s.spec_stats["fallback_steps"] > 0


def test_spec_mid_window_dispatch_failure_heals():
    """A verify episode whose every retry raises: preempt-and-requeue with
    kept tokens, audit clean, outputs identical to a fault-free run."""
    from deepspeed_tpu.resilience import FaultPlan, install_plan

    clean = Request(prompt=np.array([1, 2], np.int32), max_new_tokens=8)
    s0 = _sched(SpecFakeExecutor(), drafter=ChainDrafter())
    s0.submit(clean)
    s0.run_to_completion()

    faulty = Request(prompt=np.array([1, 2], np.int32), max_new_tokens=8)
    s = _sched(SpecFakeExecutor(), drafter=ChainDrafter(),
               dispatch_retries=1)
    s.submit(faulty)
    # dispatch 0 is the prefill; fail the SECOND verify window entirely
    install_plan(FaultPlan(dispatch_raise_at=2, dispatch_raise_times=2))
    try:
        s.run_to_completion()
    finally:
        install_plan(None)
    assert faulty.tokens == clean.tokens
    assert faulty.preemptions >= 1
    assert s.counters.get("dispatch_failed", 0) >= 1
    assert s.audit()["ok"] and s.allocator.allocated_pages == 0


def test_spec_preemption_under_pool_pressure():
    """Mid-window page exhaustion preempts the newest slot (kept tokens)
    and the run still completes with the exact chain outputs."""
    ex = SpecFakeExecutor()
    s = _sched(ex, drafter=ChainDrafter(), num_slots=2, num_pages=8,
               page_size=2, pages_per_seq=8, spec_k=4)
    a = Request(prompt=np.array([1, 2, 3], np.int32), max_new_tokens=8)
    b = Request(prompt=np.array([50, 51, 52], np.int32), max_new_tokens=8)
    s.submit(a)
    s.submit(b)
    s.run_to_completion()
    assert a.tokens == [4, 5, 6, 7, 8, 9, 10, 11]
    assert b.tokens == [53, 54, 55, 56, 57, 58, 59, 60]
    assert a.preemptions + b.preemptions >= 1
    assert s.audit()["ok"] and s.allocator.allocated_pages == 0


# ----------------------------------------------------- engine end to end
@pytest.fixture(scope="module")
def tiny_setup():
    cfg = _tiny()
    params = G.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _engine(cfg, params, **spec_kw):
    draft = spec_kw.pop("_draft", None)
    return ServingEngine(cfg, params, ServingConfig(
        num_slots=2, page_size=8, max_model_len=64, prefill_chunk=16,
        dtype="float32", decode_block=2, max_queue=16, **spec_kw),
        draft=draft)


def _run_wl(eng, seed=11):
    wl = make_open_loop_workload(5, rate_rps=500.0, prompt_len=(3, 20),
                                 max_new=(4, 12), vocab_size=64, seed=seed)
    rep = run_continuous(eng, wl)
    assert rep["finished"] == len(wl)
    return wl, rep


def test_engine_spec_greedy_equivalence(tiny_setup):
    cfg, params = tiny_setup
    off_wl, _ = _run_wl(_engine(cfg, params))
    on_wl, rep = _run_wl(_engine(cfg, params, spec_drafter="ngram",
                                 spec_k=4))
    for a, b in zip(off_wl, on_wl):
        assert a.tokens == b.tokens, (a.rid, a.tokens, b.tokens)
    assert rep["spec"]["windows"] > 0
    assert rep["pool_audit_ok"]


@pytest.mark.slow
def test_engine_spec_kv8_greedy_equivalence(tiny_setup):
    """Quantized pools: spec-on vs spec-off at kv_bits=8 stay identical —
    the window's dense-context verification plus sequential-exact commit
    does not move any argmax on this model."""
    cfg, params = tiny_setup
    off_wl, _ = _run_wl(_engine(cfg, params, kv_bits=8))
    on_wl, rep = _run_wl(_engine(cfg, params, kv_bits=8,
                                 spec_drafter="ngram", spec_k=4))
    for a, b in zip(off_wl, on_wl):
        assert a.tokens == b.tokens, (a.rid, a.tokens, b.tokens)
    assert rep["spec"]["windows"] > 0


@pytest.mark.slow
def test_engine_draft_model_drafter(tiny_setup):
    """draft == target: near-total acceptance, strictly fewer dispatches
    than the n-gram run, identical outputs."""
    cfg, params = tiny_setup
    off_wl, off_rep = _run_wl(_engine(cfg, params))
    on_wl, rep = _run_wl(_engine(cfg, params, spec_drafter="draft_model",
                                 _draft=(cfg, params), spec_k=4))
    for a, b in zip(off_wl, on_wl):
        assert a.tokens == b.tokens
    assert rep["spec"]["accept_rate"] > 0.5
    assert rep["decode_steps"] < off_rep["decode_steps"]


@pytest.mark.slow
def test_engine_spec_under_chaos(tiny_setup):
    """End-to-end greedy equivalence holds across an injected verify
    dispatch failure (mid-window preemption on the real engine)."""
    from deepspeed_tpu.resilience import FaultPlan, install_plan

    cfg, params = tiny_setup
    off_wl, _ = _run_wl(_engine(cfg, params))
    eng = _engine(cfg, params, spec_drafter="ngram", spec_k=4)
    eng.warmup()
    install_plan(FaultPlan(dispatch_raise_at=6, dispatch_raise_times=3))
    try:
        on_wl, rep = _run_wl(eng)
    finally:
        install_plan(None)
    for a, b in zip(off_wl, on_wl):
        assert a.tokens == b.tokens, (a.rid, a.tokens, b.tokens)
    assert rep["recovery_counters"].get("dispatch_error", 0) > 0
    assert rep["pool_audit_ok"]


def test_engine_verify_shapes_bounded_and_rule_silent(tiny_setup):
    """Warmup compiles one verify program per ladder entry and the
    unbucketed-decode-shape rule stays silent on the full compile log."""
    from deepspeed_tpu.analysis import analyze_compile_log

    cfg, params = tiny_setup
    eng = _engine(cfg, params, spec_drafter="ngram", spec_k=4)
    n = eng.warmup()
    verify_shapes = [tuple(e["shape"]) for e in eng.compile_log
                     if e["kind"] == "serving_verify"]
    assert verify_shapes == [(2, 2), (3, 2), (5, 2)]
    _run_wl(eng)
    assert len(eng.compile_log) == n  # traffic compiled NOTHING new
    assert not analyze_compile_log(eng).findings


def test_spec_window_at_table_capacity(tiny_setup):
    """A request whose prompt+max_new EQUALS max_model_len speculates right
    up to the table edge: out-of-range window scatter positions must DROP,
    never clip onto a committable position (a clipped rejected-draft K/V at
    S-1 would flip the final committed token). Regression for the gather
    fallback's capacity-edge overwrite."""
    cfg, params = tiny_setup
    max_len = 64

    def run(spec):
        eng = _engine(cfg, params,
                      **(dict(spec_drafter="ngram", spec_k=4) if spec
                         else {}))
        # prompt + max_new == max_model_len, page-aligned table
        req = Request(prompt=(np.arange(32, dtype=np.int32) % 7 + 1),
                      max_new_tokens=max_len - 32)
        sched = eng.make_scheduler()
        assert sched.submit(req)
        sched.run_to_completion()
        assert sched.audit()["ok"]
        return req

    off = run(False)
    on = run(True)
    assert len(on.tokens) == len(off.tokens) == 32
    assert on.tokens == off.tokens


def test_verify_phase_rides_decode_deadline(tiny_setup):
    """Arming decode_deadline_s must also arm the verify phase — with a
    drafter configured nearly every dispatch is a verify, and a wedged one
    has to trip the same PR 7 stall ladder a wedged decode does."""
    from deepspeed_tpu.resilience.watchdog import SERVING_PHASES

    assert "serving_verify" in SERVING_PHASES
    cfg, params = tiny_setup
    eng = _engine(cfg, params, spec_drafter="ngram", spec_k=2,
                  decode_deadline_s=5.0)
    sched = eng.make_scheduler()
    try:
        assert sched.watchdog is not None
        assert sched.watchdog.deadlines.get("serving_verify") == 5.0
    finally:
        sched.close()


def test_auto_slots_prices_explicit_draft_pair(monkeypatch, tiny_setup):
    """num_slots='auto' with ServingEngine(draft=(cfg, params)) must charge
    the PASSED draft model's params+cache, not silently skip them because
    no spec_draft_model preset name was set."""
    from deepspeed_tpu.runtime import aot

    cfg, params = tiny_setup
    seen = {}
    real = aot.speculation_hbm_bytes

    def spy(model, **kw):
        out = real(model, **kw)
        seen.update(out)
        return out

    def fake_report(model, *, batch=1, **kw):
        peak = int(0.05 * aot.HBM_BYTES * batch)
        fit = aot.fit_verdict(peak)
        return {"model": model, "batch": batch, "cache_dtype": "bfloat16",
                "per_device_bytes": {"peak": peak}, "fit": fit,
                "fits_v5e_hbm": fit["confidence"] != "oom"}

    monkeypatch.setattr(aot, "decode_program_report", fake_report)
    monkeypatch.setattr(aot, "speculation_hbm_bytes", spy)
    eng = ServingEngine(cfg, params, ServingConfig(
        num_slots="auto", model_name="gpt2-125m", page_size=8,
        max_model_len=64, prefill_chunk=16, dtype="float32",
        spec_drafter="draft_model", spec_k=2), draft=(cfg, params))
    assert eng.num_slots >= 1
    assert seen["parts"]["draft_params"] > 0   # the PAIR's config priced
    assert seen["parts"]["draft_cache"] > 0


def test_engine_rejects_nonzero_temperature(tiny_setup):
    cfg, params = tiny_setup
    with pytest.raises(NotImplementedError):
        ServingEngine(cfg, params, ServingConfig(max_model_len=64,
                                                 sampling_temperature=0.7))


# ------------------------------------------------------------------ dslint
def test_spec_rule_fire_and_silent():
    import types

    from deepspeed_tpu.analysis import analyze_compile_log

    def duck(**kw):
        base = dict(spec_drafter="ngram", sampling_temperature=0.0,
                    spec_acceptance="greedy", spec_equivalence_harness=False,
                    max_queue=8)
        base.update(kw)
        return types.SimpleNamespace(
            serving=types.SimpleNamespace(**base), compile_log=[])

    hot = analyze_compile_log(duck(sampling_temperature=0.8)).findings
    assert any(f.rule_id == "serving/speculation-without-greedy-gate"
               for f in hot)
    hot2 = analyze_compile_log(duck(spec_acceptance="topk")).findings
    assert any(f.rule_id == "serving/speculation-without-greedy-gate"
               for f in hot2)
    # silent: greedy path; harness-flagged non-greedy; no drafter
    assert not [f for f in analyze_compile_log(duck()).findings
                if f.rule_id == "serving/speculation-without-greedy-gate"]
    assert not [f for f in analyze_compile_log(
        duck(sampling_temperature=0.8,
             spec_equivalence_harness=True)).findings
        if f.rule_id == "serving/speculation-without-greedy-gate"]
    assert not [f for f in analyze_compile_log(
        duck(spec_drafter=None, sampling_temperature=0.8)).findings
        if f.rule_id == "serving/speculation-without-greedy-gate"]


# --------------------------------------------------------------- aot + fleet
def test_speculation_hbm_bytes_accounting():
    from deepspeed_tpu.runtime.aot import speculation_hbm_bytes

    ng = speculation_hbm_bytes("gpt2-125m", num_slots=8, spec_k=4,
                               max_model_len=512)
    assert ng["total"] == ng["parts"]["verify_window"] > 0
    dm = speculation_hbm_bytes("gpt2-760m", draft_model="gpt2-125m",
                               num_slots=8, spec_k=4, max_model_len=512)
    assert dm["parts"]["draft_params"] > 0
    assert dm["parts"]["draft_cache"] > 0
    assert dm["total"] > ng["total"]
    # the draft cache scales with slots; params do not
    dm2 = speculation_hbm_bytes("gpt2-760m", draft_model="gpt2-125m",
                                num_slots=16, spec_k=4, max_model_len=512)
    assert dm2["parts"]["draft_cache"] == 2 * dm["parts"]["draft_cache"]
    assert dm2["parts"]["draft_params"] == dm["parts"]["draft_params"]


def test_admission_limit_charges_speculation(monkeypatch):
    """num_slots='auto' with a drafter armed admits no MORE than without:
    the probe's peak is topped up with speculation bytes before the fit
    verdict (decode_program_report faked — no TPU compiler needed)."""
    from deepspeed_tpu.runtime import aot

    hbm = aot.HBM_BYTES

    def fake_report(model, *, batch=1, **kw):
        peak = int(0.04 * hbm * batch)   # fits up to ~24 slots bare
        fit = aot.fit_verdict(peak)
        return {"model": model, "batch": batch, "cache_dtype": "bfloat16",
                "per_device_bytes": {"peak": peak}, "fit": fit,
                "fits_v5e_hbm": fit["confidence"] != "oom"}

    monkeypatch.setattr(aot, "decode_program_report", fake_report)
    bare = aot.serving_admission_limit("gpt2-125m", hi=32)
    spec = aot.serving_admission_limit("gpt2-125m", hi=32,
                                       draft_model="gpt2-125m", spec_k=4,
                                       spec_max_len=2048)
    assert spec["max_slots"] <= bare["max_slots"]
    assert spec["speculation"]["total"] > 0
    # and the fleet plan consumes the same reduced verdict
    plan = aot.fleet_replica_plan("gpt2-125m", target_total_slots=32, hi=32,
                                  draft_model="gpt2-125m", spec_k=4,
                                  spec_max_len=2048)
    assert plan["slots_per_replica"] == spec["max_slots"]


def test_summarize_events_merges_spec_rows():
    from deepspeed_tpu.inference.fleet import summarize_events

    now = 1000.0
    events = [
        {"unix_time": 995.0, "event": "request_routed"},
        {"unix_time": 996.0, "event": "spec_window", "value": 6.0,
         "drafted": 8, "accepted": 5},
        {"unix_time": 997.0, "event": "spec_window", "value": 2.0,
         "drafted": 8, "accepted": 1},
        {"unix_time": 900.0, "event": "spec_window", "value": 9.0,
         "drafted": 8, "accepted": 8},   # outside the window: ignored
    ]
    s = summarize_events(events, now, 10.0)
    assert s["spec_windows"] == 2
    assert s["spec_accept_rate"] == pytest.approx(6 / 16)
    assert s["spec_tokens_per_dispatch"] == pytest.approx(4.0)
    quiet = summarize_events([{"unix_time": 999.0,
                               "event": "request_routed"}], now, 10.0)
    assert "spec_windows" not in quiet
