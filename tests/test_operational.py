"""Operational layer: elasticity math, launcher parsing, autotuner, flops
profiler, ds_report."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.autotuning import Autotuner
from deepspeed_tpu.elasticity import (
    ElasticityError,
    compute_elastic_config,
    get_candidate_batch_sizes,
    get_valid_gpus,
)
from deepspeed_tpu.launcher import filter_hosts, parse_hostfile
from deepspeed_tpu.profiling import get_model_profile, profile_compiled_fn


# ------------------------------------------------------------------- elasticity
def test_candidate_batch_sizes():
    assert get_candidate_batch_sizes([2, 3], 24) == [2, 3, 4, 6, 8, 12, 16, 24]


def test_valid_gpus():
    # batch 24, micro {2,3}: w valid iff 24 % (2w)==0 or 24 % (3w)==0
    assert get_valid_gpus(24, [2, 3], 1, 12) == [1, 2, 3, 4, 6, 8, 12]


def test_compute_elastic_config_maximizes_valid_worlds():
    cfg = {"elasticity": {
        "enabled": True, "max_train_batch_size": 100,
        "micro_batch_sizes": [2, 4, 6], "min_gpus": 1, "max_gpus": 16,
        "version": 0.2}}
    bs, gpus, _ = compute_elastic_config(cfg)
    # all candidates: every valid world count must be maximal for the chosen bs
    from deepspeed_tpu.elasticity import get_candidate_batch_sizes

    best_count = max(
        len(get_valid_gpus(c, [2, 4, 6], 1, 16))
        for c in get_candidate_batch_sizes([2, 4, 6], 100))
    assert len(gpus) == best_count
    assert bs % 2 == 0

    # resolving at a concrete world size yields a dividing micro batch
    bs2, gpus2, micro = compute_elastic_config(cfg, world_size=gpus[0])
    assert bs2 == bs and micro > 0 and bs % (micro * gpus[0]) == 0


def test_elasticity_world_size_mismatch_raises():
    cfg = {"elasticity": {
        "enabled": True, "max_train_batch_size": 16,
        "micro_batch_sizes": [4], "min_gpus": 1, "max_gpus": 4}}
    with pytest.raises(ElasticityError, match="not among"):
        compute_elastic_config(cfg, world_size=3)
    with pytest.raises(ElasticityError, match="disabled"):
        compute_elastic_config({"elasticity": {"enabled": False}})


# ------------------------------------------------------------------- launcher
def test_parse_hostfile_and_filters():
    hosts = parse_hostfile([
        "worker-0 slots=4  # comment",
        "",
        "worker-1 slots=4",
        "worker-2 slots=2",
    ])
    assert hosts == {"worker-0": 4, "worker-1": 4, "worker-2": 2}

    pool = filter_hosts(hosts, include="worker-0:1,3@worker-2")
    assert pool == {"worker-0": [1, 3], "worker-2": [0, 1]}

    pool = filter_hosts(hosts, exclude="worker-1")
    assert sorted(pool) == ["worker-0", "worker-2"]

    pool = filter_hosts(hosts, exclude="worker-0:0,1,2,3")
    assert "worker-0" not in pool

    with pytest.raises(ValueError, match="mutually exclusive"):
        filter_hosts(hosts, include="worker-0", exclude="worker-1")
    with pytest.raises(ValueError, match="unknown hosts"):
        filter_hosts(hosts, include="nope")
    with pytest.raises(ValueError, match="duplicate host"):
        parse_hostfile(["a slots=1", "a slots=2"])


def test_ssh_runner_command_construction():
    from deepspeed_tpu.launcher.runner import SSHRunner, parse_args

    args = parse_args(["--launcher", "ssh", "train.py", "--lr", "1e-4"])
    args.launch_cmd = "python train.py --lr 1e-4"
    pool = {"h0": [0], "h1": [0]}
    cmds = SSHRunner(args, pool).get_cmd({"DS_COORD_PORT": "1234"}, pool)
    assert len(cmds) == 2
    assert cmds[0][0] == "ssh" and cmds[0][-2] == "h0"
    assert "JAX_PROCESS_ID=0" in cmds[0][-1]
    assert "JAX_PROCESS_ID=1" in cmds[1][-1]
    assert "JAX_COORDINATOR_ADDRESS=h0:1234" in cmds[1][-1]
    assert "JAX_NUM_PROCESSES=2" in cmds[1][-1]


# ------------------------------------------------------------------- autotuner
def test_autotuner_picks_best_and_prunes(tmp_path):
    base = {"train_micro_batch_size_per_gpu": 1,
            "autotuning": {"enabled": True, "metric": "throughput"}}
    tuner = Autotuner(base, tuning_space={
        "train_micro_batch_size_per_gpu": [1, 2, 4],
        "zero_optimization.stage": [0, 2]},
        results_dir=str(tmp_path))

    def fake_trial(cfg):
        mb = cfg["train_micro_batch_size_per_gpu"]
        stage = cfg["zero_optimization"]["stage"]
        if mb == 4:
            raise MemoryError("OOM")  # pruned point
        return mb * 10 + (5 if stage == 2 else 0)

    best = tuner.tune(fake_trial)
    assert best is not None
    assert best.config["train_micro_batch_size_per_gpu"] == 2
    assert best.config["zero_optimization"]["stage"] == 2
    results = json.loads((tmp_path / "results.json").read_text())
    assert results["best"] == best.config
    errors = [e for e in results["experiments"] if e["error"]]
    assert len(errors) == 2  # both mb=4 points pruned


@pytest.mark.slow
def test_autotuner_model_knob_dimensions(tmp_path):
    """VERDICT r2 weak #1 / r1 weak #7: remat policy, flash block sizes and
    other MODEL knobs are searchable via 'model.*' dimensions (the 'tuner'
    sub-block), and reach the model factory through default_trial_runner."""
    import numpy as np

    from deepspeed_tpu.autotuning.autotuner import default_trial_runner
    from deepspeed_tpu.models import build_gpt, gpt

    base = {"train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "bf16": {"enabled": False},
            "autotuning": {"enabled": True, "tuner": {
                "model.remat_policy": ["nothing_saveable",
                                       "dots_with_no_batch_dims_saveable"],
            }}}
    tuner = Autotuner(base, tuning_space={
        "train_micro_batch_size_per_gpu": [2],
        "zero_optimization.stage": [1]},
        results_dir=str(tmp_path))
    assert "model.remat_policy" in tuner.space

    seen = []

    def model_factory(**overrides):
        seen.append(dict(overrides))
        import dataclasses

        cfg = gpt.GPTConfig(vocab_size=64, n_layer=2, n_head=2, d_model=32,
                            max_seq_len=32, remat=True)
        return build_gpt(dataclasses.replace(cfg, **overrides))[0]

    def batch_factory(bs):
        return {"input_ids": np.zeros((bs, 16), np.int32)}

    best = tuner.tune(default_trial_runner(model_factory, batch_factory, steps=1))
    assert best is not None
    assert sorted(s["remat_policy"] for s in seen) == [
        "dots_with_no_batch_dims_saveable", "nothing_saveable"]


def test_autotuner_latency_metric(tmp_path):
    tuner = Autotuner({}, tuning_space={
        "train_micro_batch_size_per_gpu": [1, 2],
        "zero_optimization.stage": [0]}, metric="latency",
        results_dir=str(tmp_path))
    best = tuner.tune(lambda cfg: cfg["train_micro_batch_size_per_gpu"])
    assert best.config["train_micro_batch_size_per_gpu"] == 1


# ------------------------------------------------------------------- profiler
def test_profile_compiled_fn_reports_flops():
    import jax.numpy as jnp

    a = jnp.ones((128, 128), jnp.float32)
    prof = profile_compiled_fn(lambda x: x @ x, a)
    # 2*N^3 flops for a square matmul
    assert prof["flops"] >= 2 * 128 ** 3 * 0.9
    assert prof["latency_s"] > 0


def test_get_model_profile():
    from deepspeed_tpu.models import build_gpt
    from deepspeed_tpu.models.gpt import GPTConfig

    model, cfg = build_gpt(GPTConfig(
        vocab_size=64, d_model=32, n_layer=1, n_head=2, max_seq_len=16))
    batch = {"input_ids": np.zeros((2, 16), np.int32)}
    prof = get_model_profile(model, batch)
    assert prof["params"] > 0 and prof["flops"] > 0


@pytest.mark.slow
def test_engine_flops_profiler_config_hook(tmp_path):
    """flops_profiler config block must actually fire at profile_step."""
    from deepspeed_tpu.models import build_gpt
    from deepspeed_tpu.models.gpt import GPTConfig

    out = str(tmp_path / "prof.txt")
    model, cfg = build_gpt(GPTConfig(
        vocab_size=64, d_model=32, n_layer=1, n_head=2, max_seq_len=16))
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, config={
            "train_micro_batch_size_per_gpu": 1,
            "flops_profiler": {"enabled": True, "profile_step": 2,
                               "output_file": out},
            "steps_per_print": 0})
    r = np.random.default_rng(0)
    b = {"input_ids": r.integers(0, 64, (8, 16), dtype=np.int32)}
    engine.train_batch(b)
    assert not os.path.exists(out)
    engine.train_batch(b)  # step 2: profile fires
    assert os.path.exists(out)
    assert "Flops Profiler" in open(out).read()


@pytest.mark.slow
def test_flops_profiler_on_engine():
    from deepspeed_tpu.models import build_gpt
    from deepspeed_tpu.models.gpt import GPTConfig
    from deepspeed_tpu.profiling import FlopsProfiler

    model, cfg = build_gpt(GPTConfig(
        vocab_size=64, d_model=32, n_layer=1, n_head=2, max_seq_len=16))
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, config={"train_micro_batch_size_per_gpu": 1,
                             "steps_per_print": 0})
    prof = FlopsProfiler(engine)
    r = np.random.default_rng(0)
    prof.profile_train_batch({"input_ids": r.integers(0, 64, (8, 16), dtype=np.int32)})
    text = prof.print_model_profile()
    assert "Flops Profiler" in text and "params" in text
    assert prof.get_total_params() > 0


# ------------------------------------------------------------------- ds_report
@pytest.mark.slow
def test_ds_report_runs():
    from deepspeed_tpu.env_report import main, op_report

    ops = dict(op_report())
    assert ops.get("ds_cpu_ops") is True
    assert main() == 0


def test_launcher_elastic_flag_requires_config():
    from deepspeed_tpu.launcher.runner import main as launcher_main

    with pytest.raises(SystemExit, match="elastic_training"):
        launcher_main(["--elastic_training", "train.py"])


def test_per_module_profile_tree():
    """VERDICT r4 'next' #7: per-unit decomposition (embed / layer x L / head
    / optimizer) with exact XLA cost_analysis flops and additive totals."""
    from deepspeed_tpu.models.gpt import GPTConfig
    from deepspeed_tpu.profiling.flops_profiler import (
        format_module_tree, per_module_profile)

    cfg = GPTConfig(vocab_size=128, d_model=32, n_layer=2, n_head=2,
                    max_seq_len=32)
    p = per_module_profile(cfg, 2, 32, n_timing_runs=1)
    u = p["units"]
    assert set(u) == {"embed", "layer", "head", "optimizer"}
    assert u["layer"]["count"] == 2
    total = (u["embed"]["fwd"]["flops"]
             + 2 * (u["layer"]["fwd"]["flops"] + u["layer"]["bwd"]["flops"])
             + u["head"]["fwd_bwd"]["flops"]
             + u["optimizer"]["update"]["flops"])
    assert p["totals"]["flops"] == total
    # optimizer update covers the FULL parameter tree (scaled from one layer)
    assert u["optimizer"]["params"] == p["totals"]["params"]
    text = format_module_tree(p)
    assert "(embed)" in text and "layers x2" in text and "(optimizer)" in text


def test_print_model_profile_includes_module_tree():
    """The engine-attached report must carry the reference-style per-module
    tree (profiler.py:236 parity) when the model exposes its GPTConfig."""
    from deepspeed_tpu.models import build_gpt
    from deepspeed_tpu.models.gpt import GPTConfig
    from deepspeed_tpu.profiling import FlopsProfiler

    model, cfg = build_gpt(GPTConfig(
        vocab_size=64, d_model=32, n_layer=1, n_head=2, max_seq_len=16))
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, config={"train_micro_batch_size_per_gpu": 1,
                             "steps_per_print": 0})
    prof = FlopsProfiler(engine)
    r = np.random.default_rng(0)
    prof.profile_train_batch(
        {"input_ids": r.integers(0, 64, (8, 16), dtype=np.int32)})
    text = prof.print_model_profile()
    assert "layers x1" in text and "(head)" in text
    # the module profile picked up the profiled batch geometry
    assert prof.profile["modules"]["seq"] == 16


def test_elastic_config_fingerprint_immutability(monkeypatch):
    """Parity: ensure_immutable_elastic_config (elasticity.py:254) — the
    runtime refuses a config whose convergence-relevant knobs drifted from
    what the scheduler scaled the job by."""
    import json as _json

    from deepspeed_tpu.elasticity import (
        ELASTICITY_CONFIG_ENV, ElasticityError, elasticity_enabled,
        ensure_immutable_elastic_config)
    from deepspeed_tpu.elasticity import compute_elastic_config as cec

    cfg = {"elasticity": {"enabled": True, "max_train_batch_size": 128,
                          "micro_batch_sizes": [2, 4],
                          "min_gpus": 1, "max_gpus": 8}}
    monkeypatch.delenv(ELASTICITY_CONFIG_ENV, raising=False)
    monkeypatch.delenv("DEEPSPEED_ELASTICITY_CONFIG", raising=False)
    warned = []
    assert not ensure_immutable_elastic_config(cfg["elasticity"],
                                               warn=warned.append)
    assert warned  # no scheduler config: warn, don't refuse
    cec(cfg)  # planning proceeds

    monkeypatch.setenv(ELASTICITY_CONFIG_ENV, _json.dumps(cfg))
    assert ensure_immutable_elastic_config(cfg["elasticity"])
    cec(cfg)

    drifted = {"elasticity": dict(cfg["elasticity"],
                                  max_train_batch_size=256)}
    monkeypatch.setenv(ELASTICITY_CONFIG_ENV, _json.dumps(drifted))
    with pytest.raises(ElasticityError, match="max_train_batch_size"):
        cec(cfg)
    # micro-batch drift refused too; ORDER of micro batches is not drift
    reordered = {"elasticity": dict(cfg["elasticity"],
                                    micro_batch_sizes=[4, 2])}
    monkeypatch.setenv(ELASTICITY_CONFIG_ENV, _json.dumps(reordered))
    assert ensure_immutable_elastic_config(cfg["elasticity"])
    monkeypatch.setenv(ELASTICITY_CONFIG_ENV, _json.dumps(
        {"elasticity": dict(cfg["elasticity"], micro_batch_sizes=[2, 8])}))
    with pytest.raises(ElasticityError, match="micro_batch_sizes"):
        ensure_immutable_elastic_config(cfg["elasticity"])

    # the reference's env spelling is honored for imported launch scripts
    monkeypatch.delenv(ELASTICITY_CONFIG_ENV)
    monkeypatch.setenv("DEEPSPEED_ELASTICITY_CONFIG", _json.dumps(drifted))
    with pytest.raises(ElasticityError):
        ensure_immutable_elastic_config(cfg["elasticity"])

    monkeypatch.setenv("DEEPSPEED_ELASTICITY_CONFIG", "not json{")
    with pytest.raises(ElasticityError, match="valid JSON"):
        ensure_immutable_elastic_config(cfg["elasticity"])
    assert elasticity_enabled(cfg) and not elasticity_enabled({})


def test_elastic_agent_exports_fingerprint_env(monkeypatch):
    """The agent (acting as the scheduler) must hand its workers the
    fingerprint env so their runtimes can verify immutability."""
    import json as _json

    from deepspeed_tpu.elasticity import ELASTICITY_CONFIG_ENV
    from deepspeed_tpu.elasticity.elastic_agent import DSElasticAgent

    cfg = {"elasticity": {"enabled": True, "max_train_batch_size": 64,
                          "micro_batch_sizes": [2], "min_gpus": 1,
                          "max_gpus": 4}}
    monkeypatch.delenv(ELASTICITY_CONFIG_ENV, raising=False)
    captured = {}

    class FakeProc:
        def poll(self):
            return 0

        def wait(self, timeout=None):
            return 0

    def fake_popen(argv, env=None, **kw):
        captured["env"] = env
        return FakeProc()

    monkeypatch.setattr("subprocess.Popen", fake_popen)
    agent = DSElasticAgent(lambda spec: ["true"], cfg,
                         device_count_fn=lambda: 2, poll_interval=0.01)
    res = agent.run()
    assert res.state == "SUCCEEDED"
    fp = _json.loads(captured["env"][ELASTICITY_CONFIG_ENV])
    assert fp["elasticity"]["max_train_batch_size"] == 64


def test_queued_resources_runner_commands():
    """Provision/describe/launch command construction + ACTIVE polling
    (fills the reference's cluster-scheduler runner role,
    multinode_runner.py:164,211)."""
    import argparse

    from deepspeed_tpu.launcher.runner import QueuedResourcesRunner

    args = argparse.Namespace(
        tpu_name="slice1", accelerator_type="v5litepod-16",
        runtime_version="tpu-ubuntu2204-base", zone="us-west4-a",
        project="proj", spot=True, launch_cmd="python t.py")
    r = QueuedResourcesRunner(args, {"worker-0": [0], "worker-1": [0]})
    cmd = r.provision_cmd()
    assert cmd[:6] == ["gcloud", "compute", "tpus", "queued-resources",
                       "create", "slice1"]
    assert "--accelerator-type" in cmd and "--spot" in cmd
    assert "us-west4-a" in cmd and "proj" in cmd
    assert "describe" in r.describe_cmd()

    states = iter(["WAITING_FOR_RESOURCES", "PROVISIONING", "ACTIVE"])

    class P:
        def __init__(self, s):
            self.stdout = s

    assert r.wait_active(poll_s=0, run=lambda *a, **k: P(next(states))) == \
        "ACTIVE"
    with pytest.raises(RuntimeError, match="FAILED"):
        r.wait_active(poll_s=0, run=lambda *a, **k: P("FAILED"))

    class Err:
        returncode = 1
        stdout = ""
        stderr = "ERROR: auth expired"

    with pytest.raises(RuntimeError, match="auth expired"):
        r.wait_active(poll_s=0, max_describe_failures=3,
                      run=lambda *a, **k: Err())
    # launch path is the gcloud worker fan-out against the provisioned node,
    # scoped to the SAME zone/project as provisioning
    launch = r.get_cmd({"DS_COORD_PORT": "8476"}, r.resource_pool)
    assert launch[0][:6] == ["gcloud", "compute", "tpus", "tpu-vm", "ssh",
                             "slice1"]
    assert "us-west4-a" in launch[0] and "proj" in launch[0]


def test_gke_runner_manifest(tmp_path):
    """Indexed-Job manifest: completion index = JAX process id, pod-0 DNS =
    coordinator, per-host TPU resource limit, headless service."""
    import argparse

    from deepspeed_tpu.launcher.runner import GKERunner

    args = argparse.Namespace(
        tpu_name="dsjob", gke_image="gcr.io/x/img:1", gke_namespace="ml",
        gke_tpu_accelerator="tpu-v5-lite-podslice", gke_topology="2x4",
        gke_chips_per_host=4, launch_cmd="python train.py --deepspeed")
    r = GKERunner(args, {f"worker-{i}": [0] for i in range(4)})
    m = r.render_manifest({"DS_COORD_PORT": "8476", "PYTHONPATH": "/app"})
    assert "completions: 4" in m and "parallelism: 4" in m
    assert "completionMode: Indexed" in m
    assert "JAX_PROCESS_ID=$JOB_COMPLETION_INDEX" in m
    assert "JAX_COORDINATOR_ADDRESS=dsjob-0.dsjob:8476" in m
    assert "google.com/tpu: 4" in m
    assert "clusterIP: None" in m and "namespace: ml" in m
    # host paths must NOT leak into the container (the image has its own)
    assert "PYTHONPATH" not in m and "export DS_COORD_PORT=8476" in m
    assert "python train.py --deepspeed" in m
    # the manifest must actually PARSE (substring asserts missed a
    # block-scalar indentation bug once)
    import yaml as _yaml

    docs = list(_yaml.safe_load_all(m))
    assert [d["kind"] for d in docs] == ["Service", "Job"]
    job = docs[1]["spec"]
    assert job["completions"] == 4 and job["completionMode"] == "Indexed"
    ctr = job["template"]["spec"]["containers"][0]
    assert ctr["resources"]["limits"]["google.com/tpu"] == 4
    assert "python train.py --deepspeed" in ctr["args"][0]
    cmd = r.get_cmd({"DS_COORD_PORT": "8476", "PYTHONPATH": "/app"},
                    r.resource_pool)
    assert cmd[0][:3] == ["kubectl", "apply", "-f"]
    assert open(cmd[0][3]).read() == m
    import os as _os

    _os.unlink(cmd[0][3])


def test_launcher_refuses_silent_local_run_for_managed_slices(tmp_path):
    """gke/queued-resources with no resolved workers must refuse, not
    silently run the script on the operator's machine."""
    from deepspeed_tpu.launcher.runner import main as launcher_main

    with pytest.raises(SystemExit, match="needs a hostfile or"):
        launcher_main(["--launcher", "gke", "--hostfile",
                       str(tmp_path / "missing"), "train.py"])


def test_elastic_agent_accepts_object_config(monkeypatch):
    """The agent must handle the pydantic-shaped config (an object with
    .elasticity), not just dicts, through the fingerprint export."""
    from deepspeed_tpu.elasticity import ELASTICITY_CONFIG_ENV
    from deepspeed_tpu.elasticity.elastic_agent import DSElasticAgent

    class Cfg:
        elasticity = {"enabled": True, "max_train_batch_size": 64,
                      "micro_batch_sizes": [2], "min_gpus": 1, "max_gpus": 4}

        def get(self, *a):  # pydantic models have no dict .get
            raise AssertionError("dict path used on object config")

    monkeypatch.delenv(ELASTICITY_CONFIG_ENV, raising=False)
    agent = DSElasticAgent(lambda spec: ["true"], Cfg(),
                           device_count_fn=lambda: 2, poll_interval=0.01)
    assert agent._elastic_block["max_train_batch_size"] == 64
    spec = agent.resolve(2)
    assert spec.world_size == 2


@pytest.mark.slow
def test_profile_modules_none_without_gpt_config():
    """A model without a GPTConfig (e.g. MoE) yields no module tree; the
    report must still print instead of raising."""
    from deepspeed_tpu.models import build_gpt_moe
    from deepspeed_tpu.profiling import FlopsProfiler

    model, _ = build_gpt_moe("tiny-moe")
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, config={"train_micro_batch_size_per_gpu": 1,
                             "steps_per_print": 0})
    prof = FlopsProfiler(engine)
    r = np.random.default_rng(0)
    prof.profile_train_batch(
        {"input_ids": r.integers(0, 256, (8, 32), dtype=np.int32)})
    assert prof.profile_modules() is None
    text = prof.print_model_profile()
    assert "Flops Profiler" in text and "layers x" not in text
