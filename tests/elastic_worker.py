"""Elastic-agent worker fixture: trains a tiny GPT on a forced-CPU mesh of
``--elastic-world`` devices, checkpointing every step, resuming from the latest
checkpoint on start. Used by test_elastic_agent.py (kill-and-resume),
test_reshard.py and scripts/elastic_smoke.py (chaos-tested device-loss
recovery, docs/RESILIENCE.md "Elastic membership").

Elastic-resume extensions (all optional; defaults keep the original
behavior):

- ``--resilience``: arm the ``resilience`` block (commit-protocol saves,
  auto-resume from the newest committed tag, recovery-event log — the
  ``reshard_applied`` event lands in ``<ckpt>/recovery_events.jsonl``).
- ``--cursor-data``: drive batches from ``engine.data_cursor`` (the
  checkpointable-cursor contract the reshard path keeps sample-exact).
- ``--qgrad``: arm the quantized gradient exchange with error feedback —
  the run carries the world-size-coupled ``qgrad_residual`` state the
  reshard-on-load path must reset by policy.
- ``--lose-at N``: install a ``lose_worker_at_step`` fault plan (SIGKILL at
  data cursor N — a dp worker dying with its lost device).
- ``--pid-file``: write our pid at start (the smoke's device probe treats
  this process's existence as one device's health).
- ``--out-state``: npz dump of the final engine state for bitwise compares.
- ``--elastic-config JSON``: include this ``elasticity`` block in the ds
  config — exercises the runtime-side validation + the scheduler
  fingerprint check against ``DS_TPU_ELASTICITY_CONFIG``.
"""

import argparse
import json
import os
import sys


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--ckpt-dir", required=True)
    p.add_argument("--log", required=True)
    p.add_argument("--steps", type=int, required=True)
    p.add_argument("--crash-at", type=int, default=-1)
    p.add_argument("--on-crash-write", default=None,
                   help="'path:text' written just before the simulated crash "
                        "(models the membership change that caused it)")
    p.add_argument("--elastic-world", type=int, required=True)
    p.add_argument("--elastic-micro", type=int, required=True)
    p.add_argument("--elastic-gas", type=int, required=True)
    p.add_argument("--resilience", action="store_true")
    p.add_argument("--cursor-data", action="store_true")
    p.add_argument("--qgrad", action="store_true")
    p.add_argument("--lose-at", type=int, default=-1)
    p.add_argument("--pid-file", default=None)
    p.add_argument("--out-state", default=None)
    p.add_argument("--elastic-config", default=None)
    args = p.parse_args()

    if args.pid_file:
        with open(args.pid_file, "w") as f:
            f.write(str(os.getpid()))

    # strip any inherited device-count flag so ours wins (XLA_FLAGS is read at
    # backend init, which has not happened yet even though sitecustomize
    # imported jax)
    flags = " ".join(
        f for f in os.environ.get("XLA_FLAGS", "").split()
        if not f.startswith("--xla_force_host_platform_device_count"))
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={args.elastic_world}")
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["DS_TPU_ACCELERATOR"] = "cpu"
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import deepspeed_tpu as ds
    from deepspeed_tpu.models import build_gpt, gpt
    from deepspeed_tpu.runtime.topology import MeshTopology

    world, micro, gas = args.elastic_world, args.elastic_micro, args.elastic_gas
    model, cfg = build_gpt(gpt.GPTConfig(
        vocab_size=64, n_layer=2, n_head=2, d_model=32, max_seq_len=32))
    topo = MeshTopology.create(dp=world, devices=jax.devices()[:world])
    config = {
        "train_micro_batch_size_per_gpu": micro,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 1},
        "mesh": {"dp": world},
        "bf16": {"enabled": False},
        "steps_per_print": 0,
    }
    if args.qgrad:
        config["zero_optimization"].update({
            "zero_quantized_gradients": True,
            "zero_quantize_error_feedback": True,
        })
    if args.elastic_config:
        config["elasticity"] = json.loads(args.elastic_config)
    if args.resilience:
        res = {"enabled": True, "save_dir": args.ckpt_dir}
        if args.lose_at >= 0:
            res["chaos"] = {"lose_worker_at_step": args.lose_at}
        config["resilience"] = res
    engine, _, _, _ = ds.initialize(model=model, topology=topo, config=config)
    if not args.resilience:
        engine.load_checkpoint(args.ckpt_dir)  # no-op on the first launch
    # resilience mode auto-resumed from the newest COMMITTED tag at init

    effective = micro * gas * world

    def batch_for(step: int):
        # deterministic per-step data, independent of the decomposition: the
        # same `effective`-sized batch regardless of world/micro/gas. A small
        # repeating set (2 distinct batches) so the loss measurably descends
        # and a resumed run is distinguishable from a cold restart.
        r = np.random.default_rng(1000 + step % 2)
        ids = r.integers(0, 64, size=(effective, 16), dtype=np.int32)
        if gas > 1:
            ids = ids.reshape(gas, micro * world, 16)
        return {"input_ids": ids}

    while engine.global_steps < args.steps:
        index = engine.data_cursor if args.cursor_data else engine.global_steps
        m = engine.train_batch(batch_for(index))
        with open(args.log, "a") as f:
            f.write(json.dumps({
                "step": engine.global_steps, "loss": float(m["loss"]),
                "cursor": engine.data_cursor, "index": index,
                "world": world, "micro": micro, "gas": gas,
                "effective": effective}) + "\n")
        engine.save_checkpoint(args.ckpt_dir)
        if args.crash_at >= 0 and engine.global_steps >= args.crash_at:
            if args.on_crash_write:
                path, text = args.on_crash_write.rsplit(":", 1)
                with open(path, "w") as f:
                    f.write(text)
            os._exit(17)  # simulated worker failure

    if args.out_state:
        from deepspeed_tpu.checkpoint.serialization import (
            _UINT_FOR_SIZE,
            _fetch_full,
            _flatten_with_paths,
        )

        out = {}
        for key, leaf in _flatten_with_paths(engine.state)[0]:
            arr = _fetch_full(leaf)
            if arr.dtype.kind not in "biufc":
                arr = arr.view(_UINT_FOR_SIZE[arr.dtype.itemsize])
            out[key] = arr
        np.savez(args.out_state, **out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
