"""Elastic-agent worker fixture: trains a tiny GPT on a forced-CPU mesh of
``--elastic-world`` devices, checkpointing every step, resuming from the latest
checkpoint on start. Used by test_elastic_agent.py (kill-and-resume)."""

import argparse
import json
import os
import sys


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--ckpt-dir", required=True)
    p.add_argument("--log", required=True)
    p.add_argument("--steps", type=int, required=True)
    p.add_argument("--crash-at", type=int, default=-1)
    p.add_argument("--on-crash-write", default=None,
                   help="'path:text' written just before the simulated crash "
                        "(models the membership change that caused it)")
    p.add_argument("--elastic-world", type=int, required=True)
    p.add_argument("--elastic-micro", type=int, required=True)
    p.add_argument("--elastic-gas", type=int, required=True)
    args = p.parse_args()

    # strip any inherited device-count flag so ours wins (XLA_FLAGS is read at
    # backend init, which has not happened yet even though sitecustomize
    # imported jax)
    flags = " ".join(
        f for f in os.environ.get("XLA_FLAGS", "").split()
        if not f.startswith("--xla_force_host_platform_device_count"))
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={args.elastic_world}")
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["DS_TPU_ACCELERATOR"] = "cpu"
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import deepspeed_tpu as ds
    from deepspeed_tpu.models import build_gpt, gpt
    from deepspeed_tpu.runtime.topology import MeshTopology

    world, micro, gas = args.elastic_world, args.elastic_micro, args.elastic_gas
    model, cfg = build_gpt(gpt.GPTConfig(
        vocab_size=64, n_layer=2, n_head=2, d_model=32, max_seq_len=32))
    topo = MeshTopology.create(dp=world, devices=jax.devices()[:world])
    engine, _, _, _ = ds.initialize(model=model, topology=topo, config={
        "train_micro_batch_size_per_gpu": micro,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 1},
        "mesh": {"dp": world},
        "bf16": {"enabled": False},
        "steps_per_print": 0,
    })
    engine.load_checkpoint(args.ckpt_dir)  # no-op on the first launch

    effective = micro * gas * world

    def batch_for(step: int):
        # deterministic per-step data, independent of the decomposition: the
        # same `effective`-sized batch regardless of world/micro/gas. A small
        # repeating set (2 distinct batches) so the loss measurably descends
        # and a resumed run is distinguishable from a cold restart.
        r = np.random.default_rng(1000 + step % 2)
        ids = r.integers(0, 64, size=(effective, 16), dtype=np.int32)
        if gas > 1:
            ids = ids.reshape(gas, micro * world, 16)
        return {"input_ids": ids}

    while engine.global_steps < args.steps:
        step = engine.global_steps
        m = engine.train_batch(batch_for(step))
        with open(args.log, "a") as f:
            f.write(json.dumps({
                "step": engine.global_steps, "loss": float(m["loss"]),
                "world": world, "micro": micro, "gas": gas,
                "effective": effective}) + "\n")
        engine.save_checkpoint(args.ckpt_dir)
        if args.crash_at >= 0 and engine.global_steps >= args.crash_at:
            if args.on_crash_write:
                path, text = args.on_crash_write.rsplit(":", 1)
                with open(path, "w") as f:
                    f.write(text)
            os._exit(17)  # simulated worker failure
    return 0


if __name__ == "__main__":
    sys.exit(main())
