"""Preemption-safe training: commit protocol, fault injection, auto-resume.

The acceptance bar (ISSUE 4): a SIGKILL injected at every checkpoint-write
phase never yields a load of partial state — resume restores either the
previous committed tag or the new one, and a killed-and-resumed run ends
bitwise-identical to an uninterrupted one.
"""

import json
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import GPTConfig, build_gpt
from deepspeed_tpu.resilience import (
    CheckpointCorruptionError,
    FaultPlan,
    PREEMPTED_EXIT_CODE,
    RetryBudgetExceeded,
    RetryingWriter,
    UncommittedTagError,
    commit_tag,
    committed_tags,
    crc32c,
    install_plan,
    is_committed,
    quarantine_tag,
    read_events,
    read_latest,
    resolve_tag_for_load,
    verify_tag,
    write_latest,
)

WORKER = os.path.join(os.path.dirname(__file__), "resilience_worker.py")
TINY = GPTConfig(vocab_size=256, n_layer=2, n_head=4, d_model=64, max_seq_len=64)


@pytest.fixture(autouse=True)
def _clear_fault_plan():
    yield
    install_plan(None)


def make_engine(save_dir=None, handlers=False, extra=None):
    model, _ = build_gpt(TINY)
    cfg = {
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "steps_per_print": 0,
    }
    if save_dir is not None:
        cfg["resilience"] = {"enabled": True, "save_dir": str(save_dir),
                             "install_signal_handlers": handlers}
    cfg.update(extra or {})
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=cfg)
    return engine


def batch(seed=0, n=16):
    rng = np.random.default_rng(seed)
    return {"input_ids": rng.integers(0, 256, size=(n, 32), dtype=np.int32)}


def _corrupt(path: str) -> None:
    with open(path, "r+b") as f:
        f.seek(os.path.getsize(path) // 2)
        chunk = f.read(8) or b"\0"
        f.seek(-len(chunk), os.SEEK_CUR)
        f.write(bytes(b ^ 0xFF for b in chunk))


# ------------------------------------------------------------------- primitives
def test_crc32c_known_vectors():
    # RFC 3720 / Castagnoli test vectors — guards the pure-Python fallback
    # (and any C implementation the image provides) against each other
    assert crc32c(b"") == 0
    assert crc32c(b"123456789") == 0xE3069283
    assert crc32c(b"\x00" * 32) == 0x8A9136AA
    assert crc32c(b"\xff" * 32) == 0x62A8AB43
    # incremental == one-shot
    assert crc32c(b"6789", crc32c(b"12345")) == crc32c(b"123456789")


def test_retrying_writer_absorbs_transient_errors(tmp_path):
    install_plan(FaultPlan(fail_io_times=2))
    w = RetryingWriter(attempts=4, sleep=lambda d: None)
    w.write_bytes(str(tmp_path / "x.bin"), b"payload")
    assert (tmp_path / "x.bin").read_bytes() == b"payload"
    assert w.retries_performed >= 2


def test_retrying_writer_bounded():
    install_plan(FaultPlan(fail_io_times=99))
    w = RetryingWriter(attempts=3, sleep=lambda d: None)
    with pytest.raises(RetryBudgetExceeded, match="after 3 attempts"):
        w.write_bytes("/tmp/never_written.bin", b"x")


def test_fault_plan_io_stall(tmp_path):
    import time

    install_plan(FaultPlan(stall_io_seconds=0.2, stall_io_times=1))
    t0 = time.monotonic()
    RetryingWriter().write_bytes(str(tmp_path / "s.bin"), b"x")
    assert time.monotonic() - t0 >= 0.2


def test_recovery_log_rotates_by_size(tmp_path):
    """The JSONL recovery sink must not grow without bound: past max_bytes
    it shifts to .1/.2/... (keep last N), read_events merges generations
    oldest-first, and a torn tail in any generation is tolerated."""
    from deepspeed_tpu.resilience import RecoveryLog

    path = str(tmp_path / "recovery_events.jsonl")
    log = RecoveryLog(path, role="engine", max_bytes=2048, keep=2)
    for i in range(200):  # each entry ~100 bytes -> several rotations
        log.record("tick", value=i, seq=i)
    assert os.path.exists(path + ".1") and os.path.exists(path + ".2")
    assert not os.path.exists(path + ".3")  # keep=2 drops older generations
    assert os.path.getsize(path) < 2048 + 256  # post-rotation file is fresh
    events = read_events(str(tmp_path), keep=2)
    seqs = [e["seq"] for e in events if e["event"] == "tick"]
    assert seqs == sorted(seqs) and seqs[-1] == 199  # oldest-first, no loss
    assert len(seqs) < 200  # the oldest generation really dropped
    # a Serving-prefixed log routes scalars to Serving/* on the monitor
    seen = []

    class Mon:
        def write_events(self, evs):
            seen.extend(evs)

    RecoveryLog(monitor=Mon(), role="serving",
                prefix="Serving").record("request_shed")
    assert seen and seen[0][0] == "Serving/request_shed"


def _mk_tag(save_dir, name="global_step1", payload=b"A" * 100):
    tag_dir = os.path.join(str(save_dir), name)
    os.makedirs(os.path.join(tag_dir, "state", "arrays"), exist_ok=True)
    with open(os.path.join(tag_dir, "state", "arrays", "0.npy"), "wb") as f:
        f.write(payload)
    with open(os.path.join(tag_dir, "meta.json"), "w") as f:
        f.write("{}")
    return tag_dir


def test_manifest_commit_verify_quarantine(tmp_path):
    tag_dir = _mk_tag(tmp_path)
    # uncommitted: must be rejected even though every content file is fine
    with pytest.raises(UncommittedTagError, match="no COMMIT marker"):
        verify_tag(tag_dir)
    assert not is_committed(tag_dir)
    manifest = commit_tag(tag_dir)
    assert set(manifest["files"]) == {"meta.json", "state/arrays/0.npy"}
    assert is_committed(tag_dir)
    verify_tag(tag_dir)
    # corrupt one shard: precise rejection naming file + reason
    _corrupt(os.path.join(tag_dir, "state", "arrays", "0.npy"))
    with pytest.raises(CheckpointCorruptionError,
                       match=r"state/arrays/0\.npy.*corrupted shard"):
        verify_tag(tag_dir)
    # shallow check still passes (size unchanged) — deep=True is what catches it
    verify_tag(tag_dir, deep=False)
    # truncated manifest: rejected against the COMMIT record
    tag2 = _mk_tag(tmp_path, "global_step2")
    commit_tag(tag2)
    with open(os.path.join(tag2, "MANIFEST.json"), "r+b") as f:
        f.truncate(os.path.getsize(os.path.join(tag2, "MANIFEST.json")) // 2)
    with pytest.raises(CheckpointCorruptionError, match="truncated or rewritten"):
        verify_tag(tag2)
    # quarantine revokes load eligibility but keeps the data
    tag3 = _mk_tag(tmp_path, "global_step3")
    commit_tag(tag3)
    write_latest(str(tmp_path), "global_step3")
    quarantine_tag(str(tmp_path), "global_step3", "crash loop")
    assert not is_committed(tag3)
    assert os.path.exists(os.path.join(tag3, "state", "arrays", "0.npy"))
    with pytest.raises(UncommittedTagError, match="quarantined"):
        verify_tag(tag3)


def test_invalidate_before_rewrite(tmp_path):
    """Re-saving an existing tag must first revoke its COMMIT: a kill during
    the rewrite would otherwise leave a stale marker blessing mixed shards."""
    from deepspeed_tpu.resilience import invalidate_tag

    tag_dir = _mk_tag(tmp_path)
    commit_tag(tag_dir)
    assert is_committed(tag_dir)
    invalidate_tag(tag_dir)
    assert not is_committed(tag_dir)
    with pytest.raises(UncommittedTagError):
        verify_tag(tag_dir)
    commit_tag(tag_dir)  # rewrite completes: commit restores loadability
    verify_tag(tag_dir)


def test_checksum_algo_recorded_not_assumed(tmp_path, monkeypatch):
    """The manifest records its checksum algorithm; readers dispatch on the
    record, not on their own environment — write with crc32c, verify under a
    host forced to crc32."""
    tag_dir = _mk_tag(tmp_path, "global_step9")
    monkeypatch.setenv("DS_CHECKPOINT_CHECKSUM", "crc32c")
    commit_tag(tag_dir)
    manifest = json.load(open(os.path.join(tag_dir, "MANIFEST.json")))
    assert manifest["checksum"] == "crc32c"
    monkeypatch.setenv("DS_CHECKPOINT_CHECKSUM", "crc32")
    verify_tag(tag_dir)  # still verifies: algo comes from the COMMIT record
    monkeypatch.setenv("DS_CHECKPOINT_CHECKSUM", "md5")
    with pytest.raises(ValueError, match="DS_CHECKPOINT_CHECKSUM"):
        commit_tag(_mk_tag(tmp_path, "global_step10"))


def test_resolve_falls_back_to_newest_committed(tmp_path):
    for i in (1, 2, 3):
        commit_tag(_mk_tag(tmp_path, f"global_step{i}", payload=bytes([i]) * 50))
    write_latest(str(tmp_path), "global_step3")
    # bit rot in the latest tag: resolution falls back to step2 and reports why
    _corrupt(os.path.join(str(tmp_path), "global_step3", "state", "arrays", "0.npy"))
    tag, rejected = resolve_tag_for_load(str(tmp_path))
    assert tag == "global_step2"
    assert [t for t, _ in rejected] == ["global_step3"]
    # explicit tag: no fallback, the corruption raises
    with pytest.raises(CheckpointCorruptionError):
        resolve_tag_for_load(str(tmp_path), tag="global_step3")
    # empty dir: (None, []) — "nothing to load" is not an error
    assert resolve_tag_for_load(str(tmp_path / "empty")) == (None, [])
    # all tags bad: a precise aggregate error
    for i in (1, 2):
        _corrupt(os.path.join(str(tmp_path), f"global_step{i}",
                              "state", "arrays", "0.npy"))
    with pytest.raises(CheckpointCorruptionError, match="no loadable checkpoint"):
        resolve_tag_for_load(str(tmp_path))


# ------------------------------------------------------------- engine protocol
def test_engine_save_writes_commit_protocol(tmp_path, devices):
    e = make_engine()
    e.train_batch(batch(0))
    ckpt = e.save_checkpoint(str(tmp_path))
    assert os.path.exists(os.path.join(ckpt, "MANIFEST.json"))
    assert os.path.exists(os.path.join(ckpt, "COMMIT"))
    manifest = verify_tag(ckpt)  # full CRC pass over what the engine wrote
    assert any(f.startswith("state/arrays/") for f in manifest["files"])
    assert read_latest(str(tmp_path)) == "global_step1"
    meta = json.load(open(os.path.join(ckpt, "meta.json")))
    assert len(meta["rng_key"]) == 2  # host PRNG chain for step-exact resume
    assert meta["emergency"] is False
    # overwrite of the same tag (e.g. drain at the step of a periodic save):
    # COMMIT is revoked up front and restored by the new commit
    ckpt2 = e.save_checkpoint(str(tmp_path), tag="global_step1")
    verify_tag(ckpt2)

    # corrupt the only tag: auto-load must reject it with a precise error,
    # not half-load; an older committed tag would be the fallback
    shard = os.path.join(ckpt, "state", "arrays", "0.npy")
    _corrupt(shard)
    e2 = make_engine()
    with pytest.raises(CheckpointCorruptionError, match="no loadable checkpoint"):
        e2.load_checkpoint(str(tmp_path))


@pytest.mark.slow
def test_engine_load_falls_back_to_previous_committed_tag(tmp_path, devices):
    e = make_engine()
    e.train_batch(batch(0))
    e.save_checkpoint(str(tmp_path))
    e.train_batch(batch(1))
    e.save_checkpoint(str(tmp_path))
    assert committed_tags(str(tmp_path)) == ["global_step1", "global_step2"]
    # bit rot in the newest tag
    _corrupt(os.path.join(str(tmp_path), "global_step2", "state", "arrays", "1.npy"))
    e2 = make_engine()
    path, _ = e2.load_checkpoint(str(tmp_path))
    assert path.endswith("global_step1")
    assert e2.global_steps == 1
    # explicit request for the corrupt tag still raises
    e3 = make_engine()
    with pytest.raises(CheckpointCorruptionError, match="corrupted shard"):
        e3.load_checkpoint(str(tmp_path), tag="global_step2")


def test_format_version_rejected_explicitly(tmp_path, devices):
    import msgpack

    e = make_engine()
    e.train_batch(batch(0))
    ckpt = e.save_checkpoint(str(tmp_path))
    state_msgpack = os.path.join(ckpt, "state", "state.msgpack")
    with open(state_msgpack, "rb") as f:
        meta = msgpack.unpackb(f.read())
    meta["format_version"] = 99
    with open(state_msgpack, "wb") as f:
        f.write(msgpack.packb(meta))
    from deepspeed_tpu.checkpoint.serialization import load_pytree

    with pytest.raises(ValueError, match="format_version 99"):
        load_pytree(e.state, os.path.join(ckpt, "state"))


# ------------------------------------------------------------------ preemption
@pytest.mark.slow
def test_drain_emergency_save_and_auto_resume(tmp_path, devices):
    e = make_engine(save_dir=tmp_path)
    e.train_batch(batch(0))
    e.request_drain("test-preemption")
    with pytest.raises(SystemExit) as exc:
        e.train_batch(batch(1))
    assert exc.value.code == PREEMPTED_EXIT_CODE
    tags = committed_tags(str(tmp_path))
    assert tags == ["global_step2"]  # the drained step was saved, committed
    meta = json.load(open(tmp_path / "global_step2" / "meta.json"))
    assert meta["emergency"] is True

    # a fresh engine with the same resilience block auto-resumes at init
    e2 = make_engine(save_dir=tmp_path)
    assert e2.global_steps == 2
    assert e2._preemptions_survived == 1
    events = {ev["event"] for ev in read_events(str(tmp_path))}
    assert {"emergency_save", "preemption_survived",
            "resume_latency_s"} <= events
    # training continues normally from the drained state
    m = e2.train_batch(batch(2))
    assert np.isfinite(float(m["loss"]))


def test_sigterm_sets_drain_flag_in_process(tmp_path, devices):
    e = make_engine(save_dir=tmp_path, handlers=True)
    guard = e._preemption_guard
    try:
        assert guard.installed
        e.train_batch(batch(0))
        os.kill(os.getpid(), signal.SIGTERM)  # delivered to our handler
        assert guard.drain_requested and guard.signal_name == "SIGTERM"
        with pytest.raises(SystemExit) as exc:
            e.train_batch(batch(1))
        assert exc.value.code == PREEMPTED_EXIT_CODE
    finally:
        guard.uninstall()
    assert committed_tags(str(tmp_path)) == ["global_step2"]


# ------------------------------------------------------------- kill-and-resume
def _run_worker(ckpt, steps, out_state=None, fault=None, log=None,
                timeout=240):
    cmd = [sys.executable, WORKER, "--ckpt-dir", str(ckpt),
           "--steps", str(steps)]
    if out_state:
        cmd += ["--out-state", str(out_state)]
    if log:
        cmd += ["--log", str(log)]
    env = dict(os.environ)
    env.pop("DS_FAULT_PLAN", None)
    if fault:
        env["DS_FAULT_PLAN"] = json.dumps(fault)
    return subprocess.run(cmd, env=env, capture_output=True, text=True,
                          timeout=timeout)


def _assert_bitwise_equal(npz_a, npz_b):
    with np.load(npz_a) as a, np.load(npz_b) as b:
        assert sorted(a.files) == sorted(b.files)
        for k in a.files:
            np.testing.assert_array_equal(a[k], b[k], err_msg=k)


@pytest.mark.slow
@pytest.mark.parametrize("phase", ["shard:1", "pre-manifest", "pre-commit",
                                   "post-commit", "pre-latest"])
def test_sigkill_at_every_phase_resumes_bitwise(tmp_path, phase):
    """The acceptance criterion: SIGKILL at each write phase, then resume —
    final state must be bitwise identical to an uninterrupted run."""
    steps = 4
    ref = _run_worker(tmp_path / "ref", steps, out_state=tmp_path / "ref.npz")
    assert ref.returncode == 0, ref.stderr[-800:]

    ckpt = tmp_path / "ckpt"
    # kill during the 3rd save (save #2, i.e. the one after step 3)
    killed = _run_worker(ckpt, steps,
                         fault={"kill_at_phase": phase, "kill_at_save": 2})
    assert killed.returncode in (-9, 137), (
        f"fault plan did not fire: rc={killed.returncode}\n{killed.stderr[-800:]}")
    # never a torn visible state: every tag present is either committed+valid
    # or has no COMMIT marker at all
    for tag in os.listdir(ckpt):
        tag_dir = os.path.join(str(ckpt), tag)
        if not os.path.isdir(tag_dir):
            continue
        if is_committed(tag_dir):
            verify_tag(tag_dir)
    resumed = _run_worker(ckpt, steps, out_state=tmp_path / "resumed.npz")
    assert resumed.returncode == 0, resumed.stderr[-800:]
    _assert_bitwise_equal(tmp_path / "ref.npz", tmp_path / "resumed.npz")


@pytest.mark.slow
def test_sigterm_drain_subprocess_roundtrip(tmp_path):
    """Full preemption lifecycle out of process: SIGTERM → drain save →
    exit 83 → relaunch auto-resumes and finishes with continuous steps."""
    ckpt = tmp_path / "ckpt"
    log = tmp_path / "log.jsonl"
    ready = tmp_path / "ready"
    cmd = [sys.executable, WORKER, "--ckpt-dir", str(ckpt), "--steps", "50",
           "--log", str(log), "--step-sleep", "0.3",
           "--ready-file", str(ready)]
    env = dict(os.environ)
    env.pop("DS_FAULT_PLAN", None)
    proc = subprocess.Popen(cmd, env=env)
    import time

    deadline = time.monotonic() + 240
    while not ready.exists():
        assert proc.poll() is None, "worker died before its first step"
        assert time.monotonic() < deadline, "worker never became ready"
        time.sleep(0.2)
    proc.send_signal(signal.SIGTERM)
    rc = proc.wait(timeout=120)
    assert rc == PREEMPTED_EXIT_CODE
    drained_step = max(json.loads(ln)["step"] for ln in log.read_text().splitlines())
    meta_tag = read_latest(str(ckpt))
    assert json.load(open(ckpt / meta_tag / "meta.json"))["emergency"] is True

    done = _run_worker(ckpt, steps=drained_step + 2, log=log)
    assert done.returncode == 0, done.stderr[-800:]
    steps = [json.loads(ln)["step"] for ln in log.read_text().splitlines()]
    assert steps == sorted(steps)  # resumed, never reset
    assert steps[-1] == drained_step + 2
