"""Resilience worker fixture: tiny GPT on one forced-CPU device, checkpoint
after every step, ``resilience`` block enabled (auto-resume + drain
handlers). Faults are injected via the ``DS_FAULT_PLAN`` env var set by the
driver (test_resilience.py, scripts/chaos_smoke.py) — the worker itself has
no fault-specific code, which is the point: the kill lands in the production
save path.

Exit codes: 0 = reached --steps; 83 (PREEMPTED_EXIT_CODE) = drained after
SIGTERM; -9 / 137 = fault-plan SIGKILL fired.
"""

import argparse
import json
import os
import sys
import time


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--ckpt-dir", required=True)
    p.add_argument("--steps", type=int, required=True)
    p.add_argument("--out-state", default=None,
                   help="npz path for the final engine state (bitwise compare)")
    p.add_argument("--log", default=None, help="jsonl per-step log")
    p.add_argument("--step-sleep", type=float, default=0.0,
                   help="per-step sleep (gives the driver a SIGTERM window)")
    p.add_argument("--ready-file", default=None,
                   help="written after the first step completes")
    p.add_argument("--sentinel", action="store_true",
                   help="enable the divergence sentinel (rollback + cursor "
                        "skip) and drive batches from engine.data_cursor")
    args = p.parse_args()

    # single forced-CPU device, independent of the inherited test env
    flags = " ".join(
        f for f in os.environ.get("XLA_FLAGS", "").split()
        if not f.startswith("--xla_force_host_platform_device_count"))
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=1"
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["DS_TPU_ACCELERATOR"] = "cpu"
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import deepspeed_tpu as ds
    from deepspeed_tpu.models import build_gpt, gpt

    model, _ = build_gpt(gpt.GPTConfig(
        vocab_size=64, n_layer=2, n_head=2, d_model=32, max_seq_len=32))
    res_cfg = {"enabled": True, "save_dir": args.ckpt_dir}
    if args.sentinel:
        # tight thresholds: the worker runs a handful of steps, so the
        # sentinel must arm immediately (warmup 1) and a NaN must heal
        res_cfg["sentinel"] = {"enabled": True, "warmup_steps": 1,
                               "cursor_checkpointable": True}
    engine, _, _, _ = ds.initialize(model=model, config={
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "bf16": {"enabled": False},
        "steps_per_print": 0,
        # auto-resume from the newest committed tag + SIGTERM drain -> 83
        "resilience": res_cfg,
    })

    def batch_for(step: int):
        r = np.random.default_rng(1000 + step)
        return {"input_ids": r.integers(0, 64, size=(2, 16), dtype=np.int32)}

    while engine.global_steps < args.steps:
        cursor = engine.data_cursor if args.sentinel else engine.global_steps
        m = engine.train_batch(batch_for(cursor))
        if m.get("skipped_batch"):
            continue  # poisoned cursor consumed without a step
        if args.log:
            with open(args.log, "a") as f:
                f.write(json.dumps({"step": engine.global_steps,
                                    "cursor": engine.data_cursor,
                                    "loss": float(m["loss"]),
                                    "rolled_back": bool(
                                        m.get("health", {}).get("rolled_back"))
                                    }) + "\n")
        if args.ready_file and engine.global_steps == 1:
            with open(args.ready_file, "w") as f:
                f.write("ready")
        if args.step_sleep:
            time.sleep(args.step_sleep)
        engine.save_checkpoint(args.ckpt_dir)

    if args.out_state:
        from deepspeed_tpu.checkpoint.serialization import (
            _UINT_FOR_SIZE,
            _fetch_full,
            _flatten_with_paths,
        )

        flat, _ = _flatten_with_paths(engine.state)
        out = {}
        for key, leaf in flat:
            arr = _fetch_full(leaf)
            if arr.dtype.kind not in "biufc":
                key = f"{key}::{arr.dtype}"
                arr = arr.view(_UINT_FOR_SIZE[arr.dtype.itemsize])
            out[key.replace("/", ".")] = arr
        np.savez(args.out_state, **out)
    print(f"WORKER_DONE step={engine.global_steps}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
