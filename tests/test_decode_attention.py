"""Pallas decode-attention kernel vs dense reference; generate-path integration."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.pallas.decode_attention import decode_attention


def _dense_decode(q, k_cache, v_cache, cur_len):
    """q: [B, 1, H, Dh]; k_cache/v_cache: [B, H, S, Dh]."""
    B, _, H, Dh = q.shape
    S = k_cache.shape[2]
    s = jnp.einsum("bthd,bhsd->bhts", q.astype(jnp.float32),
                   k_cache.astype(jnp.float32)) / np.sqrt(Dh)
    mask = jnp.arange(S)[None, None, None, :] < cur_len
    s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhts,bhsd->bthd", p, v_cache.astype(jnp.float32))


@pytest.mark.parametrize("cur_len", [1, 7, 16, 32])
def test_decode_matches_dense(rng, cur_len):
    B, S, H, Dh = 2, 32, 4, 16
    q = jnp.asarray(rng.normal(size=(B, 1, H, Dh)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, H, S, Dh)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, H, S, Dh)), jnp.float32)
    out = decode_attention(q, k, v, jnp.int32(cur_len), block_k=8)
    ref = _dense_decode(q, k, v, cur_len)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=1e-4)


@pytest.mark.parametrize("batch", [1, 8, 16, 32])
def test_decode_wide_batch(rng, batch):
    """Regression for the b16 BlockSpec/index_map Mosaic rejection
    (BENCH_r02.json): the (b, h, ki) grid must run at every batch width.
    The scalar length operand now rides scalar prefetch (SMEM), not a
    memory-space-less VMEM block."""
    S, H, Dh = 64, 4, 16
    q = jnp.asarray(rng.normal(size=(batch, 1, H, Dh)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(batch, H, S, Dh)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(batch, H, S, Dh)), jnp.float32)
    out = decode_attention(q, k, v, jnp.int32(40), block_k=16)
    ref = _dense_decode(q, k, v, 40)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=1e-4)


def test_decode_per_row_lengths(rng):
    """Continuous batching: every batch row decodes at its OWN cache length
    (a [B] lengths vector instead of the legacy scalar)."""
    B, S, H, Dh = 16, 64, 4, 16
    q = jnp.asarray(rng.normal(size=(B, 1, H, Dh)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, H, S, Dh)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, H, S, Dh)), jnp.float32)
    lens = jnp.asarray(rng.integers(1, S + 1, size=(B,)), jnp.int32)
    out = np.asarray(decode_attention(q, k, v, lens, block_k=16))
    for b in range(B):
        ref = _dense_decode(q[b:b + 1], k[b:b + 1], v[b:b + 1],
                            int(lens[b]))
        np.testing.assert_allclose(out[b:b + 1], np.asarray(ref),
                                   atol=2e-5, rtol=1e-4)
    with pytest.raises(ValueError, match="scalar or"):
        decode_attention(q, k, v, lens[: B // 2], block_k=16)


def _scatter_pool(rng, k, v, page_size, num_pages):
    """Place a contiguous [B, H, S, Dh] cache into a shuffled page pool;
    returns (k_pages [H, P, ps, Dh], v_pages, tables [B, S/ps])."""
    B, H, S, Dh = k.shape
    per_seq = S // page_size
    assert B * per_seq <= num_pages - 1
    ids = list(range(1, num_pages))
    rng.shuffle(ids)
    k_pages = np.zeros((H, num_pages, page_size, Dh), np.float32)
    v_pages = np.zeros((H, num_pages, page_size, Dh), np.float32)
    tables = np.zeros((B, per_seq), np.int32)
    for b in range(B):
        for i in range(per_seq):
            pg = ids.pop()
            tables[b, i] = pg
            sl = slice(i * page_size, (i + 1) * page_size)
            k_pages[:, pg] = k[b, :, sl, :]
            v_pages[:, pg] = v[b, :, sl, :]
    return jnp.asarray(k_pages), jnp.asarray(v_pages), jnp.asarray(tables)


@pytest.mark.parametrize("impl", ["kernel", "gather"])
def test_paged_decode_matches_dense(rng, impl):
    """The block-table gather (kernel index_map or XLA fallback) must be
    invisible: paged output == dense contiguous-cache attention at mixed
    per-row lengths."""
    from deepspeed_tpu.ops.pallas.decode_attention import \
        paged_decode_attention

    B, S, H, Dh, ps = 8, 64, 4, 16, 16
    q = jnp.asarray(rng.normal(size=(B, 1, H, Dh)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, H, S, Dh)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, H, S, Dh)), jnp.float32)
    lens = jnp.asarray(rng.integers(1, S + 1, size=(B,)), jnp.int32)
    k_pages, v_pages, tables = _scatter_pool(rng, np.asarray(k),
                                             np.asarray(v), ps, 64)
    out = paged_decode_attention(q, k_pages, v_pages, lens, tables,
                                 impl=impl)
    ref = _dense_decode(q, k, v, lens.reshape(B, 1, 1, 1))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=1e-4)


def test_paged_gather_fallback_bitwise_vs_dense(rng):
    """The XLA fallback is the same arithmetic as attending over a
    contiguous cache holding the same tokens — BITWISE, not just close
    (the paged layout must introduce zero numerical drift off-TPU)."""
    from deepspeed_tpu.ops.pallas.decode_attention import \
        _paged_gather_attention

    B, S, H, Dh, ps = 4, 32, 2, 8, 8
    q = jnp.asarray(rng.normal(size=(B, 1, H, Dh)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, H, S, Dh)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, H, S, Dh)), jnp.float32)
    lens = jnp.asarray(rng.integers(1, S + 1, size=(B,)), jnp.int32)
    k_pages, v_pages, tables = _scatter_pool(rng, np.asarray(k),
                                             np.asarray(v), ps, 32)
    scale = 1.0 / np.sqrt(Dh)
    paged = _paged_gather_attention(q, k_pages, v_pages, lens, tables, scale)
    # identity layout: a contiguous pool whose table is [0, 1, 2, ...]
    ident_k = jnp.asarray(np.asarray(k).transpose(1, 0, 2, 3).reshape(
        H, B * S // ps, ps, Dh))
    ident_v = jnp.asarray(np.asarray(v).transpose(1, 0, 2, 3).reshape(
        H, B * S // ps, ps, Dh))
    ident_t = jnp.arange(B * (S // ps), dtype=jnp.int32).reshape(B, S // ps)
    dense = _paged_gather_attention(q, ident_k, ident_v, lens, ident_t, scale)
    np.testing.assert_array_equal(np.asarray(paged), np.asarray(dense))


def _quantize_pool(pool, qmax):
    """Per-(head, page) symmetric quantization of a [H, P, ps, Dh] pool."""
    amax = np.abs(pool).max(axis=(2, 3))
    scales = np.where(amax > 0, amax / qmax, 1.0).astype(np.float32)
    q = np.clip(np.round(pool / scales[:, :, None, None]),
                -qmax - 1, qmax).astype(np.int8)
    return q, scales


def _pack4(q):
    xi = q.astype(np.int32)
    Dh = q.shape[-1]
    return ((xi[..., :Dh // 2] & 0xF) | (xi[..., Dh // 2:] << 4)).astype(
        np.int8)


@pytest.mark.parametrize("batch", [1, 8, 16])
@pytest.mark.parametrize("bits", [8, 4])
@pytest.mark.parametrize("impl", ["kernel", "gather"])
def test_quantized_paged_decode_matches_dequant_dense(rng, batch, bits, impl):
    """The quantized paged kernel (dequant fused into the online-softmax
    body, scales on scalar prefetch) must equal the dequantize-then-dense
    reference to fp tolerance, at mixed per-row lengths, for int8 and
    nibble-packed int4, across a batch sweep (the b16 BlockSpec regression
    class must not come back with the extra prefetch operands)."""
    from deepspeed_tpu.ops.pallas.decode_attention import \
        paged_decode_attention

    S, H, Dh, ps = 64, 4, 16, 16
    q = jnp.asarray(rng.normal(size=(batch, 1, H, Dh)), jnp.float32)
    k = rng.normal(size=(batch, H, S, Dh)).astype(np.float32)
    v = rng.normal(size=(batch, H, S, Dh)).astype(np.float32)
    lens = jnp.asarray(rng.integers(1, S + 1, size=(batch,)), jnp.int32)
    k_pages, v_pages, tables = _scatter_pool(rng, k, v, ps,
                                             batch * (S // ps) + 1)
    qmax = 127.0 if bits == 8 else 7.0
    kq, ks = _quantize_pool(np.asarray(k_pages), qmax)
    vq, vs = _quantize_pool(np.asarray(v_pages), qmax)
    # dequantize-then-dense reference over the SAME payload
    kd = (kq.astype(np.float32) * ks[:, :, None, None])
    vd = (vq.astype(np.float32) * vs[:, :, None, None])
    ref = paged_decode_attention(q, jnp.asarray(kd), jnp.asarray(vd), lens,
                                 tables, impl="gather")
    if bits == 4:
        kq, vq = _pack4(kq), _pack4(vq)
    out = paged_decode_attention(q, jnp.asarray(kq), jnp.asarray(vq), lens,
                                 tables, impl=impl,
                                 k_scales=jnp.asarray(ks),
                                 v_scales=jnp.asarray(vs))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=3e-5, rtol=1e-4)


def test_quantized_gather_fallback_bitwise_vs_dequant(rng):
    """Off-TPU the quantized fallback consumes the int payload with the
    exact arithmetic of dequantize-then-dense — BITWISE, so the XLA path
    introduces zero drift beyond the quantization itself."""
    from deepspeed_tpu.ops.pallas.decode_attention import (
        _paged_gather_attention, unpack_kv_int4)

    B, S, H, Dh, ps = 4, 32, 2, 8, 8
    q = jnp.asarray(rng.normal(size=(B, 1, H, Dh)), jnp.float32)
    k = rng.normal(size=(B, H, S, Dh)).astype(np.float32)
    v = rng.normal(size=(B, H, S, Dh)).astype(np.float32)
    lens = jnp.asarray(rng.integers(1, S + 1, size=(B,)), jnp.int32)
    k_pages, v_pages, tables = _scatter_pool(rng, k, v, ps, 32)
    kq, ks = _quantize_pool(np.asarray(k_pages), 7.0)
    vq, vs = _quantize_pool(np.asarray(v_pages), 7.0)
    scale = 1.0 / np.sqrt(Dh)
    out = _paged_gather_attention(q, jnp.asarray(_pack4(kq)),
                                  jnp.asarray(_pack4(vq)), lens, tables,
                                  scale, jnp.asarray(ks), jnp.asarray(vs))
    # reference: unpack + dequantize by hand, then the dense fallback
    kd = np.asarray(unpack_kv_int4(jnp.asarray(_pack4(kq))))
    vd = np.asarray(unpack_kv_int4(jnp.asarray(_pack4(vq))))
    assert np.array_equal(kd, kq.astype(np.float32))  # pack roundtrip exact
    ref = _paged_gather_attention(
        q, jnp.asarray(kd * ks[:, :, None, None]),
        jnp.asarray(vd * vs[:, :, None, None]), lens, tables, scale)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_quantized_paged_rejects_mismatched_payload(rng):
    from deepspeed_tpu.ops.pallas.decode_attention import \
        paged_decode_attention

    q = jnp.zeros((1, 1, 2, 8), jnp.float32)
    bad = jnp.zeros((2, 4, 8, 5), jnp.int8)  # neither Dh nor Dh//2
    scales = jnp.ones((2, 4), jnp.float32)
    with pytest.raises(ValueError, match="matches neither"):
        paged_decode_attention(q, bad, bad, jnp.ones(1, jnp.int32),
                               jnp.zeros((1, 1), jnp.int32),
                               k_scales=scales, v_scales=scales)
    with pytest.raises(ValueError, match="both"):
        paged_decode_attention(q, bad, bad, jnp.ones(1, jnp.int32),
                               jnp.zeros((1, 1), jnp.int32),
                               k_scales=scales)


def test_decode_length_is_traced(rng):
    """One compiled kernel must serve every decode step (length as data)."""
    B, S, H, Dh = 1, 16, 2, 8
    q = jnp.asarray(rng.normal(size=(B, 1, H, Dh)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, H, S, Dh)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, H, S, Dh)), jnp.float32)

    f = jax.jit(lambda q, k, v, n: decode_attention(q, k, v, n, block_k=8))
    for n in (1, 5, 12):
        out = f(q, k, v, jnp.int32(n))
        ref = _dense_decode(q, k, v, n)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=1e-4)


@pytest.mark.slow
def test_decode_kernel_path_matches_dense_logits(rng):
    """The cached forward with the kernel (use_flash=True) matches the dense
    cached path to float tolerance — per-step logits, not argmax chains (two
    softmax implementations may differ by ulps)."""
    import dataclasses

    from deepspeed_tpu.models import gpt as G
    from deepspeed_tpu.models.gpt import GPTConfig, init_params

    cfg = GPTConfig(vocab_size=64, d_model=32, n_layer=2, n_head=4,
                    max_seq_len=32, use_flash=True)
    params = init_params(cfg, jax.random.PRNGKey(0))
    ids = rng.integers(0, 64, size=(2, 8)).astype(np.int32)

    def run(cfg_):
        cache = G.init_cache(cfg_, 2, 32, jnp.float32)
        _, cache = G.forward_with_cache(cfg_, params, jnp.asarray(ids), cache)
        # three decode steps
        outs = []
        for t in range(3):
            tok = jnp.full((2, 1), t + 1, jnp.int32)
            logits, cache = G.forward_with_cache(cfg_, params, tok, cache)
            outs.append(np.asarray(logits))
        return np.concatenate(outs, axis=1)

    out_kernel = run(cfg)
    out_dense = run(dataclasses.replace(cfg, use_flash=False))
    np.testing.assert_allclose(out_kernel, out_dense, atol=2e-4, rtol=1e-3)
