"""Pallas decode-attention kernel vs dense reference; generate-path integration."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.pallas.decode_attention import decode_attention


def _dense_decode(q, k_cache, v_cache, cur_len):
    """q: [B, 1, H, Dh]; k_cache/v_cache: [B, H, S, Dh]."""
    B, _, H, Dh = q.shape
    S = k_cache.shape[2]
    s = jnp.einsum("bthd,bhsd->bhts", q.astype(jnp.float32),
                   k_cache.astype(jnp.float32)) / np.sqrt(Dh)
    mask = jnp.arange(S)[None, None, None, :] < cur_len
    s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhts,bhsd->bthd", p, v_cache.astype(jnp.float32))


@pytest.mark.parametrize("cur_len", [1, 7, 16, 32])
def test_decode_matches_dense(rng, cur_len):
    B, S, H, Dh = 2, 32, 4, 16
    q = jnp.asarray(rng.normal(size=(B, 1, H, Dh)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, H, S, Dh)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, H, S, Dh)), jnp.float32)
    out = decode_attention(q, k, v, jnp.int32(cur_len), block_k=8)
    ref = _dense_decode(q, k, v, cur_len)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=1e-4)


def test_decode_length_is_traced(rng):
    """One compiled kernel must serve every decode step (length as data)."""
    B, S, H, Dh = 1, 16, 2, 8
    q = jnp.asarray(rng.normal(size=(B, 1, H, Dh)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, H, S, Dh)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, H, S, Dh)), jnp.float32)

    f = jax.jit(lambda q, k, v, n: decode_attention(q, k, v, n, block_k=8))
    for n in (1, 5, 12):
        out = f(q, k, v, jnp.int32(n))
        ref = _dense_decode(q, k, v, n)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=1e-4)


@pytest.mark.slow
def test_decode_kernel_path_matches_dense_logits(rng):
    """The cached forward with the kernel (use_flash=True) matches the dense
    cached path to float tolerance — per-step logits, not argmax chains (two
    softmax implementations may differ by ulps)."""
    import dataclasses

    from deepspeed_tpu.models import gpt as G
    from deepspeed_tpu.models.gpt import GPTConfig, init_params

    cfg = GPTConfig(vocab_size=64, d_model=32, n_layer=2, n_head=4,
                    max_seq_len=32, use_flash=True)
    params = init_params(cfg, jax.random.PRNGKey(0))
    ids = rng.integers(0, 64, size=(2, 8)).astype(np.int32)

    def run(cfg_):
        cache = G.init_cache(cfg_, 2, 32, jnp.float32)
        _, cache = G.forward_with_cache(cfg_, params, jnp.asarray(ids), cache)
        # three decode steps
        outs = []
        for t in range(3):
            tok = jnp.full((2, 1), t + 1, jnp.int32)
            logits, cache = G.forward_with_cache(cfg_, params, tok, cache)
            outs.append(np.asarray(logits))
        return np.concatenate(outs, axis=1)

    out_kernel = run(cfg)
    out_dense = run(dataclasses.replace(cfg, use_flash=False))
    np.testing.assert_allclose(out_kernel, out_dense, atol=2e-4, rtol=1e-3)
