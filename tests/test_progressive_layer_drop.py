"""Progressive Layer Drop (parity: reference runtime/progressive_layer_drop.py
+ arXiv:2010.13369): theta schedule, in-scan layer gating, engine integration.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.models import build_gpt
from deepspeed_tpu.models.gpt import GPTConfig, forward, init_params
from deepspeed_tpu.runtime.progressive_layer_drop import ProgressiveLayerDrop


def test_theta_schedule_matches_reference_formula():
    pld = ProgressiveLayerDrop(theta=0.5, gamma=0.001)
    assert pld.get_theta() == 1.0
    for t in (1, 10, 1000, 100000):
        pld.update_state(t)
        expect = (1.0 - 0.5) * np.exp(-0.001 * t) + 0.5
        assert pld.get_theta() == pytest.approx(expect, rel=1e-9)
    assert pld.get_state()["progressive_layer_drop"] is True
    # late in training theta approaches the configured floor
    pld.update_state(10_000_000)
    assert pld.get_theta() == pytest.approx(0.5, abs=1e-6)


def _tiny(n_layer=2):
    cfg = GPTConfig(vocab_size=64, d_model=32, n_layer=n_layer, n_head=2,
                    max_seq_len=16)
    params = init_params(cfg, jax.random.PRNGKey(0))
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 64, (2, 16)),
                      jnp.int32)
    return cfg, params, ids


def test_pld_theta_one_is_identity():
    """theta=1 keeps every layer with probability 1 — the baseline up to the
    x + (y-x) residual-form rounding (a dropped layer would differ hugely)."""
    cfg, params, ids = _tiny()
    rngs = {"dropout": jax.random.PRNGKey(3)}
    base = np.asarray(forward(cfg, params, ids, rngs=rngs, train=True),
                      np.float32)
    pld = np.asarray(forward(cfg, params, ids, rngs=rngs, train=True,
                             pld_theta=jnp.float32(1.0)), np.float32)
    np.testing.assert_allclose(base, pld, atol=1e-4, rtol=1e-4)


def test_pld_theta_zero_drops_last_layer():
    """With theta=0 the deepest layer's keep probability is exactly 0: poison
    its weights — the output must match the clean model under the same rng."""
    cfg, params, ids = _tiny(n_layer=2)
    poisoned = jax.tree_util.tree_map(lambda x: x, params)
    blocks = dict(poisoned["blocks"])
    qkv = np.asarray(blocks["qkv_w"], np.float32).copy()
    qkv[1] = 1e30  # layer index 1 == deepest layer
    blocks["qkv_w"] = jnp.asarray(qkv)
    poisoned["blocks"] = blocks
    rngs = {"dropout": jax.random.PRNGKey(5)}
    out_clean = forward(cfg, params, ids, rngs=rngs, train=True,
                        pld_theta=jnp.float32(0.0))
    out_poison = forward(cfg, poisoned, ids, rngs=rngs, train=True,
                         pld_theta=jnp.float32(0.0))
    assert np.isfinite(np.asarray(out_poison, np.float32)).all()
    np.testing.assert_array_equal(np.asarray(out_clean),
                                  np.asarray(out_poison))


def test_pld_exclusive_with_stochastic_depth():
    cfg, params, ids = _tiny()
    cfg = cfg.__class__(**{**cfg.__dict__, "stochastic_depth": 0.1})
    with pytest.raises(ValueError, match="stochastic_depth"):
        forward(cfg, init_params(cfg, jax.random.PRNGKey(0)), ids,
                rngs={"dropout": jax.random.PRNGKey(0)}, train=True,
                pld_theta=jnp.float32(0.5))


def _engine(extra=None):
    model, cfg = build_gpt(GPTConfig(
        vocab_size=128, d_model=32, n_layer=3, n_head=2, max_seq_len=32))
    config = {
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "progressive_layer_drop": {"enabled": True, "theta": 0.5,
                                   "gamma": 0.01},
        "steps_per_print": 0,
    }
    config.update(extra or {})
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)
    return engine, cfg


@pytest.mark.slow
def test_engine_pld_trains_and_tracks_theta():
    e, cfg = _engine()
    assert e.progressive_layer_drop is not None
    r = np.random.default_rng(0)
    b = {"input_ids": r.integers(0, cfg.vocab_size, (16, 16), dtype=np.int32)}
    losses = [float(e.train_batch(b)["loss"]) for _ in range(6)]
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]
    expect = (1.0 - 0.5) * np.exp(-0.01 * 6) + 0.5
    assert e.progressive_layer_drop.get_theta() == pytest.approx(expect)


def test_engine_pld_rejects_offload():
    with pytest.raises(ValueError, match="progressive_layer_drop"):
        _engine({"zero_optimization": {
            "offload_optimizer": {"device": "cpu"}}})
