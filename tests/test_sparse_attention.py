"""Blocksparse attention: layout builders + Pallas kernel vs dense reference.

Mirrors the reference's tests/unit/ops/sparse_attention intent: kernel output
must equal dense attention masked to the layout, forward and backward.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.pallas.blocksparse_attention import (
    blocksparse_attention,
    layout_tables,
)
from deepspeed_tpu.ops.sparse_attention import (
    BigBirdSparsityConfig,
    BSLongformerSparsityConfig,
    DenseSparsityConfig,
    FixedSparsityConfig,
    LocalSlidingWindowSparsityConfig,
    SparseSelfAttention,
    VariableSparsityConfig,
)

BLOCK = 8  # tiny blocks for CPU interpret mode
NEG = -1e30


def _dense_masked(q, k, v, layout, block, causal):
    """Reference: dense attention with the blocksparse + causal mask applied."""
    B, T, H, D = q.shape
    n = T // block
    mask = np.kron(np.asarray(layout), np.ones((block, block)))  # [H, T, T]
    if causal:
        mask = mask * np.tril(np.ones((T, T)))
    s = jnp.einsum("bthd,bshd->bhts", q, k) / np.sqrt(D)
    s = jnp.where(jnp.asarray(mask[None]) > 0, s, NEG)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhts,bshd->bthd", p, v)


def _qkv(rng, B=1, T=64, H=2, D=16):
    s = (B, T, H, D)
    return (jnp.asarray(rng.normal(size=s), jnp.float32),
            jnp.asarray(rng.normal(size=s), jnp.float32),
            jnp.asarray(rng.normal(size=s), jnp.float32))


# ------------------------------------------------------------------- layouts
def test_layout_shapes_and_diagonal():
    for cfg in [
        DenseSparsityConfig(num_heads=2, block=BLOCK),
        FixedSparsityConfig(num_heads=2, block=BLOCK, num_local_blocks=4),
        VariableSparsityConfig(num_heads=2, block=BLOCK),
        BigBirdSparsityConfig(num_heads=2, block=BLOCK),
        BSLongformerSparsityConfig(num_heads=2, block=BLOCK),
        LocalSlidingWindowSparsityConfig(num_heads=2, block=BLOCK),
    ]:
        layout = cfg.make_layout(64)
        assert layout.shape == (2, 8, 8)
        idx = np.arange(8)
        assert (layout[:, idx, idx] == 1).all()  # diagonal always active


def test_unidirectional_layouts_are_lower_triangular():
    for cfg in [
        FixedSparsityConfig(num_heads=2, block=BLOCK, num_local_blocks=4,
                            attention="unidirectional"),
        BigBirdSparsityConfig(num_heads=2, block=BLOCK, attention="unidirectional"),
        LocalSlidingWindowSparsityConfig(num_heads=2, block=BLOCK),
    ]:
        layout = cfg.make_layout(64)
        assert (np.triu(layout, k=1) == 0).all()


def test_sliding_window_is_banded():
    cfg = LocalSlidingWindowSparsityConfig(
        num_heads=1, block=BLOCK, num_sliding_window_blocks=2)
    layout = cfg.make_layout(64)
    # causal band of width 2 blocks
    for i in range(8):
        active = np.nonzero(layout[0, i])[0]
        assert active.min() >= max(0, i - 1) and active.max() == i


def test_layout_seq_not_divisible_raises():
    with pytest.raises(ValueError, match="multiple of block"):
        DenseSparsityConfig(num_heads=1, block=BLOCK).make_layout(60)


def test_layout_tables_roundtrip():
    cfg = BigBirdSparsityConfig(num_heads=2, block=BLOCK)
    layout = cfg.make_layout(64)
    kidx, kcnt, qidx, qcnt = layout_tables(layout)
    # reconstruct the layout from the tables
    recon = np.zeros_like(layout)
    for h in range(2):
        for i in range(8):
            recon[h, i, kidx[h, i, : kcnt[h, i]]] = 1
    np.testing.assert_array_equal(recon, layout)
    recon_t = np.zeros_like(layout)
    for h in range(2):
        for j in range(8):
            recon_t[h, qidx[h, j, : qcnt[h, j]], j] = 1
    np.testing.assert_array_equal(recon_t, layout)


# ------------------------------------------------------------------- kernel
@pytest.mark.parametrize("causal", [True, False])
def test_dense_layout_matches_dense_attention(rng, causal):
    q, k, v = _qkv(rng)
    layout = DenseSparsityConfig(num_heads=2, block=BLOCK).make_layout(64)
    out = blocksparse_attention(q, k, v, layout, BLOCK, causal=causal)
    ref = _dense_masked(q, k, v, layout, BLOCK, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=1e-4)


@pytest.mark.parametrize("make_cfg", [
    lambda: FixedSparsityConfig(num_heads=2, block=BLOCK, num_local_blocks=2,
                                attention="unidirectional"),
    lambda: BigBirdSparsityConfig(num_heads=2, block=BLOCK,
                                  num_sliding_window_blocks=3,
                                  attention="unidirectional"),
    lambda: BSLongformerSparsityConfig(num_heads=2, block=BLOCK,
                                       num_sliding_window_blocks=3),
    lambda: LocalSlidingWindowSparsityConfig(num_heads=2, block=BLOCK),
])
def test_sparse_matches_masked_dense(rng, make_cfg):
    cfg = make_cfg()
    causal = getattr(cfg, "attention", "bidirectional") == "unidirectional"
    q, k, v = _qkv(rng)
    layout = cfg.make_layout(64)
    out = blocksparse_attention(q, k, v, layout, BLOCK, causal=causal)
    ref = _dense_masked(q, k, v, layout, BLOCK, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=1e-4)


def test_sparse_grads_match_masked_dense(rng):
    cfg = FixedSparsityConfig(num_heads=2, block=BLOCK, num_local_blocks=2,
                              attention="unidirectional")
    q, k, v = _qkv(rng, T=32)
    layout = cfg.make_layout(32)

    def loss_sparse(q, k, v):
        return (blocksparse_attention(q, k, v, layout, BLOCK, causal=True) ** 2).sum()

    def loss_dense(q, k, v):
        return (_dense_masked(q, k, v, layout, BLOCK, True) ** 2).sum()

    gs = jax.grad(loss_sparse, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gs, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4, rtol=1e-3)


def test_sparse_self_attention_module(rng):
    module = SparseSelfAttention(
        BigBirdSparsityConfig(num_heads=2, block=BLOCK, attention="unidirectional"))
    q, k, v = _qkv(rng)
    out = module(q, k, v)
    assert out.shape == q.shape
    assert module.causal is True
    assert 0.0 < module.density(64) < 1.0
    # head-count mismatch guard
    with pytest.raises(ValueError, match="heads"):
        module(q[:, :, :1], k[:, :, :1], v[:, :, :1])


# ------------------------------------------------------- grafting utilities
def test_graft_sparse_attention_dense_config_matches_dense():
    """DenseSparsityConfig layout is all-ones, so the grafted model must
    reproduce the ungrafted forward exactly (kernel-equivalence check)."""
    import dataclasses

    from deepspeed_tpu.models.gpt import GPTConfig, init_params, loss_fn
    from deepspeed_tpu.ops.sparse_attention import (
        DenseSparsityConfig,
        replace_self_attention_with_sparse,
    )

    cfg = GPTConfig(vocab_size=64, n_layer=2, n_head=4, d_model=32,
                    max_seq_len=64, use_flash=False)
    params = init_params(cfg, jax.random.PRNGKey(0))
    batch = {"input_ids": np.random.default_rng(0).integers(
        0, 64, (2, 64), np.int32)}
    dense_loss, _ = loss_fn(cfg, params, batch, train=False)
    sc = DenseSparsityConfig(num_heads=4, block=16)
    sparse_cfg = replace_self_attention_with_sparse(cfg, sc)
    sparse_loss, _ = loss_fn(sparse_cfg, params, batch, train=False)
    np.testing.assert_allclose(float(sparse_loss), float(dense_loss),
                               rtol=2e-5)


def test_graft_bigbird_runs_and_differs():
    from deepspeed_tpu.models.gpt import GPTConfig, init_params, loss_fn
    from deepspeed_tpu.ops.sparse_attention import (
        BigBirdSparsityConfig,
        replace_self_attention_with_sparse,
    )

    cfg = GPTConfig(vocab_size=64, n_layer=2, n_head=4, d_model=32,
                    max_seq_len=128, use_flash=False)
    params = init_params(cfg, jax.random.PRNGKey(0))
    batch = {"input_ids": np.random.default_rng(1).integers(
        0, 64, (2, 128), np.int32)}
    dense_loss, _ = loss_fn(cfg, params, batch, train=False)
    sc = BigBirdSparsityConfig(num_heads=4, block=16, num_random_blocks=1,
                               num_sliding_window_blocks=2,
                               num_global_blocks=1)
    sparse_cfg = replace_self_attention_with_sparse(cfg, sc)
    sparse_loss, _ = loss_fn(sparse_cfg, params, batch, train=False)
    assert np.isfinite(float(sparse_loss))
    assert abs(float(sparse_loss) - float(dense_loss)) > 1e-6


def test_graft_head_mismatch_raises():
    from deepspeed_tpu.models.gpt import GPTConfig
    from deepspeed_tpu.ops.sparse_attention import (
        FixedSparsityConfig,
        replace_self_attention_with_sparse,
    )

    with pytest.raises(ValueError, match="heads"):
        replace_self_attention_with_sparse(
            GPTConfig(n_head=4), FixedSparsityConfig(num_heads=8))


def test_extend_position_embedding_tiles_table():
    from deepspeed_tpu.ops.sparse_attention import extend_position_embedding

    table = np.arange(12, dtype=np.float32).reshape(6, 2)
    out = extend_position_embedding({"wpe": table}, 15)
    got = np.asarray(out["wpe"])
    assert got.shape == (15, 2)
    np.testing.assert_array_equal(got[:6], table)
    np.testing.assert_array_equal(got[6:12], table)
    np.testing.assert_array_equal(got[12:], table[:3])
    with pytest.raises(ValueError, match="<= current"):
        extend_position_embedding({"wpe": table}, 4)
    with pytest.raises(ValueError, match="no learned position"):
        extend_position_embedding({"other": table}, 32)


def test_extended_model_runs_longer_sequences():
    import dataclasses

    from deepspeed_tpu.models.gpt import GPTConfig, init_params, loss_fn
    from deepspeed_tpu.ops.sparse_attention import extend_position_embedding

    cfg = GPTConfig(vocab_size=64, n_layer=1, n_head=2, d_model=16,
                    max_seq_len=32, use_flash=False)
    params = extend_position_embedding(
        init_params(cfg, jax.random.PRNGKey(0)), 64)
    long_cfg = dataclasses.replace(cfg, max_seq_len=64)
    batch = {"input_ids": np.random.default_rng(0).integers(
        0, 64, (1, 64), np.int32)}
    loss, _ = loss_fn(long_cfg, params, batch, train=False)
    assert np.isfinite(float(loss))


def test_pad_unpad_roundtrip():
    from deepspeed_tpu.ops.sparse_attention import (
        pad_to_block_size,
        unpad_sequence_output,
    )

    ids = jnp.ones((2, 30), jnp.int32)
    mask = jnp.ones((2, 30), jnp.int32)
    pids, pmask, pad = pad_to_block_size(ids, 16, pad_token_id=9,
                                         attention_mask=mask)
    assert pids.shape == (2, 32) and pad == 2
    assert int(pids[0, -1]) == 9 and int(pmask[0, -1]) == 0
    out = unpad_sequence_output(jnp.zeros((2, 32, 4)), pad)
    assert out.shape == (2, 30, 4)
    # already aligned: no-op
    pids2, _, pad2 = pad_to_block_size(jnp.ones((2, 32), jnp.int32), 16)
    assert pad2 == 0 and pids2.shape == (2, 32)
