"""ZeRO extras + misc parity fills: TiledLinear (ref runtime/zero/tiling.py:27),
the zero.Init / GatheredParameters user surface
(ref partition_parameters.py:539,1519), comms per-step scaling report
(r1 weak #8), stochastic depth (ref StochasticTransformer), ds_ssh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import deepspeed_tpu as ds
from deepspeed_tpu.models import build_gpt, gpt


# -------------------------------------------------------------- TiledLinear
def test_tiled_linear_matches_dense(rng):
    from deepspeed_tpu.runtime.zero import TiledLinear

    tl = TiledLinear(in_features=12, out_features=20, out_splits=4)
    params = tl.init(jax.random.PRNGKey(0))
    x = jnp.asarray(rng.normal(size=(3, 12)), jnp.float32)
    y = tl.apply(params, x)
    dense = x @ tl.dense_weight(params) + jnp.concatenate(
        [params["b_tiles"][t] for t in range(4)])
    np.testing.assert_allclose(np.asarray(y), np.asarray(dense), rtol=1e-5)
    assert y.shape == (3, 20)
    # differentiable through the tiled scan
    g = jax.grad(lambda p: tl.apply(p, x).sum())(params)
    assert g["w_tiles"].shape == params["w_tiles"].shape
    # invalid splits fail loudly
    with pytest.raises(ValueError):
        TiledLinear(in_features=4, out_features=10, out_splits=3)
    with pytest.raises(NotImplementedError):
        TiledLinear(in_features=4, out_features=8, in_splits=2)


def test_tiled_linear_zero3_shards_tiles(devices):
    """Under ZeRO-3 the tile axis gets dp-sharded: each gather inside the scan
    fetches one tile, the reference TiledLinear's memory contract."""
    from deepspeed_tpu.runtime.topology import MeshTopology
    from deepspeed_tpu.runtime.zero import TiledLinear, ZeroShardingPolicy
    from deepspeed_tpu.runtime.zero.config import DeepSpeedZeroConfig

    tl = TiledLinear(in_features=8, out_features=32, out_splits=8,
                     use_bias=False)
    params = tl.init(jax.random.PRNGKey(0))
    topo = MeshTopology.create(dp=8, devices=devices)
    policy = ZeroShardingPolicy(topo, DeepSpeedZeroConfig(
        stage=3, stage3_param_persistence_threshold=0))
    spec = policy.param_spec(params["w_tiles"].shape, tl.specs()["w_tiles"])
    assert "dp" in str(spec)  # tile (or another) axis is ZeRO-sharded


# -------------------------------------------------- GatheredParameters / Init
def _tiny_engine():
    model, _ = build_gpt(gpt.GPTConfig(
        vocab_size=64, n_layer=2, n_head=2, d_model=32, max_seq_len=32))
    engine, _, _, _ = ds.initialize(model=model, config={
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 3, "stage3_param_persistence_threshold": 0},
        "mesh": {"dp": 8},
        "bf16": {"enabled": False},
        "steps_per_print": 0,
    })
    return engine


@pytest.mark.slow
def test_gathered_parameters_read_and_modify(rng):
    engine = _tiny_engine()
    with ds.zero.GatheredParameters(engine, paths=["wte"]) as full:
        assert full["wte"].shape == (64, 32)  # full logical value on host
        before = full["wte"].copy()

    new_emb = rng.normal(size=(64, 32)).astype(np.float32)
    with ds.zero.GatheredParameters(engine, paths=["wte"], modify=True) as full:
        full["wte"][:] = new_emb

    wte = engine.state["params"]["wte"]
    np.testing.assert_allclose(np.asarray(jax.device_get(wte)), new_emb,
                               rtol=1e-6)
    assert not wte.sharding.is_fully_replicated  # sharding preserved
    assert np.abs(before - new_emb).max() > 0
    # master stayed in sync
    m = engine.state["master"].get("wte") if engine.state["master"] else None
    if m is not None:
        np.testing.assert_allclose(np.asarray(jax.device_get(m)), new_emb,
                                   rtol=1e-6)
    # training still works after the surgery
    ids = rng.integers(0, 64, size=(8, 16)).astype(np.int32)
    assert np.isfinite(float(engine.train_batch({"input_ids": ids})["loss"]))


def test_zero_init_context_is_usable():
    with ds.zero.Init():
        engine = _tiny_engine()
    assert engine.zero_optimization_stage() == 3


# -------------------------------------------------------------- comms scaling
def test_comms_summary_scales_with_steps(rng):
    from deepspeed_tpu import comm

    comm.configure(enabled=True)
    comm.comms_logger.reset()
    comm.comms_logger.record("all_reduce", 1000)
    out1 = comm.comms_logger.log_summary()
    out5 = comm.comms_logger.log_summary(scale=5)
    assert "bytes=1000" in out1
    assert "bytes=5000" in out5 and "x 5 executions" in out5
    comm.configure(enabled=False)
    comm.comms_logger.reset()


# -------------------------------------------------------------- stochastic depth
@pytest.mark.slow
def test_stochastic_depth_trains_and_evals_deterministically(rng):
    cfg = gpt.GPTConfig(vocab_size=64, n_layer=4, n_head=2, d_model=32,
                        max_seq_len=32, stochastic_depth=0.5)
    params = gpt.init_params(cfg, jax.random.PRNGKey(0))
    ids = jnp.asarray(rng.integers(0, 64, size=(2, 16)), jnp.int32)
    # eval path ignores stochastic depth -> deterministic, equals sd=0 config
    e1 = gpt.forward(cfg, params, ids, train=False)
    import dataclasses

    e2 = gpt.forward(dataclasses.replace(cfg, stochastic_depth=0.0),
                     params, ids, train=False)
    np.testing.assert_array_equal(np.asarray(e1), np.asarray(e2))
    # train path with different rngs gives different (finite) outputs
    r1 = gpt.forward(cfg, params, ids,
                     rngs={"dropout": jax.random.PRNGKey(1)}, train=True)
    r2 = gpt.forward(cfg, params, ids,
                     rngs={"dropout": jax.random.PRNGKey(2)}, train=True)
    assert np.all(np.isfinite(np.asarray(r1)))
    assert np.abs(np.asarray(r1) - np.asarray(r2)).max() > 0


# -------------------------------------------------------------- ds_ssh
def test_ds_ssh_parses_and_reports_missing_hostfile(tmp_path, capsys):
    from deepspeed_tpu.launcher.ds_ssh import main

    rc = main(["-H", str(tmp_path / "nope"), "echo", "hi"])
    assert rc == 2

    hostfile = tmp_path / "hostfile"
    hostfile.write_text("hostA slots=4\nhostB slots=4\n")
    # 'ssh' to fake hosts fails fast; we assert selection + failure reporting
    rc = main(["-H", str(hostfile), "--timeout", "5", "--include", "hostA",
               "echo", "hi"])
    err = capsys.readouterr().err
    assert rc != 0 and "hostA" in err and "hostB" not in err